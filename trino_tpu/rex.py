"""Typed row-expression IR.

Reference parity: core/trino-main/.../sql/relational/ (RowExpression,
CallExpression, SpecialForm, ConstantExpression, InputReferenceExpression).
Produced by the analyzer/planner from the AST; consumed by the executor,
which traces it into jitted XLA computations (the reference's
ExpressionCompiler bytecode step → jax.jit, SURVEY.md §7.2).

Three-valued logic: every expression evaluates to a value lane + validity
lane; AND/OR/NOT follow SQL Kleene semantics in the evaluator.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional, Tuple

from .types import BOOLEAN, Type


class RowExpr:
    __slots__ = ()
    type: Type


@dataclass(frozen=True)
class InputRef(RowExpr):
    """Reference to a column of the input Batch by symbol name."""
    name: str
    type: Type

    def __str__(self):
        return self.name


@dataclass(frozen=True)
class Const(RowExpr):
    """Literal; value is a host python scalar (None == typed NULL).
    Strings stay python str; DATE is days-since-epoch int; intervals are
    millis (day-time) / months (year-month)."""
    value: object
    type: Type

    def __str__(self):
        return repr(self.value)


@dataclass(frozen=True)
class Call(RowExpr):
    """Scalar function or operator application. ``fn`` is the resolved
    function name (lower case); operators use their symbol ('+', '=',
    'and', 'not', 'is_null', 'like', ...). Argument coercions are
    explicit Casts inserted by the analyzer."""
    fn: str
    args: Tuple[RowExpr, ...]
    type: Type

    def __str__(self):
        return f"{self.fn}({', '.join(map(str, self.args))})"


@dataclass(frozen=True)
class Cast(RowExpr):
    arg: RowExpr
    type: Type
    safe: bool = False      # TRY_CAST yields NULL instead of failing

    def __str__(self):
        return f"cast({self.arg} as {self.type})"


@dataclass(frozen=True)
class CaseExpr(RowExpr):
    """Searched CASE (SpecialForm.WHEN/SWITCH in the reference)."""
    whens: Tuple[Tuple[RowExpr, RowExpr], ...]
    default: Optional[RowExpr]
    type: Type

    def __str__(self):
        parts = " ".join(f"when {c} then {v}" for c, v in self.whens)
        return f"case {parts} else {self.default} end"


@dataclass(frozen=True)
class Lambda(RowExpr):
    """Lambda argument of a higher-order function (reference:
    sql/relational/LambdaDefinitionExpression). ``params`` are fresh
    symbol names the body refers to via InputRef; the evaluator binds
    them to flat element lanes (exec/expr.py lambda machinery)."""
    params: Tuple[str, ...]
    body: RowExpr
    type: Type  # the body's result type

    def __str__(self):
        return f"({', '.join(self.params)}) -> {self.body}"


TRUE = Const(True, BOOLEAN)
FALSE = Const(False, BOOLEAN)


def and_all(exprs) -> RowExpr:
    exprs = [e for e in exprs if e is not None and e != TRUE]
    if not exprs:
        return TRUE
    out = exprs[0]
    for e in exprs[1:]:
        out = Call("and", (out, e), BOOLEAN)
    return out


def or_all(exprs) -> RowExpr:
    exprs = list(exprs)
    if not exprs:
        return FALSE
    out = exprs[0]
    for e in exprs[1:]:
        out = Call("or", (out, e), BOOLEAN)
    return out


def walk(e: RowExpr):
    """Pre-order traversal."""
    yield e
    if isinstance(e, Call):
        for a in e.args:
            yield from walk(a)
    elif isinstance(e, Lambda):
        yield from walk(e.body)
    elif isinstance(e, Cast):
        yield from walk(e.arg)
    elif isinstance(e, CaseExpr):
        for c, v in e.whens:
            yield from walk(c)
            yield from walk(v)
        if e.default is not None:
            yield from walk(e.default)


def input_names(e: RowExpr):
    """Free InputRef names (lambda parameters are bound, not inputs)."""
    out = set()

    def go(x, bound):
        if isinstance(x, InputRef):
            if x.name not in bound:
                out.add(x.name)
        elif isinstance(x, Call):
            for a in x.args:
                go(a, bound)
        elif isinstance(x, Lambda):
            go(x.body, bound | set(x.params))
        elif isinstance(x, Cast):
            go(x.arg, bound)
        elif isinstance(x, CaseExpr):
            for c, v in x.whens:
                go(c, bound)
                go(v, bound)
            if x.default is not None:
                go(x.default, bound)

    go(e, frozenset())
    return out


def replace_inputs(e: RowExpr, mapping) -> RowExpr:
    """Rewrite InputRefs through mapping (name -> RowExpr or name)."""
    if isinstance(e, InputRef):
        m = mapping.get(e.name)
        if m is None:
            return e
        return InputRef(m, e.type) if isinstance(m, str) else m
    if isinstance(e, Call):
        return Call(e.fn, tuple(replace_inputs(a, mapping) for a in e.args),
                    e.type)
    if isinstance(e, Lambda):
        inner = {k: v for k, v in mapping.items() if k not in e.params}
        return Lambda(e.params, replace_inputs(e.body, inner), e.type)
    if isinstance(e, Cast):
        return Cast(replace_inputs(e.arg, mapping), e.type, e.safe)
    if isinstance(e, CaseExpr):
        return CaseExpr(
            tuple((replace_inputs(c, mapping), replace_inputs(v, mapping))
                  for c, v in e.whens),
            None if e.default is None
            else replace_inputs(e.default, mapping), e.type)
    return e


def split_conjuncts(e: Optional[RowExpr]):
    """Flatten an AND tree into a conjunct list
    (reference: sql/ExpressionUtils.extractConjuncts)."""
    if e is None or e == TRUE:
        return []
    if isinstance(e, Call) and e.fn == "and":
        return split_conjuncts(e.args[0]) + split_conjuncts(e.args[1])
    return [e]


# functions whose value must be re-evaluated per query/row — plans may
# not cache programs containing them, and optimizer rewrites may not
# duplicate or move them across row-set boundaries
VOLATILE_FNS = frozenset({"now", "current_date", "current_time",
                          "current_timestamp", "localtime",
                          "localtimestamp", "random", "rand", "uuid"})


def expr_volatile(e: RowExpr) -> bool:
    """True when the expression tree contains a volatile call."""
    return any(isinstance(x, Call) and x.fn in VOLATILE_FNS
               for x in walk(e))
