"""SQL frontend: tokenizer → parser → AST → analyzer.

Reference parity: core/trino-parser (SqlBase.g4, AstBuilder.java,
SqlParser.java) and core/trino-main sql/analyzer. Re-implemented as a
hand-written recursive-descent parser rather than a generated one: the
grammar subset the engine executes is stable and a direct parser keeps
error messages precise with zero build-time tooling.
"""

from .parser import parse_statement  # noqa: F401
