"""SQL abstract syntax tree.

Reference parity: core/trino-parser/src/main/java/io/trino/sql/tree/
(~100 node classes, AstVisitor pattern). Nodes here are frozen dataclasses;
traversal is structural (match on type) rather than a visitor hierarchy —
idiomatic Python, and the analyzer/planner are the only consumers.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Tuple, Union


class Node:
    """Base of every AST node."""
    __slots__ = ()


# --------------------------------------------------------------------------
# Expressions (reference: sql/tree/Expression.java subclasses)
# --------------------------------------------------------------------------

class Expression(Node):
    __slots__ = ()


@dataclass(frozen=True)
class Literal(Expression):
    value: object            # python int / float / str / bool / None
    type_name: Optional[str] = None   # e.g. 'date', 'decimal(3,1)'; None=infer


@dataclass(frozen=True)
class IntervalLiteral(Expression):
    value: str               # e.g. '3'
    unit: str                # day | month | year | hour | minute | second
    sign: int = 1


@dataclass(frozen=True)
class Identifier(Expression):
    """Possibly-qualified column reference, e.g. l.orderkey."""
    parts: Tuple[str, ...]

    @property
    def name(self) -> str:
        return self.parts[-1]

    def __str__(self) -> str:
        return ".".join(self.parts)


@dataclass(frozen=True)
class Star(Expression):
    """`*` or `t.*` in a select list or count(*)."""
    qualifier: Optional[str] = None


@dataclass(frozen=True)
class BinaryOp(Expression):
    op: str                  # + - * / % = <> < <= > >= and or ||
    left: Expression
    right: Expression


@dataclass(frozen=True)
class UnaryOp(Expression):
    op: str                  # - + not
    operand: Expression


@dataclass(frozen=True)
class IsNull(Expression):
    operand: Expression
    negated: bool = False


@dataclass(frozen=True)
class IsDistinctFrom(Expression):
    left: Expression
    right: Expression
    negated: bool = False


@dataclass(frozen=True)
class Between(Expression):
    operand: Expression
    low: Expression
    high: Expression
    negated: bool = False


@dataclass(frozen=True)
class InList(Expression):
    operand: Expression
    items: Tuple[Expression, ...]
    negated: bool = False


@dataclass(frozen=True)
class InSubquery(Expression):
    operand: Expression
    query: "Query"
    negated: bool = False


@dataclass(frozen=True)
class Exists(Expression):
    query: "Query"
    negated: bool = False


@dataclass(frozen=True)
class ScalarSubquery(Expression):
    query: "Query"


@dataclass(frozen=True)
class QuantifiedComparison(Expression):
    """x > ALL (subquery) / x = ANY (subquery)."""
    op: str
    quantifier: str          # all | any | some
    operand: Expression
    query: "Query"


@dataclass(frozen=True)
class Like(Expression):
    operand: Expression
    pattern: Expression
    escape: Optional[Expression] = None
    negated: bool = False


@dataclass(frozen=True)
class Case(Expression):
    """Searched CASE; simple CASE is desugared by the parser
    (reference: sql/tree/SimpleCaseExpression rewritten in analysis)."""
    whens: Tuple[Tuple[Expression, Expression], ...]
    default: Optional[Expression] = None


@dataclass(frozen=True)
class Cast(Expression):
    operand: Expression
    type_name: str
    safe: bool = False       # TRY_CAST


@dataclass(frozen=True)
class FunctionCall(Expression):
    name: str                # lower-cased
    args: Tuple[Expression, ...]
    distinct: bool = False
    filter: Optional[Expression] = None       # FILTER (WHERE ...)
    order_by: Tuple["SortItem", ...] = ()     # for array_agg etc.
    window: Optional["WindowSpec"] = None     # OVER (...)


@dataclass(frozen=True)
class WindowSpec(Node):
    partition_by: Tuple[Expression, ...] = ()
    order_by: Tuple["SortItem", ...] = ()
    frame: Optional["WindowFrame"] = None


@dataclass(frozen=True)
class WindowFrame(Node):
    unit: str                # rows | range | groups
    start_type: str          # unbounded_preceding|preceding|current|following|unbounded_following
    start_value: Optional[Expression] = None
    end_type: str = "current"
    end_value: Optional[Expression] = None


@dataclass(frozen=True)
class Extract(Expression):
    field: str               # year | month | day | hour | minute | second ...
    operand: Expression


@dataclass(frozen=True)
class Subscript(Expression):
    base: Expression
    index: Expression


@dataclass(frozen=True)
class RowConstructor(Expression):
    items: Tuple[Expression, ...]


@dataclass(frozen=True)
class ArrayConstructor(Expression):
    items: Tuple[Expression, ...]


@dataclass(frozen=True)
class LambdaExpression(Expression):
    params: Tuple[str, ...]
    body: Expression


# --------------------------------------------------------------------------
# Relations (reference: sql/tree/Relation.java subclasses)
# --------------------------------------------------------------------------

class Relation(Node):
    __slots__ = ()


@dataclass(frozen=True)
class Table(Relation):
    parts: Tuple[str, ...]   # [catalog.][schema.]table

    def __str__(self) -> str:
        return ".".join(self.parts)


@dataclass(frozen=True)
class AliasedRelation(Relation):
    relation: Relation
    alias: str
    column_names: Tuple[str, ...] = ()


@dataclass(frozen=True)
class SubqueryRelation(Relation):
    query: "Query"


@dataclass(frozen=True)
class Join(Relation):
    join_type: str           # inner | left | right | full | cross
    left: Relation
    right: Relation
    on: Optional[Expression] = None
    using: Tuple[str, ...] = ()


@dataclass(frozen=True)
class Unnest(Relation):
    exprs: Tuple[Expression, ...]
    with_ordinality: bool = False


@dataclass(frozen=True)
class ValuesRelation(Relation):
    rows: Tuple[Tuple[Expression, ...], ...]


@dataclass(frozen=True)
class TableSample(Relation):
    relation: Relation
    method: str              # bernoulli | system
    percentage: Expression = None  # type: ignore


# --------------------------------------------------------------------------
# Query structure (reference: sql/tree/{Query,QuerySpecification,...}.java)
# --------------------------------------------------------------------------

@dataclass(frozen=True)
class SelectItem(Node):
    expr: Expression
    alias: Optional[str] = None


@dataclass(frozen=True)
class SortItem(Node):
    expr: Expression
    ascending: bool = True
    nulls_first: Optional[bool] = None  # None = type default (last for asc)


@dataclass(frozen=True)
class GroupingSets(Node):
    """GROUP BY GROUPING SETS / CUBE / ROLLUP — normalized to explicit
    sets of expression indices into a flat expression list."""
    exprs: Tuple[Expression, ...]
    sets: Tuple[Tuple[int, ...], ...]


class QueryBody(Node):
    __slots__ = ()


@dataclass(frozen=True)
class QuerySpecification(QueryBody):
    select_items: Tuple[SelectItem, ...]
    distinct: bool = False
    from_: Optional[Relation] = None
    where: Optional[Expression] = None
    group_by: Optional[GroupingSets] = None
    having: Optional[Expression] = None
    order_by: Tuple[SortItem, ...] = ()
    limit: Optional[int] = None
    offset: int = 0


@dataclass(frozen=True)
class SetOperation(QueryBody):
    op: str                  # union | intersect | except
    distinct: bool
    left: QueryBody
    right: QueryBody


@dataclass(frozen=True)
class ValuesBody(QueryBody):
    rows: Tuple[Tuple[Expression, ...], ...]


@dataclass(frozen=True)
class WithQuery(Node):
    name: str
    query: "Query"
    column_names: Tuple[str, ...] = ()


@dataclass(frozen=True)
class Query(Node):
    """Full query: WITH list + body + outer ORDER BY/LIMIT (for set ops)."""
    body: QueryBody
    with_queries: Tuple[WithQuery, ...] = ()
    order_by: Tuple[SortItem, ...] = ()
    limit: Optional[int] = None
    offset: int = 0


# --------------------------------------------------------------------------
# Statements (reference: sql/tree/Statement.java subclasses)
# --------------------------------------------------------------------------

class Statement(Node):
    __slots__ = ()


@dataclass(frozen=True)
class QueryStatement(Statement):
    query: Query


@dataclass(frozen=True)
class Explain(Statement):
    statement: Statement
    analyze: bool = False
    type: str = "distributed"   # logical | distributed | io


@dataclass(frozen=True)
class ShowTables(Statement):
    schema: Optional[Tuple[str, ...]] = None
    like: Optional[str] = None


@dataclass(frozen=True)
class ShowSchemas(Statement):
    catalog: Optional[str] = None


@dataclass(frozen=True)
class ShowCatalogs(Statement):
    pass


@dataclass(frozen=True)
class ShowColumns(Statement):
    table: Tuple[str, ...] = ()


@dataclass(frozen=True)
class ShowSession(Statement):
    pass


@dataclass(frozen=True)
class ShowFunctions(Statement):
    pass


@dataclass(frozen=True)
class SetSession(Statement):
    name: str = ""
    value: Expression = None  # type: ignore


@dataclass(frozen=True)
class ResetSession(Statement):
    name: str = ""


@dataclass(frozen=True)
class ColumnDefinition(Node):
    name: str
    type_name: str
    nullable: bool = True


@dataclass(frozen=True)
class CreateTable(Statement):
    name: Tuple[str, ...]
    columns: Tuple[ColumnDefinition, ...] = ()
    query: Optional[Query] = None          # CREATE TABLE AS
    if_not_exists: bool = False
    properties: Tuple[Tuple[str, Expression], ...] = ()


@dataclass(frozen=True)
class DropTable(Statement):
    name: Tuple[str, ...] = ()
    if_exists: bool = False


@dataclass(frozen=True)
class Insert(Statement):
    table: Tuple[str, ...] = ()
    columns: Tuple[str, ...] = ()
    query: Query = None  # type: ignore


@dataclass(frozen=True)
class Delete(Statement):
    table: Tuple[str, ...] = ()
    where: Optional[Expression] = None


@dataclass(frozen=True)
class Update(Statement):
    table: Tuple[str, ...] = ()
    assignments: Tuple[Tuple[str, Expression], ...] = ()
    where: Optional[Expression] = None


@dataclass(frozen=True)
class MergeClause(Node):
    """One WHEN [NOT] MATCHED [AND cond] THEN action arm."""
    matched: bool
    condition: Optional[Expression]
    action: str                                  # update | delete | insert
    assignments: Tuple[Tuple[str, Expression], ...] = ()
    insert_columns: Tuple[str, ...] = ()
    insert_values: Tuple[Expression, ...] = ()


@dataclass(frozen=True)
class Merge(Statement):
    target: Tuple[str, ...] = ()
    target_alias: Optional[str] = None
    source: Relation = None  # type: ignore
    on: Expression = None    # type: ignore
    clauses: Tuple[MergeClause, ...] = ()


@dataclass(frozen=True)
class UseStatement(Statement):
    catalog: Optional[str] = None
    schema: str = ""


@dataclass(frozen=True)
class CreateView(Statement):
    name: Tuple[str, ...] = ()
    query: Query = None  # type: ignore
    replace: bool = False


@dataclass(frozen=True)
class DropView(Statement):
    name: Tuple[str, ...] = ()
    if_exists: bool = False


@dataclass(frozen=True)
class ShowCreate(Statement):
    kind: str = "table"       # table | view
    name: Tuple[str, ...] = ()


@dataclass(frozen=True)
class Prepare(Statement):
    name: str = ""
    statement: Statement = None  # type: ignore


@dataclass(frozen=True)
class ExecuteStmt(Statement):
    name: str = ""
    params: Tuple[Expression, ...] = ()


@dataclass(frozen=True)
class Deallocate(Statement):
    name: str = ""


@dataclass(frozen=True)
class ShowStats(Statement):
    table: Tuple[str, ...]


@dataclass(frozen=True)
class Describe(Statement):
    table: Tuple[str, ...] = ()


@dataclass(frozen=True)
class DescribeInput(Statement):
    name: str = ""


@dataclass(frozen=True)
class DescribeOutput(Statement):
    name: str = ""


@dataclass(frozen=True)
class Grant(Statement):
    """GRANT privileges ON [TABLE] t TO grantee [WITH GRANT OPTION]
    (reference: sql/tree/Grant.java, execution/GrantTask.java)."""
    privileges: Tuple[str, ...] = ()   # empty = ALL PRIVILEGES
    table: Tuple[str, ...] = ()
    grantee: str = ""
    grant_option: bool = False


@dataclass(frozen=True)
class Revoke(Statement):
    privileges: Tuple[str, ...] = ()
    table: Tuple[str, ...] = ()
    grantee: str = ""
    grant_option_for: bool = False


@dataclass(frozen=True)
class Deny(Statement):
    privileges: Tuple[str, ...] = ()
    table: Tuple[str, ...] = ()
    grantee: str = ""


@dataclass(frozen=True)
class ShowGrants(Statement):
    table: Optional[Tuple[str, ...]] = None


@dataclass(frozen=True)
class CallStatement(Statement):
    name: Tuple[str, ...] = ()
    args: Tuple[Expression, ...] = ()


@dataclass(frozen=True)
class StartTransaction(Statement):
    pass


@dataclass(frozen=True)
class Commit(Statement):
    pass


@dataclass(frozen=True)
class Rollback(Statement):
    pass


def replace_parameters(node, values):
    """Substitute `?` placeholders (Literal(type_name='parameter')) with
    the given Literal values, in source order (reference:
    sql/planner/ParameterRewriter.java). Raises ValueError on arity
    mismatch."""
    import dataclasses as _dc
    state = [0]

    def conv(v):
        if isinstance(v, Node):
            return go(v)
        if isinstance(v, tuple):
            return tuple(conv(x) for x in v)
        return v

    def go(n):
        if isinstance(n, Literal) and n.type_name == "parameter":
            i = state[0]
            state[0] += 1
            if i >= len(values):
                raise ValueError(
                    f"query takes at least {state[0]} parameters but "
                    f"only {len(values)} were given")
            return values[i]
        if hasattr(n, "__dataclass_fields__"):
            changes = {}
            for f in n.__dataclass_fields__:
                v = getattr(n, f)
                nv = conv(v)
                if nv is not v:
                    changes[f] = nv
            return _dc.replace(n, **changes) if changes else n
        return n

    out = go(node)
    return out, state[0]


def count_parameters(node) -> int:
    return sum(1 for e in walk_expressions(node)
               if isinstance(e, Literal) and e.type_name == "parameter")


def walk_expressions(node, cross_subqueries: bool = True):
    """Yield every Expression reachable from an AST node (pre-order).

    ``cross_subqueries=False`` stops at subquery boundaries
    (QueryStatement/Relation values): an aggregate or window call
    inside a ScalarSubquery belongs to THAT query's planning, not the
    enclosing one — descending made `CASE WHEN (SELECT count(*) ...)`
    hoist the inner aggregate into the outer AggregationNode."""
    def _push(stack, v):
        if isinstance(v, (Query, QueryBody, QueryStatement, Relation)) \
                and not cross_subqueries:
            return
        if isinstance(v, Node):
            stack.append(v)

    stack = [node]
    while stack:
        n = stack.pop()
        if isinstance(n, Expression):
            yield n
        if hasattr(n, "__dataclass_fields__"):
            for f in n.__dataclass_fields__:
                v = getattr(n, f)
                if isinstance(v, Node):
                    _push(stack, v)
                elif isinstance(v, tuple):
                    for item in v:
                        if isinstance(item, Node):
                            _push(stack, item)
                        elif isinstance(item, tuple):
                            for x in item:
                                if isinstance(x, Node):
                                    _push(stack, x)
