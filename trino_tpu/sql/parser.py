"""Recursive-descent SQL parser.

Reference parity: core/trino-parser (SqlBase.g4 888-line grammar +
AstBuilder.java). Covers the executable surface: SELECT queries (joins,
subqueries, set operations, WITH, window functions, grouping sets),
VALUES, EXPLAIN, SHOW, SET/RESET SESSION, CREATE TABLE [AS], INSERT,
DELETE, USE. Operator precedence follows the grammar's booleanExpression/
valueExpression/primaryExpression stratification.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from . import ast as A
from .tokenizer import ParseError, Token, tokenize

_RESERVED_STOP = {
    # keywords that terminate an expression / select item / relation
    "from", "where", "group", "having", "order", "limit", "offset", "union",
    "intersect", "except", "on", "using", "join", "inner", "left", "right",
    "full", "cross", "as", "by", "asc", "desc", "nulls", "when", "then",
    "else", "end", "and", "or", "not", "in", "like", "between", "is",
    "select", "with", "fetch", "escape", "case", "cast", "distinct", "all",
    "any", "some", "exists", "over", "partition", "rows", "range", "groups",
    "filter", "tablesample",
}

_INTERVAL_UNITS = {"year", "month", "day", "hour", "minute", "second",
                   "week", "quarter"}

_EXTRACT_FIELDS = {"year", "quarter", "month", "week", "day", "day_of_month",
                   "day_of_week", "dow", "day_of_year", "doy",
                   "year_of_week", "yow", "hour", "minute", "second",
                   "timezone_hour", "timezone_minute"}


def parse_statement(sql: str) -> A.Statement:
    return _Parser(tokenize(sql)).parse_statement()


def parse_expression(sql: str) -> A.Expression:
    p = _Parser(tokenize(sql))
    e = p.expression()
    p.expect_eof()
    return e


class _Parser:
    def __init__(self, tokens: List[Token]):
        self.tokens = tokens
        self.pos = 0

    # --- token utilities --------------------------------------------------
    def peek(self, ahead: int = 0) -> Token:
        return self.tokens[min(self.pos + ahead, len(self.tokens) - 1)]

    def next(self) -> Token:
        t = self.tokens[self.pos]
        if t.kind != "eof":
            self.pos += 1
        return t

    def at_kw(self, *kws: str, ahead: int = 0) -> bool:
        t = self.peek(ahead)
        return t.kind == "ident" and t.value in kws

    def at_op(self, *ops: str, ahead: int = 0) -> bool:
        t = self.peek(ahead)
        return t.kind == "op" and t.value in ops

    def accept_kw(self, *kws: str) -> bool:
        if self.at_kw(*kws):
            self.next()
            return True
        return False

    def accept_op(self, *ops: str) -> bool:
        if self.at_op(*ops):
            self.next()
            return True
        return False

    def expect_kw(self, kw: str) -> Token:
        t = self.peek()
        if not self.at_kw(kw):
            raise ParseError(f"expected {kw.upper()}, found {t.value!r}",
                             t.line, t.column)
        return self.next()

    def expect_op(self, op: str) -> Token:
        t = self.peek()
        if not self.at_op(op):
            raise ParseError(f"expected {op!r}, found {t.value!r}",
                             t.line, t.column)
        return self.next()

    def expect_eof(self):
        t = self.peek()
        if t.kind == "op" and t.value == ";":
            self.next()     # one trailing semicolon is fine ...
            t = self.peek()
        if t.kind != "eof":  # ... but further statements are rejected
            raise ParseError(f"unexpected trailing input {t.value!r}",
                             t.line, t.column)

    def integer(self) -> int:
        t = self.peek()
        if t.kind != "integer":
            raise ParseError(f"expected integer, found {t.value!r}",
                             t.line, t.column)
        self.next()
        return int(t.value)

    def identifier(self) -> str:
        t = self.peek()
        if t.kind in ("ident", "qident"):
            self.next()
            return t.value
        raise ParseError(f"expected identifier, found {t.value!r}",
                         t.line, t.column)

    def qualified_name(self) -> Tuple[str, ...]:
        parts = [self.identifier()]
        while self.accept_op("."):
            parts.append(self.identifier())
        return tuple(parts)

    # --- statements -------------------------------------------------------
    def parse_statement(self) -> A.Statement:
        stmt = self._statement()
        self.expect_eof()
        return stmt

    def _statement(self) -> A.Statement:
        if self.at_kw("explain"):
            self.next()
            analyze = self.accept_kw("analyze")
            etype = "distributed"
            if self.accept_op("("):
                while not self.accept_op(")"):
                    if self.peek().kind == "eof":
                        raise ParseError("unexpected end of EXPLAIN "
                                         "options", self.peek().line,
                                         self.peek().column)
                    if self.accept_kw("type"):
                        etype = self.identifier()
                    elif self.accept_kw("format"):
                        self.identifier()
                    else:
                        self.next()
                    self.accept_op(",")
            return A.Explain(self._statement(), analyze, etype)
        if self.at_kw("show"):
            return self._show()
        if self.at_kw("grant", "revoke", "deny"):
            return self._grant()
        if self.at_kw("set"):
            self.next()
            self.expect_kw("session")
            name = ".".join(self.qualified_name())
            self.expect_op("=")
            return A.SetSession(name, self.expression())
        if self.at_kw("reset"):
            self.next()
            self.expect_kw("session")
            return A.ResetSession(".".join(self.qualified_name()))
        if self.at_kw("use"):
            self.next()
            parts = self.qualified_name()
            if len(parts) == 2:
                return A.UseStatement(parts[0], parts[1])
            return A.UseStatement(None, parts[0])
        if self.at_kw("create"):
            if self.at_kw("view", ahead=1) or (
                    self.at_kw("or", ahead=1)
                    and self.at_kw("replace", ahead=2)
                    and self.at_kw("view", ahead=3)):
                self.next()
                replace = False
                if self.accept_kw("or"):
                    self.expect_kw("replace")
                    replace = True
                self.expect_kw("view")
                name = self.qualified_name()
                self.expect_kw("as")
                return A.CreateView(name, self.query(), replace)
            return self._create_table()
        if self.at_kw("drop"):
            self.next()
            kind = "view" if self.accept_kw("view") else "table"
            if kind == "table":
                self.expect_kw("table")
            if_exists = False
            if self.accept_kw("if"):
                self.expect_kw("exists")
                if_exists = True
            if kind == "view":
                return A.DropView(self.qualified_name(), if_exists)
            return A.DropTable(self.qualified_name(), if_exists)
        if self.at_kw("describe", "desc"):
            self.next()
            if self.accept_kw("input"):
                return A.DescribeInput(self.identifier())
            if self.accept_kw("output"):
                return A.DescribeOutput(self.identifier())
            return A.Describe(self.qualified_name())
        if self.at_kw("prepare"):
            self.next()
            name = self.identifier()
            self.expect_kw("from")
            return A.Prepare(name, self._statement())
        if self.at_kw("execute"):
            self.next()
            name = self.identifier()
            params: List[A.Expression] = []
            if self.accept_kw("using"):
                params.append(self.expression())
                while self.accept_op(","):
                    params.append(self.expression())
            return A.ExecuteStmt(name, tuple(params))
        if self.at_kw("deallocate"):
            self.next()
            self.accept_kw("prepare")
            return A.Deallocate(self.identifier())
        if self.at_kw("call"):
            self.next()
            name = self.qualified_name()
            args: List[A.Expression] = []
            self.expect_op("(")
            if not self.at_op(")"):
                args.append(self.expression())
                while self.accept_op(","):
                    args.append(self.expression())
            self.expect_op(")")
            return A.CallStatement(name, tuple(args))
        if self.at_kw("start"):
            self.next()
            self.expect_kw("transaction")
            # isolation/read-only modifiers accepted and ignored
            while self.peek().kind != "eof":
                self.next()
            return A.StartTransaction()
        if self.at_kw("commit"):
            self.next()
            self.accept_kw("work")
            return A.Commit()
        if self.at_kw("rollback"):
            self.next()
            self.accept_kw("work")
            return A.Rollback()
        if self.at_kw("insert"):
            self.next()
            self.expect_kw("into")
            table = self.qualified_name()
            columns: Tuple[str, ...] = ()
            if self.at_op("(") and self._looks_like_column_list():
                self.expect_op("(")
                cols = [self.identifier()]
                while self.accept_op(","):
                    cols.append(self.identifier())
                self.expect_op(")")
                columns = tuple(cols)
            return A.Insert(table, columns, self.query())
        if self.at_kw("delete"):
            self.next()
            self.expect_kw("from")
            table = self.qualified_name()
            where = self.expression() if self.accept_kw("where") else None
            return A.Delete(table, where)
        if self.at_kw("update"):
            self.next()
            table = self.qualified_name()
            self.expect_kw("set")
            assigns = [self._assignment()]
            while self.accept_op(","):
                assigns.append(self._assignment())
            where = self.expression() if self.accept_kw("where") else None
            return A.Update(table, tuple(assigns), where)
        if self.at_kw("merge"):
            return self._merge()
        return A.QueryStatement(self.query())

    def _assignment(self):
        name = self.identifier()
        self.expect_op("=")
        return (name, self.expression())

    def _merge(self) -> "A.Merge":
        self.expect_kw("merge")
        self.expect_kw("into")
        target = self.qualified_name()
        alias = None
        if self.accept_kw("as"):
            alias = self.identifier()
        elif not self.at_kw("using"):
            alias = self.identifier()
        self.expect_kw("using")
        source = self._table_or_subquery()
        self.expect_kw("on")
        on = self.expression()
        clauses = []
        while self.at_kw("when"):
            self.next()
            matched = not self.accept_kw("not")
            self.expect_kw("matched")
            cond = self.expression() if self.accept_kw("and") else None
            self.expect_kw("then")
            if self.accept_kw("update"):
                self.expect_kw("set")
                assigns = [self._assignment()]
                while self.accept_op(","):
                    assigns.append(self._assignment())
                clauses.append(A.MergeClause(matched, cond, "update",
                                             tuple(assigns)))
            elif self.accept_kw("delete"):
                clauses.append(A.MergeClause(matched, cond, "delete"))
            else:
                self.expect_kw("insert")
                cols: List[str] = []
                if self.at_op("("):
                    self.expect_op("(")
                    cols.append(self.identifier())
                    while self.accept_op(","):
                        cols.append(self.identifier())
                    self.expect_op(")")
                self.expect_kw("values")
                self.expect_op("(")
                vals = [self.expression()]
                while self.accept_op(","):
                    vals.append(self.expression())
                self.expect_op(")")
                clauses.append(A.MergeClause(
                    matched, cond, "insert", (), tuple(cols),
                    tuple(vals)))
        if not clauses:
            t = self.peek()
            raise ParseError("MERGE requires at least one WHEN clause",
                             t.line, t.column)
        return A.Merge(target, alias, source, on, tuple(clauses))

    def _table_or_subquery(self) -> "A.Relation":
        if self.at_op("("):
            self.expect_op("(")
            q = self.query()
            self.expect_op(")")
            rel: A.Relation = A.SubqueryRelation(q)
        else:
            rel = A.Table(self.qualified_name())
        return self._maybe_alias(rel)

    def _looks_like_column_list(self) -> bool:
        # distinguish INSERT INTO t (a, b) SELECT  from  INSERT INTO t (SELECT ...)
        return not self.at_kw("select", "with", "values", ahead=1)

    def _show(self) -> A.Statement:
        self.expect_kw("show")
        if self.accept_kw("tables"):
            schema = None
            if self.accept_kw("from", "in"):
                schema = self.qualified_name()
            like = None
            if self.accept_kw("like"):
                like = self.next().value
            return A.ShowTables(schema, like)
        if self.accept_kw("schemas"):
            catalog = None
            if self.accept_kw("from", "in"):
                catalog = self.identifier()
            return A.ShowSchemas(catalog)
        if self.accept_kw("catalogs"):
            return A.ShowCatalogs()
        if self.accept_kw("columns"):
            self.expect_kw("from")
            return A.ShowColumns(self.qualified_name())
        if self.accept_kw("session"):
            return A.ShowSession()
        if self.accept_kw("functions"):
            return A.ShowFunctions()
        if self.accept_kw("create"):
            kind = "view" if self.accept_kw("view") else "table"
            if kind == "table":
                self.expect_kw("table")
            return A.ShowCreate(kind, self.qualified_name())
        if self.accept_kw("stats"):
            self.expect_kw("for")
            return A.ShowStats(self.qualified_name())
        if self.accept_kw("grants"):
            table = None
            if self.accept_kw("on"):
                self.accept_kw("table")
                table = self.qualified_name()
            return A.ShowGrants(table)
        t = self.peek()
        raise ParseError(f"unsupported SHOW {t.value!r}", t.line, t.column)

    _PRIVILEGES = ("select", "insert", "delete", "update")

    def _privilege_list(self) -> Tuple[Tuple[str, ...], bool]:
        """privilege [, ...] | ALL PRIVILEGES -> (privs, is_all)."""
        if self.accept_kw("all"):
            self.accept_kw("privileges")
            return tuple(self._PRIVILEGES), True
        privs = []
        while True:
            t = self.peek()
            p = self.identifier().lower()
            if p not in self._PRIVILEGES:
                raise ParseError(f"unknown privilege {p!r}", t.line,
                                 t.column)
            privs.append(p)
            if not self.accept_op(","):
                break
        return tuple(privs), False

    def _grant(self) -> A.Statement:
        """GRANT/REVOKE/DENY (reference: sql/tree/{Grant,Revoke,Deny}
        grammar rules in SqlBase.g4)."""
        if self.accept_kw("grant"):
            privs, _ = self._privilege_list()
            self.expect_kw("on")
            self.accept_kw("table")
            table = self.qualified_name()
            self.expect_kw("to")
            self.accept_kw("user", "role")
            grantee = self.identifier()
            opt = False
            if self.accept_kw("with"):
                self.expect_kw("grant")
                self.expect_kw("option")
                opt = True
            return A.Grant(privs, table, grantee, opt)
        if self.accept_kw("deny"):
            privs, _ = self._privilege_list()
            self.expect_kw("on")
            self.accept_kw("table")
            table = self.qualified_name()
            self.expect_kw("to")
            self.accept_kw("user", "role")
            return A.Deny(privs, table, self.identifier())
        self.expect_kw("revoke")
        opt = False
        if self.accept_kw("grant"):
            self.expect_kw("option")
            self.expect_kw("for")
            opt = True
        privs, _ = self._privilege_list()
        self.expect_kw("on")
        self.accept_kw("table")
        table = self.qualified_name()
        self.expect_kw("from")
        self.accept_kw("user", "role")
        return A.Revoke(privs, table, self.identifier(), opt)

    def _create_table(self) -> A.Statement:
        self.expect_kw("create")
        self.expect_kw("table")
        if_not_exists = False
        if self.accept_kw("if"):
            self.expect_kw("not")
            self.expect_kw("exists")
            if_not_exists = True
        name = self.qualified_name()
        columns: List[A.ColumnDefinition] = []
        query = None
        if self.at_op("(") and not self.at_kw(
                "select", "with", "values", ahead=1):
            self.expect_op("(")
            while True:
                cname = self.identifier()
                ctype = self._type_name()
                nullable = True
                if self.accept_kw("not"):
                    self.expect_kw("null")
                    nullable = False
                columns.append(A.ColumnDefinition(cname, ctype, nullable))
                if not self.accept_op(","):
                    break
            self.expect_op(")")
        props: List[Tuple[str, A.Expression]] = []
        if self.accept_kw("with"):
            self.expect_op("(")
            while True:
                pname = self.identifier()
                self.expect_op("=")
                props.append((pname, self.expression()))
                if not self.accept_op(","):
                    break
            self.expect_op(")")
        if self.accept_kw("as"):
            if self.accept_op("("):
                query = self.query()
                self.expect_op(")")
            else:
                query = self.query()
        return A.CreateTable(name, tuple(columns), query, if_not_exists,
                             tuple(props))

    # --- queries ----------------------------------------------------------
    def query(self) -> A.Query:
        with_queries: List[A.WithQuery] = []
        if self.accept_kw("with"):
            self.accept_kw("recursive")
            while True:
                name = self.identifier()
                cols: Tuple[str, ...] = ()
                if self.accept_op("("):
                    cl = [self.identifier()]
                    while self.accept_op(","):
                        cl.append(self.identifier())
                    self.expect_op(")")
                    cols = tuple(cl)
                self.expect_kw("as")
                self.expect_op("(")
                q = self.query()
                self.expect_op(")")
                with_queries.append(A.WithQuery(name, q, cols))
                if not self.accept_op(","):
                    break
        body = self._set_operation()
        order_by: Tuple[A.SortItem, ...] = ()
        limit = None
        offset = 0
        if self.accept_kw("order"):
            self.expect_kw("by")
            order_by = self._sort_items()
        if self.accept_kw("offset"):
            offset = self.integer()
            self.accept_kw("rows", "row")
        if self.accept_kw("limit"):
            limit = None if self.accept_kw("all") else self.integer()
            # postgres-style trailing OFFSET (Trino puts OFFSET first;
            # accept both orders)
            if self.accept_kw("offset"):
                offset = self.integer()
                self.accept_kw("rows", "row")
        if self.accept_kw("fetch"):
            self.accept_kw("first", "next")
            limit = self.integer()
            self.accept_kw("rows", "row")
            self.accept_kw("only")
        if isinstance(body, A.QuerySpecification) and (
                order_by or limit is not None or offset):
            # ORDER BY / LIMIT / OFFSET of a plain SELECT live on the spec
            # (reference: SqlBase.g4 puts them at the query level; the
            # planner reads them off QuerySpecification for a simple query)
            body = A.QuerySpecification(
                body.select_items, body.distinct, body.from_, body.where,
                body.group_by, body.having, order_by, limit, offset)
            order_by, limit, offset = (), None, 0
        if not with_queries and not order_by and limit is None \
                and not offset:
            return A.Query(body)
        return A.Query(body, tuple(with_queries), order_by, limit, offset)

    def _set_operation(self) -> A.QueryBody:
        # UNION/EXCEPT level; INTERSECT binds tighter (SQL standard,
        # reference: SqlBase.g4 queryTerm stratification)
        left = self._intersect_term()
        while self.at_kw("union", "except"):
            op = self.next().value
            distinct = True
            if self.accept_kw("all"):
                distinct = False
            else:
                self.accept_kw("distinct")
            right = self._intersect_term()
            left = A.SetOperation(op, distinct, left, right)
        return left

    def _intersect_term(self) -> A.QueryBody:
        left = self._query_term()
        while self.at_kw("intersect"):
            self.next()
            distinct = True
            if self.accept_kw("all"):
                distinct = False
            else:
                self.accept_kw("distinct")
            right = self._query_term()
            left = A.SetOperation("intersect", distinct, left, right)
        return left

    def _query_term(self) -> A.QueryBody:
        if self.accept_op("("):
            q = self.query()
            self.expect_op(")")
            # flatten parenthesized query back into a body
            if (not q.with_queries and not q.order_by and q.limit is None
                    and not q.offset):
                return q.body
            # wrap: a parenthesized full query inside a set op — treat as
            # a subquery spec selecting all of it
            return A.QuerySpecification(
                select_items=(A.SelectItem(A.Star()),),
                from_=A.SubqueryRelation(q))
        if self.at_kw("values"):
            self.next()
            rows = [self._values_row()]
            while self.accept_op(","):
                rows.append(self._values_row())
            return A.ValuesBody(tuple(rows))
        return self._query_spec()

    def _values_row(self) -> Tuple[A.Expression, ...]:
        if self.accept_op("("):
            items = [self.expression()]
            while self.accept_op(","):
                items.append(self.expression())
            self.expect_op(")")
            return tuple(items)
        return (self.expression(),)

    def _query_spec(self) -> A.QuerySpecification:
        self.expect_kw("select")
        distinct = False
        if self.accept_kw("distinct"):
            distinct = True
        else:
            self.accept_kw("all")
        items = [self._select_item()]
        while self.accept_op(","):
            items.append(self._select_item())
        from_ = None
        if self.accept_kw("from"):
            from_ = self._relation()
            while self.accept_op(","):
                right = self._relation()
                from_ = A.Join("cross", from_, right)
        where = self.expression() if self.accept_kw("where") else None
        group_by = None
        if self.accept_kw("group"):
            self.expect_kw("by")
            group_by = self._grouping()
        having = self.expression() if self.accept_kw("having") else None
        return A.QuerySpecification(tuple(items), distinct, from_, where,
                                    group_by, having)

    def _select_item(self) -> A.SelectItem:
        if self.at_op("*"):
            self.next()
            return A.SelectItem(A.Star())
        # t.*  — lookahead: ident . *
        if (self.peek().kind in ("ident", "qident")
                and self.at_op(".", ahead=1) and self.at_op("*", ahead=2)):
            q = self.identifier()
            self.next()
            self.next()
            return A.SelectItem(A.Star(q))
        e = self.expression()
        alias = None
        if self.accept_kw("as"):
            alias = self.identifier()
        elif (self.peek().kind == "qident"
              or (self.peek().kind == "ident"
                  and self.peek().value not in _RESERVED_STOP)):
            alias = self.identifier()
        return A.SelectItem(e, alias)

    def _sort_items(self) -> Tuple[A.SortItem, ...]:
        items = [self._sort_item()]
        while self.accept_op(","):
            items.append(self._sort_item())
        return tuple(items)

    def _sort_item(self) -> A.SortItem:
        e = self.expression()
        asc = True
        if self.accept_kw("desc"):
            asc = False
        else:
            self.accept_kw("asc")
        nulls_first = None
        if self.accept_kw("nulls"):
            if self.accept_kw("first"):
                nulls_first = True
            else:
                self.expect_kw("last")
                nulls_first = False
        return A.SortItem(e, asc, nulls_first)

    def _grouping(self) -> A.GroupingSets:
        """GROUP BY list, with GROUPING SETS/ROLLUP/CUBE normalized to
        explicit index sets (reference: sql/analyzer groupingSets
        normalization in StatementAnalyzer)."""
        exprs: List[A.Expression] = []
        sets: List[Tuple[int, ...]] = []
        simple: List[int] = []

        def intern(e: A.Expression) -> int:
            exprs.append(e)
            return len(exprs) - 1

        def parse_set() -> Tuple[int, ...]:
            if self.accept_op("("):
                if self.accept_op(")"):
                    return ()
                ids = [intern(self.expression())]
                while self.accept_op(","):
                    ids.append(intern(self.expression()))
                self.expect_op(")")
                return tuple(ids)
            return (intern(self.expression()),)

        complex_sets: List[List[Tuple[int, ...]]] = []
        while True:
            if self.at_kw("grouping"):
                self.next()
                self.expect_kw("sets")
                self.expect_op("(")
                gs = [parse_set()]
                while self.accept_op(","):
                    gs.append(parse_set())
                self.expect_op(")")
                complex_sets.append(gs)
            elif self.at_kw("rollup"):
                self.next()
                self.expect_op("(")
                ids = [intern(self.expression())]
                while self.accept_op(","):
                    ids.append(intern(self.expression()))
                self.expect_op(")")
                complex_sets.append(
                    [tuple(ids[:k]) for k in range(len(ids), -1, -1)])
            elif self.at_kw("cube"):
                self.next()
                self.expect_op("(")
                ids = [intern(self.expression())]
                while self.accept_op(","):
                    ids.append(intern(self.expression()))
                self.expect_op(")")
                out = []
                for mask in range(1 << len(ids)):
                    out.append(tuple(ids[k] for k in range(len(ids))
                                     if mask & (1 << k)))
                complex_sets.append(out[::-1])
            else:
                simple.append(intern(self.expression()))
            if not self.accept_op(","):
                break
        if not complex_sets:
            sets = [tuple(simple)]
        else:
            # cross-product of grouping element sets, prefixed by simple cols
            base: List[Tuple[int, ...]] = [tuple(simple)]
            for gs in complex_sets:
                base = [b + s for b in base for s in gs]
            sets = base
        return A.GroupingSets(tuple(exprs), tuple(sets))

    # --- relations --------------------------------------------------------
    def _relation(self) -> A.Relation:
        left = self._sampled_relation()
        while True:
            if self.accept_kw("cross"):
                self.expect_kw("join")
                right = self._sampled_relation()
                left = A.Join("cross", left, right)
                continue
            jt = None
            if self.at_kw("join"):
                jt = "inner"
            elif self.at_kw("inner") and self.at_kw("join", ahead=1):
                self.next()
                jt = "inner"
            elif self.at_kw("left", "right", "full"):
                jt = self.peek().value
                self.next()
                self.accept_kw("outer")
            if jt is None:
                return left
            self.expect_kw("join")
            right = self._sampled_relation()
            if self.accept_kw("on"):
                left = A.Join(jt, left, right, on=self.expression())
            elif self.accept_kw("using"):
                self.expect_op("(")
                cols = [self.identifier()]
                while self.accept_op(","):
                    cols.append(self.identifier())
                self.expect_op(")")
                left = A.Join(jt, left, right, using=tuple(cols))
            else:
                t = self.peek()
                raise ParseError("JOIN requires ON or USING",
                                 t.line, t.column)

    def _sampled_relation(self) -> A.Relation:
        rel = self._aliased_relation()
        if self.accept_kw("tablesample"):
            method = self.identifier()
            self.expect_op("(")
            pct = self.expression()
            self.expect_op(")")
            rel = A.TableSample(rel, method, pct)
            # alias may follow the sample
            rel = self._maybe_alias(rel)
        return rel

    def _aliased_relation(self) -> A.Relation:
        rel = self._primary_relation()
        return self._maybe_alias(rel)

    def _maybe_alias(self, rel: A.Relation) -> A.Relation:
        alias = None
        cols: Tuple[str, ...] = ()
        if self.accept_kw("as"):
            alias = self.identifier()
        elif (self.peek().kind == "qident"
              or (self.peek().kind == "ident"
                  and self.peek().value not in _RESERVED_STOP)):
            alias = self.identifier()
        if alias is not None:
            if self.at_op("(") and self.peek(1).kind in ("ident", "qident") \
                    and (self.at_op(",", ahead=2) or self.at_op(")", ahead=2)):
                self.expect_op("(")
                cl = [self.identifier()]
                while self.accept_op(","):
                    cl.append(self.identifier())
                self.expect_op(")")
                cols = tuple(cl)
            return A.AliasedRelation(rel, alias, cols)
        return rel

    def _primary_relation(self) -> A.Relation:
        if self.accept_op("("):
            if self.at_kw("select", "with", "values") or self.at_op("("):
                q = self.query()
                self.expect_op(")")
                return A.SubqueryRelation(q)
            rel = self._relation()
            self.expect_op(")")
            return rel
        if self.at_kw("unnest"):
            self.next()
            self.expect_op("(")
            exprs = [self.expression()]
            while self.accept_op(","):
                exprs.append(self.expression())
            self.expect_op(")")
            with_ord = False
            if self.accept_kw("with"):
                self.expect_kw("ordinality")
                with_ord = True
            return A.Unnest(tuple(exprs), with_ord)
        if self.at_kw("values"):
            self.next()
            rows = [self._values_row()]
            while self.accept_op(","):
                rows.append(self._values_row())
            return A.ValuesRelation(tuple(rows))
        return A.Table(self.qualified_name())

    # --- expressions ------------------------------------------------------
    def expression(self) -> A.Expression:
        return self._or_expr()

    def _or_expr(self) -> A.Expression:
        left = self._and_expr()
        while self.accept_kw("or"):
            left = A.BinaryOp("or", left, self._and_expr())
        return left

    def _and_expr(self) -> A.Expression:
        left = self._not_expr()
        while self.accept_kw("and"):
            left = A.BinaryOp("and", left, self._not_expr())
        return left

    def _not_expr(self) -> A.Expression:
        if self.accept_kw("not"):
            return A.UnaryOp("not", self._not_expr())
        return self._predicate()

    def _predicate(self) -> A.Expression:
        if self.at_kw("exists"):
            self.next()
            self.expect_op("(")
            q = self.query()
            self.expect_op(")")
            return A.Exists(q)
        left = self._value_expr()
        while True:
            negated = False
            if self.at_kw("not") and self.at_kw(
                    "in", "like", "between", ahead=1):
                self.next()
                negated = True
            if self.accept_kw("in"):
                self.expect_op("(")
                if self.at_kw("select", "with"):
                    q = self.query()
                    self.expect_op(")")
                    left = A.InSubquery(left, q, negated)
                else:
                    items = [self.expression()]
                    while self.accept_op(","):
                        items.append(self.expression())
                    self.expect_op(")")
                    left = A.InList(left, tuple(items), negated)
                continue
            if self.accept_kw("like"):
                pattern = self._value_expr()
                escape = None
                if self.accept_kw("escape"):
                    escape = self._value_expr()
                left = A.Like(left, pattern, escape, negated)
                continue
            if self.accept_kw("between"):
                low = self._value_expr()
                self.expect_kw("and")
                high = self._value_expr()
                left = A.Between(left, low, high, negated)
                continue
            if self.accept_kw("is"):
                neg = self.accept_kw("not")
                if self.accept_kw("null"):
                    left = A.IsNull(left, neg)
                elif self.accept_kw("distinct"):
                    self.expect_kw("from")
                    right = self._value_expr()
                    left = A.IsDistinctFrom(left, right, neg)
                elif self.accept_kw("true"):
                    # x IS [NOT] TRUE == x IS [NOT] NOT-DISTINCT-FROM TRUE
                    # (never NULL, unlike = under 3-valued logic)
                    left = A.IsDistinctFrom(left, A.Literal(True),
                                            negated=not neg)
                elif self.accept_kw("false"):
                    left = A.IsDistinctFrom(left, A.Literal(False),
                                            negated=not neg)
                else:
                    t = self.peek()
                    raise ParseError("expected NULL or DISTINCT after IS",
                                     t.line, t.column)
                continue
            if self.at_op("=", "<>", "!=", "<", "<=", ">", ">="):
                op = self.next().value
                if op == "!=":
                    op = "<>"
                if self.at_kw("all", "any", "some"):
                    quant = self.next().value
                    self.expect_op("(")
                    q = self.query()
                    self.expect_op(")")
                    left = A.QuantifiedComparison(op, quant, left, q)
                else:
                    left = A.BinaryOp(op, left, self._value_expr())
                continue
            return left

    def _value_expr(self) -> A.Expression:
        left = self._additive()
        while self.at_op("||"):
            self.next()
            left = A.BinaryOp("||", left, self._additive())
        return left

    def _additive(self) -> A.Expression:
        left = self._multiplicative()
        while True:
            if self.at_op("+", "-"):
                op = self.next().value
                left = A.BinaryOp(op, left, self._multiplicative())
                continue
            # expr AT TIME ZONE 'zone' (reference: AtTimeZone desugar)
            if (self.at_kw("at") and self.at_kw("time", ahead=1)
                    and self.at_kw("zone", ahead=2)):
                self.next()
                self.next()
                self.next()
                left = A.FunctionCall("at_timezone",
                                      (left, self._multiplicative()))
                continue
            return left

    def _multiplicative(self) -> A.Expression:
        left = self._unary()
        while self.at_op("*", "/", "%"):
            op = self.next().value
            left = A.BinaryOp(op, left, self._unary())
        return left

    def _unary(self) -> A.Expression:
        if self.at_op("-"):
            self.next()
            return A.UnaryOp("-", self._unary())
        if self.at_op("+"):
            self.next()
            return self._unary()
        return self._postfix()

    def _postfix(self) -> A.Expression:
        e = self._primary()
        while True:
            if self.at_op("["):
                self.next()
                idx = self.expression()
                self.expect_op("]")
                e = A.Subscript(e, idx)
                continue
            if (self.at_op(".") and isinstance(e, A.Identifier)
                    and self.peek(1).kind in ("ident", "qident")):
                self.next()
                e = A.Identifier(e.parts + (self.identifier(),))
                continue
            if (self.at_op(".") and not isinstance(e, A.Identifier)):
                # row-field dereference on a non-identifier base
                self.next()
                e = A.FunctionCall("$field", (e, A.Literal(
                    self.identifier())))
                continue
            return e

    def _primary(self) -> A.Expression:
        t = self.peek()
        if t.kind == "integer":
            self.next()
            return A.Literal(int(t.value))
        if t.kind == "decimal":
            self.next()
            return A.Literal(t.value, "decimal")
        if t.kind == "float":
            self.next()
            return A.Literal(float(t.value))
        if t.kind == "string":
            self.next()
            return A.Literal(t.value)
        if t.kind == "qident":
            return A.Identifier((self.identifier(),))
        if self.at_op("("):
            self.next()
            if self.at_kw("select", "with"):
                q = self.query()
                self.expect_op(")")
                return A.ScalarSubquery(q)
            e = self.expression()
            if self.at_op(","):
                items = [e]
                while self.accept_op(","):
                    items.append(self.expression())
                self.expect_op(")")
                # (x, y) -> expr multi-parameter lambda
                if self.at_op("->", "=>") and all(
                        isinstance(i, A.Identifier) and len(i.parts) == 1
                        for i in items):
                    self.next()
                    return A.LambdaExpression(
                        tuple(i.parts[0] for i in items),
                        self.expression())
                return A.RowConstructor(tuple(items))
            self.expect_op(")")
            # (x) -> y lambda
            if self.at_op("->", "=>") and isinstance(e, A.Identifier) \
                    and len(e.parts) == 1:
                self.next()
                return A.LambdaExpression((e.parts[0],), self.expression())
            return e
        if self.at_op("?"):
            self.next()
            return A.Literal(None, "parameter")
        if t.kind != "ident":
            raise ParseError(f"unexpected token {t.value!r}",
                             t.line, t.column)
        kw = t.value
        if kw == "null":
            self.next()
            return A.Literal(None)
        if kw in ("true", "false"):
            self.next()
            return A.Literal(kw == "true")
        if kw in ("date", "timestamp", "time") and \
                self.peek(1).kind == "string":
            self.next()
            s = self.next().value
            return A.Literal(s, kw)
        if kw == "interval":
            self.next()
            sign = 1
            if self.accept_op("-"):
                sign = -1
            elif self.accept_op("+"):
                pass
            v = self.next()
            ut = self.peek()
            unit = self.identifier().rstrip("s")
            if unit not in _INTERVAL_UNITS:
                raise ParseError(f"invalid interval unit {unit!r}",
                                 ut.line, ut.column)
            # INTERVAL 'n' DAY TO SECOND — accept and keep leading unit
            if self.accept_kw("to"):
                self.identifier()
            return A.IntervalLiteral(v.value, unit, sign)
        if kw == "case":
            return self._case()
        if kw in ("cast", "try_cast"):
            self.next()
            self.expect_op("(")
            e = self.expression()
            self.expect_kw("as")
            tn = self._type_name()
            self.expect_op(")")
            return A.Cast(e, tn, safe=(kw == "try_cast"))
        if kw == "extract":
            self.next()
            self.expect_op("(")
            fld = self.identifier()
            if fld not in _EXTRACT_FIELDS:
                raise ParseError(f"invalid EXTRACT field {fld!r}",
                                 t.line, t.column)
            self.expect_kw("from")
            e = self.expression()
            self.expect_op(")")
            return A.Extract(fld, e)
        if kw == "substring" and self.at_op("(", ahead=1):
            # substring(x FROM a [FOR b]) or substring(x, a, b)
            self.next()
            self.expect_op("(")
            e = self.expression()
            if self.accept_kw("from"):
                start = self.expression()
                length = None
                if self.accept_kw("for"):
                    length = self.expression()
                self.expect_op(")")
                args = (e, start) if length is None else (e, start, length)
                return A.FunctionCall("substring", args)
            args = [e]
            while self.accept_op(","):
                args.append(self.expression())
            self.expect_op(")")
            return A.FunctionCall("substring", tuple(args))
        if kw == "position" and self.at_op("(", ahead=1):
            self.next()
            self.expect_op("(")
            sub = self.expression()
            self.expect_kw("in")
            s = self.expression()
            self.expect_op(")")
            return A.FunctionCall("strpos", (s, sub))
        if kw == "trim" and self.at_op("(", ahead=1):
            self.next()
            self.expect_op("(")
            fn = "trim"
            if self.at_kw("leading", "trailing", "both"):
                side = self.next().value
                fn = {"leading": "ltrim", "trailing": "rtrim",
                      "both": "trim"}[side]
                if self.accept_kw("from"):
                    e = self.expression()
                    self.expect_op(")")
                    return A.FunctionCall(fn, (e,))
                chars = self.expression()
                self.expect_kw("from")
                e = self.expression()
                self.expect_op(")")
                return A.FunctionCall(fn, (e, chars))
            e = self.expression()
            self.expect_op(")")
            return A.FunctionCall(fn, (e,))
        if kw == "array" and self.at_op("[", ahead=1):
            self.next()
            self.next()
            items = []
            if not self.at_op("]"):
                items.append(self.expression())
                while self.accept_op(","):
                    items.append(self.expression())
            self.expect_op("]")
            return A.ArrayConstructor(tuple(items))
        if kw == "row" and self.at_op("(", ahead=1):
            self.next()
            self.expect_op("(")
            items = [self.expression()]
            while self.accept_op(","):
                items.append(self.expression())
            self.expect_op(")")
            return A.RowConstructor(tuple(items))
        if kw in ("current_date", "current_timestamp", "current_time",
                  "localtime", "localtimestamp", "current_user"):
            self.next()
            if self.accept_op("("):
                self.expect_op(")")
            return A.FunctionCall(kw, ())
        # function call or plain identifier
        if self.at_op("(", ahead=1):
            return self._function_call()
        name = self.identifier()
        # single-param lambda:  x -> expr
        if self.at_op("->", "=>"):
            self.next()
            return A.LambdaExpression((name,), self.expression())
        return A.Identifier((name,))

    def _case(self) -> A.Expression:
        self.expect_kw("case")
        operand = None
        if not self.at_kw("when"):
            operand = self.expression()
        whens: List[Tuple[A.Expression, A.Expression]] = []
        while self.accept_kw("when"):
            cond = self.expression()
            if operand is not None:
                cond = A.BinaryOp("=", operand, cond)
            self.expect_kw("then")
            whens.append((cond, self.expression()))
        default = self.expression() if self.accept_kw("else") else None
        self.expect_kw("end")
        return A.Case(tuple(whens), default)

    def _function_call(self) -> A.Expression:
        name = self.identifier()
        self.expect_op("(")
        distinct = False
        args: List[A.Expression] = []
        order_by: Tuple[A.SortItem, ...] = ()
        if self.at_op("*"):
            self.next()
            self.expect_op(")")
            args = [A.Star()]
        else:
            if self.accept_kw("distinct"):
                distinct = True
            else:
                self.accept_kw("all")
            if not self.at_op(")"):
                args.append(self.expression())
                while self.accept_op(","):
                    args.append(self.expression())
            if self.accept_kw("order"):
                self.expect_kw("by")
                order_by = self._sort_items()
            self.expect_op(")")
        filt = None
        if self.at_kw("filter") and self.at_op("(", ahead=1):
            self.next()
            self.expect_op("(")
            self.expect_kw("where")
            filt = self.expression()
            self.expect_op(")")
        window = None
        if self.accept_kw("over"):
            window = self._window_spec()
        return A.FunctionCall(name, tuple(args), distinct, filt, order_by,
                              window)

    def _window_spec(self) -> A.WindowSpec:
        self.expect_op("(")
        partition: Tuple[A.Expression, ...] = ()
        order_by: Tuple[A.SortItem, ...] = ()
        frame = None
        if self.accept_kw("partition"):
            self.expect_kw("by")
            pl = [self.expression()]
            while self.accept_op(","):
                pl.append(self.expression())
            partition = tuple(pl)
        if self.accept_kw("order"):
            self.expect_kw("by")
            order_by = self._sort_items()
        if self.at_kw("rows", "range", "groups"):
            unit = self.next().value
            if self.accept_kw("between"):
                st, sv = self._frame_bound()
                self.expect_kw("and")
                et, ev = self._frame_bound()
            else:
                st, sv = self._frame_bound()
                et, ev = "current", None
            frame = A.WindowFrame(unit, st, sv, et, ev)
        self.expect_op(")")
        return A.WindowSpec(partition, order_by, frame)

    def _frame_bound(self) -> Tuple[str, Optional[A.Expression]]:
        if self.accept_kw("unbounded"):
            if self.accept_kw("preceding"):
                return "unbounded_preceding", None
            self.expect_kw("following")
            return "unbounded_following", None
        if self.accept_kw("current"):
            self.expect_kw("row")
            return "current", None
        e = self.expression()
        if self.accept_kw("preceding"):
            return "preceding", e
        self.expect_kw("following")
        return "following", e

    def _type_name(self) -> str:
        base = self.identifier()
        if base == "double" and self.accept_kw("precision"):
            base = "double"
        if base == "interval":
            u1 = self.identifier()
            if self.accept_kw("to"):
                self.identifier()
            return ("interval day to second"
                    if u1.startswith(("day", "hour", "minute", "second"))
                    else "interval year to month")
        if base in ("array", "map", "row") and self.at_op("("):
            # parameters are themselves types (recursive), plus field
            # names for row(...)
            self.expect_op("(")
            inner: List[str] = []
            while True:
                if base == "row" and self.peek().kind in ("ident", "qident") \
                        and not self.at_op("(", ahead=1) \
                        and not self.at_op(",", ahead=1) \
                        and not self.at_op(")", ahead=1):
                    fname = self.identifier()
                    inner.append(f"{fname} {self._type_name()}")
                else:
                    inner.append(self._type_name())
                if not self.accept_op(","):
                    break
            self.expect_op(")")
            return f"{base}({', '.join(inner)})"
        params: List[str] = []
        if self.accept_op("("):
            params.append(self.next().value)
            while self.accept_op(","):
                params.append(self.next().value)
            self.expect_op(")")
        name = f"{base}({','.join(params)})" if params else base
        if base in ("timestamp", "time") and self.at_kw("with", "without"):
            without = self.at_kw("without")
            self.next()
            self.expect_kw("time")
            self.expect_kw("zone")
            if not without:
                name += " with time zone"
        return name
