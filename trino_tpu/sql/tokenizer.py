"""SQL tokenizer.

Reference parity: the lexer rules of core/trino-parser's SqlBase.g4
(IDENTIFIER, QUOTED_IDENTIFIER, STRING, DECIMAL_VALUE, comments, operator
tokens). Produces a flat token list consumed by the recursive-descent
parser.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional


class ParseError(ValueError):
    """Syntax error (reference: spi/StandardErrorCode SYNTAX_ERROR)."""

    def __init__(self, message: str, line: int = 0, column: int = 0):
        super().__init__(
            f"line {line}:{column}: {message}" if line else message)
        self.line = line
        self.column = column


@dataclass(frozen=True)
class Token:
    kind: str     # ident | qident | string | integer | decimal | float | op | eof
    value: str    # normalized text (idents lower-cased unless quoted)
    line: int
    column: int

    def upper(self) -> str:
        return self.value.upper()


_MULTI_OPS = ("<>", "!=", "<=", ">=", "||", "->", "=>")
_SINGLE_OPS = "+-*/%<>=(),.;[]?:"


def tokenize(sql: str) -> List[Token]:
    tokens: List[Token] = []
    i, n = 0, len(sql)
    line, col = 1, 1

    def advance(k: int):
        nonlocal i, line, col
        for _ in range(k):
            if i < n and sql[i] == "\n":
                line += 1
                col = 1
            else:
                col += 1
            i += 1

    while i < n:
        c = sql[i]
        if c in " \t\r\n":
            advance(1)
            continue
        if c == "-" and sql[i:i + 2] == "--":
            j = sql.find("\n", i)
            advance((j if j >= 0 else n) - i)
            continue
        if c == "/" and sql[i:i + 2] == "/*":
            j = sql.find("*/", i + 2)
            if j < 0:
                raise ParseError("unterminated comment", line, col)
            advance(j + 2 - i)
            continue
        tl, tc = line, col
        if c == "'":
            # string literal, '' escapes a quote
            j = i + 1
            buf = []
            while True:
                if j >= n:
                    raise ParseError("unterminated string", tl, tc)
                if sql[j] == "'":
                    if j + 1 < n and sql[j + 1] == "'":
                        buf.append("'")
                        j += 2
                        continue
                    break
                buf.append(sql[j])
                j += 1
            tokens.append(Token("string", "".join(buf), tl, tc))
            advance(j + 1 - i)
            continue
        if c == '"':
            j = i + 1
            buf = []
            while True:
                if j >= n:
                    raise ParseError("unterminated identifier", tl, tc)
                if sql[j] == '"':
                    if j + 1 < n and sql[j + 1] == '"':
                        buf.append('"')
                        j += 2
                        continue
                    break
                buf.append(sql[j])
                j += 1
            tokens.append(Token("qident", "".join(buf), tl, tc))
            advance(j + 1 - i)
            continue
        if c.isdigit() or (c == "." and i + 1 < n and sql[i + 1].isdigit()):
            j = i
            seen_dot = False
            seen_exp = False
            while j < n:
                ch = sql[j]
                if ch.isdigit():
                    j += 1
                elif ch == "." and not seen_dot and not seen_exp:
                    seen_dot = True
                    j += 1
                elif ch in "eE" and not seen_exp and j + 1 < n and (
                        sql[j + 1].isdigit() or
                        (sql[j + 1] in "+-" and j + 2 < n
                         and sql[j + 2].isdigit())):
                    seen_exp = True
                    j += 2 if sql[j + 1] in "+-" else 1
                else:
                    break
            text = sql[i:j]
            kind = ("float" if seen_exp
                    else "decimal" if seen_dot else "integer")
            tokens.append(Token(kind, text, tl, tc))
            advance(j - i)
            continue
        if c.isalpha() or c == "_":
            j = i
            while j < n and (sql[j].isalnum() or sql[j] == "_"):
                j += 1
            tokens.append(Token("ident", sql[i:j].lower(), tl, tc))
            advance(j - i)
            continue
        two = sql[i:i + 2]
        if two in _MULTI_OPS:
            tokens.append(Token("op", two, tl, tc))
            advance(2)
            continue
        if c in _SINGLE_OPS:
            tokens.append(Token("op", c, tl, tc))
            advance(1)
            continue
        raise ParseError(f"unexpected character {c!r}", tl, tc)
    tokens.append(Token("eof", "", line, col))
    return tokens
