"""PEP 249 (DB-API 2.0) driver over the client protocol.

Reference parity: client/trino-jdbc (10.2k loc, JDBC 4 over
trino-client). Python's database ecosystem equivalent of JDBC is
DB-API 2.0, so this module plays the trino-jdbc role: ``connect()`` /
``Connection`` / ``Cursor`` with qmark-style parameter binding
(rendered through PREPARE/EXECUTE on the server), ``description``
metadata, fetchone/fetchmany/fetchall, and iteration.

    from trino_tpu.dbapi import connect
    conn = connect("http://127.0.0.1:8080", user="alice")
    cur = conn.cursor()
    cur.execute("SELECT n_name FROM tpch.tiny.nation WHERE "
                "n_nationkey = ?", (3,))
    print(cur.fetchall())
"""

from __future__ import annotations

import datetime
import itertools
from typing import Any, List, Optional, Sequence, Tuple

from .client import ClientError, StatementClient

apilevel = "2.0"
threadsafety = 1          # threads may share the module, not connections
paramstyle = "qmark"


class Error(Exception):
    pass


class InterfaceError(Error):
    pass


class DatabaseError(Error):
    pass


class ProgrammingError(DatabaseError):
    pass


class OperationalError(DatabaseError):
    pass


def connect(uri: str, user: str = "user", catalog: str = "tpch",
            schema: str = "tiny", **kw) -> "Connection":
    return Connection(uri, user=user, catalog=catalog, schema=schema,
                      **kw)


class Connection:
    def __init__(self, uri: str, user: str = "user",
                 catalog: str = "tpch", schema: str = "tiny",
                 session_properties=None, timeout: float = 600.0):
        self._client = StatementClient(
            uri, user=user, catalog=catalog, schema=schema,
            session_properties=session_properties, timeout=timeout)
        self._closed = False

    # --- DB-API surface --------------------------------------------------
    def cursor(self) -> "Cursor":
        if self._closed:
            raise InterfaceError("connection is closed")
        return Cursor(self)

    def close(self) -> None:
        self._closed = True

    def commit(self) -> None:
        """Engine statements auto-commit; explicit transactions go
        through cursor.execute('START TRANSACTION') etc."""

    def rollback(self) -> None:
        raise OperationalError(
            "rollback() outside an explicit transaction; run "
            "START TRANSACTION / ROLLBACK statements instead")

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


_PREP_COUNTER = itertools.count(1)


def _render_param(v: Any) -> str:
    """Literal rendering for qmark parameters (the reference JDBC
    driver binds through PREPARE/EXECUTE; we inline EXECUTE ... USING
    literals, which round-trips through the same parameter machinery
    server-side)."""
    if v is None:
        return "NULL"
    if isinstance(v, bool):
        return "TRUE" if v else "FALSE"
    if isinstance(v, float):
        import math
        if math.isnan(v):
            return "nan()"
        if math.isinf(v):
            return "infinity()" if v > 0 else "-infinity()"
        return repr(v)
    if isinstance(v, int):
        return repr(v)
    import decimal
    if isinstance(v, decimal.Decimal):
        return str(v)          # lexes as an exact DECIMAL literal
    if isinstance(v, datetime.datetime):
        return f"TIMESTAMP '{v.isoformat(sep=' ')}'"
    if isinstance(v, datetime.date):
        return f"DATE '{v.isoformat()}'"
    s = str(v).replace("'", "''")
    return f"'{s}'"


class Cursor:
    arraysize = 1

    def __init__(self, conn: Connection):
        self._conn = conn
        self._rows: List[list] = []
        self._pos = 0
        self.description: Optional[List[Tuple]] = None
        self.rowcount = -1
        self.query_id: Optional[str] = None

    # --- execution -------------------------------------------------------
    def execute(self, operation: str,
                parameters: Optional[Sequence] = None) -> "Cursor":
        if self._conn._closed:
            raise InterfaceError("connection is closed")
        client = self._conn._client
        sql = operation
        try:
            if parameters:
                name = f"dbapi_{next(_PREP_COUNTER)}"
                client.execute(f"PREPARE {name} FROM {operation}")
                args = ", ".join(_render_param(p) for p in parameters)
                try:
                    res = client.execute(f"EXECUTE {name} USING {args}")
                finally:
                    try:
                        client.execute(f"DEALLOCATE PREPARE {name}")
                    except ClientError:
                        pass
            else:
                res = client.execute(sql)
        except ClientError as e:
            raise ProgrammingError(str(e)) from e
        self._rows = res.rows
        self._pos = 0
        self.query_id = res.query_id
        self.description = [
            (c["name"], c.get("type"), None, None, None, None, None)
            for c in res.columns] or None
        self.rowcount = (res.update_count
                         if res.update_count is not None
                         else len(res.rows))
        return self

    def executemany(self, operation: str,
                    seq_of_parameters: Sequence[Sequence]) -> "Cursor":
        for params in seq_of_parameters:
            self.execute(operation, params)
        return self

    # --- fetching --------------------------------------------------------
    def fetchone(self) -> Optional[list]:
        if self._pos >= len(self._rows):
            return None
        row = self._rows[self._pos]
        self._pos += 1
        return row

    def fetchmany(self, size: Optional[int] = None) -> List[list]:
        n = size if size is not None else self.arraysize
        out = self._rows[self._pos:self._pos + n]
        self._pos += len(out)
        return out

    def fetchall(self) -> List[list]:
        out = self._rows[self._pos:]
        self._pos = len(self._rows)
        return out

    def __iter__(self):
        while True:
            row = self.fetchone()
            if row is None:
                return
            yield row

    def close(self) -> None:
        self._rows = []

    def setinputsizes(self, sizes) -> None:
        pass

    def setoutputsize(self, size, column=None) -> None:
        pass
