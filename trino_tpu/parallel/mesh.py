"""Device mesh management + batch sharding.

Reference parity: the scheduler's node topology — NodeScheduler /
InternalNodeManager (execution/scheduler/NodeScheduler.java) mapped onto
the TPU model: workers == mesh devices along one "workers" axis; a
Trino *task* on node i == the shard-i slice of an SPMD program
(SURVEY.md §2.7 inter-node data parallelism row).

A distributed Batch keeps its columns as global jax.Arrays sharded on the
row axis with NamedSharding(P("workers")); each device owns a
``per_shard_cap`` slice. Row liveness is per shard: shard d's live rows
are the first ``num_rows[d]`` of its slice (num_rows is a replicated
[n_dev] vector — the analog of per-task row counts in TaskStatus).
"""

from __future__ import annotations

from dataclasses import dataclass, replace as dc_replace
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..columnar import Batch, Column
from ..config import capacity_for

AXIS = "workers"


def get_mesh(n_devices: Optional[int] = None) -> Mesh:
    devs = jax.devices()
    n = n_devices or len(devs)
    return Mesh(np.asarray(devs[:n]), (AXIS,))


def row_spec(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P(AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


@dataclass(frozen=True)
class ShardedBatch:
    """Row-sharded Batch: every column lane has global shape
    [n_dev * per_shard_cap] with shard d owning
    [d*per_shard_cap, (d+1)*per_shard_cap); ``num_rows`` is an [n_dev]
    replicated vector of per-shard live counts."""
    columns: Dict[str, Column]
    num_rows: jax.Array          # [n_dev] int64, replicated
    mesh: Mesh
    per_shard_cap: int

    @property
    def n_shards(self) -> int:
        return self.mesh.devices.size

    def total_rows_host(self) -> int:
        return int(jnp.sum(self.num_rows))

    def schema(self):
        return {k: c.type for k, c in self.columns.items()}


def shard_batch(batch: Batch, mesh: Mesh,
                per_shard_cap: Optional[int] = None) -> ShardedBatch:
    """Round-robin-by-range scatter of a host Batch across the mesh
    (the analog of assigning splits to worker tasks)."""
    n = mesh.devices.size
    total = batch.num_rows_host()
    per = per_shard_cap or capacity_for(
        max((total + n - 1) // n, 1), minimum=8)
    counts = np.zeros(n, dtype=np.int64)
    base = total // n
    rem = total % n
    counts[:] = base
    counts[:rem] += 1
    assert counts.max() <= per
    spec = row_spec(mesh)
    cols = {}
    offs = np.concatenate([[0], np.cumsum(counts)])[:-1]
    gather_idx = np.zeros(n * per, dtype=np.int64)
    for d in range(n):
        gather_idx[d * per: d * per + counts[d]] = np.arange(
            offs[d], offs[d] + counts[d])
    gidx = jnp.asarray(gather_idx)
    for name, c in batch.columns.items():
        data = jax.device_put(jnp.take(jnp.asarray(c.data), gidx,
                                       mode="clip"), spec)
        valid = (None if c.valid is None else jax.device_put(
            jnp.take(jnp.asarray(c.valid), gidx, mode="clip"), spec))
        d2 = (None if c.data2 is None else jax.device_put(
            jnp.take(jnp.asarray(c.data2), gidx, mode="clip"), spec))
        cols[name] = Column(c.type, data, valid, c.dictionary, d2)
    return ShardedBatch(cols, jnp.asarray(counts), mesh, per)


def shard_parts(parts: Sequence[Batch], mesh: Mesh) -> ShardedBatch:
    """Place per-worker Batches directly: part i -> device i (splits
    already assigned per node, the SourcePartitionedScheduler path)."""
    n = mesh.devices.size
    assert len(parts) == n
    per = max(capacity_for(max(p.num_rows_host() for p in parts),
                           minimum=8), 8)
    from ..columnar import pad_batch
    parts = [pad_batch(p, per) for p in parts]
    # merge dictionaries per column across parts
    names = parts[0].names
    spec = row_spec(mesh)
    cols = {}
    counts = jnp.asarray([p.num_rows_host() for p in parts],
                         dtype=jnp.int64)
    for name in names:
        pcols = [p.column(name) for p in parts]
        typ = pcols[0].type
        from ..types import is_string
        if is_string(typ):
            merged = pcols[0].dictionary
            remaps = [np.arange(len(merged), dtype=np.int32)]
            for c in pcols[1:]:
                merged, _, ro = merged.merge(c.dictionary)
                remaps.append(ro)
            lanes = [np.asarray(rm)[np.asarray(c.data)]
                     for c, rm in zip(pcols, remaps)]
            data = jax.device_put(
                jnp.asarray(np.concatenate(lanes).astype(np.int32)), spec)
            dic = merged
        else:
            data = jax.device_put(
                jnp.concatenate([jnp.asarray(c.data) for c in pcols]),
                spec)
            dic = None
        valid = None
        if any(c.valid is not None for c in pcols):
            vl = [np.ones(per, bool) if c.valid is None
                  else np.asarray(c.valid) for c in pcols]
            valid = jax.device_put(jnp.asarray(np.concatenate(vl)), spec)
        cols[name] = Column(typ, data, valid, dic)
    return ShardedBatch(cols, counts, mesh, per)


def unshard_batch(sb: ShardedBatch) -> Batch:
    """GATHER: collect live prefixes of every shard into one host Batch
    (the final exchange to the coordinator)."""
    n, per = sb.n_shards, sb.per_shard_cap
    counts = np.asarray(sb.num_rows)
    total = int(counts.sum())
    cap = capacity_for(max(total, 1), minimum=8)
    idx_parts = [np.arange(counts[d], dtype=np.int64) + d * per
                 for d in range(n)]
    idx = np.concatenate(idx_parts) if idx_parts else np.zeros(0, np.int64)
    idx = np.pad(idx, (0, cap - len(idx)))
    gidx = jnp.asarray(idx)
    cols = {}
    for name, c in sb.columns.items():
        data = jnp.take(jnp.asarray(c.data), gidx, mode="clip")
        valid = (None if c.valid is None
                 else jnp.take(jnp.asarray(c.valid), gidx, mode="clip"))
        d2 = (None if c.data2 is None
              else jnp.take(jnp.asarray(c.data2), gidx, mode="clip"))
        elements = c.elements
        if elements is not None:
            # array offsets are shard-local; after the gather the flat
            # elements lanes of all shards are stacked, so each row's
            # start shifts by its shard's slice of the elements array
            ecap = int(jnp.asarray(elements.data).shape[0]) // max(n, 1)
            shard_of_row = gidx // per
            data = data + shard_of_row * ecap
        cols[name] = Column(c.type, jax.device_put(data),
                            None if valid is None else jax.device_put(
                                valid), c.dictionary,
                            None if d2 is None else jax.device_put(d2),
                            elements)
    return Batch(cols, total)
