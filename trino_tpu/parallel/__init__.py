from .mesh import get_mesh, shard_batch, unshard_batch  # noqa: F401
from .spmd import (distributed_group_aggregate,  # noqa: F401
                   repartition_by_hash)
