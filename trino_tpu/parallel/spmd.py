"""SPMD collective kernels: the TPU data plane.

Reference parity: the exchange layer — PartitionedOutputOperator.java:55
(hash partition + scatter into per-partition buffers), ExchangeClient.java
:149 (pull + merge), BroadcastOutputBuffer (replicate). TPU-first redesign
(SURVEY.md §2.7, §7.4): REMOTE REPARTITION == ``jax.lax.all_to_all`` over
the ICI mesh inside a ``shard_map``; REPLICATE == ``all_gather``; GATHER
== host collect (mesh.py unshard_batch). There is no wire serde or
pull/ack protocol inside a slice — XLA schedules the collective.

The same columnar kernels (ops/groupby, ops/join, exec/expr) run
unchanged inside the shard_map trace: a Trino *task* is the per-shard
slice of one SPMD program. Host syncs happen only between shard_map
calls, for data-dependent capacity decisions (the two-phase pattern of
ops/join.py, lifted to the distributed case).
"""

from __future__ import annotations

from functools import partial
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

try:
    from jax import shard_map
except ImportError:
    # pre-0.6 jax ships shard_map under experimental with the old
    # check_rep knob (check_vma is its rename); adapt so the call
    # sites below stay on the modern spelling
    from jax.experimental.shard_map import shard_map as _shard_map_exp

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True):
        return _shard_map_exp(f, mesh=mesh, in_specs=in_specs,
                              out_specs=out_specs, check_rep=check_vma)

from ..columnar import Batch, Column
from ..ops.groupby import AggInput, group_aggregate
from ..ops.hashing import hash_columns
from .mesh import AXIS, ShardedBatch, row_spec


def _col_specs(cols: Dict[str, Column], spec) -> Dict[str, Column]:
    """A pytree of PartitionSpecs shaped like the columns dict."""
    return jax.tree.map(lambda _: spec, cols)


# --------------------------------------------------------------------------
# shard-level repartition (runs inside a shard_map trace)
# --------------------------------------------------------------------------

def _shard_repartition(cols: Dict[str, Column], my_n: jax.Array,
                       key_names: Sequence[str], n_dev: int,
                       out_cap: int) -> Tuple[Dict[str, Column],
                                              jax.Array]:
    """Per-shard: hash-bin rows by destination, all_to_all, compact.
    Returns (received columns [out_cap], my new row count)."""
    h = hash_columns([cols[k] for k in key_names])
    pid = (h % jnp.uint64(n_dev)).astype(jnp.int32)
    return _shard_exchange(cols, my_n, pid, n_dev, out_cap)


def _shard_exchange(cols: Dict[str, Column], my_n: jax.Array,
                    pid: jax.Array, n_dev: int,
                    out_cap: int) -> Tuple[Dict[str, Column], jax.Array]:
    """Per-shard exchange body: given each row's destination shard id,
    bin rows, all_to_all, compact. The received rows preserve
    (source-shard, source-position) order within each destination."""
    some = next(iter(cols.values()))
    per = int(some.data.shape[0])
    live = jnp.arange(per, dtype=jnp.int64) < my_n
    sort_key = jnp.where(live, pid, n_dev)
    order = jnp.argsort(sort_key, stable=True)

    counts = jax.ops.segment_sum(
        live.astype(jnp.int64), jnp.clip(pid, 0, n_dev - 1),
        num_segments=n_dev)
    starts = jnp.cumsum(counts) - counts

    # send slot matrix [n_dev, per]: bin p's row j comes from
    # order[starts[p] + j]
    j = jnp.arange(per, dtype=jnp.int64)[None, :]
    src = starts[:, None] + j
    send_idx = jnp.take(order, jnp.clip(src, 0, per - 1), axis=0)

    recv_counts = jax.lax.all_to_all(counts, AXIS, 0, 0)
    new_n = jnp.sum(recv_counts)

    # compact gather index over the received [n_dev, per] buffers
    rj = jnp.arange(per, dtype=jnp.int64)[None, :]
    recv_live = (rj < recv_counts[:, None]).reshape(-1)
    flat_idx = jnp.nonzero(recv_live, size=out_cap, fill_value=0)[0]

    out: Dict[str, Column] = {}
    for name, c in cols.items():
        lanes = [c.data] + ([c.valid] if c.valid is not None else []) \
            + ([c.data2] if c.data2 is not None else [])
        moved = []
        for lane in lanes:
            send = jnp.take(jnp.asarray(lane), send_idx, axis=0)
            recv = jax.lax.all_to_all(send, AXIS, 0, 0)
            moved.append(jnp.take(recv.reshape(-1), flat_idx, axis=0))
        data = moved[0]
        k = 1
        valid = None
        if c.valid is not None:
            valid = moved[k]
            k += 1
        d2 = moved[k] if c.data2 is not None else None
        out[name] = Column(c.type, data, valid, c.dictionary, d2)
    return out, new_n


def _shard_broadcast(cols: Dict[str, Column], num_rows_vec: jax.Array,
                     out_cap: int) -> Tuple[Dict[str, Column], jax.Array]:
    """Per-shard: replicate every shard's live rows to all shards
    (REPLICATE exchange / broadcast join build side)."""
    some = next(iter(cols.values()))
    per = int(some.data.shape[0])
    n_dev = num_rows_vec.shape[0]
    j = jnp.arange(per, dtype=jnp.int64)[None, :]
    live = (j < num_rows_vec[:, None]).reshape(-1)
    flat_idx = jnp.nonzero(live, size=out_cap, fill_value=0)[0]
    new_n = jnp.sum(num_rows_vec)
    out: Dict[str, Column] = {}
    for name, c in cols.items():
        lanes = [c.data] + ([c.valid] if c.valid is not None else []) \
            + ([c.data2] if c.data2 is not None else [])
        moved = []
        for lane in lanes:
            g = jax.lax.all_gather(jnp.asarray(lane), AXIS)  # [n_dev, per]
            moved.append(jnp.take(g.reshape(-1), flat_idx, axis=0))
        data = moved[0]
        k = 1
        valid = None
        if c.valid is not None:
            valid = moved[k]
            k += 1
        d2 = moved[k] if c.data2 is not None else None
        out[name] = Column(c.type, data, valid, c.dictionary, d2)
    return out, new_n


# --------------------------------------------------------------------------
# whole-mesh operations (host API over ShardedBatch)
# --------------------------------------------------------------------------

def repartition_by_hash(sb: ShardedBatch, key_names: Sequence[str],
                        out_cap: Optional[int] = None) -> ShardedBatch:
    """REMOTE REPARTITION: redistribute rows so equal keys land on the
    same shard. ``out_cap`` bounds the post-exchange per-shard capacity;
    default is the safe worst case n_dev * per_shard_cap."""
    n = sb.n_shards
    cap = out_cap or n * sb.per_shard_cap

    def f(cols, num_rows_vec):
        d = jax.lax.axis_index(AXIS)
        my_n = num_rows_vec[d]
        out, new_n = _shard_repartition(cols, my_n, key_names, n, cap)
        counts = jax.lax.all_gather(new_n, AXIS)
        return out, counts

    mesh = sb.mesh
    fn = shard_map(
        f, mesh=mesh,
        in_specs=(_col_specs(sb.columns, P(AXIS)), P()),
        out_specs=(_col_specs(sb.columns, P(AXIS)), P()),
        check_vma=False)
    cols, counts = fn(sb.columns, sb.num_rows)
    return ShardedBatch(cols, counts, mesh, cap)


# --------------------------------------------------------------------------
# range repartition (distributed sort / merge-exchange analog)
# --------------------------------------------------------------------------

def _range_pid(batch: Batch, sort_keys, splitter_lanes) -> jax.Array:
    """Destination shard id per row: the number of splitters whose
    composite sort-lane tuple is strictly below the row's. Splitters
    ascend, so shard ids ascend with ORDER BY position — shard-major
    concatenation of per-shard sorted rows IS the global order."""
    from ..ops.sort import sort_lanes
    lanes = sort_lanes(batch, sort_keys)[1:]  # drop the liveness lane
    some = lanes[0]
    dest = jnp.zeros(some.shape, jnp.int32)
    n_split = len(splitter_lanes[0])
    for si in range(n_split):
        gt = jnp.zeros(some.shape, bool)
        eq = jnp.ones(some.shape, bool)
        for lane, sl in zip(lanes, splitter_lanes):
            sval = jnp.asarray(sl[si], dtype=lane.dtype)
            gt = gt | (eq & (lane > sval))
            eq = eq & (lane == sval)
        dest = dest + gt.astype(jnp.int32)
    return dest


def sample_range_splitters(sb: ShardedBatch, sort_keys,
                           samples_per_shard: int = 256):
    """Phase 0 of a distributed sort: evenly sample each shard's sort
    lanes, gather the samples, and pick n_dev-1 splitters at sample
    quantiles (the reference's sampled range partitioning for
    distributed_sort / MergeOperator's range exchange). Returns a list
    of per-lane splitter value arrays, or None when the relation is
    empty."""
    import numpy as np
    from ..ops.sort import sort_lanes
    n = sb.n_shards
    S = samples_per_shard

    def f(cols, num_rows_vec):
        d = jax.lax.axis_index(AXIS)
        my_n = num_rows_vec[d]
        b = Batch(cols, my_n)
        lanes = sort_lanes(b, sort_keys)[1:]
        pos = (jnp.arange(S, dtype=jnp.int64)
               * jnp.maximum(my_n, 1)) // S
        samp = tuple(
            jnp.take(l, jnp.clip(pos, 0, l.shape[0] - 1), mode="clip")
            for l in lanes)
        live = jnp.arange(S, dtype=jnp.int64) < my_n
        return samp + (live,)

    # out_specs needs the lane count up front; derive it from a tiny
    # 8-row head batch so no full-column lane computation runs here
    head = {name: Column(c.type, jnp.asarray(c.data)[:8],
                         None if c.valid is None
                         else jnp.asarray(c.valid)[:8], c.dictionary)
            for name, c in sb.columns.items()}
    n_lanes_probe = len(sort_lanes(Batch(head, 0), sort_keys)) - 1

    g = shard_map(f, mesh=sb.mesh,
                  in_specs=(_col_specs(sb.columns, P(AXIS)), P()),
                  out_specs=tuple([P(AXIS)] * (n_lanes_probe + 1)),
                  check_vma=False)
    out = g(sb.columns, sb.num_rows)
    live = np.asarray(out[-1])
    if not live.any():
        return None
    lanes_h = [np.asarray(l)[live] for l in out[:-1]]
    order = np.lexsort(lanes_h[::-1])
    m = len(order)
    picks = [order[min(((i + 1) * m) // n, m - 1)] for i in range(n - 1)]
    return [l[picks] for l in lanes_h]


def range_dest_counts(sb: ShardedBatch, sort_keys,
                      splitter_lanes) -> jax.Array:
    """Per-destination row totals for a range exchange (two-phase
    capacity sizing, mirroring repartition_dest_counts)."""
    n = sb.n_shards

    def f(cols, num_rows_vec):
        d = jax.lax.axis_index(AXIS)
        my_n = num_rows_vec[d]
        some = next(iter(cols.values()))
        per = int(some.data.shape[0])
        live = jnp.arange(per, dtype=jnp.int64) < my_n
        pid = _range_pid(Batch(cols, my_n), sort_keys, splitter_lanes)
        counts = jax.ops.segment_sum(
            live.astype(jnp.int64), jnp.clip(pid, 0, n - 1),
            num_segments=n)
        return jax.lax.psum(counts, AXIS)

    g = shard_map(f, mesh=sb.mesh,
                  in_specs=(_col_specs(sb.columns, P(AXIS)), P()),
                  out_specs=P(),
                  check_vma=False)
    return g(sb.columns, sb.num_rows)


def repartition_by_range(sb: ShardedBatch, sort_keys, splitter_lanes,
                         out_cap: Optional[int] = None) -> ShardedBatch:
    """Range exchange: redistribute rows so shard i holds the i-th
    ORDER BY slice. A per-shard sort afterwards yields a globally
    sorted relation under shard-major gather (unshard_batch)."""
    n = sb.n_shards
    cap = out_cap or n * sb.per_shard_cap

    def f(cols, num_rows_vec):
        d = jax.lax.axis_index(AXIS)
        my_n = num_rows_vec[d]
        pid = _range_pid(Batch(cols, my_n), sort_keys, splitter_lanes)
        out, new_n = _shard_exchange(cols, my_n, pid, n, cap)
        counts = jax.lax.all_gather(new_n, AXIS)
        return out, counts

    fn = shard_map(
        f, mesh=sb.mesh,
        in_specs=(_col_specs(sb.columns, P(AXIS)), P()),
        out_specs=(_col_specs(sb.columns, P(AXIS)), P()),
        check_vma=False)
    cols, counts = fn(sb.columns, sb.num_rows)
    return ShardedBatch(cols, counts, sb.mesh, cap)


def distributed_group_aggregate(sb: ShardedBatch,
                                key_names: Sequence[str],
                                aggs: Sequence[AggInput],
                                out_cap: Optional[int] = None
                                ) -> ShardedBatch:
    """PARTIAL agg per shard -> all_to_all by key hash -> FINAL agg.

    This is the PushPartialAggregationThroughExchange plan shape
    (SURVEY.md §2.7 partial/final row) as one SPMD program: every
    aggregate below declares a combine that is itself a segment op,
    so the partial output columns feed the final step directly."""
    from ..ops.groupby import COMBINABLE_KINDS
    n = sb.n_shards
    partial_cap = sb.per_shard_cap
    exch_cap = n * partial_cap if out_cap is None else out_cap

    decomposable = all(a.kind in COMBINABLE_KINDS for a in aggs)
    if decomposable:
        finals: List[AggInput] = [
            AggInput(COMBINABLE_KINDS[a.kind], a.output, None, a.output)
            for a in aggs]

    def f(cols, num_rows_vec):
        d = jax.lax.axis_index(AXIS)
        my_n = num_rows_vec[d]
        local = Batch(cols, my_n)
        if decomposable:
            part = group_aggregate(local, list(key_names), list(aggs),
                                   groups_capacity=partial_cap)
            moved, new_n = _shard_repartition(
                part.columns, part.num_rows_device(), key_names, n,
                exch_cap)
            fin = group_aggregate(Batch(moved, new_n), list(key_names),
                                  finals, groups_capacity=exch_cap)
        else:
            # non-decomposable aggregates (count_distinct / percentile /
            # argmin / argmax): repartition ROWS by key hash first, then
            # aggregate exactly — every group is wholly on one shard
            moved, new_n = _shard_repartition(
                cols, my_n, key_names, n, exch_cap)
            fin = group_aggregate(Batch(moved, new_n), list(key_names),
                                  list(aggs), groups_capacity=exch_cap)
        counts = jax.lax.all_gather(fin.num_rows_device(), AXIS)
        return fin.columns, counts

    mesh = sb.mesh
    fn = shard_map(f, mesh=mesh,
                   in_specs=(_col_specs(sb.columns, P(AXIS)), P()),
                   out_specs=(P(AXIS), P()),
                   check_vma=False)
    cols, counts = fn(sb.columns, sb.num_rows)
    return ShardedBatch(cols, counts, mesh, exch_cap)


def shard_apply(sb: ShardedBatch, fn, out_cap: Optional[int] = None
                ) -> ShardedBatch:
    """Run a Batch->Batch transformation independently on every shard
    (the intra-task pipeline segment between exchanges: filter/project/
    partial ops — SURVEY.md §2.7 intra-node row). ``fn`` must keep the
    capacity at ``out_cap`` (default: unchanged)."""
    cap = out_cap or sb.per_shard_cap

    def f(cols, num_rows_vec):
        d = jax.lax.axis_index(AXIS)
        out = fn(Batch(cols, num_rows_vec[d]))
        counts = jax.lax.all_gather(out.num_rows_device(), AXIS)
        return out.columns, counts

    g = shard_map(f, mesh=sb.mesh,
                  in_specs=(_col_specs(sb.columns, P(AXIS)), P()),
                  out_specs=(P(AXIS), P()),
                  check_vma=False)
    cols, counts = g(sb.columns, sb.num_rows)
    return ShardedBatch(cols, counts, sb.mesh, cap)


def shard_totals(sb: ShardedBatch, fn) -> jax.Array:
    """Per-shard scalar reduction (e.g. join-size phase 1): fn(Batch) ->
    int scalar; returns the [n_dev] vector (host-readable)."""

    def f(cols, num_rows_vec):
        d = jax.lax.axis_index(AXIS)
        t = fn(Batch(cols, num_rows_vec[d]))
        return jax.lax.all_gather(t, AXIS)

    g = shard_map(f, mesh=sb.mesh,
                  in_specs=(_col_specs(sb.columns, P(AXIS)), P()),
                  out_specs=P(),
                  check_vma=False)
    return g(sb.columns, sb.num_rows)


def repartition_dest_counts(sb: ShardedBatch,
                            key_names: Sequence[str]) -> jax.Array:
    """Phase 1 of a two-phase repartition: the [n_dev] vector of row
    totals each destination shard would receive — lets the caller size
    the exchange capacity from real counts instead of the
    n_dev * per_shard_cap worst case (VERDICT weak #10)."""
    n = sb.n_shards

    def f(cols, num_rows_vec):
        d = jax.lax.axis_index(AXIS)
        my_n = num_rows_vec[d]
        some = next(iter(cols.values()))
        per = int(some.data.shape[0])
        live = jnp.arange(per, dtype=jnp.int64) < my_n
        h = hash_columns([cols[k] for k in key_names])
        pid = (h % jnp.uint64(n)).astype(jnp.int32)
        counts = jax.ops.segment_sum(
            live.astype(jnp.int64), jnp.clip(pid, 0, n - 1),
            num_segments=n)
        return jax.lax.psum(counts, AXIS)

    g = shard_map(f, mesh=sb.mesh,
                  in_specs=(_col_specs(sb.columns, P(AXIS)), P()),
                  out_specs=P(),
                  check_vma=False)
    return g(sb.columns, sb.num_rows)


def shard_apply2s(sa: ShardedBatch, sb: ShardedBatch, fn,
                  out_cap: int) -> ShardedBatch:
    """Per-shard transformation over two co-sharded operands (the
    PARTITIONED-distribution join body: both sides already hash-
    repartitioned on the join keys, so a shard joins only its slice)."""

    def f(acols, an, bcols, bn):
        d = jax.lax.axis_index(AXIS)
        out = fn(Batch(acols, an[d]), Batch(bcols, bn[d]))
        counts = jax.lax.all_gather(out.num_rows_device(), AXIS)
        return out.columns, counts

    g = shard_map(
        f, mesh=sa.mesh,
        in_specs=(_col_specs(sa.columns, P(AXIS)), P(),
                  _col_specs(sb.columns, P(AXIS)), P()),
        out_specs=(P(AXIS), P()),
        check_vma=False)
    cols, counts = g(sa.columns, sa.num_rows, sb.columns, sb.num_rows)
    return ShardedBatch(cols, counts, sa.mesh, out_cap)


def shard_totals2s(sa: ShardedBatch, sb: ShardedBatch, fn) -> jax.Array:
    """Per-shard scalar over two co-sharded operands."""

    def f(acols, an, bcols, bn):
        d = jax.lax.axis_index(AXIS)
        t = fn(Batch(acols, an[d]), Batch(bcols, bn[d]))
        return jax.lax.all_gather(t, AXIS)

    g = shard_map(
        f, mesh=sa.mesh,
        in_specs=(_col_specs(sa.columns, P(AXIS)), P(),
                  _col_specs(sb.columns, P(AXIS)), P()),
        out_specs=P(),
        check_vma=False)
    return g(sa.columns, sa.num_rows, sb.columns, sb.num_rows)


def shard_apply2(sa: ShardedBatch, b_host: Batch, fn,
                 out_cap: int) -> ShardedBatch:
    """Per-shard transformation with a REPLICATED second operand (a
    broadcast-join build side / filtering source): fn(shard Batch,
    replicated Batch) -> Batch of capacity out_cap."""

    def f(cols, num_rows_vec, bcols, bn):
        d = jax.lax.axis_index(AXIS)
        out = fn(Batch(cols, num_rows_vec[d]), Batch(bcols, bn))
        counts = jax.lax.all_gather(out.num_rows_device(), AXIS)
        return out.columns, counts

    g = shard_map(
        f, mesh=sa.mesh,
        in_specs=(_col_specs(sa.columns, P(AXIS)), P(),
                  _col_specs(b_host.columns, P()), P()),
        out_specs=(P(AXIS), P()),
        check_vma=False)
    cols, counts = g(sa.columns, sa.num_rows, b_host.columns,
                     jnp.asarray(b_host.num_rows_host(), jnp.int64))
    return ShardedBatch(cols, counts, sa.mesh, out_cap)


def shard_totals2(sa: ShardedBatch, b_host: Batch, fn) -> jax.Array:
    """Per-shard scalar with replicated second operand."""

    def f(cols, num_rows_vec, bcols, bn):
        d = jax.lax.axis_index(AXIS)
        t = fn(Batch(cols, num_rows_vec[d]), Batch(bcols, bn))
        return jax.lax.all_gather(t, AXIS)

    g = shard_map(
        f, mesh=sa.mesh,
        in_specs=(_col_specs(sa.columns, P(AXIS)), P(),
                  _col_specs(b_host.columns, P()), P()),
        out_specs=P(),
        check_vma=False)
    return g(sa.columns, sa.num_rows, b_host.columns,
             jnp.asarray(b_host.num_rows_host(), jnp.int64))


def broadcast_sharded(sb: ShardedBatch,
                      out_cap: Optional[int] = None) -> ShardedBatch:
    """REPLICATE exchange: every shard ends up with every row."""
    n = sb.n_shards
    cap = out_cap or n * sb.per_shard_cap

    def f(cols, num_rows_vec):
        out, new_n = _shard_broadcast(cols, num_rows_vec, cap)
        counts = jax.lax.all_gather(new_n, AXIS)
        return out, counts

    fn = shard_map(f, mesh=sb.mesh,
                   in_specs=(_col_specs(sb.columns, P(AXIS)), P()),
                   out_specs=(P(AXIS), P()),
                   check_vma=False)
    cols, counts = fn(sb.columns, sb.num_rows)
    # broadcast output is replicated per shard; counts[d] all equal total
    return ShardedBatch(cols, counts, sb.mesh, cap)
