"""Plugin/test toolkit — lib/trino-plugin-toolkit + testing's
QueryAssertions, collapsed to the helpers plugin authors actually use.

``assert_query`` runs SQL and compares rows (order-insensitive by
default, like the reference's MaterializedResult comparisons);
``assert_query_fails`` checks the error message; ``TestingConnector``
is a minimal in-memory connector for SPI tests.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

from .catalog import TableMetadata, ColumnMetadata
from .columnar import batch_from_pylist
from .connectors.memory import MemoryConnector
from .types import Type


def _canon(rows):
    out = []
    for r in rows:
        out.append(tuple(
            float(v) if isinstance(v, float) else v for v in r))
    return out


def assert_query(runner, sql: str, expected: Sequence[Sequence],
                 ordered: bool = False) -> None:
    """testing/QueryAssertions.assertQuery: run, compare rows.
    Floats compare with a small tolerance."""
    got = _canon(runner.execute(sql).rows)
    exp = _canon(expected)
    if not ordered:
        got = sorted(got, key=repr)
        exp = sorted(exp, key=repr)
    assert len(got) == len(exp), \
        f"row count {len(got)} != {len(exp)}\n got: {got}\n exp: {exp}"
    for g, e in zip(got, exp):
        assert len(g) == len(e), f"width {g} vs {e}"
        for gv, ev in zip(g, e):
            if isinstance(gv, float) and isinstance(ev, (int, float)):
                assert abs(gv - float(ev)) <= 1e-9 * max(
                    1.0, abs(ev)), f"{gv} != {ev} in {g} vs {e}"
            else:
                assert gv == ev, f"{gv!r} != {ev!r} in {g} vs {e}"


def assert_query_fails(runner, sql: str, match: str) -> None:
    """assertQueryFails: the query must raise and the message must
    contain ``match``."""
    try:
        runner.execute(sql)
    except Exception as e:   # noqa: BLE001
        assert match.lower() in str(e).lower(), \
            f"error {e!r} does not contain {match!r}"
        return
    raise AssertionError(f"query did not fail: {sql}")


class TestingConnector(MemoryConnector):
    """The reference's TestingMetadata stand-in: a MemoryConnector
    with a one-call ``add_table(name, schema, rows)`` loader (the SPI
    surface itself — metadata/splits/read — is MemoryConnector's,
    so SPI changes have one implementation to track)."""

    __test__ = False      # not a pytest collection target

    name = "testing"

    def __init__(self, schema: str = "default"):
        super().__init__()
        self._schema = schema

    def add_table(self, name: str, schema: Dict[str, Type],
                  rows: List[dict]) -> None:
        self.create_table(TableMetadata(self._schema, name, tuple(
            ColumnMetadata(n, t) for n, t in schema.items())))
        self.insert(self._schema, name, batch_from_pylist(
            {c: [r.get(c) for r in rows] for c in schema},
            dict(schema)))
