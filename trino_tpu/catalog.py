"""Connector SPI + catalog management.

Reference parity: core/trino-spi/src/main/java/io/trino/spi/connector/
(Connector, ConnectorMetadata, ConnectorSplitManager, ConnectorPageSource —
spi/connector/ConnectorPageSource.java:47) and the engine-side
metadata/CatalogManager.java + MetadataManager.java routing. TPU-first
redesign: a connector's read path produces columnar ``Batch``es per split
(host numpy, uploaded to HBM lazily), not row cursors.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from .columnar import Batch
from .types import Type


@dataclass(frozen=True)
class ColumnMetadata:
    """spi/connector/ColumnMetadata.java"""
    name: str
    type: Type
    # connector-provided columns (ColumnMetadata.isHidden analog —
    # e.g. the stream connector's _partition/_offset ledger): still
    # selectable by name, but never an INSERT target
    hidden: bool = False


@dataclass(frozen=True)
class TableMetadata:
    """spi/connector/ConnectorTableMetadata.java"""
    schema: str
    name: str
    columns: Tuple[ColumnMetadata, ...]

    def column_type(self, name: str) -> Type:
        for c in self.columns:
            if c.name == name:
                return c.type
        raise KeyError(name)

    @property
    def column_names(self) -> List[str]:
        return [c.name for c in self.columns]


@dataclass(frozen=True)
class ColumnStatistics:
    """spi/statistics/ColumnStatistics.java: distinct-value count,
    value range (numeric/date columns; None for strings), null
    fraction."""
    ndv: float
    min_value: Optional[float] = None
    max_value: Optional[float] = None
    null_fraction: float = 0.0


@dataclass(frozen=True)
class ViewDefinition:
    """Engine view object (reference: metadata/ViewDefinition.java):
    the parsed query plus the original SQL text for SHOW CREATE VIEW."""
    query: object            # sql.ast.Query
    sql: str = ""


@dataclass(frozen=True)
class TableHandle:
    """Engine-side handle: catalog + connector's table identity
    (reference: metadata/TableHandle.java wrapping
    ConnectorTableHandle). ``constraint``/``limit`` carry accepted
    pushdowns (applyFilter/applyLimit results baked into the handle,
    like the reference's connector-specific handle evolution)."""
    catalog: str
    schema: str
    table: str
    constraint: Optional[object] = None    # predicate.TupleDomain
    limit: Optional[int] = None


@dataclass(frozen=True)
class Split:
    """One unit of scan parallelism (spi/connector/ConnectorSplit.java).
    ``part``/``part_count`` mirror the tpch connector's split addressing
    (plugin/trino-tpch/.../TpchSplitManager.java:32-46)."""
    handle: TableHandle
    part: int
    part_count: int


class Connector:
    """Connector SPI (spi/connector/Connector.java + ConnectorMetadata +
    ConnectorSplitManager + page source in one surface — the engine is in
    one process per node, so the factory indirection is unnecessary)."""

    name: str = "connector"

    # Splits are deterministic + immutable (pure generators): the
    # engine may cache read results device-resident across queries.
    scan_cache_ok: bool = False

    # --- metadata --------------------------------------------------------
    def list_schemas(self) -> List[str]:
        raise NotImplementedError

    def list_tables(self, schema: str) -> List[str]:
        raise NotImplementedError

    def get_table_metadata(self, schema: str,
                           table: str) -> Optional[TableMetadata]:
        raise NotImplementedError

    # --- splits ----------------------------------------------------------
    def get_splits(self, handle: TableHandle,
                   desired_parallelism: int = 1) -> List[Split]:
        return [Split(handle, 0, 1)]

    # --- data in ---------------------------------------------------------
    def read_split(self, split: Split,
                   columns: Sequence[str]) -> Batch:
        """Produce the split's rows for the requested columns
        (spi/connector/ConnectorPageSource.java:47 getNextPage, batched)."""
        raise NotImplementedError

    # --- data versioning (spi/connector/ConnectorMetadata
    # getTableHandleForExecute's table-version analog) --------------------
    def data_version(self) -> Optional[int]:
        """Monotonic data version for result-cache invalidation
        (exec/resultcache.py): a cached result is valid only while
        every scanned connector reports the version it was captured
        under. None = unversioned (mutations invisible to the engine,
        e.g. external JDBC sources) — results over it are uncacheable.
        Immutable pure generators (scan_cache_ok) are constant-1."""
        return 1 if self.scan_cache_ok else None

    # --- statistics (spi/statistics/TableStatistics.java) ----------------
    def table_row_count(self, handle: TableHandle) -> Optional[float]:
        return None

    def column_statistics(self, handle: TableHandle,
                          column: str) -> Optional["ColumnStatistics"]:
        """Per-column stats for the CBO (spi/statistics/
        ColumnStatistics.java); None = unknown."""
        return None

    # --- pushdown hooks (ConnectorMetadata.applyFilter/applyLimit) -------
    def apply_filter(self, handle: TableHandle, constraint):
        """Offer a TupleDomain over connector column names. Return
        (new_handle, fully_enforced) to accept, or None to decline.
        fully_enforced=True lets the engine drop the translated
        conjuncts entirely — only safe when read_split enforces the
        handle's constraint (predicate.filter_batch_host)."""
        return None

    def apply_limit(self, handle: TableHandle, limit: int):
        """Return a new handle that will produce at most ``limit`` rows
        per split (engine keeps its Limit node), or None."""
        return None

    # --- data out (spi/connector/ConnectorPageSink.java) -----------------
    def create_table(self, metadata: TableMetadata) -> None:
        raise NotImplementedError(f"{self.name}: CREATE TABLE not supported")

    def drop_table(self, schema: str, table: str) -> None:
        raise NotImplementedError(f"{self.name}: DROP TABLE not supported")

    def insert(self, schema: str, table: str, batch: Batch) -> int:
        raise NotImplementedError(f"{self.name}: INSERT not supported")

    # --- procedures (spi/procedure/Procedure.java) -----------------------
    def call_procedure(self, schema: str, name: str, args: list):
        raise KeyError(
            f"Procedure '{self.name}.{schema}.{name}' not registered")

    # --- transactions (spi/transaction/ConnectorTransactionHandle) -------
    def snapshot_state(self):
        """Opaque copy-on-begin state for the engine transaction manager
        (None = connector is read-only / not transactional)."""
        return None

    def restore_state(self, state) -> None:
        raise NotImplementedError(f"{self.name}: not transactional")


def accept_filter_pushdown(handle: TableHandle, constraint):
    """Shared applyFilter acceptance: intersect into the handle; the
    connector's read_split MUST then enforce handle.constraint."""
    merged = constraint if handle.constraint is None else \
        handle.constraint.intersect(constraint)
    return dataclasses.replace(handle, constraint=merged), True


def accept_limit_pushdown(handle: TableHandle, limit: int):
    """Shared applyLimit acceptance: keep the smaller limit; None when
    the handle already guarantees no more rows."""
    if handle.limit is not None and handle.limit <= limit:
        return None
    return dataclasses.replace(handle, limit=limit)


class CatalogManager:
    """metadata/CatalogManager.java — name → Connector registry, plus
    the engine-side view store (reference: MetadataManager view
    routing; views here are engine objects rather than per-connector
    since every connector would store the same SQL text)."""

    def __init__(self, access_control=None):
        self._catalogs: Dict[str, Connector] = {}
        self._views: Dict[Tuple[str, str, str], "ViewDefinition"] = {}
        # AccessControl SPI consulted by the planner/runner (None =
        # allow all; security/AccessControlManager.java)
        self.access_control = access_control
        # engine-level grant store (reference routes GRANT to connector
        # metadata — MetadataManager.grantTablePrivileges; ours is
        # engine-scoped so every connector gets GRANT support):
        # (grantee, privilege, catalog, schema, table) -> grantable
        self.grants: Dict[Tuple[str, str, str, str, str], bool] = {}
        # DENY entries (same key; deny wins over grant)
        self.denies: set = set()

    # --- views -----------------------------------------------------------
    def create_view(self, catalog: str, schema: str, name: str,
                    view: "ViewDefinition",
                    replace: bool = False) -> None:
        key = (catalog, schema, name)
        if key in self._views and not replace:
            raise KeyError(
                f"View '{catalog}.{schema}.{name}' already exists")
        self._views[key] = view

    def drop_view(self, catalog: str, schema: str, name: str) -> bool:
        return self._views.pop((catalog, schema, name), None) is not None

    def get_view(self, catalog: str, schema: str,
                 name: str) -> Optional["ViewDefinition"]:
        return self._views.get((catalog, schema, name))

    def list_views(self, catalog: str, schema: str) -> List[str]:
        return sorted(n for (c, s, n) in self._views
                      if c == catalog and s == schema)

    def register(self, name: str, connector: Connector) -> None:
        self._catalogs[name] = connector

    def connector(self, name: str) -> Connector:
        try:
            return self._catalogs[name]
        except KeyError:
            raise KeyError(f"Catalog '{name}' does not exist") from None

    def list_catalogs(self) -> List[str]:
        return sorted(self._catalogs)

    def resolve_table(self, catalog: str, schema: str,
                      table: str) -> Tuple[TableHandle, TableMetadata]:
        conn = self.connector(catalog)
        meta = conn.get_table_metadata(schema, table)
        if meta is None:
            raise KeyError(
                f"Table '{catalog}.{schema}.{table}' does not exist")
        return TableHandle(catalog, schema, table), meta
