"""Connector SPI + catalog management.

Reference parity: core/trino-spi/src/main/java/io/trino/spi/connector/
(Connector, ConnectorMetadata, ConnectorSplitManager, ConnectorPageSource —
spi/connector/ConnectorPageSource.java:47) and the engine-side
metadata/CatalogManager.java + MetadataManager.java routing. TPU-first
redesign: a connector's read path produces columnar ``Batch``es per split
(host numpy, uploaded to HBM lazily), not row cursors.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from .columnar import Batch
from .types import Type


@dataclass(frozen=True)
class ColumnMetadata:
    """spi/connector/ColumnMetadata.java"""
    name: str
    type: Type


@dataclass(frozen=True)
class TableMetadata:
    """spi/connector/ConnectorTableMetadata.java"""
    schema: str
    name: str
    columns: Tuple[ColumnMetadata, ...]

    def column_type(self, name: str) -> Type:
        for c in self.columns:
            if c.name == name:
                return c.type
        raise KeyError(name)

    @property
    def column_names(self) -> List[str]:
        return [c.name for c in self.columns]


@dataclass(frozen=True)
class TableHandle:
    """Engine-side handle: catalog + connector's table identity
    (reference: metadata/TableHandle.java wrapping
    ConnectorTableHandle)."""
    catalog: str
    schema: str
    table: str


@dataclass(frozen=True)
class Split:
    """One unit of scan parallelism (spi/connector/ConnectorSplit.java).
    ``part``/``part_count`` mirror the tpch connector's split addressing
    (plugin/trino-tpch/.../TpchSplitManager.java:32-46)."""
    handle: TableHandle
    part: int
    part_count: int


class Connector:
    """Connector SPI (spi/connector/Connector.java + ConnectorMetadata +
    ConnectorSplitManager + page source in one surface — the engine is in
    one process per node, so the factory indirection is unnecessary)."""

    name: str = "connector"

    # --- metadata --------------------------------------------------------
    def list_schemas(self) -> List[str]:
        raise NotImplementedError

    def list_tables(self, schema: str) -> List[str]:
        raise NotImplementedError

    def get_table_metadata(self, schema: str,
                           table: str) -> Optional[TableMetadata]:
        raise NotImplementedError

    # --- splits ----------------------------------------------------------
    def get_splits(self, handle: TableHandle,
                   desired_parallelism: int = 1) -> List[Split]:
        return [Split(handle, 0, 1)]

    # --- data in ---------------------------------------------------------
    def read_split(self, split: Split,
                   columns: Sequence[str]) -> Batch:
        """Produce the split's rows for the requested columns
        (spi/connector/ConnectorPageSource.java:47 getNextPage, batched)."""
        raise NotImplementedError

    # --- statistics (spi/statistics/TableStatistics.java) ----------------
    def table_row_count(self, handle: TableHandle) -> Optional[float]:
        return None

    # --- data out (spi/connector/ConnectorPageSink.java) -----------------
    def create_table(self, metadata: TableMetadata) -> None:
        raise NotImplementedError(f"{self.name}: CREATE TABLE not supported")

    def drop_table(self, schema: str, table: str) -> None:
        raise NotImplementedError(f"{self.name}: DROP TABLE not supported")

    def insert(self, schema: str, table: str, batch: Batch) -> int:
        raise NotImplementedError(f"{self.name}: INSERT not supported")


class CatalogManager:
    """metadata/CatalogManager.java — name → Connector registry."""

    def __init__(self):
        self._catalogs: Dict[str, Connector] = {}

    def register(self, name: str, connector: Connector) -> None:
        self._catalogs[name] = connector

    def connector(self, name: str) -> Connector:
        try:
            return self._catalogs[name]
        except KeyError:
            raise KeyError(f"Catalog '{name}' does not exist") from None

    def list_catalogs(self) -> List[str]:
        return sorted(self._catalogs)

    def resolve_table(self, catalog: str, schema: str,
                      table: str) -> Tuple[TableHandle, TableMetadata]:
        conn = self.connector(catalog)
        meta = conn.get_table_metadata(schema, table)
        if meta is None:
            raise KeyError(
                f"Table '{catalog}.{schema}.{table}' does not exist")
        return TableHandle(catalog, schema, table), meta
