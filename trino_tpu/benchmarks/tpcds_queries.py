"""TPC-DS query texts (authored from the TPC-DS specification v2.x
query templates with the default substitution parameters, adapted
where our generator's synthetic string domains differ (class names,
counties, buy-potential buckets) and with stddev_samp columns dropped
from q17 (the sqlite oracle lacks them); q53/q89/q98's agg-in-window
sums are expressed as the equivalent two-level form; reference
harness: testing/trino-benchto-benchmarks/src/main/resources/sql/presto/
tpcds/). BASELINE.json configs[4] is q64.

Unqualified table names resolve against the session catalog/schema
(run with catalog=tpcds).
"""

TPCDS_QUERIES = {
    1: """
WITH customer_total_return AS (
  SELECT sr_customer_sk ctr_customer_sk, sr_store_sk ctr_store_sk,
         sum(sr_return_amt) ctr_total_return
  FROM store_returns, date_dim
  WHERE sr_returned_date_sk = d_date_sk AND d_year = 2000
  GROUP BY sr_customer_sk, sr_store_sk)
SELECT c_customer_id
FROM customer_total_return ctr1, store, customer
WHERE ctr1.ctr_total_return > (SELECT avg(ctr_total_return) * 1.2
                               FROM customer_total_return ctr2
                               WHERE ctr1.ctr_store_sk = ctr2.ctr_store_sk)
  AND s_store_sk = ctr1.ctr_store_sk
  AND s_state = 'TN'
  AND ctr1.ctr_customer_sk = c_customer_sk
ORDER BY c_customer_id
LIMIT 100
""",
    6: """
SELECT a.ca_state state, count(*) cnt
FROM customer_address a, customer c, store_sales s, date_dim d, item i
WHERE a.ca_address_sk = c.c_current_addr_sk
  AND c.c_customer_sk = s.ss_customer_sk
  AND s.ss_sold_date_sk = d.d_date_sk
  AND s.ss_item_sk = i.i_item_sk
  AND d.d_month_seq = (SELECT DISTINCT d_month_seq FROM date_dim
                       WHERE d_year = 2000 AND d_moy = 5)
  AND i.i_current_price > 1.2 * (SELECT avg(j.i_current_price)
                                 FROM item j
                                 WHERE j.i_category = i.i_category)
GROUP BY a.ca_state
HAVING count(*) >= 10
ORDER BY cnt, a.ca_state
LIMIT 100
""",
    15: """
SELECT ca_zip, sum(cs_sales_price) total
FROM catalog_sales, customer, customer_address, date_dim
WHERE cs_bill_customer_sk = c_customer_sk
  AND c_current_addr_sk = ca_address_sk
  AND (substr(ca_zip, 1, 5) IN ('85669', '86197', '88274', '83405',
                                '86475', '85392', '85460', '80348',
                                '81792')
       OR ca_state IN ('CA', 'WA', 'GA')
       OR cs_sales_price > 500)
  AND cs_sold_date_sk = d_date_sk
  AND d_qoy = 2 AND d_year = 2000
GROUP BY ca_zip
ORDER BY ca_zip
LIMIT 100
""",
    17: """
SELECT i_item_id, i_item_desc, s_state,
       count(ss_quantity) store_sales_quantitycount,
       avg(ss_quantity) store_sales_quantityave,
       count(sr_return_quantity) store_returns_quantitycount,
       avg(sr_return_quantity) store_returns_quantityave,
       count(cs_quantity) catalog_sales_quantitycount,
       avg(cs_quantity) catalog_sales_quantityave
FROM store_sales, store_returns, catalog_sales,
     date_dim d1, date_dim d2, date_dim d3, store, item
WHERE d1.d_quarter_name = '2000Q1'
  AND d1.d_date_sk = ss_sold_date_sk
  AND i_item_sk = ss_item_sk
  AND s_store_sk = ss_store_sk
  AND ss_customer_sk = sr_customer_sk
  AND ss_item_sk = sr_item_sk
  AND ss_ticket_number = sr_ticket_number
  AND sr_returned_date_sk = d2.d_date_sk
  AND d2.d_quarter_name IN ('2000Q1', '2000Q2', '2000Q3')
  AND sr_customer_sk = cs_bill_customer_sk
  AND sr_item_sk = cs_item_sk
  AND cs_sold_date_sk = d3.d_date_sk
  AND d3.d_quarter_name IN ('2000Q1', '2000Q2', '2000Q3')
GROUP BY i_item_id, i_item_desc, s_state
ORDER BY i_item_id, i_item_desc, s_state
LIMIT 100
""",
    25: """
SELECT i_item_id, i_item_desc, s_store_id, s_store_name,
       sum(ss_net_profit) store_sales_profit,
       sum(sr_net_loss) store_returns_loss,
       sum(cs_net_profit) catalog_sales_profit
FROM store_sales, store_returns, catalog_sales,
     date_dim d1, date_dim d2, date_dim d3, store, item
WHERE d1.d_moy = 4 AND d1.d_year = 2000
  AND d1.d_date_sk = ss_sold_date_sk
  AND i_item_sk = ss_item_sk
  AND s_store_sk = ss_store_sk
  AND ss_customer_sk = sr_customer_sk
  AND ss_item_sk = sr_item_sk
  AND ss_ticket_number = sr_ticket_number
  AND sr_returned_date_sk = d2.d_date_sk
  AND d2.d_moy BETWEEN 4 AND 10 AND d2.d_year = 2000
  AND sr_customer_sk = cs_bill_customer_sk
  AND sr_item_sk = cs_item_sk
  AND cs_sold_date_sk = d3.d_date_sk
  AND d3.d_moy BETWEEN 4 AND 10 AND d3.d_year = 2000
GROUP BY i_item_id, i_item_desc, s_store_id, s_store_name
ORDER BY i_item_id, i_item_desc, s_store_id, s_store_name
LIMIT 100
""",
    27: """
SELECT i_item_id, s_state,
       avg(ss_quantity) agg1, avg(ss_list_price) agg2,
       avg(ss_coupon_amt) agg3, avg(ss_sales_price) agg4
FROM store_sales, customer_demographics, date_dim, store, item
WHERE ss_sold_date_sk = d_date_sk
  AND ss_item_sk = i_item_sk
  AND ss_store_sk = s_store_sk
  AND ss_cdemo_sk = cd_demo_sk
  AND cd_gender = 'M' AND cd_marital_status = 'S'
  AND cd_education_status = 'College'
  AND d_year = 2000
  AND s_state IN ('TN', 'OH', 'TX', 'GA', 'IL')
GROUP BY ROLLUP (i_item_id, s_state)
ORDER BY i_item_id NULLS LAST, s_state NULLS LAST
LIMIT 100
""",
    28: """
SELECT *
FROM (SELECT avg(ss_list_price) b1_lp, count(ss_list_price) b1_cnt,
             count(DISTINCT ss_list_price) b1_cntd
      FROM store_sales
      WHERE ss_quantity BETWEEN 0 AND 5
        AND (ss_list_price BETWEEN 8 AND 18
             OR ss_coupon_amt BETWEEN 459 AND 1459
             OR ss_wholesale_cost BETWEEN 57 AND 77)) b1,
     (SELECT avg(ss_list_price) b2_lp, count(ss_list_price) b2_cnt,
             count(DISTINCT ss_list_price) b2_cntd
      FROM store_sales
      WHERE ss_quantity BETWEEN 6 AND 10
        AND (ss_list_price BETWEEN 90 AND 100
             OR ss_coupon_amt BETWEEN 2323 AND 3323
             OR ss_wholesale_cost BETWEEN 31 AND 51)) b2,
     (SELECT avg(ss_list_price) b3_lp, count(ss_list_price) b3_cnt,
             count(DISTINCT ss_list_price) b3_cntd
      FROM store_sales
      WHERE ss_quantity BETWEEN 11 AND 15
        AND (ss_list_price BETWEEN 142 AND 152
             OR ss_coupon_amt BETWEEN 12214 AND 13214
             OR ss_wholesale_cost BETWEEN 79 AND 99)) b3,
     (SELECT avg(ss_list_price) b4_lp, count(ss_list_price) b4_cnt,
             count(DISTINCT ss_list_price) b4_cntd
      FROM store_sales
      WHERE ss_quantity BETWEEN 16 AND 20
        AND (ss_list_price BETWEEN 135 AND 145
             OR ss_coupon_amt BETWEEN 6071 AND 7071
             OR ss_wholesale_cost BETWEEN 38 AND 58)) b4,
     (SELECT avg(ss_list_price) b5_lp, count(ss_list_price) b5_cnt,
             count(DISTINCT ss_list_price) b5_cntd
      FROM store_sales
      WHERE ss_quantity BETWEEN 21 AND 25
        AND (ss_list_price BETWEEN 122 AND 132
             OR ss_coupon_amt BETWEEN 836 AND 1836
             OR ss_wholesale_cost BETWEEN 17 AND 37)) b5,
     (SELECT avg(ss_list_price) b6_lp, count(ss_list_price) b6_cnt,
             count(DISTINCT ss_list_price) b6_cntd
      FROM store_sales
      WHERE ss_quantity BETWEEN 26 AND 30
        AND (ss_list_price BETWEEN 80 AND 90
             OR ss_coupon_amt BETWEEN 2502 AND 3502
             OR ss_wholesale_cost BETWEEN 68 AND 88)) b6
LIMIT 100
""",
    43: """
SELECT s_store_name, s_store_id,
       sum(CASE WHEN d_day_name = 'Sunday' THEN ss_sales_price
           ELSE NULL END) sun_sales,
       sum(CASE WHEN d_day_name = 'Monday' THEN ss_sales_price
           ELSE NULL END) mon_sales,
       sum(CASE WHEN d_day_name = 'Tuesday' THEN ss_sales_price
           ELSE NULL END) tue_sales,
       sum(CASE WHEN d_day_name = 'Wednesday' THEN ss_sales_price
           ELSE NULL END) wed_sales,
       sum(CASE WHEN d_day_name = 'Thursday' THEN ss_sales_price
           ELSE NULL END) thu_sales,
       sum(CASE WHEN d_day_name = 'Friday' THEN ss_sales_price
           ELSE NULL END) fri_sales,
       sum(CASE WHEN d_day_name = 'Saturday' THEN ss_sales_price
           ELSE NULL END) sat_sales
FROM date_dim, store_sales, store
WHERE d_date_sk = ss_sold_date_sk
  AND s_store_sk = ss_store_sk
  AND d_year = 2000
GROUP BY s_store_name, s_store_id
ORDER BY s_store_name, s_store_id, sun_sales, mon_sales, tue_sales,
         wed_sales, thu_sales, fri_sales, sat_sales
LIMIT 100
""",
    48: """
SELECT sum(ss_quantity) total
FROM store_sales, store, customer_demographics, customer_address,
     date_dim
WHERE s_store_sk = ss_store_sk
  AND ss_sold_date_sk = d_date_sk AND d_year = 2000
  AND ((cd_demo_sk = ss_cdemo_sk AND cd_marital_status = 'M'
        AND cd_education_status = '4 yr Degree'
        AND ss_sales_price BETWEEN 100.00 AND 150.00)
       OR (cd_demo_sk = ss_cdemo_sk AND cd_marital_status = 'D'
           AND cd_education_status = '2 yr Degree'
           AND ss_sales_price BETWEEN 50.00 AND 100.00)
       OR (cd_demo_sk = ss_cdemo_sk AND cd_marital_status = 'S'
           AND cd_education_status = 'College'
           AND ss_sales_price BETWEEN 150.00 AND 200.00))
  AND ((ss_addr_sk = ca_address_sk AND ca_country = 'United States'
        AND ca_state IN ('CA', 'OH', 'TX')
        AND ss_net_profit BETWEEN 0 AND 2000)
       OR (ss_addr_sk = ca_address_sk AND ca_country = 'United States'
           AND ca_state IN ('OR', 'MN', 'KY')
           AND ss_net_profit BETWEEN 150 AND 3000)
       OR (ss_addr_sk = ca_address_sk AND ca_country = 'United States'
           AND ca_state IN ('VA', 'CA', 'MS')
           AND ss_net_profit BETWEEN 50 AND 25000))
""",
    53: """
SELECT * FROM (
  SELECT i_manufact_id, sum_sales,
         avg(sum_sales) OVER (PARTITION BY i_manufact_id)
             avg_quarterly_sales
  FROM (SELECT i_manufact_id, d_qoy, sum(ss_sales_price) sum_sales
        FROM item, store_sales, date_dim, store
        WHERE ss_item_sk = i_item_sk AND ss_sold_date_sk = d_date_sk
          AND ss_store_sk = s_store_sk
          AND d_month_seq IN (1200, 1201, 1202, 1203, 1204, 1205,
                              1206, 1207, 1208, 1209, 1210, 1211)
          AND ((i_category IN ('Books', 'Children', 'Electronics')
                AND i_class IN ('class#1', 'class#2', 'class#3'))
               OR (i_category IN ('Women', 'Music', 'Men')
                   AND i_class IN ('class#4', 'class#5', 'class#6')))
        GROUP BY i_manufact_id, d_qoy) sales) tmp1
WHERE CASE WHEN avg_quarterly_sales > 0
           THEN abs(sum_sales - avg_quarterly_sales)
                / avg_quarterly_sales
           ELSE NULL END > 0.1
ORDER BY avg_quarterly_sales, sum_sales, i_manufact_id
LIMIT 100
""",
    59: """
WITH wss AS (
  SELECT d_week_seq, ss_store_sk,
         sum(CASE WHEN d_day_name = 'Sunday' THEN ss_sales_price
             ELSE NULL END) sun_sales,
         sum(CASE WHEN d_day_name = 'Monday' THEN ss_sales_price
             ELSE NULL END) mon_sales,
         sum(CASE WHEN d_day_name = 'Wednesday' THEN ss_sales_price
             ELSE NULL END) wed_sales,
         sum(CASE WHEN d_day_name = 'Friday' THEN ss_sales_price
             ELSE NULL END) fri_sales
  FROM store_sales, date_dim
  WHERE d_date_sk = ss_sold_date_sk
  GROUP BY d_week_seq, ss_store_sk)
SELECT s_store_name1, s_store_id1, d_week_seq1,
       sun_sales1 / sun_sales2 sun_r, mon_sales1 / mon_sales2 mon_r,
       wed_sales1 / wed_sales2 wed_r, fri_sales1 / fri_sales2 fri_r
FROM (SELECT s_store_name s_store_name1, wss.d_week_seq d_week_seq1,
             s_store_id s_store_id1, sun_sales sun_sales1,
             mon_sales mon_sales1, wed_sales wed_sales1,
             fri_sales fri_sales1
      FROM wss, store, date_dim d
      WHERE d.d_week_seq = wss.d_week_seq
        AND ss_store_sk = s_store_sk
        AND d_month_seq BETWEEN 1200 AND 1211) y,
     (SELECT s_store_name s_store_name2, wss.d_week_seq d_week_seq2,
             s_store_id s_store_id2, sun_sales sun_sales2,
             mon_sales mon_sales2, wed_sales wed_sales2,
             fri_sales fri_sales2
      FROM wss, store, date_dim d
      WHERE d.d_week_seq = wss.d_week_seq
        AND ss_store_sk = s_store_sk
        AND d_month_seq BETWEEN 1212 AND 1223) x
WHERE s_store_id1 = s_store_id2
  AND d_week_seq1 = d_week_seq2 - 52
ORDER BY s_store_name1, s_store_id1, d_week_seq1
LIMIT 100
""",
    65: """
SELECT s_store_name, i_item_desc, sc.revenue, i_current_price,
       i_wholesale_cost, i_brand
FROM store, item,
     (SELECT ss_store_sk, avg(revenue) ave
      FROM (SELECT ss_store_sk, ss_item_sk,
                   sum(ss_sales_price) revenue
            FROM store_sales, date_dim
            WHERE ss_sold_date_sk = d_date_sk
              AND d_month_seq BETWEEN 1200 AND 1211
            GROUP BY ss_store_sk, ss_item_sk) sa
      GROUP BY ss_store_sk) sb,
     (SELECT ss_store_sk, ss_item_sk, sum(ss_sales_price) revenue
      FROM store_sales, date_dim
      WHERE ss_sold_date_sk = d_date_sk
        AND d_month_seq BETWEEN 1200 AND 1211
      GROUP BY ss_store_sk, ss_item_sk) sc
WHERE sb.ss_store_sk = sc.ss_store_sk
  AND sc.revenue <= 0.1 * sb.ave
  AND s_store_sk = sc.ss_store_sk
  AND i_item_sk = sc.ss_item_sk
ORDER BY s_store_name, i_item_desc, sc.revenue
LIMIT 100
""",
    73: """
SELECT c_last_name, c_first_name, ss_ticket_number, cnt
FROM (SELECT ss_ticket_number, ss_customer_sk, count(*) cnt
      FROM store_sales, date_dim, store, household_demographics
      WHERE ss_sold_date_sk = d_date_sk
        AND ss_store_sk = s_store_sk
        AND ss_hdemo_sk = hd_demo_sk
        AND d_dom BETWEEN 1 AND 2
        AND (hd_buy_potential = '>10000'
             OR hd_buy_potential = 'Unknown')
        AND hd_vehicle_count > 0
        AND CASE WHEN hd_vehicle_count > 0
                 THEN hd_dep_count / hd_vehicle_count
                 ELSE NULL END > 1
        AND d_year IN (2000, 2001, 2002)
        AND s_county IN ('Williamson County', 'Ziebach County',
                         'Walker County', 'Daviess County')
      GROUP BY ss_ticket_number, ss_customer_sk) dj, customer
WHERE ss_customer_sk = c_customer_sk
  AND cnt BETWEEN 1 AND 5
ORDER BY cnt DESC, c_last_name ASC, ss_ticket_number
LIMIT 100
""",
    79: """
SELECT c_last_name, c_first_name, substr(s_city, 1, 30) city,
       ss_ticket_number, amt, profit
FROM (SELECT ss_ticket_number, ss_customer_sk, s_city,
             sum(ss_coupon_amt) amt, sum(ss_net_profit) profit
      FROM store_sales, date_dim, store, household_demographics
      WHERE ss_sold_date_sk = d_date_sk
        AND ss_store_sk = s_store_sk
        AND ss_hdemo_sk = hd_demo_sk
        AND (hd_dep_count = 6 OR hd_vehicle_count > 2)
        AND d_dow = 1
        AND d_year IN (2000, 2001, 2002)
        AND s_number_employees BETWEEN 200 AND 295
      GROUP BY ss_ticket_number, ss_customer_sk, ss_addr_sk,
               s_city) ms, customer
WHERE ss_customer_sk = c_customer_sk
ORDER BY c_last_name, c_first_name, city, profit, ss_ticket_number
LIMIT 100
""",
    89: """
SELECT * FROM (
  SELECT i_category, i_class, i_brand, s_store_name, s_company_name,
         d_moy, sum_sales,
         avg(sum_sales) OVER (PARTITION BY i_category, i_brand,
                              s_store_name, s_company_name)
             avg_monthly_sales
  FROM (SELECT i_category, i_class, i_brand, s_store_name,
               s_company_name, d_moy, sum(ss_sales_price) sum_sales
        FROM item, store_sales, date_dim, store
        WHERE ss_item_sk = i_item_sk AND ss_sold_date_sk = d_date_sk
          AND ss_store_sk = s_store_sk
          AND d_year = 2000
          AND ((i_category IN ('Women', 'Music', 'Men')
                AND i_class IN ('class#1', 'class#2', 'class#3'))
               OR (i_category IN ('Jewelry', 'Shoes', 'Children')
                   AND i_class IN ('class#4', 'class#5', 'class#6')))
        GROUP BY i_category, i_class, i_brand, s_store_name,
                 s_company_name, d_moy) t) tmp1
WHERE CASE WHEN avg_monthly_sales <> 0
           THEN abs(sum_sales - avg_monthly_sales) / avg_monthly_sales
           ELSE NULL END > 0.1
ORDER BY sum_sales - avg_monthly_sales, s_store_name, sum_sales,
         i_category, i_class, i_brand, d_moy
LIMIT 100
""",
    98: """
SELECT i_item_id, i_item_desc, i_category, i_class, i_current_price,
       itemrevenue,
       itemrevenue * 100.0
           / sum(itemrevenue) OVER (PARTITION BY i_class) revenueratio
FROM (SELECT i_item_id, i_item_desc, i_category, i_class,
             i_current_price, sum(ss_ext_sales_price) itemrevenue
      FROM store_sales, item, date_dim
      WHERE ss_item_sk = i_item_sk
        AND i_category IN ('Women', 'Music', 'Men')
        AND ss_sold_date_sk = d_date_sk
        AND d_date BETWEEN DATE '2000-02-01' AND DATE '2000-03-01'
      GROUP BY i_item_id, i_item_desc, i_category, i_class,
               i_current_price) t
ORDER BY i_category, i_class, i_item_id, i_item_desc, revenueratio
LIMIT 100
""",
    3: """
SELECT d_year, i_brand_id, i_brand, sum(ss_ext_sales_price) sum_agg
FROM date_dim, store_sales, item
WHERE d_date_sk = ss_sold_date_sk
  AND ss_item_sk = i_item_sk
  AND i_manufact_id = 128
  AND d_moy = 11
GROUP BY d_year, i_brand_id, i_brand
ORDER BY d_year, sum_agg DESC, i_brand_id
LIMIT 100
""",
    7: """
SELECT i_item_id,
       avg(ss_quantity) agg1,
       avg(ss_list_price) agg2,
       avg(ss_coupon_amt) agg3,
       avg(ss_sales_price) agg4
FROM store_sales, customer_demographics, date_dim, item, promotion
WHERE ss_sold_date_sk = d_date_sk
  AND ss_item_sk = i_item_sk
  AND ss_cdemo_sk = cd_demo_sk
  AND ss_promo_sk = p_promo_sk
  AND cd_gender = 'M'
  AND cd_marital_status = 'S'
  AND cd_education_status = 'College'
  AND (p_channel_email = 'N' OR p_channel_event = 'N')
  AND d_year = 2000
GROUP BY i_item_id
ORDER BY i_item_id
LIMIT 100
""",
    42: """
SELECT d_year, i_category_id, i_category, sum(ss_ext_sales_price) total
FROM date_dim, store_sales, item
WHERE d_date_sk = ss_sold_date_sk
  AND ss_item_sk = i_item_sk
  AND i_manager_id = 1
  AND d_moy = 11
  AND d_year = 2000
GROUP BY d_year, i_category_id, i_category
ORDER BY total DESC, d_year, i_category_id, i_category
LIMIT 100
""",
    52: """
SELECT d_year, i_brand_id, i_brand, sum(ss_ext_sales_price) ext_price
FROM date_dim, store_sales, item
WHERE d_date_sk = ss_sold_date_sk
  AND ss_item_sk = i_item_sk
  AND i_manager_id = 1
  AND d_moy = 11
  AND d_year = 2000
GROUP BY d_year, i_brand_id, i_brand
ORDER BY d_year, ext_price DESC, i_brand_id
LIMIT 100
""",
    55: """
SELECT i_brand_id brand_id, i_brand brand, sum(ss_ext_sales_price) ext_price
FROM date_dim, store_sales, item
WHERE d_date_sk = ss_sold_date_sk
  AND ss_item_sk = i_item_sk
  AND i_manager_id = 28
  AND d_moy = 11
  AND d_year = 1999
GROUP BY i_brand_id, i_brand
ORDER BY ext_price DESC, i_brand_id
LIMIT 100
""",
    64: """
WITH cs_ui AS (
  SELECT cs_item_sk,
         sum(cs_ext_list_price) AS sale,
         sum(cr_refunded_cash + cr_reversed_charge + cr_store_credit)
             AS refund
  FROM catalog_sales, catalog_returns
  WHERE cs_item_sk = cr_item_sk
    AND cs_order_number = cr_order_number
  GROUP BY cs_item_sk
  HAVING sum(cs_ext_list_price) >
         2 * sum(cr_refunded_cash + cr_reversed_charge + cr_store_credit)
),
cross_sales AS (
  SELECT i_product_name AS product_name,
         i_item_sk AS item_sk,
         s_store_name AS store_name,
         s_zip AS store_zip,
         ad1.ca_street_number AS b_street_number,
         ad1.ca_street_name AS b_street_name,
         ad1.ca_city AS b_city,
         ad1.ca_zip AS b_zip,
         ad2.ca_street_number AS c_street_number,
         ad2.ca_street_name AS c_street_name,
         ad2.ca_city AS c_city,
         ad2.ca_zip AS c_zip,
         d1.d_year AS syear,
         d2.d_year AS fsyear,
         d3.d_year AS s2year,
         count(*) AS cnt,
         sum(ss_wholesale_cost) AS s1,
         sum(ss_list_price) AS s2,
         sum(ss_coupon_amt) AS s3
  FROM store_sales, store_returns, cs_ui,
       date_dim d1, date_dim d2, date_dim d3,
       store, customer,
       customer_demographics cd1, customer_demographics cd2,
       promotion,
       household_demographics hd1, household_demographics hd2,
       customer_address ad1, customer_address ad2,
       income_band ib1, income_band ib2, item
  WHERE ss_store_sk = s_store_sk
    AND ss_sold_date_sk = d1.d_date_sk
    AND ss_customer_sk = c_customer_sk
    AND ss_cdemo_sk = cd1.cd_demo_sk
    AND ss_hdemo_sk = hd1.hd_demo_sk
    AND ss_addr_sk = ad1.ca_address_sk
    AND ss_item_sk = i_item_sk
    AND ss_item_sk = sr_item_sk
    AND ss_ticket_number = sr_ticket_number
    AND ss_item_sk = cs_ui.cs_item_sk
    AND c_current_cdemo_sk = cd2.cd_demo_sk
    AND c_current_hdemo_sk = hd2.hd_demo_sk
    AND c_current_addr_sk = ad2.ca_address_sk
    AND c_first_sales_date_sk = d2.d_date_sk
    AND c_first_shipto_date_sk = d3.d_date_sk
    AND ss_promo_sk = p_promo_sk
    AND hd1.hd_income_band_sk = ib1.ib_income_band_sk
    AND hd2.hd_income_band_sk = ib2.ib_income_band_sk
    AND cd1.cd_marital_status <> cd2.cd_marital_status
    AND i_color IN ('purple', 'burlywood', 'indian', 'spring',
                    'floral', 'medium')
    AND i_current_price BETWEEN 64 AND 74
    AND i_current_price BETWEEN 65 AND 79
  GROUP BY i_product_name, i_item_sk, s_store_name, s_zip,
           ad1.ca_street_number, ad1.ca_street_name, ad1.ca_city,
           ad1.ca_zip, ad2.ca_street_number, ad2.ca_street_name,
           ad2.ca_city, ad2.ca_zip, d1.d_year, d2.d_year, d3.d_year
)
SELECT cs1.product_name, cs1.store_name, cs1.store_zip,
       cs1.b_street_number, cs1.b_street_name, cs1.b_city, cs1.b_zip,
       cs1.c_street_number, cs1.c_street_name, cs1.c_city, cs1.c_zip,
       cs1.syear, cs1.cnt,
       cs1.s1 AS s11, cs1.s2 AS s21, cs1.s3 AS s31,
       cs2.s1 AS s12, cs2.s2 AS s22, cs2.s3 AS s32,
       cs2.syear AS syear2, cs2.cnt AS cnt2
FROM cross_sales cs1, cross_sales cs2
WHERE cs1.item_sk = cs2.item_sk
  AND cs1.syear = 1999
  AND cs2.syear = 2000
  AND cs2.cnt <= cs1.cnt
  AND cs1.store_name = cs2.store_name
  AND cs1.store_zip = cs2.store_zip
ORDER BY cs1.product_name, cs1.store_name, cs2.cnt, 14, 15, 16, 17, 18
""",
    # ---- round-4 batch: web/catalog channels, inventory, time_dim ----
    12: """
SELECT i_item_id, i_item_desc, i_category, i_class, i_current_price,
       itemrevenue,
       itemrevenue * 100.0
           / sum(itemrevenue) OVER (PARTITION BY i_class) revenueratio
FROM (SELECT i_item_id, i_item_desc, i_category, i_class,
             i_current_price, sum(ws_ext_sales_price) itemrevenue
      FROM web_sales, item, date_dim
      WHERE ws_item_sk = i_item_sk
        AND i_category IN ('Sports', 'Books', 'Home')
        AND ws_sold_date_sk = d_date_sk
        AND d_date BETWEEN DATE '1999-02-22' AND DATE '1999-03-24'
      GROUP BY i_item_id, i_item_desc, i_category, i_class,
               i_current_price) t
ORDER BY i_category, i_class, i_item_id, i_item_desc, revenueratio
LIMIT 100
""",
    20: """
SELECT i_item_id, i_item_desc, i_category, i_class, i_current_price,
       itemrevenue,
       itemrevenue * 100.0
           / sum(itemrevenue) OVER (PARTITION BY i_class) revenueratio
FROM (SELECT i_item_id, i_item_desc, i_category, i_class,
             i_current_price, sum(cs_ext_sales_price) itemrevenue
      FROM catalog_sales, item, date_dim
      WHERE cs_item_sk = i_item_sk
        AND i_category IN ('Sports', 'Books', 'Home')
        AND cs_sold_date_sk = d_date_sk
        AND d_date BETWEEN DATE '1999-02-22' AND DATE '1999-03-24'
      GROUP BY i_item_id, i_item_desc, i_category, i_class,
               i_current_price) t
ORDER BY i_category, i_class, i_item_id, i_item_desc, revenueratio
LIMIT 100
""",
    26: """
SELECT i_item_id,
       avg(cs_quantity) agg1, avg(cs_list_price) agg2,
       avg(cs_coupon_amt) agg3, avg(cs_sales_price) agg4
FROM catalog_sales, customer_demographics, date_dim, item, promotion
WHERE cs_sold_date_sk = d_date_sk
  AND cs_item_sk = i_item_sk
  AND cs_bill_cdemo_sk = cd_demo_sk
  AND cs_promo_sk = p_promo_sk
  AND cd_gender = 'M' AND cd_marital_status = 'S'
  AND cd_education_status = 'College'
  AND (p_channel_email = 'N' OR p_channel_event = 'N')
  AND d_year = 2000
GROUP BY i_item_id
ORDER BY i_item_id
LIMIT 100
""",
    32: """
SELECT sum(cs_ext_discount_amt) excess_discount_amount
FROM catalog_sales cs1, item, date_dim
WHERE i_manufact_id = 977
  AND i_item_sk = cs1.cs_item_sk
  AND d_date BETWEEN DATE '2000-01-27' AND DATE '2000-04-26'
  AND d_date_sk = cs1.cs_sold_date_sk
  AND cs1.cs_ext_discount_amt
      > (SELECT 1.3 * avg(cs_ext_discount_amt)
         FROM catalog_sales cs2, date_dim d2
         WHERE cs2.cs_item_sk = cs1.cs_item_sk
           AND d2.d_date BETWEEN DATE '2000-01-27'
                             AND DATE '2000-04-26'
           AND d2.d_date_sk = cs2.cs_sold_date_sk)
LIMIT 100
""",
    37: """
SELECT i_item_id, i_item_desc, i_current_price
FROM item, inventory, date_dim, catalog_sales
WHERE i_current_price BETWEEN 68 AND 98
  AND inv_item_sk = i_item_sk
  AND d_date_sk = inv_date_sk
  AND d_date BETWEEN DATE '2000-02-01' AND DATE '2000-04-01'
  AND i_manufact_id IN (677, 940, 694, 808)
  AND inv_quantity_on_hand BETWEEN 100 AND 500
  AND cs_item_sk = i_item_sk
GROUP BY i_item_id, i_item_desc, i_current_price
ORDER BY i_item_id
LIMIT 100
""",
    62: """
SELECT substr(w_warehouse_name, 1, 20) wname, sm_type, web_name,
       sum(CASE WHEN ws_ship_date_sk - ws_sold_date_sk <= 30
                THEN 1 ELSE 0 END) AS days30,
       sum(CASE WHEN ws_ship_date_sk - ws_sold_date_sk > 30
                 AND ws_ship_date_sk - ws_sold_date_sk <= 60
                THEN 1 ELSE 0 END) AS days31_60,
       sum(CASE WHEN ws_ship_date_sk - ws_sold_date_sk > 60
                 AND ws_ship_date_sk - ws_sold_date_sk <= 90
                THEN 1 ELSE 0 END) AS days61_90,
       sum(CASE WHEN ws_ship_date_sk - ws_sold_date_sk > 90
                 AND ws_ship_date_sk - ws_sold_date_sk <= 120
                THEN 1 ELSE 0 END) AS days91_120,
       sum(CASE WHEN ws_ship_date_sk - ws_sold_date_sk > 120
                THEN 1 ELSE 0 END) AS days_over_120
FROM web_sales, warehouse, ship_mode, web_site, date_dim
WHERE d_month_seq BETWEEN 1200 AND 1211
  AND ws_ship_date_sk = d_date_sk
  AND ws_warehouse_sk = w_warehouse_sk
  AND ws_ship_mode_sk = sm_ship_mode_sk
  AND ws_web_site_sk = web_site_sk
GROUP BY substr(w_warehouse_name, 1, 20), sm_type, web_name
ORDER BY wname, sm_type, web_name
LIMIT 100
""",
    82: """
SELECT i_item_id, i_item_desc, i_current_price
FROM item, inventory, date_dim, store_sales
WHERE i_current_price BETWEEN 62 AND 92
  AND inv_item_sk = i_item_sk
  AND d_date_sk = inv_date_sk
  AND d_date BETWEEN DATE '2000-05-25' AND DATE '2000-07-24'
  AND i_manufact_id IN (129, 270, 821, 423)
  AND inv_quantity_on_hand BETWEEN 100 AND 500
  AND ss_item_sk = i_item_sk
GROUP BY i_item_id, i_item_desc, i_current_price
ORDER BY i_item_id
LIMIT 100
""",
    86: """
SELECT total_sum, i_category, i_class, lochierarchy,
       rank() OVER (PARTITION BY lochierarchy,
                        CASE WHEN cls_grouping = 0
                             THEN i_category END
                    ORDER BY total_sum DESC) rank_within_parent
FROM (SELECT sum(ws_net_paid) total_sum, i_category, i_class,
             grouping(i_category) + grouping(i_class) lochierarchy,
             grouping(i_class) cls_grouping
      FROM web_sales, date_dim d1, item
      WHERE d1.d_month_seq BETWEEN 1200 AND 1211
        AND d1.d_date_sk = ws_sold_date_sk
        AND i_item_sk = ws_item_sk
      GROUP BY ROLLUP (i_category, i_class)) t
ORDER BY lochierarchy DESC,
         CASE WHEN lochierarchy = 0 THEN i_category END,
         rank_within_parent
LIMIT 100
""",
    92: """
SELECT sum(ws_ext_discount_amt) excess_discount_amount
FROM web_sales ws1, item, date_dim
WHERE i_manufact_id = 350
  AND i_item_sk = ws1.ws_item_sk
  AND d_date BETWEEN DATE '2000-01-27' AND DATE '2000-04-26'
  AND d_date_sk = ws1.ws_sold_date_sk
  AND ws1.ws_ext_discount_amt
      > (SELECT 1.3 * avg(ws_ext_discount_amt)
         FROM web_sales ws2, date_dim d2
         WHERE ws2.ws_item_sk = ws1.ws_item_sk
           AND d2.d_date BETWEEN DATE '2000-01-27'
                             AND DATE '2000-04-26'
           AND d2.d_date_sk = ws2.ws_sold_date_sk)
ORDER BY excess_discount_amount
LIMIT 100
""",
    93: """
SELECT ss_customer_sk, sum(act_sales) sumsales
FROM (SELECT ss_customer_sk,
             CASE WHEN sr_return_quantity IS NOT NULL
                  THEN (ss_quantity - sr_return_quantity)
                       * ss_sales_price
                  ELSE ss_quantity * ss_sales_price END act_sales
      FROM store_sales
      LEFT JOIN store_returns ON sr_item_sk = ss_item_sk
                             AND sr_ticket_number = ss_ticket_number,
           reason
      WHERE sr_reason_sk = r_reason_sk
        AND r_reason_desc = 'reason 28') t
GROUP BY ss_customer_sk
ORDER BY sumsales, ss_customer_sk
LIMIT 100
""",
    96: """
SELECT count(*) cnt
FROM store_sales, household_demographics, time_dim, store
WHERE ss_sold_time_sk = time_dim.t_time_sk
  AND ss_hdemo_sk = household_demographics.hd_demo_sk
  AND ss_store_sk = s_store_sk
  AND time_dim.t_hour = 20
  AND time_dim.t_minute >= 30
  AND household_demographics.hd_dep_count = 7
  AND store.s_store_name = 'ese'
ORDER BY count(*)
LIMIT 100
""",
    99: """
SELECT substr(w_warehouse_name, 1, 20) wname, sm_type, cc_name,
       sum(CASE WHEN cs_ship_date_sk - cs_sold_date_sk <= 30
                THEN 1 ELSE 0 END) AS days30,
       sum(CASE WHEN cs_ship_date_sk - cs_sold_date_sk > 30
                 AND cs_ship_date_sk - cs_sold_date_sk <= 60
                THEN 1 ELSE 0 END) AS days31_60,
       sum(CASE WHEN cs_ship_date_sk - cs_sold_date_sk > 60
                 AND cs_ship_date_sk - cs_sold_date_sk <= 90
                THEN 1 ELSE 0 END) AS days61_90,
       sum(CASE WHEN cs_ship_date_sk - cs_sold_date_sk > 90
                 AND cs_ship_date_sk - cs_sold_date_sk <= 120
                THEN 1 ELSE 0 END) AS days91_120,
       sum(CASE WHEN cs_ship_date_sk - cs_sold_date_sk > 120
                THEN 1 ELSE 0 END) AS days_over_120
FROM catalog_sales, warehouse, ship_mode, call_center, date_dim
WHERE d_month_seq BETWEEN 1200 AND 1211
  AND cs_ship_date_sk = d_date_sk
  AND cs_warehouse_sk = w_warehouse_sk
  AND cs_ship_mode_sk = sm_ship_mode_sk
  AND cs_call_center_sk = cc_call_center_sk
GROUP BY substr(w_warehouse_name, 1, 20), sm_type, cc_name
ORDER BY wname, sm_type, cc_name
LIMIT 100
""",
    13: """
SELECT avg(ss_quantity) q, avg(ss_ext_sales_price) esp,
       avg(ss_ext_wholesale_cost) ewc, sum(ss_ext_wholesale_cost) swc
FROM store_sales, store, customer_demographics,
     household_demographics, customer_address, date_dim
WHERE s_store_sk = ss_store_sk
  AND ss_sold_date_sk = d_date_sk AND d_year = 2001
  AND ((ss_hdemo_sk = hd_demo_sk AND cd_demo_sk = ss_cdemo_sk
        AND cd_marital_status = 'M'
        AND cd_education_status = 'Advanced Degree'
        AND ss_sales_price BETWEEN 100.00 AND 150.00
        AND hd_dep_count = 3)
       OR (ss_hdemo_sk = hd_demo_sk AND cd_demo_sk = ss_cdemo_sk
           AND cd_marital_status = 'S'
           AND cd_education_status = 'College'
           AND ss_sales_price BETWEEN 50.00 AND 100.00
           AND hd_dep_count = 1)
       OR (ss_hdemo_sk = hd_demo_sk AND cd_demo_sk = ss_cdemo_sk
           AND cd_marital_status = 'W'
           AND cd_education_status = '2 yr Degree'
           AND ss_sales_price BETWEEN 150.00 AND 200.00
           AND hd_dep_count = 1))
  AND ((ss_addr_sk = ca_address_sk AND ca_country = 'United States'
        AND ca_state IN ('TX', 'OH', 'TX')
        AND ss_net_profit BETWEEN 100 AND 200)
       OR (ss_addr_sk = ca_address_sk
           AND ca_country = 'United States'
           AND ca_state IN ('OR', 'NM', 'KY')
           AND ss_net_profit BETWEEN 150 AND 300)
       OR (ss_addr_sk = ca_address_sk
           AND ca_country = 'United States'
           AND ca_state IN ('VA', 'TX', 'MS')
           AND ss_net_profit BETWEEN 50 AND 250))
""",
    16: """
SELECT count(DISTINCT cs_order_number) order_count,
       sum(cs_ext_ship_cost) total_shipping_cost,
       sum(cs_net_profit) total_net_profit
FROM catalog_sales cs1, date_dim, customer_address, call_center
WHERE d_date BETWEEN DATE '2002-02-01' AND DATE '2002-04-02'
  AND cs1.cs_ship_date_sk = d_date_sk
  AND cs1.cs_ship_addr_sk = ca_address_sk
  AND ca_state = 'GA'
  AND cs1.cs_call_center_sk = cc_call_center_sk
  AND cc_county = 'Williamson County'
  AND EXISTS (SELECT *
              FROM catalog_sales cs2
              WHERE cs1.cs_order_number = cs2.cs_order_number
                AND cs1.cs_warehouse_sk <> cs2.cs_warehouse_sk)
  AND NOT EXISTS (SELECT *
                  FROM catalog_returns cr1
                  WHERE cs1.cs_order_number = cr1.cr_order_number)
ORDER BY count(DISTINCT cs_order_number)
LIMIT 100
""",
    19: """
SELECT i_brand_id brand_id, i_brand brand, i_manufact_id, i_manufact,
       sum(ss_ext_sales_price) ext_price
FROM date_dim, store_sales, item, customer, customer_address, store
WHERE d_date_sk = ss_sold_date_sk
  AND ss_item_sk = i_item_sk
  AND i_manager_id = 8
  AND d_moy = 11 AND d_year = 1998
  AND ss_customer_sk = c_customer_sk
  AND c_current_addr_sk = ca_address_sk
  AND ss_store_sk = s_store_sk
  AND substr(ca_zip, 1, 5) <> substr(s_zip, 1, 5)
GROUP BY i_brand, i_brand_id, i_manufact_id, i_manufact
ORDER BY ext_price DESC, i_brand, i_brand_id, i_manufact_id,
         i_manufact
LIMIT 100
""",
    21: """
SELECT w_warehouse_name, i_item_id,
       sum(CASE WHEN d_date < DATE '2000-03-11'
                THEN inv_quantity_on_hand ELSE 0 END) inv_before,
       sum(CASE WHEN d_date >= DATE '2000-03-11'
                THEN inv_quantity_on_hand ELSE 0 END) inv_after
FROM inventory, warehouse, item, date_dim
WHERE i_current_price BETWEEN 0.99 AND 1.49
  AND i_item_sk = inv_item_sk
  AND inv_warehouse_sk = w_warehouse_sk
  AND inv_date_sk = d_date_sk
  AND d_date BETWEEN DATE '2000-02-10' AND DATE '2000-04-10'
GROUP BY w_warehouse_name, i_item_id
HAVING (CASE WHEN sum(CASE WHEN d_date < DATE '2000-03-11'
                           THEN inv_quantity_on_hand ELSE 0 END) > 0
             THEN sum(CASE WHEN d_date >= DATE '2000-03-11'
                           THEN inv_quantity_on_hand ELSE 0 END)
                  * 1.000
                  / sum(CASE WHEN d_date < DATE '2000-03-11'
                             THEN inv_quantity_on_hand ELSE 0 END)
             ELSE NULL END) BETWEEN 2.000 / 3.000 AND 3.000 / 2.000
ORDER BY w_warehouse_name, i_item_id
LIMIT 100
""",
    22: """
SELECT i_product_name, i_brand, i_class, i_category,
       avg(inv_quantity_on_hand) qoh
FROM inventory, date_dim, item
WHERE inv_date_sk = d_date_sk
  AND inv_item_sk = i_item_sk
  AND d_month_seq BETWEEN 1200 AND 1211
GROUP BY ROLLUP (i_product_name, i_brand, i_class, i_category)
ORDER BY qoh, i_product_name, i_brand, i_class, i_category
LIMIT 100
""",
    29: """
SELECT i_item_id, i_item_desc, s_store_id, s_store_name,
       sum(ss_quantity) store_sales_quantity,
       sum(sr_return_quantity) store_returns_quantity,
       sum(cs_quantity) catalog_sales_quantity
FROM store_sales, store_returns, catalog_sales, date_dim d1,
     date_dim d2, date_dim d3, store, item
WHERE d1.d_moy = 9 AND d1.d_year = 1999
  AND d1.d_date_sk = ss_sold_date_sk
  AND i_item_sk = ss_item_sk
  AND s_store_sk = ss_store_sk
  AND ss_customer_sk = sr_customer_sk
  AND ss_item_sk = sr_item_sk
  AND ss_ticket_number = sr_ticket_number
  AND sr_returned_date_sk = d2.d_date_sk
  AND d2.d_moy BETWEEN 9 AND 12 AND d2.d_year = 1999
  AND sr_customer_sk = cs_bill_customer_sk
  AND sr_item_sk = cs_item_sk
  AND cs_sold_date_sk = d3.d_date_sk
  AND d3.d_year IN (1999, 2000, 2001)
GROUP BY i_item_id, i_item_desc, s_store_id, s_store_name
ORDER BY i_item_id, i_item_desc, s_store_id, s_store_name
LIMIT 100
""",
    33: """
WITH ss AS (
  SELECT i_manufact_id, sum(ss_ext_sales_price) total_sales
  FROM store_sales, date_dim, customer_address, item
  WHERE i_manufact_id IN (SELECT i_manufact_id FROM item
                          WHERE i_category = 'Electronics')
    AND ss_item_sk = i_item_sk
    AND ss_sold_date_sk = d_date_sk
    AND d_year = 1998 AND d_moy = 5
    AND ss_addr_sk = ca_address_sk
    AND ca_gmt_offset = -5
  GROUP BY i_manufact_id),
cs AS (
  SELECT i_manufact_id, sum(cs_ext_sales_price) total_sales
  FROM catalog_sales, date_dim, customer_address, item
  WHERE i_manufact_id IN (SELECT i_manufact_id FROM item
                          WHERE i_category = 'Electronics')
    AND cs_item_sk = i_item_sk
    AND cs_sold_date_sk = d_date_sk
    AND d_year = 1998 AND d_moy = 5
    AND cs_bill_addr_sk = ca_address_sk
    AND ca_gmt_offset = -5
  GROUP BY i_manufact_id),
ws AS (
  SELECT i_manufact_id, sum(ws_ext_sales_price) total_sales
  FROM web_sales, date_dim, customer_address, item
  WHERE i_manufact_id IN (SELECT i_manufact_id FROM item
                          WHERE i_category = 'Electronics')
    AND ws_item_sk = i_item_sk
    AND ws_sold_date_sk = d_date_sk
    AND d_year = 1998 AND d_moy = 5
    AND ws_bill_addr_sk = ca_address_sk
    AND ca_gmt_offset = -5
  GROUP BY i_manufact_id)
SELECT i_manufact_id, sum(total_sales) total_sales
FROM (SELECT * FROM ss
      UNION ALL SELECT * FROM cs
      UNION ALL SELECT * FROM ws) tmp1
GROUP BY i_manufact_id
ORDER BY total_sales, i_manufact_id
LIMIT 100
""",
    38: """
SELECT count(*) cnt
FROM (SELECT DISTINCT c_last_name, c_first_name, d_date
      FROM store_sales, date_dim, customer
      WHERE store_sales.ss_sold_date_sk = date_dim.d_date_sk
        AND store_sales.ss_customer_sk = customer.c_customer_sk
        AND d_month_seq BETWEEN 1200 AND 1211
      INTERSECT
      SELECT DISTINCT c_last_name, c_first_name, d_date
      FROM catalog_sales, date_dim, customer
      WHERE catalog_sales.cs_sold_date_sk = date_dim.d_date_sk
        AND catalog_sales.cs_bill_customer_sk
            = customer.c_customer_sk
        AND d_month_seq BETWEEN 1200 AND 1211
      INTERSECT
      SELECT DISTINCT c_last_name, c_first_name, d_date
      FROM web_sales, date_dim, customer
      WHERE web_sales.ws_sold_date_sk = date_dim.d_date_sk
        AND web_sales.ws_bill_customer_sk = customer.c_customer_sk
        AND d_month_seq BETWEEN 1200 AND 1211) hot_cust
LIMIT 100
""",
    87: """
SELECT count(*) cnt
FROM ((SELECT DISTINCT c_last_name, c_first_name, d_date
       FROM store_sales, date_dim, customer
       WHERE store_sales.ss_sold_date_sk = date_dim.d_date_sk
         AND store_sales.ss_customer_sk = customer.c_customer_sk
         AND d_month_seq BETWEEN 1200 AND 1211)
      EXCEPT
      (SELECT DISTINCT c_last_name, c_first_name, d_date
       FROM catalog_sales, date_dim, customer
       WHERE catalog_sales.cs_sold_date_sk = date_dim.d_date_sk
         AND catalog_sales.cs_bill_customer_sk
             = customer.c_customer_sk
         AND d_month_seq BETWEEN 1200 AND 1211)
      EXCEPT
      (SELECT DISTINCT c_last_name, c_first_name, d_date
       FROM web_sales, date_dim, customer
       WHERE web_sales.ws_sold_date_sk = date_dim.d_date_sk
         AND web_sales.ws_bill_customer_sk = customer.c_customer_sk
         AND d_month_seq BETWEEN 1200 AND 1211)) cool_cust
""",
    88: """
SELECT *
FROM (SELECT count(*) h8_30_to_9
      FROM store_sales, household_demographics, time_dim, store
      WHERE ss_sold_time_sk = time_dim.t_time_sk
        AND ss_hdemo_sk = household_demographics.hd_demo_sk
        AND ss_store_sk = s_store_sk
        AND time_dim.t_hour = 8 AND time_dim.t_minute >= 30
        AND ((household_demographics.hd_dep_count = 4
              AND household_demographics.hd_vehicle_count <= 3)
             OR (household_demographics.hd_dep_count = 2
                 AND household_demographics.hd_vehicle_count <= 1)
             OR (household_demographics.hd_dep_count = 0
                 AND household_demographics.hd_vehicle_count <= 2))
        AND store.s_store_name = 'ese') s1,
     (SELECT count(*) h9_to_9_30
      FROM store_sales, household_demographics, time_dim, store
      WHERE ss_sold_time_sk = time_dim.t_time_sk
        AND ss_hdemo_sk = household_demographics.hd_demo_sk
        AND ss_store_sk = s_store_sk
        AND time_dim.t_hour = 9 AND time_dim.t_minute < 30
        AND ((household_demographics.hd_dep_count = 4
              AND household_demographics.hd_vehicle_count <= 3)
             OR (household_demographics.hd_dep_count = 2
                 AND household_demographics.hd_vehicle_count <= 1)
             OR (household_demographics.hd_dep_count = 0
                 AND household_demographics.hd_vehicle_count <= 2))
        AND store.s_store_name = 'ese') s2,
     (SELECT count(*) h9_30_to_10
      FROM store_sales, household_demographics, time_dim, store
      WHERE ss_sold_time_sk = time_dim.t_time_sk
        AND ss_hdemo_sk = household_demographics.hd_demo_sk
        AND ss_store_sk = s_store_sk
        AND time_dim.t_hour = 9 AND time_dim.t_minute >= 30
        AND ((household_demographics.hd_dep_count = 4
              AND household_demographics.hd_vehicle_count <= 3)
             OR (household_demographics.hd_dep_count = 2
                 AND household_demographics.hd_vehicle_count <= 1)
             OR (household_demographics.hd_dep_count = 0
                 AND household_demographics.hd_vehicle_count <= 2))
        AND store.s_store_name = 'ese') s3,
     (SELECT count(*) h10_to_10_30
      FROM store_sales, household_demographics, time_dim, store
      WHERE ss_sold_time_sk = time_dim.t_time_sk
        AND ss_hdemo_sk = household_demographics.hd_demo_sk
        AND ss_store_sk = s_store_sk
        AND time_dim.t_hour = 10 AND time_dim.t_minute < 30
        AND ((household_demographics.hd_dep_count = 4
              AND household_demographics.hd_vehicle_count <= 3)
             OR (household_demographics.hd_dep_count = 2
                 AND household_demographics.hd_vehicle_count <= 1)
             OR (household_demographics.hd_dep_count = 0
                 AND household_demographics.hd_vehicle_count <= 2))
        AND store.s_store_name = 'ese') s4
""",
    90: """
SELECT cast(amc AS double) / cast(pmc AS double) am_pm_ratio
FROM (SELECT count(*) amc
      FROM web_sales, household_demographics, time_dim, web_page
      WHERE ws_sold_time_sk = time_dim.t_time_sk
        AND ws_ship_hdemo_sk = household_demographics.hd_demo_sk
        AND ws_web_page_sk = web_page.wp_web_page_sk
        AND time_dim.t_hour BETWEEN 8 AND 9
        AND household_demographics.hd_dep_count = 6
        AND web_page.wp_char_count BETWEEN 5000 AND 5200) at1,
     (SELECT count(*) pmc
      FROM web_sales, household_demographics, time_dim, web_page
      WHERE ws_sold_time_sk = time_dim.t_time_sk
        AND ws_ship_hdemo_sk = household_demographics.hd_demo_sk
        AND ws_web_page_sk = web_page.wp_web_page_sk
        AND time_dim.t_hour BETWEEN 19 AND 20
        AND household_demographics.hd_dep_count = 6
        AND web_page.wp_char_count BETWEEN 5000 AND 5200) pt
ORDER BY am_pm_ratio
LIMIT 100
""",
    94: """
SELECT count(DISTINCT ws_order_number) order_count,
       sum(ws_ext_ship_cost) total_shipping_cost,
       sum(ws_net_profit) total_net_profit
FROM web_sales ws1, date_dim, customer_address, web_site
WHERE d_date BETWEEN DATE '1999-02-01' AND DATE '1999-04-02'
  AND ws1.ws_ship_date_sk = d_date_sk
  AND ws1.ws_ship_addr_sk = ca_address_sk
  AND ca_state = 'IL'
  AND ws1.ws_web_site_sk = web_site_sk
  AND web_company_name = 'pri'
  AND EXISTS (SELECT *
              FROM web_sales ws2
              WHERE ws1.ws_order_number = ws2.ws_order_number
                AND ws1.ws_warehouse_sk <> ws2.ws_warehouse_sk)
  AND NOT EXISTS (SELECT *
                  FROM web_returns wr1
                  WHERE ws1.ws_order_number = wr1.wr_order_number)
ORDER BY count(DISTINCT ws_order_number)
LIMIT 100
""",
    8: """
SELECT s_store_name, sum(ss_net_profit) profit
FROM store_sales, date_dim, store,
     (SELECT ca_zip
      FROM ((SELECT substr(ca_zip, 1, 5) ca_zip
             FROM customer_address
             WHERE substr(ca_zip, 1, 5) IN
                   ('24250', '38800', '50440', '59170', '75369',
                    '77697', '86136', '87494', '92635', '97000'))
            INTERSECT
            (SELECT ca_zip
             FROM (SELECT substr(ca_zip, 1, 5) ca_zip, count(*) cnt
                   FROM customer_address, customer
                   WHERE ca_address_sk = c_current_addr_sk
                     AND c_preferred_cust_flag = 'Y'
                   GROUP BY substr(ca_zip, 1, 5)
                   HAVING count(*) > 1) a1)) a2) v1
WHERE ss_store_sk = s_store_sk
  AND ss_sold_date_sk = d_date_sk
  AND d_qoy = 2 AND d_year = 1998
  AND substr(s_zip, 1, 2) = substr(v1.ca_zip, 1, 2)
GROUP BY s_store_name
ORDER BY s_store_name
LIMIT 100
""",
    18: """
SELECT i_item_id, ca_country, ca_state, ca_county,
       avg(cs_quantity) agg1, avg(cs_list_price) agg2,
       avg(cs_coupon_amt) agg3, avg(cs_sales_price) agg4,
       avg(cs_net_profit) agg5, avg(c_birth_year) agg6,
       avg(cd1.cd_dep_count) agg7
FROM catalog_sales, customer_demographics cd1,
     customer_demographics cd2, customer, customer_address,
     date_dim, item
WHERE cs_sold_date_sk = d_date_sk
  AND cs_item_sk = i_item_sk
  AND cs_bill_cdemo_sk = cd1.cd_demo_sk
  AND cs_bill_customer_sk = c_customer_sk
  AND cd1.cd_gender = 'F'
  AND cd1.cd_education_status = 'Unknown'
  AND c_current_cdemo_sk = cd2.cd_demo_sk
  AND c_current_addr_sk = ca_address_sk
  AND c_birth_month IN (1, 6, 8, 9, 12, 2)
  AND d_year = 1998
  AND ca_state IN ('MS', 'IN', 'ND', 'OK', 'NM', 'VA', 'MS')
GROUP BY ROLLUP (i_item_id, ca_country, ca_state, ca_county)
ORDER BY ca_country, ca_state, ca_county, i_item_id
LIMIT 100
""",
    31: """
WITH ss AS (
  SELECT ca_county, d_qoy, d_year,
         sum(ss_ext_sales_price) store_sales
  FROM store_sales, date_dim, customer_address
  WHERE ss_sold_date_sk = d_date_sk
    AND ss_addr_sk = ca_address_sk
  GROUP BY ca_county, d_qoy, d_year),
ws AS (
  SELECT ca_county, d_qoy, d_year,
         sum(ws_ext_sales_price) web_sales
  FROM web_sales, date_dim, customer_address
  WHERE ws_sold_date_sk = d_date_sk
    AND ws_bill_addr_sk = ca_address_sk
  GROUP BY ca_county, d_qoy, d_year)
SELECT ss1.ca_county, ss1.d_year,
       ws2.web_sales / ws1.web_sales web_q1_q2_increase,
       ss2.store_sales / ss1.store_sales store_q1_q2_increase,
       ws3.web_sales / ws2.web_sales web_q2_q3_increase,
       ss3.store_sales / ss2.store_sales store_q2_q3_increase
FROM ss ss1, ss ss2, ss ss3, ws ws1, ws ws2, ws ws3
WHERE ss1.d_qoy = 1 AND ss1.d_year = 2000
  AND ss1.ca_county = ss2.ca_county
  AND ss2.d_qoy = 2 AND ss2.d_year = 2000
  AND ss2.ca_county = ss3.ca_county
  AND ss3.d_qoy = 3 AND ss3.d_year = 2000
  AND ss1.ca_county = ws1.ca_county
  AND ws1.d_qoy = 1 AND ws1.d_year = 2000
  AND ws1.ca_county = ws2.ca_county
  AND ws2.d_qoy = 2 AND ws2.d_year = 2000
  AND ws1.ca_county = ws3.ca_county
  AND ws3.d_qoy = 3 AND ws3.d_year = 2000
  AND CASE WHEN ws1.web_sales > 0
           THEN ws2.web_sales / ws1.web_sales ELSE NULL END
      > CASE WHEN ss1.store_sales > 0
             THEN ss2.store_sales / ss1.store_sales ELSE NULL END
  AND CASE WHEN ws2.web_sales > 0
           THEN ws3.web_sales / ws2.web_sales ELSE NULL END
      > CASE WHEN ss2.store_sales > 0
             THEN ss3.store_sales / ss2.store_sales ELSE NULL END
ORDER BY ss1.ca_county
""",
    34: """
SELECT c_last_name, c_first_name, c_salutation,
       c_preferred_cust_flag, ss_ticket_number, cnt
FROM (SELECT ss_ticket_number, ss_customer_sk, count(*) cnt
      FROM store_sales, date_dim, store, household_demographics
      WHERE store_sales.ss_sold_date_sk = date_dim.d_date_sk
        AND store_sales.ss_store_sk = store.s_store_sk
        AND store_sales.ss_hdemo_sk
            = household_demographics.hd_demo_sk
        AND (date_dim.d_dom BETWEEN 1 AND 3
             OR date_dim.d_dom BETWEEN 25 AND 28)
        AND (household_demographics.hd_buy_potential = '>10000'
             OR household_demographics.hd_buy_potential = 'Unknown')
        AND household_demographics.hd_vehicle_count > 0
        AND (CASE WHEN household_demographics.hd_vehicle_count > 0
                  THEN household_demographics.hd_dep_count * 1.000
                       / household_demographics.hd_vehicle_count
                  ELSE NULL END) > 1.2
        AND date_dim.d_year IN (1999, 2000, 2001)
        AND store.s_county = 'Williamson County'
      GROUP BY ss_ticket_number, ss_customer_sk) dn, customer
WHERE ss_customer_sk = c_customer_sk
  AND cnt BETWEEN 15 AND 20
ORDER BY c_last_name, c_first_name, c_salutation,
         c_preferred_cust_flag DESC, ss_ticket_number
""",
    36: """
SELECT gross_margin, i_category, i_class, lochierarchy,
       rank() OVER (PARTITION BY lochierarchy,
                        CASE WHEN cls_grouping = 0
                             THEN i_category END
                    ORDER BY gross_margin) rank_within_parent
FROM (SELECT sum(ss_net_profit) / sum(ss_ext_sales_price)
                 gross_margin,
             i_category, i_class,
             grouping(i_category) + grouping(i_class) lochierarchy,
             grouping(i_class) cls_grouping
      FROM store_sales, date_dim d1, item, store
      WHERE d1.d_year = 2001
        AND d1.d_date_sk = ss_sold_date_sk
        AND i_item_sk = ss_item_sk
        AND s_store_sk = ss_store_sk
        AND s_state IN ('TN', 'OH', 'TX', 'GA', 'IL')
      GROUP BY ROLLUP (i_category, i_class)) t
ORDER BY lochierarchy DESC,
         CASE WHEN lochierarchy = 0 THEN i_category END,
         rank_within_parent
LIMIT 100
""",
    45: """
SELECT ca_zip, ca_city, sum(ws_sales_price) total
FROM web_sales, customer, customer_address, date_dim, item
WHERE ws_bill_customer_sk = c_customer_sk
  AND c_current_addr_sk = ca_address_sk
  AND ws_item_sk = i_item_sk
  AND (substr(ca_zip, 1, 5) IN
           ('24250', '38800', '50440', '59170', '75369',
            '77697', '86136', '87494', '92635', '97000')
       OR i_item_id IN (SELECT i_item_id
                        FROM item
                        WHERE i_item_sk IN (2, 3, 5, 7, 11, 13,
                                            17, 19, 23, 29)))
  AND ws_sold_date_sk = d_date_sk
  AND d_qoy = 2 AND d_year = 2001
GROUP BY ca_zip, ca_city
ORDER BY ca_zip, ca_city
LIMIT 100
""",
    46: """
SELECT c_last_name, c_first_name, ca_city, bought_city,
       ss_ticket_number, amt, profit
FROM (SELECT ss_ticket_number, ss_customer_sk,
             ca_city bought_city, sum(ss_coupon_amt) amt,
             sum(ss_net_profit) profit
      FROM store_sales, date_dim, store,
           household_demographics, customer_address
      WHERE store_sales.ss_sold_date_sk = date_dim.d_date_sk
        AND store_sales.ss_store_sk = store.s_store_sk
        AND store_sales.ss_hdemo_sk
            = household_demographics.hd_demo_sk
        AND store_sales.ss_addr_sk
            = customer_address.ca_address_sk
        AND (household_demographics.hd_dep_count = 4
             OR household_demographics.hd_vehicle_count = 3)
        AND date_dim.d_dow IN (6, 0)
        AND date_dim.d_year IN (1999, 2000, 2001)
        AND store.s_city IN ('Fairview', 'Midway')
      GROUP BY ss_ticket_number, ss_customer_sk, ss_addr_sk,
               ca_city) dn,
     customer, customer_address current_addr
WHERE ss_customer_sk = c_customer_sk
  AND customer.c_current_addr_sk = current_addr.ca_address_sk
  AND current_addr.ca_city <> bought_city
ORDER BY c_last_name, c_first_name, ca_city, bought_city,
         ss_ticket_number
LIMIT 100
""",
    56: """
WITH ss AS (
  SELECT i_item_id, sum(ss_ext_sales_price) total_sales
  FROM store_sales, date_dim, customer_address, item
  WHERE i_item_id IN (SELECT i_item_id FROM item
                      WHERE i_color IN ('slate', 'blanched', 'beige'))
    AND ss_item_sk = i_item_sk
    AND ss_sold_date_sk = d_date_sk
    AND d_year = 2001 AND d_moy = 2
    AND ss_addr_sk = ca_address_sk
    AND ca_gmt_offset = -5
  GROUP BY i_item_id),
cs AS (
  SELECT i_item_id, sum(cs_ext_sales_price) total_sales
  FROM catalog_sales, date_dim, customer_address, item
  WHERE i_item_id IN (SELECT i_item_id FROM item
                      WHERE i_color IN ('slate', 'blanched', 'beige'))
    AND cs_item_sk = i_item_sk
    AND cs_sold_date_sk = d_date_sk
    AND d_year = 2001 AND d_moy = 2
    AND cs_bill_addr_sk = ca_address_sk
    AND ca_gmt_offset = -5
  GROUP BY i_item_id),
ws AS (
  SELECT i_item_id, sum(ws_ext_sales_price) total_sales
  FROM web_sales, date_dim, customer_address, item
  WHERE i_item_id IN (SELECT i_item_id FROM item
                      WHERE i_color IN ('slate', 'blanched', 'beige'))
    AND ws_item_sk = i_item_sk
    AND ws_sold_date_sk = d_date_sk
    AND d_year = 2001 AND d_moy = 2
    AND ws_bill_addr_sk = ca_address_sk
    AND ca_gmt_offset = -5
  GROUP BY i_item_id)
SELECT i_item_id, sum(total_sales) total_sales
FROM (SELECT * FROM ss
      UNION ALL SELECT * FROM cs
      UNION ALL SELECT * FROM ws) tmp1
GROUP BY i_item_id
ORDER BY total_sales, i_item_id
LIMIT 100
""",
    60: """
WITH ss AS (
  SELECT i_item_id, sum(ss_ext_sales_price) total_sales
  FROM store_sales, date_dim, customer_address, item
  WHERE i_item_id IN (SELECT i_item_id FROM item
                      WHERE i_category = 'Music')
    AND ss_item_sk = i_item_sk
    AND ss_sold_date_sk = d_date_sk
    AND d_year = 1998 AND d_moy = 9
    AND ss_addr_sk = ca_address_sk
    AND ca_gmt_offset = -5
  GROUP BY i_item_id),
cs AS (
  SELECT i_item_id, sum(cs_ext_sales_price) total_sales
  FROM catalog_sales, date_dim, customer_address, item
  WHERE i_item_id IN (SELECT i_item_id FROM item
                      WHERE i_category = 'Music')
    AND cs_item_sk = i_item_sk
    AND cs_sold_date_sk = d_date_sk
    AND d_year = 1998 AND d_moy = 9
    AND cs_bill_addr_sk = ca_address_sk
    AND ca_gmt_offset = -5
  GROUP BY i_item_id),
ws AS (
  SELECT i_item_id, sum(ws_ext_sales_price) total_sales
  FROM web_sales, date_dim, customer_address, item
  WHERE i_item_id IN (SELECT i_item_id FROM item
                      WHERE i_category = 'Music')
    AND ws_item_sk = i_item_sk
    AND ws_sold_date_sk = d_date_sk
    AND d_year = 1998 AND d_moy = 9
    AND ws_bill_addr_sk = ca_address_sk
    AND ca_gmt_offset = -5
  GROUP BY i_item_id)
SELECT i_item_id, sum(total_sales) total_sales
FROM (SELECT * FROM ss
      UNION ALL SELECT * FROM cs
      UNION ALL SELECT * FROM ws) tmp1
GROUP BY i_item_id
ORDER BY i_item_id, total_sales
LIMIT 100
""",
    68: """
SELECT c_last_name, c_first_name, ca_city, bought_city,
       ss_ticket_number, extended_price, extended_tax, list_price
FROM (SELECT ss_ticket_number, ss_customer_sk,
             ca_city bought_city,
             sum(ss_ext_sales_price) extended_price,
             sum(ss_ext_list_price) list_price,
             sum(ss_ext_tax) extended_tax
      FROM store_sales, date_dim, store,
           household_demographics, customer_address
      WHERE store_sales.ss_sold_date_sk = date_dim.d_date_sk
        AND store_sales.ss_store_sk = store.s_store_sk
        AND store_sales.ss_hdemo_sk
            = household_demographics.hd_demo_sk
        AND store_sales.ss_addr_sk
            = customer_address.ca_address_sk
        AND date_dim.d_dom BETWEEN 1 AND 2
        AND (household_demographics.hd_dep_count = 5
             OR household_demographics.hd_vehicle_count = 3)
        AND date_dim.d_year IN (1999, 2000, 2001)
        AND store.s_city IN ('Midway', 'Fairview')
      GROUP BY ss_ticket_number, ss_customer_sk, ss_addr_sk,
               ca_city) dn,
     customer, customer_address current_addr
WHERE ss_customer_sk = c_customer_sk
  AND customer.c_current_addr_sk = current_addr.ca_address_sk
  AND current_addr.ca_city <> bought_city
ORDER BY c_last_name, ss_ticket_number
LIMIT 100
""",
    76: """
SELECT channel, col_name, d_year, d_qoy, i_category,
       count(*) sales_cnt, sum(ext_sales_price) sales_amt
FROM (SELECT 'store' AS channel, 'ss_store_sk' col_name,
             d_year, d_qoy, i_category,
             ss_ext_sales_price ext_sales_price
      FROM store_sales, item, date_dim
      WHERE ss_store_sk IS NULL
        AND ss_sold_date_sk = d_date_sk
        AND ss_item_sk = i_item_sk
      UNION ALL
      SELECT 'web' AS channel, 'ws_ship_customer_sk' col_name,
             d_year, d_qoy, i_category,
             ws_ext_sales_price ext_sales_price
      FROM web_sales, item, date_dim
      WHERE ws_ship_customer_sk IS NULL
        AND ws_sold_date_sk = d_date_sk
        AND ws_item_sk = i_item_sk
      UNION ALL
      SELECT 'catalog' AS channel, 'cs_ship_addr_sk' col_name,
             d_year, d_qoy, i_category,
             cs_ext_sales_price ext_sales_price
      FROM catalog_sales, item, date_dim
      WHERE cs_ship_addr_sk IS NULL
        AND cs_sold_date_sk = d_date_sk
        AND cs_item_sk = i_item_sk) foo
GROUP BY channel, col_name, d_year, d_qoy, i_category
ORDER BY channel, col_name, d_year, d_qoy, i_category
LIMIT 100
""",
    84: """
SELECT c_customer_id customer_id,
       c_last_name || ', ' || c_first_name customername
FROM customer, customer_address, customer_demographics,
     household_demographics, income_band, store_returns
WHERE ca_city = 'Fairview'
  AND c_current_addr_sk = ca_address_sk
  AND ib_lower_bound >= 38128
  AND ib_upper_bound <= 38128 + 50000
  AND ib_income_band_sk = hd_income_band_sk
  AND cd_demo_sk = sr_cdemo_sk
  AND hd_demo_sk = c_current_hdemo_sk
  AND cd_demo_sk = c_current_cdemo_sk
ORDER BY c_customer_id
LIMIT 100
""",
    91: """
SELECT cc_call_center_id call_center, cc_name call_center_name,
       cc_manager manager, sum(cr_net_loss) returns_loss
FROM call_center, catalog_returns, date_dim, customer,
     customer_address, customer_demographics,
     household_demographics
WHERE cr_call_center_sk = cc_call_center_sk
  AND cr_returned_date_sk = d_date_sk
  AND cr_returning_customer_sk = c_customer_sk
  AND cd_demo_sk = c_current_cdemo_sk
  AND hd_demo_sk = c_current_hdemo_sk
  AND ca_address_sk = c_current_addr_sk
  AND d_year = 1998 AND d_moy = 11
  AND ((cd_marital_status = 'M'
        AND cd_education_status = 'Unknown')
       OR (cd_marital_status = 'W'
           AND cd_education_status = 'Advanced Degree'))
  AND hd_buy_potential LIKE 'Unknown%'
  AND ca_gmt_offset = -7
GROUP BY cc_call_center_id, cc_name, cc_manager,
         cd_marital_status, cd_education_status
ORDER BY sum(cr_net_loss) DESC
""",
    2: """
WITH wscs AS (
  SELECT sold_date_sk, sales_price
  FROM (SELECT ws_sold_date_sk sold_date_sk,
               ws_ext_sales_price sales_price
        FROM web_sales
        UNION ALL
        SELECT cs_sold_date_sk, cs_ext_sales_price
        FROM catalog_sales) t),
wswscs AS (
  SELECT d_week_seq,
         sum(CASE WHEN d_day_name = 'Sunday'
                  THEN sales_price ELSE NULL END) sun_sales,
         sum(CASE WHEN d_day_name = 'Monday'
                  THEN sales_price ELSE NULL END) mon_sales,
         sum(CASE WHEN d_day_name = 'Tuesday'
                  THEN sales_price ELSE NULL END) tue_sales,
         sum(CASE WHEN d_day_name = 'Wednesday'
                  THEN sales_price ELSE NULL END) wed_sales,
         sum(CASE WHEN d_day_name = 'Thursday'
                  THEN sales_price ELSE NULL END) thu_sales,
         sum(CASE WHEN d_day_name = 'Friday'
                  THEN sales_price ELSE NULL END) fri_sales,
         sum(CASE WHEN d_day_name = 'Saturday'
                  THEN sales_price ELSE NULL END) sat_sales
  FROM wscs, date_dim
  WHERE d_date_sk = sold_date_sk
  GROUP BY d_week_seq)
SELECT d_week_seq1, round(sun_sales1 / sun_sales2, 2) r1,
       round(mon_sales1 / mon_sales2, 2) r2,
       round(tue_sales1 / tue_sales2, 2) r3,
       round(wed_sales1 / wed_sales2, 2) r4,
       round(thu_sales1 / thu_sales2, 2) r5,
       round(fri_sales1 / fri_sales2, 2) r6,
       round(sat_sales1 / sat_sales2, 2) r7
FROM (SELECT wswscs.d_week_seq d_week_seq1,
             sun_sales sun_sales1, mon_sales mon_sales1,
             tue_sales tue_sales1, wed_sales wed_sales1,
             thu_sales thu_sales1, fri_sales fri_sales1,
             sat_sales sat_sales1
      FROM wswscs, date_dim
      WHERE date_dim.d_week_seq = wswscs.d_week_seq
        AND d_year = 2001) y,
     (SELECT wswscs.d_week_seq d_week_seq2,
             sun_sales sun_sales2, mon_sales mon_sales2,
             tue_sales tue_sales2, wed_sales wed_sales2,
             thu_sales thu_sales2, fri_sales fri_sales2,
             sat_sales sat_sales2
      FROM wswscs, date_dim
      WHERE date_dim.d_week_seq = wswscs.d_week_seq
        AND d_year = 2002) z
WHERE d_week_seq1 = d_week_seq2 - 53
ORDER BY d_week_seq1
""",
    9: """
SELECT CASE WHEN (SELECT count(*) FROM store_sales
                  WHERE ss_quantity BETWEEN 1 AND 20) > 20000
            THEN (SELECT avg(ss_ext_discount_amt) FROM store_sales
                  WHERE ss_quantity BETWEEN 1 AND 20)
            ELSE (SELECT avg(ss_net_profit) FROM store_sales
                  WHERE ss_quantity BETWEEN 1 AND 20) END bucket1,
       CASE WHEN (SELECT count(*) FROM store_sales
                  WHERE ss_quantity BETWEEN 21 AND 40) > 15000
            THEN (SELECT avg(ss_ext_discount_amt) FROM store_sales
                  WHERE ss_quantity BETWEEN 21 AND 40)
            ELSE (SELECT avg(ss_net_profit) FROM store_sales
                  WHERE ss_quantity BETWEEN 21 AND 40) END bucket2,
       CASE WHEN (SELECT count(*) FROM store_sales
                  WHERE ss_quantity BETWEEN 41 AND 60) > 10000
            THEN (SELECT avg(ss_ext_discount_amt) FROM store_sales
                  WHERE ss_quantity BETWEEN 41 AND 60)
            ELSE (SELECT avg(ss_net_profit) FROM store_sales
                  WHERE ss_quantity BETWEEN 41 AND 60) END bucket3,
       CASE WHEN (SELECT count(*) FROM store_sales
                  WHERE ss_quantity BETWEEN 61 AND 80) > 5000
            THEN (SELECT avg(ss_ext_discount_amt) FROM store_sales
                  WHERE ss_quantity BETWEEN 61 AND 80)
            ELSE (SELECT avg(ss_net_profit) FROM store_sales
                  WHERE ss_quantity BETWEEN 61 AND 80) END bucket4,
       CASE WHEN (SELECT count(*) FROM store_sales
                  WHERE ss_quantity BETWEEN 81 AND 100) > 1000
            THEN (SELECT avg(ss_ext_discount_amt) FROM store_sales
                  WHERE ss_quantity BETWEEN 81 AND 100)
            ELSE (SELECT avg(ss_net_profit) FROM store_sales
                  WHERE ss_quantity BETWEEN 81 AND 100) END bucket5
FROM reason
WHERE r_reason_sk = 1
""",
    11: """
WITH year_total AS (
  SELECT c_customer_id customer_id, c_first_name customer_first_name,
         c_last_name customer_last_name,
         c_preferred_cust_flag customer_preferred_cust_flag,
         c_birth_country customer_birth_country,
         d_year dyear,
         sum(ss_ext_list_price - ss_ext_discount_amt) year_total,
         's' sale_type
  FROM customer, store_sales, date_dim
  WHERE c_customer_sk = ss_customer_sk
    AND ss_sold_date_sk = d_date_sk
  GROUP BY c_customer_id, c_first_name, c_last_name,
           c_preferred_cust_flag, c_birth_country, d_year
  UNION ALL
  SELECT c_customer_id, c_first_name, c_last_name,
         c_preferred_cust_flag, c_birth_country, d_year,
         sum(ws_ext_list_price - ws_ext_discount_amt) year_total,
         'w' sale_type
  FROM customer, web_sales, date_dim
  WHERE c_customer_sk = ws_bill_customer_sk
    AND ws_sold_date_sk = d_date_sk
  GROUP BY c_customer_id, c_first_name, c_last_name,
           c_preferred_cust_flag, c_birth_country, d_year)
SELECT t_s_secyear.customer_id, t_s_secyear.customer_first_name,
       t_s_secyear.customer_last_name,
       t_s_secyear.customer_preferred_cust_flag
FROM year_total t_s_firstyear, year_total t_s_secyear,
     year_total t_w_firstyear, year_total t_w_secyear
WHERE t_s_secyear.customer_id = t_s_firstyear.customer_id
  AND t_s_firstyear.customer_id = t_w_secyear.customer_id
  AND t_s_firstyear.customer_id = t_w_firstyear.customer_id
  AND t_s_firstyear.sale_type = 's'
  AND t_w_firstyear.sale_type = 'w'
  AND t_s_secyear.sale_type = 's'
  AND t_w_secyear.sale_type = 'w'
  AND t_s_firstyear.dyear = 2001
  AND t_s_secyear.dyear = 2001 + 1
  AND t_w_firstyear.dyear = 2001
  AND t_w_secyear.dyear = 2001 + 1
  AND t_s_firstyear.year_total > 0
  AND t_w_firstyear.year_total > 0
  AND CASE WHEN t_w_firstyear.year_total > 0
           THEN t_w_secyear.year_total / t_w_firstyear.year_total
           ELSE 0.0 END
      > CASE WHEN t_s_firstyear.year_total > 0
             THEN t_s_secyear.year_total / t_s_firstyear.year_total
             ELSE 0.0 END
ORDER BY t_s_secyear.customer_id,
         t_s_secyear.customer_first_name,
         t_s_secyear.customer_last_name,
         t_s_secyear.customer_preferred_cust_flag
LIMIT 100
""",
    47: """
WITH v1 AS (
  SELECT i_category, i_brand, s_store_name, s_company_name,
         d_year, d_moy, sum_sales,
         avg(sum_sales) OVER (PARTITION BY i_category, i_brand,
                                  s_store_name, s_company_name,
                                  d_year) avg_monthly_sales,
         rank() OVER (PARTITION BY i_category, i_brand,
                          s_store_name, s_company_name
                      ORDER BY d_year, d_moy) rn
  FROM (SELECT i_category, i_brand, s_store_name, s_company_name,
               d_year, d_moy, sum(ss_sales_price) sum_sales
        FROM item, store_sales, date_dim, store
        WHERE ss_item_sk = i_item_sk
          AND ss_sold_date_sk = d_date_sk
          AND ss_store_sk = s_store_sk
          AND (d_year = 1999
               OR (d_year = 1998 AND d_moy = 12)
               OR (d_year = 2000 AND d_moy = 1))
        GROUP BY i_category, i_brand, s_store_name,
                 s_company_name, d_year, d_moy) inner_v1),
v2 AS (
  SELECT v1.i_category, v1.i_brand, v1.s_store_name,
         v1.s_company_name, v1.d_year, v1.d_moy,
         v1.avg_monthly_sales, v1.sum_sales,
         v1_lag.sum_sales psum, v1_lead.sum_sales nsum
  FROM v1, v1 v1_lag, v1 v1_lead
  WHERE v1.i_category = v1_lag.i_category
    AND v1.i_category = v1_lead.i_category
    AND v1.i_brand = v1_lag.i_brand
    AND v1.i_brand = v1_lead.i_brand
    AND v1.s_store_name = v1_lag.s_store_name
    AND v1.s_store_name = v1_lead.s_store_name
    AND v1.s_company_name = v1_lag.s_company_name
    AND v1.s_company_name = v1_lead.s_company_name
    AND v1.rn = v1_lag.rn + 1
    AND v1.rn = v1_lead.rn - 1)
SELECT *
FROM v2
WHERE d_year = 1999
  AND avg_monthly_sales > 0
  AND CASE WHEN avg_monthly_sales > 0
           THEN abs(sum_sales - avg_monthly_sales)
                / avg_monthly_sales
           ELSE NULL END > 0.1
ORDER BY sum_sales - avg_monthly_sales, nsum
LIMIT 100
""",
    50: """
SELECT s_store_name, s_company_id, s_street_number, s_street_name,
       s_street_type, s_suite_number, s_city, s_county, s_state,
       s_zip,
       sum(CASE WHEN sr_returned_date_sk - ss_sold_date_sk <= 30
                THEN 1 ELSE 0 END) AS days30,
       sum(CASE WHEN sr_returned_date_sk - ss_sold_date_sk > 30
                 AND sr_returned_date_sk - ss_sold_date_sk <= 60
                THEN 1 ELSE 0 END) AS days31_60,
       sum(CASE WHEN sr_returned_date_sk - ss_sold_date_sk > 60
                 AND sr_returned_date_sk - ss_sold_date_sk <= 90
                THEN 1 ELSE 0 END) AS days61_90,
       sum(CASE WHEN sr_returned_date_sk - ss_sold_date_sk > 90
                 AND sr_returned_date_sk - ss_sold_date_sk <= 120
                THEN 1 ELSE 0 END) AS days91_120,
       sum(CASE WHEN sr_returned_date_sk - ss_sold_date_sk > 120
                THEN 1 ELSE 0 END) AS days_over_120
FROM store_sales, store_returns, store, date_dim d1, date_dim d2
WHERE d2.d_year = 2001 AND d2.d_moy = 8
  AND ss_ticket_number = sr_ticket_number
  AND ss_item_sk = sr_item_sk
  AND ss_sold_date_sk = d1.d_date_sk
  AND sr_returned_date_sk = d2.d_date_sk
  AND ss_customer_sk = sr_customer_sk
  AND ss_store_sk = s_store_sk
GROUP BY s_store_name, s_company_id, s_street_number,
         s_street_name, s_street_type, s_suite_number, s_city,
         s_county, s_state, s_zip
ORDER BY s_store_name, s_company_id, s_street_number,
         s_street_name, s_street_type, s_suite_number, s_city,
         s_county, s_state, s_zip
LIMIT 100
""",
    51: """
WITH web_v1 AS (
  SELECT item_sk, d_date,
         sum(daily) OVER (PARTITION BY item_sk ORDER BY d_date
                          ROWS BETWEEN UNBOUNDED PRECEDING
                               AND CURRENT ROW) cume_sales
  FROM (SELECT ws_item_sk item_sk, d_date,
               sum(ws_sales_price) daily
        FROM web_sales, date_dim
        WHERE ws_sold_date_sk = d_date_sk
          AND d_month_seq BETWEEN 1200 AND 1211
          AND ws_item_sk IS NOT NULL
        GROUP BY ws_item_sk, d_date) t),
store_v1 AS (
  SELECT item_sk, d_date,
         sum(daily) OVER (PARTITION BY item_sk ORDER BY d_date
                          ROWS BETWEEN UNBOUNDED PRECEDING
                               AND CURRENT ROW) cume_sales
  FROM (SELECT ss_item_sk item_sk, d_date,
               sum(ss_sales_price) daily
        FROM store_sales, date_dim
        WHERE ss_sold_date_sk = d_date_sk
          AND d_month_seq BETWEEN 1200 AND 1211
          AND ss_item_sk IS NOT NULL
        GROUP BY ss_item_sk, d_date) t)
SELECT *
FROM (SELECT item_sk, d_date, web_sales, store_sales,
             max(web_sales) OVER (PARTITION BY item_sk
                                  ORDER BY d_date
                                  ROWS BETWEEN UNBOUNDED PRECEDING
                                       AND CURRENT ROW)
                 web_cumulative,
             max(store_sales) OVER (PARTITION BY item_sk
                                    ORDER BY d_date
                                    ROWS BETWEEN UNBOUNDED PRECEDING
                                         AND CURRENT ROW)
                 store_cumulative
      FROM (SELECT CASE WHEN web.item_sk IS NOT NULL
                        THEN web.item_sk ELSE store.item_sk END
                       item_sk,
                   CASE WHEN web.d_date IS NOT NULL
                        THEN web.d_date ELSE store.d_date END d_date,
                   web.cume_sales web_sales,
                   store.cume_sales store_sales
            FROM web_v1 web
            FULL OUTER JOIN store_v1 store
                ON (web.item_sk = store.item_sk
                    AND web.d_date = store.d_date)) x) y
WHERE web_cumulative > store_cumulative
ORDER BY item_sk, d_date
LIMIT 100
""",
    57: """
WITH v1 AS (
  SELECT i_category, i_brand, cc_name, d_year, d_moy, sum_sales,
         avg(sum_sales) OVER (PARTITION BY i_category, i_brand,
                                  cc_name, d_year)
             avg_monthly_sales,
         rank() OVER (PARTITION BY i_category, i_brand, cc_name
                      ORDER BY d_year, d_moy) rn
  FROM (SELECT i_category, i_brand, cc_name, d_year, d_moy,
               sum(cs_sales_price) sum_sales
        FROM item, catalog_sales, date_dim, call_center
        WHERE cs_item_sk = i_item_sk
          AND cs_sold_date_sk = d_date_sk
          AND cc_call_center_sk = cs_call_center_sk
          AND (d_year = 1999
               OR (d_year = 1998 AND d_moy = 12)
               OR (d_year = 2000 AND d_moy = 1))
        GROUP BY i_category, i_brand, cc_name, d_year,
                 d_moy) inner_v1),
v2 AS (
  SELECT v1.i_category, v1.i_brand, v1.cc_name, v1.d_year,
         v1.d_moy, v1.avg_monthly_sales, v1.sum_sales,
         v1_lag.sum_sales psum, v1_lead.sum_sales nsum
  FROM v1, v1 v1_lag, v1 v1_lead
  WHERE v1.i_category = v1_lag.i_category
    AND v1.i_category = v1_lead.i_category
    AND v1.i_brand = v1_lag.i_brand
    AND v1.i_brand = v1_lead.i_brand
    AND v1.cc_name = v1_lag.cc_name
    AND v1.cc_name = v1_lead.cc_name
    AND v1.rn = v1_lag.rn + 1
    AND v1.rn = v1_lead.rn - 1)
SELECT *
FROM v2
WHERE d_year = 1999
  AND avg_monthly_sales > 0
  AND CASE WHEN avg_monthly_sales > 0
           THEN abs(sum_sales - avg_monthly_sales)
                / avg_monthly_sales
           ELSE NULL END > 0.1
ORDER BY sum_sales - avg_monthly_sales, nsum
LIMIT 100
""",
    63: """
SELECT *
FROM (SELECT i_manager_id, sum_sales,
             avg(sum_sales) OVER (PARTITION BY i_manager_id)
                 avg_monthly_sales
      FROM (SELECT i_manager_id, sum(ss_sales_price) sum_sales
            FROM item, store_sales, date_dim, store
            WHERE ss_item_sk = i_item_sk
              AND ss_sold_date_sk = d_date_sk
              AND ss_store_sk = s_store_sk
              AND d_month_seq IN (1200, 1201, 1202, 1203, 1204,
                                  1205, 1206, 1207, 1208, 1209,
                                  1210, 1211)
              AND ((i_category IN ('Books', 'Children',
                                   'Electronics')
                    AND i_class IN ('class#1', 'class#2',
                                    'class#3'))
                   OR (i_category IN ('Women', 'Music', 'Men')
                       AND i_class IN ('class#4', 'class#5',
                                       'class#6')))
            GROUP BY i_manager_id, d_moy) t1) tmp1
WHERE CASE WHEN avg_monthly_sales > 0
           THEN abs(sum_sales - avg_monthly_sales)
                / avg_monthly_sales
           ELSE NULL END > 0.1
ORDER BY i_manager_id, avg_monthly_sales, sum_sales
LIMIT 100
""",
    70: """
SELECT total_sum, s_state, s_county, lochierarchy,
       rank() OVER (PARTITION BY lochierarchy,
                        CASE WHEN county_grouping = 0
                             THEN s_state END
                    ORDER BY total_sum DESC) rank_within_parent
FROM (SELECT sum(ss_net_profit) total_sum, s_state, s_county,
             grouping(s_state) + grouping(s_county) lochierarchy,
             grouping(s_county) county_grouping
      FROM store_sales, date_dim d1, store
      WHERE d1.d_month_seq BETWEEN 1200 AND 1211
        AND d1.d_date_sk = ss_sold_date_sk
        AND s_store_sk = ss_store_sk
        AND s_state IN
            (SELECT s_state
             FROM (SELECT s_state s_state,
                          rank() OVER (PARTITION BY s_state
                                       ORDER BY sum(ss_net_profit)
                                           DESC) ranking
                   FROM store_sales, store, date_dim
                   WHERE d_month_seq BETWEEN 1200 AND 1211
                     AND d_date_sk = ss_sold_date_sk
                     AND s_store_sk = ss_store_sk
                   GROUP BY s_state) tmp1
             WHERE ranking <= 5)
      GROUP BY ROLLUP (s_state, s_county)) t
ORDER BY lochierarchy DESC,
         CASE WHEN lochierarchy = 0 THEN s_state END,
         rank_within_parent
LIMIT 100
""",
    74: """
WITH year_total AS (
  SELECT c_customer_id customer_id, c_first_name customer_first_name,
         c_last_name customer_last_name, d_year AS year_,
         sum(ss_net_paid) year_total, 's' sale_type
  FROM customer, store_sales, date_dim
  WHERE c_customer_sk = ss_customer_sk
    AND ss_sold_date_sk = d_date_sk
    AND d_year IN (2001, 2001 + 1)
  GROUP BY c_customer_id, c_first_name, c_last_name, d_year
  UNION ALL
  SELECT c_customer_id, c_first_name, c_last_name, d_year,
         sum(ws_net_paid), 'w'
  FROM customer, web_sales, date_dim
  WHERE c_customer_sk = ws_bill_customer_sk
    AND ws_sold_date_sk = d_date_sk
    AND d_year IN (2001, 2001 + 1)
  GROUP BY c_customer_id, c_first_name, c_last_name, d_year)
SELECT t_s_secyear.customer_id, t_s_secyear.customer_first_name,
       t_s_secyear.customer_last_name
FROM year_total t_s_firstyear, year_total t_s_secyear,
     year_total t_w_firstyear, year_total t_w_secyear
WHERE t_s_secyear.customer_id = t_s_firstyear.customer_id
  AND t_s_firstyear.customer_id = t_w_secyear.customer_id
  AND t_s_firstyear.customer_id = t_w_firstyear.customer_id
  AND t_s_firstyear.sale_type = 's'
  AND t_w_firstyear.sale_type = 'w'
  AND t_s_secyear.sale_type = 's'
  AND t_w_secyear.sale_type = 'w'
  AND t_s_firstyear.year_ = 2001
  AND t_s_secyear.year_ = 2001 + 1
  AND t_w_firstyear.year_ = 2001
  AND t_w_secyear.year_ = 2001 + 1
  AND t_s_firstyear.year_total > 0
  AND t_w_firstyear.year_total > 0
  AND CASE WHEN t_w_firstyear.year_total > 0
           THEN t_w_secyear.year_total / t_w_firstyear.year_total
           ELSE NULL END
      > CASE WHEN t_s_firstyear.year_total > 0
             THEN t_s_secyear.year_total / t_s_firstyear.year_total
             ELSE NULL END
ORDER BY 1, 1, 1
LIMIT 100
""",
    97: """
WITH ssci AS (
  SELECT ss_customer_sk customer_sk, ss_item_sk item_sk
  FROM store_sales, date_dim
  WHERE ss_sold_date_sk = d_date_sk
    AND d_month_seq BETWEEN 1200 AND 1211
  GROUP BY ss_customer_sk, ss_item_sk),
csci AS (
  SELECT cs_bill_customer_sk customer_sk, cs_item_sk item_sk
  FROM catalog_sales, date_dim
  WHERE cs_sold_date_sk = d_date_sk
    AND d_month_seq BETWEEN 1200 AND 1211
  GROUP BY cs_bill_customer_sk, cs_item_sk)
SELECT sum(CASE WHEN ssci.customer_sk IS NOT NULL
                 AND csci.customer_sk IS NULL
                THEN 1 ELSE 0 END) store_only,
       sum(CASE WHEN ssci.customer_sk IS NULL
                 AND csci.customer_sk IS NOT NULL
                THEN 1 ELSE 0 END) catalog_only,
       sum(CASE WHEN ssci.customer_sk IS NOT NULL
                 AND csci.customer_sk IS NOT NULL
                THEN 1 ELSE 0 END) store_and_catalog
FROM ssci
FULL OUTER JOIN csci ON (ssci.customer_sk = csci.customer_sk
                         AND ssci.item_sk = csci.item_sk)
LIMIT 100
""",
    4: """
WITH year_total AS (
  SELECT c_customer_id customer_id, c_first_name customer_first_name,
         c_last_name customer_last_name,
         c_preferred_cust_flag customer_preferred_cust_flag,
         c_birth_country customer_birth_country, d_year dyear,
         sum(((ss_ext_list_price - ss_ext_wholesale_cost
               - ss_ext_discount_amt) + ss_ext_sales_price) / 2)
             year_total,
         's' sale_type
  FROM customer, store_sales, date_dim
  WHERE c_customer_sk = ss_customer_sk
    AND ss_sold_date_sk = d_date_sk
  GROUP BY c_customer_id, c_first_name, c_last_name,
           c_preferred_cust_flag, c_birth_country, d_year
  UNION ALL
  SELECT c_customer_id, c_first_name, c_last_name,
         c_preferred_cust_flag, c_birth_country, d_year,
         sum(((cs_ext_list_price - cs_ext_wholesale_cost
               - cs_ext_discount_amt) + cs_ext_sales_price) / 2),
         'c' sale_type
  FROM customer, catalog_sales, date_dim
  WHERE c_customer_sk = cs_bill_customer_sk
    AND cs_sold_date_sk = d_date_sk
  GROUP BY c_customer_id, c_first_name, c_last_name,
           c_preferred_cust_flag, c_birth_country, d_year
  UNION ALL
  SELECT c_customer_id, c_first_name, c_last_name,
         c_preferred_cust_flag, c_birth_country, d_year,
         sum(((ws_ext_list_price - ws_ext_wholesale_cost
               - ws_ext_discount_amt) + ws_ext_sales_price) / 2),
         'w' sale_type
  FROM customer, web_sales, date_dim
  WHERE c_customer_sk = ws_bill_customer_sk
    AND ws_sold_date_sk = d_date_sk
  GROUP BY c_customer_id, c_first_name, c_last_name,
           c_preferred_cust_flag, c_birth_country, d_year)
SELECT t_s_secyear.customer_id, t_s_secyear.customer_first_name,
       t_s_secyear.customer_last_name,
       t_s_secyear.customer_preferred_cust_flag
FROM year_total t_s_firstyear, year_total t_s_secyear,
     year_total t_c_firstyear, year_total t_c_secyear,
     year_total t_w_firstyear, year_total t_w_secyear
WHERE t_s_secyear.customer_id = t_s_firstyear.customer_id
  AND t_s_firstyear.customer_id = t_c_secyear.customer_id
  AND t_s_firstyear.customer_id = t_c_firstyear.customer_id
  AND t_s_firstyear.customer_id = t_w_firstyear.customer_id
  AND t_s_firstyear.customer_id = t_w_secyear.customer_id
  AND t_s_firstyear.sale_type = 's'
  AND t_c_firstyear.sale_type = 'c'
  AND t_w_firstyear.sale_type = 'w'
  AND t_s_secyear.sale_type = 's'
  AND t_c_secyear.sale_type = 'c'
  AND t_w_secyear.sale_type = 'w'
  AND t_s_firstyear.dyear = 2001
  AND t_s_secyear.dyear = 2001 + 1
  AND t_c_firstyear.dyear = 2001
  AND t_c_secyear.dyear = 2001 + 1
  AND t_w_firstyear.dyear = 2001
  AND t_w_secyear.dyear = 2001 + 1
  AND t_s_firstyear.year_total > 0
  AND t_c_firstyear.year_total > 0
  AND t_w_firstyear.year_total > 0
  AND CASE WHEN t_c_firstyear.year_total > 0
           THEN t_c_secyear.year_total / t_c_firstyear.year_total
           ELSE NULL END
      > CASE WHEN t_s_firstyear.year_total > 0
             THEN t_s_secyear.year_total / t_s_firstyear.year_total
             ELSE NULL END
  AND CASE WHEN t_c_firstyear.year_total > 0
           THEN t_c_secyear.year_total / t_c_firstyear.year_total
           ELSE NULL END
      > CASE WHEN t_w_firstyear.year_total > 0
             THEN t_w_secyear.year_total / t_w_firstyear.year_total
             ELSE NULL END
ORDER BY t_s_secyear.customer_id,
         t_s_secyear.customer_first_name,
         t_s_secyear.customer_last_name,
         t_s_secyear.customer_preferred_cust_flag
LIMIT 100
""",
    10: """
SELECT cd_gender, cd_marital_status, cd_education_status,
       count(*) cnt1, cd_purchase_estimate, count(*) cnt2,
       cd_credit_rating, count(*) cnt3,
       cd_dep_count, count(*) cnt4,
       cd_dep_employed_count, count(*) cnt5,
       cd_dep_college_count, count(*) cnt6
FROM customer c, customer_address ca, customer_demographics
WHERE c.c_current_addr_sk = ca.ca_address_sk
  AND ca_county IN ('Williamson County', 'Ziebach County',
                    'Walker County', 'Daviess County',
                    'Barrow County')
  AND cd_demo_sk = c.c_current_cdemo_sk
  AND EXISTS (SELECT * FROM store_sales, date_dim
              WHERE c.c_customer_sk = ss_customer_sk
                AND ss_sold_date_sk = d_date_sk
                AND d_year = 2002 AND d_moy BETWEEN 1 AND 4)
  AND (EXISTS (SELECT * FROM web_sales, date_dim
               WHERE c.c_customer_sk = ws_bill_customer_sk
                 AND ws_sold_date_sk = d_date_sk
                 AND d_year = 2002 AND d_moy BETWEEN 1 AND 4)
       OR EXISTS (SELECT * FROM catalog_sales, date_dim
                  WHERE c.c_customer_sk = cs_ship_customer_sk
                    AND cs_sold_date_sk = d_date_sk
                    AND d_year = 2002 AND d_moy BETWEEN 1 AND 4))
GROUP BY cd_gender, cd_marital_status, cd_education_status,
         cd_purchase_estimate, cd_credit_rating, cd_dep_count,
         cd_dep_employed_count, cd_dep_college_count
ORDER BY cd_gender, cd_marital_status, cd_education_status,
         cd_purchase_estimate, cd_credit_rating, cd_dep_count,
         cd_dep_employed_count, cd_dep_college_count
LIMIT 100
""",
    30: """
WITH customer_total_return AS (
  SELECT wr_returning_customer_sk ctr_customer_sk,
         ca_state ctr_state, sum(wr_return_amt) ctr_total_return
  FROM web_returns, date_dim, customer_address
  WHERE wr_returned_date_sk = d_date_sk AND d_year = 2002
    AND wr_returning_addr_sk = ca_address_sk
  GROUP BY wr_returning_customer_sk, ca_state)
SELECT c_customer_id, c_salutation, c_first_name, c_last_name,
       c_preferred_cust_flag, c_birth_day, c_birth_month,
       c_birth_year, c_birth_country, ctr_total_return
FROM customer_total_return ctr1, customer_address, customer
WHERE ctr1.ctr_total_return
      > (SELECT avg(ctr_total_return) * 1.2
         FROM customer_total_return ctr2
         WHERE ctr1.ctr_state = ctr2.ctr_state)
  AND ca_address_sk = c_current_addr_sk
  AND ca_state = 'GA'
  AND ctr1.ctr_customer_sk = c_customer_sk
ORDER BY c_customer_id, c_salutation, c_first_name, c_last_name,
         c_preferred_cust_flag, c_birth_day, c_birth_month,
         c_birth_year, c_birth_country, ctr_total_return
LIMIT 100
""",
    35: """
SELECT ca_state, cd_gender, cd_marital_status,
       cd_dep_count, count(*) cnt1,
       avg(cd_dep_count) a1, max(cd_dep_count) m1,
       sum(cd_dep_count) s1,
       cd_dep_employed_count, count(*) cnt2,
       avg(cd_dep_employed_count) a2,
       max(cd_dep_employed_count) m2,
       sum(cd_dep_employed_count) s2,
       cd_dep_college_count, count(*) cnt3,
       avg(cd_dep_college_count) a3,
       max(cd_dep_college_count) m3, sum(cd_dep_college_count) s3
FROM customer c, customer_address ca, customer_demographics
WHERE c.c_current_addr_sk = ca.ca_address_sk
  AND cd_demo_sk = c.c_current_cdemo_sk
  AND EXISTS (SELECT * FROM store_sales, date_dim
              WHERE c.c_customer_sk = ss_customer_sk
                AND ss_sold_date_sk = d_date_sk
                AND d_year = 2002 AND d_qoy < 4)
  AND (EXISTS (SELECT * FROM web_sales, date_dim
               WHERE c.c_customer_sk = ws_bill_customer_sk
                 AND ws_sold_date_sk = d_date_sk
                 AND d_year = 2002 AND d_qoy < 4)
       OR EXISTS (SELECT * FROM catalog_sales, date_dim
                  WHERE c.c_customer_sk = cs_ship_customer_sk
                    AND cs_sold_date_sk = d_date_sk
                    AND d_year = 2002 AND d_qoy < 4))
GROUP BY ca_state, cd_gender, cd_marital_status, cd_dep_count,
         cd_dep_employed_count, cd_dep_college_count
ORDER BY ca_state, cd_gender, cd_marital_status, cd_dep_count,
         cd_dep_employed_count, cd_dep_college_count
LIMIT 100
""",
    40: """
SELECT w_state, i_item_id,
       sum(CASE WHEN d_date < DATE '2000-03-11'
                THEN cs_sales_price
                     - coalesce(cr_refunded_cash, 0)
                ELSE 0 END) sales_before,
       sum(CASE WHEN d_date >= DATE '2000-03-11'
                THEN cs_sales_price
                     - coalesce(cr_refunded_cash, 0)
                ELSE 0 END) sales_after
FROM catalog_sales
LEFT OUTER JOIN catalog_returns
    ON (cs_order_number = cr_order_number
        AND cs_item_sk = cr_item_sk),
     warehouse, item, date_dim
WHERE i_current_price BETWEEN 0.99 AND 1.49
  AND i_item_sk = cs_item_sk
  AND cs_warehouse_sk = w_warehouse_sk
  AND cs_sold_date_sk = d_date_sk
  AND d_date BETWEEN DATE '2000-02-10' AND DATE '2000-04-10'
GROUP BY w_state, i_item_id
ORDER BY w_state, i_item_id
LIMIT 100
""",
    41: """
SELECT DISTINCT i_product_name
FROM item i1
WHERE i_manufact_id BETWEEN 738 AND 778
  AND (SELECT count(*) AS item_cnt
       FROM item
       WHERE (i_manufact = i1.i_manufact
              AND ((i_category = 'Women'
                    AND (i_color = 'powder' OR i_color = 'khaki')
                    AND (i_units = 'Ounce' OR i_units = 'Each')
                    AND (i_size = 'medium' OR i_size = 'extra large'))
                   OR (i_category = 'Women'
                       AND (i_color = 'brown' OR i_color = 'honeydew')
                       AND (i_units = 'Bundle' OR i_units = 'Ton')
                       AND (i_size = 'N/A' OR i_size = 'small'))
                   OR (i_category = 'Men'
                       AND (i_color = 'floral' OR i_color = 'deep')
                       AND (i_units = 'Case' OR i_units = 'Dozen')
                       AND (i_size = 'petite' OR i_size = 'large'))
                   OR (i_category = 'Men'
                       AND (i_color = 'light' OR i_color = 'cornflower')
                       AND (i_units = 'Box' OR i_units = 'Pound')
                       AND (i_size = 'medium'
                            OR i_size = 'extra large'))))
          OR (i_manufact = i1.i_manufact
              AND ((i_category = 'Women'
                    AND (i_color = 'midnight' OR i_color = 'snow')
                    AND (i_units = 'Pallet' OR i_units = 'Gross')
                    AND (i_size = 'medium' OR i_size = 'extra large'))
                   OR (i_category = 'Women'
                       AND (i_color = 'cyan' OR i_color = 'papaya')
                       AND (i_units = 'Cup' OR i_units = 'Dram')
                       AND (i_size = 'N/A' OR i_size = 'small'))
                   OR (i_category = 'Men'
                       AND (i_color = 'orange' OR i_color = 'frosted')
                       AND (i_units = 'Each' OR i_units = 'Tbl')
                       AND (i_size = 'petite' OR i_size = 'large'))
                   OR (i_category = 'Men'
                       AND (i_color = 'forest' OR i_color = 'ghost')
                       AND (i_units = 'Lb' OR i_units = 'Bunch')
                       AND (i_size = 'medium'
                            OR i_size = 'extra large'))))) > 0
ORDER BY i_product_name
LIMIT 100
""",
    49: """
SELECT channel, item, return_ratio, return_rank, currency_rank
FROM (SELECT 'web' AS channel, web.item, web.return_ratio,
             web.return_rank, web.currency_rank
      FROM (SELECT item, return_ratio, currency_ratio,
                   rank() OVER (ORDER BY return_ratio) return_rank,
                   rank() OVER (ORDER BY currency_ratio)
                       currency_rank
            FROM (SELECT ws.ws_item_sk item,
                         cast(sum(coalesce(wr.wr_return_quantity, 0))
                              AS double)
                         / cast(sum(coalesce(ws.ws_quantity, 0))
                                AS double) return_ratio,
                         cast(sum(coalesce(wr.wr_return_amt, 0))
                              AS double)
                         / cast(sum(coalesce(ws.ws_net_paid, 0))
                                AS double) currency_ratio
                  FROM web_sales ws
                  LEFT OUTER JOIN web_returns wr
                      ON (ws.ws_order_number = wr.wr_order_number
                          AND ws.ws_item_sk = wr.wr_item_sk),
                       date_dim
                  WHERE wr.wr_return_amt > 100
                    AND ws.ws_net_profit > 1
                    AND ws.ws_net_paid > 0
                    AND ws.ws_quantity > 0
                    AND ws_sold_date_sk = d_date_sk
                    AND d_year = 2001 AND d_moy = 12
                  GROUP BY ws.ws_item_sk) in_web) web
      WHERE web.return_rank <= 10 OR web.currency_rank <= 10
      UNION
      SELECT 'catalog' AS channel, cat.item, cat.return_ratio,
             cat.return_rank, cat.currency_rank
      FROM (SELECT item, return_ratio, currency_ratio,
                   rank() OVER (ORDER BY return_ratio) return_rank,
                   rank() OVER (ORDER BY currency_ratio)
                       currency_rank
            FROM (SELECT cs.cs_item_sk item,
                         cast(sum(coalesce(cr.cr_return_quantity, 0))
                              AS double)
                         / cast(sum(coalesce(cs.cs_quantity, 0))
                                AS double) return_ratio,
                         cast(sum(coalesce(cr.cr_return_amount, 0))
                              AS double)
                         / cast(sum(coalesce(cs.cs_net_paid, 0))
                                AS double) currency_ratio
                  FROM catalog_sales cs
                  LEFT OUTER JOIN catalog_returns cr
                      ON (cs.cs_order_number = cr.cr_order_number
                          AND cs.cs_item_sk = cr.cr_item_sk),
                       date_dim
                  WHERE cr.cr_return_amount > 100
                    AND cs.cs_net_profit > 1
                    AND cs.cs_net_paid > 0
                    AND cs.cs_quantity > 0
                    AND cs_sold_date_sk = d_date_sk
                    AND d_year = 2001 AND d_moy = 12
                  GROUP BY cs.cs_item_sk) in_cat) cat
      WHERE cat.return_rank <= 10 OR cat.currency_rank <= 10
      UNION
      SELECT 'store' AS channel, sts.item, sts.return_ratio,
             sts.return_rank, sts.currency_rank
      FROM (SELECT item, return_ratio, currency_ratio,
                   rank() OVER (ORDER BY return_ratio) return_rank,
                   rank() OVER (ORDER BY currency_ratio)
                       currency_rank
            FROM (SELECT sts.ss_item_sk item,
                         cast(sum(coalesce(sr.sr_return_quantity, 0))
                              AS double)
                         / cast(sum(coalesce(sts.ss_quantity, 0))
                                AS double) return_ratio,
                         cast(sum(coalesce(sr.sr_return_amt, 0))
                              AS double)
                         / cast(sum(coalesce(sts.ss_net_paid, 0))
                                AS double) currency_ratio
                  FROM store_sales sts
                  LEFT OUTER JOIN store_returns sr
                      ON (sts.ss_ticket_number = sr.sr_ticket_number
                          AND sts.ss_item_sk = sr.sr_item_sk),
                       date_dim
                  WHERE sr.sr_return_amt > 100
                    AND sts.ss_net_profit > 1
                    AND sts.ss_net_paid > 0
                    AND sts.ss_quantity > 0
                    AND ss_sold_date_sk = d_date_sk
                    AND d_year = 2001 AND d_moy = 12
                  GROUP BY sts.ss_item_sk) in_store) sts
      WHERE sts.return_rank <= 10 OR sts.currency_rank <= 10) t
ORDER BY 1, 4, 5, 2
LIMIT 100
""",
    58: """
WITH ss_items AS (
  SELECT i_item_id item_id, sum(ss_ext_sales_price) ss_item_rev
  FROM store_sales, item, date_dim
  WHERE ss_item_sk = i_item_sk
    AND d_date IN (SELECT d_date FROM date_dim
                   WHERE d_week_seq = (SELECT d_week_seq
                                       FROM date_dim
                                       WHERE d_date
                                             = DATE '2000-01-03'))
    AND ss_sold_date_sk = d_date_sk
  GROUP BY i_item_id),
cs_items AS (
  SELECT i_item_id item_id, sum(cs_ext_sales_price) cs_item_rev
  FROM catalog_sales, item, date_dim
  WHERE cs_item_sk = i_item_sk
    AND d_date IN (SELECT d_date FROM date_dim
                   WHERE d_week_seq = (SELECT d_week_seq
                                       FROM date_dim
                                       WHERE d_date
                                             = DATE '2000-01-03'))
    AND cs_sold_date_sk = d_date_sk
  GROUP BY i_item_id),
ws_items AS (
  SELECT i_item_id item_id, sum(ws_ext_sales_price) ws_item_rev
  FROM web_sales, item, date_dim
  WHERE ws_item_sk = i_item_sk
    AND d_date IN (SELECT d_date FROM date_dim
                   WHERE d_week_seq = (SELECT d_week_seq
                                       FROM date_dim
                                       WHERE d_date
                                             = DATE '2000-01-03'))
    AND ws_sold_date_sk = d_date_sk
  GROUP BY i_item_id)
SELECT ss_items.item_id, ss_item_rev,
       ss_item_rev / ((ss_item_rev + cs_item_rev + ws_item_rev) / 3)
           * 100 ss_dev,
       cs_item_rev,
       cs_item_rev / ((ss_item_rev + cs_item_rev + ws_item_rev) / 3)
           * 100 cs_dev,
       ws_item_rev,
       ws_item_rev / ((ss_item_rev + cs_item_rev + ws_item_rev) / 3)
           * 100 ws_dev,
       (ss_item_rev + cs_item_rev + ws_item_rev) / 3 average
FROM ss_items, cs_items, ws_items
WHERE ss_items.item_id = cs_items.item_id
  AND ss_items.item_id = ws_items.item_id
  AND ss_item_rev BETWEEN 0.9 * cs_item_rev AND 1.1 * cs_item_rev
  AND ss_item_rev BETWEEN 0.9 * ws_item_rev AND 1.1 * ws_item_rev
  AND cs_item_rev BETWEEN 0.9 * ss_item_rev AND 1.1 * ss_item_rev
  AND cs_item_rev BETWEEN 0.9 * ws_item_rev AND 1.1 * ws_item_rev
  AND ws_item_rev BETWEEN 0.9 * ss_item_rev AND 1.1 * ss_item_rev
  AND ws_item_rev BETWEEN 0.9 * cs_item_rev AND 1.1 * cs_item_rev
ORDER BY item_id, ss_item_rev
LIMIT 100
""",
    61: """
SELECT promotions, total,
       cast(promotions AS double) / cast(total AS double) * 100
           ratio
FROM (SELECT sum(ss_ext_sales_price) promotions
      FROM store_sales, store, promotion, date_dim, customer,
           customer_address, item
      WHERE ss_sold_date_sk = d_date_sk
        AND ss_store_sk = s_store_sk
        AND ss_promo_sk = p_promo_sk
        AND ss_customer_sk = c_customer_sk
        AND ca_address_sk = c_current_addr_sk
        AND ss_item_sk = i_item_sk
        AND ca_gmt_offset = -5
        AND i_category = 'Jewelry'
        AND (p_channel_dmail = 'Y' OR p_channel_email = 'Y'
             OR p_channel_tv = 'Y')
        AND d_year = 1998 AND d_moy = 11) promotional_sales,
     (SELECT sum(ss_ext_sales_price) total
      FROM store_sales, store, date_dim, customer,
           customer_address, item
      WHERE ss_sold_date_sk = d_date_sk
        AND ss_store_sk = s_store_sk
        AND ss_customer_sk = c_customer_sk
        AND ca_address_sk = c_current_addr_sk
        AND ss_item_sk = i_item_sk
        AND ca_gmt_offset = -5
        AND i_category = 'Jewelry'
        AND d_year = 1998 AND d_moy = 11) all_sales
ORDER BY promotions, total
LIMIT 100
""",
    69: """
SELECT cd_gender, cd_marital_status, cd_education_status,
       count(*) cnt1, cd_purchase_estimate, count(*) cnt2,
       cd_credit_rating, count(*) cnt3
FROM customer c, customer_address ca, customer_demographics
WHERE c.c_current_addr_sk = ca.ca_address_sk
  AND ca_state IN ('KY', 'GA', 'NM')
  AND cd_demo_sk = c.c_current_cdemo_sk
  AND EXISTS (SELECT * FROM store_sales, date_dim
              WHERE c.c_customer_sk = ss_customer_sk
                AND ss_sold_date_sk = d_date_sk
                AND d_year = 2001 AND d_moy BETWEEN 4 AND 6)
  AND NOT EXISTS (SELECT * FROM web_sales, date_dim
                  WHERE c.c_customer_sk = ws_bill_customer_sk
                    AND ws_sold_date_sk = d_date_sk
                    AND d_year = 2001 AND d_moy BETWEEN 4 AND 6)
  AND NOT EXISTS (SELECT * FROM catalog_sales, date_dim
                  WHERE c.c_customer_sk = cs_ship_customer_sk
                    AND cs_sold_date_sk = d_date_sk
                    AND d_year = 2001 AND d_moy BETWEEN 4 AND 6)
GROUP BY cd_gender, cd_marital_status, cd_education_status,
         cd_purchase_estimate, cd_credit_rating
ORDER BY cd_gender, cd_marital_status, cd_education_status,
         cd_purchase_estimate, cd_credit_rating
LIMIT 100
""",
    81: """
WITH customer_total_return AS (
  SELECT cr_returning_customer_sk ctr_customer_sk,
         ca_state ctr_state,
         sum(cr_return_amt_inc_tax) ctr_total_return
  FROM catalog_returns, date_dim, customer_address
  WHERE cr_returned_date_sk = d_date_sk AND d_year = 2000
    AND cr_returning_addr_sk = ca_address_sk
  GROUP BY cr_returning_customer_sk, ca_state)
SELECT c_customer_id, c_salutation, c_first_name, c_last_name,
       ca_street_number, ca_street_name, ca_street_type,
       ca_suite_number, ca_city, ca_county, ca_state, ca_zip,
       ca_country, ca_gmt_offset, ca_location_type,
       ctr_total_return
FROM customer_total_return ctr1, customer_address, customer
WHERE ctr1.ctr_total_return
      > (SELECT avg(ctr_total_return) * 1.2
         FROM customer_total_return ctr2
         WHERE ctr1.ctr_state = ctr2.ctr_state)
  AND ca_address_sk = c_current_addr_sk
  AND ca_state = 'GA'
  AND ctr1.ctr_customer_sk = c_customer_sk
ORDER BY c_customer_id, c_salutation, c_first_name, c_last_name,
         ca_street_number, ca_street_name, ca_street_type,
         ca_suite_number, ca_city, ca_county, ca_state, ca_zip,
         ca_country, ca_gmt_offset, ca_location_type,
         ctr_total_return
LIMIT 100
""",
    83: """
WITH sr_items AS (
  SELECT i_item_id item_id, sum(sr_return_quantity) sr_item_qty
  FROM store_returns, item, date_dim
  WHERE sr_item_sk = i_item_sk
    AND d_date IN (SELECT d_date FROM date_dim
                   WHERE d_week_seq IN
                         (SELECT d_week_seq FROM date_dim
                          WHERE d_date IN (DATE '2000-06-30',
                                           DATE '2000-09-27',
                                           DATE '2000-11-17')))
    AND sr_returned_date_sk = d_date_sk
  GROUP BY i_item_id),
cr_items AS (
  SELECT i_item_id item_id, sum(cr_return_quantity) cr_item_qty
  FROM catalog_returns, item, date_dim
  WHERE cr_item_sk = i_item_sk
    AND d_date IN (SELECT d_date FROM date_dim
                   WHERE d_week_seq IN
                         (SELECT d_week_seq FROM date_dim
                          WHERE d_date IN (DATE '2000-06-30',
                                           DATE '2000-09-27',
                                           DATE '2000-11-17')))
    AND cr_returned_date_sk = d_date_sk
  GROUP BY i_item_id),
wr_items AS (
  SELECT i_item_id item_id, sum(wr_return_quantity) wr_item_qty
  FROM web_returns, item, date_dim
  WHERE wr_item_sk = i_item_sk
    AND d_date IN (SELECT d_date FROM date_dim
                   WHERE d_week_seq IN
                         (SELECT d_week_seq FROM date_dim
                          WHERE d_date IN (DATE '2000-06-30',
                                           DATE '2000-09-27',
                                           DATE '2000-11-17')))
    AND wr_returned_date_sk = d_date_sk
  GROUP BY i_item_id)
SELECT sr_items.item_id,
       sr_item_qty,
       sr_item_qty * 1.0000
           / (sr_item_qty + cr_item_qty + wr_item_qty) / 3.0000
           * 100 sr_dev,
       cr_item_qty,
       cr_item_qty * 1.0000
           / (sr_item_qty + cr_item_qty + wr_item_qty) / 3.0000
           * 100 cr_dev,
       wr_item_qty,
       wr_item_qty * 1.0000
           / (sr_item_qty + cr_item_qty + wr_item_qty) / 3.0000
           * 100 wr_dev,
       (sr_item_qty + cr_item_qty + wr_item_qty) / 3.0 average
FROM sr_items, cr_items, wr_items
WHERE sr_items.item_id = cr_items.item_id
  AND sr_items.item_id = wr_items.item_id
ORDER BY sr_items.item_id, sr_item_qty
LIMIT 100
""",
    85: """
SELECT substr(r_reason_desc, 1, 20) reason,
       avg(ws_quantity) q, avg(wr_refunded_cash) rc,
       avg(wr_fee) fee
FROM web_sales, web_returns, web_page, customer_demographics cd1,
     customer_demographics cd2, customer_address, date_dim, reason
WHERE ws_web_page_sk = wp_web_page_sk
  AND ws_item_sk = wr_item_sk
  AND ws_order_number = wr_order_number
  AND ws_sold_date_sk = d_date_sk AND d_year = 2000
  AND cd1.cd_demo_sk = wr_refunded_cdemo_sk
  AND cd2.cd_demo_sk = wr_returning_cdemo_sk
  AND ca_address_sk = wr_refunded_addr_sk
  AND r_reason_sk = wr_reason_sk
  AND ((cd1.cd_marital_status = 'M'
        AND cd1.cd_marital_status = cd2.cd_marital_status
        AND cd1.cd_education_status = 'Advanced Degree'
        AND cd1.cd_education_status = cd2.cd_education_status
        AND ws_sales_price BETWEEN 100.00 AND 150.00)
       OR (cd1.cd_marital_status = 'S'
           AND cd1.cd_marital_status = cd2.cd_marital_status
           AND cd1.cd_education_status = 'College'
           AND cd1.cd_education_status = cd2.cd_education_status
           AND ws_sales_price BETWEEN 50.00 AND 100.00)
       OR (cd1.cd_marital_status = 'W'
           AND cd1.cd_marital_status = cd2.cd_marital_status
           AND cd1.cd_education_status = '2 yr Degree'
           AND cd1.cd_education_status = cd2.cd_education_status
           AND ws_sales_price BETWEEN 150.00 AND 200.00))
  AND ((ca_country = 'United States'
        AND ca_state IN ('IN', 'OH', 'NJ')
        AND ws_net_profit BETWEEN 100 AND 200)
       OR (ca_country = 'United States'
           AND ca_state IN ('WI', 'CT', 'KY')
           AND ws_net_profit BETWEEN 150 AND 300)
       OR (ca_country = 'United States'
           AND ca_state IN ('LA', 'IA', 'AR')
           AND ws_net_profit BETWEEN 50 AND 250))
GROUP BY r_reason_desc
ORDER BY substr(r_reason_desc, 1, 20), avg(ws_quantity),
         avg(wr_refunded_cash), avg(wr_fee)
LIMIT 100
""",
    95: """
WITH ws_wh AS (
  SELECT ws1.ws_order_number,
         ws1.ws_warehouse_sk wh1, ws2.ws_warehouse_sk wh2
  FROM web_sales ws1, web_sales ws2
  WHERE ws1.ws_order_number = ws2.ws_order_number
    AND ws1.ws_warehouse_sk <> ws2.ws_warehouse_sk)
SELECT count(DISTINCT ws_order_number) order_count,
       sum(ws_ext_ship_cost) total_shipping_cost,
       sum(ws_net_profit) total_net_profit
FROM web_sales ws1, date_dim, customer_address, web_site
WHERE d_date BETWEEN DATE '1999-02-01' AND DATE '1999-04-02'
  AND ws1.ws_ship_date_sk = d_date_sk
  AND ws1.ws_ship_addr_sk = ca_address_sk
  AND ca_state = 'IL'
  AND ws1.ws_web_site_sk = web_site_sk
  AND web_company_name = 'pri'
  AND ws1.ws_order_number IN (SELECT ws_order_number
                              FROM ws_wh)
  AND ws1.ws_order_number IN (SELECT wr_order_number
                              FROM web_returns, ws_wh
                              WHERE wr_order_number
                                    = ws_wh.ws_order_number)
ORDER BY count(DISTINCT ws_order_number)
LIMIT 100
""",
    5: """
WITH ssr AS (
  SELECT s_store_id,
         sum(sales_price) sales, sum(profit) profit,
         sum(return_amt) returns_, sum(net_loss) profit_loss
  FROM (SELECT ss_store_sk AS store_sk,
               ss_sold_date_sk AS date_sk,
               ss_ext_sales_price AS sales_price,
               ss_net_profit AS profit,
               cast(0 AS double) AS return_amt,
               cast(0 AS double) AS net_loss
        FROM store_sales
        UNION ALL
        SELECT sr_store_sk, sr_returned_date_sk,
               cast(0 AS double), cast(0 AS double),
               sr_return_amt, sr_net_loss
        FROM store_returns) salesreturns,
       date_dim, store
  WHERE date_sk = d_date_sk
    AND d_date BETWEEN DATE '2000-08-23' AND DATE '2000-09-06'
    AND store_sk = s_store_sk
  GROUP BY s_store_id),
csr AS (
  SELECT cp_catalog_page_id,
         sum(sales_price) sales, sum(profit) profit,
         sum(return_amt) returns_, sum(net_loss) profit_loss
  FROM (SELECT cs_catalog_page_sk AS page_sk,
               cs_sold_date_sk AS date_sk,
               cs_ext_sales_price AS sales_price,
               cs_net_profit AS profit,
               cast(0 AS double) AS return_amt,
               cast(0 AS double) AS net_loss
        FROM catalog_sales
        UNION ALL
        SELECT cr_catalog_page_sk, cr_returned_date_sk,
               cast(0 AS double), cast(0 AS double),
               cr_return_amount, cr_net_loss
        FROM catalog_returns) salesreturns,
       date_dim, catalog_page
  WHERE date_sk = d_date_sk
    AND d_date BETWEEN DATE '2000-08-23' AND DATE '2000-09-06'
    AND page_sk = cp_catalog_page_sk
  GROUP BY cp_catalog_page_id),
wsr AS (
  SELECT web_site_id,
         sum(sales_price) sales, sum(profit) profit,
         sum(return_amt) returns_, sum(net_loss) profit_loss
  FROM (SELECT ws_web_site_sk AS wsr_web_site_sk,
               ws_sold_date_sk AS date_sk,
               ws_ext_sales_price AS sales_price,
               ws_net_profit AS profit,
               cast(0 AS double) AS return_amt,
               cast(0 AS double) AS net_loss
        FROM web_sales
        UNION ALL
        SELECT ws_web_site_sk, wr_returned_date_sk,
               cast(0 AS double), cast(0 AS double),
               wr_return_amt, wr_net_loss
        FROM web_returns
        LEFT OUTER JOIN web_sales
            ON (wr_item_sk = ws_item_sk
                AND wr_order_number = ws_order_number)) salesreturns,
       date_dim, web_site
  WHERE date_sk = d_date_sk
    AND d_date BETWEEN DATE '2000-08-23' AND DATE '2000-09-06'
    AND wsr_web_site_sk = web_site_sk
  GROUP BY web_site_id)
SELECT channel, id, sum(sales) sales, sum(returns_) returns_,
       sum(profit - profit_loss) profit
FROM (SELECT 'store channel' AS channel,
             'store' || s_store_id AS id,
             sales, returns_, profit, profit_loss
      FROM ssr
      UNION ALL
      SELECT 'catalog channel', 'catalog_page' || cp_catalog_page_id,
             sales, returns_, profit, profit_loss
      FROM csr
      UNION ALL
      SELECT 'web channel', 'web_site' || web_site_id,
             sales, returns_, profit, profit_loss
      FROM wsr) x
GROUP BY ROLLUP (channel, id)
ORDER BY channel NULLS LAST, id NULLS LAST
LIMIT 100
""",
    14: """
WITH cross_items AS (
  SELECT i_item_sk ss_item_sk
  FROM item,
       (SELECT iss.i_brand_id brand_id, iss.i_class_id class_id,
               iss.i_category_id category_id
        FROM store_sales, item iss, date_dim d1
        WHERE ss_item_sk = iss.i_item_sk
          AND ss_sold_date_sk = d1.d_date_sk
          AND d1.d_year BETWEEN 1999 AND 1999 + 2
        INTERSECT
        SELECT ics.i_brand_id, ics.i_class_id, ics.i_category_id
        FROM catalog_sales, item ics, date_dim d2
        WHERE cs_item_sk = ics.i_item_sk
          AND cs_sold_date_sk = d2.d_date_sk
          AND d2.d_year BETWEEN 1999 AND 1999 + 2
        INTERSECT
        SELECT iws.i_brand_id, iws.i_class_id, iws.i_category_id
        FROM web_sales, item iws, date_dim d3
        WHERE ws_item_sk = iws.i_item_sk
          AND ws_sold_date_sk = d3.d_date_sk
          AND d3.d_year BETWEEN 1999 AND 1999 + 2) t
  WHERE i_brand_id = brand_id
    AND i_class_id = class_id
    AND i_category_id = category_id),
avg_sales AS (
  SELECT avg(quantity * list_price) average_sales
  FROM (SELECT ss_quantity quantity, ss_list_price list_price
        FROM store_sales, date_dim
        WHERE ss_sold_date_sk = d_date_sk
          AND d_year BETWEEN 1999 AND 1999 + 2
        UNION ALL
        SELECT cs_quantity, cs_list_price
        FROM catalog_sales, date_dim
        WHERE cs_sold_date_sk = d_date_sk
          AND d_year BETWEEN 1999 AND 1999 + 2
        UNION ALL
        SELECT ws_quantity, ws_list_price
        FROM web_sales, date_dim
        WHERE ws_sold_date_sk = d_date_sk
          AND d_year BETWEEN 1999 AND 1999 + 2) x)
SELECT channel, i_brand_id, i_class_id, i_category_id,
       sum(sales) sum_sales, sum(number_sales) sum_number_sales
FROM (SELECT 'store' channel, i_brand_id, i_class_id,
             i_category_id, sum(ss_quantity * ss_list_price) sales,
             count(*) number_sales
      FROM store_sales, item, date_dim
      WHERE ss_item_sk IN (SELECT ss_item_sk FROM cross_items)
        AND ss_item_sk = i_item_sk
        AND ss_sold_date_sk = d_date_sk
        AND d_year = 1999 + 2 AND d_moy = 11
      GROUP BY i_brand_id, i_class_id, i_category_id
      HAVING sum(ss_quantity * ss_list_price)
             > (SELECT average_sales FROM avg_sales)
      UNION ALL
      SELECT 'catalog', i_brand_id, i_class_id, i_category_id,
             sum(cs_quantity * cs_list_price), count(*)
      FROM catalog_sales, item, date_dim
      WHERE cs_item_sk IN (SELECT ss_item_sk FROM cross_items)
        AND cs_item_sk = i_item_sk
        AND cs_sold_date_sk = d_date_sk
        AND d_year = 1999 + 2 AND d_moy = 11
      GROUP BY i_brand_id, i_class_id, i_category_id
      HAVING sum(cs_quantity * cs_list_price)
             > (SELECT average_sales FROM avg_sales)
      UNION ALL
      SELECT 'web', i_brand_id, i_class_id, i_category_id,
             sum(ws_quantity * ws_list_price), count(*)
      FROM web_sales, item, date_dim
      WHERE ws_item_sk IN (SELECT ss_item_sk FROM cross_items)
        AND ws_item_sk = i_item_sk
        AND ws_sold_date_sk = d_date_sk
        AND d_year = 1999 + 2 AND d_moy = 11
      GROUP BY i_brand_id, i_class_id, i_category_id
      HAVING sum(ws_quantity * ws_list_price)
             > (SELECT average_sales FROM avg_sales)) y
GROUP BY ROLLUP (channel, i_brand_id, i_class_id, i_category_id)
ORDER BY channel NULLS LAST, i_brand_id NULLS LAST,
         i_class_id NULLS LAST, i_category_id NULLS LAST
LIMIT 100
""",
    23: """
WITH frequent_ss_items AS (
  SELECT substr(i_item_desc, 1, 30) itemdesc, i_item_sk item_sk,
         d_date solddate, count(*) cnt
  FROM store_sales, date_dim, item
  WHERE ss_sold_date_sk = d_date_sk
    AND ss_item_sk = i_item_sk
    AND d_year IN (2000, 2000 + 1, 2000 + 2, 2000 + 3)
  GROUP BY substr(i_item_desc, 1, 30), i_item_sk, d_date
  HAVING count(*) > 4),
max_store_sales AS (
  SELECT max(csales) tpcds_cmax
  FROM (SELECT c_customer_sk,
               sum(ss_quantity * ss_sales_price) csales
        FROM store_sales, customer, date_dim
        WHERE ss_customer_sk = c_customer_sk
          AND ss_sold_date_sk = d_date_sk
          AND d_year IN (2000, 2000 + 1, 2000 + 2, 2000 + 3)
        GROUP BY c_customer_sk) x),
best_ss_customer AS (
  SELECT c_customer_sk,
         sum(ss_quantity * ss_sales_price) ssales
  FROM store_sales, customer
  WHERE ss_customer_sk = c_customer_sk
  GROUP BY c_customer_sk
  HAVING sum(ss_quantity * ss_sales_price)
         > 0.5 * (SELECT tpcds_cmax FROM max_store_sales))
SELECT sum(sales) total
FROM (SELECT cs_quantity * cs_list_price sales
      FROM catalog_sales, date_dim
      WHERE d_year = 2000 AND d_moy = 2
        AND cs_sold_date_sk = d_date_sk
        AND cs_item_sk IN (SELECT item_sk FROM frequent_ss_items)
        AND cs_bill_customer_sk IN (SELECT c_customer_sk
                                    FROM best_ss_customer)
      UNION ALL
      SELECT ws_quantity * ws_list_price sales
      FROM web_sales, date_dim
      WHERE d_year = 2000 AND d_moy = 2
        AND ws_sold_date_sk = d_date_sk
        AND ws_item_sk IN (SELECT item_sk FROM frequent_ss_items)
        AND ws_bill_customer_sk IN (SELECT c_customer_sk
                                    FROM best_ss_customer)) t
LIMIT 100
""",
    24: """
WITH ssales AS (
  SELECT c_last_name, c_first_name, s_store_name, ca_state,
         s_state, i_color, i_current_price, i_manager_id,
         i_units, i_size, sum(ss_net_paid) netpaid
  FROM store_sales, store_returns, store, item, customer,
       customer_address
  WHERE ss_ticket_number = sr_ticket_number
    AND ss_item_sk = sr_item_sk
    AND ss_customer_sk = c_customer_sk
    AND ss_item_sk = i_item_sk
    AND ss_store_sk = s_store_sk
    AND c_current_addr_sk = ca_address_sk
    AND c_birth_country <> upper(ca_country)
    AND s_zip = ca_zip
    AND s_market_id = 8
  GROUP BY c_last_name, c_first_name, s_store_name, ca_state,
           s_state, i_color, i_current_price, i_manager_id,
           i_units, i_size)
SELECT c_last_name, c_first_name, s_store_name,
       sum(netpaid) paid
FROM ssales
WHERE i_color = 'pale'
GROUP BY c_last_name, c_first_name, s_store_name
HAVING sum(netpaid) > (SELECT 0.05 * avg(netpaid) FROM ssales)
ORDER BY c_last_name, c_first_name, s_store_name
""",
    39: """
WITH inv AS (
  SELECT w_warehouse_name, w_warehouse_sk, i_item_sk, d_moy,
         stdev, mean,
         CASE mean WHEN 0 THEN NULL ELSE stdev / mean END cov
  FROM (SELECT w_warehouse_name, w_warehouse_sk, i_item_sk, d_moy,
               stddev_samp(inv_quantity_on_hand) stdev,
               avg(inv_quantity_on_hand) mean
        FROM inventory, item, warehouse, date_dim
        WHERE inv_item_sk = i_item_sk
          AND inv_warehouse_sk = w_warehouse_sk
          AND inv_date_sk = d_date_sk
          AND d_year = 2001
        GROUP BY w_warehouse_name, w_warehouse_sk, i_item_sk,
                 d_moy) foo
  WHERE CASE mean WHEN 0 THEN 0 ELSE stdev / mean END > 1)
SELECT inv1.w_warehouse_sk wsk1, inv1.i_item_sk isk1,
       inv1.d_moy moy1, inv1.mean mean1, inv1.cov cov1,
       inv2.w_warehouse_sk wsk2, inv2.i_item_sk isk2,
       inv2.d_moy moy2, inv2.mean mean2, inv2.cov cov2
FROM inv inv1, inv inv2
WHERE inv1.i_item_sk = inv2.i_item_sk
  AND inv1.w_warehouse_sk = inv2.w_warehouse_sk
  AND inv1.d_moy = 1
  AND inv2.d_moy = 1 + 1
ORDER BY wsk1, isk1, moy1, mean1, cov1, mean2, cov2
""",
    44: """
SELECT asceding.rnk, i1.i_product_name best_performing,
       i2.i_product_name worst_performing
FROM (SELECT *
      FROM (SELECT item_sk,
                   rank() OVER (ORDER BY rank_col ASC) rnk
            FROM (SELECT ss_item_sk item_sk,
                         avg(ss_net_profit) rank_col
                  FROM store_sales ss1
                  WHERE ss_store_sk = 2
                  GROUP BY ss_item_sk
                  HAVING avg(ss_net_profit)
                         > 0.9 * (SELECT avg(ss_net_profit)
                                  FROM store_sales
                                  WHERE ss_store_sk = 2
                                    AND ss_addr_sk IS NULL)) v1) v11
      WHERE rnk < 11) asceding,
     (SELECT *
      FROM (SELECT item_sk,
                   rank() OVER (ORDER BY rank_col DESC) rnk
            FROM (SELECT ss_item_sk item_sk,
                         avg(ss_net_profit) rank_col
                  FROM store_sales ss1
                  WHERE ss_store_sk = 2
                  GROUP BY ss_item_sk
                  HAVING avg(ss_net_profit)
                         > 0.9 * (SELECT avg(ss_net_profit)
                                  FROM store_sales
                                  WHERE ss_store_sk = 2
                                    AND ss_addr_sk IS NULL)) v2) v21
      WHERE rnk < 11) descending,
     item i1, item i2
WHERE asceding.rnk = descending.rnk
  AND i1.i_item_sk = asceding.item_sk
  AND i2.i_item_sk = descending.item_sk
ORDER BY asceding.rnk
""",
    54: """
WITH my_customers AS (
  SELECT DISTINCT c_customer_sk, c_current_addr_sk
  FROM (SELECT cs_sold_date_sk sold_date_sk,
               cs_bill_customer_sk customer_sk,
               cs_item_sk item_sk
        FROM catalog_sales
        UNION ALL
        SELECT ws_sold_date_sk, ws_bill_customer_sk, ws_item_sk
        FROM web_sales) cs_or_ws_sales,
       item, date_dim, customer
  WHERE sold_date_sk = d_date_sk
    AND item_sk = i_item_sk
    AND i_category = 'Women'
    AND i_class = 'class#1'
    AND c_customer_sk = cs_or_ws_sales.customer_sk
    AND d_moy = 12 AND d_year = 1998),
my_revenue AS (
  SELECT c_customer_sk, sum(ss_ext_sales_price) revenue
  FROM my_customers, store_sales, customer_address, store,
       date_dim
  WHERE c_current_addr_sk = ca_address_sk
    AND ca_county = s_county
    AND ca_state = s_state
    AND ss_sold_date_sk = d_date_sk
    AND c_customer_sk = ss_customer_sk
    AND d_month_seq BETWEEN (SELECT DISTINCT d_month_seq + 1
                             FROM date_dim
                             WHERE d_year = 1998 AND d_moy = 12)
                        AND (SELECT DISTINCT d_month_seq + 3
                             FROM date_dim
                             WHERE d_year = 1998 AND d_moy = 12)
  GROUP BY c_customer_sk),
segments AS (
  SELECT cast(revenue / 50 AS bigint) segment
  FROM my_revenue)
SELECT segment, count(*) num_customers,
       segment * 50 segment_base
FROM segments
GROUP BY segment
ORDER BY segment, num_customers
LIMIT 100
""",
    66: """
SELECT w_warehouse_name, w_warehouse_sq_ft, w_city, w_county,
       w_state, w_country, ship_carriers, year_,
       sum(jan_sales) jan_sales, sum(feb_sales) feb_sales,
       sum(mar_sales) mar_sales, sum(apr_sales) apr_sales,
       sum(may_sales) may_sales, sum(jun_sales) jun_sales,
       sum(jul_sales) jul_sales, sum(aug_sales) aug_sales,
       sum(sep_sales) sep_sales, sum(oct_sales) oct_sales,
       sum(nov_sales) nov_sales, sum(dec_sales) dec_sales,
       sum(jan_net) jan_net, sum(feb_net) feb_net,
       sum(mar_net) mar_net, sum(apr_net) apr_net,
       sum(may_net) may_net, sum(jun_net) jun_net,
       sum(jul_net) jul_net, sum(aug_net) aug_net,
       sum(sep_net) sep_net, sum(oct_net) oct_net,
       sum(nov_net) nov_net, sum(dec_net) dec_net
FROM (SELECT w_warehouse_name, w_warehouse_sq_ft, w_city,
             w_county, w_state, w_country,
             'DHL' || ',' || 'BARIAN' AS ship_carriers,
             d_year AS year_,
             sum(CASE WHEN d_moy = 1 THEN ws_ext_sales_price
                      ELSE 0 END) AS jan_sales,
             sum(CASE WHEN d_moy = 2 THEN ws_ext_sales_price
                      ELSE 0 END) AS feb_sales,
             sum(CASE WHEN d_moy = 3 THEN ws_ext_sales_price
                      ELSE 0 END) AS mar_sales,
             sum(CASE WHEN d_moy = 4 THEN ws_ext_sales_price
                      ELSE 0 END) AS apr_sales,
             sum(CASE WHEN d_moy = 5 THEN ws_ext_sales_price
                      ELSE 0 END) AS may_sales,
             sum(CASE WHEN d_moy = 6 THEN ws_ext_sales_price
                      ELSE 0 END) AS jun_sales,
             sum(CASE WHEN d_moy = 7 THEN ws_ext_sales_price
                      ELSE 0 END) AS jul_sales,
             sum(CASE WHEN d_moy = 8 THEN ws_ext_sales_price
                      ELSE 0 END) AS aug_sales,
             sum(CASE WHEN d_moy = 9 THEN ws_ext_sales_price
                      ELSE 0 END) AS sep_sales,
             sum(CASE WHEN d_moy = 10 THEN ws_ext_sales_price
                      ELSE 0 END) AS oct_sales,
             sum(CASE WHEN d_moy = 11 THEN ws_ext_sales_price
                      ELSE 0 END) AS nov_sales,
             sum(CASE WHEN d_moy = 12 THEN ws_ext_sales_price
                      ELSE 0 END) AS dec_sales,
             sum(CASE WHEN d_moy = 1 THEN ws_net_paid
                      ELSE 0 END) AS jan_net,
             sum(CASE WHEN d_moy = 2 THEN ws_net_paid
                      ELSE 0 END) AS feb_net,
             sum(CASE WHEN d_moy = 3 THEN ws_net_paid
                      ELSE 0 END) AS mar_net,
             sum(CASE WHEN d_moy = 4 THEN ws_net_paid
                      ELSE 0 END) AS apr_net,
             sum(CASE WHEN d_moy = 5 THEN ws_net_paid
                      ELSE 0 END) AS may_net,
             sum(CASE WHEN d_moy = 6 THEN ws_net_paid
                      ELSE 0 END) AS jun_net,
             sum(CASE WHEN d_moy = 7 THEN ws_net_paid
                      ELSE 0 END) AS jul_net,
             sum(CASE WHEN d_moy = 8 THEN ws_net_paid
                      ELSE 0 END) AS aug_net,
             sum(CASE WHEN d_moy = 9 THEN ws_net_paid
                      ELSE 0 END) AS sep_net,
             sum(CASE WHEN d_moy = 10 THEN ws_net_paid
                      ELSE 0 END) AS oct_net,
             sum(CASE WHEN d_moy = 11 THEN ws_net_paid
                      ELSE 0 END) AS nov_net,
             sum(CASE WHEN d_moy = 12 THEN ws_net_paid
                      ELSE 0 END) AS dec_net
      FROM web_sales, warehouse, date_dim, time_dim, ship_mode
      WHERE ws_warehouse_sk = w_warehouse_sk
        AND ws_sold_date_sk = d_date_sk
        AND ws_sold_time_sk = t_time_sk
        AND ws_ship_mode_sk = sm_ship_mode_sk
        AND d_year = 2001
        AND t_time BETWEEN 30838 AND 30838 + 28800
        AND sm_carrier IN ('DHL', 'BARIAN')
      GROUP BY w_warehouse_name, w_warehouse_sq_ft, w_city,
               w_county, w_state, w_country, d_year
      UNION ALL
      SELECT w_warehouse_name, w_warehouse_sq_ft, w_city,
             w_county, w_state, w_country,
             'DHL' || ',' || 'BARIAN' AS ship_carriers,
             d_year AS year_,
             sum(CASE WHEN d_moy = 1 THEN cs_ext_sales_price
                      ELSE 0 END) AS jan_sales,
             sum(CASE WHEN d_moy = 2 THEN cs_ext_sales_price
                      ELSE 0 END) AS feb_sales,
             sum(CASE WHEN d_moy = 3 THEN cs_ext_sales_price
                      ELSE 0 END) AS mar_sales,
             sum(CASE WHEN d_moy = 4 THEN cs_ext_sales_price
                      ELSE 0 END) AS apr_sales,
             sum(CASE WHEN d_moy = 5 THEN cs_ext_sales_price
                      ELSE 0 END) AS may_sales,
             sum(CASE WHEN d_moy = 6 THEN cs_ext_sales_price
                      ELSE 0 END) AS jun_sales,
             sum(CASE WHEN d_moy = 7 THEN cs_ext_sales_price
                      ELSE 0 END) AS jul_sales,
             sum(CASE WHEN d_moy = 8 THEN cs_ext_sales_price
                      ELSE 0 END) AS aug_sales,
             sum(CASE WHEN d_moy = 9 THEN cs_ext_sales_price
                      ELSE 0 END) AS sep_sales,
             sum(CASE WHEN d_moy = 10 THEN cs_ext_sales_price
                      ELSE 0 END) AS oct_sales,
             sum(CASE WHEN d_moy = 11 THEN cs_ext_sales_price
                      ELSE 0 END) AS nov_sales,
             sum(CASE WHEN d_moy = 12 THEN cs_ext_sales_price
                      ELSE 0 END) AS dec_sales,
             sum(CASE WHEN d_moy = 1 THEN cs_net_paid
                      ELSE 0 END) AS jan_net,
             sum(CASE WHEN d_moy = 2 THEN cs_net_paid
                      ELSE 0 END) AS feb_net,
             sum(CASE WHEN d_moy = 3 THEN cs_net_paid
                      ELSE 0 END) AS mar_net,
             sum(CASE WHEN d_moy = 4 THEN cs_net_paid
                      ELSE 0 END) AS apr_net,
             sum(CASE WHEN d_moy = 5 THEN cs_net_paid
                      ELSE 0 END) AS may_net,
             sum(CASE WHEN d_moy = 6 THEN cs_net_paid
                      ELSE 0 END) AS jun_net,
             sum(CASE WHEN d_moy = 7 THEN cs_net_paid
                      ELSE 0 END) AS jul_net,
             sum(CASE WHEN d_moy = 8 THEN cs_net_paid
                      ELSE 0 END) AS aug_net,
             sum(CASE WHEN d_moy = 9 THEN cs_net_paid
                      ELSE 0 END) AS sep_net,
             sum(CASE WHEN d_moy = 10 THEN cs_net_paid
                      ELSE 0 END) AS oct_net,
             sum(CASE WHEN d_moy = 11 THEN cs_net_paid
                      ELSE 0 END) AS nov_net,
             sum(CASE WHEN d_moy = 12 THEN cs_net_paid
                      ELSE 0 END) AS dec_net
      FROM catalog_sales, warehouse, date_dim, time_dim, ship_mode
      WHERE cs_warehouse_sk = w_warehouse_sk
        AND cs_sold_date_sk = d_date_sk
        AND cs_sold_time_sk = t_time_sk
        AND cs_ship_mode_sk = sm_ship_mode_sk
        AND d_year = 2001
        AND t_time BETWEEN 30838 AND 30838 + 28800
        AND sm_carrier IN ('DHL', 'BARIAN')
      GROUP BY w_warehouse_name, w_warehouse_sq_ft, w_city,
               w_county, w_state, w_country, d_year) x
GROUP BY w_warehouse_name, w_warehouse_sq_ft, w_city, w_county,
         w_state, w_country, ship_carriers, year_
ORDER BY w_warehouse_name
LIMIT 100
""",
    67: """
SELECT *
FROM (SELECT i_category, i_class, i_brand, i_product_name, d_year,
             d_qoy, d_moy, s_store_id, sumsales,
             rank() OVER (PARTITION BY i_category
                          ORDER BY sumsales DESC) rk
      FROM (SELECT i_category, i_class, i_brand, i_product_name,
                   d_year, d_qoy, d_moy, s_store_id,
                   sum(coalesce(ss_sales_price * ss_quantity, 0))
                       sumsales
            FROM store_sales, date_dim, store, item
            WHERE ss_sold_date_sk = d_date_sk
              AND ss_item_sk = i_item_sk
              AND ss_store_sk = s_store_sk
              AND d_month_seq BETWEEN 1200 AND 1211
            GROUP BY ROLLUP (i_category, i_class, i_brand,
                             i_product_name, d_year, d_qoy, d_moy,
                             s_store_id)) dw1) dw2
WHERE rk <= 100
ORDER BY i_category NULLS LAST, i_class NULLS LAST,
         i_brand NULLS LAST, i_product_name NULLS LAST,
         d_year NULLS LAST, d_qoy NULLS LAST, d_moy NULLS LAST,
         s_store_id NULLS LAST, sumsales, rk
LIMIT 100
""",
    71: """
SELECT i_brand_id brand_id, i_brand brand, t_hour, t_minute,
       sum(ext_price) ext_price
FROM item,
     (SELECT ws_ext_sales_price AS ext_price,
             ws_sold_date_sk AS sold_date_sk,
             ws_item_sk AS sold_item_sk,
             ws_sold_time_sk AS time_sk
      FROM web_sales, date_dim
      WHERE d_date_sk = ws_sold_date_sk
        AND d_moy = 11 AND d_year = 1999
      UNION ALL
      SELECT cs_ext_sales_price, cs_sold_date_sk, cs_item_sk,
             cs_sold_time_sk
      FROM catalog_sales, date_dim
      WHERE d_date_sk = cs_sold_date_sk
        AND d_moy = 11 AND d_year = 1999
      UNION ALL
      SELECT ss_ext_sales_price, ss_sold_date_sk, ss_item_sk,
             ss_sold_time_sk
      FROM store_sales, date_dim
      WHERE d_date_sk = ss_sold_date_sk
        AND d_moy = 11 AND d_year = 1999) tmp,
     time_dim
WHERE sold_item_sk = i_item_sk
  AND i_manager_id = 1
  AND time_sk = t_time_sk
  AND (t_meal_time = 'breakfast' OR t_meal_time = 'dinner')
GROUP BY i_brand, i_brand_id, t_hour, t_minute
ORDER BY ext_price DESC, i_brand_id
""",
    72: """
SELECT i_item_desc, w_warehouse_name, d1.d_week_seq,
       sum(CASE WHEN p_promo_sk IS NULL THEN 1 ELSE 0 END)
           no_promo,
       sum(CASE WHEN p_promo_sk IS NOT NULL THEN 1 ELSE 0 END)
           promo,
       count(*) total_cnt
FROM catalog_sales
JOIN inventory ON (cs_item_sk = inv_item_sk)
JOIN warehouse ON (w_warehouse_sk = inv_warehouse_sk)
JOIN item ON (i_item_sk = cs_item_sk)
JOIN customer_demographics ON (cs_bill_cdemo_sk = cd_demo_sk)
JOIN household_demographics ON (cs_bill_hdemo_sk = hd_demo_sk)
JOIN date_dim d1 ON (cs_sold_date_sk = d1.d_date_sk)
JOIN date_dim d2 ON (inv_date_sk = d2.d_date_sk)
JOIN date_dim d3 ON (cs_ship_date_sk = d3.d_date_sk)
LEFT OUTER JOIN promotion ON (cs_promo_sk = p_promo_sk)
LEFT OUTER JOIN catalog_returns
    ON (cr_item_sk = cs_item_sk
        AND cr_order_number = cs_order_number)
WHERE d1.d_week_seq = d2.d_week_seq
  AND inv_quantity_on_hand < cs_quantity
  AND d3.d_date > d1.d_date + interval '5' day
  AND hd_buy_potential = '>10000'
  AND d1.d_year = 1999
  AND cd_marital_status = 'D'
GROUP BY i_item_desc, w_warehouse_name, d1.d_week_seq
ORDER BY total_cnt DESC, i_item_desc, w_warehouse_name,
         d1.d_week_seq
LIMIT 100
""",
    75: """
WITH all_sales AS (
  SELECT d_year, i_brand_id, i_class_id, i_category_id,
         i_manufact_id,
         sum(sales_cnt) sales_cnt, sum(sales_amt) sales_amt
  FROM (SELECT d_year, i_brand_id, i_class_id, i_category_id,
               i_manufact_id,
               cs_quantity - coalesce(cr_return_quantity, 0)
                   sales_cnt,
               cs_ext_sales_price
                   - coalesce(cr_return_amount, 0.0) sales_amt
        FROM catalog_sales
        JOIN item ON i_item_sk = cs_item_sk
        JOIN date_dim ON d_date_sk = cs_sold_date_sk
        LEFT JOIN catalog_returns
            ON (cs_order_number = cr_order_number
                AND cs_item_sk = cr_item_sk)
        WHERE i_category = 'Books'
        UNION
        SELECT d_year, i_brand_id, i_class_id, i_category_id,
               i_manufact_id,
               ss_quantity - coalesce(sr_return_quantity, 0),
               ss_ext_sales_price - coalesce(sr_return_amt, 0.0)
        FROM store_sales
        JOIN item ON i_item_sk = ss_item_sk
        JOIN date_dim ON d_date_sk = ss_sold_date_sk
        LEFT JOIN store_returns
            ON (ss_ticket_number = sr_ticket_number
                AND ss_item_sk = sr_item_sk)
        WHERE i_category = 'Books'
        UNION
        SELECT d_year, i_brand_id, i_class_id, i_category_id,
               i_manufact_id,
               ws_quantity - coalesce(wr_return_quantity, 0),
               ws_ext_sales_price - coalesce(wr_return_amt, 0.0)
        FROM web_sales
        JOIN item ON i_item_sk = ws_item_sk
        JOIN date_dim ON d_date_sk = ws_sold_date_sk
        LEFT JOIN web_returns
            ON (ws_order_number = wr_order_number
                AND ws_item_sk = wr_item_sk)
        WHERE i_category = 'Books') sales_detail
  GROUP BY d_year, i_brand_id, i_class_id, i_category_id,
           i_manufact_id)
SELECT prev_yr.d_year prev_year, curr_yr.d_year year_,
       curr_yr.i_brand_id, curr_yr.i_class_id,
       curr_yr.i_category_id, curr_yr.i_manufact_id,
       prev_yr.sales_cnt prev_yr_cnt,
       curr_yr.sales_cnt curr_yr_cnt,
       curr_yr.sales_cnt - prev_yr.sales_cnt sales_cnt_diff,
       curr_yr.sales_amt - prev_yr.sales_amt sales_amt_diff
FROM all_sales curr_yr, all_sales prev_yr
WHERE curr_yr.i_brand_id = prev_yr.i_brand_id
  AND curr_yr.i_class_id = prev_yr.i_class_id
  AND curr_yr.i_category_id = prev_yr.i_category_id
  AND curr_yr.i_manufact_id = prev_yr.i_manufact_id
  AND curr_yr.d_year = 2002
  AND prev_yr.d_year = 2002 - 1
  AND cast(curr_yr.sales_cnt AS double)
      / cast(prev_yr.sales_cnt AS double) < 0.9
ORDER BY sales_cnt_diff, sales_amt_diff
LIMIT 100
""",
    77: """
WITH ss AS (
  SELECT s_store_sk, sum(ss_ext_sales_price) sales,
         sum(ss_net_profit) profit
  FROM store_sales, date_dim, store
  WHERE ss_sold_date_sk = d_date_sk
    AND d_date BETWEEN DATE '2000-08-23' AND DATE '2000-09-22'
    AND ss_store_sk = s_store_sk
  GROUP BY s_store_sk),
sr AS (
  SELECT s_store_sk, sum(sr_return_amt) returns_,
         sum(sr_net_loss) profit_loss
  FROM store_returns, date_dim, store
  WHERE sr_returned_date_sk = d_date_sk
    AND d_date BETWEEN DATE '2000-08-23' AND DATE '2000-09-22'
    AND sr_store_sk = s_store_sk
  GROUP BY s_store_sk),
cs AS (
  SELECT cs_call_center_sk, sum(cs_ext_sales_price) sales,
         sum(cs_net_profit) profit
  FROM catalog_sales, date_dim
  WHERE cs_sold_date_sk = d_date_sk
    AND d_date BETWEEN DATE '2000-08-23' AND DATE '2000-09-22'
  GROUP BY cs_call_center_sk),
cr AS (
  SELECT cr_call_center_sk, sum(cr_return_amount) returns_,
         sum(cr_net_loss) profit_loss
  FROM catalog_returns, date_dim
  WHERE cr_returned_date_sk = d_date_sk
    AND d_date BETWEEN DATE '2000-08-23' AND DATE '2000-09-22'
  GROUP BY cr_call_center_sk),
ws AS (
  SELECT wp_web_page_sk, sum(ws_ext_sales_price) sales,
         sum(ws_net_profit) profit
  FROM web_sales, date_dim, web_page
  WHERE ws_sold_date_sk = d_date_sk
    AND d_date BETWEEN DATE '2000-08-23' AND DATE '2000-09-22'
    AND ws_web_page_sk = wp_web_page_sk
  GROUP BY wp_web_page_sk),
wr AS (
  SELECT wp_web_page_sk, sum(wr_return_amt) returns_,
         sum(wr_net_loss) profit_loss
  FROM web_returns, date_dim, web_page
  WHERE wr_returned_date_sk = d_date_sk
    AND d_date BETWEEN DATE '2000-08-23' AND DATE '2000-09-22'
    AND wr_web_page_sk = wp_web_page_sk
  GROUP BY wp_web_page_sk)
SELECT channel, id, sum(sales) sales, sum(returns_) returns_,
       sum(profit) profit
FROM (SELECT 'store channel' AS channel, ss.s_store_sk AS id,
             sales, coalesce(returns_, 0) returns_,
             profit - coalesce(profit_loss, 0) profit
      FROM ss
      LEFT JOIN sr ON ss.s_store_sk = sr.s_store_sk
      UNION ALL
      SELECT 'catalog channel', cs_call_center_sk,
             sales, returns_, profit - profit_loss
      FROM cs, cr
      UNION ALL
      SELECT 'web channel', ws.wp_web_page_sk,
             sales, coalesce(returns_, 0),
             profit - coalesce(profit_loss, 0)
      FROM ws
      LEFT JOIN wr ON ws.wp_web_page_sk = wr.wp_web_page_sk) x
GROUP BY ROLLUP (channel, id)
ORDER BY channel NULLS LAST, id NULLS LAST, sales
LIMIT 100
""",
    78: """
WITH ws AS (
  SELECT d_year AS ws_sold_year, ws_item_sk,
         ws_bill_customer_sk ws_customer_sk,
         sum(ws_quantity) ws_qty, sum(ws_wholesale_cost) ws_wc,
         sum(ws_sales_price) ws_sp
  FROM web_sales
  LEFT JOIN web_returns ON wr_order_number = ws_order_number
                        AND ws_item_sk = wr_item_sk
  JOIN date_dim ON ws_sold_date_sk = d_date_sk
  WHERE wr_order_number IS NULL
  GROUP BY d_year, ws_item_sk, ws_bill_customer_sk),
cs AS (
  SELECT d_year AS cs_sold_year, cs_item_sk,
         cs_bill_customer_sk cs_customer_sk,
         sum(cs_quantity) cs_qty, sum(cs_wholesale_cost) cs_wc,
         sum(cs_sales_price) cs_sp
  FROM catalog_sales
  LEFT JOIN catalog_returns ON cr_order_number = cs_order_number
                            AND cs_item_sk = cr_item_sk
  JOIN date_dim ON cs_sold_date_sk = d_date_sk
  WHERE cr_order_number IS NULL
  GROUP BY d_year, cs_item_sk, cs_bill_customer_sk),
ss AS (
  SELECT d_year AS ss_sold_year, ss_item_sk,
         ss_customer_sk,
         sum(ss_quantity) ss_qty, sum(ss_wholesale_cost) ss_wc,
         sum(ss_sales_price) ss_sp
  FROM store_sales
  LEFT JOIN store_returns ON sr_ticket_number = ss_ticket_number
                          AND ss_item_sk = sr_item_sk
  JOIN date_dim ON ss_sold_date_sk = d_date_sk
  WHERE sr_ticket_number IS NULL
  GROUP BY d_year, ss_item_sk, ss_customer_sk)
SELECT ss_customer_sk,
       round(ss_qty * 1.00
             / (coalesce(ws_qty, 0) + coalesce(cs_qty, 0) + 1),
             2) ratio,
       ss_qty store_qty, ss_wc store_wholesale_cost,
       ss_sp store_sales_price,
       coalesce(ws_qty, 0) + coalesce(cs_qty, 0)
           other_chan_qty,
       coalesce(ws_wc, 0) + coalesce(cs_wc, 0)
           other_chan_wholesale_cost,
       coalesce(ws_sp, 0) + coalesce(cs_sp, 0)
           other_chan_sales_price
FROM ss
LEFT JOIN ws ON (ws_sold_year = ss_sold_year
                 AND ws_item_sk = ss_item_sk
                 AND ws_customer_sk = ss_customer_sk)
LEFT JOIN cs ON (cs_sold_year = ss_sold_year
                 AND cs_item_sk = ss_item_sk
                 AND cs_customer_sk = ss_customer_sk)
WHERE (coalesce(ws_qty, 0) > 0 OR coalesce(cs_qty, 0) > 0)
  AND ss_sold_year = 2000
ORDER BY ss_customer_sk, ss_qty DESC, ss_wc DESC, ss_sp DESC,
         other_chan_qty, other_chan_wholesale_cost,
         other_chan_sales_price, ratio
LIMIT 100
""",
    80: """
WITH ssr AS (
  SELECT s_store_id AS store_id,
         sum(ss_ext_sales_price) AS sales,
         sum(coalesce(sr_return_amt, 0)) AS returns_,
         sum(ss_net_profit - coalesce(sr_net_loss, 0)) AS profit
  FROM store_sales
  LEFT OUTER JOIN store_returns
      ON (ss_item_sk = sr_item_sk
          AND ss_ticket_number = sr_ticket_number),
       date_dim, store, item, promotion
  WHERE ss_sold_date_sk = d_date_sk
    AND d_date BETWEEN DATE '2000-08-23' AND DATE '2000-09-22'
    AND ss_store_sk = s_store_sk
    AND ss_item_sk = i_item_sk
    AND i_current_price > 50
    AND ss_promo_sk = p_promo_sk
    AND p_channel_tv = 'N'
  GROUP BY s_store_id),
csr AS (
  SELECT cp_catalog_page_id AS catalog_page_id,
         sum(cs_ext_sales_price) AS sales,
         sum(coalesce(cr_return_amount, 0)) AS returns_,
         sum(cs_net_profit - coalesce(cr_net_loss, 0)) AS profit
  FROM catalog_sales
  LEFT OUTER JOIN catalog_returns
      ON (cs_item_sk = cr_item_sk
          AND cs_order_number = cr_order_number),
       date_dim, catalog_page, item, promotion
  WHERE cs_sold_date_sk = d_date_sk
    AND d_date BETWEEN DATE '2000-08-23' AND DATE '2000-09-22'
    AND cs_catalog_page_sk = cp_catalog_page_sk
    AND cs_item_sk = i_item_sk
    AND i_current_price > 50
    AND cs_promo_sk = p_promo_sk
    AND p_channel_tv = 'N'
  GROUP BY cp_catalog_page_id),
wsr AS (
  SELECT web_site_id,
         sum(ws_ext_sales_price) AS sales,
         sum(coalesce(wr_return_amt, 0)) AS returns_,
         sum(ws_net_profit - coalesce(wr_net_loss, 0)) AS profit
  FROM web_sales
  LEFT OUTER JOIN web_returns
      ON (ws_item_sk = wr_item_sk
          AND ws_order_number = wr_order_number),
       date_dim, web_site, item, promotion
  WHERE ws_sold_date_sk = d_date_sk
    AND d_date BETWEEN DATE '2000-08-23' AND DATE '2000-09-22'
    AND ws_web_site_sk = web_site_sk
    AND ws_item_sk = i_item_sk
    AND i_current_price > 50
    AND ws_promo_sk = p_promo_sk
    AND p_channel_tv = 'N'
  GROUP BY web_site_id)
SELECT channel, id, sum(sales) sales, sum(returns_) returns_,
       sum(profit) profit
FROM (SELECT 'store channel' AS channel,
             'store' || store_id AS id, sales, returns_, profit
      FROM ssr
      UNION ALL
      SELECT 'catalog channel',
             'catalog_page' || catalog_page_id,
             sales, returns_, profit
      FROM csr
      UNION ALL
      SELECT 'web channel', 'web_site' || web_site_id,
             sales, returns_, profit
      FROM wsr) x
GROUP BY ROLLUP (channel, id)
ORDER BY channel NULLS LAST, id NULLS LAST
LIMIT 100
""",
}
