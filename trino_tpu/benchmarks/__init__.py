"""Benchmark workloads (TPC-H / TPC-DS query texts and harnesses).

Reference parity: testing/trino-benchto-benchmarks (macro SQL suites) and
testing/trino-benchmark (hand-coded operator pipelines).
"""

from .tpch_queries import TPCH_QUERIES  # noqa: F401
