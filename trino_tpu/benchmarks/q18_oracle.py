"""Independent numpy oracle for TPC-H q18 at any scale factor.

Recomputes the q18 result straight from the generator's field functions
(no engine code in the loop) so engine runs at sf >= 1 — beyond what
the sqlite oracle tier can hold — still have an exact cross-check.
Reference measurement shape: BASELINE configs[3] (q18 large build-side
join + IN-subquery semi-join); validated the engine's q18@sf10 run
(100 rows, 2026-07-31) row-for-row.
"""

from __future__ import annotations

from typing import List

import numpy as np

from ..connectors.tpch import (_LineFields, _cust_key, _line_counts,
                               _order_date, _order_key, table_rows)


def q18_oracle(sf: float, limit: int = 100,
               qty_bar: float = 300.0) -> List[list]:
    """[[c_name, c_custkey, o_orderkey, o_orderdate(days), o_totalprice,
    sum_qty], ...] — q18's exact result, top ``limit`` by
    (totalprice DESC, orderdate ASC). Field values come from the
    connector's own _LineFields (one definition of the lineitem
    layout); only the per-order aggregation is local."""
    n_orders = table_rows("orders", sf)
    qty_sum = np.zeros(n_orders + 1, np.float64)
    total = np.zeros(n_orders + 1, np.float64)
    chunk = 1 << 21
    for lo in range(0, n_orders, chunk):
        hi = min(lo + chunk, n_orders)
        idx = np.arange(lo + 1, hi + 1, dtype=np.int64)
        counts = _line_counts(idx)
        order_rep = np.repeat(idx, counts)
        line_no = np.concatenate(
            [np.arange(1, c + 1) for c in counts]).astype(np.int64)
        lf = _LineFields(order_rep, line_no, sf)
        price = (lf.extendedprice * (1.0 + lf.tax)
                 * (1.0 - lf.discount))
        np.add.at(qty_sum, order_rep, lf.quantity)
        np.add.at(total, order_rep, price)
        total[lo + 1:hi + 1] = np.round(total[lo + 1:hi + 1], 2)
    sel = np.nonzero(qty_sum > qty_bar)[0]
    okey = _order_key(sel)
    ckey = _cust_key(sel, table_rows("customer", sf))
    odate = _order_date(sel)
    tp = total[sel]
    order = np.lexsort((odate, -tp))[:limit]
    return [[f"Customer#{ckey[i]:09d}", int(ckey[i]), int(okey[i]),
             int(odate[i]), float(tp[i]), float(qty_sum[sel[i]])]
            for i in order]
