"""Streaming ingestion + continuous queries (ROADMAP item 5).

Three layers over the batch engine:

- ``log.py``: an append-only partitioned message log — the in-process
  broker of a kafka/redis-class connector, durable as length-prefixed
  segment files under ``CONFIG.stream_dir`` so coordinator and worker
  PROCESSES share one log through the filesystem.
- ``offsets.py``: per-consumer committed offsets spooled under the
  reserved fragment -3 (first-commit-wins per epoch), so incremental
  scans resume from the committed watermark instead of offset 0 and
  re-ingestion after a crash is idempotent up to the last sealed epoch.
- ``continuous.py``: long-lived INSERT INTO ... SELECT jobs and
  periodic-refresh (optionally watermarked, windowed) materialized
  views that re-dispatch the incremental plan on a cadence through the
  coordinator's normal query tracker — every cycle is a real tracked
  query riding the stage DAG, FTE retries, and observability.

The SQL-visible half is ``connectors/stream.py`` (catalog ``stream``):
topics are tables decoded through ``formats/record_decoder.py``, splits
are per-partition offset ranges, and an exact offset window can be
pinned into the table NAME (``"t$win.<p>:<s>:<e>,...#<consumer>"``) so
it rides the serialized plan to any worker process.
"""

from .log import MessageLog, get_log  # noqa: F401
from .offsets import OffsetStore  # noqa: F401
