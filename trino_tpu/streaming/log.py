"""Append-only partitioned message log — the in-process broker.

Reference parity: the kafka connector's broker surface collapsed to
what the engine consumes (plugin/trino-kafka KafkaConsumerManager +
topic metadata): topics hold N partitions, a partition is a strictly
ordered sequence of byte messages addressed by offset, producers
append, consumers read half-open offset ranges.

Durability/layout: ``<base>/<topic>/topic.json`` (decoder kind +
fields + partition count, written once at topic creation) and one
segment file per partition, ``<base>/<topic>/p<k>.log``, holding
``[4-byte BE length][payload]`` frames. Appends go through one
``os.write`` on an ``O_APPEND`` fd — the frame lands atomically at the
tail, so concurrent producers (ingest HTTP threads here, a worker
process next door) interleave whole messages, never bytes. Readers
keep an in-memory offset index per partition and extend it by
scanning only the bytes appended since their last scan, which is what
makes a coordinator see a worker-side ingest (and vice versa) without
any broker-to-broker protocol: the filesystem IS the replication.

``get_log()`` returns the process-wide broker for a base dir — the
ingest HTTP route, the stream connector's scans and the continuous
scheduler must observe one index, not three.
"""

from __future__ import annotations

import json
import os
import struct
import threading
from typing import Dict, List, Optional, Sequence, Tuple

from ..config import CONFIG
from ..fte.faultpoints import fault_point
from ..obs.metrics import INGEST_BYTES, INGEST_ROWS

_LEN = struct.Struct(">I")

# topic.json field spec: [name, type string, mapping or None]
TopicFields = List[Tuple[str, str, Optional[str]]]


class _Partition:
    """One partition's segment file + its offset index."""

    def __init__(self, path: str):
        self.path = path
        self.lock = threading.Lock()
        # byte position of each record's frame start; positions[i] is
        # the frame of offset i. Extended by _refresh scans only.
        self._positions: List[int] = []
        self._scanned = 0            # bytes of self.path fully indexed

    def _refresh_locked(self) -> None:
        try:
            size = os.path.getsize(self.path)
        except OSError:
            return
        if size <= self._scanned:
            return
        with open(self.path, "rb") as f:
            f.seek(self._scanned)
            pos = self._scanned
            buf = f.read(size - self._scanned)
        i = 0
        while i + _LEN.size <= len(buf):
            (n,) = _LEN.unpack_from(buf, i)
            if i + _LEN.size + n > len(buf):
                break                # torn tail: re-scan next time
            self._positions.append(pos + i)
            i += _LEN.size + n
        self._scanned = pos + i

    def end_offset(self) -> int:
        with self.lock:
            self._refresh_locked()
            return len(self._positions)

    def append(self, messages: Sequence[bytes],
               fsync: bool) -> Tuple[int, int]:
        """Append messages; returns the [start, end) offsets covered.
        The whole batch is ONE O_APPEND write: a killed producer
        leaves at most one torn frame at the tail, which the index
        scan refuses to step past."""
        frame = b"".join(_LEN.pack(len(m)) + m for m in messages)
        with self.lock:
            self._refresh_locked()
            fd = os.open(self.path,
                         os.O_WRONLY | os.O_APPEND | os.O_CREAT, 0o600)
            try:
                os.write(fd, frame)
                if fsync:
                    os.fsync(fd)
            finally:
                os.close(fd)
            self._refresh_locked()
            end = len(self._positions)
            return end - len(messages), end

    def read(self, start: int, end: int) -> List[bytes]:
        with self.lock:
            self._refresh_locked()
            end = min(end, len(self._positions))
            if start >= end:
                return []
            first = self._positions[start]
        out: List[bytes] = []
        with open(self.path, "rb") as f:
            f.seek(first)
            for _ in range(end - start):
                (n,) = _LEN.unpack(f.read(_LEN.size))
                out.append(f.read(n))
        return out


class MessageLog:
    """All topics under one base dir; safe for concurrent producers
    and consumers across threads AND processes (see module doc)."""

    def __init__(self, base_dir: Optional[str] = None):
        self.base_dir = base_dir or CONFIG.stream_dir
        self._lock = threading.Lock()
        self._topics: Dict[str, dict] = {}       # topic -> config
        self._parts: Dict[Tuple[str, int], _Partition] = {}
        self._rr: Dict[str, int] = {}            # round-robin cursor

    # --- topic management ------------------------------------------------
    def _topic_dir(self, topic: str) -> str:
        # topics become path components and table names: reject
        # separators and the window-suffix marker outright
        if (not topic or "/" in topic or "\\" in topic or "$" in topic
                or topic.startswith(".")):
            raise ValueError(f"invalid topic name {topic!r}")
        return os.path.join(self.base_dir, topic)

    def create_topic(self, topic: str, decoder: str = "json",
                     fields: Optional[TopicFields] = None,
                     partitions: Optional[int] = None) -> dict:
        """Idempotent: an existing topic's config wins (first writer
        seals it via O_EXCL, racers adopt the winner)."""
        d = self._topic_dir(topic)
        cfg = {"topic": topic, "decoder": decoder,
               "fields": [list(f) for f in (fields or [])],
               "partitions": int(partitions
                                 or CONFIG.stream_partitions)}
        os.makedirs(d, exist_ok=True)
        path = os.path.join(d, "topic.json")
        try:
            fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_EXCL,
                         0o600)
            with os.fdopen(fd, "w") as f:
                json.dump(cfg, f)
        except FileExistsError:
            pass
        return self.topic_config(topic)

    def topic_config(self, topic: str) -> Optional[dict]:
        with self._lock:
            cfg = self._topics.get(topic)
        if cfg is not None:
            return cfg
        try:
            with open(os.path.join(self._topic_dir(topic),
                                   "topic.json")) as f:
                cfg = json.load(f)
        except (OSError, ValueError):
            return None
        with self._lock:
            self._topics.setdefault(topic, cfg)
            return self._topics[topic]

    def topics(self) -> List[str]:
        try:
            names = os.listdir(self.base_dir)
        except OSError:
            return []
        return sorted(t for t in names
                      if self.topic_config(t) is not None)

    def drop_topic(self, topic: str) -> None:
        d = self._topic_dir(topic)
        import shutil
        shutil.rmtree(d, ignore_errors=True)
        with self._lock:
            self._topics.pop(topic, None)
            for k in [k for k in self._parts if k[0] == topic]:
                self._parts.pop(k)

    # --- data plane ------------------------------------------------------
    def _partition(self, topic: str, part: int) -> _Partition:
        key = (topic, part)
        with self._lock:
            p = self._parts.get(key)
            if p is None:
                p = _Partition(os.path.join(self._topic_dir(topic),
                                            f"p{part}.log"))
                self._parts[key] = p
            return p

    def append(self, topic: str, messages: Sequence[bytes],
               partition: Optional[int] = None,
               key: Optional[str] = None) -> Dict[int, Tuple[int, int]]:
        """Append messages to one partition (explicit ``partition``,
        hash of ``key``, else round-robin). Returns
        {partition: (start, end)}. Implicitly creates an unknown topic
        with the default json decoder (schemaless until CREATE TABLE /
        create_topic declares fields)."""
        cfg = self.topic_config(topic) or self.create_topic(topic)
        nparts = int(cfg.get("partitions") or 1)
        if partition is None:
            if key is not None:
                # stable across processes (hash() is seed-randomized)
                import zlib
                partition = zlib.crc32(key.encode()) % nparts
            else:
                with self._lock:
                    partition = self._rr.get(topic, 0) % nparts
                    self._rr[topic] = partition + 1
        elif not 0 <= partition < nparts:
            raise ValueError(
                f"partition {partition} out of range for topic "
                f"{topic!r} ({nparts} partitions)")
        # chaos site: a crash here is a producer dying BEFORE the
        # frame lands — the at-least-once retry case; a crash between
        # append and the producer's HTTP response is the duplicate
        # case the offset-windowed reader dedupes by position
        fault_point("stream.pre_append")
        messages = [m if isinstance(m, bytes) else bytes(m)
                    for m in messages]
        rng = self._partition(topic, partition).append(
            messages, CONFIG.stream_fsync)
        INGEST_ROWS.inc(len(messages), topic=topic)
        INGEST_BYTES.inc(sum(len(m) for m in messages), topic=topic)
        return {partition: rng}

    def end_offsets(self, topic: str) -> Dict[int, int]:
        cfg = self.topic_config(topic)
        if cfg is None:
            return {}
        return {p: self._partition(topic, p).end_offset()
                for p in range(int(cfg.get("partitions") or 1))}

    def read(self, topic: str, partition: int, start: int,
             end: int) -> List[bytes]:
        return self._partition(topic, partition).read(start, end)

    def data_version(self) -> int:
        """Monotonic over appends (result-cache invalidation): total
        indexed bytes across every partition segment on disk."""
        total = 0
        for t in self.topics():
            d = self._topic_dir(t)
            try:
                for n in os.listdir(d):
                    if n.startswith("p") and n.endswith(".log"):
                        total += os.path.getsize(os.path.join(d, n))
            except OSError:
                pass
        return total


def ingest_http(log: "MessageLog", topic: str, body: bytes,
                params: Dict[str, list]) -> dict:
    """The one POST /v1/ingest/{topic} implementation shared by the
    coordinator and the task worker: newline-delimited messages in the
    body (empty lines skipped), optional ``partition`` / ``key`` query
    params routing the whole batch."""
    messages = [ln for ln in body.split(b"\n") if ln]
    partition = (int(params["partition"][0])
                 if params.get("partition") else None)
    key = params["key"][0] if params.get("key") else None
    ranges: Dict[int, Tuple[int, int]] = {}
    if messages:
        ranges = log.append(topic, messages, partition=partition,
                            key=key)
    return {"topic": topic, "count": len(messages),
            "ranges": {str(p): [s, e]
                       for p, (s, e) in ranges.items()},
            "endOffsets": {str(p): e
                           for p, e in log.end_offsets(topic).items()}}


_LOGS: Dict[str, MessageLog] = {}
_LOGS_LOCK = threading.Lock()


def get_log(base_dir: Optional[str] = None) -> MessageLog:
    """The process-wide broker for a base dir (see module doc)."""
    base = os.path.abspath(base_dir or CONFIG.stream_dir)
    with _LOGS_LOCK:
        log = _LOGS.get(base)
        if log is None:
            log = MessageLog(base)
            _LOGS[base] = log
        return log
