"""Spool-backed consumer offset store — the ingest watermark ledger.

Reference parity: Kafka's ``__consumer_offsets`` topic, rebuilt on the
FTE spool (fte/spool.py) so every backend — local dir AND the
object-store shape — works unchanged and a replacement coordinator on
the same spool resumes consumers where the dead one sealed them.

Addressing: consumer ``c`` commits under query id ``stream.c`` and the
reserved fragment -3 (-1 = persisted results, -2 = execution
manifests), one spool PART per monotonically increasing EPOCH. An
epoch's frame is the JSON offsets map {topic: {partition: next
offset}} as of the END of that cycle. First-commit-wins per
(consumer, epoch) is the idempotency mechanism: two racing cycle
drivers (a coordinator failing over mid-commit, a retried cycle) can
both attempt epoch N but only one frame seals, and the loser reads
the winner's watermark back instead of double-advancing.

``load`` probes epochs UPWARD from the last one this process saw —
O(new epochs), not O(history) — so a continuous job polling every few
hundred ms pays one spool read per cycle, not a scan.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Dict, Optional, Tuple

from ..fte.faultpoints import fault_point
from ..fte.spool import SpoolManager
from ..obs.metrics import OFFSET_COMMITS

# reserved spool fragment for consumer offsets (see fte/spool.py:
# -1 persisted results, -2 execution manifests)
OFFSETS_FRAGMENT = -3

# {topic: {partition: next offset to read}}
Offsets = Dict[str, Dict[int, int]]


def _qid(consumer: str) -> str:
    if not consumer or "/" in consumer:
        raise ValueError(f"invalid consumer name {consumer!r}")
    return f"stream.{consumer}"


class OffsetStore:
    def __init__(self, spool: SpoolManager):
        self.spool = spool
        self._lock = threading.Lock()
        self._last: Dict[str, Tuple[int, Offsets]] = {}  # cache

    def commit(self, consumer: str, epoch: int,
               offsets: Offsets) -> bool:
        """Seal ``offsets`` as consumer's epoch. Returns True when
        THIS commit won the epoch, False when an earlier one already
        had (the caller should reload and resume from the winner)."""
        # chaos site: a crash here is the cycle dying AFTER its data
        # landed but BEFORE the watermark advanced — the next cycle
        # re-covers the same window (at-least-once across the gap)
        fault_point("stream.pre_offset_commit")
        frame = json.dumps({"epoch": int(epoch),
                            "offsets": offsets}).encode()
        # attempt id = pid: distinct racers get distinct attempts, so
        # the returned winner tells us whether OUR frame sealed
        attempt = os.getpid()
        win = self.spool.commit(_qid(consumer), OFFSETS_FRAGMENT,
                                int(epoch), attempt, [frame])
        won = win == attempt
        OFFSET_COMMITS.inc(
            outcome="committed" if won else "superseded")
        if won:
            with self._lock:
                last = self._last.get(consumer)
                if last is None or last[0] < epoch:
                    self._last[consumer] = (int(epoch), offsets)
        return won

    def _read_epoch(self, consumer: str,
                    epoch: int) -> Optional[Offsets]:
        frames = self.spool.read(_qid(consumer), OFFSETS_FRAGMENT,
                                 int(epoch))
        if not frames:
            return None
        try:
            doc = json.loads(frames[0])
            return {t: {int(p): int(o) for p, o in parts.items()}
                    for t, parts in doc.get("offsets", {}).items()}
        except (ValueError, AttributeError):
            return None

    def load(self, consumer: str) -> Tuple[int, Offsets]:
        """(last committed epoch, its offsets); (0, {}) when the
        consumer has never committed. Epochs start at 1."""
        with self._lock:
            epoch, offs = self._last.get(consumer, (0, {}))
        while True:
            nxt = self._read_epoch(consumer, epoch + 1)
            if nxt is None:
                break
            epoch, offs = epoch + 1, nxt
        with self._lock:
            last = self._last.get(consumer)
            if last is None or last[0] < epoch:
                self._last[consumer] = (epoch, offs)
        return epoch, offs

    def release(self, consumer: str) -> None:
        """Drop a canceled consumer's ledger."""
        self.spool.release(_qid(consumer))
        with self._lock:
            self._last.pop(consumer, None)
