"""Continuous queries: long-lived jobs re-dispatching incremental plans.

Three job kinds, all driven by one per-job scheduler thread on a
``stream_poll_interval_ms`` cadence, every cycle a REAL query through
the submit function the coordinator wires in (so cycles ride the
stage DAG, FTE retries, resource groups and show up in
``system.runtime.queries`` with source ``continuous``):

- ``insert`` — exactly-once incremental ``INSERT INTO ... SELECT``:
  each cycle snapshots the log's end offsets, pins the half-open
  window into the stream table reference (connectors/stream.py
  ``window_ref`` — the window rides the plan through serde, so every
  task retry reads identical rows), runs the INSERT, and only then
  commits the advanced offsets (streaming/offsets.py, epoch = cycle).
  A worker killed mid-ingest is retried WITHIN the cycle's query by
  the FTE machinery — same window, zero duplicated, zero lost rows. A
  coordinator crash in the gap between INSERT success and offset
  commit re-covers that one window (at-least-once across failover —
  the classic non-transactional-sink boundary, documented, not
  hidden).
- ``view`` — periodic-refresh materialized view: each cycle fully
  recomputes the SELECT and atomically swaps the target table
  (MemoryConnector.replace).
- ``window`` — watermarked windowed aggregation: an exactly-once
  incremental copy of the stream lands in a staging table (same
  offset machinery as ``insert``), the watermark advances to
  ``max(event time) - lateness``, and the view SQL — with
  ``{watermark}`` substituted and the stream reference redirected to
  staging — recomputes the target. Late arrivals within lateness
  re-aggregate on the next cycle because finalization is driven by
  the watermark predicate in the job's own SQL.

Durability: every state transition appends the full job record to a
JSONL ledger next to the coordinator's history dir; a replacement
coordinator on the same spool replays the ledger (last record per job
wins) and restarts RUNNING jobs, whose consumers resume from their
committed offset epochs — the PR 17 failover story extended to jobs.
"""

from __future__ import annotations

import json
import os
import threading
import time
import uuid
from typing import Callable, Dict, List, Optional

from ..catalog import ColumnMetadata, TableMetadata
from ..columnar import batch_from_pylist
from ..config import CONFIG
from ..obs.metrics import CONTINUOUS_CYCLES, CONTINUOUS_JOBS
from .log import MessageLog, get_log
from .offsets import OffsetStore
from ..connectors.stream import window_ref

_KINDS = ("insert", "view", "window")
# consecutive failed cycles before a job is declared FAILED (a single
# transient cycle failure — a killed worker, a full queue — must not
# kill a long-lived job)
_MAX_CONSECUTIVE_FAILURES = 5


class ContinuousJob:
    def __init__(self, job_id: str, spec: dict):
        self.job_id = job_id
        self.kind = spec["kind"]
        self.sql = spec["sql"]
        self.topic = spec.get("topic", "")
        self.target = spec.get("target", "")
        self.stream_table = spec.get(
            "stream_table",
            f"stream.default.{self.topic}" if self.topic else "")
        self.poll_ms = int(spec.get("poll_interval_ms")
                           or CONFIG.stream_poll_interval_ms)
        self.ts_column = spec.get("ts_column", "")
        self.lateness_ms = int(spec.get("lateness_ms")
                               or CONFIG.stream_lateness_ms)
        self.state = spec.get("state", "RUNNING")
        self.created = float(spec.get("created") or time.time())
        self.cycles = int(spec.get("cycles") or 0)
        self.rows_total = int(spec.get("rows_total") or 0)
        self.last_epoch = int(spec.get("last_epoch") or 0)
        self.watermark: Optional[float] = spec.get("watermark")
        self.last_error = spec.get("last_error", "")
        self._failures = 0
        self._stop = threading.Event()

    def to_dict(self) -> dict:
        return {"job_id": self.job_id, "kind": self.kind,
                "sql": self.sql, "topic": self.topic,
                "target": self.target,
                "stream_table": self.stream_table,
                "poll_interval_ms": self.poll_ms,
                "ts_column": self.ts_column,
                "lateness_ms": self.lateness_ms,
                "state": self.state, "created": self.created,
                "cycles": self.cycles,
                "rows_total": self.rows_total,
                "last_epoch": self.last_epoch,
                "watermark": self.watermark,
                "last_error": self.last_error}


def _split_fqn(fqn: str):
    parts = fqn.split(".")
    if len(parts) != 3:
        raise ValueError(
            f"expected catalog.schema.table, got {fqn!r}")
    return parts[0], parts[1], parts[2]


class ContinuousQueryManager:
    """Owns every job's scheduler thread + the durable job ledger.

    ``run_sql(sql) -> QueryResult`` raises on failure; the coordinator
    wires it to tracker.submit + wait (cycles are tracked queries), a
    bare runner works for unit tests. ``catalogs`` is only consulted
    for the view/window REPLACE primitive."""

    def __init__(self, run_sql: Callable, catalogs,
                 offsets: OffsetStore,
                 jobs_path: Optional[str] = None,
                 log: Optional[MessageLog] = None):
        self.run_sql = run_sql
        self.catalogs = catalogs
        self.offsets = offsets
        self.log = log or get_log()
        self.jobs_path = jobs_path
        self._jobs: Dict[str, ContinuousJob] = {}
        self._threads: Dict[str, threading.Thread] = {}
        self._lock = threading.Lock()
        self._shutdown = threading.Event()

    # --- ledger ----------------------------------------------------------
    def _persist(self, job: ContinuousJob) -> None:
        if not self.jobs_path:
            return
        os.makedirs(os.path.dirname(self.jobs_path), exist_ok=True)
        with open(self.jobs_path, "a") as f:
            f.write(json.dumps(job.to_dict()) + "\n")

    def restart_jobs(self) -> int:
        """Boot-time replay (coordinator failover): last record per
        job wins; RUNNING jobs restart, their consumers resuming from
        committed offsets. Returns how many restarted."""
        if not self.jobs_path or not os.path.exists(self.jobs_path):
            return 0
        latest: Dict[str, dict] = {}
        with open(self.jobs_path) as f:
            for line in f:
                try:
                    rec = json.loads(line)
                    latest[rec["job_id"]] = rec
                except (ValueError, KeyError):
                    continue
        n = 0
        for rec in latest.values():
            if rec.get("state") != "RUNNING":
                continue
            job = ContinuousJob(rec["job_id"], rec)
            with self._lock:
                if job.job_id in self._jobs:
                    continue
                self._jobs[job.job_id] = job
            self._start_thread(job)
            n += 1
        return n

    # --- lifecycle -------------------------------------------------------
    def create(self, spec: dict) -> dict:
        kind = spec.get("kind")
        if kind not in _KINDS:
            raise ValueError(
                f"kind must be one of {_KINDS}, got {kind!r}")
        if not spec.get("sql"):
            raise ValueError("sql is required")
        if kind in ("insert", "window") and not spec.get("topic"):
            raise ValueError(f"{kind} jobs require a topic")
        if kind in ("view", "window"):
            _split_fqn(spec.get("target", ""))   # validates
        if kind == "window" and not spec.get("ts_column"):
            raise ValueError("window jobs require ts_column")
        job = ContinuousJob(
            f"cq_{time.strftime('%Y%m%d_%H%M%S')}_"
            f"{uuid.uuid4().hex[:6]}", spec)
        with self._lock:
            self._jobs[job.job_id] = job
        self._persist(job)
        self._start_thread(job)
        return job.to_dict()

    def _start_thread(self, job: ContinuousJob) -> None:
        t = threading.Thread(target=self._drive, args=(job,),
                             name=f"continuous-{job.job_id}",
                             daemon=True)
        self._threads[job.job_id] = t
        CONTINUOUS_JOBS.inc()
        t.start()

    def cancel(self, job_id: str) -> bool:
        with self._lock:
            job = self._jobs.get(job_id)
        if job is None:
            return False
        if job.state == "RUNNING":
            job.state = "CANCELED"
            self._persist(job)
        job._stop.set()
        return True

    def stop(self) -> None:
        """Coordinator shutdown: halt scheduler threads WITHOUT a
        state transition — jobs stay RUNNING in the ledger so the
        next coordinator restarts them."""
        self._shutdown.set()
        with self._lock:
            jobs = list(self._jobs.values())
        for j in jobs:
            j._stop.set()
        for t in self._threads.values():
            t.join(timeout=5.0)

    def get(self, job_id: str) -> Optional[dict]:
        with self._lock:
            job = self._jobs.get(job_id)
        return job.to_dict() if job else None

    def infos(self) -> List[dict]:
        with self._lock:
            return [j.to_dict() for j in self._jobs.values()]

    # --- the scheduler ---------------------------------------------------
    def _drive(self, job: ContinuousJob) -> None:
        try:
            while not job._stop.is_set() and job.state == "RUNNING":
                try:
                    advanced = self._cycle(job)
                    job._failures = 0
                    CONTINUOUS_CYCLES.inc(
                        outcome="advanced" if advanced else "idle")
                except Exception as e:   # noqa: BLE001 — job survives
                    job._failures += 1
                    job.last_error = f"{type(e).__name__}: {e}"[:500]
                    CONTINUOUS_CYCLES.inc(outcome="failed")
                    if job._failures >= _MAX_CONSECUTIVE_FAILURES:
                        job.state = "FAILED"
                        self._persist(job)
                        break
                job._stop.wait(job.poll_ms / 1000.0)
        finally:
            CONTINUOUS_JOBS.dec()

    def _pending_window(self, job: ContinuousJob):
        """(epoch to commit next, {partition: (start, end)}) — the
        exact rows this cycle owns, or None when fully caught up."""
        epoch, committed = self.offsets.load(job.job_id)
        start = committed.get(job.topic, {})
        ends = self.log.end_offsets(job.topic)
        window = {p: (start.get(p, 0), e) for p, e in ends.items()}
        if all(s >= e for s, e in window.values()):
            return None
        return epoch + 1, window

    def _windowed_ref(self, job: ContinuousJob, window) -> str:
        cat, schema, _ = _split_fqn(job.stream_table)
        topic_ref = window_ref(job.topic, window, job.job_id)
        return f'{cat}.{schema}."{topic_ref}"'

    def _rewrite(self, sql: str, job: ContinuousJob,
                 replacement: str) -> str:
        if job.stream_table not in sql:
            raise ValueError(
                f"job sql must reference {job.stream_table}")
        return sql.replace(job.stream_table, replacement)

    def _materialize(self, target: str, result) -> None:
        cat, schema, table = _split_fqn(target)
        conn = self.catalogs.connector(cat)
        batch = batch_from_pylist(
            {c: [row[i] for row in result.rows]
             for i, c in enumerate(result.columns)},
            dict(zip(result.columns, result.types)))
        if conn.get_table_metadata(schema, table) is None:
            conn.create_table(TableMetadata(schema, table, tuple(
                ColumnMetadata(c, t)
                for c, t in zip(result.columns, result.types))))
        conn.replace(schema, table, batch)

    def _commit(self, job: ContinuousJob, epoch: int,
                window) -> None:
        self.offsets.commit(
            job.job_id, epoch,
            {job.topic: {p: e for p, (_, e) in window.items()}})
        job.last_epoch = epoch

    def _cycle(self, job: ContinuousJob) -> bool:
        if job.kind == "view":
            res = self.run_sql(job.sql)
            self._materialize(job.target, res)
            job.cycles += 1
            job.rows_total += len(res.rows)
            return True
        pending = self._pending_window(job)
        if pending is None:
            return False
        epoch, window = pending
        ref = self._windowed_ref(job, window)
        if job.kind == "insert":
            res = self.run_sql(self._rewrite(job.sql, job, ref))
            job.rows_total += int(res.update_count or 0)
        else:                                    # window
            staging = self._staging_fqn(job)
            cat, schema, table = _split_fqn(staging)
            exists = self.catalogs.connector(cat).get_table_metadata(
                schema, table) is not None
            copy_sql = (
                f"INSERT INTO {staging} SELECT * FROM {ref}"
                if exists else
                f"CREATE TABLE {staging} AS SELECT * FROM {ref}")
            res = self.run_sql(copy_sql)
            job.rows_total += int(res.update_count or 0)
        # the window's INSERT succeeded: seal the epoch. A crash in
        # THIS gap is the documented at-least-once boundary.
        self._commit(job, epoch, window)
        if job.kind == "window":
            self._refresh_window_view(job)
        job.cycles += 1
        return True

    def _staging_fqn(self, job: ContinuousJob) -> str:
        cat, schema, table = _split_fqn(job.target)
        # staging lives next to the target so REPLACE and the
        # recompute read through one connector
        return f"{cat}.{schema}.{table}__cq_staging"

    def _refresh_window_view(self, job: ContinuousJob) -> None:
        staging = self._staging_fqn(job)
        wm_res = self.run_sql(
            f"SELECT max({job.ts_column}) FROM {staging}")
        max_ts = wm_res.rows[0][0] if wm_res.rows else None
        if max_ts is None:
            return
        job.watermark = float(max_ts) - job.lateness_ms
        sql = self._rewrite(job.sql, job, staging)
        sql = sql.replace("{watermark}", repr(job.watermark))
        self._materialize(job.target, self.run_sql(sql))
