"""Pattern-matching rule engine — lib/trino-matching.

Reference parity: io.trino.matching's Pattern/Captures/Match, the
machinery under every iterative-optimizer rule
(sql/planner/iterative/Rule.java declares `Pattern pattern()`;
IterativeOptimizer matches it before invoking apply). The optimizer
here is whole-tree rewrites, so this engine serves the same role at
the call sites that benefit from declarative shape tests
(planner/optimizer.py's partial-TopN and partial-limit rules declare
their trigger shapes with it; the union half of those rules stays
imperative because the projection-chain walk has no pattern form).

Usage:
    CAP = Capture("union")
    P = (Pattern.type_of(TopNNode)
         .with_prop("step", "SINGLE")
         .with_source(Pattern.type_of(UnionNode).capture_as(CAP)))
    m = P.match(node)
    if m:
        union = m[CAP]
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional


class Capture:
    """A named slot filled by ``capture_as`` during a match
    (matching/Capture.java)."""

    def __init__(self, name: str = ""):
        self.name = name

    def __repr__(self):
        return f"Capture({self.name})"


class Match:
    """A successful match: truthy, indexable by Capture
    (matching/Match.java + Captures)."""

    def __init__(self, captures: Dict[Capture, Any]):
        self._captures = captures

    def __bool__(self):
        return True

    def __getitem__(self, cap: Capture):
        return self._captures[cap]


class Pattern:
    """Composable structural pattern (matching/Pattern.java):
    type check + property predicates + per-source sub-patterns +
    captures."""

    def __init__(self, cls: Optional[type] = None):
        self._cls = cls
        self._checks: list = []      # (name, predicate)
        self._sources: Dict[str, "Pattern"] = {}
        self._capture: Optional[Capture] = None

    # -- builders (each returns a copied pattern: patterns are shared
    # module-level constants, like the reference's) --------------------
    @staticmethod
    def type_of(cls: type) -> "Pattern":
        return Pattern(cls)

    @staticmethod
    def any() -> "Pattern":
        return Pattern(None)

    def _copy(self) -> "Pattern":
        p = Pattern(self._cls)
        p._checks = list(self._checks)
        p._sources = dict(self._sources)
        p._capture = self._capture
        return p

    def with_prop(self, name: str, value) -> "Pattern":
        p = self._copy()
        p._checks.append((name, lambda v, want=value: v == want))
        return p

    def matching(self, name: str,
                 predicate: Callable[[Any], bool]) -> "Pattern":
        p = self._copy()
        p._checks.append((name, predicate))
        return p

    def with_source(self, sub: "Pattern",
                    attr: str = "source") -> "Pattern":
        p = self._copy()
        p._sources[attr] = sub
        return p

    def capture_as(self, cap: Capture) -> "Pattern":
        p = self._copy()
        p._capture = cap
        return p

    # -- matching ------------------------------------------------------
    def match(self, node) -> Optional[Match]:
        caps: Dict[Capture, Any] = {}
        return Match(caps) if self._match_into(node, caps) else None

    def _match_into(self, node, caps: Dict[Capture, Any]) -> bool:
        if self._cls is not None and not isinstance(node, self._cls):
            return False
        for name, pred in self._checks:
            # strict getattr: a typo'd property must raise, not make
            # the pattern silently never match (a disabled optimizer
            # rule with no failing test is the worst outcome)
            if not pred(getattr(node, name)):
                return False
        for attr, sub in self._sources.items():
            child = getattr(node, attr, None)
            if child is None or not sub._match_into(child, caps):
                return False
        if self._capture is not None:
            caps[self._capture] = node
        return True
