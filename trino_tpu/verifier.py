"""Query verifier: replay queries against two engines and compare.

Reference parity: service/trino-verifier (PrestoVerifier.java — runs a
control and a test cluster over the same query suite, compares row
sets with float tolerance, reports per-query verdicts). Ours accepts
any pair of objects with ``execute(sql).rows`` — LocalQueryRunner,
distributed runner, or the HTTP client — so it doubles as the
local-vs-distributed and engine-vs-oracle harness."""

from __future__ import annotations

import math
import time
from dataclasses import dataclass, field
from typing import Callable, List, Optional, Sequence, Tuple


@dataclass
class VerifierResult:
    sql: str
    status: str                  # MATCH | MISMATCH | CONTROL_ERROR |
    #                              TEST_ERROR | BOTH_ERROR
    detail: str = ""
    control_wall_s: float = 0.0
    test_wall_s: float = 0.0


def _normalize(rows: Sequence[Sequence], sort: bool) -> List[tuple]:
    out = [tuple(r) for r in rows]
    if sort:
        out.sort(key=lambda r: tuple(
            (v is None, str(type(v)), str(v)) for v in r))
    return out


def _values_match(a, b, rel_tol: float) -> bool:
    if a is None or b is None:
        return a is None and b is None
    if isinstance(a, float) or isinstance(b, float):
        try:
            return math.isclose(float(a), float(b), rel_tol=rel_tol,
                                abs_tol=1e-9)
        except (TypeError, ValueError):
            return False
    return a == b


def rows_match(control: Sequence[Sequence], test: Sequence[Sequence],
               ordered: bool = False,
               rel_tol: float = 1e-9) -> Optional[str]:
    """None when equal; else a human-readable first difference."""
    ca = _normalize(control, not ordered)
    cb = _normalize(test, not ordered)
    if len(ca) != len(cb):
        return f"row count {len(ca)} != {len(cb)}"
    for i, (ra, rb) in enumerate(zip(ca, cb)):
        if len(ra) != len(rb):
            return f"row {i}: arity {len(ra)} != {len(rb)}"
        for j, (va, vb) in enumerate(zip(ra, rb)):
            if not _values_match(va, vb, rel_tol):
                return f"row {i} col {j}: {va!r} != {vb!r}"
    return None


class Verifier:
    """Drives the comparison over a suite of queries."""

    def __init__(self, control, test, rel_tol: float = 1e-9):
        self.control = control
        self.test = test
        self.rel_tol = rel_tol

    def verify(self, sql: str, ordered: Optional[bool] = None
               ) -> VerifierResult:
        if ordered is None:
            ordered = "order by" in sql.lower()
        c_rows = t_rows = None
        c_err = t_err = None
        t0 = time.perf_counter()
        try:
            c_rows = self.control.execute(sql).rows
        except Exception as e:
            c_err = str(e)
        t1 = time.perf_counter()
        try:
            t_rows = self.test.execute(sql).rows
        except Exception as e:
            t_err = str(e)
        t2 = time.perf_counter()
        if c_err and t_err:
            return VerifierResult(sql, "BOTH_ERROR",
                                  f"{c_err} / {t_err}",
                                  t1 - t0, t2 - t1)
        if c_err:
            return VerifierResult(sql, "CONTROL_ERROR", c_err,
                                  t1 - t0, t2 - t1)
        if t_err:
            return VerifierResult(sql, "TEST_ERROR", t_err,
                                  t1 - t0, t2 - t1)
        diff = rows_match(c_rows, t_rows, ordered, self.rel_tol)
        if diff is None:
            return VerifierResult(sql, "MATCH", "", t1 - t0, t2 - t1)
        return VerifierResult(sql, "MISMATCH", diff, t1 - t0, t2 - t1)

    def run_suite(self, queries: Sequence[str]) -> List[VerifierResult]:
        return [self.verify(q) for q in queries]


def report(results: Sequence[VerifierResult]) -> str:
    lines = []
    counts: dict = {}
    for r in results:
        counts[r.status] = counts.get(r.status, 0) + 1
        mark = "OK " if r.status == "MATCH" else r.status
        lines.append(f"{mark:>14}  {r.control_wall_s*1000:7.1f}ms / "
                     f"{r.test_wall_s*1000:7.1f}ms  "
                     f"{r.sql[:80]}" +
                     (f"  [{r.detail[:60]}]" if r.detail else ""))
    lines.append("")
    lines.append("  ".join(f"{k}={v}" for k, v in sorted(counts.items())))
    return "\n".join(lines)
