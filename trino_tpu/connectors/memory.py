"""In-memory connector: CREATE TABLE / INSERT target and test stand-in.

Reference parity: plugin/trino-memory (MemoryConnector, MemoryMetadata,
MemoryPagesStore — 3.3k loc). Stores appended Batches per table; reads
concatenate them (host-resident; upload to HBM happens lazily at first
kernel touch like every Batch).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

from ..catalog import (ColumnMetadata, Connector, Split, TableHandle,
                       TableMetadata)
from ..columnar import Batch, concat_batches, empty_batch


class MemoryConnector(Connector):
    name = "memory"

    def __init__(self):
        # (schema, table) -> (metadata, [Batch])
        self._tables: Dict[Tuple[str, str],
                           Tuple[TableMetadata, List[Batch]]] = {}
        self._schemas = {"default"}
        # bumped on every mutation: the result cache keys validity on
        # it, so a cached SELECT goes stale the moment data changes
        self._version = 1

    def data_version(self) -> Optional[int]:
        return self._version

    def list_schemas(self) -> List[str]:
        return sorted(self._schemas)

    def list_tables(self, schema: str) -> List[str]:
        return sorted(t for (s, t) in self._tables if s == schema)

    def get_table_metadata(self, schema, table) -> Optional[TableMetadata]:
        entry = self._tables.get((schema, table))
        return entry[0] if entry else None

    def create_schema(self, schema: str) -> None:
        self._schemas.add(schema)
        self._version += 1

    def create_table(self, metadata: TableMetadata) -> None:
        key = (metadata.schema, metadata.name)
        if key in self._tables:
            raise ValueError(
                f"Table '{metadata.schema}.{metadata.name}' already exists")
        self._schemas.add(metadata.schema)
        self._tables[key] = (metadata, [])
        self._version += 1

    def drop_table(self, schema: str, table: str) -> None:
        self._tables.pop((schema, table), None)
        self._version += 1

    def insert(self, schema: str, table: str, batch: Batch) -> int:
        meta, batches = self._tables[(schema, table)]
        batch = batch.rename(dict(zip(batch.names, meta.column_names)))
        batches.append(batch)
        self._version += 1
        return batch.num_rows_host()

    def replace(self, schema: str, table: str, batch: Batch) -> None:
        """Swap table contents (DELETE rewrites the survivors)."""
        meta, _ = self._tables[(schema, table)]
        batch = batch.rename(dict(zip(batch.names, meta.column_names)))
        self._tables[(schema, table)] = (meta, [batch])
        self._version += 1

    def read_split(self, split: Split, columns: Sequence[str]) -> Batch:
        meta, batches = self._tables[(split.handle.schema,
                                      split.handle.table)]
        if not batches:
            return empty_batch(
                {c.name: c.type for c in meta.columns
                 if c.name in set(columns)})
        whole = concat_batches(batches)
        if split.handle.constraint is not None \
                or split.handle.limit is not None:
            from ..predicate import filter_batch_host
            whole = filter_batch_host(whole, split.handle.constraint,
                                      split.handle.limit)
        return whole.select_columns(list(columns))

    # --- pushdown (ConnectorMetadata.applyFilter/applyLimit) -------------
    def apply_filter(self, handle: TableHandle, constraint):
        from ..catalog import accept_filter_pushdown
        return accept_filter_pushdown(handle, constraint)

    def apply_limit(self, handle: TableHandle, limit: int):
        from ..catalog import accept_limit_pushdown
        return accept_limit_pushdown(handle, limit)

    def table_row_count(self, handle: TableHandle) -> Optional[float]:
        entry = self._tables.get((handle.schema, handle.table))
        if entry is None:
            return None
        return float(sum(b.num_rows_host() for b in entry[1]))

    # --- transactions: snapshot-on-begin, restore-on-rollback ------------
    def snapshot_state(self):
        return ({k: (meta, list(batches))
                 for k, (meta, batches) in self._tables.items()},
                set(self._schemas))

    def restore_state(self, state) -> None:
        tables, schemas = state
        self._tables = {k: (meta, list(batches))
                        for k, (meta, batches) in tables.items()}
        self._schemas = set(schemas)
        self._version += 1


class BlackholeConnector(Connector):
    """plugin/trino-blackhole — instant-discard sink for write benchmarks."""

    name = "blackhole"

    def __init__(self):
        self._tables: Dict[Tuple[str, str], TableMetadata] = {}

    def list_schemas(self) -> List[str]:
        return ["default"]

    def list_tables(self, schema: str) -> List[str]:
        return sorted(t for (s, t) in self._tables if s == schema)

    def get_table_metadata(self, schema, table) -> Optional[TableMetadata]:
        return self._tables.get((schema, table))

    def create_table(self, metadata: TableMetadata) -> None:
        self._tables[(metadata.schema, metadata.name)] = metadata

    def drop_table(self, schema: str, table: str) -> None:
        self._tables.pop((schema, table), None)

    def insert(self, schema: str, table: str, batch: Batch) -> int:
        return batch.num_rows_host()

    def read_split(self, split: Split, columns: Sequence[str]) -> Batch:
        meta = self._tables[(split.handle.schema, split.handle.table)]
        return empty_batch({c.name: c.type for c in meta.columns
                            if c.name in set(columns)})
