"""Local-file connector: a directory of parquet/ORC/CSV/JSON files as
tables.

Reference parity: plugin/trino-local-file (1.9k loc) generalized with
the record decoders of lib/trino-record-decoder (JSON/CSV row decoders)
and the parquet binding the hive plugin provides in the reference.
Each file (or basename) is a table; parquet files split per ROW GROUP
so scans parallelize like the reference's split model."""

from __future__ import annotations

import csv
import io
import json
import os
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..catalog import (ColumnMetadata, Connector, Split, TableHandle,
                       TableMetadata)
from ..columnar import Batch, batch_from_pylist
from ..types import (BIGINT, BOOLEAN, DOUBLE, Type, VARCHAR)

_EXTS = (".parquet", ".orc", ".csv", ".tsv", ".json", ".ndjson")


class LocalFileConnector(Connector):
    name = "localfile"

    def __init__(self, root: str):
        import threading
        self.root = root
        self._write_lock = threading.Lock()

    # --- metadata --------------------------------------------------------
    def list_schemas(self) -> List[str]:
        return ["default"]

    def _path_of(self, table: str) -> Optional[str]:
        for fn in sorted(os.listdir(self.root)):
            base, ext = os.path.splitext(fn)
            if ext.lower() in _EXTS and base.lower() == table:
                return os.path.join(self.root, fn)
        return None

    def list_tables(self, schema: str) -> List[str]:
        out = []
        if schema != "default" or not os.path.isdir(self.root):
            return out
        for fn in sorted(os.listdir(self.root)):
            base, ext = os.path.splitext(fn)
            if ext.lower() in _EXTS:
                out.append(base.lower())
        return out

    def get_table_metadata(self, schema, table) -> Optional[TableMetadata]:
        path = self._path_of(table)
        if path is None:
            return None
        schema_map = self._schema_for(path)
        return TableMetadata(schema, table, tuple(
            ColumnMetadata(n, t) for n, t in schema_map.items()))

    def _schema_for(self, path: str) -> Dict[str, Type]:
        ext = os.path.splitext(path)[1].lower()
        if ext == ".parquet":
            from ..formats.parquet import schema_of
            return schema_of(path)
        if ext == ".orc":
            from ..formats.orc import schema_of
            return schema_of(path)
        if ext in (".csv", ".tsv"):
            rows = self._csv_rows(path, limit=100)
            return _infer_schema(rows)
        rows = self._json_rows(path, limit=100)
        return _infer_schema(rows)

    # --- rows ------------------------------------------------------------
    def _csv_rows(self, path: str,
                  limit: Optional[int] = None) -> List[dict]:
        delim = "\t" if path.lower().endswith(".tsv") else ","
        out = []
        with open(path, newline="") as f:
            for i, row in enumerate(csv.DictReader(f, delimiter=delim)):
                if limit is not None and i >= limit:
                    break
                out.append({k.lower(): v for k, v in row.items()})
        return out

    def _json_rows(self, path: str,
                   limit: Optional[int] = None) -> List[dict]:
        out = []
        with open(path) as f:
            for i, line in enumerate(f):
                if limit is not None and i >= limit:
                    break
                line = line.strip()
                if line:
                    out.append({k.lower(): v
                                for k, v in json.loads(line).items()})
        return out

    # --- data out (page-sink SPI: the reference's
    # ConnectorPageSink writing ORC/Parquet files — lib/trino-orc
    # OrcWriter / trino-parquet ParquetWriter; here formats/
    # {orc,parquet}_writer.py) -------------------------------------------
    write_format = "parquet"          # or "orc"
    # types both writers round-trip exactly (smallint would silently
    # widen to integer on rewrite — reject it up front)
    _WRITABLE = ("bigint", "integer", "double", "boolean", "date")

    def _check_writable(self, name: str, t: Type) -> None:
        from ..types import is_string
        if t.name not in self._WRITABLE and not is_string(t):
            raise ValueError(
                f"localfile writer: column '{name}' has type {t}, "
                f"which the {self.write_format} writer cannot "
                "round-trip exactly")

    def _write(self, path: str, batch: Batch,
               fmt: Optional[str] = None) -> None:
        fmt = fmt or ("orc" if path.lower().endswith(".orc")
                      else "parquet")
        if fmt == "orc":
            from ..formats.orc_writer import write_orc
            write_orc(path, batch)
        else:
            from ..formats.parquet_writer import write_parquet
            write_parquet(path, batch)

    def _read_table(self, path: str) -> Batch:
        """Whole-table read, shared by insert's rewrite and read_split
        (one extension dispatch)."""
        ext = os.path.splitext(path)[1].lower()
        if ext == ".parquet":
            from ..formats.parquet import read_parquet
            return read_parquet(path)
        if ext == ".orc":
            from ..formats.orc import read_orc
            return read_orc(path)
        raise ValueError(f"writes to {ext} tables are not supported")

    def _check_schema(self, schema: str) -> None:
        if schema != "default":
            raise KeyError(f"Schema '{schema}' does not exist")

    def create_table(self, metadata: TableMetadata) -> None:
        self._check_schema(metadata.schema)
        if self._path_of(metadata.name) is not None:
            raise ValueError(
                f"Table '{metadata.name}' already exists")
        for c in metadata.columns:
            self._check_writable(c.name, c.type)
        from ..columnar import empty_batch
        path = os.path.join(self.root,
                            f"{metadata.name}.{self.write_format}")
        self._write(path, empty_batch(
            {c.name: c.type for c in metadata.columns}))

    def drop_table(self, schema: str, table: str) -> None:
        self._check_schema(schema)
        path = self._path_of(table)
        if path is None:
            raise KeyError(f"table {table} does not exist")
        os.remove(path)

    def insert(self, schema: str, table: str, batch: Batch) -> int:
        """Append by rewrite under the connector's write lock (single
        -file tables; the reference's page sink streams new files into
        a directory instead). The incoming batch is aligned to the
        table schema: missing columns fill with NULL, unknown columns
        are rejected."""
        self._check_schema(schema)
        with self._write_lock:
            path = self._path_of(table)
            if path is None:
                raise KeyError(f"table {table} does not exist")
            tschema = self._schema_for(path)
            extra = [c for c in batch.columns if c not in tschema]
            if extra:
                raise ValueError(
                    f"INSERT columns {extra} do not exist in "
                    f"'{table}'")
            from ..columnar import column_from_pylist
            n = batch.num_rows_host()
            cols = {}
            for name, t in tschema.items():
                if name in batch.columns:
                    cols[name] = batch.column(name)
                else:
                    # NULL fill at the batch's capacity so every
                    # column shares one capacity bucket
                    cols[name] = column_from_pylist(
                        [None] * batch.capacity, t)
            aligned = Batch(cols, n)
            existing = self._read_table(path)
            from ..exec.executor import device_concat
            merged = (aligned if existing.num_rows_host() == 0
                      else device_concat([existing, aligned]))
            ext = os.path.splitext(path)[1].lower()
            # the tmp suffix hides the real extension: pass the format
            tmp = f"{path}.{os.getpid()}.tmp"
            self._write(tmp, merged, fmt=ext.lstrip("."))
            os.replace(tmp, path)
            return n

    # --- splits ----------------------------------------------------------
    def get_splits(self, handle: TableHandle,
                   desired_parallelism: int = 1) -> List[Split]:
        path = self._path_of(handle.table)
        if path and path.lower().endswith(".parquet"):
            from ..formats.parquet import num_row_groups
            n = max(1, num_row_groups(path))
            return [Split(handle, i, n) for i in range(n)]
        if path and path.lower().endswith(".orc"):
            from ..formats.orc import num_stripes
            n = max(1, num_stripes(path))
            return [Split(handle, i, n) for i in range(n)]
        return [Split(handle, 0, 1)]

    # --- data in ---------------------------------------------------------
    def read_split(self, split: Split, columns: Sequence[str]) -> Batch:
        path = self._path_of(split.handle.table)
        if path is None:
            raise KeyError(f"table {split.handle.table} vanished")
        ext = os.path.splitext(path)[1].lower()
        need = list(columns)
        if split.handle.constraint is not None:
            # constraint columns must be materialized to enforce the
            # accepted pushdown even when projection-pruned
            for c, _ in split.handle.constraint.domains:
                if c not in need:
                    need.append(c)
        if ext == ".parquet":
            from ..formats.parquet import read_parquet
            batch = read_parquet(
                path, columns=need,
                row_group=split.part if split.part_count > 1 else None)
        elif ext == ".orc":
            from ..formats.orc import read_orc
            batch = read_orc(
                path, columns=need,
                stripe_index=split.part if split.part_count > 1
                else None)
        else:
            rows = (self._csv_rows(path) if ext in (".csv", ".tsv")
                    else self._json_rows(path))
            schema = self._schema_for(path)
            data = {}
            for name, t in schema.items():
                data[name] = [_coerce(r.get(name), t) for r in rows]
            batch = batch_from_pylist(data, schema)
        if split.handle.constraint is not None \
                or split.handle.limit is not None:
            from ..predicate import filter_batch_host
            batch = filter_batch_host(batch, split.handle.constraint,
                                      split.handle.limit)
        return batch.select_columns(list(columns))

    def apply_filter(self, handle: TableHandle, constraint):
        from ..catalog import accept_filter_pushdown
        return accept_filter_pushdown(handle, constraint)

    def apply_limit(self, handle: TableHandle, limit: int):
        from ..catalog import accept_limit_pushdown
        return accept_limit_pushdown(handle, limit)

    def table_row_count(self, handle: TableHandle) -> Optional[float]:
        path = self._path_of(handle.table)
        if path and path.lower().endswith(".parquet"):
            from ..formats.parquet import read_metadata
            return float(read_metadata(path).num_rows)
        if path and path.lower().endswith(".orc"):
            from ..formats.orc import read_meta
            return float(read_meta(path).num_rows)
        return None


def _infer_schema(rows: List[dict]) -> Dict[str, Type]:
    """Type inference over sampled rows (record-decoder style: every
    CSV value is text; JSON carries bool/number natively)."""
    if not rows:
        return {}
    schema: Dict[str, Type] = {}
    for key in rows[0]:
        vals = [r.get(key) for r in rows if r.get(key) not in (None, "")]
        schema[key] = _infer_type(vals)
    return schema


def _infer_type(vals: list) -> Type:
    if not vals:
        return VARCHAR
    if all(isinstance(v, bool) for v in vals):
        return BOOLEAN
    if all(isinstance(v, int) and not isinstance(v, bool)
           for v in vals):
        return BIGINT
    if all(isinstance(v, (int, float)) and not isinstance(v, bool)
           for v in vals):
        return DOUBLE
    if all(isinstance(v, str) for v in vals):
        if all(_is_int(v) for v in vals):
            return BIGINT
        if all(_is_float(v) for v in vals):
            return DOUBLE
        low = {v.lower() for v in vals}
        if low <= {"true", "false"}:
            return BOOLEAN
    return VARCHAR


def _is_int(s: str) -> bool:
    try:
        int(s)
        return True
    except ValueError:
        return False


def _is_float(s: str) -> bool:
    try:
        float(s)
        return True
    except ValueError:
        return False


def _coerce(v, t: Type):
    if v is None or v == "":
        return None
    if t is BIGINT:
        return int(v)
    if t is DOUBLE:
        return float(v)
    if t is BOOLEAN:
        if isinstance(v, bool):
            return v
        return str(v).lower() == "true"
    return str(v)
