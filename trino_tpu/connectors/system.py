"""System catalog: runtime introspection tables + procedures.

Reference parity: connector/system/ (QuerySystemTable.java,
NodeSystemTable.java, KillQueryProcedure.java — 25+ files). The
connector is constructed over a provider object (the Coordinator or a
QueryTracker) exposing ``query_infos()`` / ``node_infos()`` /
``kill_query(id)``; in a plain LocalQueryRunner the provider is a stub
with no queries.

PR 19 grows the runtime schema into the engine's self-observation
surface: ``queries`` serves the durable query-history records (terminal
queries with error classification, timing attribution and the
canonical plan key — live QUEUED/RUNNING queries ride along),
``operator_stats`` serves the learned-stats registry's per-operator
selectivity/throughput EMAs (exec/learnedstats.py), and ``metrics``
serves the current metrics registry rolled up cluster-wide plus the
periodic snapshot ring (obs/history.py MetricsRing) — so
``SELECT * FROM system.runtime.queries WHERE error_code IS NOT NULL
ORDER BY wall_s DESC`` works through the normal query path."""

from __future__ import annotations

import time
from typing import List, Optional, Sequence

from ..catalog import (ColumnMetadata, Connector, Split, TableHandle,
                       TableMetadata)
from ..columnar import Batch, batch_from_pylist
from ..types import BIGINT, BOOLEAN, DOUBLE, VARCHAR

_RUNTIME_TABLES = {
    "queries": (
        ("query_id", VARCHAR), ("state", VARCHAR), ("user", VARCHAR),
        ("source", VARCHAR), ("query", VARCHAR),
        ("sql_digest", VARCHAR), ("plan_key", VARCHAR),
        ("error_code", VARCHAR), ("error_type", VARCHAR),
        ("queued_s", DOUBLE), ("wall_s", DOUBLE), ("cpu_s", DOUBLE),
        ("device_s", DOUBLE), ("rows", BIGINT),
        ("peak_memory_bytes", BIGINT), ("spill_bytes", BIGINT),
        ("stream_chunks", BIGINT), ("retries", BIGINT),
        ("trace_id", VARCHAR), ("created", VARCHAR),
    ),
    "operator_stats": (
        ("plan_key", VARCHAR), ("operator", VARCHAR),
        ("occurrence", BIGINT), ("observations", BIGINT),
        ("selectivity", DOUBLE), ("rows_per_s", DOUBLE),
        ("rows_in", BIGINT), ("rows_out", BIGINT),
        ("wall_s", DOUBLE), ("updated", VARCHAR),
    ),
    "metrics": (
        ("captured_ms", BIGINT), ("node", VARCHAR), ("name", VARCHAR),
        ("labels", VARCHAR), ("value", DOUBLE), ("sample", VARCHAR),
    ),
    "continuous_queries": (
        ("job_id", VARCHAR), ("kind", VARCHAR), ("state", VARCHAR),
        ("sql", VARCHAR), ("target", VARCHAR), ("topic", VARCHAR),
        ("poll_ms", BIGINT), ("cycles", BIGINT),
        ("rows_total", BIGINT), ("last_epoch", BIGINT),
        ("watermark", DOUBLE), ("last_error", VARCHAR),
        ("created", VARCHAR),
    ),
    "nodes": (
        ("node_id", VARCHAR), ("http_uri", VARCHAR),
        ("node_version", VARCHAR), ("coordinator", BOOLEAN),
        ("state", VARCHAR),
    ),
    "resource_groups": (
        ("name", VARCHAR), ("running", BIGINT), ("queued", BIGINT),
        ("hard_concurrency_limit", BIGINT), ("max_queued", BIGINT),
    ),
}


def _iso(epoch) -> str:
    try:
        return time.strftime("%Y-%m-%dT%H:%M:%S",
                             time.localtime(float(epoch)))
    except (TypeError, ValueError, OverflowError, OSError):
        return ""


class SystemProvider:
    """Provider SPI; the Coordinator implements these."""

    def query_infos(self) -> List[dict]:
        return []

    def node_infos(self) -> List[dict]:
        return []

    def resource_group_infos(self) -> List[dict]:
        return []

    def history_infos(self) -> List[dict]:
        """Query-history records (obs/history.py record schema) —
        terminal queries first, live ones appended by the
        coordinator's implementation."""
        return []

    def operator_stat_infos(self) -> List[dict]:
        """Learned-stats registry snapshot
        (exec/learnedstats.py LearnedStatsRegistry.snapshot)."""
        return []

    def continuous_query_infos(self) -> List[dict]:
        """Continuous-query job snapshots
        (streaming/continuous.py ContinuousJob.to_dict)."""
        return []

    def metric_infos(self) -> List[dict]:
        """Flattened metric samples: dicts with captured_ms, node,
        name, labels, value, sample ("current" | "ring")."""
        return []

    def kill_query(self, query_id: str) -> bool:
        raise KeyError(f"query not found: {query_id}")


class SystemConnector(Connector):
    name = "system"

    def __init__(self, provider: Optional[SystemProvider] = None):
        self.provider = provider or SystemProvider()

    def list_schemas(self) -> List[str]:
        return ["runtime"]

    def list_tables(self, schema: str) -> List[str]:
        if schema == "runtime":
            return sorted(_RUNTIME_TABLES)
        return []

    def get_table_metadata(self, schema, table) -> Optional[TableMetadata]:
        cols = _RUNTIME_TABLES.get(table) if schema == "runtime" else None
        if cols is None:
            return None
        return TableMetadata(schema, table, tuple(
            ColumnMetadata(n, t) for n, t in cols))

    def read_split(self, split: Split, columns: Sequence[str]) -> Batch:
        table = split.handle.table
        cols = _RUNTIME_TABLES[table]
        if table == "queries":
            rows = [
                (h.get("query_id", ""), h.get("state", ""),
                 h.get("user", ""), h.get("source", ""),
                 h.get("sql", h.get("query", "")),
                 h.get("sql_digest", ""), h.get("plan_key", ""),
                 h.get("error_name"), h.get("error_type"),
                 float(h.get("queued_s") or 0.0),
                 float(h.get("wall_s") or 0.0),
                 float(h.get("cpu_s") or 0.0),
                 float(h.get("device_s") or 0.0),
                 int(h.get("rows") or 0),
                 int(h.get("peak_memory_bytes") or 0),
                 int(h.get("spill_bytes") or 0),
                 int(h.get("stream_chunks") or 0),
                 int(h.get("retries") or 0),
                 h.get("trace_id"), _iso(h.get("created")))
                for h in self.provider.history_infos()]
        elif table == "operator_stats":
            rows = [
                (s.get("key", ""), s.get("op", ""),
                 int(s.get("idx") or 0), int(s.get("n") or 0),
                 s.get("selectivity"), s.get("rows_per_s"),
                 int(s.get("rows_in") or 0),
                 int(s.get("rows_out") or 0),
                 float(s.get("wall_s") or 0.0),
                 _iso(s.get("updated")))
                for s in self.provider.operator_stat_infos()]
        elif table == "metrics":
            rows = [
                (int(m.get("captured_ms") or 0), m.get("node", ""),
                 m.get("name", ""), m.get("labels", ""),
                 float(m.get("value") or 0.0),
                 m.get("sample", "current"))
                for m in self.provider.metric_infos()]
        elif table == "continuous_queries":
            rows = [
                (j.get("job_id", ""), j.get("kind", ""),
                 j.get("state", ""), j.get("sql", ""),
                 j.get("target"), j.get("topic"),
                 int(j.get("poll_interval_ms") or 0),
                 int(j.get("cycles") or 0),
                 int(j.get("rows_total") or 0),
                 int(j.get("last_epoch") or 0), j.get("watermark"),
                 j.get("last_error"), _iso(j.get("created")))
                for j in self.provider.continuous_query_infos()]
        elif table == "nodes":
            rows = [
                (i.get("nodeId", ""), i.get("uri", ""),
                 i.get("nodeVersion", ""), i.get("coordinator", False),
                 i.get("state", "active"))
                for i in self.provider.node_infos()]
        else:
            rows = [
                (i.get("name", ""), i.get("running", 0),
                 i.get("queued", 0), i.get("hardConcurrencyLimit", 0),
                 i.get("maxQueued", 0))
                for i in self.provider.resource_group_infos()]
        names = [n for n, _ in cols]
        data = {n: [r[i] for r in rows] for i, n in enumerate(names)}
        return batch_from_pylist(data, dict(cols)).select_columns(
            [c for c in columns])

    # --- procedures (connector/system/KillQueryProcedure.java) -----------
    def call_procedure(self, schema: str, name: str, args: list):
        if schema == "runtime" and name == "kill_query":
            if not args:
                raise ValueError("kill_query(query_id) requires an id")
            ok = self.provider.kill_query(str(args[0]))
            if not ok:
                raise KeyError(f"query not found: {args[0]}")
            return
        raise KeyError(f"Procedure '{schema}.{name}' not registered")
