"""System catalog: runtime introspection tables + procedures.

Reference parity: connector/system/ (QuerySystemTable.java,
NodeSystemTable.java, KillQueryProcedure.java — 25+ files). The
connector is constructed over a provider object (the Coordinator or a
QueryTracker) exposing ``query_infos()`` / ``node_infos()`` /
``kill_query(id)``; in a plain LocalQueryRunner the provider is a stub
with no queries."""

from __future__ import annotations

import time
from typing import List, Optional, Sequence

from ..catalog import (ColumnMetadata, Connector, Split, TableHandle,
                       TableMetadata)
from ..columnar import Batch, batch_from_pylist
from ..types import BIGINT, BOOLEAN, VARCHAR

_RUNTIME_TABLES = {
    "queries": (
        ("query_id", VARCHAR), ("state", VARCHAR), ("user", VARCHAR),
        ("source", VARCHAR), ("query", VARCHAR),
        ("queued_time_ms", BIGINT), ("analysis_time_ms", BIGINT),
        ("created", VARCHAR),
    ),
    "nodes": (
        ("node_id", VARCHAR), ("http_uri", VARCHAR),
        ("node_version", VARCHAR), ("coordinator", BOOLEAN),
        ("state", VARCHAR),
    ),
    "resource_groups": (
        ("name", VARCHAR), ("running", BIGINT), ("queued", BIGINT),
        ("hard_concurrency_limit", BIGINT), ("max_queued", BIGINT),
    ),
}


class SystemProvider:
    """Provider SPI; the Coordinator implements these."""

    def query_infos(self) -> List[dict]:
        return []

    def node_infos(self) -> List[dict]:
        return []

    def resource_group_infos(self) -> List[dict]:
        return []

    def kill_query(self, query_id: str) -> bool:
        raise KeyError(f"query not found: {query_id}")


class SystemConnector(Connector):
    name = "system"

    def __init__(self, provider: Optional[SystemProvider] = None):
        self.provider = provider or SystemProvider()

    def list_schemas(self) -> List[str]:
        return ["runtime"]

    def list_tables(self, schema: str) -> List[str]:
        if schema == "runtime":
            return sorted(_RUNTIME_TABLES)
        return []

    def get_table_metadata(self, schema, table) -> Optional[TableMetadata]:
        cols = _RUNTIME_TABLES.get(table) if schema == "runtime" else None
        if cols is None:
            return None
        return TableMetadata(schema, table, tuple(
            ColumnMetadata(n, t) for n, t in cols))

    def read_split(self, split: Split, columns: Sequence[str]) -> Batch:
        table = split.handle.table
        cols = _RUNTIME_TABLES[table]
        if table == "queries":
            rows = [
                (i.get("queryId", ""), i.get("state", ""),
                 i.get("user", ""), i.get("source", ""),
                 i.get("query", ""), i.get("elapsedTimeMillis", 0),
                 i.get("analysisTimeMillis", 0), i.get("created", ""))
                for i in self.provider.query_infos()]
        elif table == "nodes":
            rows = [
                (i.get("nodeId", ""), i.get("uri", ""),
                 i.get("nodeVersion", ""), i.get("coordinator", False),
                 i.get("state", "active"))
                for i in self.provider.node_infos()]
        else:
            rows = [
                (i.get("name", ""), i.get("running", 0),
                 i.get("queued", 0), i.get("hardConcurrencyLimit", 0),
                 i.get("maxQueued", 0))
                for i in self.provider.resource_group_infos()]
        names = [n for n, _ in cols]
        data = {n: [r[i] for r in rows] for i, n in enumerate(names)}
        return batch_from_pylist(data, dict(cols)).select_columns(
            [c for c in columns])

    # --- procedures (connector/system/KillQueryProcedure.java) -----------
    def call_procedure(self, schema: str, name: str, args: list):
        if schema == "runtime" and name == "kill_query":
            if not args:
                raise ValueError("kill_query(query_id) requires an id")
            ok = self.provider.kill_query(str(args[0]))
            if not ok:
                raise KeyError(f"query not found: {args[0]}")
            return
        raise KeyError(f"Procedure '{schema}.{name}' not registered")
