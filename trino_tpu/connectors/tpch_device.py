"""Device-side TPC-H generation: lineitem/orders lanes born in HBM.

Reference parity: plugin/trino-tpch/.../TpchRecordSet.java:43-51 —
the generator is split-addressable and scales by design. On a 1-core
host the numpy leg tops out around ~1M rows/s; at sf100 (600M lineitem
rows) host generation alone would dwarf the query. The counter-based
RNG (value = mix(seed, row_index)) is branch-free integer arithmetic —
exactly what the TPU's VPU eats — so the lanes are generated directly
on device, bit-identical to the numpy leg (tests/test_tpch_device.py
asserts exact equality).

Strings: dictionary-coded columns (returnflag, linestatus, shipmode,
shipinstruct, orderstatus, orderpriority) are device-generatable — the
code lane is integer math, the dictionary is static. Free-text comment
columns and per-row formatted keys (o_clerk) stay on the host path.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..columnar import Batch, Column, StringDictionary
from ..config import capacity_for
from ..types import BIGINT, DATE, DOUBLE, INTEGER, VarcharType

from .tpch import (CURRENTDATE, INSTRUCTIONS, MODES, ORDER_DATE_SPAN,
                   PRIORITIES, STARTDATE, _SEED, table_rows,
                   _strings as _dict_col)
# _strings shares its StringDictionary cache across host and device
# generation — dictionary identity is static trace metadata, so sharing
# keeps one compiled pipeline per query instead of one per split
# (codes.astype(np.int32) on a jax array stays on device)

_C1 = jnp.uint64(0xBF58476D1CE4E5B9)
_C2 = jnp.uint64(0x94D049BB133111EB)
_GOLD = jnp.uint64(0x9E3779B97F4A7C15)


def _mix(x: jax.Array) -> jax.Array:
    x = x ^ (x >> jnp.uint64(30))
    x = x * _C1
    x = x ^ (x >> jnp.uint64(27))
    x = x * _C2
    x = x ^ (x >> jnp.uint64(31))
    return x


def _u64(seed: int, idx: jax.Array) -> jax.Array:
    return _mix(jnp.uint64(seed) * _GOLD + idx.astype(jnp.uint64))


def _randint(seed: int, idx: jax.Array, lo: int, hi: int) -> jax.Array:
    span = jnp.uint64(hi - lo + 1)
    return (lo + (_u64(seed, idx) % span).astype(jnp.int64))


def _order_key(i: jax.Array) -> jax.Array:
    return ((i >> 3) << 5) | (i & 7)


def _order_date(order_idx: jax.Array) -> jax.Array:
    return STARTDATE + _randint(_SEED["orders"] + 4, order_idx, 0,
                                ORDER_DATE_SPAN)


def _cust_key(order_idx: jax.Array, c_count: int) -> jax.Array:
    j = _randint(_SEED["orders"] + 3, order_idx, 1,
                 max(2 * c_count // 3, 1))
    return 3 * ((j - 1) // 2) + 1 + ((j - 1) % 2)


def _line_counts(order_idx: jax.Array) -> jax.Array:
    return _randint(_SEED["lineitem"] + 1, order_idx, 1, 7)


def _retailprice(partkey: jax.Array) -> jax.Array:
    pk = partkey.astype(jnp.int64)
    return (90000 + (pk // 10) % 20001 + 100 * (pk % 1000)) / 100.0


def _ps_suppkey(partkey: jax.Array, i: jax.Array,
                s_count: int) -> jax.Array:
    pk = partkey.astype(jnp.int64)
    s = jnp.int64(s_count)
    return (pk + i * (s // 4 + (pk - 1) // s)) % s + 1


# --------------------------------------------------------------------------
# per-table device column sets
# --------------------------------------------------------------------------

LINEITEM_DEVICE_COLS = {
    "l_orderkey", "l_partkey", "l_suppkey", "l_linenumber",
    "l_quantity", "l_extendedprice", "l_discount", "l_tax",
    "l_shipdate", "l_commitdate", "l_receiptdate", "l_returnflag",
    "l_linestatus", "l_shipinstruct", "l_shipmode"}

ORDERS_DEVICE_COLS = {
    "o_orderkey", "o_custkey", "o_orderstatus", "o_totalprice",
    "o_orderdate", "o_orderpriority", "o_shippriority"}


def device_columns(table: str) -> Optional[set]:
    if table == "lineitem":
        return LINEITEM_DEVICE_COLS
    if table == "orders":
        return ORDERS_DEVICE_COLS
    return None


# --------------------------------------------------------------------------
# lineitem
# --------------------------------------------------------------------------

def _line_grid(lo: int, hi: int):
    """(order_rep, line_no, live-compact index, total) for order
    indices (lo, hi] — static 7-wide grid compacted by a host count
    (two-phase capacity pattern; values depend only on
    (order_idx, linenumber) so compaction order matches numpy repeat)."""
    oi = jnp.arange(lo + 1, hi + 1, dtype=jnp.int64)
    counts = _line_counts(oi)
    total = int(jnp.sum(counts))
    o_grid = jnp.repeat(oi, 7)                     # static repeat
    ln_grid = jnp.tile(jnp.arange(1, 8, dtype=jnp.int64), hi - lo)
    live = ln_grid <= jnp.repeat(counts, 7)
    cap = capacity_for(max(total, 1), minimum=8)
    idx = jnp.nonzero(live, size=cap, fill_value=0)[0]
    return jnp.take(o_grid, idx), jnp.take(ln_grid, idx), total, cap


def lineitem_batch(lo: int, hi: int, sf: float,
                   columns: List[str]) -> Batch:
    """Device-generated lineitem rows for order indices (lo, hi]."""
    S = _SEED["lineitem"]
    order_rep, line_no, total, cap = _line_grid(lo, hi)
    rid = order_rep * 8 + line_no
    p_count = table_rows("part", sf)
    s_count = table_rows("supplier", sf)
    need = set(columns)
    out: Dict[str, Column] = {}

    partkey = None
    if need & {"l_partkey", "l_suppkey", "l_extendedprice"}:
        partkey = _randint(S + 2, rid, 1, p_count)
    odate = None
    if need & {"l_shipdate", "l_commitdate", "l_receiptdate",
               "l_returnflag", "l_linestatus"}:
        odate = _order_date(order_rep)
    shipdate = None
    if need & {"l_shipdate", "l_receiptdate", "l_returnflag",
               "l_linestatus"}:
        shipdate = odate + _randint(S + 7, rid, 1, 121)

    if "l_orderkey" in need:
        out["l_orderkey"] = Column(BIGINT, _order_key(order_rep), None)
    if "l_partkey" in need:
        out["l_partkey"] = Column(BIGINT, partkey, None)
    if "l_suppkey" in need:
        out["l_suppkey"] = Column(
            BIGINT, _ps_suppkey(partkey, _randint(S + 3, rid, 0, 3),
                                s_count), None)
    if "l_linenumber" in need:
        out["l_linenumber"] = Column(INTEGER,
                                     line_no.astype(jnp.int32), None)
    if need & {"l_quantity", "l_extendedprice"}:
        qty = _randint(S + 4, rid, 1, 50).astype(jnp.float64)
        if "l_quantity" in need:
            out["l_quantity"] = Column(DOUBLE, qty, None)
        if "l_extendedprice" in need:
            out["l_extendedprice"] = Column(
                DOUBLE, qty * _retailprice(partkey), None)
    if "l_discount" in need:
        out["l_discount"] = Column(
            DOUBLE, _randint(S + 5, rid, 0, 10) / 100.0, None)
    if "l_tax" in need:
        out["l_tax"] = Column(
            DOUBLE, _randint(S + 6, rid, 0, 8) / 100.0, None)
    if "l_shipdate" in need:
        out["l_shipdate"] = Column(DATE, shipdate.astype(jnp.int32),
                                   None)
    if "l_commitdate" in need:
        out["l_commitdate"] = Column(
            DATE, (odate + _randint(S + 8, rid, 30, 90))
            .astype(jnp.int32), None)
    if "l_receiptdate" in need or "l_returnflag" in need:
        # shipdate is always materialized here: both triggering columns
        # are in the set that forces it above
        receipt = shipdate + _randint(S + 9, rid, 1, 30)
        if "l_receiptdate" in need:
            out["l_receiptdate"] = Column(DATE,
                                          receipt.astype(jnp.int32),
                                          None)
        if "l_returnflag" in need:
            returned = receipt <= CURRENTDATE
            ra = (_u64(S + 20, rid) % jnp.uint64(2)).astype(jnp.int64)
            flag = jnp.where(returned, ra, 2).astype(jnp.int32)
            out["l_returnflag"] = _dict_col(["R", "A", "N"], flag,
                                            VarcharType(1))
    if "l_linestatus" in need:
        st = (shipdate > CURRENTDATE).astype(jnp.int32)
        out["l_linestatus"] = _dict_col(["F", "O"], st,
                                        VarcharType(1))
    if "l_shipinstruct" in need:
        si = _randint(S + 21, rid, 0, 3).astype(jnp.int32)
        out["l_shipinstruct"] = _dict_col(INSTRUCTIONS, si,
                                          VarcharType(25))
    if "l_shipmode" in need:
        sm = _randint(S + 22, rid, 0, 6).astype(jnp.int32)
        out["l_shipmode"] = _dict_col(MODES, sm, VarcharType(10))
    return Batch({c: out[c] for c in columns}, total)


# --------------------------------------------------------------------------
# orders
# --------------------------------------------------------------------------

def orders_batch(lo: int, hi: int, sf: float,
                 columns: List[str]) -> Batch:
    """Device-generated orders rows for order indices (lo, hi]."""
    S = _SEED["orders"]
    idx = jnp.arange(lo + 1, hi + 1, dtype=jnp.int64)
    n = hi - lo
    cap = capacity_for(max(n, 1), minimum=8)
    pad = cap - n

    def _padded(a):
        return jnp.pad(a, (0, pad))

    need = set(columns)
    out: Dict[str, Column] = {}
    if "o_orderkey" in need:
        out["o_orderkey"] = Column(BIGINT, _padded(_order_key(idx)),
                                   None)
    if "o_custkey" in need:
        out["o_custkey"] = Column(
            BIGINT, _padded(_cust_key(idx, table_rows("customer", sf))),
            None)
    if need & {"o_orderstatus", "o_totalprice"}:
        # aggregates of this order's generated lineitems, on the static
        # 7-wide grid (no compaction needed: dead cells are masked)
        SL = _SEED["lineitem"]
        counts = _line_counts(idx)
        o_grid = jnp.repeat(idx, 7)
        ln_grid = jnp.tile(jnp.arange(1, 8, dtype=jnp.int64), n)
        live = ln_grid <= jnp.repeat(counts, 7)
        rid = o_grid * 8 + ln_grid
        pk = _randint(SL + 2, rid, 1, table_rows("part", sf))
        qty = _randint(SL + 4, rid, 1, 50).astype(jnp.float64)
        disc = _randint(SL + 5, rid, 0, 10) / 100.0
        tax = _randint(SL + 6, rid, 0, 8) / 100.0
        price = qty * _retailprice(pk) * (1.0 + tax) * (1.0 - disc)
        price = jnp.where(live, price, 0.0).reshape(n, 7)
        # sequential left-to-right adds: bit-identical to the host
        # leg's np.add.at accumulation (XLA's tree reduction rounds
        # differently in the last ULP)
        total = price[:, 0]
        for k in range(1, 7):
            total = total + price[:, k]
        # rint(x*100)/100 — numpy's around algorithm with a TRUE
        # division (jnp.round multiplies by the 0.01 reciprocal, which
        # lands on the other float neighbor for ~14% of values)
        total = jnp.divide(jnp.rint(total * 100.0), 100.0)
        if "o_totalprice" in need:
            out["o_totalprice"] = Column(DOUBLE, _padded(total), None)
        if "o_orderstatus" in need:
            odate_grid = _order_date(o_grid)
            ship = odate_grid + _randint(SL + 7, rid, 1, 121)
            shipped = jnp.where(live, (ship <= CURRENTDATE)
                                .astype(jnp.int64), 0).reshape(n, 7)
            n_shipped = jnp.sum(shipped, axis=1)
            status = jnp.where(
                n_shipped == 0, 0,
                jnp.where(n_shipped == counts, 1, 2)).astype(jnp.int32)
            out["o_orderstatus"] = _dict_col(
                ["O", "F", "P"], _padded(status), VarcharType(1))
    if "o_orderdate" in need:
        out["o_orderdate"] = Column(
            DATE, _padded(_order_date(idx).astype(jnp.int32)), None)
    if "o_orderpriority" in need:
        p = _randint(S + 5, idx, 0, 4).astype(jnp.int32)
        out["o_orderpriority"] = _dict_col(PRIORITIES, _padded(p),
                                           VarcharType(15))
    if "o_shippriority" in need:
        out["o_shippriority"] = Column(
            INTEGER, jnp.zeros((cap,), jnp.int32), None)
    return Batch({c: out[c] for c in columns}, n)


# --------------------------------------------------------------------------
# device-side pushdown enforcement (the filter_batch_host analog)
# --------------------------------------------------------------------------

def device_filter(batch: Batch, constraint, limit: Optional[int]) -> Batch:
    """Apply an accepted TupleDomain + limit to a device-resident batch
    without a host round-trip. Dictionary columns evaluate the domain
    once per dictionary VALUE host-side (a tiny table), then gather the
    per-code verdicts; numeric columns translate ranges to jnp
    comparisons. Generator columns carry no NULLs."""
    from ..ops import compact
    if constraint is not None and constraint.is_none:
        return Batch(batch.columns, 0)
    if constraint is not None and not constraint.is_all():
        mask = batch.row_valid()
        for col, dom in constraint.domains:
            if col not in batch.columns or dom.is_all:
                continue
            c = batch.columns[col]
            if c.dictionary is not None:
                vals = c.dictionary.values.astype(str)
                tbl = dom.mask_for(
                    np.arange(len(vals)), None,
                    lambda cds, v=vals: v[np.clip(
                        cds.astype(np.int64), 0, len(v) - 1)])
                m = jnp.take(jnp.asarray(tbl),
                             jnp.asarray(c.data).astype(jnp.int32),
                             mode="clip")
            else:
                data = jnp.asarray(c.data)
                m = jnp.zeros(data.shape, bool)
                for r in dom.ranges:
                    rm = jnp.ones(data.shape, bool)
                    if r.low is not None:
                        rm = rm & ((data >= r.low) if r.low_inclusive
                                   else (data > r.low))
                    if r.high is not None:
                        rm = rm & ((data <= r.high) if r.high_inclusive
                                   else (data < r.high))
                    m = m | rm
            mask = mask & m
        batch = compact.filter_batch(batch, mask)
    if limit is not None:
        from ..ops.compact import limit_batch
        batch = limit_batch(batch, limit)
    return batch
