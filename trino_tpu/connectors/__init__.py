"""Connector implementations (reference: plugin/* — 45 modules; here the
engine-critical set: tpch generator, memory, blackhole, system)."""
