"""JDBC-class connector over a SQL database — the base-jdbc framework.

Reference parity: plugin/trino-base-jdbc (JdbcClient: metadata from
the remote catalog, split = one remote query, applyFilter pushes
domains into the remote WHERE clause) and its family (postgresql/
mysql/...). The only in-image SQL database is sqlite3 (stdlib), so
SqliteConnector plays the remote system; the pushdown machinery —
TupleDomain -> SQL text with bound parameters — is the part every
family member shares.

TPU-first shape: the remote rows land column-at-a-time into Batch
lanes (one fetchall, transposed) — the device never sees row objects.
"""

from __future__ import annotations

import sqlite3
import threading
from typing import Dict, List, Optional, Sequence, Tuple

from ..catalog import (ColumnMetadata, Connector, Split, TableHandle,
                       TableMetadata, accept_filter_pushdown,
                       accept_limit_pushdown)
from ..columnar import Batch, batch_from_pylist
from ..types import (BIGINT, BOOLEAN, DOUBLE, Type, VARCHAR,
                     is_string, parse_type)

_TYPE_MAP = {
    "integer": BIGINT, "int": BIGINT, "bigint": BIGINT,
    "smallint": BIGINT, "tinyint": BIGINT,
    "real": DOUBLE, "double": DOUBLE, "float": DOUBLE,
    "numeric": DOUBLE, "decimal": DOUBLE,
    "text": VARCHAR, "varchar": VARCHAR, "char": VARCHAR,
    "clob": VARCHAR, "boolean": BOOLEAN, "date": VARCHAR,
}


def _sql_type(decl: str) -> Type:
    base = decl.split("(")[0].strip().lower() if decl else "text"
    return _TYPE_MAP.get(base, VARCHAR)


def _quote(ident: str) -> str:
    return '"' + ident.replace('"', '""') + '"'


def domain_to_sql(column: str, dom) -> Tuple[str, list]:
    """One column Domain -> (SQL predicate, parameters) — the WHERE
    half of base-jdbc's QueryBuilder.toPredicate."""
    if dom.is_all:
        return "1=1", []
    parts = []
    params: list = []
    for r in dom.ranges:
        if r.is_point():
            parts.append(f"{_quote(column)} = ?")
            params.append(r.low)
            continue
        conj = []
        if r.low is not None:
            conj.append(f"{_quote(column)} "
                        f"{'>=' if r.low_inclusive else '>'} ?")
            params.append(r.low)
        if r.high is not None:
            conj.append(f"{_quote(column)} "
                        f"{'<=' if r.high_inclusive else '<'} ?")
            params.append(r.high)
        if not conj:
            # unbounded range (e.g. the IS NOT NULL domain): matches
            # every non-null value
            parts.append("1=1")
        elif len(conj) > 1:
            parts.append("(" + " AND ".join(conj) + ")")
        else:
            parts.append(conj[0])
    pred = "(" + " OR ".join(parts) + ")" if parts else "1=0"
    if dom.null_allowed:
        pred = f"({pred} OR {_quote(column)} IS NULL)"
    else:
        pred = f"({pred} AND {_quote(column)} IS NOT NULL)"
    return pred, params


class SqliteConnector(Connector):
    """base-jdbc over sqlite3: schemas/tables/columns read from the
    remote catalog, filters and limits pushed into the remote query."""

    name = "jdbc"

    def __init__(self, database: str = ":memory:",
                 schema: str = "public"):
        self._db = database
        self._schema = schema
        self._lock = threading.Lock()
        self._conn = sqlite3.connect(database, check_same_thread=False)
        self._col_cache: Dict[str, List[Tuple[str, Type]]] = {}

    # -- remote access -------------------------------------------------
    def execute_remote(self, sql: str, params: Sequence = ()) -> list:
        """Run a statement on the remote side (test setup / the
        reference's TestingH2JdbcModule role). DDL/DML invalidates the
        column cache."""
        with self._lock:
            cur = self._conn.execute(sql, tuple(params))
            rows = cur.fetchall()
            self._conn.commit()
        head = sql.lstrip()[:6].upper()
        if head in ("CREATE", "DROP  ", "ALTER ") or \
                head.startswith(("DROP", "ALTER")):
            self._col_cache.clear()
        return rows

    # -- metadata ------------------------------------------------------
    def list_schemas(self) -> List[str]:
        return [self._schema]

    def list_tables(self, schema: str) -> List[str]:
        if schema != self._schema:
            return []
        return [r[0].lower() for r in self.execute_remote(
            "SELECT name FROM sqlite_master WHERE type='table' "
            "ORDER BY name")]

    def _columns(self, table: str) -> List[Tuple[str, Type]]:
        cached = self._col_cache.get(table)
        if cached is None:
            rows = self.execute_remote(
                f"PRAGMA table_info({_quote(table)})")
            cached = [(r[1].lower(), _sql_type(r[2])) for r in rows]
            self._col_cache[table] = cached
        return cached

    def get_table_metadata(self, schema: str,
                           table: str) -> Optional[TableMetadata]:
        if schema != self._schema \
                or table not in self.list_tables(schema):
            return None
        return TableMetadata(schema, table, tuple(
            ColumnMetadata(n, t) for n, t in self._columns(table)))

    def table_row_count(self, handle: TableHandle) -> Optional[float]:
        try:
            return float(self.execute_remote(
                f"SELECT count(*) FROM {_quote(handle.table)}")[0][0])
        except sqlite3.Error:
            return None

    # -- pushdown (applyFilter/applyLimit -> remote WHERE/LIMIT) -------
    def apply_filter(self, handle: TableHandle, constraint):
        return accept_filter_pushdown(handle, constraint)

    def apply_limit(self, handle: TableHandle, limit: int):
        return accept_limit_pushdown(handle, limit)

    # -- data ----------------------------------------------------------
    def get_splits(self, handle: TableHandle,
                   desired_parallelism: int = 1) -> List[Split]:
        return [Split(handle, 0, 1)]   # one remote query per scan

    def read_split(self, split: Split,
                   columns: Sequence[str]) -> Batch:
        handle = split.handle
        cols = list(columns) or [
            n for n, _ in self._columns(handle.table)][:1]
        types = dict(self._columns(handle.table))
        sel = ", ".join(_quote(c) for c in cols)
        sql = f"SELECT {sel} FROM {_quote(handle.table)}"
        params: list = []
        if handle.constraint is not None \
                and not handle.constraint.is_all():
            if handle.constraint.is_none:
                sql += " WHERE 1=0"
            else:
                preds = []
                for col, dom in handle.constraint.domains:
                    p, ps = domain_to_sql(col, dom)
                    preds.append(p)
                    params.extend(ps)
                sql += " WHERE " + " AND ".join(preds)
        if handle.limit is not None:
            sql += f" LIMIT {int(handle.limit)}"
        rows = self.execute_remote(sql, params)
        # C-speed transpose: rows -> one value list per column
        lanes = (list(map(list, zip(*rows))) if rows
                 else [[] for _ in cols])
        data: Dict[str, list] = {}
        schema: Dict[str, Type] = {}
        for c, lane in zip(cols, lanes):
            t = types.get(c, VARCHAR)
            if t is BOOLEAN:
                lane = [None if v is None else bool(v) for v in lane]
            elif is_string(t):
                lane = [v.decode("utf-8", "replace")
                        if isinstance(v, bytes) else v for v in lane]
            data[c] = lane
            schema[c] = t
        return batch_from_pylist(data, schema)
