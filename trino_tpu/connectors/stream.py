"""Stream connector: topics of the append-only message log as tables.

Reference parity: plugin/trino-kafka (KafkaMetadata, KafkaSplitManager,
KafkaRecordSetProvider) collapsed onto the in-process broker
(streaming/log.py). A topic is a table in schema ``default``; its rows
are the messages decoded through ``formats/record_decoder.py`` (json /
csv / raw per the topic config), plus two connector columns every
stream table carries:

- ``_partition`` BIGINT — the message's log partition
- ``_offset``    BIGINT — its offset within that partition

(the reference's $-prefixed internal kafka columns; renamed because $
is reserved here for the window suffix). They make the ingest ledger
SQL-visible: ``SELECT _partition, max(_offset) ... GROUP BY 1`` is the
zero-dup/zero-loss proof the streaming e2e asserts.

Offset windows ride the TABLE NAME: a scan of
``"events$win.0:10:20,1:0:15#job1"`` reads exactly offsets [10,20) of
partition 0 and [0,15) of partition 1. The suffix survives plan serde
to any worker process (quoted identifiers pass the tokenizer
verbatim), which is what makes a continuous query's incremental cycle
EXACT: every retry of every task re-reads the identical window, so
first-commit-wins dedup upstream sees bit-identical frames. Scans
without a window read [committed ...0, live end) — a plain
``SELECT count(*) FROM stream.default.events`` watches the log grow.

Splits are per-partition (one split per log partition), so a
multi-partition topic fans out across workers like any other scan.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

from ..catalog import (ColumnMetadata, Connector, Split, TableHandle,
                       TableMetadata)
from ..columnar import Batch, _pad, column_from_pylist
from ..formats.record_decoder import DecoderField, create_decoder
from ..streaming.log import MessageLog, get_log
from ..types import BIGINT, VARCHAR, parse_type

# {partition: (start, end)} — the exact half-open ranges of one scan
Window = Dict[int, Tuple[int, int]]

_PARTITION_COL = "_partition"
_OFFSET_COL = "_offset"


def window_ref(topic: str, window: Window, consumer: str = "") -> str:
    """Encode an exact scan window into a table reference (quote it
    in SQL: ``"events$win.0:0:10#job1"``)."""
    spans = ",".join(f"{p}:{s}:{e}"
                     for p, (s, e) in sorted(window.items()))
    tag = f"#{consumer}" if consumer else ""
    return f"{topic}$win.{spans}{tag}"


def parse_table_ref(name: str) -> Tuple[str, Optional[Window]]:
    """Invert ``window_ref``; a plain topic name parses to (name,
    None) = scan-to-live-end."""
    if "$win." not in name:
        return name, None
    topic, _, rest = name.partition("$win.")
    rest = rest.partition("#")[0]
    window: Window = {}
    for span in rest.split(","):
        if not span:
            continue
        p, s, e = span.split(":")
        window[int(p)] = (int(s), int(e))
    return topic, window


class StreamConnector(Connector):
    name = "stream"
    # appends mutate live-end scans between queries; data_version()
    # below gives the result cache a real invalidation signal instead
    scan_cache_ok = False

    def __init__(self, base_dir: Optional[str] = None,
                 log: Optional[MessageLog] = None):
        self.log = log or get_log(base_dir)

    # --- metadata --------------------------------------------------------
    def list_schemas(self) -> List[str]:
        return ["default"]

    def list_tables(self, schema: str) -> List[str]:
        return self.log.topics() if schema == "default" else []

    def _decoder_fields(self, cfg: dict) -> List[DecoderField]:
        fields = cfg.get("fields") or []
        if not fields:
            # schemaless topic (implicitly created by a first ingest):
            # the whole message is one varchar column
            return [DecoderField("_message", VARCHAR)]
        return [DecoderField(n, parse_type(t), m)
                for n, t, m in fields]

    def get_table_metadata(self, schema: str,
                           table: str) -> Optional[TableMetadata]:
        if schema != "default":
            return None
        topic, _ = parse_table_ref(table)
        cfg = self.log.topic_config(topic)
        if cfg is None:
            return None
        cols = tuple(ColumnMetadata(f.name, f.type)
                     for f in self._decoder_fields(cfg))
        cols += (ColumnMetadata(_PARTITION_COL, BIGINT, hidden=True),
                 ColumnMetadata(_OFFSET_COL, BIGINT, hidden=True))
        # keep the windowed name in the metadata so the handle the
        # planner builds from it round-trips the window through serde
        return TableMetadata(schema, table, cols)

    # --- scan ------------------------------------------------------------
    def _window(self, table: str) -> Tuple[str, Window]:
        topic, window = parse_table_ref(table)
        if window is None:
            window = {p: (0, e)
                      for p, e in self.log.end_offsets(topic).items()}
        return topic, window

    def get_splits(self, handle: TableHandle,
                   desired_parallelism: int = 1) -> List[Split]:
        _, window = self._window(handle.table)
        nparts = max(len(window), 1)
        return [Split(handle, p, nparts) for p in sorted(window)] \
            or [Split(handle, 0, 1)]

    def read_split(self, split: Split,
                   columns: Sequence[str]) -> Batch:
        topic, window = self._window(split.handle.table)
        cfg = self.log.topic_config(topic)
        if cfg is None:
            raise KeyError(f"stream topic {topic!r} does not exist")
        part = sorted(window)[split.part] if window else 0
        start, end = window.get(part, (0, 0))
        messages = self.log.read(topic, part, start, end)
        fields = self._decoder_fields(cfg)
        # schemaless topics (no declared fields) always decode raw:
        # the whole message IS the _message column, json or not
        kind = (cfg.get("decoder", "json") if cfg.get("fields")
                else "raw")
        decoder = create_decoder(kind, fields)
        batch = decoder.decode(messages)
        cap = batch.capacity
        cols = dict(batch.columns)
        n = len(messages)
        cols[_PARTITION_COL] = _pad(
            column_from_pylist([part] * n, BIGINT), cap)
        cols[_OFFSET_COL] = _pad(
            column_from_pylist(list(range(start, start + n)), BIGINT),
            cap)
        return Batch(cols, batch.num_rows).select_columns(
            list(columns))

    def table_row_count(self, handle: TableHandle) -> Optional[float]:
        _, window = self._window(handle.table)
        return float(sum(e - s for s, e in window.values()))

    def data_version(self) -> Optional[int]:
        return self.log.data_version()

    # --- DDL / writes ----------------------------------------------------
    def create_table(self, metadata: TableMetadata) -> None:
        """CREATE TABLE stream.default.t (...) creates the topic with
        the json decoder; each column maps its own name as the
        document path. Connector columns are implicit — declaring
        them is an error."""
        fields = []
        for c in metadata.columns:
            if c.name in (_PARTITION_COL, _OFFSET_COL):
                raise ValueError(
                    f"column {c.name!r} is reserved on stream tables")
            fields.append((c.name, getattr(c.type, "name",
                                           str(c.type)), None))
        self.log.create_topic(metadata.name, "json", fields)

    def drop_table(self, schema: str, table: str) -> None:
        topic, _ = parse_table_ref(table)
        self.log.drop_topic(topic)

    def insert(self, schema: str, table: str, batch: Batch) -> int:
        """INSERT INTO a topic appends one json document per row —
        the SQL-side producer (the HTTP side is /v1/ingest)."""
        import json as _json
        topic, _ = parse_table_ref(table)
        cfg = self.log.topic_config(topic)
        if cfg is None:
            raise KeyError(f"stream topic {topic!r} does not exist")
        names = [n for n in batch.names
                 if n not in (_PARTITION_COL, _OFFSET_COL)]
        rows = batch.select_columns(names).to_pylist()
        msgs = [_json.dumps(dict(zip(names, r)),
                            default=str).encode()
                for r in rows]
        if msgs:
            self.log.append(topic, msgs)
        return len(msgs)
