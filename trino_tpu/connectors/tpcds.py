"""TPC-DS data-generator connector.

Reference parity: plugin/trino-tpcds (TpcdsMetadata.java,
TpcdsRecordSetProvider.java, TpcdsSplitManager.java) — on-the-fly
deterministic TPC-DS data, the star-schema benchmark workhorse
(BASELINE.json configs[4] = q64).

Same TPU-first design as connectors/tpch.py: every value is a pure
function of ``(column_seed, absolute_row_index)`` through a splitmix64
counter hash, so any split generates its row range independently and
fully vectorized — no sequential dsdgen state. Value families
(distributions, vocabularies, key ranges) follow the TPC-DS spec v2.x;
the bit-exact dsdgen output is intentionally not reproduced.

Schema: all 24 TPC-DS tables (the three sales/returns channel pairs,
inventory, and the full dimension set through web_site/ship_mode/
reason/time_dim) with their commonly queried columns. Referential
integrity: every foreign key is drawn from the referenced table's live
key range; returns reference actual sales rows by strided index so
(item_sk, ticket/order) pairs join.
"""

from __future__ import annotations

import datetime
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..catalog import (ColumnMetadata as CM, Connector, Split, TableHandle,
                       TableMetadata)
from ..columnar import Batch, Column, StringDictionary, pad_batch
from ..config import capacity_for
from ..types import BIGINT, DATE, DOUBLE, INTEGER, Type, VarcharType
from .tpch import _mix, _u64, _randint, _uniform, _strings

_EPOCH = datetime.date(1970, 1, 1).toordinal()

# d_date_sk numbering: Julian-day style; sk 2415022 == 1900-01-02
_SK0 = 2415022
_D0 = datetime.date(1900, 1, 2).toordinal()
_N_DATES = 73049  # 1900-01-02 .. 2100-01-01, fixed at every SF


def _date_sk(y: int, m: int, d: int) -> int:
    return _SK0 + (datetime.date(y, m, d).toordinal() - _D0)


_SALES_SK_LO = _date_sk(1998, 1, 1)
_SALES_SK_HI = _date_sk(2002, 12, 31)

SCHEMAS: Dict[str, float] = {
    "tiny": 0.01, "sf1": 1.0, "sf10": 10.0, "sf100": 100.0,
}

# spec row counts at known scale points (TpcdsScaling); geometric
# interpolation elsewhere. None -> fixed count at every scale.
_SCALE_POINTS = {
    "store_sales":           {0.01: 120527, 1: 2880404, 10: 28800991,
                              100: 287997024},
    "store_returns":         {0.01: 11925, 1: 287514, 10: 2875432,
                              100: 28795080},
    "catalog_sales":         {0.01: 89807, 1: 1441548, 10: 14401261,
                              100: 143997065},
    "catalog_returns":       {0.01: 8923, 1: 144067, 10: 1439749,
                              100: 14404374},
    "item":                  {0.01: 2000, 1: 18000, 10: 102000,
                              100: 204000},
    "customer":              {0.01: 1000, 1: 100000, 10: 500000,
                              100: 2000000},
    "customer_address":      {0.01: 1000, 1: 50000, 10: 250000,
                              100: 1000000},
    "customer_demographics": {0.01: 19208, 1: 1920800, 10: 1920800,
                              100: 1920800},
    "store":                 {0.01: 2, 1: 12, 10: 102, 100: 402},
    "promotion":             {0.01: 30, 1: 300, 10: 500, 100: 1000},
    "warehouse":             {0.01: 1, 1: 5, 10: 10, 100: 15},
    "web_sales":             {0.01: 7198, 1: 719384, 10: 7197566,
                              100: 71997522},
    "web_returns":           {0.01: 718, 1: 71763, 10: 719217,
                              100: 7197670},
    "web_site":              {0.01: 2, 1: 30, 10: 42, 100: 54},
    "web_page":              {0.01: 2, 1: 60, 10: 200, 100: 2040},
    "inventory":             {0.01: 117450, 1: 11745000, 10: 133110000,
                              100: 399330000},
    "call_center":           {0.01: 2, 1: 6, 10: 24, 100: 30},
    "catalog_page":          {0.01: 11718, 1: 11718, 10: 12000,
                              100: 20400},
    "reason":                {0.01: 35, 1: 35, 10: 60, 100: 70},
    "household_demographics": None,   # 7200 fixed
    "income_band":           None,    # 20 fixed
    "date_dim":              None,    # 73049 fixed
    "time_dim":              None,    # 86400 fixed
    "ship_mode":             None,    # 20 fixed
}
_FIXED_ROWS = {"household_demographics": 7200, "income_band": 20,
               "date_dim": _N_DATES, "time_dim": 86400,
               "ship_mode": 20}


def table_rows(table: str, sf: float) -> int:
    pts = _SCALE_POINTS[table]
    if pts is None:
        return _FIXED_ROWS[table]
    if sf in pts:
        return pts[sf]
    keys = sorted(pts)
    if sf <= keys[0]:
        return max(1, int(pts[keys[0]] * sf / keys[0]))
    for lo, hi in zip(keys, keys[1:]):
        if sf <= hi:
            # geometric interpolation in log-sf space
            import math
            t = (math.log(sf) - math.log(lo)) / (
                math.log(hi) - math.log(lo))
            return int(pts[lo] * (pts[hi] / pts[lo]) ** t)
    return int(pts[keys[-1]] * sf / keys[-1])


# --------------------------------------------------------------------------
# vocabularies (spec-style value families)
# --------------------------------------------------------------------------

COLORS = ("purple burlywood indian spring floral medium almond antique "
          "aquamarine azure beige bisque black blanched blue blush brown "
          "chartreuse chiffon chocolate coral cornflower cornsilk cream "
          "cyan dark deep dim dodger drab firebrick forest frosted "
          "gainsboro ghost goldenrod green grey honeydew hot ivory khaki "
          "lace lavender lawn lemon light lime linen magenta maroon "
          "metallic midnight mint misty moccasin navajo navy olive orange "
          "orchid pale papaya peach peru pink plum powder puff red rose "
          "rosy royal saddle salmon sandy seashell sienna sky slate smoke "
          "snow steel tan thistle tomato turquoise violet wheat white "
          "yellow").split()

_UNITS = ("Unknown ought able pri ese anti cally ation eing n st").split()
_STREET_NAMES = ("Main Oak Park First Second Elm Lake Hill Maple Pine "
                 "Cedar Ridge Spring View Walnut Washington Wilson "
                 "Church College Davis Dogwood Fifth Forest Fourth "
                 "Franklin Green Highland Jackson Johnson Lee Lincoln "
                 "Locust Meadow Mill North Poplar railroad River Smith "
                 "South Sunset Sycamore Third Valley West Williams "
                 "Woodland 1st 2nd 3rd 4th 5th 6th 7th 8th 9th 10th "
                 "11th 12th 13th 14th 15th").split()
_STREET_TYPES = ("Street Ave Blvd Boulevard Circle Court Ct Dr Drive "
                 "Lane Ln Parkway Pkwy RD Road ST Way Wy").split()
_CITIES = ("Midway Fairview Oakland Five_Points Oak_Grove Pleasant_Hill "
           "Centerville Liberty Salem Greenville Bethel Clinton "
           "Springfield Marion Union Wilson Glendale Antioch Concord "
           "Enterprise Farmington Five_Forks Friendship Georgetown "
           "Glenwood Greenfield Greenwood Hamilton Harmony Highland_Park "
           "Hillcrest Hopewell Jackson Jamestown Kingston Lakeside "
           "Lakeview Lebanon Lincoln Macedonia Maple_Grove Mount_Olive "
           "Mount_Pleasant Mount_Vernon Mount_Zion New_Hope Newport "
           "Newtown Oakdale Oakwood Philadelphia Pine_Grove Pleasant_"
           "Grove Pleasant_Valley Plainview Providence Riverdale "
           "Riverside Riverview Shady_Grove Shiloh Spring_Hill "
           "Spring_Valley Stringtown Summit Sunnyside Unionville "
           "Valley_View Walnut_Grove Waterloo Westgate White_Oak "
           "Wildwood Woodland Woodlawn Woodville").split()
_MARITAL = ["M", "S", "D", "W", "U"]
_GENDER = ["M", "F"]
_EDUCATION = ["Primary", "Secondary", "College", "2 yr Degree",
              "4 yr Degree", "Advanced Degree", "Unknown"]
_CREDIT = ["Low Risk", "High Risk", "Good", "Unknown"]
_BUY_POTENTIAL = [">10000", "5001-10000", "1001-5000", "501-1000",
                  "0-500", "Unknown"]
_PROMO_CHANNELS = ["N", "Y"]
_CATEGORIES = ["Women", "Men", "Children", "Shoes", "Music", "Jewelry",
               "Home", "Sports", "Books", "Electronics"]
_P_NAMES = ("ese anti pri ought able eing cally ation n st bar ation "
            "eingoughtable callyought ableought").split()


def _zip_strings(seed: int, idx: np.ndarray, typ: Type) -> Column:
    z = (_u64(seed, idx) % np.uint64(100000)).astype(np.int64)
    vals = [f"{v:05d}" for v in range(0, 100000, 97)]
    # snap to a bounded dictionary (zips repeat heavily in reality)
    codes = (z % np.uint64(len(vals))).astype(np.int32)
    return _strings(vals, codes, typ)


def _word_column(seed: int, idx: np.ndarray, words: List[str],
                 n_words: int, typ: Type) -> Column:
    picks = [_randint(seed + k, idx, 0, len(words) - 1)
             for k in range(n_words)]
    out = np.empty(len(idx), dtype=object)
    for i in range(len(idx)):
        out[i] = " ".join(words[int(picks[k][i])] for k in range(n_words))
    dic, codes = StringDictionary.from_strings(list(out))
    return Column(typ, codes, None, dic)


def _key_name_column(prefix: str, idx: np.ndarray, typ: Type) -> Column:
    out = np.empty(len(idx), dtype=object)
    for i in range(len(idx)):
        out[i] = f"{prefix}{int(idx[i]):016d}"
    dic, codes = StringDictionary.from_strings(list(out))
    return Column(typ, codes, None, dic)


# seed order is FROZEN for the original 14 tables (reordering would
# silently regenerate every dataset); new tables append after them
_SEED_ORDER = [
    "catalog_returns", "catalog_sales", "customer", "customer_address",
    "customer_demographics", "date_dim", "household_demographics",
    "income_band", "item", "promotion", "store", "store_returns",
    "store_sales", "warehouse",
    # round-4 additions
    "web_sales", "web_returns", "web_site", "web_page", "inventory",
    "time_dim", "reason", "ship_mode", "call_center", "catalog_page",
]
_SEED = {t: 1000 + 31 * i for i, t in enumerate(_SEED_ORDER)}


def _fk(seed: int, idx: np.ndarray, n_ref: int,
        null_frac: float = 0.0):
    """Foreign key into [1, n_ref]; optional NULL fraction."""
    k = 1 + (_u64(seed, idx) % np.uint64(max(n_ref, 1))).astype(np.int64)
    if null_frac <= 0.0:
        return k, None
    valid = _uniform(seed + 7777, idx) >= null_frac
    return k, valid


def _price(seed: int, idx: np.ndarray, lo: float, hi: float) -> np.ndarray:
    return np.round(lo + _uniform(seed, idx) * (hi - lo), 2)


class TpcdsConnector(Connector):
    name = "tpcds"
    scan_cache_ok = True      # pure generator: splits are immutable

    def __init__(self, rows_per_split: int = 1 << 17):
        self.rows_per_split = rows_per_split

    # --- metadata --------------------------------------------------------
    def list_schemas(self) -> List[str]:
        return list(SCHEMAS)

    def list_tables(self, schema: str) -> List[str]:
        return sorted(_SCALE_POINTS) if schema in SCHEMAS else []

    def get_table_metadata(self, schema, table) -> Optional[TableMetadata]:
        if schema in SCHEMAS and table in _TABLES:
            return TableMetadata(schema, table, tuple(_TABLES[table]))
        return None

    def table_row_count(self, handle: TableHandle) -> Optional[float]:
        return float(table_rows(handle.table, SCHEMAS[handle.schema]))

    # --- splits ----------------------------------------------------------
    def get_splits(self, handle: TableHandle,
                   desired_parallelism: int = 1) -> List[Split]:
        sf = SCHEMAS[handle.schema]
        units = table_rows(handle.table, sf)
        per = self.rows_per_split
        n_splits = max(1, (units + per - 1) // per)
        return [Split(handle, p, n_splits) for p in range(n_splits)]

    # --- data ------------------------------------------------------------
    def read_split(self, split: Split, columns: Sequence[str]) -> Batch:
        sf = SCHEMAS[split.handle.schema]
        table = split.handle.table
        units = table_rows(table, sf)
        lo = split.part * units // split.part_count
        hi = (split.part + 1) * units // split.part_count
        idx = np.arange(lo + 1, hi + 1, dtype=np.int64)  # 1-based keys
        gen = getattr(self, "_" + table)
        return gen(idx, sf, columns)

    def _finish(self, cols: Dict[str, Column], n: int,
                columns: Sequence[str]) -> Batch:
        out = {name: cols[name] for name in columns}
        return pad_batch(Batch(out, n), capacity_for(n, minimum=8))

    # --- dimension tables ------------------------------------------------
    def _date_dim(self, idx, sf, columns) -> Batch:
        need = set(columns)
        ords = _D0 + (idx - 1)
        days = ords - _EPOCH
        # vectorized calendar via numpy datetime64
        d64 = days.astype("datetime64[D]")
        y = d64.astype("datetime64[Y]").astype(np.int64) + 1970
        m64 = d64.astype("datetime64[M]")
        moy = (m64.astype(np.int64) % 12) + 1
        dom = (d64 - m64.astype("datetime64[D]")).astype(np.int64) + 1
        cols: Dict[str, Column] = {
            "d_date_sk": Column(BIGINT, _SK0 + (idx - 1), None),
            "d_date": Column(DATE, days.astype(np.int64), None),
            "d_year": Column(INTEGER, y.astype(np.int64), None),
            "d_moy": Column(INTEGER, moy.astype(np.int64), None),
            "d_dom": Column(INTEGER, dom.astype(np.int64), None),
            "d_qoy": Column(INTEGER, ((moy - 1) // 3 + 1), None),
            "d_dow": Column(INTEGER, (days + 4) % 7, None),
        }
        if "d_month_seq" in need:
            cols["d_month_seq"] = Column(
                BIGINT, (y - 1900) * 12 + (moy - 1), None)
        if "d_week_seq" in need:
            cols["d_week_seq"] = Column(BIGINT, (days + 4) // 7, None)
        if "d_day_name" in need:
            names = ["Sunday", "Monday", "Tuesday", "Wednesday",
                     "Thursday", "Friday", "Saturday"]
            cols["d_day_name"] = _strings(
                names, ((days + 4) % 7).astype(np.int32), VarcharType(9))
        if "d_quarter_name" in need:
            y0, y1 = int(y.min()), int(y.max())
            vals = [f"{yy}Q{q}" for yy in range(y0, y1 + 1)
                    for q in range(1, 5)]
            codes = ((y - y0) * 4 + (moy - 1) // 3).astype(np.int32)
            cols["d_quarter_name"] = _strings(vals, codes,
                                              VarcharType(6))
        return self._finish(cols, len(idx), columns)

    def _item(self, idx, sf, columns) -> Batch:
        S = _SEED["item"]
        need = set(columns)
        n = len(idx)
        cols: Dict[str, Column] = {
            "i_item_sk": Column(BIGINT, idx.copy(), None)}
        if "i_item_id" in need:
            cols["i_item_id"] = _key_name_column("AAAAAAAA", idx,
                                                 VarcharType(16))
        if "i_product_name" in need:
            cols["i_product_name"] = _word_column(
                S + 2, idx, _P_NAMES, 4, VarcharType(50))
        if "i_item_desc" in need:
            cols["i_item_desc"] = _word_column(
                S + 13, idx, _P_NAMES, 8, VarcharType(200))
        if "i_color" in need:
            cols["i_color"] = _strings(
                COLORS,
                (_u64(S + 3, idx) % np.uint64(len(COLORS))).astype(
                    np.int32), VarcharType(20))
        cols["i_current_price"] = Column(
            DOUBLE, _price(S + 4, idx, 0.09, 99.99), None)
        cols["i_wholesale_cost"] = Column(
            DOUBLE, _price(S + 5, idx, 0.05, 70.0), None)
        if "i_brand_id" in need or "i_brand" in need:
            brand_id = _randint(S + 6, idx, 1, 1000)
            cols["i_brand_id"] = Column(BIGINT, brand_id, None)
            if "i_brand" in need:
                vals = [f"{_UNITS[b % 10]}{_UNITS[(b // 10) % 10]} #{b}"
                        for b in range(1, 1001)]
                cols["i_brand"] = _strings(
                    vals, (brand_id - 1).astype(np.int32), VarcharType(50))
        if "i_manufact_id" in need or "i_manufact" in need:
            mid = _randint(S + 7, idx, 1, 1000)
            cols["i_manufact_id"] = Column(BIGINT, mid, None)
            if "i_manufact" in need:
                vals = [f"{_UNITS[m % 10]}{_UNITS[(m // 10) % 10]}"
                        for m in range(1, 1001)]
                cols["i_manufact"] = _strings(
                    vals, (mid - 1).astype(np.int32), VarcharType(50))
        if "i_category" in need or "i_category_id" in need:
            cid = _randint(S + 8, idx, 1, len(_CATEGORIES))
            cols["i_category_id"] = Column(BIGINT, cid, None)
            cols["i_category"] = _strings(
                _CATEGORIES, (cid - 1).astype(np.int32), VarcharType(50))
        if "i_class_id" in need or "i_class" in need:
            clid = _randint(S + 9, idx, 1, 16)
            cols["i_class_id"] = Column(BIGINT, clid, None)
            if "i_class" in need:
                vals = [f"class#{c}" for c in range(1, 17)]
                cols["i_class"] = _strings(
                    vals, (clid - 1).astype(np.int32), VarcharType(50))
        if "i_manager_id" in need:
            cols["i_manager_id"] = Column(
                BIGINT, _randint(S + 10, idx, 1, 100), None)
        if "i_size" in need:
            sizes = ["petite", "small", "medium", "large", "extra large",
                     "N/A"]
            cols["i_size"] = _strings(
                sizes, (_u64(S + 11, idx) % np.uint64(6)).astype(np.int32),
                VarcharType(20))
        if "i_units" in need:
            units = ["Each", "Dozen", "Case", "Pallet", "Gross", "Box",
                     "Pound", "Ounce", "Ton", "Unknown"]
            cols["i_units"] = _strings(
                units, (_u64(S + 12, idx) % np.uint64(10)).astype(
                    np.int32), VarcharType(10))
        return self._finish(cols, n, columns)

    def _customer(self, idx, sf, columns) -> Batch:
        S = _SEED["customer"]
        need = set(columns)
        n = len(idx)
        n_cd = table_rows("customer_demographics", sf)
        n_hd = table_rows("household_demographics", sf)
        n_ca = table_rows("customer_address", sf)
        cols: Dict[str, Column] = {
            "c_customer_sk": Column(BIGINT, idx.copy(), None)}
        if "c_customer_id" in need:
            cols["c_customer_id"] = _key_name_column(
                "AAAAAAAA", idx, VarcharType(16))
        for name, nref, s in (("c_current_cdemo_sk", n_cd, 2),
                              ("c_current_hdemo_sk", n_hd, 3),
                              ("c_current_addr_sk", n_ca, 4)):
            k, v = _fk(S + s, idx, nref, null_frac=0.02)
            cols[name] = Column(BIGINT, k, v)
        for name, s in (("c_first_sales_date_sk", 5),
                        ("c_first_shipto_date_sk", 6)):
            sk = _randint(S + s, idx, _date_sk(1990, 1, 1),
                          _date_sk(2002, 12, 31))
            cols[name] = Column(BIGINT, sk, None)
        if "c_first_name" in need:
            names = [f"First{i}" for i in range(512)]
            cols["c_first_name"] = _strings(
                names, (_u64(S + 8, idx) % np.uint64(512)).astype(
                    np.int32), VarcharType(20))
        if "c_last_name" in need:
            names = [f"Last{i}" for i in range(1024)]
            cols["c_last_name"] = _strings(
                names, (_u64(S + 9, idx) % np.uint64(1024)).astype(
                    np.int32), VarcharType(30))
        if "c_birth_year" in need:
            cols["c_birth_year"] = Column(
                INTEGER, _randint(S + 10, idx, 1924, 1992), None)
        if "c_birth_month" in need:
            cols["c_birth_month"] = Column(
                INTEGER, _randint(S + 12, idx, 1, 12), None)
        if "c_birth_day" in need:
            cols["c_birth_day"] = Column(
                INTEGER, _randint(S + 13, idx, 1, 28), None)
        if "c_preferred_cust_flag" in need:
            cols["c_preferred_cust_flag"] = _strings(
                ["N", "Y"],
                (_u64(S + 14, idx) % np.uint64(2)).astype(np.int32),
                VarcharType(1))
        if "c_salutation" in need:
            sal = ["Mr.", "Mrs.", "Ms.", "Miss", "Dr.", "Sir"]
            cols["c_salutation"] = _strings(
                sal, (_u64(S + 15, idx) % np.uint64(6)).astype(np.int32),
                VarcharType(10))
        if "c_email_address" in need:
            cols["c_email_address"] = _key_name_column(
                "Customer@example.", idx, VarcharType(50))
        if "c_last_review_date_sk" in need:
            cols["c_last_review_date_sk"] = Column(
                BIGINT, _randint(S + 16, idx, _date_sk(1999, 1, 1),
                                 _date_sk(2002, 12, 31)), None)
        if "c_birth_country" in need:
            from .tpch import NATIONS
            vals = [n0.upper() for n0, _ in NATIONS]
            cols["c_birth_country"] = _strings(
                vals, (_u64(S + 11, idx) % np.uint64(len(vals))).astype(
                    np.int32), VarcharType(20))
        return self._finish(cols, n, columns)

    def _customer_address(self, idx, sf, columns) -> Batch:
        S = _SEED["customer_address"]
        n = len(idx)
        cols: Dict[str, Column] = {
            "ca_address_sk": Column(BIGINT, idx.copy(), None)}
        num_vals = [str(v) for v in range(1, 1001)]
        cols["ca_street_number"] = _strings(
            num_vals, (_u64(S + 2, idx) % np.uint64(1000)).astype(
                np.int32), VarcharType(10))
        sn = (_u64(S + 3, idx)
              % np.uint64(len(_STREET_NAMES))).astype(np.int64)
        st = (_u64(S + 4, idx)
              % np.uint64(len(_STREET_TYPES))).astype(np.int64)
        vals = [f"{a} {b}" for a in _STREET_NAMES for b in _STREET_TYPES]
        codes = (sn * len(_STREET_TYPES) + st).astype(np.int32)
        cols["ca_street_name"] = _strings(vals, codes, VarcharType(60))
        cols["ca_city"] = _strings(
            [c.replace("_", " ") for c in _CITIES],
            (_u64(S + 5, idx) % np.uint64(len(_CITIES))).astype(np.int32),
            VarcharType(60))
        cols["ca_zip"] = _zip_strings(S + 6, idx, VarcharType(10))
        cols["ca_state"] = _strings(
            ["AL", "CA", "GA", "IL", "IN", "KS", "KY", "LA", "MI", "MN",
             "MO", "MS", "NC", "NE", "NY", "OH", "OK", "OR", "PA", "SC",
             "TN", "TX", "VA", "WA", "WV"],
            (_u64(S + 7, idx) % np.uint64(25)).astype(np.int32),
            VarcharType(2))
        cols["ca_country"] = _strings(
            ["United States"], np.zeros(n, np.int32), VarcharType(20))
        cols["ca_county"] = _strings(
            ["Williamson County", "Ziebach County", "Walker County",
             "Daviess County", "Barrow County", "Franklin Parish",
             "Luce County", "Richland County", "Furnas County",
             "Maverick County"],
            (_u64(S + 8, idx) % np.uint64(10)).astype(np.int32),
            VarcharType(30))
        cols["ca_gmt_offset"] = Column(
            DOUBLE, -5.0 - (_u64(S + 9, idx)
                            % np.uint64(4)).astype(np.int64), None)
        cols["ca_street_type"] = _strings(
            _STREET_TYPES,
            (_u64(S + 10, idx)
             % np.uint64(len(_STREET_TYPES))).astype(np.int32),
            VarcharType(15))
        cols["ca_suite_number"] = _strings(
            [f"Suite {v}" for v in range(0, 100, 10)],
            (_u64(S + 11, idx) % np.uint64(10)).astype(np.int32),
            VarcharType(10))
        cols["ca_location_type"] = _strings(
            ["apartment", "condo", "single family"],
            (_u64(S + 12, idx) % np.uint64(3)).astype(np.int32),
            VarcharType(20))
        return self._finish(cols, n, columns)

    def _customer_demographics(self, idx, sf, columns) -> Batch:
        # fully cross-joined demographic space, decoded from the key
        # (spec: cd is the cross product of its attribute domains)
        n = len(idx)
        k = idx - 1
        cols: Dict[str, Column] = {
            "cd_demo_sk": Column(BIGINT, idx.copy(), None)}
        g = (k % 2).astype(np.int32)
        k2 = k // 2
        ms = (k2 % 5).astype(np.int32)
        k3 = k2 // 5
        ed = (k3 % 7).astype(np.int32)
        k4 = k3 // 7
        cols["cd_gender"] = _strings(_GENDER, g, VarcharType(1))
        cols["cd_marital_status"] = _strings(_MARITAL, ms, VarcharType(1))
        cols["cd_education_status"] = _strings(_EDUCATION, ed,
                                               VarcharType(20))
        cols["cd_purchase_estimate"] = Column(
            BIGINT, ((k4 % 20) + 1) * 500, None)
        cols["cd_credit_rating"] = _strings(
            _CREDIT, ((k4 // 20) % 4).astype(np.int32), VarcharType(10))
        cols["cd_dep_count"] = Column(BIGINT, (k4 // 80) % 7, None)
        cols["cd_dep_employed_count"] = Column(
            BIGINT, (k4 // 560) % 7, None)
        cols["cd_dep_college_count"] = Column(
            BIGINT, (k4 // 3920) % 7, None)
        return self._finish(cols, n, columns)

    def _household_demographics(self, idx, sf, columns) -> Batch:
        n = len(idx)
        k = idx - 1
        cols: Dict[str, Column] = {
            "hd_demo_sk": Column(BIGINT, idx.copy(), None)}
        cols["hd_income_band_sk"] = Column(BIGINT, (k % 20) + 1, None)
        cols["hd_buy_potential"] = _strings(
            _BUY_POTENTIAL, ((k // 20) % 6).astype(np.int32),
            VarcharType(15))
        cols["hd_dep_count"] = Column(BIGINT, (k // 120) % 10, None)
        cols["hd_vehicle_count"] = Column(BIGINT, (k // 1200) % 6 - 1,
                                          None)
        return self._finish(cols, n, columns)

    def _income_band(self, idx, sf, columns) -> Batch:
        n = len(idx)
        cols = {
            "ib_income_band_sk": Column(BIGINT, idx.copy(), None),
            "ib_lower_bound": Column(BIGINT, (idx - 1) * 10000, None),
            "ib_upper_bound": Column(BIGINT, idx * 10000 - 1, None),
        }
        return self._finish(cols, n, columns)

    def _store(self, idx, sf, columns) -> Batch:
        S = _SEED["store"]
        n = len(idx)
        cols: Dict[str, Column] = {
            "s_store_sk": Column(BIGINT, idx.copy(), None)}
        cols["s_store_id"] = _key_name_column("AAAAAAAA", idx,
                                              VarcharType(16))
        names = [f"{u}" for u in _UNITS]
        cols["s_store_name"] = _strings(
            names, ((idx - 1) % len(names)).astype(np.int32),
            VarcharType(50))
        cols["s_zip"] = _zip_strings(S + 3, idx, VarcharType(10))
        cols["s_state"] = _strings(
            ["TN", "OH", "TX", "GA", "IL"],
            (_u64(S + 4, idx) % np.uint64(5)).astype(np.int32),
            VarcharType(2))
        cols["s_city"] = _strings(
            [c.replace("_", " ") for c in _CITIES[:20]],
            (_u64(S + 5, idx) % np.uint64(20)).astype(np.int32),
            VarcharType(60))
        cols["s_number_employees"] = Column(
            BIGINT, _randint(S + 6, idx, 200, 300), None)
        cols["s_county"] = _strings(
            ["Williamson County", "Ziebach County", "Walker County",
             "Daviess County", "Barrow County"],
            (_u64(S + 7, idx) % np.uint64(5)).astype(np.int32),
            VarcharType(30))
        cols["s_company_name"] = _strings(
            ["Unknown"], np.zeros(n, np.int32), VarcharType(50))
        cols["s_company_id"] = Column(BIGINT, np.ones(n, np.int64),
                                      None)
        cols["s_market_id"] = Column(
            BIGINT, _randint(S + 12, idx, 1, 10), None)
        cols["s_street_number"] = _strings(
            [str(v) for v in range(1, 1001)],
            (_u64(S + 8, idx) % np.uint64(1000)).astype(np.int32),
            VarcharType(10))
        sn = (_u64(S + 9, idx)
              % np.uint64(len(_STREET_NAMES))).astype(np.int32)
        cols["s_street_name"] = _strings(_STREET_NAMES, sn,
                                         VarcharType(60))
        cols["s_street_type"] = _strings(
            _STREET_TYPES,
            (_u64(S + 10, idx)
             % np.uint64(len(_STREET_TYPES))).astype(np.int32),
            VarcharType(15))
        cols["s_suite_number"] = _strings(
            [f"Suite {v}" for v in range(0, 100, 10)],
            (_u64(S + 11, idx) % np.uint64(10)).astype(np.int32),
            VarcharType(10))
        return self._finish(cols, n, columns)

    def _promotion(self, idx, sf, columns) -> Batch:
        S = _SEED["promotion"]
        n = len(idx)
        cols: Dict[str, Column] = {
            "p_promo_sk": Column(BIGINT, idx.copy(), None)}
        cols["p_promo_id"] = _key_name_column("AAAAAAAA", idx,
                                              VarcharType(16))
        for cname, s in (("p_channel_dmail", 2), ("p_channel_email", 3),
                         ("p_channel_tv", 4), ("p_channel_event", 5),
                         ("p_channel_catalog", 6)):
            cols[cname] = _strings(
                _PROMO_CHANNELS,
                (_u64(S + s, idx) % np.uint64(2)).astype(np.int32),
                VarcharType(1))
        cols["p_cost"] = Column(DOUBLE, _price(S + 7, idx, 500.0, 2000.0),
                                None)
        return self._finish(cols, n, columns)

    def _warehouse(self, idx, sf, columns) -> Batch:
        S = _SEED["warehouse"]
        n = len(idx)
        cols = {
            "w_warehouse_sk": Column(BIGINT, idx.copy(), None),
            "w_warehouse_name": _key_name_column("Warehouse#", idx,
                                                 VarcharType(20)),
            "w_warehouse_sq_ft": Column(
                BIGINT, _randint(S + 2, idx, 50000, 1000000), None),
            "w_city": _strings(
                [c.replace("_", " ") for c in _CITIES[:20]],
                (_u64(S + 3, idx) % np.uint64(20)).astype(np.int32),
                VarcharType(60)),
            "w_county": _strings(
                ["Williamson County", "Ziebach County", "Walker County",
                 "Daviess County", "Barrow County"],
                (_u64(S + 4, idx) % np.uint64(5)).astype(np.int32),
                VarcharType(30)),
            "w_state": _strings(
                ["TN", "OH", "TX", "GA", "IL"],
                (_u64(S + 5, idx) % np.uint64(5)).astype(np.int32),
                VarcharType(2)),
            "w_country": _strings(
                ["United States"], np.zeros(n, np.int32),
                VarcharType(20)),
        }
        return self._finish(cols, n, columns)

    def _time_dim(self, idx, sf, columns) -> Batch:
        """One row per second of day: sk 0..86399 (spec time_dim)."""
        n = len(idx)
        t = idx - 1                       # 0-based seconds
        hour = t // 3600
        minute = (t // 60) % 60
        second = t % 60
        cols: Dict[str, Column] = {
            "t_time_sk": Column(BIGINT, t.copy(), None),
            "t_time": Column(BIGINT, t.copy(), None),
            "t_hour": Column(BIGINT, hour, None),
            "t_minute": Column(BIGINT, minute, None),
            "t_second": Column(BIGINT, second, None),
        }
        cols["t_am_pm"] = _strings(
            ["AM", "PM"], (hour >= 12).astype(np.int32), VarcharType(2))
        meal = np.select(
            [(hour >= 6) & (hour <= 8), (hour >= 11) & (hour <= 13),
             (hour >= 17) & (hour <= 19)],
            [1, 2, 3], default=0).astype(np.int32)
        cols["t_meal_time"] = Column(
            VarcharType(20),
            meal,
            meal > 0,
            StringDictionary(np.asarray(
                ["", "breakfast", "lunch", "dinner"], dtype=object)))
        return self._finish(cols, n, columns)

    def _reason(self, idx, sf, columns) -> Batch:
        n = len(idx)
        descs = ["Package was damaged", "Stopped working",
                 "Did not get it on time", "Not the product that was "
                 "ordred", "Parts missing", "Does not work with a "
                 "product that I have", "Gift exchange", "Did not like "
                 "the color", "Did not like the model", "Did not like "
                 "the make", "Did not fit", "Wrong size", "Lost my job",
                 "unauthoized purchase", "Found a better price in a "
                 "store", "Found a better extension in a store",
                 "No service location in my area", "Duplicate purchase",
                 "Its the best", "Did not like the warranty",
                 "reason 21", "reason 22", "reason 23", "reason 24",
                 "reason 25", "reason 26", "reason 27", "reason 28",
                 "reason 29", "reason 30", "reason 31", "reason 32",
                 "reason 33", "reason 34", "reason 35"]
        cols = {
            "r_reason_sk": Column(BIGINT, idx.copy(), None),
            "r_reason_id": _key_name_column("AAAAAAAA", idx,
                                            VarcharType(16)),
            "r_reason_desc": _strings(
                descs, ((idx - 1) % len(descs)).astype(np.int32),
                VarcharType(100)),
        }
        return self._finish(cols, n, columns)

    def _ship_mode(self, idx, sf, columns) -> Batch:
        n = len(idx)
        types = ["EXPRESS", "NEXT DAY", "OVERNIGHT", "REGULAR",
                 "TWO DAY"]
        carriers = ["UPS", "FEDEX", "AIRBORNE", "USPS", "DHL", "TBS",
                    "ZHOU", "ZOUROS", "MSC", "LATVIAN", "DIAMOND",
                    "BARIAN", "ALLIANCE", "ORIENTAL", "BOXBUNDLES",
                    "GREAT EASTERN", "HARMSTORF", "PRIVATECARRIER",
                    "GERMA", "RUPEKSA"]
        cols = {
            "sm_ship_mode_sk": Column(BIGINT, idx.copy(), None),
            "sm_ship_mode_id": _key_name_column("AAAAAAAA", idx,
                                                VarcharType(16)),
            "sm_type": _strings(
                types, ((idx - 1) % 5).astype(np.int32), VarcharType(30)),
            "sm_carrier": _strings(
                carriers, ((idx - 1) % 20).astype(np.int32),
                VarcharType(20)),
            "sm_code": _strings(
                ["AIR", "SURFACE", "SEA", "LIBRARY"],
                ((idx - 1) % 4).astype(np.int32), VarcharType(10)),
        }
        return self._finish(cols, n, columns)

    def _call_center(self, idx, sf, columns) -> Batch:
        S = _SEED["call_center"]
        n = len(idx)
        names = ["NY Metro", "Mid Atlantic", "Mideast", "North Midwest",
                 "Pacific Northwest", "Southwest", "California",
                 "Hawaii/Alaska", "Northeast", "Southeast"]
        cols = {
            "cc_call_center_sk": Column(BIGINT, idx.copy(), None),
            "cc_call_center_id": _key_name_column("AAAAAAAA", idx,
                                                  VarcharType(16)),
            "cc_name": _strings(
                names, ((idx - 1) % len(names)).astype(np.int32),
                VarcharType(50)),
            "cc_manager": _word_column(S + 2, idx, _P_NAMES, 2,
                                       VarcharType(40)),
            "cc_county": _strings(
                ["Williamson County", "Ziebach County", "Walker County",
                 "Daviess County", "Barrow County"],
                (_u64(S + 3, idx) % np.uint64(5)).astype(np.int32),
                VarcharType(30)),
        }
        return self._finish(cols, n, columns)

    def _catalog_page(self, idx, sf, columns) -> Batch:
        n = len(idx)
        cols = {
            "cp_catalog_page_sk": Column(BIGINT, idx.copy(), None),
            "cp_catalog_page_id": _key_name_column("AAAAAAAA", idx,
                                                   VarcharType(16)),
            "cp_catalog_number": Column(
                BIGINT, (idx - 1) // 108 + 1, None),
            "cp_catalog_page_number": Column(
                BIGINT, (idx - 1) % 108 + 1, None),
        }
        return self._finish(cols, n, columns)

    def _web_site(self, idx, sf, columns) -> Batch:
        n = len(idx)
        names = [f"site_{i}" for i in range(40)]
        cols = {
            "web_site_sk": Column(BIGINT, idx.copy(), None),
            "web_site_id": _key_name_column("AAAAAAAA", idx,
                                            VarcharType(16)),
            "web_name": _strings(
                names, ((idx - 1) % len(names)).astype(np.int32),
                VarcharType(50)),
            "web_company_name": _strings(
                ["pri", "ought", "able", "ese", "anti", "cally"],
                ((idx - 1) % 6).astype(np.int32), VarcharType(50)),
        }
        return self._finish(cols, n, columns)

    def _web_page(self, idx, sf, columns) -> Batch:
        S = _SEED["web_page"]
        n = len(idx)
        cols = {
            "wp_web_page_sk": Column(BIGINT, idx.copy(), None),
            "wp_web_page_id": _key_name_column("AAAAAAAA", idx,
                                               VarcharType(16)),
            "wp_char_count": Column(
                BIGINT, _randint(S + 2, idx, 100, 8000), None),
        }
        return self._finish(cols, n, columns)

    def _inventory(self, idx, sf, columns) -> Batch:
        """Weekly stock levels: key decodes to (week, item, warehouse);
        inv_date_sk steps 7 days from 1998-01-01 (spec: one snapshot
        per week over the sales window)."""
        S = _SEED["inventory"]
        n = len(idx)
        n_item = table_rows("item", sf)
        n_wh = table_rows("warehouse", sf)
        k = idx - 1
        # week varies FASTEST so every scale factor covers the whole
        # 5-year window (261 weekly snapshots) — with item-fastest
        # decode, small scales stop in early 1999 and date-filtered
        # queries (q37/q82/q21/q22) go empty at tiny
        week = k % 261
        rest = k // 261
        item = (rest % n_item) + 1
        wh = (rest // n_item) % n_wh + 1
        cols = {
            "inv_date_sk": Column(
                BIGINT, _date_sk(1998, 1, 1) + 7 * week, None),
            "inv_item_sk": Column(BIGINT, item, None),
            "inv_warehouse_sk": Column(BIGINT, wh, None),
            "inv_quantity_on_hand": Column(
                BIGINT, _randint(S + 2, idx, 0, 1000),
                _uniform(S + 3, idx) >= 0.05),
        }
        return self._finish(cols, n, columns)

    # --- fact tables -----------------------------------------------------
    def _store_sales(self, idx, sf, columns) -> Batch:
        S = _SEED["store_sales"]
        need = set(columns)
        n = len(idx)
        n_item = table_rows("item", sf)
        cols: Dict[str, Column] = {}
        # ~12 line items per ticket
        ticket = (idx - 1) // 12 + 1
        cols["ss_ticket_number"] = Column(BIGINT, ticket, None)
        cols["ss_item_sk"] = Column(
            BIGINT, 1 + (_u64(S + 2, idx) % np.uint64(n_item)).astype(
                np.int64), None)
        # per-TICKET foreign keys (all items of a basket share customer,
        # store, date — the spec's ticket semantics)
        cols["ss_sold_date_sk"] = Column(
            BIGINT, _randint(S + 3, ticket, _SALES_SK_LO, _SALES_SK_HI),
            _uniform(S + 103, ticket) >= 0.02)
        if "ss_sold_time_sk" in need:
            cols["ss_sold_time_sk"] = Column(
                BIGINT, _randint(S + 33, ticket, 28800, 75600), None)
        for cname, ref, s, nf in (
                ("ss_customer_sk", table_rows("customer", sf), 4, 0.02),
                ("ss_cdemo_sk",
                 table_rows("customer_demographics", sf), 5, 0.02),
                ("ss_hdemo_sk",
                 table_rows("household_demographics", sf), 6, 0.02),
                ("ss_addr_sk",
                 table_rows("customer_address", sf), 7, 0.02),
                ("ss_store_sk", table_rows("store", sf), 8, 0.02),
                ("ss_promo_sk", table_rows("promotion", sf), 9, 0.02)):
            k, v = _fk(S + s, ticket, ref, null_frac=nf)
            cols[cname] = Column(BIGINT, k, v)
        qty = _randint(S + 10, idx, 1, 100)
        whole = _price(S + 11, idx, 1.0, 100.0)
        lp = np.round(whole * (1.0 + _uniform(S + 12, idx)), 2)
        sp = np.round(lp * (0.2 + 0.8 * _uniform(S + 13, idx)), 2)
        cols["ss_quantity"] = Column(BIGINT, qty, None)
        cols["ss_wholesale_cost"] = Column(DOUBLE, whole, None)
        cols["ss_list_price"] = Column(DOUBLE, lp, None)
        cols["ss_sales_price"] = Column(DOUBLE, sp, None)
        if "ss_ext_sales_price" in need:
            cols["ss_ext_sales_price"] = Column(
                DOUBLE, np.round(sp * qty, 2), None)
        if "ss_ext_list_price" in need:
            cols["ss_ext_list_price"] = Column(
                DOUBLE, np.round(lp * qty, 2), None)
        if "ss_ext_discount_amt" in need:
            cols["ss_ext_discount_amt"] = Column(
                DOUBLE, np.round((lp - sp) * qty, 2), None)
        if "ss_ext_wholesale_cost" in need:
            cols["ss_ext_wholesale_cost"] = Column(
                DOUBLE, np.round(whole * qty, 2), None)
        if "ss_ext_tax" in need:
            cols["ss_ext_tax"] = Column(
                DOUBLE, np.round(sp * qty * 0.01
                                 * _randint(S + 16, idx, 0, 9), 2),
                None)
        cols["ss_coupon_amt"] = Column(
            DOUBLE,
            np.where(_uniform(S + 14, idx) < 0.2,
                     _price(S + 15, idx, 0.0, 500.0), 0.0), None)
        if "ss_net_paid" in need:
            cols["ss_net_paid"] = Column(
                DOUBLE, np.round(sp * qty, 2), None)
        if "ss_net_profit" in need:
            cols["ss_net_profit"] = Column(
                DOUBLE, np.round((sp - whole) * qty, 2), None)
        return self._finish(cols, n, columns)

    def _store_returns(self, idx, sf, columns) -> Batch:
        """Each return references a real store_sales row (strided, so
        (item_sk, ticket_number) pairs are unique and join back)."""
        S = _SEED["store_returns"]
        need = set(columns)
        n = len(idx)
        sf_rows = table_rows("store_sales", sf)
        sr_rows = table_rows("store_returns", sf)
        ss_idx = 1 + (idx - 1) * sf_rows // sr_rows
        Sss = _SEED["store_sales"]
        n_item = table_rows("item", sf)
        ticket = (ss_idx - 1) // 12 + 1
        cols: Dict[str, Column] = {}
        cols["sr_item_sk"] = Column(
            BIGINT, 1 + (_u64(Sss + 2, ss_idx)
                         % np.uint64(n_item)).astype(np.int64), None)
        cols["sr_ticket_number"] = Column(BIGINT, ticket, None)
        cols["sr_returned_date_sk"] = Column(
            BIGINT, _randint(S + 2, idx, _SALES_SK_LO, _SALES_SK_HI),
            None)
        # the return references the originating sale's customer and
        # store (spec: returns come from the matched ticket), so joins
        # back via (ticket, customer) — q17/q25 — find real matches
        k, v = _fk(Sss + 4, ticket, table_rows("customer", sf), 0.02)
        cols["sr_customer_sk"] = Column(BIGINT, k, v)
        if "sr_store_sk" in need:
            k, v = _fk(Sss + 8, ticket, table_rows("store", sf), 0.02)
            cols["sr_store_sk"] = Column(BIGINT, k, v)
        qty = _randint(S + 4, idx, 1, 20)
        cols["sr_return_quantity"] = Column(BIGINT, qty, None)
        amt = _price(S + 5, idx, 1.0, 300.0)
        cols["sr_return_amt"] = Column(DOUBLE, amt, None)
        if "sr_net_loss" in need:
            cols["sr_net_loss"] = Column(
                DOUBLE, _price(S + 6, idx, 0.5, 150.0), None)
        if "sr_reason_sk" in need:
            k, v = _fk(S + 7, idx, table_rows("reason", sf), 0.02)
            cols["sr_reason_sk"] = Column(BIGINT, k, v)
        if "sr_return_time_sk" in need:
            cols["sr_return_time_sk"] = Column(
                BIGINT, _randint(S + 8, idx, 28800, 61200), None)
        for cname, s in (("sr_fee", 9), ("sr_refunded_cash", 10),
                         ("sr_reversed_charge", 11),
                         ("sr_store_credit", 12)):
            if cname in need:
                cols[cname] = Column(
                    DOUBLE, _price(S + s, idx, 0.0, 100.0), None)
        if "sr_cdemo_sk" in need:
            k, v = _fk(S + 13, idx,
                       table_rows("customer_demographics", sf), 0.02)
            cols["sr_cdemo_sk"] = Column(BIGINT, k, v)
        return self._finish(cols, n, columns)

    def _catalog_sales(self, idx, sf, columns) -> Batch:
        S = _SEED["catalog_sales"]
        need = set(columns)
        n = len(idx)
        n_item = table_rows("item", sf)
        cols: Dict[str, Column] = {}
        cols["cs_order_number"] = Column(BIGINT, idx.copy(), None)
        cols["cs_item_sk"] = Column(
            BIGINT, 1 + (_u64(S + 2, idx) % np.uint64(n_item)).astype(
                np.int64), None)
        sold = _randint(S + 3, idx, _SALES_SK_LO, _SALES_SK_HI)
        cols["cs_sold_date_sk"] = Column(BIGINT, sold, None)
        if "cs_ship_date_sk" in need:
            cols["cs_ship_date_sk"] = Column(
                BIGINT, sold + _randint(S + 30, idx, 1, 120), None)
        if "cs_sold_time_sk" in need:
            cols["cs_sold_time_sk"] = Column(
                BIGINT, _randint(S + 31, idx, 0, 86399), None)
        for cname, ref, s in (
                ("cs_bill_customer_sk", table_rows("customer", sf), 4),
                ("cs_ship_customer_sk", table_rows("customer", sf), 5),
                ("cs_bill_cdemo_sk",
                 table_rows("customer_demographics", sf), 12),
                ("cs_bill_hdemo_sk",
                 table_rows("household_demographics", sf), 13),
                ("cs_bill_addr_sk",
                 table_rows("customer_address", sf), 14),
                ("cs_ship_addr_sk",
                 table_rows("customer_address", sf), 15),
                ("cs_call_center_sk", table_rows("call_center", sf), 16),
                ("cs_catalog_page_sk",
                 table_rows("catalog_page", sf), 17),
                ("cs_ship_mode_sk", table_rows("ship_mode", sf), 18),
                ("cs_promo_sk", table_rows("promotion", sf), 19),
                ("cs_warehouse_sk", table_rows("warehouse", sf), 6)):
            k, v = _fk(S + s, idx, ref, 0.02)
            cols[cname] = Column(BIGINT, k, v)
        qty = _randint(S + 7, idx, 1, 100)
        lp = _price(S + 8, idx, 1.0, 200.0)
        cols["cs_quantity"] = Column(BIGINT, qty, None)
        cols["cs_list_price"] = Column(DOUBLE, lp, None)
        cols["cs_ext_list_price"] = Column(
            DOUBLE, np.round(lp * qty, 2), None)
        if need & {"cs_sales_price", "cs_ext_sales_price",
                   "cs_ext_discount_amt", "cs_net_paid"}:
            sp = np.round(lp * (0.2 + 0.8 * _uniform(S + 9, idx)), 2)
            cols["cs_sales_price"] = Column(DOUBLE, sp, None)
            cols["cs_ext_sales_price"] = Column(
                DOUBLE, np.round(sp * qty, 2), None)
            if "cs_ext_discount_amt" in need:
                cols["cs_ext_discount_amt"] = Column(
                    DOUBLE, np.round((lp - sp) * qty, 2), None)
            if "cs_net_paid" in need:
                cols["cs_net_paid"] = Column(
                    DOUBLE, np.round(sp * qty, 2), None)
        if need & {"cs_wholesale_cost", "cs_ext_wholesale_cost"}:
            whole = _price(S + 10, idx, 1.0, 100.0)
            if "cs_wholesale_cost" in need:
                cols["cs_wholesale_cost"] = Column(DOUBLE, whole, None)
            if "cs_ext_wholesale_cost" in need:
                cols["cs_ext_wholesale_cost"] = Column(
                    DOUBLE, np.round(whole * qty, 2), None)
        if "cs_ext_ship_cost" in need:
            cols["cs_ext_ship_cost"] = Column(
                DOUBLE, _price(S + 20, idx, 0.0, 50.0), None)
        if "cs_coupon_amt" in need:
            cols["cs_coupon_amt"] = Column(
                DOUBLE,
                np.where(_uniform(S + 21, idx) < 0.2,
                         _price(S + 22, idx, 0.0, 500.0), 0.0), None)
        if "cs_net_profit" in need:
            cols["cs_net_profit"] = Column(
                DOUBLE, _price(S + 11, idx, -500.0, 500.0), None)
        return self._finish(cols, n, columns)

    def _web_sales(self, idx, sf, columns) -> Batch:
        S = _SEED["web_sales"]
        need = set(columns)
        n = len(idx)
        n_item = table_rows("item", sf)
        cols: Dict[str, Column] = {}
        cols["ws_order_number"] = Column(BIGINT, idx.copy(), None)
        cols["ws_item_sk"] = Column(
            BIGINT, 1 + (_u64(S + 2, idx) % np.uint64(n_item)).astype(
                np.int64), None)
        sold = _randint(S + 3, idx, _SALES_SK_LO, _SALES_SK_HI)
        cols["ws_sold_date_sk"] = Column(BIGINT, sold, None)
        if "ws_ship_date_sk" in need:
            cols["ws_ship_date_sk"] = Column(
                BIGINT, sold + _randint(S + 30, idx, 1, 120), None)
        if "ws_sold_time_sk" in need:
            cols["ws_sold_time_sk"] = Column(
                BIGINT, _randint(S + 31, idx, 0, 86399), None)
        for cname, ref, s in (
                ("ws_bill_customer_sk", table_rows("customer", sf), 4),
                ("ws_ship_customer_sk", table_rows("customer", sf), 5),
                ("ws_bill_cdemo_sk",
                 table_rows("customer_demographics", sf), 12),
                ("ws_bill_hdemo_sk",
                 table_rows("household_demographics", sf), 13),
                ("ws_ship_hdemo_sk",
                 table_rows("household_demographics", sf), 21),
                ("ws_bill_addr_sk",
                 table_rows("customer_address", sf), 14),
                ("ws_ship_addr_sk",
                 table_rows("customer_address", sf), 15),
                ("ws_warehouse_sk", table_rows("warehouse", sf), 6),
                ("ws_web_page_sk", table_rows("web_page", sf), 16),
                ("ws_web_site_sk", table_rows("web_site", sf), 17),
                ("ws_ship_mode_sk", table_rows("ship_mode", sf), 18),
                ("ws_promo_sk", table_rows("promotion", sf), 19)):
            k, v = _fk(S + s, idx, ref, 0.02)
            cols[cname] = Column(BIGINT, k, v)
        qty = _randint(S + 7, idx, 1, 100)
        lp = _price(S + 8, idx, 1.0, 200.0)
        whole = _price(S + 10, idx, 1.0, 100.0)
        sp = np.round(lp * (0.2 + 0.8 * _uniform(S + 9, idx)), 2)
        cols["ws_quantity"] = Column(BIGINT, qty, None)
        cols["ws_list_price"] = Column(DOUBLE, lp, None)
        cols["ws_sales_price"] = Column(DOUBLE, sp, None)
        cols["ws_wholesale_cost"] = Column(DOUBLE, whole, None)
        cols["ws_ext_list_price"] = Column(
            DOUBLE, np.round(lp * qty, 2), None)
        cols["ws_ext_sales_price"] = Column(
            DOUBLE, np.round(sp * qty, 2), None)
        if "ws_ext_wholesale_cost" in need:
            cols["ws_ext_wholesale_cost"] = Column(
                DOUBLE, np.round(whole * qty, 2), None)
        if "ws_ext_discount_amt" in need:
            cols["ws_ext_discount_amt"] = Column(
                DOUBLE, np.round((lp - sp) * qty, 2), None)
        if "ws_ext_ship_cost" in need:
            cols["ws_ext_ship_cost"] = Column(
                DOUBLE, _price(S + 20, idx, 0.0, 50.0), None)
        if "ws_net_paid" in need:
            cols["ws_net_paid"] = Column(
                DOUBLE, np.round(sp * qty, 2), None)
        if "ws_net_profit" in need:
            cols["ws_net_profit"] = Column(
                DOUBLE, np.round((sp - whole) * qty, 2), None)
        return self._finish(cols, n, columns)

    def _web_returns(self, idx, sf, columns) -> Batch:
        """Each return references a real web_sales row (strided)."""
        S = _SEED["web_returns"]
        need = set(columns)
        n = len(idx)
        ws_rows = table_rows("web_sales", sf)
        wr_rows = table_rows("web_returns", sf)
        ws_idx = 1 + (idx - 1) * ws_rows // wr_rows
        Sws = _SEED["web_sales"]
        n_item = table_rows("item", sf)
        cols: Dict[str, Column] = {}
        cols["wr_item_sk"] = Column(
            BIGINT, 1 + (_u64(Sws + 2, ws_idx)
                         % np.uint64(n_item)).astype(np.int64), None)
        cols["wr_order_number"] = Column(BIGINT, ws_idx, None)
        cols["wr_returned_date_sk"] = Column(
            BIGINT, _randint(S + 2, idx, _SALES_SK_LO, _SALES_SK_HI),
            None)
        for cname, sref in (("wr_refunded_customer_sk", 4),
                            ("wr_returning_customer_sk", 4)):
            k, v = _fk(Sws + sref, ws_idx, table_rows("customer", sf),
                       0.02)
            cols[cname] = Column(BIGINT, k, v)
        if "wr_web_page_sk" in need:
            k, v = _fk(Sws + 16, ws_idx, table_rows("web_page", sf),
                       0.02)
            cols["wr_web_page_sk"] = Column(BIGINT, k, v)
        if "wr_reason_sk" in need:
            k, v = _fk(S + 5, idx, table_rows("reason", sf), 0.02)
            cols["wr_reason_sk"] = Column(BIGINT, k, v)
        qty = _randint(S + 6, idx, 1, 20)
        cols["wr_return_quantity"] = Column(BIGINT, qty, None)
        cols["wr_return_amt"] = Column(
            DOUBLE, _price(S + 7, idx, 1.0, 300.0), None)
        if "wr_net_loss" in need:
            cols["wr_net_loss"] = Column(
                DOUBLE, _price(S + 8, idx, 0.5, 150.0), None)
        if "wr_refunded_cash" in need:
            cols["wr_refunded_cash"] = Column(
                DOUBLE, _price(S + 9, idx, 0.0, 200.0), None)
        if "wr_fee" in need:
            cols["wr_fee"] = Column(
                DOUBLE, _price(S + 10, idx, 0.5, 100.0), None)
        for cname, sref, tbl in (
                ("wr_returning_addr_sk", 11, "customer_address"),
                ("wr_refunded_addr_sk", 12, "customer_address"),
                ("wr_refunded_cdemo_sk", 13, "customer_demographics"),
                ("wr_returning_cdemo_sk", 14,
                 "customer_demographics")):
            if cname in need:
                k, v = _fk(S + sref, idx, table_rows(tbl, sf), 0.02)
                cols[cname] = Column(BIGINT, k, v)
        return self._finish(cols, n, columns)

    def _catalog_returns(self, idx, sf, columns) -> Batch:
        S = _SEED["catalog_returns"]
        n = len(idx)
        cs_rows = table_rows("catalog_sales", sf)
        cr_rows = table_rows("catalog_returns", sf)
        cs_idx = 1 + (idx - 1) * cs_rows // cr_rows
        Scs = _SEED["catalog_sales"]
        n_item = table_rows("item", sf)
        cols: Dict[str, Column] = {}
        cols["cr_item_sk"] = Column(
            BIGINT, 1 + (_u64(Scs + 2, cs_idx)
                         % np.uint64(n_item)).astype(np.int64), None)
        cols["cr_order_number"] = Column(BIGINT, cs_idx, None)
        cols["cr_returned_date_sk"] = Column(
            BIGINT, _randint(S + 2, idx, _SALES_SK_LO, _SALES_SK_HI),
            None)
        cols["cr_refunded_cash"] = Column(
            DOUBLE, _price(S + 3, idx, 0.0, 200.0), None)
        cols["cr_reversed_charge"] = Column(
            DOUBLE, _price(S + 4, idx, 0.0, 100.0), None)
        cols["cr_store_credit"] = Column(
            DOUBLE, _price(S + 5, idx, 0.0, 100.0), None)
        cols["cr_return_quantity"] = Column(
            BIGINT, _randint(S + 6, idx, 1, 20), None)
        need = set(columns)
        if "cr_return_amount" in need:
            cols["cr_return_amount"] = Column(
                DOUBLE, _price(S + 7, idx, 1.0, 300.0), None)
        if "cr_net_loss" in need:
            cols["cr_net_loss"] = Column(
                DOUBLE, _price(S + 8, idx, 0.5, 150.0), None)
        if "cr_returning_customer_sk" in need:
            k, v = _fk(S + 9, idx, table_rows("customer", sf), 0.02)
            cols["cr_returning_customer_sk"] = Column(BIGINT, k, v)
        if "cr_call_center_sk" in need:
            k, v = _fk(S + 10, idx, table_rows("call_center", sf), 0.02)
            cols["cr_call_center_sk"] = Column(BIGINT, k, v)
        if "cr_catalog_page_sk" in need:
            k, v = _fk(S + 11, idx, table_rows("catalog_page", sf),
                       0.02)
            cols["cr_catalog_page_sk"] = Column(BIGINT, k, v)
        if "cr_reason_sk" in need:
            k, v = _fk(S + 12, idx, table_rows("reason", sf), 0.02)
            cols["cr_reason_sk"] = Column(BIGINT, k, v)
        if "cr_return_amt_inc_tax" in need:
            cols["cr_return_amt_inc_tax"] = Column(
                DOUBLE, _price(S + 13, idx, 1.0, 320.0), None)
        if "cr_returning_addr_sk" in need:
            k, v = _fk(S + 14, idx,
                       table_rows("customer_address", sf), 0.02)
            cols["cr_returning_addr_sk"] = Column(BIGINT, k, v)
        return self._finish(cols, n, columns)


# column catalogs (metadata surface; generation is lazy per need)
def _cm(name: str, typ: Type) -> CM:
    return CM(name, typ)


_V = VarcharType
_TABLES: Dict[str, List[CM]] = {
    "date_dim": [
        _cm("d_date_sk", BIGINT), _cm("d_date", DATE),
        _cm("d_year", INTEGER), _cm("d_moy", INTEGER),
        _cm("d_dom", INTEGER), _cm("d_qoy", INTEGER),
        _cm("d_dow", INTEGER), _cm("d_month_seq", BIGINT),
        _cm("d_week_seq", BIGINT), _cm("d_day_name", _V(9)),
        _cm("d_quarter_name", _V(6))],
    "item": [
        _cm("i_item_sk", BIGINT), _cm("i_item_id", _V(16)),
        _cm("i_product_name", _V(50)), _cm("i_item_desc", _V(200)),
        _cm("i_color", _V(20)),
        _cm("i_current_price", DOUBLE), _cm("i_wholesale_cost", DOUBLE),
        _cm("i_brand_id", BIGINT), _cm("i_brand", _V(50)),
        _cm("i_manufact_id", BIGINT), _cm("i_manufact", _V(50)),
        _cm("i_category_id", BIGINT),
        _cm("i_category", _V(50)), _cm("i_class_id", BIGINT),
        _cm("i_class", _V(50)), _cm("i_manager_id", BIGINT),
        _cm("i_size", _V(20)), _cm("i_units", _V(10))],
    "customer": [
        _cm("c_customer_sk", BIGINT), _cm("c_customer_id", _V(16)),
        _cm("c_current_cdemo_sk", BIGINT),
        _cm("c_current_hdemo_sk", BIGINT),
        _cm("c_current_addr_sk", BIGINT),
        _cm("c_first_sales_date_sk", BIGINT),
        _cm("c_first_shipto_date_sk", BIGINT),
        _cm("c_first_name", _V(20)), _cm("c_last_name", _V(30)),
        _cm("c_birth_year", INTEGER), _cm("c_birth_month", INTEGER),
        _cm("c_birth_day", INTEGER), _cm("c_birth_country", _V(20)),
        _cm("c_preferred_cust_flag", _V(1)), _cm("c_salutation", _V(10)),
        _cm("c_email_address", _V(50)),
        _cm("c_last_review_date_sk", BIGINT)],
    "customer_address": [
        _cm("ca_address_sk", BIGINT), _cm("ca_street_number", _V(10)),
        _cm("ca_street_name", _V(60)), _cm("ca_city", _V(60)),
        _cm("ca_zip", _V(10)), _cm("ca_state", _V(2)),
        _cm("ca_country", _V(20)), _cm("ca_county", _V(30)),
        _cm("ca_gmt_offset", DOUBLE), _cm("ca_street_type", _V(15)),
        _cm("ca_suite_number", _V(10)),
        _cm("ca_location_type", _V(20))],
    "customer_demographics": [
        _cm("cd_demo_sk", BIGINT), _cm("cd_gender", _V(1)),
        _cm("cd_marital_status", _V(1)),
        _cm("cd_education_status", _V(20)),
        _cm("cd_purchase_estimate", BIGINT),
        _cm("cd_credit_rating", _V(10)), _cm("cd_dep_count", BIGINT),
        _cm("cd_dep_employed_count", BIGINT),
        _cm("cd_dep_college_count", BIGINT)],
    "household_demographics": [
        _cm("hd_demo_sk", BIGINT), _cm("hd_income_band_sk", BIGINT),
        _cm("hd_buy_potential", _V(15)), _cm("hd_dep_count", BIGINT),
        _cm("hd_vehicle_count", BIGINT)],
    "income_band": [
        _cm("ib_income_band_sk", BIGINT), _cm("ib_lower_bound", BIGINT),
        _cm("ib_upper_bound", BIGINT)],
    "store": [
        _cm("s_store_sk", BIGINT), _cm("s_store_id", _V(16)),
        _cm("s_store_name", _V(50)), _cm("s_zip", _V(10)),
        _cm("s_state", _V(2)), _cm("s_city", _V(60)),
        _cm("s_number_employees", BIGINT),
        _cm("s_county", _V(30)), _cm("s_company_name", _V(50)),
        _cm("s_company_id", BIGINT), _cm("s_market_id", BIGINT),
        _cm("s_street_number", _V(10)),
        _cm("s_street_name", _V(60)), _cm("s_street_type", _V(15)),
        _cm("s_suite_number", _V(10))],
    "promotion": [
        _cm("p_promo_sk", BIGINT), _cm("p_promo_id", _V(16)),
        _cm("p_channel_dmail", _V(1)), _cm("p_channel_email", _V(1)),
        _cm("p_channel_tv", _V(1)), _cm("p_channel_event", _V(1)),
        _cm("p_channel_catalog", _V(1)), _cm("p_cost", DOUBLE)],
    "warehouse": [
        _cm("w_warehouse_sk", BIGINT), _cm("w_warehouse_name", _V(20)),
        _cm("w_warehouse_sq_ft", BIGINT), _cm("w_city", _V(60)),
        _cm("w_county", _V(30)), _cm("w_state", _V(2)),
        _cm("w_country", _V(20))],
    "store_sales": [
        _cm("ss_sold_date_sk", BIGINT), _cm("ss_sold_time_sk", BIGINT),
        _cm("ss_item_sk", BIGINT),
        _cm("ss_customer_sk", BIGINT), _cm("ss_cdemo_sk", BIGINT),
        _cm("ss_hdemo_sk", BIGINT), _cm("ss_addr_sk", BIGINT),
        _cm("ss_store_sk", BIGINT), _cm("ss_promo_sk", BIGINT),
        _cm("ss_ticket_number", BIGINT), _cm("ss_quantity", BIGINT),
        _cm("ss_wholesale_cost", DOUBLE), _cm("ss_list_price", DOUBLE),
        _cm("ss_sales_price", DOUBLE),
        _cm("ss_ext_sales_price", DOUBLE),
        _cm("ss_ext_list_price", DOUBLE),
        _cm("ss_ext_discount_amt", DOUBLE),
        _cm("ss_ext_wholesale_cost", DOUBLE),
        _cm("ss_ext_tax", DOUBLE),
        _cm("ss_coupon_amt", DOUBLE), _cm("ss_net_paid", DOUBLE),
        _cm("ss_net_profit", DOUBLE)],
    "store_returns": [
        _cm("sr_item_sk", BIGINT), _cm("sr_ticket_number", BIGINT),
        _cm("sr_returned_date_sk", BIGINT),
        _cm("sr_return_time_sk", BIGINT),
        _cm("sr_customer_sk", BIGINT), _cm("sr_cdemo_sk", BIGINT),
        _cm("sr_store_sk", BIGINT), _cm("sr_reason_sk", BIGINT),
        _cm("sr_return_quantity", BIGINT),
        _cm("sr_return_amt", DOUBLE), _cm("sr_net_loss", DOUBLE),
        _cm("sr_fee", DOUBLE), _cm("sr_refunded_cash", DOUBLE),
        _cm("sr_reversed_charge", DOUBLE),
        _cm("sr_store_credit", DOUBLE)],
    "catalog_sales": [
        _cm("cs_sold_date_sk", BIGINT), _cm("cs_sold_time_sk", BIGINT),
        _cm("cs_ship_date_sk", BIGINT), _cm("cs_item_sk", BIGINT),
        _cm("cs_order_number", BIGINT),
        _cm("cs_bill_customer_sk", BIGINT),
        _cm("cs_ship_customer_sk", BIGINT),
        _cm("cs_bill_cdemo_sk", BIGINT),
        _cm("cs_bill_hdemo_sk", BIGINT),
        _cm("cs_bill_addr_sk", BIGINT), _cm("cs_ship_addr_sk", BIGINT),
        _cm("cs_call_center_sk", BIGINT),
        _cm("cs_catalog_page_sk", BIGINT),
        _cm("cs_ship_mode_sk", BIGINT), _cm("cs_promo_sk", BIGINT),
        _cm("cs_warehouse_sk", BIGINT), _cm("cs_quantity", BIGINT),
        _cm("cs_list_price", DOUBLE), _cm("cs_ext_list_price", DOUBLE),
        _cm("cs_sales_price", DOUBLE),
        _cm("cs_ext_sales_price", DOUBLE),
        _cm("cs_ext_discount_amt", DOUBLE),
        _cm("cs_wholesale_cost", DOUBLE),
        _cm("cs_ext_wholesale_cost", DOUBLE),
        _cm("cs_ext_ship_cost", DOUBLE), _cm("cs_coupon_amt", DOUBLE),
        _cm("cs_net_paid", DOUBLE), _cm("cs_net_profit", DOUBLE)],
    "catalog_returns": [
        _cm("cr_item_sk", BIGINT), _cm("cr_order_number", BIGINT),
        _cm("cr_returned_date_sk", BIGINT),
        _cm("cr_refunded_cash", DOUBLE),
        _cm("cr_reversed_charge", DOUBLE),
        _cm("cr_store_credit", DOUBLE),
        _cm("cr_return_quantity", BIGINT),
        _cm("cr_return_amount", DOUBLE), _cm("cr_net_loss", DOUBLE),
        _cm("cr_returning_customer_sk", BIGINT),
        _cm("cr_call_center_sk", BIGINT),
        _cm("cr_catalog_page_sk", BIGINT),
        _cm("cr_reason_sk", BIGINT),
        _cm("cr_return_amt_inc_tax", DOUBLE),
        _cm("cr_returning_addr_sk", BIGINT)],
    "web_sales": [
        _cm("ws_sold_date_sk", BIGINT), _cm("ws_sold_time_sk", BIGINT),
        _cm("ws_ship_date_sk", BIGINT), _cm("ws_item_sk", BIGINT),
        _cm("ws_order_number", BIGINT),
        _cm("ws_bill_customer_sk", BIGINT),
        _cm("ws_ship_customer_sk", BIGINT),
        _cm("ws_bill_cdemo_sk", BIGINT),
        _cm("ws_bill_hdemo_sk", BIGINT),
        _cm("ws_ship_hdemo_sk", BIGINT),
        _cm("ws_bill_addr_sk", BIGINT), _cm("ws_ship_addr_sk", BIGINT),
        _cm("ws_web_page_sk", BIGINT), _cm("ws_web_site_sk", BIGINT),
        _cm("ws_ship_mode_sk", BIGINT), _cm("ws_warehouse_sk", BIGINT),
        _cm("ws_promo_sk", BIGINT), _cm("ws_quantity", BIGINT),
        _cm("ws_wholesale_cost", DOUBLE), _cm("ws_list_price", DOUBLE),
        _cm("ws_sales_price", DOUBLE),
        _cm("ws_ext_discount_amt", DOUBLE),
        _cm("ws_ext_sales_price", DOUBLE),
        _cm("ws_ext_wholesale_cost", DOUBLE),
        _cm("ws_ext_list_price", DOUBLE),
        _cm("ws_ext_ship_cost", DOUBLE), _cm("ws_net_paid", DOUBLE),
        _cm("ws_net_profit", DOUBLE)],
    "web_returns": [
        _cm("wr_returned_date_sk", BIGINT), _cm("wr_item_sk", BIGINT),
        _cm("wr_order_number", BIGINT),
        _cm("wr_refunded_customer_sk", BIGINT),
        _cm("wr_returning_customer_sk", BIGINT),
        _cm("wr_web_page_sk", BIGINT), _cm("wr_reason_sk", BIGINT),
        _cm("wr_return_quantity", BIGINT),
        _cm("wr_return_amt", DOUBLE), _cm("wr_net_loss", DOUBLE),
        _cm("wr_refunded_cash", DOUBLE), _cm("wr_fee", DOUBLE),
        _cm("wr_returning_addr_sk", BIGINT),
        _cm("wr_refunded_addr_sk", BIGINT),
        _cm("wr_refunded_cdemo_sk", BIGINT),
        _cm("wr_returning_cdemo_sk", BIGINT)],
    "web_site": [
        _cm("web_site_sk", BIGINT), _cm("web_site_id", _V(16)),
        _cm("web_name", _V(50)), _cm("web_company_name", _V(50))],
    "web_page": [
        _cm("wp_web_page_sk", BIGINT), _cm("wp_web_page_id", _V(16)),
        _cm("wp_char_count", BIGINT)],
    "inventory": [
        _cm("inv_date_sk", BIGINT), _cm("inv_item_sk", BIGINT),
        _cm("inv_warehouse_sk", BIGINT),
        _cm("inv_quantity_on_hand", BIGINT)],
    "time_dim": [
        _cm("t_time_sk", BIGINT), _cm("t_time", BIGINT),
        _cm("t_hour", BIGINT), _cm("t_minute", BIGINT),
        _cm("t_second", BIGINT), _cm("t_am_pm", _V(2)),
        _cm("t_meal_time", _V(20))],
    "reason": [
        _cm("r_reason_sk", BIGINT), _cm("r_reason_id", _V(16)),
        _cm("r_reason_desc", _V(100))],
    "ship_mode": [
        _cm("sm_ship_mode_sk", BIGINT), _cm("sm_ship_mode_id", _V(16)),
        _cm("sm_type", _V(30)), _cm("sm_carrier", _V(20)),
        _cm("sm_code", _V(10))],
    "call_center": [
        _cm("cc_call_center_sk", BIGINT),
        _cm("cc_call_center_id", _V(16)), _cm("cc_name", _V(50)),
        _cm("cc_manager", _V(40)), _cm("cc_county", _V(30))],
    "catalog_page": [
        _cm("cp_catalog_page_sk", BIGINT),
        _cm("cp_catalog_page_id", _V(16)),
        _cm("cp_catalog_number", BIGINT),
        _cm("cp_catalog_page_number", BIGINT)],
}
