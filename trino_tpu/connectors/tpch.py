"""TPC-H data-generator connector.

Reference parity: plugin/trino-tpch (TpchConnectorFactory.java,
TpchMetadata.java, TpchRecordSetProvider.java, TpchSplitManager.java:32-46)
— generates TPC-H data on the fly, deterministically, per split.

TPU-first redesign (SURVEY.md Appendix B.6): instead of a stateful
row-cursor (airlift dbgen port), every value is a pure function of
``(column_seed, absolute_row_index)`` through a splitmix64 counter hash.
Any split can therefore generate its exact row range independently, fully
vectorized in numpy, with no sequential RNG state — the generator itself is
data-parallel. Distributions follow the TPC-H specification rev 2.18
(value ranges, key sparsity, date windows, comment token injection);
the bit-exact dbgen text grammar is intentionally not reproduced.

Schemas: tiny (SF 0.01), sf1, sf10, sf100, sf1000 — matching the
reference connector's schema set (TpchMetadata.java SCHEMA_NAMES).
"""

from __future__ import annotations

import datetime
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..catalog import (ColumnMetadata as CM, Connector, Split, TableHandle,
                       TableMetadata)
from ..columnar import Batch, Column, StringDictionary, pad_batch
from ..config import capacity_for
from ..types import (BIGINT, DATE, DOUBLE, INTEGER, Type, VarcharType)

_EPOCH = datetime.date(1970, 1, 1).toordinal()


def _days(y: int, m: int, d: int) -> int:
    return datetime.date(y, m, d).toordinal() - _EPOCH


STARTDATE = _days(1992, 1, 1)
CURRENTDATE = _days(1995, 6, 17)
ENDDATE = _days(1998, 12, 31)
ORDER_DATE_SPAN = (ENDDATE - 151) - STARTDATE  # o_orderdate upper bound

SCHEMAS: Dict[str, float] = {
    "tiny": 0.01, "sf1": 1.0, "sf10": 10.0, "sf100": 100.0, "sf1000": 1000.0,
}

# --------------------------------------------------------------------------
# counter-based RNG: value = f(seed, row_index), vectorized
# --------------------------------------------------------------------------

_C1 = np.uint64(0xBF58476D1CE4E5B9)
_C2 = np.uint64(0x94D049BB133111EB)


def _mix(x: np.ndarray) -> np.ndarray:
    with np.errstate(over="ignore"):
        x = x ^ (x >> np.uint64(30))
        x = x * _C1
        x = x ^ (x >> np.uint64(27))
        x = x * _C2
        x = x ^ (x >> np.uint64(31))
    return x


def _u64(seed: int, idx: np.ndarray) -> np.ndarray:
    with np.errstate(over="ignore"):
        return _mix(np.uint64(seed) * np.uint64(0x9E3779B97F4A7C15)
                    + idx.astype(np.uint64))


def _randint(seed: int, idx: np.ndarray, lo: int, hi: int) -> np.ndarray:
    """Uniform integer in [lo, hi], inclusive, per row."""
    span = np.uint64(hi - lo + 1)
    return (lo + (_u64(seed, idx) % span).astype(np.int64)).astype(np.int64)


def _uniform(seed: int, idx: np.ndarray) -> np.ndarray:
    return (_u64(seed, idx) >> np.uint64(11)).astype(np.float64) / float(1 << 53)


# --------------------------------------------------------------------------
# fixed vocabularies (TPC-H spec 4.2.2.13)
# --------------------------------------------------------------------------

NATIONS = [  # (name, regionkey)
    ("ALGERIA", 0), ("ARGENTINA", 1), ("BRAZIL", 1), ("CANADA", 1),
    ("EGYPT", 4), ("ETHIOPIA", 0), ("FRANCE", 3), ("GERMANY", 3),
    ("INDIA", 2), ("INDONESIA", 2), ("IRAN", 4), ("IRAQ", 4),
    ("JAPAN", 2), ("JORDAN", 4), ("KENYA", 0), ("MOROCCO", 0),
    ("MOZAMBIQUE", 0), ("PERU", 1), ("CHINA", 2), ("ROMANIA", 3),
    ("SAUDI ARABIA", 4), ("VIETNAM", 2), ("RUSSIA", 3),
    ("UNITED KINGDOM", 3), ("UNITED STATES", 1),
]
REGIONS = ["AFRICA", "AMERICA", "ASIA", "EUROPE", "MIDDLE EAST"]

P_NAME_WORDS = (
    "almond antique aquamarine azure beige bisque black blanched blue "
    "blush brown burlywood burnished chartreuse chiffon chocolate coral "
    "cornflower cornsilk cream cyan dark deep dim dodger drab firebrick "
    "floral forest frosted gainsboro ghost goldenrod green grey honeydew "
    "hot indian ivory khaki lace lavender lawn lemon light lime linen "
    "magenta maroon medium metallic midnight mint misty moccasin navajo "
    "navy olive orange orchid pale papaya peach peru pink plum powder "
    "puff purple red rose rosy royal saddle salmon sandy seashell sienna "
    "sky slate smoke snow spring steel tan thistle tomato turquoise "
    "violet wheat white yellow").split()

TYPE_S1 = ["STANDARD", "SMALL", "MEDIUM", "LARGE", "ECONOMY", "PROMO"]
TYPE_S2 = ["ANODIZED", "BURNISHED", "PLATED", "POLISHED", "BRUSHED"]
TYPE_S3 = ["TIN", "NICKEL", "BRASS", "STEEL", "COPPER"]
CONTAINER_S1 = ["SM", "LG", "MED", "JUMBO", "WRAP"]
CONTAINER_S2 = ["CASE", "BOX", "BAG", "JAR", "PKG", "PACK", "CAN", "DRUM"]
SEGMENTS = ["AUTOMOBILE", "BUILDING", "FURNITURE", "MACHINERY", "HOUSEHOLD"]
PRIORITIES = ["1-URGENT", "2-HIGH", "3-MEDIUM", "4-NOT SPECIFIED", "5-LOW"]
INSTRUCTIONS = ["DELIVER IN PERSON", "COLLECT COD", "NONE",
                "TAKE BACK RETURN"]
MODES = ["REG AIR", "AIR", "RAIL", "SHIP", "TRUCK", "MAIL", "FOB"]

_COMMENT_WORDS = (
    "carefully quickly blithely furiously slyly fluffily final express "
    "regular special bold pending ironic even silent unusual daring "
    "deposits requests accounts packages instructions theodolites "
    "platelets foxes ideas dependencies excuses pinto beans asymptotes "
    "courts dolphins multipliers sauternes warhorses sheaves dugouts "
    "sleep wake cajole nag haggle detect integrate boost engage breach "
    "among across above against along until again after about the")


_DICT_CACHE: Dict[tuple, StringDictionary] = {}


def _strings(values: Sequence[str], codes: np.ndarray, typ: Type) -> Column:
    # fixed-vocabulary dictionaries are shared by identity across splits
    # so jitted pipelines (dictionary is static trace metadata) compile
    # once per query, not once per split
    key = tuple(values)
    d = _DICT_CACHE.get(key)
    if d is None:
        d = StringDictionary(np.asarray(list(values), dtype=object))
        _DICT_CACHE[key] = d
    return Column(typ, codes.astype(np.int32), None, d)


def _text_column(seed: int, idx: np.ndarray, typ: Type,
                 inject: Optional[Dict[str, np.ndarray]] = None) -> Column:
    """Pseudo-text comments: 5-8 pool words per row. ``inject`` maps a
    phrase to a boolean row mask that must contain it (spec 4.2.2.10's
    'special requests' / 'Customer Complaints' text injections)."""
    words = _COMMENT_WORDS.split()
    nw = len(words)
    n = len(idx)
    lens = _randint(seed + 11, idx, 5, 8)
    picks = [_randint(seed + 13 + k, idx, 0, nw - 1) for k in range(8)]
    out = np.empty(n, dtype=object)
    for i in range(n):
        out[i] = " ".join(words[int(picks[k][i])]
                          for k in range(int(lens[i])))
    if inject:
        for phrase, mask in inject.items():
            rows = np.nonzero(mask)[0]
            for i in rows:
                out[i] = f"{out[i].split(' ', 1)[0]} {phrase}"
    dic, codes = StringDictionary.from_strings(list(out))
    return Column(typ, codes, None, dic)


def _alnum_column(seed: int, idx: np.ndarray, typ: Type) -> Column:
    """Random address-like strings (v-strings, spec 4.2.2.7)."""
    h1 = _u64(seed, idx)
    h2 = _u64(seed + 1, idx)
    out = np.empty(len(idx), dtype=object)
    for i in range(len(idx)):
        s = f"{int(h1[i]):016x}{int(h2[i]):08x}"
        out[i] = s[: 10 + int(h2[i]) % 15]
    dic, codes = StringDictionary.from_strings(list(out))
    return Column(typ, codes, None, dic)


def _phone_column(seed: int, idx: np.ndarray,
                  nationkey: np.ndarray) -> Column:
    a = _randint(seed + 1, idx, 100, 999)
    b = _randint(seed + 2, idx, 100, 999)
    c = _randint(seed + 3, idx, 1000, 9999)
    out = np.empty(len(idx), dtype=object)
    for i in range(len(idx)):
        out[i] = (f"{int(nationkey[i]) + 10:02d}-{int(a[i])}-"
                  f"{int(b[i])}-{int(c[i])}")
    dic, codes = StringDictionary.from_strings(list(out))
    return Column(VarcharType(15), codes, None, dic)


def _fmt_key_column(prefix: str, keys: np.ndarray, typ: Type) -> Column:
    out = np.empty(len(keys), dtype=object)
    for i in range(len(keys)):
        out[i] = f"{prefix}{int(keys[i]):09d}"
    dic, codes = StringDictionary.from_strings(list(out))
    return Column(typ, codes, None, dic)


# --------------------------------------------------------------------------
# table schemas (column order and types mirror plugin/trino-tpch's
# TpchTable column lists; prices are DOUBLE as in the reference connector)
# --------------------------------------------------------------------------

TABLES: Dict[str, List[CM]] = {
    "region": [CM("r_regionkey", BIGINT), CM("r_name", VarcharType(25)),
               CM("r_comment", VarcharType(152))],
    "nation": [CM("n_nationkey", BIGINT), CM("n_name", VarcharType(25)),
               CM("n_regionkey", BIGINT), CM("n_comment", VarcharType(152))],
    "supplier": [CM("s_suppkey", BIGINT), CM("s_name", VarcharType(25)),
                 CM("s_address", VarcharType(40)),
                 CM("s_nationkey", BIGINT), CM("s_phone", VarcharType(15)),
                 CM("s_acctbal", DOUBLE), CM("s_comment", VarcharType(101))],
    "part": [CM("p_partkey", BIGINT), CM("p_name", VarcharType(55)),
             CM("p_mfgr", VarcharType(25)), CM("p_brand", VarcharType(10)),
             CM("p_type", VarcharType(25)), CM("p_size", INTEGER),
             CM("p_container", VarcharType(10)),
             CM("p_retailprice", DOUBLE), CM("p_comment", VarcharType(23))],
    "partsupp": [CM("ps_partkey", BIGINT), CM("ps_suppkey", BIGINT),
                 CM("ps_availqty", INTEGER), CM("ps_supplycost", DOUBLE),
                 CM("ps_comment", VarcharType(199))],
    "customer": [CM("c_custkey", BIGINT), CM("c_name", VarcharType(25)),
                 CM("c_address", VarcharType(40)),
                 CM("c_nationkey", BIGINT), CM("c_phone", VarcharType(15)),
                 CM("c_acctbal", DOUBLE),
                 CM("c_mktsegment", VarcharType(10)),
                 CM("c_comment", VarcharType(117))],
    "orders": [CM("o_orderkey", BIGINT), CM("o_custkey", BIGINT),
               CM("o_orderstatus", VarcharType(1)),
               CM("o_totalprice", DOUBLE), CM("o_orderdate", DATE),
               CM("o_orderpriority", VarcharType(15)),
               CM("o_clerk", VarcharType(15)),
               CM("o_shippriority", INTEGER),
               CM("o_comment", VarcharType(79))],
    "lineitem": [CM("l_orderkey", BIGINT), CM("l_partkey", BIGINT),
                 CM("l_suppkey", BIGINT), CM("l_linenumber", INTEGER),
                 CM("l_quantity", DOUBLE), CM("l_extendedprice", DOUBLE),
                 CM("l_discount", DOUBLE), CM("l_tax", DOUBLE),
                 CM("l_returnflag", VarcharType(1)),
                 CM("l_linestatus", VarcharType(1)),
                 CM("l_shipdate", DATE), CM("l_commitdate", DATE),
                 CM("l_receiptdate", DATE),
                 CM("l_shipinstruct", VarcharType(25)),
                 CM("l_shipmode", VarcharType(10)),
                 CM("l_comment", VarcharType(44))],
}

_BASE_ROWS = {"supplier": 10_000, "part": 200_000, "partsupp": 800_000,
              "customer": 150_000, "orders": 1_500_000}


def table_rows(table: str, sf: float) -> int:
    if table == "region":
        return 5
    if table == "nation":
        return 25
    if table == "lineitem":
        # addressed by order index; row count is derived (avg 4/order)
        raise ValueError("lineitem row count is data-dependent")
    return int(round(_BASE_ROWS[table] * sf))


# per-(table,column-group) seeds, disjoint
_SEED = {name: i * 1000 for i, name in enumerate(
    ["supplier", "part", "partsupp", "customer", "orders", "lineitem"])}


def _retailprice(partkey: np.ndarray) -> np.ndarray:
    pk = partkey.astype(np.int64)
    return (90000 + (pk // 10) % 20001 + 100 * (pk % 1000)) / 100.0


def _ps_suppkey(partkey: np.ndarray, i: np.ndarray,
                s_count: int) -> np.ndarray:
    """spec 4.2.3: ps_suppkey = (ps_partkey + (i * (S/4 +
    (ps_partkey-1)/S))) % S + 1"""
    pk = partkey.astype(np.int64)
    s = np.int64(s_count)
    return (pk + i * (s // 4 + (pk - 1) // s)) % s + 1


def _line_counts(order_idx: np.ndarray) -> np.ndarray:
    """lineitems per order, 1..7, pure function of order index."""
    return _randint(_SEED["lineitem"] + 1, order_idx, 1, 7)


def _order_key(order_idx: np.ndarray) -> np.ndarray:
    """Sparse order keys: 8 used out of every 32 (spec 4.2.3 O_ORDERKEY)."""
    i = order_idx.astype(np.int64)
    return ((i >> 3) << 5) | (i & 7)


def _order_date(order_idx: np.ndarray) -> np.ndarray:
    return STARTDATE + _randint(_SEED["orders"] + 4, order_idx, 0,
                                ORDER_DATE_SPAN)


def _cust_key(order_idx: np.ndarray, c_count: int) -> np.ndarray:
    """Random custkey never divisible by 3 (only 2/3 of customers have
    orders, spec 4.2.3)."""
    j = _randint(_SEED["orders"] + 3, order_idx, 1, max(2 * c_count // 3, 1))
    return 3 * ((j - 1) // 2) + 1 + ((j - 1) % 2)


class _LineFields:
    """All lineitem lanes for a range of global lineitem row indices,
    each a pure function of (order_idx, line_number)."""

    def __init__(self, order_idx: np.ndarray, linenumber: np.ndarray,
                 sf: float):
        S = _SEED["lineitem"]
        # unique per-row counter: order_idx * 8 + linenumber
        rid = order_idx.astype(np.int64) * 8 + linenumber
        self.orderkey = _order_key(order_idx)
        self.linenumber = linenumber
        p_count = table_rows("part", sf)
        s_count = table_rows("supplier", sf)
        self.partkey = _randint(S + 2, rid, 1, p_count)
        self.suppkey = _ps_suppkey(self.partkey,
                                   _randint(S + 3, rid, 0, 3), s_count)
        self.quantity = _randint(S + 4, rid, 1, 50).astype(np.float64)
        self.discount = _randint(S + 5, rid, 0, 10) / 100.0
        self.tax = _randint(S + 6, rid, 0, 8) / 100.0
        self.extendedprice = self.quantity * _retailprice(self.partkey)
        odate = _order_date(order_idx)
        self.shipdate = odate + _randint(S + 7, rid, 1, 121)
        self.commitdate = odate + _randint(S + 8, rid, 30, 90)
        self.receiptdate = self.shipdate + _randint(S + 9, rid, 1, 30)
        self.rid = rid


class TpchConnector(Connector):
    name = "tpch"
    scan_cache_ok = True      # pure generator: splits are immutable

    def __init__(self, rows_per_split: int = 1 << 17):
        self.rows_per_split = rows_per_split

    # --- metadata --------------------------------------------------------
    def list_schemas(self) -> List[str]:
        return list(SCHEMAS)

    def list_tables(self, schema: str) -> List[str]:
        return list(TABLES) if schema in SCHEMAS else []

    def get_table_metadata(self, schema, table) -> Optional[TableMetadata]:
        if schema in SCHEMAS and table in TABLES:
            return TableMetadata(schema, table, tuple(TABLES[table]))
        return None

    def table_row_count(self, handle: TableHandle) -> Optional[float]:
        sf = SCHEMAS[handle.schema]
        if handle.table == "lineitem":
            return table_rows("orders", sf) * 4.0
        return float(table_rows(handle.table, sf))

    def column_statistics(self, handle: TableHandle, column: str):
        """Analytic per-column stats from the TPC-H spec's value
        domains, scaled by SF (reference:
        plugin/trino-tpch/.../statistics/ ships precomputed stats
        files; ours derive from the same spec formulas)."""
        from ..catalog import ColumnStatistics as CS
        sf = SCHEMAS[handle.schema]

        def rows(t):
            return float(table_rows(t, sf))

        stats = {
            "r_regionkey": CS(5, 0, 4), "r_name": CS(5),
            "n_nationkey": CS(25, 0, 24), "n_name": CS(25),
            "n_regionkey": CS(5, 0, 4),
            "s_suppkey": CS(rows("supplier"), 1, rows("supplier")),
            "s_nationkey": CS(25, 0, 24),
            "s_acctbal": CS(rows("supplier") * 0.9, -999.99, 9999.99),
            "s_name": CS(rows("supplier")),
            "p_partkey": CS(rows("part"), 1, rows("part")),
            "p_brand": CS(25), "p_type": CS(150), "p_size": CS(50, 1,
                                                              50),
            "p_container": CS(40), "p_mfgr": CS(5),
            "p_retailprice": CS(rows("part") * 0.1, 900.0, 2099.0),
            "p_name": CS(rows("part")),
            "ps_partkey": CS(rows("part"), 1, rows("part")),
            "ps_suppkey": CS(rows("supplier"), 1, rows("supplier")),
            "ps_availqty": CS(9999, 1, 9999),
            "ps_supplycost": CS(100_000, 1.0, 1000.0),
            "c_custkey": CS(rows("customer"), 1, rows("customer")),
            "c_nationkey": CS(25, 0, 24), "c_mktsegment": CS(5),
            "c_acctbal": CS(rows("customer") * 0.9, -999.99, 9999.99),
            "c_name": CS(rows("customer")),
            "o_orderkey": CS(rows("orders"), 1, rows("orders") * 4),
            # 1/3 of customers have no orders (TPC-H 4.2.3)
            "o_custkey": CS(rows("customer") * 2 / 3, 1,
                            rows("customer")),
            "o_orderstatus": CS(3), "o_orderpriority": CS(5),
            "o_shippriority": CS(1, 0, 0), "o_clerk": CS(
                max(rows("orders") / 1500, 1)),
            "o_orderdate": CS(ORDER_DATE_SPAN, STARTDATE,
                              STARTDATE + ORDER_DATE_SPAN),
            "o_totalprice": CS(rows("orders") * 0.9, 857.71,
                               555285.16),
            "l_orderkey": CS(rows("orders"), 1, rows("orders") * 4),
            "l_partkey": CS(rows("part"), 1, rows("part")),
            "l_suppkey": CS(rows("supplier"), 1, rows("supplier")),
            "l_linenumber": CS(7, 1, 7),
            "l_quantity": CS(50, 1, 50),
            "l_extendedprice": CS(rows("part") * 0.5, 901.0,
                                  104949.5),
            "l_discount": CS(11, 0.0, 0.10),
            "l_tax": CS(9, 0.0, 0.08),
            "l_returnflag": CS(3), "l_linestatus": CS(2),
            "l_shipmode": CS(7), "l_shipinstruct": CS(4),
            "l_shipdate": CS(ENDDATE - 151 + 121 - STARTDATE - 1,
                             STARTDATE + 1, ENDDATE - 151 + 121),
            "l_commitdate": CS(ENDDATE - STARTDATE, STARTDATE + 30,
                               ENDDATE - 31),
            "l_receiptdate": CS(ENDDATE - STARTDATE, STARTDATE + 2,
                                ENDDATE),
        }
        return stats.get(column)

    # --- splits ----------------------------------------------------------
    def get_splits(self, handle: TableHandle,
                   desired_parallelism: int = 1) -> List[Split]:
        sf = SCHEMAS[handle.schema]
        if handle.table == "lineitem":
            # addressed by order index; ~4 lineitems per order
            units = table_rows("orders", sf)
            per = max(self.rows_per_split // 4, 1)
        else:
            units = table_rows(handle.table, sf)
            per = self.rows_per_split
        n_splits = max(1, min((units + per - 1) // per,
                              max(desired_parallelism * 4, 1)
                              if units > per else 1))
        n_splits = max(n_splits, min(desired_parallelism,
                                     (units + per - 1) // per) or 1)
        n_splits = (units + per - 1) // per
        return [Split(handle, p, n_splits) for p in range(max(n_splits, 1))]

    # --- data ------------------------------------------------------------
    def read_split(self, split: Split, columns: Sequence[str]) -> Batch:
        sf = SCHEMAS[split.handle.schema]
        table = split.handle.table
        handle = split.handle
        gen_cols = list(columns)
        if handle.constraint is not None:
            # generate constraint columns too, mask, then project
            for c, _ in handle.constraint.domains:
                if c not in gen_cols:
                    gen_cols.append(c)
        dev = self._read_split_device(split, sf, table, handle, gen_cols,
                                      columns)
        if dev is not None:
            return dev
        if table == "region":
            out = self._region(gen_cols)
        elif table == "nation":
            out = self._nation(gen_cols)
        else:
            if table == "lineitem":
                units = table_rows("orders", sf)
            else:
                units = table_rows(table, sf)
            lo = split.part * units // split.part_count
            hi = (split.part + 1) * units // split.part_count
            idx = np.arange(lo + 1, hi + 1, dtype=np.int64)  # 1-based
            gen = getattr(self, f"_{table}")
            out = gen(idx, sf, gen_cols)
        if handle.constraint is not None or handle.limit is not None:
            from ..predicate import filter_batch_host
            out = filter_batch_host(out, handle.constraint,
                                    handle.limit)
            out = out.select_columns(list(columns))
        return out

    def _read_split_device(self, split: Split, sf: float, table: str,
                           handle, gen_cols, columns) -> Optional[Batch]:
        """Generate the split's lanes ON DEVICE when the backend is an
        accelerator and every requested column is device-generatable
        (tpch_device.py): at sf>=10 host generation is the bottleneck —
        600M sf100 lineitem rows would take minutes on a 1-core host
        before the first byte reaches HBM. Opt out with
        TRINO_TPU_DEVICE_GEN=0 (or force on CPU with =1 for tests)."""
        import os
        mode = os.environ.get("TRINO_TPU_DEVICE_GEN", "auto")
        if mode == "0":
            return None
        if mode != "1":
            import jax
            if jax.default_backend() == "cpu":
                return None
        from .tpch_device import (device_columns, device_filter,
                                  lineitem_batch, orders_batch)
        allowed = device_columns(table)
        if allowed is None or not set(gen_cols) <= allowed:
            return None
        if table == "lineitem":
            units = table_rows("orders", sf)
        else:
            units = table_rows(table, sf)
        lo = split.part * units // split.part_count
        hi = (split.part + 1) * units // split.part_count
        gen = lineitem_batch if table == "lineitem" else orders_batch
        out = gen(lo, hi, sf, list(gen_cols))
        if handle.constraint is not None or handle.limit is not None:
            out = device_filter(out, handle.constraint, handle.limit)
            out = out.select_columns(list(columns))
        return out

    # --- pushdown (plugin/trino-tpch has no applyFilter in the
    # reference; ours accepts domains because masking at generation
    # time keeps host->HBM bytes down, the applyFilter contract) -------
    def apply_filter(self, handle: TableHandle, constraint):
        from ..catalog import accept_filter_pushdown
        return accept_filter_pushdown(handle, constraint)

    def apply_limit(self, handle: TableHandle, limit: int):
        from ..catalog import accept_limit_pushdown
        return accept_limit_pushdown(handle, limit)

    # --- per-table generators -------------------------------------------
    def _finish(self, cols: Dict[str, Column], n: int,
                columns: Sequence[str]) -> Batch:
        out = {name: cols[name] for name in columns}
        return pad_batch(Batch(out, n), capacity_for(n, minimum=8))

    def _region(self, columns) -> Batch:
        idx = np.arange(5, dtype=np.int64)
        cols = {
            "r_regionkey": Column(BIGINT, idx.copy(), None),
            "r_name": _strings(REGIONS, idx, VarcharType(25)),
            "r_comment": _text_column(901, idx, VarcharType(152)),
        }
        return self._finish(cols, 5, columns)

    def _nation(self, columns) -> Batch:
        idx = np.arange(25, dtype=np.int64)
        cols = {
            "n_nationkey": Column(BIGINT, idx.copy(), None),
            "n_name": _strings([n for n, _ in NATIONS], idx, VarcharType(25)),
            "n_regionkey": Column(
                BIGINT, np.asarray([r for _, r in NATIONS],
                                   dtype=np.int64), None),
            "n_comment": _text_column(902, idx, VarcharType(152)),
        }
        return self._finish(cols, 25, columns)

    def _supplier(self, idx, sf, columns) -> Batch:
        S = _SEED["supplier"]
        need = set(columns)
        n = len(idx)
        nationkey = _randint(S + 2, idx, 0, 24)
        cols: Dict[str, Column] = {}
        cols["s_suppkey"] = Column(BIGINT, idx.copy(), None)
        if "s_name" in need:
            cols["s_name"] = _fmt_key_column("Supplier#", idx,
                                             VarcharType(25))
        if "s_address" in need:
            cols["s_address"] = _alnum_column(S + 3, idx, VarcharType(40))
        cols["s_nationkey"] = Column(BIGINT, nationkey, None)
        if "s_phone" in need:
            cols["s_phone"] = _phone_column(S + 4, idx, nationkey)
        cols["s_acctbal"] = Column(
            DOUBLE, np.round(-999.99 + _uniform(S + 5, idx) * 10999.98, 2),
            None)
        if "s_comment" in need:
            # 5 per 10000 'Customer Complaints', 5 'Customer Recommends'
            # (spec 4.2.3; q16 keys off this)
            slot = _u64(S + 6, idx) % np.uint64(2000)
            cols["s_comment"] = _text_column(
                S + 7, idx, VarcharType(101),
                inject={"Customer Complaints": slot == 0,
                        "Customer Recommends": slot == 1})
        return self._finish(cols, n, columns)

    def _part(self, idx, sf, columns) -> Batch:
        S = _SEED["part"]
        need = set(columns)
        n = len(idx)
        mfgr = _randint(S + 2, idx, 1, 5)
        cols: Dict[str, Column] = {}
        cols["p_partkey"] = Column(BIGINT, idx.copy(), None)
        if "p_name" in need:
            w = [_randint(S + 10 + k, idx, 0, len(P_NAME_WORDS) - 1)
                 for k in range(5)]
            out = np.empty(n, dtype=object)
            for i in range(n):
                out[i] = " ".join(P_NAME_WORDS[int(w[k][i])]
                                  for k in range(5))
            dic, codes = StringDictionary.from_strings(list(out))
            cols["p_name"] = Column(VarcharType(55), codes, None, dic)
        if "p_mfgr" in need:
            vals = [f"Manufacturer#{m}" for m in range(1, 6)]
            cols["p_mfgr"] = _strings(vals, mfgr - 1, VarcharType(25))
        if "p_brand" in need:
            bn = _randint(S + 3, idx, 1, 5)
            vals = [f"Brand#{m}{b}" for m in range(1, 6)
                    for b in range(1, 6)]
            cols["p_brand"] = _strings(vals, (mfgr - 1) * 5 + bn - 1,
                                       VarcharType(10))
        if "p_type" in need:
            t = _randint(S + 4, idx, 0, 149)
            vals = [f"{a} {b} {c}" for a in TYPE_S1 for b in TYPE_S2
                    for c in TYPE_S3]
            cols["p_type"] = _strings(vals, t, VarcharType(25))
        cols["p_size"] = Column(INTEGER,
                                _randint(S + 5, idx, 1, 50)
                                .astype(np.int32), None)
        if "p_container" in need:
            c = _randint(S + 6, idx, 0, 39)
            vals = [f"{a} {b}" for a in CONTAINER_S1 for b in CONTAINER_S2]
            cols["p_container"] = _strings(vals, c, VarcharType(10))
        cols["p_retailprice"] = Column(DOUBLE, _retailprice(idx), None)
        if "p_comment" in need:
            cols["p_comment"] = _text_column(S + 7, idx, VarcharType(23))
        return self._finish(cols, n, columns)

    def _partsupp(self, idx, sf, columns) -> Batch:
        S = _SEED["partsupp"]
        n = len(idx)
        # row i (1-based over 4*P rows) -> (partkey, supplier slot)
        partkey = (idx - 1) // 4 + 1
        slot = (idx - 1) % 4
        s_count = table_rows("supplier", sf)
        cols: Dict[str, Column] = {}
        cols["ps_partkey"] = Column(BIGINT, partkey, None)
        cols["ps_suppkey"] = Column(BIGINT,
                                    _ps_suppkey(partkey, slot, s_count),
                                    None)
        cols["ps_availqty"] = Column(
            INTEGER, _randint(S + 2, idx, 1, 9999).astype(np.int32), None)
        cols["ps_supplycost"] = Column(
            DOUBLE, np.round(1.0 + _uniform(S + 3, idx) * 999.0, 2), None)
        if "ps_comment" in set(columns):
            cols["ps_comment"] = _text_column(S + 4, idx, VarcharType(199))
        return self._finish(cols, n, columns)

    def _customer(self, idx, sf, columns) -> Batch:
        S = _SEED["customer"]
        need = set(columns)
        n = len(idx)
        nationkey = _randint(S + 2, idx, 0, 24)
        cols: Dict[str, Column] = {}
        cols["c_custkey"] = Column(BIGINT, idx.copy(), None)
        if "c_name" in need:
            cols["c_name"] = _fmt_key_column("Customer#", idx,
                                             VarcharType(25))
        if "c_address" in need:
            cols["c_address"] = _alnum_column(S + 3, idx, VarcharType(40))
        cols["c_nationkey"] = Column(BIGINT, nationkey, None)
        if "c_phone" in need:
            cols["c_phone"] = _phone_column(S + 4, idx, nationkey)
        cols["c_acctbal"] = Column(
            DOUBLE, np.round(-999.99 + _uniform(S + 5, idx) * 10999.98, 2),
            None)
        if "c_mktsegment" in need:
            seg = _randint(S + 6, idx, 0, 4)
            cols["c_mktsegment"] = _strings(SEGMENTS, seg, VarcharType(10))
        if "c_comment" in need:
            cols["c_comment"] = _text_column(S + 7, idx, VarcharType(117))
        return self._finish(cols, n, columns)

    def _orders(self, idx, sf, columns) -> Batch:
        S = _SEED["orders"]
        need = set(columns)
        n = len(idx)
        c_count = table_rows("customer", sf)
        cols: Dict[str, Column] = {}
        cols["o_orderkey"] = Column(BIGINT, _order_key(idx), None)
        cols["o_custkey"] = Column(BIGINT, _cust_key(idx, c_count), None)
        odate = _order_date(idx)
        needs_lines = need & {"o_orderstatus", "o_totalprice"}
        if needs_lines:
            # derive from this order's lineitems (spec: status/totalprice
            # are aggregates of the generated lineitems)
            counts = _line_counts(idx)
            status = np.empty(n, dtype=np.int8)
            total = np.zeros(n, dtype=np.float64)
            order_rep = np.repeat(idx, counts)
            line_no = np.concatenate(
                [np.arange(1, c + 1) for c in counts]) \
                if n else np.zeros(0, np.int64)
            lf = _LineFields(order_rep, line_no.astype(np.int64), sf)
            seg = np.repeat(np.arange(n), counts)
            price = lf.extendedprice * (1.0 + lf.tax) * (1.0 - lf.discount)
            np.add.at(total, seg, price)
            shipped = lf.shipdate <= CURRENTDATE
            n_shipped = np.zeros(n, dtype=np.int64)
            np.add.at(n_shipped, seg, shipped.astype(np.int64))
            status = np.where(n_shipped == 0, 0,
                              np.where(n_shipped == counts, 1, 2))
            if "o_orderstatus" in need:
                cols["o_orderstatus"] = _strings(
                    ["O", "F", "P"], status, VarcharType(1))
            cols["o_totalprice"] = Column(DOUBLE, np.round(total, 2), None)
        cols["o_orderdate"] = Column(DATE, odate.astype(np.int32), None)
        if "o_orderpriority" in need:
            p = _randint(S + 5, idx, 0, 4)
            cols["o_orderpriority"] = _strings(PRIORITIES, p,
                                               VarcharType(15))
        if "o_clerk" in need:
            clerk = _randint(S + 6, idx, 1,
                             max(int(1000 * max(sf, 1.0)), 1))
            cols["o_clerk"] = _fmt_key_column("Clerk#", clerk,
                                              VarcharType(15))
        cols["o_shippriority"] = Column(
            INTEGER, np.zeros(n, dtype=np.int32), None)
        if "o_comment" in need:
            # ~1.6% of order comments contain 'special ... requests' (q13)
            slot = _u64(S + 7, idx) % np.uint64(64)
            cols["o_comment"] = _text_column(
                S + 8, idx, VarcharType(79),
                inject={"special packages requests": slot == 0})
        return self._finish(cols, n, columns)

    def _lineitem(self, order_idx, sf, columns) -> Batch:
        need = set(columns)
        counts = _line_counts(order_idx)
        order_rep = np.repeat(order_idx, counts)
        line_no = (np.concatenate([np.arange(1, c + 1) for c in counts])
                   if len(order_idx) else np.zeros(0, np.int64))
        lf = _LineFields(order_rep, line_no.astype(np.int64), sf)
        n = len(order_rep)
        S = _SEED["lineitem"]
        cols: Dict[str, Column] = {
            "l_orderkey": Column(BIGINT, lf.orderkey, None),
            "l_partkey": Column(BIGINT, lf.partkey, None),
            "l_suppkey": Column(BIGINT, lf.suppkey, None),
            "l_linenumber": Column(INTEGER,
                                   lf.linenumber.astype(np.int32), None),
            "l_quantity": Column(DOUBLE, lf.quantity, None),
            "l_extendedprice": Column(DOUBLE, lf.extendedprice, None),
            "l_discount": Column(DOUBLE, lf.discount, None),
            "l_tax": Column(DOUBLE, lf.tax, None),
            "l_shipdate": Column(DATE, lf.shipdate.astype(np.int32), None),
            "l_commitdate": Column(DATE, lf.commitdate.astype(np.int32),
                                   None),
            "l_receiptdate": Column(DATE, lf.receiptdate.astype(np.int32),
                                    None),
        }
        if "l_returnflag" in need:
            returned = lf.receiptdate <= CURRENTDATE
            ra = (_u64(S + 20, lf.rid) % np.uint64(2)).astype(np.int64)
            flag = np.where(returned, ra, 2)  # R/A else N
            cols["l_returnflag"] = _strings(["R", "A", "N"], flag,
                                            VarcharType(1))
        if "l_linestatus" in need:
            st = (lf.shipdate > CURRENTDATE).astype(np.int64)
            cols["l_linestatus"] = _strings(["F", "O"], st, VarcharType(1))
        if "l_shipinstruct" in need:
            si = _randint(S + 21, lf.rid, 0, 3)
            cols["l_shipinstruct"] = _strings(INSTRUCTIONS, si,
                                              VarcharType(25))
        if "l_shipmode" in need:
            sm = _randint(S + 22, lf.rid, 0, 6)
            cols["l_shipmode"] = _strings(MODES, sm, VarcharType(10))
        if "l_comment" in need:
            cols["l_comment"] = _text_column(S + 23, lf.rid,
                                             VarcharType(44))
        return self._finish(cols, n, columns)
