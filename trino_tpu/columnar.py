"""Columnar data model: Column / Batch — the TPU-native Page/Block.

Reference parity: core/trino-spi/src/main/java/io/trino/spi/Page.java:33-358
and spi/block/* (70 files). Redesigned for XLA rather than translated:

- A ``Column`` is a struct-of-arrays: a dense device value lane (``data``),
  an optional validity lane (``valid``; None means all-valid — the analog of
  Block.mayHaveNull()==false), and for string types a host-side deduplicated
  ``dictionary`` (DictionaryBlock made primary, SURVEY.md §7.1).
- A ``Batch`` is a named tuple of Columns plus a row count. Physical array
  length ("capacity") is a power-of-two bucket >= the logical ``num_rows``;
  rows past num_rows are garbage and every kernel masks them with
  ``iota < num_rows``. This is how data-dependent cardinalities (filters,
  joins) keep static shapes for XLA without a recompile per row-count.
- LazyBlock's deferred-load role (spi/block/LazyBlock.java) is played by
  host-resident numpy until a kernel first touches a column, at which point
  jnp.asarray uploads it to HBM.
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from typing import Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from . import config  # noqa: F401  (enables x64 before any jnp use)
from .config import capacity_for
from .types import (BOOLEAN, DOUBLE, BIGINT, DecimalType, Type, VarcharType,
                    CharType, is_string)

ArrayLike = Union[jax.Array, np.ndarray]


class StringDictionary:
    """Host-side deduplicated string pool backing a dictionary column.

    Codes are int32 indices into ``values``. The dictionary is immutable;
    merges produce a new dictionary plus a remap array usable as a device
    gather (reference analog: DictionaryBlock id remapping,
    spi/block/DictionaryBlock.java).

    Equality/hash are CONTENT-based (order-sensitive, via a cached
    fingerprint): the dictionary rides in the Column pytree aux, so
    jax's trace-cache treedef comparison uses ``__eq__`` — and any
    trace constant derived from a dictionary (merge remaps, per-entry
    predicate masks) is a pure function of the ordered value list.
    Content equality therefore means "same compiled program", which is
    what lets an AOT-fabricated dictionary (exec/aot.py, rebuilt from
    a hot-shape payload) land the live query on a compiled-program HIT
    instead of an identity-mismatch retrace.
    """

    __slots__ = ("values", "_index", "_fp")

    def __init__(self, values: np.ndarray, _index: Optional[dict] = None):
        self.values = np.asarray(values, dtype=object)
        self._index = _index
        self._fp: Optional[tuple] = None

    @staticmethod
    def from_strings(strings: Sequence[Optional[str]]):
        """Build (dictionary, codes) from raw strings; None -> code 0."""
        uniq: Dict[str, int] = {}
        codes = np.empty(len(strings), dtype=np.int32)
        for i, s in enumerate(strings):
            if s is None:
                codes[i] = 0
                continue
            c = uniq.get(s)
            if c is None:
                c = uniq.setdefault(s, len(uniq))
            codes[i] = c
        if not uniq:
            uniq[""] = 0
        vals = np.empty(len(uniq), dtype=object)
        for s, c in uniq.items():
            vals[c] = s
        return StringDictionary(vals, uniq), codes

    def __len__(self) -> int:
        return len(self.values)

    @property
    def index(self) -> dict:
        if self._index is None:
            self._index = {s: i for i, s in enumerate(self.values)}
        return self._index

    def code_of(self, s: str) -> int:
        """Code for s, or -1 if absent (no row can equal it)."""
        return self.index.get(s, -1)

    def rank_codes(self) -> np.ndarray:
        """rank[code] = collation rank of values[code]; for ORDER BY."""
        order = np.argsort(self.values.astype(str), kind="stable")
        ranks = np.empty(len(self.values), dtype=np.int32)
        ranks[order] = np.arange(len(self.values), dtype=np.int32)
        return ranks

    @property
    def fingerprint(self) -> tuple:
        """(length, blake2b-128 of the ordered value list) — computed
        once and cached. Order-sensitive on purpose: codes index
        ``values``, so two pools with the same set but different order
        are NOT interchangeable."""
        if self._fp is None:
            import hashlib
            h = hashlib.blake2b(digest_size=16)
            for v in self.values:
                if v is None:
                    h.update(b"\xff\x00\x00\x00\x00")
                else:
                    b = str(v).encode("utf-8", "surrogatepass")
                    h.update(len(b).to_bytes(4, "little"))
                    h.update(b)
            self._fp = (len(self.values), h.digest())
        return self._fp

    def __eq__(self, other):
        if other is self:
            return True
        if not isinstance(other, StringDictionary):
            return NotImplemented
        return self.fingerprint == other.fingerprint

    def __ne__(self, other):
        eq = self.__eq__(other)
        return eq if eq is NotImplemented else not eq

    def __hash__(self) -> int:
        return hash(self.fingerprint)

    def merge(self, other: "StringDictionary"):
        """Unify with other; returns (merged, remap_self, remap_other)."""
        if other is self:
            n = len(self.values)
            ident = np.arange(n, dtype=np.int32)
            return self, ident, ident
        idx = dict(self.index)
        vals: List[str] = list(self.values)
        remap_other = np.empty(len(other.values), dtype=np.int32)
        for i, s in enumerate(other.values):
            c = idx.get(s)
            if c is None:
                c = len(vals)
                idx[s] = c
                vals.append(s)
        for i, s in enumerate(other.values):
            remap_other[i] = idx[s]
        merged = StringDictionary(np.asarray(vals, dtype=object), idx)
        remap_self = np.arange(len(self.values), dtype=np.int32)
        return merged, remap_self, remap_other


@dataclass(frozen=True)
class Column:
    """One SQL column: value lane + validity lane (+ dictionary, + hi lane).

    ``data`` rows beyond the owning Batch's num_rows are garbage.
    ``valid`` is None when every (live) row is non-null.
    ``data2`` is the high int64 lane for DECIMAL(p>18) Int128 emulation.

    ARRAY columns (spi/block/ArrayBlock.java redesigned as
    struct-of-arrays): ``data`` is the per-row START offset into the
    flat ``elements`` column, ``data2`` the per-row LENGTH, and
    ``elements`` holds every element value (its own Column, possibly
    longer than the row capacity). Row gathers move only the
    offset/length lanes; ``elements`` is shared untouched.

    MAP columns (spi/block/MapBlock.java): same offsets/length lanes;
    ``elements`` is the flat KEY column and ``elements2`` the flat VALUE
    column, entry-aligned (key i pairs with value i).

    ROW columns (spi/block/RowBlock.java): ``children`` is one
    row-aligned Column per field; ``data`` is a dummy int8 lane that
    carries the capacity.
    """

    type: Type
    data: ArrayLike
    valid: Optional[ArrayLike] = None
    dictionary: Optional[StringDictionary] = None
    data2: Optional[ArrayLike] = None
    elements: Optional["Column"] = None
    elements2: Optional["Column"] = None
    children: Optional[Tuple["Column", ...]] = None

    def __post_init__(self):
        if is_string(self.type) and self.dictionary is None:
            raise ValueError(f"string column of type {self.type} needs a "
                             "dictionary")

    @property
    def capacity(self) -> int:
        return int(self.data.shape[0])

    def on_device(self) -> "Column":
        d = jnp.asarray(self.data)
        v = None if self.valid is None else jnp.asarray(self.valid)
        d2 = None if self.data2 is None else jnp.asarray(self.data2)
        return replace(self, data=d, valid=v, data2=d2)

    def gather(self, indices: ArrayLike, fill_invalid: Optional[ArrayLike]
               = None) -> "Column":
        """Row gather; optionally mark gathered rows invalid where
        ``fill_invalid`` is True (used for outer-join null padding)."""
        data = jnp.take(jnp.asarray(self.data), indices, axis=0,
                        mode="clip")
        valid = (None if self.valid is None
                 else jnp.take(jnp.asarray(self.valid), indices, axis=0,
                               mode="clip"))
        if fill_invalid is not None:
            base = jnp.ones_like(indices, dtype=bool) if valid is None \
                else valid
            valid = base & ~fill_invalid
        data2 = (None if self.data2 is None
                 else jnp.take(jnp.asarray(self.data2), indices, axis=0,
                               mode="clip"))
        children = (None if self.children is None
                    else tuple(c.gather(indices, fill_invalid)
                               for c in self.children))
        # elements are row-independent (offsets were gathered) — shared
        return replace(self, data=data, valid=valid, data2=data2,
                       children=children)

    def valid_mask(self, n: Optional[int] = None) -> jax.Array:
        cap = self.capacity if n is None else n
        if self.valid is None:
            return jnp.ones((cap,), dtype=bool)
        return jnp.asarray(self.valid)[:cap]

    def with_dictionary(self, dictionary: StringDictionary,
                        remap: np.ndarray) -> "Column":
        """Rewrite codes through remap into a merged dictionary."""
        codes = jnp.take(jnp.asarray(remap), jnp.asarray(self.data),
                         axis=0, mode="clip")
        return replace(self, data=codes, dictionary=dictionary)


def hi_lane_or_fill(col: "Column"):
    """``col.data2`` as a jnp lane, synthesized when absent: Int128
    decimal columns sign-extend (a negative lo zero-filled would be off
    by 2^64); every other data2 carrier (timestamptz offset, varchar
    length lane) fills with zeros. The single source of truth for
    concat sites merging mixed-representation parts."""
    import jax.numpy as jnp
    from .types import DecimalType
    if col.data2 is not None:
        return jnp.asarray(col.data2)
    if isinstance(col.type, DecimalType):
        return jnp.asarray(col.data).astype(jnp.int64) >> 63
    return jnp.zeros((col.capacity,), jnp.int64)


def _to_lane(values, typ: Type):
    """numpy-ify a python sequence for a non-string column; returns
    (data, valid|None, data2|None). ``data2`` is the Int128 high lane,
    present only for DECIMAL(p>18)."""
    dt = typ.np_dtype
    n = len(values)
    data = np.zeros(n, dtype=dt)
    valid = np.ones(n, dtype=bool)
    any_null = False
    long_decimal = isinstance(typ, DecimalType) and not typ.is_short
    is_tz = str(typ.name).endswith("with time zone")
    data2 = (np.zeros(n, dtype=np.int64)
             if long_decimal or is_tz else None)
    import datetime as _dt
    for i, v in enumerate(values):
        if v is None:
            valid[i] = False
            any_null = True
        elif is_tz:
            if isinstance(v, tuple):          # (utc_millis, offset_min)
                data[i], data2[i] = v
            elif isinstance(v, _dt.datetime):
                off = v.utcoffset()
                data2[i] = (0 if off is None
                            else int(off.total_seconds() // 60))
                naive = v.replace(tzinfo=None)
                data[i] = int((naive - _dt.datetime(1970, 1, 1))
                              .total_seconds() * 1000) \
                    - data2[i] * 60000
            else:
                data[i] = int(v)
        elif isinstance(v, _dt.datetime):
            data[i] = int((v - _dt.datetime(1970, 1, 1))
                          .total_seconds() * 1000)
        elif isinstance(v, _dt.date):
            data[i] = v.toordinal() - 719163  # 1970-01-01
        elif isinstance(typ, DecimalType):
            if isinstance(v, int):
                q = v * (10 ** typ.scale)
            else:
                # exact decimal scaling with HALF_UP (Trino rounding,
                # reference: spi/type/Decimals.java) — going through
                # binary float multiply would be off-by-one near .5
                import decimal
                # prec=80: the default 28-digit context silently rounds
                # DECIMAL(38) magnitudes during scaleb/multiply
                ctx = decimal.Context(prec=80)
                q = int(decimal.Decimal(str(v)).scaleb(typ.scale, ctx)
                        .to_integral_value(rounding=decimal.ROUND_HALF_UP))
            if long_decimal:
                # two's-complement split: lo = unsigned low 64 bits
                # (stored in an int64 lane), hi carries the sign
                lo = q & ((1 << 64) - 1)
                data[i] = lo - (1 << 64) if lo >= (1 << 63) else lo
                data2[i] = q >> 64
            else:
                data[i] = q
        elif typ is BOOLEAN or typ.name == "boolean":
            data[i] = bool(v)
        else:
            data[i] = v
    return data, (valid if any_null else None), data2


def column_from_pylist(values: Sequence, typ: Type) -> Column:
    """Build a host Column from python values (tests / VALUES literals)."""
    from .types import ArrayType, MapType, RowType
    if isinstance(typ, ArrayType):
        valid = np.asarray([v is not None for v in values], dtype=bool)
        lens = np.asarray([len(v) if v is not None else 0
                           for v in values], dtype=np.int64)
        offs = np.concatenate([[0], np.cumsum(lens)[:-1]]).astype(np.int64)
        flat: List = []
        for v in values:
            if v is not None:
                flat.extend(v)
        elements = column_from_pylist(flat or [None], typ.element)
        return Column(typ, offs, None if valid.all() else valid, None,
                      lens, elements)
    if isinstance(typ, MapType):
        valid = np.asarray([v is not None for v in values], dtype=bool)
        lens = np.asarray([len(v) if v is not None else 0
                           for v in values], dtype=np.int64)
        offs = np.concatenate([[0], np.cumsum(lens)[:-1]]).astype(np.int64)
        ks: List = []
        vs: List = []
        for v in values:
            if v is not None:
                for k, val in v.items():
                    ks.append(k)
                    vs.append(val)
        keys = column_from_pylist(ks or [None], typ.key)
        vals = column_from_pylist(vs or [None], typ.value)
        return Column(typ, offs, None if valid.all() else valid, None,
                      lens, keys, vals)
    if isinstance(typ, RowType):
        valid = np.asarray([v is not None for v in values], dtype=bool)
        kids = []
        for i, (_, ft) in enumerate(typ.fields):
            kids.append(column_from_pylist(
                [(v[i] if v is not None else None) for v in values], ft))
        return Column(typ, np.zeros(len(values), dtype=np.int8),
                      None if valid.all() else valid,
                      children=tuple(kids))
    if is_string(typ):
        dictionary, codes = StringDictionary.from_strings(
            [v for v in values])
        valid = np.asarray([v is not None for v in values], dtype=bool)
        return Column(typ, codes,
                      None if valid.all() else valid, dictionary)
    data, valid, data2 = _to_lane(values, typ)
    return Column(typ, data, valid, data2=data2)


def column_from_numpy(arr: np.ndarray, typ: Type,
                      valid: Optional[np.ndarray] = None) -> Column:
    return Column(typ, np.asarray(arr, dtype=typ.np_dtype), valid)


@dataclass(frozen=True)
class Batch:
    """A batch of rows: ordered named Columns + row count.

    ``num_rows`` may be a python int (host-known) or a 0-d device int64
    (data-dependent, e.g. post-filter). Kernels use ``num_rows_device``;
    host logic calls ``num_rows_host`` (blocks on the device value).
    """

    columns: Dict[str, Column]
    num_rows: Union[int, jax.Array]

    @property
    def names(self) -> List[str]:
        return list(self.columns.keys())

    @property
    def capacity(self) -> int:
        for c in self.columns.values():
            return c.capacity
        return 0

    def column(self, name: str) -> Column:
        return self.columns[name]

    def num_rows_device(self) -> jax.Array:
        return jnp.asarray(self.num_rows, dtype=jnp.int64)

    def num_rows_host(self) -> int:
        n = self.num_rows
        return int(n) if not isinstance(n, int) else n

    def row_valid(self) -> jax.Array:
        """iota < num_rows over the capacity."""
        return (jnp.arange(self.capacity, dtype=jnp.int64)
                < self.num_rows_device())

    def on_device(self) -> "Batch":
        return Batch({k: c.on_device() for k, c in self.columns.items()},
                     self.num_rows)

    def select_columns(self, names: Sequence[str]) -> "Batch":
        return Batch({n: self.columns[n] for n in names}, self.num_rows)

    def rename(self, mapping: Dict[str, str]) -> "Batch":
        return Batch({mapping.get(k, k): c
                      for k, c in self.columns.items()}, self.num_rows)

    def gather(self, indices: ArrayLike,
               num_rows: Union[int, jax.Array]) -> "Batch":
        return Batch({k: c.gather(indices)
                      for k, c in self.columns.items()}, num_rows)

    def _host_fetched(self) -> "Batch":
        leaves = jax.device_get(
            {k: [c.data, c.valid, c.data2]
             for k, c in self.columns.items()})
        cols = {}
        for k, c in self.columns.items():
            d, v, d2 = leaves[k]
            cols[k] = replace(c, data=d, valid=v, data2=d2)
        return Batch(cols, self.num_rows)

    # --- host materialization (result delivery / tests) ------------------
    def to_pylist(self) -> List[list]:
        """Rows as python lists (client result encoding, reference:
        server/protocol/QueryResultRows.java). All device buffers are
        fetched in ONE transfer first — on a remote-attached device
        (e.g. a TPU tunnel at ~90ms/round-trip) per-column np.asarray
        readbacks would dominate the query wall clock."""
        n = self.num_rows_host()
        batch = self._host_fetched()
        out_cols = []
        for c in batch.columns.values():
            data = np.asarray(c.data)[:n]
            valid = (np.ones(n, dtype=bool) if c.valid is None
                     else np.asarray(c.valid)[:n])
            t = c.type
            col: List = []
            if is_string(t) or (c.dictionary is not None
                                and t.name == "varbinary"):
                vals = c.dictionary.values
                for i in range(n):
                    col.append(str(vals[int(data[i])]) if valid[i] else None)
                    if (col[-1] is not None and isinstance(t, CharType)):
                        col[-1] = col[-1].ljust(t.length)
            elif isinstance(t, DecimalType):
                import decimal as _dec
                s = t.scale
                hi = None if c.data2 is None else np.asarray(c.data2)[:n]
                for i in range(n):
                    if not valid[i]:
                        col.append(None)
                    else:
                        if hi is not None:
                            # (hi, lo) two's-complement Int128: lo is the
                            # unsigned low 64 bits, hi carries the sign
                            lo = int(data[i]) & ((1 << 64) - 1)
                            q = (int(hi[i]) << 64) + lo
                        else:
                            q = int(data[i])
                        # type-stable exact materialization: int for
                        # scale 0, decimal.Decimal otherwise (the client
                        # layer formats; reference: client decimals are
                        # exact strings, FixJsonDataUtils.java)
                        col.append(q if not s
                                   else _dec.Decimal(q).scaleb(
                                       -s, _dec.Context(prec=80)))
            elif t.name == "geometry":
                # point lanes render as WKT; WKT-backed shapes pass
                # their dictionary text through (ops/geo.py)
                if c.dictionary is not None:
                    vals = c.dictionary.values
                    col = [(str(vals[int(data[i])])
                            if valid[i] else None) for i in range(n)]
                else:
                    ys = np.asarray(c.data2)[:n]
                    from .ops.geo import _fmt
                    col = [(f"POINT ({_fmt(data[i])} {_fmt(ys[i])})"
                            if valid[i] else None) for i in range(n)]
            elif t.name == "hyperloglog":
                # rendered like the client renders varbinary: base64 of
                # this engine's dense sketch framing (ops/hll.py)
                from .ops.hll import sketches_to_base64
                enc = sketches_to_base64(data[:n],
                                         np.asarray(c.data2)[:n],
                                         np.asarray(c.elements.data),
                                         t.bucket_bits)
                col = [(enc[i] if valid[i] else None) for i in range(n)]
            elif t.name == "tdigest" or t.name.startswith("qdigest("):
                from .ops.digest import sketches_to_base64 as _d64
                enc = _d64(data[:n], np.asarray(c.data2)[:n],
                           np.asarray(c.elements.data),
                           np.asarray(c.elements2.data))
                col = [(enc[i] if valid[i] else None) for i in range(n)]
            elif t.name.startswith("array("):
                # materialize the flat elements once, slice per row
                e = c.elements
                ecap = int(np.asarray(e.data).shape[0])
                epy = [r[0] for r in Batch({"e": e}, ecap).to_pylist()]
                lens = np.asarray(c.data2)[:n]
                col = [(epy[int(data[i]): int(data[i]) + int(lens[i])]
                        if valid[i] else None) for i in range(n)]
            elif t.name.startswith("map("):
                k, v = c.elements, c.elements2
                ecap = int(np.asarray(k.data).shape[0])
                kpy = [r[0] for r in Batch({"k": k}, ecap).to_pylist()]
                vpy = [r[0] for r in Batch({"v": v}, ecap).to_pylist()]
                lens = np.asarray(c.data2)[:n]
                col = []
                for i in range(n):
                    if not valid[i]:
                        col.append(None)
                        continue
                    s, ln = int(data[i]), int(lens[i])
                    col.append(dict(zip(kpy[s:s + ln], vpy[s:s + ln])))
            elif t.name.startswith("row("):
                kids = [
                    [r[0] for r in
                     Batch({"f": ch}, min(n, ch.capacity)).to_pylist()]
                    for ch in c.children]
                col = [(list(vals) if valid[i] else None)
                       for i, vals in enumerate(zip(*kids))][:n] \
                    if kids else [[] for _ in range(n)]
            elif t.name == "boolean":
                col = [bool(data[i]) if valid[i] else None for i in range(n)]
            elif t.name in ("real", "double"):
                col = [float(data[i]) if valid[i] else None
                       for i in range(n)]
            elif t.name == "date":
                import datetime as _dt
                epoch = _dt.date(1970, 1, 1).toordinal()
                col = [_dt.date.fromordinal(int(data[i]) + epoch)
                       if valid[i] else None for i in range(n)]
            elif t.name.endswith("with time zone"):
                import datetime as _dt
                offs = (np.asarray(c.data2)[:n] if c.data2 is not None
                        else np.zeros(n, np.int64))
                col = []
                for i in range(n):
                    if not valid[i]:
                        col.append(None)
                        continue
                    tz = _dt.timezone(
                        _dt.timedelta(minutes=int(offs[i])))
                    col.append(_dt.datetime(
                        1970, 1, 1, tzinfo=_dt.timezone.utc)
                        + _dt.timedelta(milliseconds=int(data[i])))
                    col[-1] = col[-1].astimezone(tz)
            elif t.name.startswith("timestamp"):
                import datetime as _dt
                col = [(_dt.datetime(1970, 1, 1)
                        + _dt.timedelta(milliseconds=int(data[i])))
                       if valid[i] else None for i in range(n)]
            elif t.name.startswith("time("):
                import datetime as _dt
                col = []
                for i in range(n):
                    if not valid[i]:
                        col.append(None)
                        continue
                    ms = int(data[i]) % 86400000
                    col.append(_dt.time(ms // 3600000,
                                        (ms // 60000) % 60,
                                        (ms // 1000) % 60,
                                        (ms % 1000) * 1000))
            else:
                col = [int(data[i]) if valid[i] else None for i in range(n)]
            out_cols.append(col)
        return [list(row) for row in zip(*out_cols)] if out_cols else []

    def schema(self) -> Dict[str, Type]:
        return {k: c.type for k, c in self.columns.items()}


def batch_from_pylist(data: Dict[str, Sequence], schema: Dict[str, Type],
                      pad_to_bucket: bool = True) -> Batch:
    cols = {}
    n = 0
    for name, typ in schema.items():
        col = column_from_pylist(data[name], typ)
        n = len(data[name])
        cols[name] = col
    if pad_to_bucket:
        # pad even empty batches: capacity-0 arrays break jnp.take
        cap = capacity_for(n, minimum=8)
        cols = {k: _pad(c, cap) for k, c in cols.items()}
    return Batch(cols, n)


def _pad(col: Column, cap: int) -> Column:
    n = col.data.shape[0]
    if n >= cap:
        return col
    pad = cap - n
    data = np.concatenate(
        [np.asarray(col.data),
         np.zeros(pad, dtype=np.asarray(col.data).dtype)])
    valid = None if col.valid is None else np.concatenate(
        [np.asarray(col.valid), np.zeros(pad, dtype=bool)])
    data2 = None if col.data2 is None else np.concatenate(
        [np.asarray(col.data2),
         np.zeros(pad, dtype=np.asarray(col.data2).dtype)])
    children = (None if col.children is None
                else tuple(_pad(c, cap) for c in col.children))
    return replace(col, data=data, valid=valid, data2=data2,
                   children=children)


def pad_batch(batch: Batch, cap: int) -> Batch:
    return Batch({k: _pad(c, cap) for k, c in batch.columns.items()},
                 batch.num_rows)


def empty_batch(schema: Dict[str, Type], capacity: int = 8) -> Batch:
    cols = {}
    for name, typ in schema.items():
        if is_string(typ):
            d, _ = StringDictionary.from_strings([])
            cols[name] = Column(typ, np.zeros(capacity, dtype=np.int32),
                                None, d)
        else:
            cols[name] = Column(
                typ, np.zeros(capacity, dtype=typ.np_dtype), None)
    return Batch(cols, 0)


# --- pytree registration ---------------------------------------------------
# Column/Batch flow through jit/shard_map traces (the SPMD data plane,
# parallel/spmd.py): lanes are children; type + dictionary are static
# aux data (a new dictionary identity retraces, which is correct — the
# compiled program embeds dictionary-derived lookup tables).

def _column_flatten(c: Column):
    return ((c.data, c.valid, c.data2, c.elements, c.elements2,
             c.children), (c.type, c.dictionary))


def _column_unflatten(aux, kids):
    data, valid, data2, elements, elements2, children = kids
    typ, dictionary = aux
    return Column(typ, data, valid, dictionary, data2, elements,
                  elements2, children)


def _batch_flatten(b: Batch):
    names = tuple(b.columns.keys())
    return (tuple(b.columns[n] for n in names), b.num_rows), names


def _batch_unflatten(names, children):
    cols, num_rows = children
    return Batch(dict(zip(names, cols)), num_rows)


jax.tree_util.register_pytree_node(Column, _column_flatten,
                                   _column_unflatten)
jax.tree_util.register_pytree_node(Batch, _batch_flatten,
                                   _batch_unflatten)


def concat_batches(batches: Sequence[Batch]) -> Batch:
    """Host-side concatenation of result batches (final GATHER stage)."""
    batches = [b for b in batches if b.num_rows_host() > 0] or batches[:1]
    if len(batches) == 1:
        return batches[0]
    names = batches[0].names
    total = sum(b.num_rows_host() for b in batches)
    cols: Dict[str, Column] = {}
    for name in names:
        parts = [b.column(name) for b in batches]
        typ = parts[0].type
        if parts[0].elements is not None or parts[0].children is not None:
            from .exec.complex import concat_columns_host
            cols[name] = concat_columns_host(
                parts, [b.num_rows_host() for b in batches],
                capacity_for(total))
            continue
        datas, valids = [], []
        if is_string(typ):
            merged = parts[0].dictionary
            remaps = [np.arange(len(merged), dtype=np.int32)]
            for p in parts[1:]:
                merged, rs, ro = merged.merge(p.dictionary)
                remaps = [r for r in remaps]
                remaps.append(ro)
            for p, rm, b in zip(parts, remaps, batches):
                n = b.num_rows_host()
                codes = np.asarray(p.data)[:n]
                datas.append(rm[codes])
                valids.append(np.ones(n, bool) if p.valid is None
                              else np.asarray(p.valid)[:n])
            data = np.concatenate(datas) if datas else np.zeros(0, np.int32)
            valid = np.concatenate(valids)
            cols[name] = Column(
                typ, data.astype(np.int32),
                None if valid.all() else valid, merged)
        else:
            has_hi = any(p.data2 is not None for p in parts)
            his = []
            for p, b in zip(parts, batches):
                n = b.num_rows_host()
                datas.append(np.asarray(p.data)[:n])
                valids.append(np.ones(n, bool) if p.valid is None
                              else np.asarray(p.valid)[:n])
                if has_hi:
                    if p.data2 is not None:
                        his.append(np.asarray(p.data2)[:n])
                    else:
                        # short-decimal part: hi lane is the sign extension
                        lo = np.asarray(p.data)[:n]
                        his.append(np.where(lo < 0, np.int64(-1),
                                            np.int64(0)))
            data = np.concatenate(datas)
            valid = np.concatenate(valids)
            cols[name] = Column(typ, data,
                                None if valid.all() else valid,
                                data2=(np.concatenate(his) if has_hi
                                       else None))
    return pad_batch(Batch(cols, total), capacity_for(total))
