"""Query session: default catalog/schema + session properties.

Reference parity: core/trino-main/.../Session.java +
SystemSessionProperties.java (88 typed properties; we carry the subset the
TPU engine consults, same names where they exist in the reference).
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field
from typing import Dict, Optional, Tuple

from .config import CONFIG

_query_counter = itertools.count(1)

# name -> (type, default). Mirrors SystemSessionProperties.java entries.
# Every property here is CONSULTED by the engine (VERDICT r2 weak #6:
# flags that lie about capabilities are worse than no flags):
#   join_distribution_type   planner/stats.py choose_join_sides
#   join_reordering_strategy planner/optimizer.py optimize (NONE | AUTOMATIC)
#   task_concurrency         exec/executor.py split parallelism
#   spill_enabled            exec/executor.py streaming (split-wise) agg
#   enable_dynamic_filtering exec/distributed.py join probe pre-filter
#   query_max_memory_per_node config/capacity ceiling (QueryError on breach)
SESSION_PROPERTIES: Dict[str, Tuple[type, object]] = {
    "join_distribution_type": (str, "AUTOMATIC"),   # :53
    "join_reordering_strategy": (str, "AUTOMATIC"),  # :85
    "task_concurrency": (int, 1),                    # :61
    "spill_enabled": (bool, CONFIG.spill_enabled),   # :91
    "enable_dynamic_filtering": (bool, True),        # :123
    # range-exchange distributed ORDER BY (exec/distributed.py
    # _dexec_SortNode); reference SystemSessionProperties :106
    "distributed_sort": (bool, True),
    "query_max_memory_per_node": (int, CONFIG.max_query_memory_per_node),
    # connector pushdown (PushPredicateIntoTableScan /
    # PushLimitIntoTableScan); consulted by planner/optimizer.py
    "pushdown_into_scan": (bool, True),
    # remote-task fan-out cap (SystemSessionProperties
    # HASH_PARTITION_COUNT :58): 0 = one task per live worker
    # (exec/remote.py RemoteScheduler)
    "hash_partition_count": (int, 0),
    # LZ4 page frames on the exchange (exchange.compression-enabled;
    # server/task_worker.py paginate)
    "exchange_compression": (bool, True),
    # wall-clock limit in seconds, 0 = unlimited (QUERY_MAX_RUN_TIME
    # :72). The coordinator derives an ABSOLUTE per-query deadline
    # (session.deadline) from it before dispatch; the executor checks
    # it between plan nodes and the remote/stage schedulers bound every
    # attempt, retry backoff, and speculation grant by the remaining
    # budget — a breach cancels in-flight worker attempts instead of
    # only failing the next coordinator poll (EXCEEDED_TIME_LIMIT)
    "query_max_run_time": (int, 0),
    # cluster-wide per-query memory cap in bytes, 0 = pool-limit only
    # (QUERY_MAX_MEMORY; enforced by server/memory.py when a cluster
    # memory pool is configured — EXCEEDED_GLOBAL_MEMORY_LIMIT)
    "query_max_memory": (int, 0),
    # cost-based join reorder/side decisions from connector statistics
    # (optimizer.use-table-statistics; planner/optimizer.py)
    "use_table_statistics": (bool, True),
    # ---- fault-tolerant execution (trino_tpu/fte/) -------------------
    # NONE fails the query on the first task failure; TASK re-dispatches
    # failed leaf-fragment tasks (reference: RetryPolicy.java +
    # SystemSessionProperties RETRY_POLICY)
    "retry_policy": (str, "NONE"),
    # TOTAL attempts per task incl. the first
    # (task-retry-attempts-per-task)
    "task_retry_attempts": (int, 4),
    # extra attempts (retries + speculative duplicates) across the
    # whole query (query-retry-attempts)
    "query_retry_attempts": (int, 16),
    # exponential backoff window between attempts
    # (retry-initial-delay / retry-max-delay)
    "retry_initial_delay_ms": (int, 50),
    "retry_max_delay_ms": (int, 2000),
    # client-side bound on one task attempt producing pages; a wedged
    # worker turns into a retriable failure instead of a hung query
    "remote_task_timeout": (int, 600),
    # straggler speculation (fte/speculate.py): re-dispatch a running
    # task once it exceeds multiplier x the fragment's median completed
    # runtime (with an absolute floor), first-completion-wins
    "speculation_enabled": (bool, False),
    "speculation_multiplier": (float, 2.0),
    "speculation_min_runtime_ms": (int, 200),
    # ---- static analysis (trino_tpu/analysis/) -----------------------
    # run the PlanSanityChecker after EVERY optimizer pass (debug mode:
    # a broken rewrite is blamed on the pass that broke the invariant).
    # The checker always runs once before remote fragment dispatch
    # regardless of this flag. (reference: the sanity battery
    # PlanSanityChecker runs per-pass under tests/assertions)
    "plan_validation": (bool, False),
    # which spool backend a query's attempts commit through when the
    # scheduler has to create one (fte/spool.py make_spool): "" defers
    # to the process default (CONFIG.spool_backend / env
    # TRINO_TPU_SPOOL_BACKEND); "local" | "memory" override it
    # (reference: exchange-manager selection in exchange.properties)
    "spool_backend": (str, ""),
    # ---- multi-stage MPP (trino_tpu/stage/) --------------------------
    # route distributed queries through the stage-DAG scheduler: the
    # plan is cut at exchange points, joins/aggregations execute ON
    # WORKERS over a hash-partitioned worker-to-worker exchange, the
    # coordinator streams only the root stage. ON by default — the
    # stage DAG IS the engine; the flat leaf-fragment scatter-gather
    # path is the explicit fallback (set false to force it; plans the
    # fragmenter declines fall back to it either way).
    "multistage_execution": (bool, True),
    # eager cross-stage pipelining (stage/scheduler.py): consumer
    # stages dispatch immediately and pull committed upstream
    # partitions WHILE their producer stage is still running (the
    # spool's first-commit-wins frames make partial reads safe). Off =
    # the per-stage barrier (each stage waits for all of its inputs) —
    # kept as the A/B baseline and the conservative mode.
    "stage_pipelining": (bool, True),
    # lower in-slice stage exchanges to device collectives
    # (stage/ici.py): when the whole stage DAG executes on one TPU
    # slice (LocalQueryRunner(distributed=True) / a mesh-backed
    # worker), the hash repartition at stage boundaries runs as
    # jax.lax.all_to_all over ICI instead of spool+HTTP frames — only
    # cross-host edges touch the spool. Off = mesh queries keep the
    # node-at-a-time distributed executor (exec/distributed.py).
    "ici_exchange": (bool, True),
    # task fan-out of intermediate (exchange-fed) stages; 0 = one task
    # per live worker (the leaf fan-out keeps following
    # hash_partition_count — reference: SystemSessionProperties
    # FAULT_TOLERANT_EXECUTION_PARTITION_COUNT)
    "exchange_partition_count": (int, 0),
    # ---- compile amortization (exec/progkey.py + exec/hotshapes.py +
    # exec/aot.py) ----------------------------------------------------
    # record this query's structural program shapes into the hot-shape
    # registry (the worker pre-warm feed): off = the query still HITS
    # warm caches but contributes nothing to them (e.g. exploratory
    # one-off SQL that must not evict the fleet's hot shapes)
    "prewarm_enabled": (bool, CONFIG.prewarm_enabled),
    # per-query budget of NEW registry entries (a generated-SQL storm
    # of one-off shapes keeps hitting existing entries but cannot
    # flood the feed); also the default count served at /v1/hotshapes
    # when the puller names no k
    "hot_shape_top_k": (int, CONFIG.prewarm_top_k),
    # ---- beyond-HBM morsel streaming (exec/streamjoin.py) ------------
    # chunk row count for streamed operators: 0 (default) auto-engages
    # streaming only when an operator's full-materialization estimate
    # exceeds the memory budget, with the chunk capacity derived from
    # the budget; > 0 FORCES every streamable scan chain / probe join
    # / streaming aggregation to chunk at (the power-of-two bucket of)
    # this row count — tests and bench pin the capacity this way;
    # < 0 disables streaming (fall back to the materialized path and
    # its memory errors — the operator escape hatch)
    "stream_chunk_rows": (int, CONFIG.stream_chunk_rows),
    # ---- worker-side multi-query runtime (exec/taskexec.py) ----------
    # stream per-task live memory reservations from workers back into
    # the coordinator's cluster memory pool DURING execution (status-
    # poll beats), so the low-memory killer acts on live worker bytes
    # instead of coordinator-side estimates. Off = workers still
    # account locally but the pool only sees coordinator reservations
    # + completion-time peaks (the pre-PR-14 behavior; the escape
    # hatch for tests pinning killer provenance).
    "live_memory_feedback": (bool, True),
    # ---- point-lookup serving (exec/resultcache.py +
    # exec/taskexec.py RaggedBatcher) ----------------------------------
    # serve a repeated identical deterministic query straight from the
    # coordinator's result cache (canonical program key + split
    # fingerprint, invalidated by connector data version) with zero
    # dispatched tasks. Opt-in: a cached result is synthesized without
    # plan/trace/stats, so interactive EXPLAIN ANALYZE-style workflows
    # keep the default off (dashboards SET it on).
    "result_cache_enabled": (bool, False),
    # coalesce compatible small fragments (same canonical program key,
    # same connector, combined rows under ragged_batch_max_rows) into
    # ONE ragged batch executed by a single compiled program, demuxed
    # per query. Opt-in: the formation window adds latency to solo
    # queries, so only storm-shaped workloads should enable it.
    "ragged_batching": (bool, False),
    # combined-row cap for one ragged batch (the batch-capacity
    # bucket); fragments whose sum would exceed it run solo
    "ragged_batch_max_rows": (int, CONFIG.ragged_batch_rows),
    # ---- distributed tracing (obs/trace.py + obs/otlp.py) ------------
    # export this query's finished trace to the configured OTLP sinks
    # (TRINO_TPU_OTLP_FILE / TRINO_TPU_OTLP_ENDPOINT). Off = the trace
    # still exists (EXPLAIN ANALYZE, /v1/query, /v1/trace) but nothing
    # leaves the process — the per-query opt-out for sensitive SQL.
    "otlp_export": (bool, True),
    # ---- query history + learned statistics (obs/history.py +
    # exec/learnedstats.py) --------------------------------------------
    # append this query's terminal record to the coordinator's durable
    # history store (GET /v1/history, system.runtime.queries). Off =
    # the query runs unrecorded — the per-query opt-out for sensitive
    # SQL (the record carries the statement text and digest).
    "query_history_enabled": (bool, True),
    # fold this query's observed per-operator rows-in/rows-out and
    # wall time into the learned-stats registry (selectivity and
    # rows/s EMAs keyed by canonical program key — GET /v1/stats,
    # system.runtime.operator_stats, the adaptive cost model's seed).
    # Off = the query still BENEFITS from learned priors but
    # contributes nothing (e.g. deliberately skewed test corpora).
    "learned_stats_enabled": (bool, True),
    # slow-query log threshold in milliseconds: a terminal query whose
    # wall time (queued included) crosses it is written — full record,
    # trace id linked — to slow_queries.jsonl next to the history
    # file. 0 disables the outlier log (the default).
    "slow_query_log_ms": (int, 0),
    # ---- streaming ingestion + continuous queries (streaming/) -------
    # default re-dispatch cadence for continuous-query jobs created
    # without an explicit poll_interval_ms (streaming/continuous.py;
    # the per-job spec value always wins). Milliseconds between the
    # end of one incremental cycle and the start of the next.
    "stream_poll_interval_ms": (int, CONFIG.stream_poll_interval_ms),
    # default allowed event-time lateness for window jobs created
    # without an explicit lateness_ms: the watermark trails
    # max(event time) by this much, so late rows within the horizon
    # still re-aggregate on the next cycle
    "stream_lateness_ms": (int, CONFIG.stream_lateness_ms),
}


@dataclass
class Session:
    catalog: Optional[str] = None
    schema: Optional[str] = None
    user: str = "user"
    properties: Dict[str, object] = field(default_factory=dict)
    # cooperative cancellation: the executor checks this between plan
    # nodes (execution/QueryStateMachine's transitionToCanceled analog)
    cancel: Optional[object] = None
    # PREPARE name FROM stmt registry (reference: Session.java
    # preparedStatements + execution/PrepareTask.java)
    prepared: Dict[str, object] = field(default_factory=dict)
    # telemetry (obs/): the current query's span tree — the runner
    # installs one per query; the executor nests jit_trace /
    # device_execute children under the open execute span
    trace: Optional[object] = None
    # event fan-out (server/events.py EventListenerManager): when set,
    # the executor fires SplitCompletedEvents from the split-read path
    events: Optional[object] = None
    # id of the query currently executing on this session (stamped by
    # the coordinator / runner; carried into events and spans)
    query_id: str = ""
    # absolute per-query deadline (time.monotonic() timebase), derived
    # from query_max_run_time by the coordinator's tracker (or by the
    # standalone runner) — the executor and the remote/stage schedulers
    # enforce it cooperatively (EXCEEDED_TIME_LIMIT on breach)
    deadline: Optional[float] = None
    # cluster memory governance (server/memory.py): a per-query
    # reservation context; when set, Executor._reserve feeds its
    # capacity estimates into the cluster pool, arming the per-group
    # limits and the low-memory killer
    memory: Optional[object] = None
    # the admitting resource group's identity + scheduling weight
    # (stamped by the coordinator tracker): the remote/stage
    # schedulers ship these in task payloads so the WORKER's shared
    # split scheduler (exec/taskexec.py) drains fair-share by group
    resource_group: str = "global"
    resource_group_weight: float = 1.0
    # worker-side split scheduler yield hook (exec/taskexec.py
    # TaskHandle.checkpoint, installed by server/task_worker.py on
    # task sessions): the executor calls it at split/chunk boundaries
    # so concurrent queries' tasks interleave on the shared runner
    # pool; None outside a scheduled worker task
    split_yield: Optional[object] = None
    # slot-releasing wait hook (exec/taskexec.py TaskHandle.run_blocked,
    # installed next to split_yield): ragged batch formation parks the
    # leader for the window and members for the leader's execution —
    # both waits MUST release the bounded runner slot or members
    # holding every slot deadlock the leader's re-acquire; None = wait
    # inline (standalone runner, no pool to starve)
    slot_wait: Optional[object] = None

    def remaining_time(self) -> Optional[float]:
        """Seconds left before the deadline (None = no deadline).
        Negative once the budget is spent."""
        if self.deadline is None:
            return None
        import time
        return self.deadline - time.monotonic()

    def get(self, name: str):
        if name in self.properties:
            return self.properties[name]
        if name in SESSION_PROPERTIES:
            return SESSION_PROPERTIES[name][1]
        raise KeyError(f"Session property '{name}' does not exist")

    def set(self, name: str, value) -> None:
        if name not in SESSION_PROPERTIES:
            raise KeyError(f"Session property '{name}' does not exist")
        want, _ = SESSION_PROPERTIES[name]
        if want is bool and isinstance(value, str):
            value = value.lower() in ("true", "1", "on")
        self.properties[name] = want(value)

    def reset(self, name: str) -> None:
        self.properties.pop(name, None)

    def next_query_id(self) -> str:
        return f"query_{next(_query_counter)}"
