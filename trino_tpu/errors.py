"""Error taxonomy: StandardErrorCode + ErrorType.

Reference parity: core/trino-spi/.../ErrorType.java:18-21 (USER_ERROR,
INTERNAL_ERROR, INSUFFICIENT_RESOURCES, EXTERNAL) and
StandardErrorCode.java (~130 codes). The client protocol and tests key
off these names/codes, so the numbering matches the reference exactly
(code = ordinal value from StandardErrorCode; EXTERNAL starts at
0x0100_0000 * 3 like the reference's blocks).
"""

from __future__ import annotations

from typing import Optional, Tuple

USER_ERROR = "USER_ERROR"
INTERNAL_ERROR = "INTERNAL_ERROR"
INSUFFICIENT_RESOURCES = "INSUFFICIENT_RESOURCES"
EXTERNAL = "EXTERNAL"

_INTERNAL_BASE = 0x0001_0000
_INSUFFICIENT_BASE = 0x0002_0000
_EXTERNAL_BASE = 0x0100_0000

# name -> (code, type). USER_ERROR block is 0-based, INTERNAL 0x10000,
# INSUFFICIENT_RESOURCES 0x20000 (StandardErrorCode.java ordinals).
STANDARD_ERROR_CODES = {
    # user errors (StandardErrorCode.java:20-90)
    "GENERIC_USER_ERROR": (0, USER_ERROR),
    "SYNTAX_ERROR": (1, USER_ERROR),
    "ABANDONED_QUERY": (2, USER_ERROR),
    "USER_CANCELED": (3, USER_ERROR),
    "PERMISSION_DENIED": (4, USER_ERROR),
    "NOT_FOUND": (5, USER_ERROR),
    "FUNCTION_NOT_FOUND": (6, USER_ERROR),
    "INVALID_FUNCTION_ARGUMENT": (7, USER_ERROR),
    "DIVISION_BY_ZERO": (8, USER_ERROR),
    "INVALID_CAST_ARGUMENT": (9, USER_ERROR),
    "OPERATOR_NOT_FOUND": (10, USER_ERROR),
    "INVALID_VIEW": (11, USER_ERROR),
    "ALREADY_EXISTS": (12, USER_ERROR),
    "NOT_SUPPORTED": (13, USER_ERROR),
    "INVALID_SESSION_PROPERTY": (14, USER_ERROR),
    "INVALID_WINDOW_FRAME": (15, USER_ERROR),
    "CONSTRAINT_VIOLATION": (16, USER_ERROR),
    "TRANSACTION_CONFLICT": (17, USER_ERROR),
    "INVALID_TABLE_PROPERTY": (18, USER_ERROR),
    "NUMERIC_VALUE_OUT_OF_RANGE": (19, USER_ERROR),
    "UNKNOWN_TRANSACTION": (20, USER_ERROR),
    "NOT_IN_TRANSACTION": (21, USER_ERROR),
    "TRANSACTION_ALREADY_ABORTED": (22, USER_ERROR),
    "READ_ONLY_VIOLATION": (23, USER_ERROR),
    "MULTI_CATALOG_WRITE_CONFLICT": (24, USER_ERROR),
    "AUTOCOMMIT_WRITE_CONFLICT": (25, USER_ERROR),
    "UNSUPPORTED_ISOLATION_LEVEL": (26, USER_ERROR),
    "INCOMPATIBLE_CLIENT": (27, USER_ERROR),
    "SUBQUERY_MULTIPLE_ROWS": (28, USER_ERROR),
    "PROCEDURE_NOT_FOUND": (29, USER_ERROR),
    "INVALID_PROCEDURE_ARGUMENT": (30, USER_ERROR),
    "QUERY_REJECTED": (31, USER_ERROR),
    "AMBIGUOUS_FUNCTION_CALL": (32, USER_ERROR),
    "INVALID_SCHEMA_PROPERTY": (33, USER_ERROR),
    "SCHEMA_NOT_EMPTY": (34, USER_ERROR),
    "QUERY_TEXT_TOO_LARGE": (35, USER_ERROR),
    "UNSUPPORTED_SUBQUERY": (36, USER_ERROR),
    "EXCEEDED_FUNCTION_MEMORY_LIMIT": (37, USER_ERROR),
    "ADMINISTRATIVELY_KILLED": (38, USER_ERROR),
    "INVALID_COLUMN_PROPERTY": (39, USER_ERROR),
    "QUERY_HAS_TOO_MANY_STAGES": (40, USER_ERROR),
    "INVALID_SPATIAL_PARTITIONING": (41, USER_ERROR),
    "INVALID_ANALYZE_PROPERTY": (42, USER_ERROR),
    "TYPE_NOT_FOUND": (43, USER_ERROR),
    "CATALOG_NOT_FOUND": (44, USER_ERROR),
    "SCHEMA_NOT_FOUND": (45, USER_ERROR),
    "TABLE_NOT_FOUND": (46, USER_ERROR),
    "COLUMN_NOT_FOUND": (47, USER_ERROR),
    "ROLE_NOT_FOUND": (48, USER_ERROR),
    "SCHEMA_ALREADY_EXISTS": (49, USER_ERROR),
    "TABLE_ALREADY_EXISTS": (50, USER_ERROR),
    "COLUMN_ALREADY_EXISTS": (51, USER_ERROR),
    "ROLE_ALREADY_EXISTS": (52, USER_ERROR),
    "DUPLICATE_NAMED_QUERY": (53, USER_ERROR),
    "DUPLICATE_COLUMN_NAME": (54, USER_ERROR),
    "MISSING_COLUMN_NAME": (55, USER_ERROR),
    "MISSING_CATALOG_NAME": (56, USER_ERROR),
    "MISSING_SCHEMA_NAME": (57, USER_ERROR),
    "TYPE_MISMATCH": (58, USER_ERROR),
    "INVALID_LITERAL": (59, USER_ERROR),
    "COLUMN_TYPE_UNKNOWN": (60, USER_ERROR),
    "MISMATCHED_COLUMN_ALIASES": (61, USER_ERROR),
    "AMBIGUOUS_NAME": (62, USER_ERROR),
    "INVALID_COLUMN_REFERENCE": (63, USER_ERROR),
    "MISSING_GROUP_BY": (64, USER_ERROR),
    "MISSING_ORDER_BY": (65, USER_ERROR),
    "MISSING_OVER": (66, USER_ERROR),
    "NESTED_AGGREGATION": (67, USER_ERROR),
    "NESTED_WINDOW": (68, USER_ERROR),
    "EXPRESSION_NOT_IN_DISTINCT": (69, USER_ERROR),
    "TOO_MANY_GROUPING_SETS": (70, USER_ERROR),
    "FUNCTION_NOT_WINDOW": (71, USER_ERROR),
    "FUNCTION_NOT_AGGREGATE": (72, USER_ERROR),
    "EXPRESSION_NOT_AGGREGATE": (73, USER_ERROR),
    "EXPRESSION_NOT_SCALAR": (74, USER_ERROR),
    "EXPRESSION_NOT_CONSTANT": (75, USER_ERROR),
    "INVALID_ARGUMENTS": (76, USER_ERROR),
    "TOO_MANY_ARGUMENTS": (77, USER_ERROR),
    "INVALID_PRIVILEGE": (78, USER_ERROR),
    "DUPLICATE_PROPERTY": (79, USER_ERROR),
    "INVALID_PARAMETER_USAGE": (80, USER_ERROR),
    "VIEW_IS_STALE": (81, USER_ERROR),
    "VIEW_IS_RECURSIVE": (82, USER_ERROR),
    "NULL_TREATMENT_NOT_ALLOWED": (83, USER_ERROR),
    "INVALID_ROW_FILTER": (84, USER_ERROR),
    "INVALID_COLUMN_MASK": (85, USER_ERROR),
    "MISSING_TABLE": (86, USER_ERROR),
    "INVALID_RECURSIVE_REFERENCE": (87, USER_ERROR),
    "MISSING_COLUMN_ALIASES": (88, USER_ERROR),
    "NESTED_RECURSIVE": (89, USER_ERROR),
    # internal errors (0x0001_0000 block)
    "GENERIC_INTERNAL_ERROR": (_INTERNAL_BASE + 0, INTERNAL_ERROR),
    "TOO_MANY_REQUESTS_FAILED": (_INTERNAL_BASE + 1, INTERNAL_ERROR),
    "PAGE_TOO_LARGE": (_INTERNAL_BASE + 2, INTERNAL_ERROR),
    "PAGE_TRANSPORT_ERROR": (_INTERNAL_BASE + 3, INTERNAL_ERROR),
    "PAGE_TRANSPORT_TIMEOUT": (_INTERNAL_BASE + 4, INTERNAL_ERROR),
    "NO_NODES_AVAILABLE": (_INTERNAL_BASE + 5, INTERNAL_ERROR),
    "REMOTE_TASK_ERROR": (_INTERNAL_BASE + 6, INTERNAL_ERROR),
    "COMPILER_ERROR": (_INTERNAL_BASE + 7, INTERNAL_ERROR),
    "REMOTE_TASK_MISMATCH": (_INTERNAL_BASE + 8, INTERNAL_ERROR),
    "SERVER_SHUTTING_DOWN": (_INTERNAL_BASE + 9, INTERNAL_ERROR),
    "FUNCTION_IMPLEMENTATION_MISSING": (
        _INTERNAL_BASE + 10, INTERNAL_ERROR),
    "REMOTE_BUFFER_CLOSE_FAILED": (_INTERNAL_BASE + 11, INTERNAL_ERROR),
    "SERVER_STARTING_UP": (_INTERNAL_BASE + 12, INTERNAL_ERROR),
    "FUNCTION_IMPLEMENTATION_ERROR": (
        _INTERNAL_BASE + 13, INTERNAL_ERROR),
    "INVALID_PROCEDURE_DEFINITION": (
        _INTERNAL_BASE + 14, INTERNAL_ERROR),
    "PROCEDURE_CALL_FAILED": (_INTERNAL_BASE + 15, INTERNAL_ERROR),
    "AMBIGUOUS_FUNCTION_IMPLEMENTATION": (
        _INTERNAL_BASE + 16, INTERNAL_ERROR),
    "ABANDONED_TASK": (_INTERNAL_BASE + 17, INTERNAL_ERROR),
    "CORRUPT_SERIALIZED_IDENTITY": (_INTERNAL_BASE + 18, INTERNAL_ERROR),
    "CORRUPT_PAGE": (_INTERNAL_BASE + 19, INTERNAL_ERROR),
    "OPTIMIZER_TIMEOUT": (_INTERNAL_BASE + 20, INTERNAL_ERROR),
    "OUT_OF_SPILL_SPACE": (_INTERNAL_BASE + 21, INSUFFICIENT_RESOURCES),
    "REMOTE_HOST_GONE": (_INTERNAL_BASE + 22, INTERNAL_ERROR),
    "CONFIGURATION_INVALID": (_INTERNAL_BASE + 23, INTERNAL_ERROR),
    "CONFIGURATION_UNAVAILABLE": (_INTERNAL_BASE + 24, INTERNAL_ERROR),
    "INVALID_RESOURCE_GROUP": (_INTERNAL_BASE + 25, INTERNAL_ERROR),
    "SERIALIZATION_ERROR": (_INTERNAL_BASE + 26, INTERNAL_ERROR),
    "REMOTE_TASK_FAILED": (_INTERNAL_BASE + 27, INTERNAL_ERROR),
    "EXCHANGE_MANAGER_NOT_CONFIGURED": (
        _INTERNAL_BASE + 28, INTERNAL_ERROR),
    # insufficient resources (0x0002_0000 block)
    "GENERIC_INSUFFICIENT_RESOURCES": (
        _INSUFFICIENT_BASE + 0, INSUFFICIENT_RESOURCES),
    "EXCEEDED_GLOBAL_MEMORY_LIMIT": (
        _INSUFFICIENT_BASE + 1, INSUFFICIENT_RESOURCES),
    "QUERY_QUEUE_FULL": (_INSUFFICIENT_BASE + 2, INSUFFICIENT_RESOURCES),
    "EXCEEDED_TIME_LIMIT": (
        _INSUFFICIENT_BASE + 3, INSUFFICIENT_RESOURCES),
    "CLUSTER_OUT_OF_MEMORY": (
        _INSUFFICIENT_BASE + 4, INSUFFICIENT_RESOURCES),
    "EXCEEDED_CPU_LIMIT": (
        _INSUFFICIENT_BASE + 5, INSUFFICIENT_RESOURCES),
    "EXCEEDED_SPILL_LIMIT": (
        _INSUFFICIENT_BASE + 6, INSUFFICIENT_RESOURCES),
    "EXCEEDED_LOCAL_MEMORY_LIMIT": (
        _INSUFFICIENT_BASE + 7, INSUFFICIENT_RESOURCES),
    "ADMINISTRATIVELY_PREEMPTED": (
        _INSUFFICIENT_BASE + 8, INSUFFICIENT_RESOURCES),
    "EXCEEDED_SCAN_LIMIT": (
        _INSUFFICIENT_BASE + 9, INSUFFICIENT_RESOURCES),
    # external
    "GENERIC_EXTERNAL": (_EXTERNAL_BASE + 0, EXTERNAL),
}


def error_info(name: str) -> Tuple[int, str]:
    return STANDARD_ERROR_CODES.get(
        name, STANDARD_ERROR_CODES["GENERIC_INTERNAL_ERROR"])


def http_status_for(error_type: str) -> int:
    """HTTP status for a non-protocol error surface (the statement
    protocol itself always carries errors in a 200 QueryResults
    payload, like the reference). USER_ERROR maps to 400,
    INSUFFICIENT_RESOURCES to 429 (the governance layer's admission
    rejections and memory kills are back-pressure, not server bugs —
    a bare 500 would make clients treat "queue full" as an outage),
    everything else stays 500."""
    if error_type == USER_ERROR:
        return 400
    if error_type == INSUFFICIENT_RESOURCES:
        return 429
    return 500


def classify(exc: BaseException) -> Tuple[str, int, str]:
    """(errorName, errorCode, errorType) for an engine exception —
    the coordinator's failure-info mapping (reference:
    util/Failures.java toFailure + ErrorCode)."""
    name = _name_for(exc)
    code, etype = error_info(name)
    return name, code, etype


def _name_for(exc: BaseException) -> str:
    explicit: Optional[str] = getattr(exc, "error_name", None)
    if explicit:
        return explicit
    cls = type(exc).__name__
    msg = str(exc)
    if cls == "ParseError" or msg.startswith("SYNTAX_ERROR"):
        return "SYNTAX_ERROR"
    if cls == "AccessDeniedError" or msg.startswith("Access Denied"):
        return "PERMISSION_DENIED"
    if cls in ("PlanningError", "FunctionResolutionError"):
        return "SYNTAX_ERROR"
    if cls == "NotImplementedError":
        return "NOT_SUPPORTED"
    low = msg.lower()
    if "does not exist" in low:
        if "table" in low or "view" in low:
            return "TABLE_NOT_FOUND"
        if "schema" in low:
            return "SCHEMA_NOT_FOUND"
        if "catalog" in low:
            return "CATALOG_NOT_FOUND"
        if "column" in low:
            return "COLUMN_NOT_FOUND"
        return "NOT_FOUND"
    if "already exists" in low:
        return "ALREADY_EXISTS"
    # governance errors (server/memory.py, server/resourcegroups.py):
    # BEFORE the "canceled" sniff — the killer's message says it
    # "canceled query X", which is a memory kill, not a user cancel —
    # and before the generic memory fallback
    if "cluster is out of memory" in low or "low-memory killer" in low:
        return "CLUSTER_OUT_OF_MEMORY"
    if "global memory limit" in low or "memory pool" in low:
        return "EXCEEDED_GLOBAL_MEMORY_LIMIT"
    if "maximum run time" in low or ("time limit" in low
                                     and "exceed" in low):
        return "EXCEEDED_TIME_LIMIT"
    if "canceled" in low:
        return "USER_CANCELED"
    if "division by zero" in low:
        return "DIVISION_BY_ZERO"
    if "memory" in low and ("exceed" in low or "limit" in low):
        return "EXCEEDED_LOCAL_MEMORY_LIMIT"
    if "queue" in low and "full" in low:
        return "QUERY_QUEUE_FULL"
    if cls == "QueryError":
        return "GENERIC_USER_ERROR"
    return "GENERIC_INTERNAL_ERROR"
