"""Analyzer + logical planner: AST -> typed PlanNode DAG.

Reference parity: this file fuses the roles of
- sql/analyzer/StatementAnalyzer.java (name/scope resolution, aggregation
  analysis) + ExpressionAnalyzer.java (type derivation, coercions),
- sql/planner/{LogicalPlanner,QueryPlanner,RelationPlanner,
  TranslationMap}.java (AST -> PlanNodes over unique symbols),
- sql/planner/SubqueryPlanner.java + the TransformCorrelated* /
  TransformUncorrelatedInPredicateSubqueryToSemiJoin iterative rules:
  subqueries are decorrelated AT PLAN TIME here (scalar-aggregate
  subqueries with equality correlation -> grouped aggregate + LEFT join;
  EXISTS -> [null-unaware] semi join with residual filter; uncorrelated
  IN -> null-aware semi join; uncorrelated scalar -> EnforceSingleRow +
  cross join).

The reference keeps Analysis as a side table; here scopes carry
(name, symbol, type) directly and expressions are translated straight to
the typed rex IR, so a separate Analysis object is unnecessary.
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field, replace as dc_replace
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .. import rex
from ..catalog import CatalogManager
from ..functions import (FunctionResolutionError, aggregate_result_type,
                         is_aggregate, is_window, scalar_result_type)
from ..plan.nodes import (Aggregate, AggregationNode, AssignUniqueIdNode,
                          EnforceSingleRowNode, FilterNode, JoinClause,
                          JoinNode, LimitNode, MarkDistinctNode, OffsetNode,
                          OutputNode, PlanNode, ProjectNode, SampleNode,
                          SemiJoinNode, SetOpNode, SortKey, SortNode,
                          TableScanNode, TopNNode, UnionNode, UnnestNode,
                          ValuesNode, WindowFunction, WindowNode)
from ..rex import (Call, CaseExpr, Cast, Const, InputRef, Lambda, RowExpr,
                   TRUE)
from ..session import Session
from ..sql import ast as A
from ..types import (BIGINT, BOOLEAN, DATE, DOUBLE, INTEGER, UNKNOWN,
                     VARCHAR, DecimalType, IntervalDayTime,
                     IntervalYearMonth, TimestampType, Type, VarcharType,
                     common_super_type, is_exact_numeric, is_integral,
                     is_numeric, is_string, parse_type)


class _NonConstValues(Exception):
    """Internal: a VALUES entry didn't constant-fold (triggers the
    UNION-ALL-of-SELECTs fallback in _plan_values)."""


class PlanningError(Exception):
    """SemanticException analog (error codes in Appendix A.8 taxonomy)."""


# --------------------------------------------------------------------------
# scopes
# --------------------------------------------------------------------------

@dataclass(frozen=True)
class Field:
    name: Optional[str]          # column name; None for anonymous exprs
    symbol: str                  # plan symbol
    type: Type
    qualifier: Optional[str] = None   # relation alias ('l', 'lineitem')

    def matches(self, parts: Tuple[str, ...]) -> bool:
        if self.name is None:
            return False
        if len(parts) == 1:
            return parts[0] == self.name
        if len(parts) == 2:
            return (self.qualifier is not None
                    and parts[0] == self.qualifier
                    and parts[1] == self.name)
        return False


@dataclass
class Scope:
    """sql/analyzer/Scope.java — visible fields + optional outer scope for
    correlated subqueries."""
    fields: List[Field]
    outer: Optional["Scope"] = None

    def resolve(self, parts: Tuple[str, ...]) -> Tuple[Field, bool]:
        """Returns (field, is_outer)."""
        lparts = tuple(p.lower() for p in parts)
        hits = [f for f in self.fields if f.matches(lparts)]
        if len(hits) > 1:
            raise PlanningError(f"Column '{'.'.join(parts)}' is ambiguous")
        if hits:
            return hits[0], False
        if self.outer is not None:
            f, _ = self.outer.resolve(parts)
            return f, True
        raise PlanningError(
            f"Column '{'.'.join(parts)}' cannot be resolved")

    def try_resolve(self, parts):
        try:
            return self.resolve(parts)
        except PlanningError:
            return None, False


@dataclass
class RelationPlan:
    root: PlanNode
    scope: Scope


# --------------------------------------------------------------------------
# planner
# --------------------------------------------------------------------------

class SymbolAllocator:
    def __init__(self):
        self._c = itertools.count()

    def new(self, hint: str) -> str:
        hint = "".join(ch if (ch.isalnum() or ch == "_") else "_"
                       for ch in (hint or "expr"))[:24].lower() or "expr"
        return f"{hint}${next(self._c)}"


class LogicalPlanner:
    def __init__(self, catalogs: CatalogManager, session: Session):
        self.catalogs = catalogs
        self.session = session
        self.symbols = SymbolAllocator()
        self._ctes: List[Dict[str, A.WithQuery]] = [{}]

    # ---- entry points ----------------------------------------------------
    def plan(self, stmt: A.Statement) -> OutputNode:
        if isinstance(stmt, A.QueryStatement):
            rp, names = self.plan_query(stmt.query)
            return OutputNode(rp.root, tuple(names),
                              tuple(f.symbol for f in rp.scope.fields))
        raise PlanningError(f"Cannot plan statement {type(stmt).__name__}")

    def plan_query(self, q: A.Query,
                   outer: Optional[Scope] = None
                   ) -> Tuple[RelationPlan, List[str]]:
        """Returns (plan, output column names)."""
        self._ctes.append({**self._ctes[-1],
                           **{w.name.lower(): w for w in q.with_queries}})
        try:
            rp, names = self._plan_body(q.body, outer)
            # outer-level ORDER BY / LIMIT / OFFSET (set-op queries)
            if q.order_by or q.limit is not None or q.offset:
                rp = self._order_limit(rp, names, q.order_by, q.limit,
                                       q.offset, outer)
            return rp, names
        finally:
            self._ctes.pop()

    # ---- query bodies ----------------------------------------------------
    def _plan_body(self, body: A.QueryBody, outer) -> Tuple[RelationPlan,
                                                            List[str]]:
        if isinstance(body, A.QuerySpecification):
            return self._plan_spec(body, outer)
        if isinstance(body, A.ValuesBody):
            return self._plan_values(body.rows), None or [
                f"_col{i}" for i in range(len(body.rows[0]))]
        if isinstance(body, A.SetOperation):
            return self._plan_setop(body, outer)
        raise PlanningError(f"unsupported query body {type(body).__name__}")

    def _plan_values(self, rows) -> RelationPlan:
        # evaluate constant expressions host-side; rows with
        # non-constant entries (map(...)/ARRAY[x]/scalar calls — the
        # reference allows arbitrary expressions in VALUES,
        # sql/planner/QueryPlanner.planValues) fall back to a UNION ALL
        # of single-row SELECTs
        try:
            return self._plan_values_const(rows)
        except _NonConstValues:
            # balanced UNION ALL tree (a left-deep chain would recurse
            # once per row and overflow on long VALUES lists)
            parts: List[A.QueryBody] = [
                A.QuerySpecification(select_items=tuple(
                    A.SelectItem(e, f"_col{i}")
                    for i, e in enumerate(row)))
                for row in rows]
            while len(parts) > 1:
                parts = [A.SetOperation("union", False, parts[i],
                                        parts[i + 1])
                         if i + 1 < len(parts) else parts[i]
                         for i in range(0, len(parts), 2)]
            rp, _ = self._plan_body(parts[0], None)
            return rp

    def _plan_values_const(self, rows) -> RelationPlan:
        n_cols = len(rows[0])
        values: List[List[object]] = []
        types: List[Type] = [UNKNOWN] * n_cols
        for row in rows:
            if len(row) != n_cols:
                raise PlanningError("VALUES rows must be the same length")
            vals = []
            for i, e in enumerate(row):
                ex = self._const_expr(e)
                t = common_super_type(types[i], ex.type)
                if t is None:
                    raise PlanningError(
                        f"VALUES column {i+1}: incompatible types "
                        f"{types[i]} and {ex.type}")
                types[i] = t
                vals.append(ex.value)
            values.append(vals)
        syms = [self.symbols.new(f"_col{i}") for i in range(n_cols)]
        node = ValuesNode(dict(zip(syms, types)),
                          tuple(tuple(r) for r in values))
        scope = Scope([Field(f"_col{i}", s, t) for i, (s, t) in
                       enumerate(zip(syms, types))])
        return RelationPlan(node, scope)

    def _const_expr(self, e: A.Expression) -> Const:
        ex = self._rewrite_expr(e, _ExprContext(self, Scope([]), None))
        folded = _const_fold(ex)
        if not isinstance(folded, Const):
            raise _NonConstValues("VALUES entries must be constant")
        return folded

    def _plan_setop(self, body: A.SetOperation, outer):
        lrp, lnames = self._plan_body(body.left, outer)
        rrp, rnames = self._plan_body(body.right, outer)
        lf, rf = lrp.scope.fields, rrp.scope.fields
        if len(lf) != len(rf):
            raise PlanningError(
                f"{body.op.upper()} sides have different column counts")
        types = []
        for a, b in zip(lf, rf):
            t = common_super_type(a.type, b.type)
            if t is None:
                raise PlanningError(
                    f"{body.op.upper()}: incompatible column types "
                    f"{a.type} / {b.type}")
            types.append(t)
        lrp = self._coerce_fields(lrp, types)
        rrp = self._coerce_fields(rrp, types)
        out_syms = [self.symbols.new(f.name or "col") for f in lf]
        schema = dict(zip(out_syms, types))
        lmap = {o: f.symbol for o, f in zip(out_syms, lrp.scope.fields)}
        rmap = {o: f.symbol for o, f in zip(out_syms, rrp.scope.fields)}
        if body.op == "union":
            node: PlanNode = UnionNode((lrp.root, rrp.root), schema,
                                       (lmap, rmap))
            if body.distinct:
                node = AggregationNode(node, tuple(out_syms), {})
        else:
            node = SetOpNode(body.op, body.distinct, lrp.root, rrp.root,
                             schema, lmap, rmap)
        scope = Scope([Field(f.name, s, t) for f, s, t in
                       zip(lf, out_syms, types)])
        return RelationPlan(node, scope), [f.name or f"_col{i}"
                                           for i, f in enumerate(lf)]

    def _coerce_fields(self, rp: RelationPlan,
                       types: List[Type]) -> RelationPlan:
        if all(f.type == t for f, t in zip(rp.scope.fields, types)):
            return rp
        assigns, fields = {}, []
        for f, t in zip(rp.scope.fields, types):
            e: RowExpr = InputRef(f.symbol, f.type)
            if f.type != t:
                e = Cast(e, t)
                sym = self.symbols.new(f.name or "cast")
            else:
                sym = f.symbol
            assigns[sym] = e
            fields.append(dc_replace(f, symbol=sym, type=t))
        return RelationPlan(ProjectNode(rp.root, assigns),
                            Scope(fields, rp.scope.outer))

    # ---- SELECT specification -------------------------------------------
    def _plan_spec(self, spec: A.QuerySpecification, outer):
        # FROM
        if spec.from_ is not None:
            rp = self._plan_relation(spec.from_, outer)
        else:
            sym = self.symbols.new("dual")
            rp = RelationPlan(
                ValuesNode({sym: BIGINT}, ((0,),)), Scope([]))
        rp.scope.outer = outer

        ctx = _ExprContext(self, rp.scope, rp.root)

        # WHERE
        if spec.where is not None:
            pred = ctx.rewrite(spec.where)
            _require_boolean(pred, "WHERE")
            ctx.root = FilterNode(ctx.root, pred)

        # aggregation analysis
        agg_calls = self._collect_aggregates(spec)
        grouped = bool(spec.group_by) or bool(agg_calls)

        select_items = self._expand_stars(spec.select_items, rp.scope)

        if grouped:
            post_ctx, group_syms = self._plan_aggregation(
                spec, agg_calls, ctx, select_items)
        else:
            post_ctx = ctx

        # window functions
        win_calls = [e for item in select_items
                     for e in A.walk_expressions(
                         item.expr, cross_subqueries=False)
                     if isinstance(e, A.FunctionCall) and e.window]
        if win_calls:
            post_ctx = self._plan_windows(post_ctx, win_calls)

        # SELECT projections
        out_syms: List[str] = []
        out_names: List[str] = []
        assigns: Dict[str, RowExpr] = {}
        for item in select_items:
            e = post_ctx.rewrite(item.expr)
            name = item.alias or _derive_name(item.expr)
            sym = self.symbols.new(name or "expr")
            assigns[sym] = e
            out_syms.append(sym)
            out_names.append((name or f"_col{len(out_names)}").lower())

        # HAVING
        if spec.having is not None:
            if not grouped:
                raise PlanningError("HAVING requires aggregation")
            h = post_ctx.rewrite(spec.having)
            _require_boolean(h, "HAVING")
            post_ctx.root = FilterNode(post_ctx.root, h)

        proj = ProjectNode(post_ctx.root, dict(assigns))
        out_fields = [Field((item.alias or _derive_name(item.expr)
                             or f"_col{i}").lower(), s, assigns[s].type)
                      for i, (item, s) in
                      enumerate(zip(select_items, out_syms))]
        result = RelationPlan(proj, Scope(out_fields, outer))

        # DISTINCT
        if spec.distinct:
            result = RelationPlan(
                AggregationNode(result.root, tuple(out_syms), {}),
                result.scope)

        # ORDER BY / LIMIT / OFFSET
        if spec.order_by or spec.limit is not None or spec.offset:
            result = self._order_limit(
                result, out_names, spec.order_by, spec.limit, spec.offset,
                outer, pre_ctx=post_ctx if not spec.distinct else None,
                pre_assigns=assigns if not spec.distinct else None)
        return result, out_names

    # ---- ORDER BY / LIMIT ------------------------------------------------
    def _order_limit(self, rp: RelationPlan, names: List[str], order_by,
                     limit, offset, outer, pre_ctx=None, pre_assigns=None):
        root = rp.root
        if order_by:
            keys: List[SortKey] = []
            extra: Dict[str, RowExpr] = {}
            out_fields = rp.scope.fields
            for si in order_by:
                sym = None
                e = si.expr
                # ordinal
                if isinstance(e, A.Literal) and isinstance(e.value, int) \
                        and e.type_name is None:
                    i = e.value
                    if not (1 <= i <= len(out_fields)):
                        raise PlanningError(
                            f"ORDER BY position {i} is out of range")
                    sym = out_fields[i - 1].symbol
                # select alias / output column
                elif isinstance(e, A.Identifier) and len(e.parts) == 1:
                    for f in out_fields:
                        if f.name == e.parts[0].lower():
                            sym = f.symbol
                            break
                if sym is None:
                    if pre_ctx is None:
                        raise PlanningError(
                            "ORDER BY expression must be an output column "
                            "for DISTINCT / set-operation queries")
                    ex = pre_ctx.rewrite(e)
                    sym = self.symbols.new("sortkey")
                    extra[sym] = ex
                asc = si.ascending
                nf = si.nulls_first if si.nulls_first is not None else False
                keys.append(SortKey(sym, asc, nf))
            if extra:
                # extend the final projection with sort keys, sort, then
                # project back down (reference: QueryPlanner sort channel
                # handling)
                assert isinstance(root, ProjectNode) and pre_assigns
                widened = dict(root.assignments)
                widened.update(extra)
                root = ProjectNode(root.source, widened)
            if limit is not None:
                root = TopNNode(root, limit + (offset or 0), tuple(keys))
            else:
                root = SortNode(root, tuple(keys))
            if extra:
                keep = {s: InputRef(s, e.type)
                        for s, e in (rp.root.assignments.items()
                                     if isinstance(rp.root, ProjectNode)
                                     else [])}
                root = ProjectNode(root, keep)
        if offset:
            root = OffsetNode(root, offset)
        if limit is not None and not order_by:
            root = LimitNode(root, limit)
        elif limit is not None and offset:
            root = LimitNode(root, limit)
        return RelationPlan(root, rp.scope)

    # ---- aggregation -----------------------------------------------------
    def _collect_aggregates(self, spec) -> List[A.FunctionCall]:
        out, seen = [], set()
        sources = [i.expr for i in spec.select_items]
        if spec.having is not None:
            sources.append(spec.having)
        for si in spec.order_by:
            sources.append(si.expr)
        for src in sources:
            for e in A.walk_expressions(src, cross_subqueries=False):
                if isinstance(e, A.FunctionCall) and not e.window \
                        and is_aggregate(e.name) and e not in seen:
                    # nested aggregates are illegal
                    for a in e.args:
                        for sub in A.walk_expressions(
                                a, cross_subqueries=False):
                            if isinstance(sub, A.FunctionCall) \
                                    and is_aggregate(sub.name):
                                raise PlanningError(
                                    "Cannot nest aggregate functions")
                    seen.add(e)
                    out.append(e)
        return out

    def _plan_aggregation(self, spec, agg_calls, ctx, select_items):
        # 1. group keys planned against the pre-agg scope
        group_exprs: List[A.Expression] = []
        grouping_sets: List[Tuple[int, ...]] = [()]
        if spec.group_by:
            group_exprs = list(spec.group_by.exprs)
            grouping_sets = list(spec.group_by.sets)
        # resolve ordinals / aliases in GROUP BY (SQL allows ordinals)
        resolved_groups: List[A.Expression] = []
        for g in group_exprs:
            if isinstance(g, A.Literal) and isinstance(g.value, int) \
                    and g.type_name is None:
                i = g.value
                if not (1 <= i <= len(select_items)):
                    raise PlanningError(
                        f"GROUP BY position {i} is out of range")
                resolved_groups.append(select_items[i - 1].expr)
            else:
                resolved_groups.append(g)

        pre_assigns: Dict[str, RowExpr] = {}
        key_syms: List[str] = []
        key_map: Dict[A.Expression, str] = {}
        for g in resolved_groups:
            e = ctx.rewrite(g)
            if isinstance(e, InputRef):
                sym = e.name
            else:
                sym = self.symbols.new("groupkey")
                pre_assigns[sym] = e
            key_syms.append(sym)
            key_map[g] = sym

        # 2. aggregate arguments pre-projected
        aggregates: Dict[str, Aggregate] = {}
        agg_map: Dict[A.Expression, Tuple[str, Type]] = {}
        for call in agg_calls:
            args: List[RowExpr] = [ctx.rewrite(a) for a in call.args
                                   if not isinstance(a, A.Star)]
            star = any(isinstance(a, A.Star) for a in call.args)
            mask_sym = None
            if call.filter is not None:
                m = ctx.rewrite(call.filter)
                _require_boolean(m, "FILTER")
                mask_sym = self.symbols.new("mask")
                pre_assigns[mask_sym] = m
            arg2_sym = None
            param = None
            if call.name == "count" and (star or not args):
                kind, arg_sym, rtype = "count_star", None, BIGINT
            elif call.name == "numeric_histogram":
                # numeric_histogram(buckets, value[, weight]): buckets
                # is a constant; value and weight are lanes
                kind = call.name
                if len(args) < 2 or len(args) > 3 \
                        or not isinstance(args[0], Const) \
                        or args[0].value is None:
                    raise PlanningError(
                        "numeric_histogram(buckets, value[, weight]): "
                        "buckets must be a constant")
                param = float(args[0].value)
                if param < 2:
                    raise PlanningError(
                        "numeric_histogram: buckets must be >= 2")
                from ..types import MapType
                rtype = MapType(DOUBLE, DOUBLE)
                a1 = args[1]
                if isinstance(a1, InputRef):
                    arg_sym = a1.name
                else:
                    arg_sym = self.symbols.new(f"{kind}_arg")
                    pre_assigns[arg_sym] = a1
                if len(args) == 3:
                    a2 = args[2]
                    if isinstance(a2, InputRef):
                        arg2_sym = a2.name
                    else:
                        arg2_sym = self.symbols.new(f"{kind}_arg2")
                        pre_assigns[arg2_sym] = a2
            elif call.name == "approx_most_frequent":
                # approx_most_frequent(buckets, value[, capacity]):
                # buckets/capacity are constants, value is the lane
                kind = call.name
                if len(args) < 2 or not isinstance(args[0], Const) \
                        or args[0].value is None:
                    raise PlanningError(
                        "approx_most_frequent(buckets, value): buckets "
                        "must be a constant")
                param = float(args[0].value)
                if param < 1:
                    raise PlanningError(
                        "approx_most_frequent: buckets must be a "
                        "positive integer")
                a1 = args[1]
                from ..types import MapType
                rtype = MapType(a1.type, BIGINT)
                if isinstance(a1, InputRef):
                    arg_sym = a1.name
                else:
                    arg_sym = self.symbols.new(f"{kind}_arg")
                    pre_assigns[arg_sym] = a1
            else:
                kind = call.name
                rtype = aggregate_result_type(kind,
                                              [a.type for a in args])
                a0 = args[0]
                if isinstance(a0, InputRef):
                    arg_sym = a0.name
                else:
                    arg_sym = self.symbols.new(f"{kind}_arg")
                    pre_assigns[arg_sym] = a0
                if len(args) > 1:
                    if kind == "approx_percentile":
                        # percentage must be constant (the reference's
                        # ApproximateDoublePercentileAggregations also
                        # requires a per-query-constant percentile)
                        a1 = args[1]
                        if not isinstance(a1, Const) or a1.value is None:
                            raise PlanningError(
                                "approx_percentile: percentage must be "
                                "a constant")
                        param = float(a1.value)
                    elif kind in ("approx_set", "approx_distinct"):
                        a1 = args[1]
                        if not isinstance(a1, Const) or a1.value is None:
                            raise PlanningError(
                                f"{kind}: max standard error must be a "
                                "constant")
                        param = float(a1.value)
                        if kind == "approx_set":
                            # validate eagerly (plan-time error beats a
                            # kernel-trace error) and re-type so the
                            # declared bucket bits match the runtime
                            # sketch
                            from ..ops.hll import bucket_bits_for_error
                            from ..types import HyperLogLogType
                            try:
                                rtype = HyperLogLogType(
                                    bucket_bits_for_error(param))
                            except ValueError as ex:
                                raise PlanningError(str(ex))
                    elif kind in ("min_by", "max_by", "corr",
                                  "covar_samp", "covar_pop",
                                  "regr_slope", "regr_intercept",
                                  "map_agg", "multimap_agg",
                                  "tdigest_agg", "qdigest_agg"):
                        a1 = args[1]
                        if isinstance(a1, InputRef):
                            arg2_sym = a1.name
                        else:
                            arg2_sym = self.symbols.new(f"{kind}_arg2")
                            pre_assigns[arg2_sym] = a1
                    else:
                        raise PlanningError(
                            f"{kind}: multi-argument aggregates not yet "
                            "supported")
                    if len(args) > 2:
                        if kind == "qdigest_agg" and len(args) == 3 \
                                and isinstance(args[2], Const) \
                                and args[2].value is not None:
                            # qdigest_agg(x, w, accuracy)
                            param = float(args[2].value)
                        else:
                            raise PlanningError(
                                f"{kind}: too many arguments")
            out_sym = self.symbols.new(call.name)
            aggregates[out_sym] = Aggregate(kind, arg_sym, rtype,
                                            call.distinct, mask_sym,
                                            arg2_sym, param)
            agg_map[call] = (out_sym, rtype)

        root = ctx.root
        if pre_assigns:
            src_schema = root.output_schema()
            full = {s: InputRef(s, t) for s, t in src_schema.items()}
            full.update(pre_assigns)
            root = ProjectNode(root, full)

        group_key_tuple = tuple(dict.fromkeys(key_syms))
        id_sym = None
        if len(grouping_sets) > 1:
            # GROUPING SETS / ROLLUP / CUBE: replicate rows per set with
            # a set-id column (plan/GroupIdNode.java). Aggregate
            # arguments/masks that coincide with grouping keys must read
            # a COPY of the column — GroupId nulls the key lanes in
            # subtotal copies but the aggregates see the original values
            # (the reference keeps separate argument mappings for this).
            from ..plan.nodes import GroupIdNode
            arg_copies: Dict[str, str] = {}
            new_aggs = {}
            for out_sym, a in aggregates.items():
                upd = {}
                for field_name in ("argument", "mask"):
                    s = getattr(a, field_name)
                    if s is not None and s in group_key_tuple:
                        if s not in arg_copies:
                            arg_copies[s] = self.symbols.new(s + "_arg")
                        upd[field_name] = arg_copies[s]
                new_aggs[out_sym] = dc_replace(a, **upd) if upd else a
            aggregates = new_aggs
            if arg_copies:
                schema = root.output_schema()
                full = {s: InputRef(s, t) for s, t in schema.items()}
                for orig, copy in arg_copies.items():
                    full[copy] = InputRef(orig, schema[orig])
                root = ProjectNode(root, full)
            id_sym = self.symbols.new("groupid")
            # grouping sets index into group_exprs; map to symbols
            expr_syms = [key_map[g] for g in resolved_groups]
            set_syms = tuple(
                tuple(dict.fromkeys(expr_syms[i] for i in s))
                for s in grouping_sets)
            root = GroupIdNode(root, set_syms, group_key_tuple, id_sym)
            group_key_tuple = group_key_tuple + (id_sym,)

        agg_node = AggregationNode(root, group_key_tuple, aggregates,
                                   group_id_symbol=id_sym)
        agg_node = self._rewrite_distinct_aggregation(agg_node)

        post = _ExprContext(self, ctx.scope, agg_node,
                            agg_map=agg_map, key_map=key_map,
                            group_symbols=set(agg_node.group_keys))
        if id_sym is not None:
            post.grouping_info = (id_sym, set_syms)
        return post, key_syms

    def _rewrite_distinct_aggregation(self, node: AggregationNode):
        """SingleDistinctAggregationToGroupBy (iterative/rule/): when every
        distinct aggregate shares one argument and there are no masks,
        dedupe via an inner group-by. count(DISTINCT x) needs no rewrite —
        the executor lowers it to the exact count_distinct kernel
        (ops/groupby.py), so it mixes freely with plain aggregates."""
        distinct = {s: a for s, a in node.aggregates.items()
                    if a.distinct}
        if not distinct:
            return node
        if all(a.kind == "count" for a in distinct.values()):
            # every distinct aggregate is count(DISTINCT) -> executor
            # handles them natively, mixing freely with plain aggs
            return node
        args = {a.argument for a in distinct.values()}
        plain = {s: a for s, a in node.aggregates.items()
                 if s not in distinct}
        if len(args) != 1 or plain or any(
                a.mask for a in distinct.values()):
            raise PlanningError(
                "mixed / multi-column DISTINCT aggregates not yet "
                "supported")
        arg = next(iter(args))
        inner_keys = tuple(dict.fromkeys(node.group_keys + ((arg,)
                           if arg else ())))
        inner = AggregationNode(node.source, inner_keys, {})
        outer_aggs = {s: dc_replace(a, distinct=False)
                      for s, a in distinct.items()}
        return AggregationNode(inner, node.group_keys, outer_aggs)

    # ---- windows ---------------------------------------------------------
    def _plan_windows(self, ctx: "_ExprContext", calls):
        win_map: Dict[A.Expression, Tuple[str, Type]] = {}
        root = ctx.root
        for call in calls:
            spec = call.window
            pre: Dict[str, RowExpr] = {}

            def to_sym(aexpr, label="winexpr") -> str:
                return as_sym(ctx.rewrite(aexpr), label)

            def as_sym(e, label="winexpr") -> str:
                if isinstance(e, InputRef):
                    return e.name
                s = self.symbols.new(label)
                pre[s] = e
                return s

            part = tuple(to_sym(p) for p in spec.partition_by)
            order = tuple(SortKey(to_sym(si.expr), si.ascending,
                                  si.nulls_first or False)
                          for si in spec.order_by)
            args = [a for a in call.args if not isinstance(a, A.Star)]
            arg_sym = None
            atype: Optional[Type] = None
            off_sym = None
            def_sym = None
            if call.name == "ntile":
                # ntile(n): the single argument is the bucket count,
                # not a value lane (operator/window/NTileFunction.java)
                if args:
                    off_sym = to_sym(args[0], "ntile_n")
            elif args:
                e0 = ctx.rewrite(args[0])
                atype = e0.type
                arg_sym = as_sym(e0, "winarg")
                if call.name in ("lag", "lead"):
                    # lag(x [, offset [, default]])
                    if len(args) > 1:
                        off_sym = to_sym(args[1], "winoff")
                    if len(args) > 2:
                        def_sym = to_sym(args[2], "windef")
                elif call.name == "nth_value":
                    # nth_value(x, n): second argument is the position
                    if len(args) < 2:
                        raise PlanningError(
                            "nth_value requires a position argument")
                    n_ex = ctx.rewrite(args[1])
                    if isinstance(n_ex, Const) and \
                            n_ex.value is not None and \
                            int(n_ex.value) <= 0:
                        raise PlanningError(
                            "Argument of nth_value must be a positive "
                            "integer")
                    off_sym = as_sym(n_ex, "winoff")
            if is_window(call.name):
                rtype = {"row_number": BIGINT, "rank": BIGINT,
                         "dense_rank": BIGINT, "ntile": BIGINT,
                         "percent_rank": DOUBLE, "cume_dist": DOUBLE,
                         }.get(call.name, atype or BIGINT)
            elif is_aggregate(call.name):
                rtype = (BIGINT if call.name == "count" and arg_sym is None
                         else aggregate_result_type(
                             call.name, [atype] if atype else []))
            else:
                raise PlanningError(
                    f"'{call.name}' is not a window function")
            if pre:
                schema = root.output_schema()
                full = {s: InputRef(s, t) for s, t in schema.items()}
                full.update(pre)
                root = ProjectNode(root, full)
            frame = spec.frame

            def frame_const(value_expr, what):
                if value_expr is None:
                    return None
                v = self._const_expr(value_expr).value
                if v is None or int(v) < 0:
                    raise PlanningError(
                        f"window frame {what} offset must be a "
                        "non-negative constant")
                return int(v)

            out_sym = self.symbols.new(call.name)
            fn = WindowFunction(
                call.name, arg_sym, rtype,
                frame_unit=frame.unit if frame else "range",
                frame_start=frame.start_type if frame
                else "unbounded_preceding",
                frame_end=frame.end_type if frame else "current",
                offset=off_sym, default=def_sym,
                frame_start_value=frame_const(
                    frame.start_value if frame else None, "start"),
                frame_end_value=frame_const(
                    frame.end_value if frame else None, "end"))
            root = WindowNode(root, part, order, {out_sym: fn})
            win_map[call] = (out_sym, rtype)
        out = _ExprContext(self, ctx.scope, root, agg_map=ctx.agg_map,
                           key_map=ctx.key_map,
                           group_symbols=ctx.group_symbols)
        out.win_map = win_map
        if hasattr(ctx, "grouping_info"):
            # grouping() must keep decoding the set index after window
            # planning replaces the context (silently-0 otherwise)
            out.grouping_info = ctx.grouping_info
        return out

    # ---- relations -------------------------------------------------------
    def _plan_relation(self, rel: A.Relation, outer) -> RelationPlan:
        if isinstance(rel, A.Table):
            return self._plan_table(rel, outer)
        if isinstance(rel, A.AliasedRelation):
            inner = self._plan_relation(rel.relation, outer)
            alias = rel.alias.lower()
            fields = []
            for i, f in enumerate(inner.scope.fields):
                name = (rel.column_names[i].lower()
                        if i < len(rel.column_names) else f.name)
                fields.append(Field(name, f.symbol, f.type, alias))
            return RelationPlan(inner.root, Scope(fields, outer))
        if isinstance(rel, A.SubqueryRelation):
            rp, _ = self.plan_query(rel.query, outer)
            return rp
        if isinstance(rel, A.ValuesRelation):
            return self._plan_values(rel.rows)
        if isinstance(rel, A.Unnest):
            return self._plan_unnest(rel, outer, None)
        if isinstance(rel, A.Join):
            return self._plan_join(rel, outer)
        if isinstance(rel, A.TableSample):
            inner = self._plan_relation(rel.relation, outer)
            ratio = self._const_expr(rel.percentage).value
            return RelationPlan(
                SampleNode(inner.root, rel.method, float(ratio) / 100.0),
                inner.scope)
        raise PlanningError(
            f"unsupported relation {type(rel).__name__}")

    def _plan_table(self, rel: A.Table, outer) -> RelationPlan:
        parts = tuple(p.lower() for p in rel.parts)
        # CTE?
        if len(parts) == 1 and parts[0] in self._ctes[-1]:
            w = self._ctes[-1][parts[0]]
            rp, names = self.plan_query(w.query)
            fields = []
            for i, f in enumerate(rp.scope.fields):
                name = (w.column_names[i].lower()
                        if i < len(w.column_names) else f.name)
                fields.append(Field(name, f.symbol, f.type, parts[0]))
            return RelationPlan(rp.root, Scope(fields, outer))
        catalog, schema, table = self._qualify(parts)
        if schema == "information_schema":
            return self._plan_information_schema(catalog, table, outer)
        view = self.catalogs.get_view(catalog, schema, table)
        if view is not None:
            # view expansion: plan the stored definition in place
            # (reference: StatementAnalyzer visitTable view branch,
            # with the analyzer's recursive-view detection)
            key = (catalog, schema, table)
            stack = getattr(self, "_view_stack", None)
            if stack is None:
                stack = self._view_stack = []
            if key in stack:
                raise PlanningError(
                    "View is recursive: " + ".".join(key))
            stack.append(key)
            try:
                rp, names = self.plan_query(view.query)
            finally:
                stack.pop()
            fields = [Field(f.name, f.symbol, f.type, table)
                      for f in rp.scope.fields]
            return RelationPlan(rp.root, Scope(fields, outer))
        ac = self.catalogs.access_control
        if ac is not None:
            from ..security import AccessDeniedError
            try:
                ac.check_can_select(self.session.user, catalog, schema,
                                    table)
            except AccessDeniedError as e:
                raise PlanningError(str(e)) from e
        handle, meta = self.catalogs.resolve_table(catalog, schema, table)
        assignments, schema_map, fields = {}, {}, []
        for cm in meta.columns:
            sym = self.symbols.new(cm.name)
            assignments[sym] = cm.name
            schema_map[sym] = cm.type
            fields.append(Field(cm.name.lower(), sym, cm.type,
                                table.lower()))
        return RelationPlan(TableScanNode(handle, assignments, schema_map),
                            Scope(fields, outer))

    def _plan_information_schema(self, catalog: str, table: str,
                                 outer) -> RelationPlan:
        """information_schema synthesized from connector metadata at plan
        time (reference: connector/informationschema/ — a virtual
        connector per catalog)."""
        conn = self.catalogs.connector(catalog)
        if table == "schemata":
            cols = [("catalog_name", VARCHAR), ("schema_name", VARCHAR)]
            rows = [(catalog, s) for s in conn.list_schemas()]
        elif table == "tables":
            cols = [("table_catalog", VARCHAR), ("table_schema", VARCHAR),
                    ("table_name", VARCHAR), ("table_type", VARCHAR)]
            rows = [(catalog, s, t, "BASE TABLE")
                    for s in conn.list_schemas()
                    for t in conn.list_tables(s)]
        elif table == "columns":
            cols = [("table_catalog", VARCHAR), ("table_schema", VARCHAR),
                    ("table_name", VARCHAR), ("column_name", VARCHAR),
                    ("ordinal_position", BIGINT),
                    ("column_default", VARCHAR),
                    ("is_nullable", VARCHAR), ("data_type", VARCHAR)]
            rows = []
            for s in conn.list_schemas():
                for t in conn.list_tables(s):
                    meta = conn.get_table_metadata(s, t)
                    for i, cm in enumerate(meta.columns):
                        rows.append((catalog, s, t, cm.name, i + 1,
                                     None, "YES", cm.type.name))
        elif table == "views":
            cols = [("table_catalog", VARCHAR), ("table_schema", VARCHAR),
                    ("table_name", VARCHAR), ("view_definition", VARCHAR)]
            rows = []
            for s in conn.list_schemas():
                for v in self.catalogs.list_views(catalog, s):
                    vd = self.catalogs.get_view(catalog, s, v)
                    rows.append((catalog, s, v,
                                 vd.sql if vd is not None else None))
        else:
            raise PlanningError(
                f"Table '{catalog}.information_schema.{table}' does not "
                "exist")
        syms = [self.symbols.new(n) for n, _ in cols]
        schema_map = {sym: ty for sym, (_, ty) in zip(syms, cols)}
        node = ValuesNode(schema_map, tuple(rows))
        scope = Scope([Field(n, sym, ty, table)
                       for sym, (n, ty) in zip(syms, cols)], outer)
        return RelationPlan(node, scope)

    def _qualify(self, parts: Tuple[str, ...]):
        if len(parts) == 3:
            return parts
        if len(parts) == 2:
            if self.session.catalog is None:
                raise PlanningError("Catalog must be specified")
            return (self.session.catalog,) + parts
        if self.session.catalog is None or self.session.schema is None:
            raise PlanningError(
                "Schema must be specified when session schema is not set")
        return (self.session.catalog, self.session.schema, parts[0])

    def _plan_unnest(self, rel: "A.Unnest", outer, lateral,
                     alias: Optional[str] = None,
                     colnames: Tuple[str, ...] = ()) -> RelationPlan:
        """FROM UNNEST(arr) [WITH ORDINALITY], standalone or lateral
        (CROSS JOIN UNNEST referencing earlier FROM items). Reference:
        RelationPlanner.visitUnnest + operator/unnest/UnnestOperator."""
        from ..types import ArrayType
        if lateral is None:
            one = self.symbols.new("unnest_src")
            base_root: PlanNode = ValuesNode({one: BIGINT}, ((0,),))
            base_scope = Scope([], outer)
        else:
            base_root, base_scope = lateral.root, lateral.scope
        replicate = tuple(base_root.output_schema())
        ctx = _ExprContext(self, base_scope, base_root)
        pre: Dict[str, RowExpr] = {}
        unnest_map: Dict[str, str] = {}
        out_fields: List[Field] = []
        i = 0
        for ex in rel.exprs:
            rx = ctx.rewrite(ex)
            if not isinstance(rx.type, ArrayType):
                raise PlanningError(
                    f"UNNEST argument must be an array (got {rx.type})")
            if isinstance(rx, InputRef):
                sym = rx.name
            else:
                sym = self.symbols.new("unnest_arg")
                pre[sym] = rx
            osym = self.symbols.new("unnest")
            unnest_map[osym] = sym
            name = colnames[i].lower() if i < len(colnames) \
                else f"col{i + 1}"
            out_fields.append(Field(name, osym, rx.type.element, alias))
            i += 1
        ord_sym = None
        if rel.with_ordinality:
            ord_sym = self.symbols.new("ordinality")
            name = colnames[i].lower() if i < len(colnames) \
                else "ordinality"
            out_fields.append(Field(name, ord_sym, BIGINT, alias))
        root = base_root
        if pre:
            schema = root.output_schema()
            full = {s: InputRef(s, t) for s, t in schema.items()}
            full.update(pre)
            root = ProjectNode(root, full)
        node = UnnestNode(root, replicate, unnest_map, ord_sym)
        base_fields = list(base_scope.fields) if lateral else []
        return RelationPlan(node, Scope(base_fields + out_fields, outer))

    def _plan_join(self, rel: A.Join, outer) -> RelationPlan:
        # lateral UNNEST: the right side references the left's columns
        un = rel.right
        un_alias, un_cols = None, ()
        if isinstance(un, A.AliasedRelation) and \
                isinstance(un.relation, A.Unnest):
            un_alias = un.alias.lower()
            un_cols = tuple(un.column_names)
            un = un.relation
        if isinstance(un, A.Unnest):
            if rel.join_type != "cross" and rel.on is not None:
                raise PlanningError(
                    "JOIN UNNEST supports only CROSS JOIN")
            left0 = self._plan_relation(rel.left, outer)
            return self._plan_unnest(un, outer, left0, un_alias, un_cols)
        left = self._plan_relation(rel.left, outer)
        right = self._plan_relation(rel.right, outer)
        combined = Scope(left.scope.fields + right.scope.fields, outer)

        if rel.join_type == "cross" and rel.on is None and not rel.using:
            return RelationPlan(
                JoinNode(left.root, right.root, "cross"), combined)

        if rel.using:
            conj = []
            for name in rel.using:
                lf, _ = Scope(left.scope.fields).resolve((name,))
                rf, _ = Scope(right.scope.fields).resolve((name,))
                conj.append(Call("=", (
                    InputRef(lf.symbol, lf.type),
                    InputRef(rf.symbol, rf.type)), BOOLEAN))
            on_expr = rex.and_all(conj)
        else:
            ctx = _ExprContext(self, combined, None)
            on_expr = ctx.rewrite(rel.on)
            _require_boolean(on_expr, "JOIN ON")

        lsyms = {f.symbol for f in left.scope.fields}
        rsyms = {f.symbol for f in right.scope.fields}
        criteria, residual = _extract_equi_criteria(on_expr, lsyms, rsyms)

        # non-equi comparisons referencing both sides stay as join filter;
        # side-local conjuncts sink only to the INNER side of the join —
        # an ON conjunct over the outer side's columns disqualifies
        # matches but must never drop outer rows (reference:
        # optimizations/PredicatePushDown.java outer-join handling)
        push_left, push_right, keep = [], [], []
        for c in residual:
            refs = rex.input_names(c)
            if refs <= lsyms and rel.join_type in ("inner", "right"):
                push_left.append(c)
            elif refs <= rsyms and rel.join_type in ("inner", "left"):
                push_right.append(c)
            else:
                keep.append(c)
        lroot = (FilterNode(left.root, rex.and_all(push_left))
                 if push_left else left.root)
        rroot = (FilterNode(right.root, rex.and_all(push_right))
                 if push_right else right.root)

        # criteria argument symbols may be expressions — pre-project
        lassign, rassign = {}, {}
        clauses = []
        for le, re_ in criteria:
            ls = self._as_symbol(le, lassign)
            rs = self._as_symbol(re_, rassign)
            clauses.append(JoinClause(ls, rs))
        if lassign:
            schema = lroot.output_schema()
            full = {s: InputRef(s, t) for s, t in schema.items()}
            full.update(lassign)
            lroot = ProjectNode(lroot, full)
        if rassign:
            schema = rroot.output_schema()
            full = {s: InputRef(s, t) for s, t in schema.items()}
            full.update(rassign)
            rroot = ProjectNode(rroot, full)

        jt = rel.join_type if rel.join_type != "cross" else "inner"
        if not clauses and jt == "inner":
            node: PlanNode = JoinNode(lroot, rroot, "cross")
            if keep:
                node = FilterNode(node, rex.and_all(keep))
        else:
            node = JoinNode(lroot, rroot, jt, tuple(clauses),
                            rex.and_all(keep) if keep else None)
        return RelationPlan(node, combined)

    def _as_symbol(self, e: RowExpr, assigns: Dict[str, RowExpr]) -> str:
        if isinstance(e, InputRef):
            return e.name
        sym = self.symbols.new("joinkey")
        assigns[sym] = e
        return sym

    # ---- subqueries (SubqueryPlanner + decorrelation rules) -------------
    def plan_scalar_subquery(self, ctx: "_ExprContext",
                             q: A.Query) -> RowExpr:
        sub, _ = self.plan_query(q, outer=ctx.scope)
        if len(sub.scope.fields) != 1:
            raise PlanningError(
                "Scalar subquery must return exactly one column")
        out_f = sub.scope.fields[0]
        corr = _correlated_symbols(sub.root, _all_symbols(ctx.root))
        if not corr:
            single = EnforceSingleRowNode(sub.root)
            ctx.root = JoinNode(ctx.root, single, "cross")
            return InputRef(out_f.symbol, out_f.type)
        # correlated: decorrelate scalar-aggregate pattern
        new_root, pairs = _decorrelate_scalar_agg(
            sub.root, corr, self.symbols)
        criteria = tuple(JoinClause(o, i) for o, i in pairs)
        ctx.root = JoinNode(ctx.root, new_root, "left", criteria)
        return InputRef(out_f.symbol, out_f.type)

    def plan_in_subquery(self, ctx: "_ExprContext", operand: RowExpr,
                         q: A.Query, negated: bool) -> RowExpr:
        sub, _ = self.plan_query(q, outer=ctx.scope)
        if len(sub.scope.fields) != 1:
            raise PlanningError(
                "IN subquery must return exactly one column")
        corr = _correlated_symbols(sub.root, _all_symbols(ctx.root))
        if corr:
            if negated:
                # the null-unaware rewrite below would turn NULL into
                # FALSE, which NOT inverts into spurious TRUE rows
                raise PlanningError(
                    "correlated NOT IN subqueries not supported")
            # correlated IN -> EXISTS-style semi join on the correlation
            # pairs plus (operand = subquery output). Null-unaware: where
            # full IN semantics would yield NULL this yields FALSE —
            # output-equivalent for a positive IN in WHERE
            # (TransformCorrelatedInPredicateToJoin's non-null-aware
            # branch in the reference).
            f = sub.scope.fields[0]
            t = common_super_type(operand.type, f.type)
            if t is None:
                raise PlanningError(
                    f"IN: incompatible types {operand.type} / {f.type}")
            new_root, pairs, residual = _decorrelate_exists(
                sub.root, corr, self.symbols)
            schema = new_root.output_schema()
            filt_sym = f.symbol
            if f.type != t:
                filt_sym = self.symbols.new("inkey")
                assigns = {s: InputRef(s, ty)
                           for s, ty in schema.items()}
                assigns[filt_sym] = Cast(InputRef(f.symbol, f.type), t)
                new_root = ProjectNode(new_root, assigns)
            src_sym = self._attach_symbol(ctx, _maybe_cast(operand, t))
            src_keys = (src_sym,) + tuple(o for o, _ in pairs)
            filt_keys = (filt_sym,) + tuple(i for _, i in pairs)
            mark = self.symbols.new("insubquery")
            ctx.root = SemiJoinMultiNode(
                ctx.root, new_root, src_keys, filt_keys, residual, mark,
                null_aware=False)
            e2: RowExpr = InputRef(mark, BOOLEAN)
            return Call("not", (e2,), BOOLEAN) if negated else e2
        f = sub.scope.fields[0]
        t = common_super_type(operand.type, f.type)
        if t is None:
            raise PlanningError(
                f"IN: incompatible types {operand.type} / {f.type}")
        src_sym = self._attach_symbol(ctx, _maybe_cast(operand, t))
        filt_root = sub.root
        if f.type != t:
            filt_sym = self.symbols.new("inkey")
            filt_root = ProjectNode(
                filt_root,
                {filt_sym: Cast(InputRef(f.symbol, f.type), t)})
        else:
            filt_sym = f.symbol
        mark = self.symbols.new("insubquery")
        ctx.root = SemiJoinNode(ctx.root, filt_root, src_sym, filt_sym,
                                mark)
        e: RowExpr = InputRef(mark, BOOLEAN)
        return Call("not", (e,), BOOLEAN) if negated else e

    def plan_exists(self, ctx: "_ExprContext", q: A.Query,
                    negated: bool) -> RowExpr:
        sub, _ = self.plan_query(q, outer=ctx.scope)
        corr = _correlated_symbols(sub.root, _all_symbols(ctx.root))
        mark = self.symbols.new("exists")
        if not corr:
            # EXISTS (uncorrelated) -> cross join against count(*)>0
            agg_sym = self.symbols.new("cnt")
            agg = AggregationNode(
                sub.root, (),
                {agg_sym: Aggregate("count_star", None, BIGINT)})
            flag = ProjectNode(agg, {mark: Call(
                ">", (InputRef(agg_sym, BIGINT), Const(0, BIGINT)),
                BOOLEAN)})
            ctx.root = JoinNode(ctx.root, flag, "cross")
        else:
            new_root, pairs, residual = _decorrelate_exists(
                sub.root, corr, self.symbols)
            src_keys, filt_keys = [], []
            schema = new_root.output_schema()
            for o, i in pairs:
                src_keys.append(o)
                filt_keys.append(i)
            ctx.root = SemiJoinMultiNode(
                ctx.root, new_root, tuple(src_keys), tuple(filt_keys),
                residual, mark, null_aware=False)
        e: RowExpr = InputRef(mark, BOOLEAN)
        return Call("not", (e,), BOOLEAN) if negated else e

    def plan_quantified(self, ctx: "_ExprContext",
                        e: A.QuantifiedComparison) -> RowExpr:
        """x <op> ALL/ANY (subquery) — rewritten over (min/max, count,
        count-non-null) of the subquery with full three-valued logic
        (reference rules: QuantifiedComparison -> aggregation rewrite in
        TransformQuantifiedComparisonApplyToCorrelatedJoin)."""
        op = "<>" if e.op == "!=" else e.op
        quant = e.quantifier.lower()
        is_all = quant == "all"
        if op == "=" and not is_all:
            return self.plan_in_subquery(
                ctx, self._rewrite_expr(e.operand, ctx), e.query, False)
        if op == "<>" and is_all:
            return self.plan_in_subquery(
                ctx, self._rewrite_expr(e.operand, ctx), e.query, True)
        if op in ("=", "<>"):
            raise PlanningError(f"{op} {quant.upper()} not supported")
        sub, _ = self.plan_query(e.query, outer=ctx.scope)
        if len(sub.scope.fields) != 1:
            raise PlanningError(
                "quantified subquery must return exactly one column")
        if _correlated_symbols(sub.root, _all_symbols(ctx.root)):
            raise PlanningError(
                "correlated quantified subqueries not supported")
        operand = self._rewrite_expr(e.operand, ctx)
        f = sub.scope.fields[0]
        t = common_super_type(operand.type, f.type)
        if t is None:
            raise PlanningError(
                f"{op} {quant}: incompatible types "
                f"{operand.type} / {f.type}")
        sub_root = sub.root
        arg_sym = f.symbol
        if f.type != t:
            arg_sym = self.symbols.new("qarg")
            sub_root = ProjectNode(
                sub_root, {arg_sym: Cast(InputRef(f.symbol, f.type), t)})
        # ALL with >/>= bounds against max; ANY against min (and
        # symmetrically for </<=)
        want_max = (op in (">", ">=")) == is_all
        b_sym = self.symbols.new("bound")
        n_sym = self.symbols.new("cnt")
        nn_sym = self.symbols.new("cnt_nonnull")
        agg = AggregationNode(sub_root, (), {
            b_sym: Aggregate("max" if want_max else "min", arg_sym, t),
            n_sym: Aggregate("count_star", None, BIGINT),
            nn_sym: Aggregate("count", arg_sym, BIGINT)})
        ctx.root = JoinNode(ctx.root, agg, "cross")
        x = _maybe_cast(operand, t)
        cmp = Call(op, (x, InputRef(b_sym, t)), BOOLEAN)
        empty = Call("=", (InputRef(n_sym, BIGINT), Const(0, BIGINT)),
                     BOOLEAN)
        has_null = Call("<", (InputRef(nn_sym, BIGINT),
                              InputRef(n_sym, BIGINT)), BOOLEAN)
        if is_all:
            # TRUE on empty; FALSE when the comparison fails against the
            # bound; NULL when it holds but the set contains NULLs
            return CaseExpr((
                (empty, rex.TRUE),
                (Call("not", (cmp,), BOOLEAN), rex.FALSE),
                (has_null, Const(None, BOOLEAN))),
                cmp, BOOLEAN)
        return CaseExpr((
            (empty, rex.FALSE),
            (cmp, rex.TRUE),
            (has_null, Const(None, BOOLEAN))),
            cmp, BOOLEAN)

    def _attach_symbol(self, ctx: "_ExprContext", e: RowExpr) -> str:
        if isinstance(e, InputRef):
            return e.name
        sym = self.symbols.new("subqkey")
        schema = ctx.root.output_schema()
        full = {s: InputRef(s, t) for s, t in schema.items()}
        full[sym] = e
        ctx.root = ProjectNode(ctx.root, full)
        return sym

    def _expand_stars(self, items, scope: Scope) -> List[A.SelectItem]:
        out = []
        for item in items:
            if isinstance(item.expr, A.Star):
                q = item.expr.qualifier
                matched = False
                for f in scope.fields:
                    if q is None or f.qualifier == q.lower():
                        matched = True
                        out.append(A.SelectItem(
                            A.Identifier(
                                ((f.qualifier, f.name) if f.qualifier
                                 else (f.name,))), f.name))
                if not matched:
                    raise PlanningError(
                        f"SELECT {q + '.' if q else ''}* has no columns")
            else:
                out.append(item)
        return out


# --------------------------------------------------------------------------
# multi-key semi join node (EXISTS decorrelation target)
# --------------------------------------------------------------------------

@dataclass(frozen=True)
class SemiJoinMultiNode(PlanNode):
    """Generalized semi join: multiple equi keys + residual filter, used
    by EXISTS decorrelation (the single-key null-aware SemiJoinNode stays
    dedicated to IN, mirroring plan/SemiJoinNode.java)."""
    source: PlanNode
    filtering_source: PlanNode
    source_keys: Tuple[str, ...]
    filtering_keys: Tuple[str, ...]
    filter: Optional[RowExpr]
    output: str
    null_aware: bool = False

    @property
    def sources(self):
        return (self.source, self.filtering_source)

    def output_schema(self):
        out = dict(self.source.output_schema())
        out[self.output] = BOOLEAN
        return out


# --------------------------------------------------------------------------
# expression translation (ExpressionAnalyzer + TranslationMap)
# --------------------------------------------------------------------------

class _ExprContext:
    """Carries the scope + current plan root (subqueries attach joins to
    the root as they are planned) + agg/window substitution maps."""

    def __init__(self, planner: LogicalPlanner, scope: Scope,
                 root: Optional[PlanNode], agg_map=None, key_map=None,
                 group_symbols=None):
        self.planner = planner
        self.scope = scope
        self.root = root
        self.agg_map = agg_map or {}
        self.key_map = key_map or {}
        self.group_symbols = group_symbols
        self.win_map: Dict[A.Expression, Tuple[str, Type]] = {}
        self.in_aggregate = False
        # lambda parameter bindings: name -> (synthetic symbol, type);
        # pushed/popped around lambda-body rewriting (reference:
        # ExpressionAnalyzer lambda scopes)
        self.lambda_params: Dict[str, Tuple[str, Type]] = {}

    def rewrite(self, e: A.Expression) -> RowExpr:
        return self.planner._rewrite_expr(e, self)


def _require_boolean(e: RowExpr, where: str):
    if e.type not in (BOOLEAN, UNKNOWN):
        raise PlanningError(
            f"{where} clause must evaluate to boolean (got {e.type})")


# the translation itself is a method of LogicalPlanner for access to
# symbols/catalogs; defined here to keep the class body readable
def _rewrite_expr(self: LogicalPlanner, e: A.Expression,
                  ctx: _ExprContext) -> RowExpr:
    # agg / group-key / window substitution first (TranslationMap)
    if ctx.agg_map or ctx.key_map or ctx.win_map:
        if e in ctx.win_map:
            sym, t = ctx.win_map[e]
            return InputRef(sym, t)
        if e in ctx.agg_map and not ctx.in_aggregate:
            sym, t = ctx.agg_map[e]
            return InputRef(sym, t)
        if e in ctx.key_map:
            sym = ctx.key_map[e]
            t = _symbol_type(ctx.root, sym)
            return InputRef(sym, t)

    if isinstance(e, A.Literal):
        return _plan_literal(e)
    if isinstance(e, A.IntervalLiteral):
        return _plan_interval(e)
    if isinstance(e, A.Identifier):
        if len(e.parts) == 1 and e.parts[0] in ctx.lambda_params:
            sym, t = ctx.lambda_params[e.parts[0]]
            return InputRef(sym, t)
        try:
            f, is_outer = ctx.scope.resolve(e.parts)
        except PlanningError:
            # row-field dereference: a.b where a is a row-typed column
            deref = _try_row_dereference(self, e, ctx)
            if deref is not None:
                return deref
            raise
        ref = InputRef(f.symbol, f.type)
        if not is_outer and ctx.group_symbols is not None \
                and not ctx.in_aggregate \
                and f.symbol not in ctx.group_symbols:
            raise PlanningError(
                f"Column '{'.'.join(e.parts)}' must appear in GROUP BY "
                "or be used in an aggregate function")
        return ref
    if isinstance(e, A.BinaryOp):
        return _plan_binary(self, e, ctx)
    if isinstance(e, A.UnaryOp):
        arg = self._rewrite_expr(e.operand, ctx)
        if e.op == "not":
            _require_boolean(arg, "NOT")
            return Call("not", (arg,), BOOLEAN)
        if e.op == "-":
            if isinstance(arg, Const) and is_numeric(arg.type):
                v = arg.value
                if v is None:
                    return Const(None, arg.type)
                if isinstance(v, str):   # decimal literals carry text
                    from decimal import Decimal
                    return Const(str(-Decimal(v)), arg.type)
                return Const(-v, arg.type)
            return Call("negate", (arg,), arg.type)
        return arg
    if isinstance(e, A.IsNull):
        arg = self._rewrite_expr(e.operand, ctx)
        out = Call("is_null", (arg,), BOOLEAN)
        return Call("not", (out,), BOOLEAN) if e.negated else out
    if isinstance(e, A.IsDistinctFrom):
        l = self._rewrite_expr(e.left, ctx)
        r = self._rewrite_expr(e.right, ctx)
        l, r = _coerce_pair(l, r, "IS DISTINCT FROM")
        out = Call("is_distinct_from", (l, r), BOOLEAN)
        return Call("not", (out,), BOOLEAN) if e.negated else out
    if isinstance(e, A.Between):
        op = self._rewrite_expr(e.operand, ctx)
        lo = self._rewrite_expr(e.low, ctx)
        hi = self._rewrite_expr(e.high, ctx)
        a, lo = _coerce_pair(op, lo, "BETWEEN")
        b, hi = _coerce_pair(op, hi, "BETWEEN")
        out = Call("and", (Call(">=", (a, lo), BOOLEAN),
                           Call("<=", (b, hi), BOOLEAN)), BOOLEAN)
        return Call("not", (out,), BOOLEAN) if e.negated else out
    if isinstance(e, A.InList):
        op = self._rewrite_expr(e.operand, ctx)
        eqs = []
        for item in e.items:
            it = self._rewrite_expr(item, ctx)
            a, b = _coerce_pair(op, it, "IN")
            eqs.append(Call("=", (a, b), BOOLEAN))
        out = rex.or_all(eqs)
        return Call("not", (out,), BOOLEAN) if e.negated else out
    if isinstance(e, A.InSubquery):
        op = self._rewrite_expr(e.operand, ctx)
        return self.plan_in_subquery(ctx, op, e.query, e.negated)
    if isinstance(e, A.Exists):
        return self.plan_exists(ctx, e.query, e.negated)
    if isinstance(e, A.ScalarSubquery):
        return self.plan_scalar_subquery(ctx, e.query)
    if isinstance(e, A.QuantifiedComparison):
        return self.plan_quantified(ctx, e)
    if isinstance(e, A.Like):
        op = self._rewrite_expr(e.operand, ctx)
        pat = self._rewrite_expr(e.pattern, ctx)
        if not is_string(op.type) or not is_string(pat.type):
            raise PlanningError("LIKE requires varchar operands")
        args = [op, pat]
        if e.escape is not None:
            args.append(self._rewrite_expr(e.escape, ctx))
        out = Call("like", tuple(args), BOOLEAN)
        return Call("not", (out,), BOOLEAN) if e.negated else out
    if isinstance(e, A.Case):
        whens = []
        val_types: List[Type] = []
        conds = []
        for c, v in e.whens:
            cc = self._rewrite_expr(c, ctx)
            _require_boolean(cc, "CASE WHEN")
            vv = self._rewrite_expr(v, ctx)
            conds.append(cc)
            whens.append(vv)
            val_types.append(vv.type)
        default = (self._rewrite_expr(e.default, ctx)
                   if e.default is not None else None)
        if default is not None:
            val_types.append(default.type)
        t = val_types[0]
        for vt in val_types[1:]:
            nt = common_super_type(t, vt)
            if nt is None:
                raise PlanningError(
                    f"CASE branches have incompatible types {t} / {vt}")
            t = nt
        whens = [_maybe_cast(v, t) for v in whens]
        default = _maybe_cast(default, t) if default is not None else None
        return CaseExpr(tuple(zip(conds, whens)), default, t)
    if isinstance(e, A.Cast):
        arg = self._rewrite_expr(e.operand, ctx)
        return Cast(arg, parse_type(e.type_name), e.safe)
    if isinstance(e, A.Extract):
        arg = self._rewrite_expr(e.operand, ctx)
        return Call(e.field.lower(), (arg,), BIGINT)
    if isinstance(e, A.FunctionCall):
        return _plan_function(self, e, ctx)
    if isinstance(e, A.ArrayConstructor):
        from ..types import ArrayType
        if not e.items:
            raise PlanningError("empty ARRAY[] requires a cast")
        items = [self._rewrite_expr(i, ctx) for i in e.items]
        t = items[0].type
        for it in items[1:]:
            nt = common_super_type(t, it.type)
            if nt is None:
                raise PlanningError(
                    f"ARRAY elements have incompatible types {t} / "
                    f"{it.type}")
            t = nt
        items = [_maybe_cast(i, t) for i in items]
        return Call("$array", tuple(items), ArrayType(t))
    if isinstance(e, A.RowConstructor):
        from ..types import RowType
        items = [self._rewrite_expr(i, ctx) for i in e.items]
        t = RowType([(None, i.type) for i in items])
        return Call("$row", tuple(items), t)
    if isinstance(e, A.LambdaExpression):
        raise PlanningError(
            "lambda expressions are only valid as arguments of "
            "higher-order functions (transform, filter, reduce, ...)")
    if isinstance(e, A.Subscript):
        from ..types import ArrayType, MapType, RowType
        base = self._rewrite_expr(e.base, ctx)
        idx = self._rewrite_expr(e.index, ctx)
        if isinstance(base.type, MapType):
            # m[k]: missing key yields NULL (element_at semantics; the
            # reference's strict m[k] raise cannot surface from a
            # compiled whole-column program)
            key = _maybe_cast(idx, base.type.key)
            return Call("element_at", (base, key), base.type.value)
        if isinstance(base.type, RowType):
            if not (isinstance(idx, Const) and idx.value is not None):
                raise PlanningError(
                    "ROW subscript must be a constant")
            i = int(idx.value)
            if not (1 <= i <= len(base.type.fields)):
                raise PlanningError(f"ROW subscript out of range: {i}")
            return Call("$field", (base, Const(i - 1, BIGINT)),
                        base.type.fields[i - 1][1])
        if not isinstance(base.type, ArrayType):
            raise PlanningError(
                f"subscript requires an array (got {base.type})")
        # constant non-positive subscripts error at plan time (the
        # reference's runtime errors, hoisted); data-dependent indexes
        # diverge: out of range yields NULL (element_at semantics)
        # because raises can't surface from inside a compiled
        # whole-column XLA program (SURVEY.md §7.2 static-shape rule)
        if isinstance(idx, Const) and idx.value is not None \
                and int(idx.value) <= 0:
            raise PlanningError(
                "Array subscript must be positive: SQL array indices "
                "start at 1")
        return Call("element_at", (base, idx), base.type.element)
    if isinstance(e, A.Star):
        raise PlanningError("'*' not allowed here")
    raise PlanningError(f"unsupported expression {type(e).__name__}")


LogicalPlanner._rewrite_expr = _rewrite_expr


def _try_row_dereference(self: LogicalPlanner, e: A.Identifier,
                         ctx: _ExprContext):
    """Resolve a.b / t.a.b where the prefix is a ROW-typed column and the
    suffix names fields (reference: ExpressionAnalyzer dereference
    resolution, sql/planner/iterative/rule/PushDownDereference*)."""
    from ..types import RowType
    parts = e.parts
    for cut in range(len(parts) - 1, 0, -1):
        base = None
        prefix = parts[:cut]
        if len(prefix) == 1 and prefix[0] in ctx.lambda_params:
            sym, t = ctx.lambda_params[prefix[0]]
            base = InputRef(sym, t)
        else:
            f, _ = ctx.scope.try_resolve(prefix)
            if f is not None:
                base = InputRef(f.symbol, f.type)
        if base is None:
            continue
        expr = base
        ok = True
        for fld in parts[cut:]:
            if not isinstance(expr.type, RowType):
                ok = False
                break
            idx = None
            for i, (fn, ft) in enumerate(expr.type.fields):
                if fn is not None and fn.lower() == fld.lower():
                    idx = i
                    break
            if idx is None:
                ok = False
                break
            expr = Call("$field", (expr, Const(idx, BIGINT)),
                        expr.type.fields[idx][1])
        if ok:
            return expr
    return None


# higher-order (lambda-taking) functions and the positions of their
# lambda arguments (reference: operator/scalar/ArrayTransformFunction
# and friends, SURVEY.md Appendix A.10)
_HIGHER_ORDER = {"transform", "filter", "reduce", "any_match",
                 "all_match", "none_match", "zip_with", "map_filter",
                 "transform_keys", "transform_values", "map_zip_with"}


def _plan_lambda(self: LogicalPlanner, lam: A.LambdaExpression,
                 ctx: _ExprContext, param_types) -> Lambda:
    if len(lam.params) != len(param_types):
        raise PlanningError(
            f"lambda has {len(lam.params)} parameters, expected "
            f"{len(param_types)}")
    saved = dict(ctx.lambda_params)
    syms = []
    for p, t in zip(lam.params, param_types):
        sym = self.symbols.new("lam_" + p)
        ctx.lambda_params[p] = (sym, t)
        syms.append(sym)
    try:
        body = self._rewrite_expr(lam.body, ctx)
    finally:
        ctx.lambda_params.clear()
        ctx.lambda_params.update(saved)
    return Lambda(tuple(syms), body, body.type)


def _plan_higher_order(self: LogicalPlanner, e: A.FunctionCall,
                       ctx: _ExprContext) -> RowExpr:
    from ..types import ArrayType, BOOLEAN as _B, MapType
    name = e.name

    def arr_of(i):
        a = self._rewrite_expr(e.args[i], ctx)
        if not isinstance(a.type, ArrayType):
            raise PlanningError(f"{name} argument {i + 1} must be an "
                                f"array (got {a.type})")
        return a

    def map_of(i):
        m = self._rewrite_expr(e.args[i], ctx)
        if not isinstance(m.type, MapType):
            raise PlanningError(f"{name} argument {i + 1} must be a map "
                                f"(got {m.type})")
        return m

    def lam(i, ptypes):
        a = e.args[i]
        if not isinstance(a, A.LambdaExpression):
            raise PlanningError(
                f"{name} argument {i + 1} must be a lambda")
        return _plan_lambda(self, a, ctx, ptypes)

    if name == "transform":
        a = arr_of(0)
        fn = lam(1, [a.type.element])
        return Call(name, (a, fn), ArrayType(fn.type))
    if name == "filter":
        a = arr_of(0)
        fn = lam(1, [a.type.element])
        _require_boolean(fn.body, "filter lambda")
        return Call(name, (a, fn), a.type)
    if name in ("any_match", "all_match", "none_match"):
        a = arr_of(0)
        fn = lam(1, [a.type.element])
        _require_boolean(fn.body, f"{name} lambda")
        return Call(name, (a, fn), BOOLEAN)
    if name == "reduce":
        a = arr_of(0)
        init = self._rewrite_expr(e.args[1], ctx)
        step = lam(2, [init.type, a.type.element])
        state_t = common_super_type(init.type, step.type) or step.type
        if state_t != step.type:
            # re-plan the step with the widened state type
            step = lam(2, [state_t, a.type.element])
        out = lam(3, [state_t])
        return Call(name, (a, _maybe_cast(init, state_t), step, out),
                    out.type)
    if name == "zip_with":
        a, b = arr_of(0), arr_of(1)
        fn = lam(2, [a.type.element, b.type.element])
        return Call(name, (a, b, fn), ArrayType(fn.type))
    if name == "map_filter":
        m = map_of(0)
        fn = lam(1, [m.type.key, m.type.value])
        _require_boolean(fn.body, "map_filter lambda")
        return Call(name, (m, fn), m.type)
    if name == "transform_keys":
        m = map_of(0)
        fn = lam(1, [m.type.key, m.type.value])
        return Call(name, (m, fn), MapType(fn.type, m.type.value))
    if name == "transform_values":
        m = map_of(0)
        fn = lam(1, [m.type.key, m.type.value])
        return Call(name, (m, fn), MapType(m.type.key, fn.type))
    if name == "map_zip_with":
        m1, m2 = map_of(0), map_of(1)
        k = common_super_type(m1.type.key, m2.type.key)
        if k is None:
            raise PlanningError("map_zip_with keys are incompatible")
        fn = lam(2, [k, m1.type.value, m2.type.value])
        return Call(name, (m1, m2, fn), MapType(k, fn.type))
    raise PlanningError(f"unsupported higher-order function {name}")


def _plan_function(self: LogicalPlanner, e: A.FunctionCall,
                   ctx: _ExprContext) -> RowExpr:
    name = e.name
    if e.window is not None:
        raise PlanningError(
            f"window function '{name}' used outside SELECT list")
    if name == "$field":
        # parser-desugared row dereference on a non-identifier base
        from ..types import RowType
        base = self._rewrite_expr(e.args[0], ctx)
        fld = e.args[1].value
        if not isinstance(base.type, RowType):
            raise PlanningError(
                f"cannot dereference .{fld} on {base.type}")
        for i, (fn_, ft) in enumerate(base.type.fields):
            if fn_ is not None and fn_.lower() == str(fld).lower():
                return Call("$field", (base, Const(i, BIGINT)), ft)
        raise PlanningError(f"row has no field named '{fld}'")
    if name in _HIGHER_ORDER and any(
            isinstance(a, A.LambdaExpression) for a in e.args):
        return _plan_higher_order(self, e, ctx)
    if name == "grouping":
        # grouping(c1, .., cn): bitmask with bit (n-1-i) set when ci is
        # NOT grouped in this row's grouping set (reference:
        # sql/analyzer + GroupingOperationRewriter — decoded here from
        # the GroupIdNode set index; constant 0 for plain GROUP BY)
        if ctx.group_symbols is None and not ctx.agg_map:
            raise PlanningError("grouping() requires GROUP BY")
        info = getattr(ctx, "grouping_info", None)
        arg_refs = []
        for a in e.args:
            r = self._rewrite_expr(a, ctx)
            if not isinstance(r, InputRef):
                raise PlanningError(
                    "grouping() arguments must be grouping expressions")
            arg_refs.append(r.name)
        if info is None:
            return Const(0, BIGINT)
        id_sym, set_syms = info
        from ..rex import CaseExpr
        whens = []
        for k, sset in enumerate(set_syms):
            mask = 0
            for s in arg_refs:
                mask = (mask << 1) | (0 if s in sset else 1)
            whens.append((Call("=", (InputRef(id_sym, BIGINT),
                                     Const(k, BIGINT)), BOOLEAN),
                          Const(mask, BIGINT)))
        return CaseExpr(tuple(whens), Const(None, BIGINT), BIGINT)
    if is_aggregate(name):
        if ctx.group_symbols is None and not ctx.agg_map:
            raise PlanningError(
                f"aggregate '{name}' not allowed here")
        raise PlanningError(f"unexpected unmapped aggregate '{name}'")
    args = tuple(self._rewrite_expr(a, ctx) for a in e.args)
    if name in ("if",) and len(args) == 2:
        args = args + (Const(None, args[1].type),)
    try:
        rtype = scalar_result_type(name, [a.type for a in args])
    except FunctionResolutionError as exc:
        raise PlanningError(str(exc)) from None
    # coerce numeric args of variadic common-type functions
    if name in ("coalesce", "greatest", "least", "if"):
        tgt = rtype
        head = args[:1] if name == "if" else ()
        tail = args[1:] if name == "if" else args
        args = tuple(head) + tuple(_maybe_cast(a, tgt) for a in tail)
    return Call(name, args, rtype)


def _plan_literal(e: A.Literal) -> Const:
    v = e.value
    if e.type_name == "decimal" and not isinstance(v, (int, float)):
        # bare decimal literal: infer (precision, scale) from the text
        # (reference: Literal analysis in ExpressionAnalyzer — "1.5" is
        # DECIMAL(2,1), never the parse_type default decimal(38,0))
        from decimal import Decimal as _D
        d = _D(str(v))
        tup = d.as_tuple()
        scale = max(0, -tup.exponent)
        precision = max(len(tup.digits), scale, 1)
        return Const(str(v), DecimalType(precision, scale))
    if e.type_name is not None:
        t = parse_type(e.type_name)
        if t is DATE:
            import datetime
            d = datetime.date.fromisoformat(str(v).strip())
            return Const(d.toordinal()
                         - datetime.date(1970, 1, 1).toordinal(), DATE)
        if isinstance(t, TimestampType):
            from ..types import TimestampTZType, iso_timestamp_tz
            ms, off = iso_timestamp_tz(str(v))
            if off is None:
                return Const(ms, t)
            return Const((ms, off), TimestampTZType(t.precision))
        from ..types import TimestampTZType as _TTZ
        if isinstance(t, _TTZ):
            from ..types import iso_timestamp_tz
            ms, off = iso_timestamp_tz(str(v))
            return Const((ms, off or 0), t)
        from ..types import TimeType as _TimeType
        if isinstance(t, _TimeType):
            from ..types import iso_time_millis
            return Const(iso_time_millis(str(v)), t)
        if isinstance(t, DecimalType):
            return Const(v, t)
        return Const(v, t)
    if v is None:
        return Const(None, UNKNOWN)
    if isinstance(v, bool):
        return Const(v, BOOLEAN)
    if isinstance(v, int):
        t = INTEGER if -(2**31) <= v < 2**31 else BIGINT
        return Const(v, t)
    if isinstance(v, float):
        # decimal literals parse as DOUBLE (reference FeaturesConfig
        # parse-decimal-literals-as-double mode)
        return Const(v, DOUBLE)
    if isinstance(v, str):
        return Const(v, VarcharType(len(v)))
    raise PlanningError(f"cannot type literal {v!r}")


def _plan_interval(e: A.IntervalLiteral) -> Const:
    n = int(e.value) * e.sign
    u = e.unit.lower()
    if u in ("year", "month", "quarter"):
        months = n * {"year": 12, "quarter": 3, "month": 1}[u]
        return Const(months, IntervalYearMonth)
    millis = n * {"day": 86400000, "hour": 3600000, "minute": 60000,
                  "second": 1000, "week": 7 * 86400000}[u]
    return Const(millis, IntervalDayTime)


_CMP = {"=", "<>", "!=", "<", "<=", ">", ">="}
_ARITH = {"+", "-", "*", "/", "%"}


def _plan_binary(self: LogicalPlanner, e: A.BinaryOp,
                 ctx: _ExprContext) -> RowExpr:
    op = e.op
    l = self._rewrite_expr(e.left, ctx)
    r = self._rewrite_expr(e.right, ctx)
    if op in ("and", "or"):
        _require_boolean(l, op.upper())
        _require_boolean(r, op.upper())
        return Call(op, (l, r), BOOLEAN)
    if op == "||":
        if is_string(l.type) and is_string(r.type):
            return Call("concat", (l, r), VARCHAR)
        raise PlanningError(f"|| not supported for {l.type}, {r.type}")
    if op in _CMP:
        op = "<>" if op == "!=" else op
        l2, r2 = _coerce_pair(l, r, op)
        return Call(op, (l2, r2), BOOLEAN)
    if op in _ARITH:
        # date/timestamp ± interval
        if l.type is DATE and r.type in (IntervalDayTime,
                                         IntervalYearMonth):
            return Call(f"date_{'add' if op == '+' else 'sub'}_interval",
                        (l, r), DATE)
        if isinstance(l.type, TimestampType) and r.type in (
                IntervalDayTime, IntervalYearMonth):
            return Call(f"ts_{'add' if op == '+' else 'sub'}_interval",
                        (l, r), l.type)
        if l.type is DATE and r.type is DATE and op == "-":
            return Call("date_diff_days", (l, r), BIGINT)
        if not (is_numeric(l.type) and is_numeric(r.type)):
            raise PlanningError(
                f"'{op}' not supported for {l.type}, {r.type}")
        t = _arith_type(op, l.type, r.type)
        l2, r2 = _maybe_cast(l, t), _maybe_cast(r, t)
        if isinstance(t, DecimalType):
            # operate on scaled int lanes; executor knows the scales
            return Call(f"decimal_{op}", (l, r), t)
        return Call(op, (l2, r2), t)
    raise PlanningError(f"unknown operator '{op}'")


def _arith_type(op: str, a: Type, b: Type) -> Type:
    """sql/planner result types for arithmetic
    (reference: spi/type/DecimalOperators precision math)."""
    if a.name == "double" or b.name == "double":
        return DOUBLE
    if a.name == "real" or b.name == "real":
        from ..types import REAL
        return REAL
    if isinstance(a, DecimalType) or isinstance(b, DecimalType):
        from ..types import default_decimal_for
        da = a if isinstance(a, DecimalType) else default_decimal_for(a)
        db = b if isinstance(b, DecimalType) else default_decimal_for(b)
        if op in ("+", "-"):
            s = max(da.scale, db.scale)
            p = min(38, max(da.precision - da.scale,
                            db.precision - db.scale) + s + 1)
            return DecimalType(p, s)
        if op == "*":
            return DecimalType(min(38, da.precision + db.precision),
                               min(38, da.scale + db.scale))
        if op == "/":
            s = max(6, da.scale)
            return DecimalType(38, s)
        if op == "%":
            return DecimalType(max(da.precision, db.precision),
                               max(da.scale, db.scale))
    t = common_super_type(a, b)
    if t is None:
        raise PlanningError(f"no common type for {a}, {b}")
    return t


def _coerce_pair(l: RowExpr, r: RowExpr, what: str):
    t = common_super_type(l.type, r.type)
    if t is None:
        raise PlanningError(
            f"{what}: incompatible types {l.type} and {r.type}")
    return _maybe_cast(l, t), _maybe_cast(r, t)


def _maybe_cast(e: RowExpr, t: Type) -> RowExpr:
    if e.type == t or e.type == UNKNOWN and isinstance(e, Const) \
            and e.value is None:
        if e.type == UNKNOWN and isinstance(e, Const):
            return Const(None, t)
        return e
    if isinstance(e, Const) and e.value is not None:
        folded = _fold_cast_const(e, t)
        if folded is not None:
            return folded
    return Cast(e, t)


def _fold_cast_const(e: Const, t: Type) -> Optional[Const]:
    v = e.value
    try:
        if t.name == "double":
            return Const(float(v), t)
        if t.name == "real":
            import numpy as np
            return Const(float(np.float32(v)), t)
        if is_integral(t):
            return Const(int(v), t)
        if isinstance(t, DecimalType):
            return Const(v, t)
        if is_string(t) and isinstance(v, str):
            return Const(v, t)
    except (TypeError, ValueError):
        return None
    return None


def _derive_name(e: A.Expression) -> Optional[str]:
    if isinstance(e, A.Identifier):
        return e.parts[-1].lower()
    if isinstance(e, A.FunctionCall):
        return e.name
    if isinstance(e, A.Extract):
        return e.field.lower()
    if isinstance(e, A.Cast):
        return _derive_name(e.operand)
    return None


def _symbol_type(root: PlanNode, sym: str) -> Type:
    return root.output_schema()[sym]


def _const_fold(e: RowExpr) -> RowExpr:
    """Minimal constant folding for VALUES (full interpreter parity with
    sql/planner/ExpressionInterpreter.java is executor-side)."""
    if isinstance(e, Const):
        return e
    if isinstance(e, Cast):
        inner = _const_fold(e.arg)
        if isinstance(inner, Const):
            if inner.value is None:
                return Const(None, e.type)
            folded = _fold_cast_const(inner, e.type)
            if folded is not None:
                return folded
    if isinstance(e, Call):
        args = [_const_fold(a) for a in e.args]
        if all(isinstance(a, Const) for a in args):
            vals = [a.value for a in args]
            if any(v is None for v in vals):
                return Const(None, e.type)
            try:
                if e.fn == "+":
                    return Const(vals[0] + vals[1], e.type)
                if e.fn == "-":
                    return Const(vals[0] - vals[1], e.type)
                if e.fn == "*":
                    return Const(vals[0] * vals[1], e.type)
                if e.fn == "/":
                    if is_integral(e.type):
                        q = abs(vals[0]) // abs(vals[1])
                        if (vals[0] < 0) != (vals[1] < 0):
                            q = -q
                        return Const(q, e.type)
                    return Const(vals[0] / vals[1], e.type)
                if e.fn == "negate":
                    return Const(-vals[0], e.type)
                if e.fn == "concat":
                    return Const("".join(vals), e.type)
            except (TypeError, ZeroDivisionError):
                pass
    return e


# --------------------------------------------------------------------------
# decorrelation helpers (TransformCorrelated* rules, at plan time)
# --------------------------------------------------------------------------

def _all_symbols(node: Optional[PlanNode]) -> Set[str]:
    if node is None:
        return set()
    syms = set(node.output_schema())
    for s in node.sources:
        syms |= _all_symbols(s)
    return syms


def _correlated_symbols(node: PlanNode, outer_syms: Set[str]) -> Set[str]:
    """Outer symbols referenced free inside the subquery plan."""
    used: Set[str] = set()

    def visit(n: PlanNode):
        produced = set()
        for s in n.sources:
            visit(s)
            produced |= set(s.output_schema())
        exprs: List[RowExpr] = []
        if isinstance(n, FilterNode):
            exprs.append(n.predicate)
        elif isinstance(n, ProjectNode):
            exprs.extend(n.assignments.values())
        elif isinstance(n, JoinNode) and n.filter is not None:
            exprs.append(n.filter)
        for e in exprs:
            for name in rex.input_names(e):
                if name not in produced and name in outer_syms:
                    used.add(name)

    visit(node)
    return used


def _decorrelate_scalar_agg(root: PlanNode, corr: Set[str], symbols):
    """TransformCorrelatedScalarAggregationToJoin: rewrite
      [Project] -> Aggregation(global) -> tree-with-correlated-filters
    into an aggregation grouped by the inner correlation keys; returns
    (new_root, [(outer_sym, inner_sym)])."""
    # peel projects above the aggregation
    projects: List[ProjectNode] = []
    node = root
    while isinstance(node, ProjectNode):
        projects.append(node)
        node = node.source
    if not isinstance(node, AggregationNode) or node.group_keys:
        raise PlanningError(
            "correlated scalar subquery must be a single aggregate "
            "(decorrelation pattern not supported)")
    agg = node
    stripped, pairs = _strip_correlated_filters(agg.source, corr)
    if not pairs:
        raise PlanningError(
            "could not extract equality correlation from subquery")
    inner_keys = tuple(dict.fromkeys(i for _, i in pairs))
    new_agg = AggregationNode(stripped, inner_keys, agg.aggregates,
                              agg.step)
    new_root: PlanNode = new_agg
    # re-apply projects, widened to carry the correlation keys through
    for p in reversed(projects):
        assigns = dict(p.assignments)
        schema = new_root.output_schema()
        for k in inner_keys:
            assigns.setdefault(k, InputRef(k, schema[k]))
        new_root = ProjectNode(new_root, assigns)
    return new_root, [(o, i) for o, i in pairs]


def _decorrelate_exists(root: PlanNode, corr: Set[str], symbols):
    """Correlated EXISTS -> semi-join shape: strip correlated conjuncts;
    equality pairs become join keys, the rest becomes a residual filter
    over (outer ∪ inner) columns."""
    stripped, pairs, residual = _strip_correlated_filters(
        root, corr, allow_residual=True)
    if not pairs and residual is None:
        raise PlanningError(
            "could not extract correlation from EXISTS subquery")
    return stripped, pairs, residual


def _strip_correlated_filters(node: PlanNode, corr: Set[str],
                              allow_residual: bool = False):
    """Remove conjuncts referencing outer symbols from Filter nodes in the
    subtree. Returns (new_node, [(outer_sym, inner_sym)]) and optionally a
    residual expression (conjuncts that are correlated but not simple
    equalities)."""
    pairs: List[Tuple[str, str]] = []
    residuals: List[RowExpr] = []

    def visit(n: PlanNode) -> PlanNode:
        if isinstance(n, FilterNode):
            src = visit(n.source)
            keep: List[RowExpr] = []
            # normalize (A and X) or (A and Y) -> A and (X or Y) first:
            # q41-style subqueries repeat the correlated conjunct inside
            # every OR arm, and only the factored form decorrelates
            from .optimizer import _split_normalized
            for c in _split_normalized(n.predicate):
                refs = rex.input_names(c)
                if refs & corr:
                    pair = _as_correlation_pair(c, corr)
                    if pair is not None:
                        pairs.append(pair)
                    elif allow_residual:
                        residuals.append(c)
                    else:
                        raise PlanningError(
                            "unsupported correlated predicate: "
                            f"{c}")
                else:
                    keep.append(c)
            if keep:
                return FilterNode(src, rex.and_all(keep))
            return src
        if isinstance(n, ProjectNode):
            src = visit(n.source)
            # widen projection to keep correlation key symbols visible
            assigns = dict(n.assignments)
            schema = src.output_schema()
            for _, i in pairs:
                if i not in assigns and i in schema:
                    assigns[i] = InputRef(i, schema[i])
            if residuals:
                for r in residuals:
                    for name in rex.input_names(r):
                        if name not in assigns and name in schema:
                            assigns[name] = InputRef(name, schema[name])
            return ProjectNode(src, assigns)
        if isinstance(n, (JoinNode,)):
            return dc_replace(n, left=visit(n.left), right=visit(n.right))
        if isinstance(n, (AggregationNode,)):
            src = visit(n.source)
            gk = n.group_keys
            extra = tuple(i for _, i in pairs if i not in gk
                          and i in src.output_schema())
            return dc_replace(n, source=src, group_keys=gk + extra)
        if not n.sources:
            return n
        if len(n.sources) == 1:
            return dc_replace(n, source=visit(n.sources[0]))
        return n

    new = visit(node)
    if allow_residual:
        return new, pairs, (rex.and_all(residuals) if residuals else None)
    return new, pairs


def _as_correlation_pair(c: RowExpr, corr: Set[str]):
    """Match `outer_sym = inner_sym` (modulo argument order)."""
    if isinstance(c, Call) and c.fn == "=" and len(c.args) == 2:
        a, b = c.args
        if isinstance(a, InputRef) and isinstance(b, InputRef):
            if a.name in corr and b.name not in corr:
                return (a.name, b.name)
            if b.name in corr and a.name not in corr:
                return (b.name, a.name)
    return None


def _extract_equi_criteria(on_expr: RowExpr, lsyms: Set[str],
                           rsyms: Set[str]):
    """Split a join condition into equi-clauses (left expr, right expr)
    and residual conjuncts (reference: JoinNode criteria extraction in
    RelationPlanner + ExtractCommonPredicates)."""
    criteria: List[Tuple[RowExpr, RowExpr]] = []
    residual: List[RowExpr] = []
    for c in rex.split_conjuncts(on_expr):
        ok = False
        if isinstance(c, Call) and c.fn == "=" and len(c.args) == 2:
            a, b = c.args
            ra, rb = rex.input_names(a), rex.input_names(b)
            if ra and rb:
                if ra <= lsyms and rb <= rsyms:
                    criteria.append((a, b))
                    ok = True
                elif ra <= rsyms and rb <= lsyms:
                    criteria.append((b, a))
                    ok = True
        if not ok:
            residual.append(c)
    return criteria, residual
