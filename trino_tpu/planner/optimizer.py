"""Logical-plan optimizer passes.

Reference parity: sql/planner/optimizations/PredicatePushDown.java +
the Prune*Columns iterative-rule family (~45 rules, SURVEY.md Appendix
A.2) + InlineProjections/MergeFilters. Implemented as whole-tree rewrites
rather than a memo/rule engine — the rule set that matters for the TPU
engine is small and the passes run once per query.

Passes (in order, PlanOptimizers.java:240 analog):
1. push_filters   — move WHERE conjuncts down; extract equi conjuncts
                    into JoinNode criteria (turns the comma-join cross
                    products of TPC-H q2/q3/q5… into hash joins).
2. prune_columns  — project away unreferenced symbols all the way into
                    TableScan assignments (generator reads less).
3. cleanup_projects — drop identity projections.
"""

from __future__ import annotations

from dataclasses import replace as dc_replace
from typing import Dict, List, Optional, Set, Tuple

from .. import rex
from ..plan.nodes import (AggregationNode, AssignUniqueIdNode,
                          EnforceSingleRowNode, ExchangeNode, FilterNode,
                          JoinClause, JoinNode, LimitNode,
                          MarkDistinctNode, OffsetNode, OutputNode,
                          PlanNode, ProjectNode, SampleNode, SemiJoinNode,
                          SetOpNode, SortNode, TableScanNode, TopNNode,
                          UnionNode, ValuesNode, WindowNode)
from ..matching import Pattern as _Pat
from ..planner.logical import SemiJoinMultiNode
from ..rex import Call, Const, InputRef, RowExpr, TRUE


def _pass_checker(session):
    """The per-pass sanity checker when the session enables debug
    validation (analysis/sanity.py; reference: the PlanSanityChecker
    battery the IterativeOptimizer runs between rules under
    assertions). Returns None when off — the common case pays one dict
    lookup, no import."""
    if session is None:
        return None
    try:
        enabled = bool(session.get("plan_validation"))
    except KeyError:        # foreign session objects without the knob
        return None
    if not enabled:
        return None
    from ..analysis.sanity import PlanSanityChecker
    return PlanSanityChecker()


def optimize(plan: PlanNode, catalogs=None, session=None) -> PlanNode:
    checker = _pass_checker(session)

    def ck(p: PlanNode, pass_name: str) -> PlanNode:
        # validated AFTER the named pass so a violation is pinned on
        # the rewrite that introduced it, not discovered at execution
        if checker is not None:
            checker.validate(p, pass_name)
        return p

    plan = ck(plan, "logical-planner")
    plan = ck(unwrap_casts(plan), "unwrap_casts")
    plan = ck(push_filters(plan), "push_filters")
    plan = ck(single_distinct_to_groupby(plan),
              "single_distinct_to_groupby")
    if catalogs is not None:
        from .stats import choose_join_sides, reorder_joins
        force = "AUTOMATIC"
        reorder = "AUTOMATIC"
        pushdown = True
        use_stats = True
        if session is not None:
            force = session.get("join_distribution_type") or "AUTOMATIC"
            reorder = (session.get("join_reordering_strategy")
                       or "AUTOMATIC")
            pushdown = bool(session.get("pushdown_into_scan"))
            use_stats = bool(session.get("use_table_statistics"))
        if not use_stats:
            # optimizer.use-table-statistics=false: keep syntactic join
            # order and runtime-heuristic distributions
            reorder = "NONE"
        if str(reorder).upper() != "NONE":
            plan = ck(reorder_joins(plan, catalogs), "reorder_joins")
        if use_stats or str(force).upper() != "AUTOMATIC":
            plan = ck(choose_join_sides(plan, catalogs, force),
                      "choose_join_sides")
        if pushdown:
            plan = ck(push_into_scan(plan, catalogs), "push_into_scan")
    plan = ck(partial_topn_through_union(plan),
              "partial_topn_through_union")
    plan = ck(prune_columns(plan), "prune_columns")
    plan = ck(cleanup_projects(plan), "cleanup_projects")
    return plan


# --------------------------------------------------------------------------
# connector pushdown (PushPredicateIntoTableScan / PushLimitIntoTableScan)
# --------------------------------------------------------------------------

def _domain_pushable(t) -> bool:
    """Types whose plan-constant values compare 1:1 against the
    connector's host lanes (predicate.filter_batch_host): integrals,
    date, bool, float, dictionary strings. DECIMAL consts are strings
    at plan time — skip."""
    from ..types import DecimalType, is_string
    if isinstance(t, DecimalType):
        return False
    return t.name in ("tinyint", "smallint", "integer", "bigint",
                      "real", "double", "date", "boolean") \
        or is_string(t)


def push_into_scan(node: PlanNode, catalogs) -> PlanNode:
    """Offer filter domains and limits to connectors
    (sql/planner/iterative/rule/PushPredicateIntoTableScan.java,
    PushLimitIntoTableScan.java). Accepted domains are baked into the
    TableHandle; fully-enforced conjuncts leave the plan."""
    from ..predicate import TupleDomain, extract_tuple_domain

    if isinstance(node, FilterNode) and \
            isinstance(node.source, TableScanNode):
        scan = node.source
        ok_syms = {sym: scan.schema[sym]
                   for sym in scan.assignments
                   if _domain_pushable(scan.schema[sym])}
        td_sym, residual = extract_tuple_domain(node.predicate, ok_syms)
        if not td_sym.is_all():
            td_conn = TupleDomain(
                tuple((scan.assignments[sym], dom)
                      for sym, dom in td_sym.domains), td_sym.is_none)
            conn = catalogs.connector(scan.handle.catalog)
            got = conn.apply_filter(scan.handle, td_conn)
            if got is not None:
                new_handle, fully = got
                new_scan = dc_replace(scan, handle=new_handle)
                if fully and not residual:
                    return new_scan
                pred = rex.and_all(residual) if fully else node.predicate
                return FilterNode(new_scan, pred)
        return node

    if isinstance(node, LimitNode):
        # limit commutes with row-preserving projections
        # (PushLimitThroughProject + PushLimitIntoTableScan)
        below = node.source
        projs = []
        while isinstance(below, ProjectNode):
            projs.append(below)
            below = below.source
        if isinstance(below, TableScanNode):
            conn = catalogs.connector(below.handle.catalog)
            got = conn.apply_limit(below.handle, node.count)
            if got is not None:
                rebuilt: PlanNode = dc_replace(below, handle=got)
                for p in reversed(projs):
                    rebuilt = dc_replace(p, source=rebuilt)
                return dc_replace(node, source=rebuilt)
        return _replace_sources(
            node, [push_into_scan(node.source, catalogs)])

    srcs = getattr(node, "sources", ())
    if not srcs:
        return node
    new_srcs = [push_into_scan(s, catalogs) for s in srcs]
    if all(a is b for a, b in zip(new_srcs, srcs)):
        return node
    return _replace_sources(node, new_srcs)


def _replace_sources(node: PlanNode, new_sources) -> PlanNode:
    """Rebuild a node with new child nodes, mapping them back onto the
    dataclass fields in ``sources`` order."""
    import dataclasses
    it = iter(new_sources)
    changes = {}
    for f in dataclasses.fields(node):
        v = getattr(node, f.name)
        if isinstance(v, PlanNode):
            changes[f.name] = next(it)
        elif isinstance(v, tuple) and v and \
                all(isinstance(x, PlanNode) for x in v):
            changes[f.name] = tuple(next(it) for _ in v)
    return dc_replace(node, **changes)


# --------------------------------------------------------------------------
# predicate pushdown
# --------------------------------------------------------------------------

def push_filters(node: PlanNode) -> PlanNode:
    return _push(node, [])


def extract_common_disjunct_conjuncts(e: RowExpr) -> List[RowExpr]:
    """(A and X) or (A and Y) -> [A, (X or Y)] — the
    ExtractCommonPredicates rewriter (sql/planner/iterative/rule/
    ExtractCommonPredicatesExpressionRewriter.java). Essential for
    TPC-H q19, whose equi-join condition lives inside every disjunct."""
    if not (isinstance(e, Call) and e.fn == "or"):
        return [e]
    disjuncts: List[RowExpr] = []

    def flatten_or(x):
        if isinstance(x, Call) and x.fn == "or":
            flatten_or(x.args[0])
            flatten_or(x.args[1])
        else:
            disjuncts.append(x)

    flatten_or(e)
    conj_sets = [rex.split_conjuncts(d) for d in disjuncts]
    common = [c for c in conj_sets[0]
              if all(c in s for s in conj_sets[1:])]
    if not common:
        return [e]
    rests = [rex.and_all([c for c in s if c not in common])
             for s in conj_sets]
    return common + [rex.or_all(rests)]


def _split_normalized(e: RowExpr) -> List[RowExpr]:
    out: List[RowExpr] = []
    for c in rex.split_conjuncts(e):
        out.extend(extract_common_disjunct_conjuncts(c))
    return out


def _push(node: PlanNode, conjuncts: List[RowExpr]) -> PlanNode:
    if isinstance(node, FilterNode):
        return _push(node.source,
                     conjuncts + _split_normalized(node.predicate))

    if isinstance(node, ProjectNode):
        # inline through the projection when conjuncts only reference
        # pass-through or cheap assignments (InlineProjections analog)
        inlineable, keep = [], []
        for c in conjuncts:
            refs = rex.input_names(c)
            if all(r in node.assignments for r in refs):
                inlineable.append(
                    rex.replace_inputs(c, dict(node.assignments)))
            else:
                keep.append(c)
        src = _push(node.source, inlineable)
        out: PlanNode = dc_replace(node, source=src)
        return _wrap(out, keep)

    if isinstance(node, JoinNode):
        return _push_join(node, conjuncts)

    if isinstance(node, (SemiJoinNode, SemiJoinMultiNode)):
        # conjuncts not referencing the mark column push to the source
        mark = node.output
        down, keep = [], []
        for c in conjuncts:
            (keep if mark in rex.input_names(c) else down).append(c)
        src = _push(node.sources[0], down)
        filt = _push(node.sources[1], [])
        if isinstance(node, SemiJoinNode):
            out = dc_replace(node, source=src, filtering_source=filt)
        else:
            out = dc_replace(node, source=src, filtering_source=filt)
        return _wrap(out, keep)

    if isinstance(node, AggregationNode):
        # conjuncts over group keys push below (PushPredicateThroughAgg)
        keys = set(node.group_keys)
        down, keep = [], []
        for c in conjuncts:
            (down if rex.input_names(c) <= keys else keep).append(c)
        src = _push(node.source, down)
        return _wrap(dc_replace(node, source=src), keep)

    if isinstance(node, WindowNode):
        # DETERMINISTIC conjuncts over the PARTITION BY keys push below
        # the window: dropping whole partitions cannot change surviving
        # rows' window values. A volatile conjunct (random() < x) would
        # thin partitions instead of dropping them whole.
        # (iterative/rule/PushdownFilterIntoWindow.java /
        # PushdownFilterIntoRowNumber.java)
        pkeys = set(node.partition_by)

        def pushable(c):
            return (rex.input_names(c) <= pkeys
                    and not rex.expr_volatile(c))
        down = [c for c in conjuncts if pushable(c)]
        keep = [c for c in conjuncts if not pushable(c)]
        src = _push(node.source, down)
        return _wrap(dc_replace(node, source=src), keep)

    if isinstance(node, (SortNode, MarkDistinctNode, AssignUniqueIdNode,
                         SampleNode, EnforceSingleRowNode,
                         ExchangeNode)):
        src = _push(node.sources[0], conjuncts
                    if not isinstance(node, (EnforceSingleRowNode,
                                             SampleNode))
                    else [])
        rest = (conjuncts if isinstance(node, (EnforceSingleRowNode,
                                               SampleNode))
                else [])
        return _wrap(dc_replace(node, source=src), rest)

    if isinstance(node, (LimitNode, OffsetNode, TopNNode)):
        # cannot push through limits
        src = _push(node.sources[0], [])
        return _wrap(dc_replace(node, source=src), conjuncts)

    if isinstance(node, UnionNode):
        children = []
        for child, smap in zip(node.children, node.symbol_maps):
            mapped = [rex.replace_inputs(c, smap) for c in conjuncts]
            children.append(_push(child, mapped))
        return dc_replace(node, children=tuple(children))

    if isinstance(node, SetOpNode):
        lmapped = [rex.replace_inputs(c, node.left_map)
                   for c in conjuncts]
        rmapped = [rex.replace_inputs(c, node.right_map)
                   for c in conjuncts]
        return dc_replace(node, left=_push(node.left, lmapped),
                          right=_push(node.right, rmapped))

    if isinstance(node, OutputNode):
        return dc_replace(node, source=_push(node.source, conjuncts))

    # leaves (TableScan, Values, RemoteSource)
    new_sources = tuple(_push(s, []) for s in node.sources)
    if new_sources != node.sources and hasattr(node, "source"):
        node = dc_replace(node, source=new_sources[0])
    return _wrap(node, conjuncts)


def _push_join(node: JoinNode, conjuncts: List[RowExpr]) -> PlanNode:
    lsyms = set(node.left.output_schema())
    rsyms = set(node.right.output_schema())
    jt = node.join_type

    left_down: List[RowExpr] = []
    right_down: List[RowExpr] = []
    new_criteria = list(node.criteria)
    keep: List[RowExpr] = []
    residual = _split_normalized(node.filter) if node.filter else []

    for c in conjuncts:
        refs = rex.input_names(c)
        if refs and refs <= lsyms and jt in ("inner", "left", "cross"):
            left_down.append(c)
        elif refs and refs <= rsyms and jt in ("inner", "cross"):
            right_down.append(c)
        elif jt in ("inner", "cross"):
            pair = _equi_pair(c, lsyms, rsyms)
            if pair is not None:
                new_criteria.append(JoinClause(*pair))
            else:
                residual.append(c)
        else:
            keep.append(c)

    # residuals that are side-local can also sink; equalities surfaced
    # by common-predicate extraction become criteria (from ON clauses)
    final_residual = []
    for c in residual:
        refs = rex.input_names(c)
        if refs and refs <= lsyms and jt in ("inner", "cross"):
            left_down.append(c)
        elif refs and refs <= rsyms and jt in ("inner", "cross"):
            right_down.append(c)
        elif jt in ("inner", "cross") and \
                (pair := _equi_pair(c, lsyms, rsyms)) is not None:
            new_criteria.append(JoinClause(*pair))
        else:
            final_residual.append(c)

    left = _push(node.left, left_down)
    right = _push(node.right, right_down)
    new_jt = "inner" if (jt == "cross" and new_criteria) else jt
    out = JoinNode(left, right, new_jt, tuple(new_criteria),
                   rex.and_all(final_residual) if final_residual else None,
                   node.distribution)
    return _wrap(out, keep)


def _equi_pair(c: RowExpr, lsyms: Set[str], rsyms: Set[str]):
    if isinstance(c, Call) and c.fn == "=" and len(c.args) == 2:
        a, b = c.args
        if isinstance(a, InputRef) and isinstance(b, InputRef):
            if a.name in lsyms and b.name in rsyms:
                return (a.name, b.name)
            if b.name in lsyms and a.name in rsyms:
                return (b.name, a.name)
    return None


def _wrap(node: PlanNode, conjuncts: List[RowExpr]) -> PlanNode:
    if not conjuncts:
        return node
    return FilterNode(node, rex.and_all(conjuncts))


# --------------------------------------------------------------------------
# column pruning
# --------------------------------------------------------------------------

def prune_columns(node: PlanNode) -> PlanNode:
    if isinstance(node, OutputNode):
        return dc_replace(node, source=_prune(node.source,
                                              set(node.symbols)))
    return _prune(node, set(node.output_schema()))


def _prune(node: PlanNode, needed: Set[str]) -> PlanNode:
    if isinstance(node, TableScanNode):
        keep = {s: c for s, c in node.assignments.items() if s in needed}
        if not keep:  # keep one column for row counting
            s = next(iter(node.assignments))
            keep = {s: node.assignments[s]}
        return TableScanNode(node.handle, keep,
                             {s: node.schema[s] for s in keep})

    if isinstance(node, ProjectNode):
        keep = {s: e for s, e in node.assignments.items() if s in needed}
        if not keep and node.assignments:
            s = next(iter(node.assignments))
            keep = {s: node.assignments[s]}
        child_needed = set()
        for e in keep.values():
            child_needed |= rex.input_names(e)
        return ProjectNode(_prune(node.source, child_needed), keep)

    if isinstance(node, FilterNode):
        child_needed = needed | rex.input_names(node.predicate)
        return FilterNode(_prune(node.source, child_needed),
                          node.predicate)

    if isinstance(node, AggregationNode):
        child_needed = set(node.group_keys)
        aggs = {s: a for s, a in node.aggregates.items()
                if s in needed or not node.aggregates}
        if not aggs and node.aggregates:
            # aggregates all pruned -> keep none; grouping keys remain
            aggs = {}
        for a in aggs.values():
            for sym in (a.argument, a.argument2, a.mask):
                if sym:
                    child_needed.add(sym)
        return dc_replace(node, source=_prune(node.source, child_needed),
                          aggregates=aggs)

    if isinstance(node, JoinNode):
        child = set(needed)
        for c in node.criteria:
            child.add(c.left)
            child.add(c.right)
        if node.filter is not None:
            child |= rex.input_names(node.filter)
        lsyms = set(node.left.output_schema())
        rsyms = set(node.right.output_schema())
        return dc_replace(
            node,
            left=_prune(node.left, child & lsyms),
            right=_prune(node.right, child & rsyms))

    if isinstance(node, SemiJoinNode):
        child = (needed - {node.output}) | {node.source_key}
        return dc_replace(
            node, source=_prune(node.source, child),
            filtering_source=_prune(node.filtering_source,
                                    {node.filtering_key}))

    if isinstance(node, SemiJoinMultiNode):
        child = (needed - {node.output}) | set(node.source_keys)
        fneed = set(node.filtering_keys)
        if node.filter is not None:
            refs = rex.input_names(node.filter)
            fsyms = set(node.filtering_source.output_schema())
            child |= (refs - fsyms)
            fneed |= (refs & fsyms)
        return dc_replace(
            node, source=_prune(node.source, child),
            filtering_source=_prune(node.filtering_source, fneed))

    if isinstance(node, (SortNode, TopNNode)):
        child = needed | {k.symbol for k in node.keys}
        return dc_replace(node, source=_prune(node.sources[0], child))

    if isinstance(node, MarkDistinctNode):
        child = (needed - {node.marker}) | set(node.keys)
        return dc_replace(node, source=_prune(node.source, child))

    if isinstance(node, AssignUniqueIdNode):
        return dc_replace(node, source=_prune(
            node.source, needed - {node.symbol}))

    if isinstance(node, WindowNode):
        child = needed - set(node.functions)
        child |= set(node.partition_by)
        child |= {k.symbol for k in node.order_by}
        for f in node.functions.values():
            for sym in (f.argument, f.offset, f.default):
                if sym:
                    child.add(sym)
        return dc_replace(node, source=_prune(node.source, child))

    if isinstance(node, UnionNode):
        keep_out = [s for s in node.schema if s in needed] or \
            list(node.schema)[:1]
        children = []
        maps = []
        for child, smap in zip(node.children, node.symbol_maps):
            cneed = {smap[s] for s in keep_out}
            children.append(_prune(child, cneed))
            maps.append({s: smap[s] for s in keep_out})
        return dc_replace(
            node, children=tuple(children),
            schema={s: node.schema[s] for s in keep_out},
            symbol_maps=tuple(maps))

    if isinstance(node, SetOpNode):
        # set-op semantics compare whole rows; keep all columns
        return dc_replace(node, left=_prune(
            node.left, set(node.left_map.values())),
            right=_prune(node.right, set(node.right_map.values())))

    if isinstance(node, (LimitNode, OffsetNode, SampleNode,
                         EnforceSingleRowNode, ExchangeNode)):
        src = node.sources[0]
        pruned = _prune(src, needed if not isinstance(
            node, EnforceSingleRowNode) else set(src.output_schema()))
        return dc_replace(node, source=pruned)

    if isinstance(node, ValuesNode):
        keep = [s for s in node.schema if s in needed] or \
            list(node.schema)[:1]
        idx = [list(node.schema).index(s) for s in keep]
        return ValuesNode({s: node.schema[s] for s in keep},
                          tuple(tuple(r[i] for i in idx)
                                for r in node.rows))

    if not node.sources:
        return node
    if len(node.sources) == 1 and hasattr(node, "source"):
        return dc_replace(node, source=_prune(
            node.sources[0], set(node.sources[0].output_schema())))
    return node


# --------------------------------------------------------------------------
# project cleanup
# --------------------------------------------------------------------------

def cleanup_projects(node: PlanNode) -> PlanNode:
    if isinstance(node, ProjectNode):
        src = cleanup_projects(node.source)
        if isinstance(src, ProjectNode):
            # merge Project(Project(x)) when outer refs inline trivially
            inlined = {}
            simple = True
            for s, e in node.assignments.items():
                inlined[s] = rex.replace_inputs(e, dict(src.assignments))
            merged = ProjectNode(src.source, inlined)
            node = merged
            src = merged.source
        else:
            node = dc_replace(node, source=src)
        if node.is_identity and \
                set(node.assignments) == set(node.source.output_schema()):
            return node.source
        return node
    if not node.sources:
        return node
    import dataclasses
    fields = {f.name for f in dataclasses.fields(node)}
    if "source" in fields:
        return dc_replace(node, source=cleanup_projects(node.sources[0]),
                          **({"left": cleanup_projects(node.left),
                              "right": cleanup_projects(node.right)}
                             if isinstance(node, SetOpNode) else {}))
    if isinstance(node, JoinNode):
        return dc_replace(node, left=cleanup_projects(node.left),
                          right=cleanup_projects(node.right))
    if isinstance(node, (SemiJoinNode, SemiJoinMultiNode)):
        return dc_replace(
            node, source=cleanup_projects(node.sources[0]),
            filtering_source=cleanup_projects(node.sources[1]))
    if isinstance(node, UnionNode):
        return dc_replace(node, children=tuple(
            cleanup_projects(c) for c in node.children))
    if isinstance(node, SetOpNode):
        return dc_replace(node, left=cleanup_projects(node.left),
                          right=cleanup_projects(node.right))
    return node


# --------------------------------------------------------------------------
# UnwrapCastInComparison (iterative/rule/UnwrapCastInComparison.java):
# CAST(col AS wider) CMP literal  ->  col CMP narrowed-literal, which
# unlocks domain pushdown into the scan for the uncast column.
# --------------------------------------------------------------------------

_CMPS = {"=", "<>", "<", "<=", ">", ">="}
_FLIP = {"<": ">", "<=": ">=", ">": "<", ">=": "<=",
         "=": "=", "<>": "<>"}
_INT_ORDER = ["tinyint", "smallint", "integer", "bigint"]
_INT_RANGE = {"tinyint": (-2 ** 7, 2 ** 7 - 1),
              "smallint": (-2 ** 15, 2 ** 15 - 1),
              "integer": (-2 ** 31, 2 ** 31 - 1),
              "bigint": (-2 ** 63, 2 ** 63 - 1)}


def _unwrap_cmp(fn: str, cast: rex.Cast, const: Const):
    """The rewritten comparison, or None when not provably safe."""
    import math
    if not isinstance(cast.arg, InputRef) or cast.safe:
        return None
    s = cast.arg.type
    t = cast.type
    v = const.value
    if v is None:
        return None
    s_name = getattr(s, "name", "")
    t_name = getattr(t, "name", "")
    if s_name in _INT_ORDER and t_name in _INT_ORDER \
            and _INT_ORDER.index(t_name) > _INT_ORDER.index(s_name):
        lo, hi = _INT_RANGE[s_name]
        if lo <= int(v) <= hi:
            return Call(fn, (cast.arg, Const(int(v), s)),
                        rex.TRUE.type)
        return None   # out-of-range: constant-fold territory, skip
    if s_name in ("tinyint", "smallint", "integer") \
            and t_name == "double":
        # bigint deliberately excluded: values above 2^53 are not exact
        # in double, so the unwrap would change results (the reference
        # rule proves round-trip exactness; int32 and below always
        # round-trip)
        fv = float(v)
        if not math.isfinite(fv):
            return None
        lo, hi = _INT_RANGE[s_name]
        if fv == math.floor(fv) and lo <= fv <= hi:
            return Call(fn, (cast.arg, Const(int(fv), s)),
                        rex.TRUE.type)
        if fn in ("<", "<=", ">", ">=") and lo <= fv <= hi:
            # non-integral bound: snap to the neighboring integer
            if fn in ("<", "<="):
                return Call("<=", (cast.arg,
                                   Const(math.floor(fv), s)),
                            rex.TRUE.type)
            return Call(">=", (cast.arg, Const(math.ceil(fv), s)),
                        rex.TRUE.type)
    return None


def _unwrap_expr(e: RowExpr) -> RowExpr:
    if isinstance(e, Call):
        args = tuple(_unwrap_expr(a) for a in e.args)
        if e.fn in _CMPS and len(args) == 2:
            a, b = args
            fn = e.fn
            if isinstance(b, rex.Cast) and isinstance(a, Const):
                a, b, fn = b, a, _FLIP[e.fn]
                out = _unwrap_cmp(fn, a, b)
            elif isinstance(a, rex.Cast) and isinstance(b, Const):
                out = _unwrap_cmp(fn, a, b)
            else:
                out = None
            if out is not None:
                return out
        if args != e.args:
            return Call(e.fn, args, e.type)
        return e
    return e


def unwrap_casts(node: PlanNode) -> PlanNode:
    srcs = node.sources
    if srcs:
        new = [unwrap_casts(s) for s in srcs]
        if any(a is not b for a, b in zip(new, srcs)):
            node = _replace_sources(node, new)
    if isinstance(node, FilterNode):
        return dc_replace(node, predicate=_unwrap_expr(node.predicate))
    if isinstance(node, JoinNode) and node.filter is not None:
        return dc_replace(node, filter=_unwrap_expr(node.filter))
    return node


# --------------------------------------------------------------------------
# SingleDistinctAggregationToGroupBy (iterative/rule/
# SingleDistinctAggregationToGroupBy.java): when EVERY aggregate is
# DISTINCT over the same argument, dedup with an inner GROUP BY and run
# plain aggregates on top — the two-level form is partial/final
# combinable, which the distributed and remote schedulers exploit.
# --------------------------------------------------------------------------

def single_distinct_to_groupby(node: PlanNode) -> PlanNode:
    from ..plan.nodes import Aggregate
    srcs = node.sources
    if srcs:
        new = [single_distinct_to_groupby(s) for s in srcs]
        if any(a is not b for a, b in zip(new, srcs)):
            node = _replace_sources(node, new)
    if not (isinstance(node, AggregationNode) and node.step == "SINGLE"
            and node.group_id_symbol is None and node.aggregates):
        return node
    aggs = node.aggregates
    if not all(a.distinct for a in aggs.values()):
        return node
    arg0 = next(iter(aggs.values())).argument
    if arg0 is None or not all(
            a.argument == arg0 and a.mask is None
            and a.argument2 is None
            and a.kind in ("count", "sum", "avg", "min", "max")
            for a in aggs.values()):
        return node
    inner_keys = tuple(dict.fromkeys(node.group_keys + (arg0,)))
    inner = AggregationNode(node.source, inner_keys, {}, "SINGLE")
    outer = {s: Aggregate(a.kind, arg0, a.type, False, None)
             for s, a in aggs.items()}
    return AggregationNode(inner, node.group_keys, outer, "SINGLE")


# --------------------------------------------------------------------------
# CreatePartialTopN / partial limit (iterative/rule/CreatePartialTopN
# .java): TopN/Limit over a UNION runs PARTIAL in every branch before
# the merge — each branch keeps only its own top n rows.
# --------------------------------------------------------------------------

def _through_projects(node: PlanNode):
    """(projects-from-top, innermost-source): the chain of row
    -preserving projections under ``node`` (TopN/Limit commute with
    them — PushLimitThroughProject)."""
    projs = []
    src = node
    while isinstance(src, ProjectNode):
        projs.append(src)
        src = src.source
    return projs, src


# rule shapes, declared with the matching engine (the reference's
# Rule.pattern() contract — lib/trino-matching; CreatePartialTopN
# declares topN().with(step SINGLE) the same way)
_TOPN_SINGLE = _Pat.type_of(TopNNode).with_prop("step", "SINGLE")
_LIMIT_FULL = _Pat.type_of(LimitNode).with_prop("partial", False)


def partial_topn_through_union(node: PlanNode) -> PlanNode:
    from ..plan.nodes import SortKey
    srcs = node.sources
    if srcs:
        new = [partial_topn_through_union(s) for s in srcs]
        if any(a is not b for a, b in zip(new, srcs)):
            node = _replace_sources(node, new)
    if _TOPN_SINGLE.match(node):
        projs, u = _through_projects(node.source)
        if isinstance(u, UnionNode):
            # remap the sort keys through the (rename) projections
            def remap(sym):
                for p in projs:
                    e = p.assignments.get(sym)
                    if not isinstance(e, InputRef):
                        return None
                    sym = e.name
                return sym
            mapped = [remap(k.symbol) for k in node.keys]
            if all(m is not None and all(m in smap
                                         for smap in u.symbol_maps)
                   for m in mapped):
                kids = []
                for child, smap in zip(u.children, u.symbol_maps):
                    ckeys = tuple(
                        SortKey(smap[m], k.ascending, k.nulls_first)
                        for m, k in zip(mapped, node.keys))
                    kids.append(TopNNode(child, node.count, ckeys,
                                         "PARTIAL"))
                rebuilt: PlanNode = dc_replace(u,
                                               children=tuple(kids))
                for p in reversed(projs):
                    rebuilt = dc_replace(p, source=rebuilt)
                return dc_replace(node, source=rebuilt, step="FINAL")
    if _LIMIT_FULL.match(node):
        projs, u = _through_projects(node.source)
        if isinstance(u, UnionNode):
            kids = tuple(LimitNode(c, node.count, True)
                         for c in u.children)
            rebuilt = dc_replace(u, children=kids)
            for p in reversed(projs):
                rebuilt = dc_replace(p, source=rebuilt)
            return dc_replace(node, source=rebuilt)
    return node
