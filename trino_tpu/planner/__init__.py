from .logical import LogicalPlanner, PlanningError  # noqa: F401
