"""Cardinality estimation + cost-based join decisions.

Reference parity: cost/ (45 files — StatsCalculator, FilterStatsCalculator,
JoinStatsRule, CostCalculatorUsingExchanges) + the cost-based rules
DetermineJoinDistributionType / ReorderJoins (SURVEY.md §2.1 "Stats &
cost"). Round-1 scope: scan row counts from connector statistics
(spi/statistics/TableStatistics analog), heuristic filter factors, and
two decisions: (a) probe/build side selection — the hash build side
should be the smaller input; (b) PARTITIONED vs REPLICATED distribution
for the distributed executor.
"""

from __future__ import annotations

from dataclasses import replace as dc_replace
from typing import Optional

from .. import rex
from ..catalog import CatalogManager
from ..plan.nodes import (AggregationNode, EnforceSingleRowNode,
                          FilterNode, JoinClause, JoinNode, LimitNode,
                          OffsetNode, PlanNode, ProjectNode, SampleNode,
                          SemiJoinNode, SetOpNode, SortNode,
                          TableScanNode, TopNNode, UnionNode, ValuesNode)
from ..rex import Call, CaseExpr, Cast, Const, InputRef

# filter selectivity heuristics (FilterStatsCalculator's defaults)
_EQ_FACTOR = 0.05
_RANGE_FACTOR = 0.35
_LIKE_FACTOR = 0.25
_OTHER_FACTOR = 0.5
# REPLICATED below this build-side estimate (DetermineJoinDistributionType)
BROADCAST_ROWS = 1_000_000.0


def estimate_rows(node: PlanNode, catalogs: CatalogManager,
                  cache: Optional[dict] = None) -> float:
    rows, _ = derive_stats(node, catalogs,
                           cache if cache is not None else {})
    return rows


def derive_stats(node: PlanNode, catalogs: CatalogManager,
                 cache: dict):
    """(row estimate, {symbol: ColumnStatistics}) per plan node —
    cost/StatsCalculator's PlanNodeStatsEstimate with per-symbol
    SymbolStatsEstimate, memoized by node identity."""
    key = id(node)
    if key in cache:
        return cache[key]
    out = _derive(node, catalogs, cache)
    cache[key] = out
    return out


def _derive(node, catalogs, cache):
    if isinstance(node, TableScanNode):
        conn = catalogs.connector(node.handle.catalog)
        est = conn.table_row_count(node.handle)
        rows = float(est) if est is not None else 10_000.0
        cols = {}
        for sym, col in node.assignments.items():
            cs = conn.column_statistics(node.handle, col)
            if cs is not None:
                cols[sym] = cs
        # a pushed-down constraint already filtered the scan
        constraint = getattr(node.handle, "constraint", None)
        if constraint is not None and not constraint.is_none:
            for col, dom in constraint.domains:
                for sym, c in node.assignments.items():
                    if c == col and sym in cols:
                        rows *= _domain_selectivity(dom, cols[sym])
        return max(rows, 1.0), cols
    if isinstance(node, FilterNode):
        rows, cols = derive_stats(node.source, catalogs, cache)
        sel, cols = _filter_stats(node.predicate, cols)
        return max(rows * sel, 1.0), cols
    if isinstance(node, ProjectNode):
        rows, cols = derive_stats(node.source, catalogs, cache)
        out = {}
        for sym, e in node.assignments.items():
            if isinstance(e, InputRef) and e.name in cols:
                out[sym] = cols[e.name]
        return rows, out
    if isinstance(node, (SortNode, SampleNode)):
        return derive_stats(node.sources[0], catalogs, cache)
    if isinstance(node, (LimitNode, TopNNode)):
        rows, cols = derive_stats(node.sources[0], catalogs, cache)
        return min(float(node.count), rows), cols
    if isinstance(node, OffsetNode):
        rows, cols = derive_stats(node.source, catalogs, cache)
        return max(rows - node.count, 0.0), cols
    if isinstance(node, AggregationNode):
        rows, cols = derive_stats(node.source, catalogs, cache)
        if not node.group_keys:
            return 1.0, {}
        ndv = 1.0
        known = True
        for k in node.group_keys:
            cs = cols.get(k)
            if cs is None:
                known = False
                break
            ndv *= max(cs.ndv, 1.0)
        est = min(ndv, rows) if known else max(rows * 0.1, 1.0)
        return max(est, 1.0), {k: v for k, v in cols.items()
                               if k in node.group_keys}
    if isinstance(node, JoinNode):
        l, lcols = derive_stats(node.left, catalogs, cache)
        r, rcols = derive_stats(node.right, catalogs, cache)
        cols = {**lcols, **rcols}
        if node.join_type == "cross" and not node.criteria:
            return l * r, cols
        if node.criteria:
            # |L ⋈ R| = |L||R| / max(ndv(l_key), ndv(r_key)) per
            # clause (cost/JoinStatsRule.java's formula)
            est = l * r
            for c in node.criteria:
                la = lcols.get(c.left) or rcols.get(c.left)
                ra = rcols.get(c.right) or lcols.get(c.right)
                denom = max((la.ndv if la else 0.0),
                            (ra.ndv if ra else 0.0), 1.0)
                if la is None and ra is None:
                    denom = max(min(l, r) * _EQ_FACTOR, 1.0)
                est /= denom
            if node.join_type in ("left", "full"):
                est = max(est, l)
            if node.join_type in ("right", "full"):
                est = max(est, r)
            return max(est, 1.0), cols
        if node.join_type == "left":
            return max(l, 1.0), cols
        return max(l, r), cols
    if isinstance(node, SemiJoinNode):
        rows, cols = derive_stats(node.source, catalogs, cache)
        return rows * 0.5, cols
    if isinstance(node, EnforceSingleRowNode):
        return 1.0, {}
    if isinstance(node, ValuesNode):
        return float(len(node.rows)), {}
    if isinstance(node, UnionNode):
        total = 0.0
        for c in node.children:
            rows, _ = derive_stats(c, catalogs, cache)
            total += rows
        return total, {}
    if isinstance(node, SetOpNode):
        return derive_stats(node.left, catalogs, cache)
    if node.sources:
        return derive_stats(node.sources[0], catalogs, cache)
    return 1_000.0, {}


def _domain_selectivity(dom, cs) -> float:
    """Fraction of a column surviving a pushed TupleDomain domain."""
    sv = dom.single_values()
    if sv is not None:
        return min(len(sv) / max(cs.ndv, 1.0), 1.0)
    if (cs.min_value is None or cs.max_value is None
            or not dom.ranges):
        return _RANGE_FACTOR
    width = max(cs.max_value - cs.min_value, 1e-9)
    frac = 0.0
    for r in dom.ranges:
        lo = cs.min_value if r.low is None else max(float(r.low),
                                                    cs.min_value)
        hi = cs.max_value if r.high is None else min(float(r.high),
                                                     cs.max_value)
        frac += max(hi - lo, 0.0) / width
    return min(max(frac, 1e-4), 1.0)


def _filter_stats(e, cols):
    """(selectivity, updated column stats) for a predicate
    (cost/FilterStatsCalculator.java: 1/ndv equality, range-fraction
    comparisons, heuristic fallbacks)."""
    factor = 1.0
    cols = dict(cols)
    for c in rex.split_conjuncts(e):
        factor *= _conjunct_selectivity(c, cols)
    return max(factor, 1e-6), cols


def _conjunct_selectivity(c, cols) -> float:
    if isinstance(c, Call):
        if c.fn == "=" and len(c.args) == 2:
            ref, const = _ref_const(c.args)
            if ref is not None and ref.name in cols:
                cs = cols[ref.name]
                cols[ref.name] = type(cs)(1.0, cs.min_value,
                                          cs.max_value)
                return 1.0 / max(cs.ndv, 1.0)
            return _EQ_FACTOR
        if c.fn in ("<", "<=", ">", ">=") and len(c.args) == 2:
            ref, const = _ref_const(c.args)
            if ref is not None and ref.name in cols \
                    and const is not None:
                cs = cols[ref.name]
                if cs.min_value is not None and \
                        cs.max_value is not None:
                    try:
                        v = float(const.value)
                    except (TypeError, ValueError):
                        return _RANGE_FACTOR
                    width = max(cs.max_value - cs.min_value, 1e-9)
                    op = c.fn if isinstance(c.args[0], InputRef) else \
                        {"<": ">", "<=": ">=", ">": "<",
                         ">=": "<="}[c.fn]
                    if op in ("<", "<="):
                        frac = (v - cs.min_value) / width
                    else:
                        frac = (cs.max_value - v) / width
                    return min(max(frac, 1e-4), 1.0)
            return _RANGE_FACTOR
        if c.fn == "like":
            return _LIKE_FACTOR
        if c.fn == "or":
            return min(_OTHER_FACTOR * 1.5, 1.0)
        if c.fn == "is_null":
            ref = c.args[0] if isinstance(c.args[0], InputRef) else None
            if ref is not None and ref.name in cols:
                return max(cols[ref.name].null_fraction, 1e-4)
            return _EQ_FACTOR
        if c.fn == "not" and isinstance(c.args[0], Call) \
                and c.args[0].fn == "is_null":
            return 1.0 - _EQ_FACTOR
        return _OTHER_FACTOR
    return _OTHER_FACTOR


def _ref_const(args):
    a, b = args
    if isinstance(a, InputRef) and isinstance(b, Const):
        return a, b
    if isinstance(b, InputRef) and isinstance(a, Const):
        return b, a
    return None, None


def reorder_joins(node: PlanNode, catalogs: CatalogManager) -> PlanNode:
    """Connectivity-first greedy join ordering over flattened inner-join
    trees (reference: iterative/rule/EliminateCrossJoins.java +
    ReorderJoins.java, reduced to one greedy pass): start from the
    largest relation (the fact-table spine), repeatedly join the
    smallest relation that an equi-edge connects to the joined set.
    Eliminates the syntactic-order cross-join blowups of comma-join
    star queries (TPC-DS q64 joins 18 relations; date_dim/demographics
    arrive before the relations that connect them)."""
    if isinstance(node, JoinNode) and node.join_type in ("inner",
                                                         "cross"):
        rels: list = []
        edges: list = []
        residuals: list = []
        _flatten_inner(node, rels, edges, residuals, catalogs)
        if len(rels) > 2:
            return _greedy_join_tree(rels, edges, residuals, catalogs)
        # fall through to generic recursion for 2-way joins
    if not node.sources:
        return node
    import dataclasses
    if dataclasses.is_dataclass(node):
        updates = {}
        for f in dataclasses.fields(node):
            v = getattr(node, f.name)
            if isinstance(v, PlanNode):
                updates[f.name] = reorder_joins(v, catalogs)
            elif isinstance(v, tuple) and v and all(
                    isinstance(x, PlanNode) for x in v):
                updates[f.name] = tuple(reorder_joins(x, catalogs)
                                        for x in v)
        if updates:
            return dc_replace(node, **updates)
    return node


def _flatten_inner(n: PlanNode, rels, edges, residuals, catalogs):
    if isinstance(n, JoinNode) and n.join_type in ("inner", "cross"):
        _flatten_inner(n.left, rels, edges, residuals, catalogs)
        _flatten_inner(n.right, rels, edges, residuals, catalogs)
        edges.extend(n.criteria)
        if n.filter is not None:
            residuals.extend(rex.split_conjuncts(n.filter))
    else:
        rels.append(reorder_joins(n, catalogs))


def _greedy_join_tree(rels, edges, residuals, catalogs) -> PlanNode:
    schemas = [set(r.output_schema()) for r in rels]
    sizes = [estimate_rows(r, catalogs) for r in rels]
    sym_rel = {s: i for i, sc in enumerate(schemas) for s in sc}
    n = len(rels)

    start = max(range(n), key=lambda i: sizes[i])
    joined = {start}
    tree: PlanNode = rels[start]
    avail = set(schemas[start])
    rem_edges = list(edges)
    rem_res = list(residuals)

    while len(joined) < n:
        cand = set()
        for e in rem_edges:
            il, ir = sym_rel[e.left], sym_rel[e.right]
            if (il in joined) != (ir in joined):
                cand.add(ir if il in joined else il)
        if not cand:
            cand = set(range(n)) - joined  # genuine cross join
        nxt = min(cand, key=lambda i: sizes[i])

        crit, keep_edges = [], []
        for e in rem_edges:
            il, ir = sym_rel[e.left], sym_rel[e.right]
            if {il, ir} <= joined | {nxt} and nxt in {il, ir}:
                crit.append(JoinClause(e.left, e.right) if il in joined
                            else JoinClause(e.right, e.left))
            else:
                keep_edges.append(e)
        rem_edges = keep_edges

        new_avail = avail | schemas[nxt]
        place, keep_res = [], []
        for c in rem_res:
            (place if rex.input_names(c) <= new_avail
             else keep_res).append(c)
        rem_res = keep_res

        tree = JoinNode(tree, rels[nxt],
                        "inner" if crit else "cross", tuple(crit),
                        rex.and_all(place) if place else None)
        joined.add(nxt)
        avail = new_avail

    if rem_res:
        tree = FilterNode(tree, rex.and_all(rem_res))
    return tree


def choose_join_sides(node: PlanNode,
                      catalogs: CatalogManager,
                      force_dist: str = "AUTOMATIC") -> PlanNode:
    """Make the smaller input the hash-build (right) side and pick the
    exchange distribution. Inner equi-joins only — outer joins keep
    their probe side (the executor flips RIGHT joins itself).
    ``force_dist`` is the join_distribution_type session property
    (SystemSessionProperties.java:53): AUTOMATIC | BROADCAST |
    PARTITIONED."""
    if isinstance(node, JoinNode):
        left = choose_join_sides(node.left, catalogs, force_dist)
        right = choose_join_sides(node.right, catalogs, force_dist)
        node = dc_replace(node, left=left, right=right)
        if node.join_type == "inner" and node.criteria:
            l_est = estimate_rows(node.left, catalogs)
            r_est = estimate_rows(node.right, catalogs)
            if l_est < r_est:
                node = JoinNode(
                    node.right, node.left, "inner",
                    tuple(JoinClause(c.right, c.left)
                          for c in node.criteria),
                    node.filter, node.distribution)
                l_est, r_est = r_est, l_est
            f = (force_dist or "AUTOMATIC").upper()
            if f == "PARTITIONED":
                dist = "partitioned"
            elif f == "BROADCAST":
                dist = "replicated"
            else:
                dist = ("replicated" if r_est <= BROADCAST_ROWS
                        else "partitioned")
            node = dc_replace(node, distribution=dist)
        return node
    if not node.sources:
        return node
    import dataclasses
    if dataclasses.is_dataclass(node):
        updates = {}
        for f in dataclasses.fields(node):
            v = getattr(node, f.name)
            if isinstance(v, PlanNode):
                updates[f.name] = choose_join_sides(v, catalogs,
                                                    force_dist)
            elif isinstance(v, tuple) and v and all(
                    isinstance(x, PlanNode) for x in v):
                updates[f.name] = tuple(
                    choose_join_sides(x, catalogs, force_dist)
                    for x in v)
        if updates:
            return dc_replace(node, **updates)
    return node
