"""Cardinality estimation + cost-based join decisions.

Reference parity: cost/ (45 files — StatsCalculator, FilterStatsCalculator,
JoinStatsRule, CostCalculatorUsingExchanges) + the cost-based rules
DetermineJoinDistributionType / ReorderJoins (SURVEY.md §2.1 "Stats &
cost"). Round-1 scope: scan row counts from connector statistics
(spi/statistics/TableStatistics analog), heuristic filter factors, and
two decisions: (a) probe/build side selection — the hash build side
should be the smaller input; (b) PARTITIONED vs REPLICATED distribution
for the distributed executor.
"""

from __future__ import annotations

from dataclasses import replace as dc_replace
from typing import Optional

from .. import rex
from ..catalog import CatalogManager
from ..plan.nodes import (AggregationNode, EnforceSingleRowNode,
                          FilterNode, JoinClause, JoinNode, LimitNode,
                          OffsetNode, PlanNode, ProjectNode, SampleNode,
                          SemiJoinNode, SetOpNode, SortNode,
                          TableScanNode, TopNNode, UnionNode, ValuesNode)
from ..rex import Call, CaseExpr, Cast, Const, InputRef

# filter selectivity heuristics (FilterStatsCalculator's defaults)
_EQ_FACTOR = 0.05
_RANGE_FACTOR = 0.35
_LIKE_FACTOR = 0.25
_OTHER_FACTOR = 0.5
# REPLICATED below this build-side estimate (DetermineJoinDistributionType)
BROADCAST_ROWS = 1_000_000.0


def estimate_rows(node: PlanNode, catalogs: CatalogManager) -> float:
    if isinstance(node, TableScanNode):
        conn = catalogs.connector(node.handle.catalog)
        est = conn.table_row_count(node.handle)
        return float(est) if est is not None else 10_000.0
    if isinstance(node, FilterNode):
        return estimate_rows(node.source, catalogs) * \
            _selectivity(node.predicate)
    if isinstance(node, (ProjectNode, SortNode, SampleNode)):
        return estimate_rows(node.sources[0], catalogs)
    if isinstance(node, (LimitNode, TopNNode)):
        return min(float(node.count),
                   estimate_rows(node.sources[0], catalogs))
    if isinstance(node, OffsetNode):
        return max(estimate_rows(node.source, catalogs) - node.count, 0.0)
    if isinstance(node, AggregationNode):
        child = estimate_rows(node.source, catalogs)
        if not node.group_keys:
            return 1.0
        return max(child * 0.1, 1.0)
    if isinstance(node, JoinNode):
        l = estimate_rows(node.left, catalogs)
        r = estimate_rows(node.right, catalogs)
        if node.join_type == "cross" and not node.criteria:
            return l * r
        if node.join_type == "left":
            return max(l, 1.0)
        # FK-join assumption: output ~ the larger side
        return max(l, r)
    if isinstance(node, SemiJoinNode):
        return estimate_rows(node.source, catalogs)
    if isinstance(node, EnforceSingleRowNode):
        return 1.0
    if isinstance(node, ValuesNode):
        return float(len(node.rows))
    if isinstance(node, UnionNode):
        return sum(estimate_rows(c, catalogs) for c in node.children)
    if isinstance(node, SetOpNode):
        return estimate_rows(node.left, catalogs)
    if node.sources:
        return estimate_rows(node.sources[0], catalogs)
    return 1_000.0


def _selectivity(e) -> float:
    factor = 1.0
    for c in rex.split_conjuncts(e):
        if isinstance(c, Call):
            if c.fn == "=":
                factor *= _EQ_FACTOR
            elif c.fn in ("<", "<=", ">", ">="):
                factor *= _RANGE_FACTOR
            elif c.fn == "like":
                factor *= _LIKE_FACTOR
            elif c.fn == "or":
                factor *= min(_OTHER_FACTOR * 1.5, 1.0)
            else:
                factor *= _OTHER_FACTOR
        else:
            factor *= _OTHER_FACTOR
    return max(factor, 1e-4)


def reorder_joins(node: PlanNode, catalogs: CatalogManager) -> PlanNode:
    """Connectivity-first greedy join ordering over flattened inner-join
    trees (reference: iterative/rule/EliminateCrossJoins.java +
    ReorderJoins.java, reduced to one greedy pass): start from the
    largest relation (the fact-table spine), repeatedly join the
    smallest relation that an equi-edge connects to the joined set.
    Eliminates the syntactic-order cross-join blowups of comma-join
    star queries (TPC-DS q64 joins 18 relations; date_dim/demographics
    arrive before the relations that connect them)."""
    if isinstance(node, JoinNode) and node.join_type in ("inner",
                                                         "cross"):
        rels: list = []
        edges: list = []
        residuals: list = []
        _flatten_inner(node, rels, edges, residuals, catalogs)
        if len(rels) > 2:
            return _greedy_join_tree(rels, edges, residuals, catalogs)
        # fall through to generic recursion for 2-way joins
    if not node.sources:
        return node
    import dataclasses
    if dataclasses.is_dataclass(node):
        updates = {}
        for f in dataclasses.fields(node):
            v = getattr(node, f.name)
            if isinstance(v, PlanNode):
                updates[f.name] = reorder_joins(v, catalogs)
            elif isinstance(v, tuple) and v and all(
                    isinstance(x, PlanNode) for x in v):
                updates[f.name] = tuple(reorder_joins(x, catalogs)
                                        for x in v)
        if updates:
            return dc_replace(node, **updates)
    return node


def _flatten_inner(n: PlanNode, rels, edges, residuals, catalogs):
    if isinstance(n, JoinNode) and n.join_type in ("inner", "cross"):
        _flatten_inner(n.left, rels, edges, residuals, catalogs)
        _flatten_inner(n.right, rels, edges, residuals, catalogs)
        edges.extend(n.criteria)
        if n.filter is not None:
            residuals.extend(rex.split_conjuncts(n.filter))
    else:
        rels.append(reorder_joins(n, catalogs))


def _greedy_join_tree(rels, edges, residuals, catalogs) -> PlanNode:
    schemas = [set(r.output_schema()) for r in rels]
    sizes = [estimate_rows(r, catalogs) for r in rels]
    sym_rel = {s: i for i, sc in enumerate(schemas) for s in sc}
    n = len(rels)

    start = max(range(n), key=lambda i: sizes[i])
    joined = {start}
    tree: PlanNode = rels[start]
    avail = set(schemas[start])
    rem_edges = list(edges)
    rem_res = list(residuals)

    while len(joined) < n:
        cand = set()
        for e in rem_edges:
            il, ir = sym_rel[e.left], sym_rel[e.right]
            if (il in joined) != (ir in joined):
                cand.add(ir if il in joined else il)
        if not cand:
            cand = set(range(n)) - joined  # genuine cross join
        nxt = min(cand, key=lambda i: sizes[i])

        crit, keep_edges = [], []
        for e in rem_edges:
            il, ir = sym_rel[e.left], sym_rel[e.right]
            if {il, ir} <= joined | {nxt} and nxt in {il, ir}:
                crit.append(JoinClause(e.left, e.right) if il in joined
                            else JoinClause(e.right, e.left))
            else:
                keep_edges.append(e)
        rem_edges = keep_edges

        new_avail = avail | schemas[nxt]
        place, keep_res = [], []
        for c in rem_res:
            (place if rex.input_names(c) <= new_avail
             else keep_res).append(c)
        rem_res = keep_res

        tree = JoinNode(tree, rels[nxt],
                        "inner" if crit else "cross", tuple(crit),
                        rex.and_all(place) if place else None)
        joined.add(nxt)
        avail = new_avail

    if rem_res:
        tree = FilterNode(tree, rex.and_all(rem_res))
    return tree


def choose_join_sides(node: PlanNode,
                      catalogs: CatalogManager,
                      force_dist: str = "AUTOMATIC") -> PlanNode:
    """Make the smaller input the hash-build (right) side and pick the
    exchange distribution. Inner equi-joins only — outer joins keep
    their probe side (the executor flips RIGHT joins itself).
    ``force_dist`` is the join_distribution_type session property
    (SystemSessionProperties.java:53): AUTOMATIC | BROADCAST |
    PARTITIONED."""
    if isinstance(node, JoinNode):
        left = choose_join_sides(node.left, catalogs, force_dist)
        right = choose_join_sides(node.right, catalogs, force_dist)
        node = dc_replace(node, left=left, right=right)
        if node.join_type == "inner" and node.criteria:
            l_est = estimate_rows(node.left, catalogs)
            r_est = estimate_rows(node.right, catalogs)
            if l_est < r_est:
                node = JoinNode(
                    node.right, node.left, "inner",
                    tuple(JoinClause(c.right, c.left)
                          for c in node.criteria),
                    node.filter, node.distribution)
                l_est, r_est = r_est, l_est
            f = (force_dist or "AUTOMATIC").upper()
            if f == "PARTITIONED":
                dist = "partitioned"
            elif f == "BROADCAST":
                dist = "replicated"
            else:
                dist = ("replicated" if r_est <= BROADCAST_ROWS
                        else "partitioned")
            node = dc_replace(node, distribution=dist)
        return node
    if not node.sources:
        return node
    import dataclasses
    if dataclasses.is_dataclass(node):
        updates = {}
        for f in dataclasses.fields(node):
            v = getattr(node, f.name)
            if isinstance(v, PlanNode):
                updates[f.name] = choose_join_sides(v, catalogs,
                                                    force_dist)
            elif isinstance(v, tuple) and v and all(
                    isinstance(x, PlanNode) for x in v):
                updates[f.name] = tuple(
                    choose_join_sides(x, catalogs, force_dist)
                    for x in v)
        if updates:
            return dc_replace(node, **updates)
    return node
