"""``trino-tpu-verifier`` console entry: replay a query file against
two HTTP endpoints (service/trino-verifier's CLI shape)."""

from __future__ import annotations

import argparse
import sys


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="trino-tpu-verifier")
    ap.add_argument("--control", required=True,
                    help="control coordinator URI")
    ap.add_argument("--test", required=True,
                    help="test coordinator URI")
    ap.add_argument("--queries", required=True,
                    help="file of queries, ';'-separated")
    args = ap.parse_args(argv)

    from .client import StatementClient
    from .verifier import Verifier, report
    with open(args.queries) as f:
        text = f.read()
    queries = [q.strip() for q in text.split(";") if q.strip()]
    v = Verifier(StatementClient(args.control),
                 StatementClient(args.test))
    results = v.run_suite(queries)
    print(report(results))
    bad = sum(1 for r in results if r.status not in ("MATCH",))
    return 1 if bad else 0


if __name__ == "__main__":
    raise SystemExit(main())
