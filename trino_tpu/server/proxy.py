"""Reverse proxy for the client protocol.

Reference parity: service/trino-proxy (ProxyResource.java — forwards
/v1/statement and result pages to a backing coordinator, rewriting
nextUri so clients keep talking to the proxy). JWT request signing is
replaced by an optional shared-secret header check."""

from __future__ import annotations

import json
import threading
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import urlparse


class Proxy:
    def __init__(self, backend_uri: str, port: int = 0,
                 shared_secret: Optional[str] = None):
        self.backend = backend_uri.rstrip("/")
        self.shared_secret = shared_secret
        self._httpd = ThreadingHTTPServer(("127.0.0.1", port),
                                          _make_handler(self))
        self.port = self._httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    @property
    def base_uri(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    def start(self) -> "Proxy":
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._httpd.shutdown()

    def rewrite(self, payload: bytes) -> bytes:
        """Point nextUri/infoUri back at the proxy."""
        try:
            obj = json.loads(payload)
        except ValueError:
            return payload
        for key in ("nextUri", "infoUri", "partialCancelUri"):
            if key in obj and isinstance(obj[key], str):
                obj[key] = obj[key].replace(self.backend, self.base_uri)
        return json.dumps(obj).encode()


def _make_handler(proxy: Proxy):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, *args):
            pass

        def _check_secret(self) -> bool:
            if proxy.shared_secret is None:
                return True
            if self.headers.get("X-Proxy-Secret") == \
                    proxy.shared_secret:
                return True
            body = b'{"error": "Forbidden"}'
            self.send_response(403)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return False

        def _forward(self, method: str):
            if not self._check_secret():
                return
            target = proxy.backend + self.path
            n = int(self.headers.get("Content-Length", 0) or 0)
            data = self.rfile.read(n) if n else None
            req = urllib.request.Request(target, data=data,
                                         method=method)
            for h in ("X-Trino-User", "X-Trino-Catalog",
                      "X-Trino-Schema", "X-Trino-Session",
                      "X-Trino-Source", "X-Trino-Prepared-Statement",
                      "Authorization", "Content-Type"):
                if self.headers.get(h):
                    req.add_header(h, self.headers[h])
            try:
                with urllib.request.urlopen(req, timeout=60) as r:
                    body = proxy.rewrite(r.read())
                    code = r.status
            except urllib.error.HTTPError as e:
                body = e.read()
                code = e.code
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            if body:
                self.wfile.write(body)

        def do_GET(self):
            self._forward("GET")

        def do_POST(self):
            self._forward("POST")

        def do_DELETE(self):
            self._forward("DELETE")

    return Handler
