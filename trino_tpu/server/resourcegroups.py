"""Hierarchical resource groups: admission control for query dispatch.

Reference parity: execution/resourcegroups/InternalResourceGroup.java
(hard_concurrency / max_queued enforcement, subgroup trees, fair and
weighted_fair scheduling) + InternalResourceGroupManager +
spi/resourcegroups (SelectionCriteria) + the file-based config plugin
(plugin/trino-resource-group-managers). Redesigned small: groups are an
explicit tree of ``ResourceGroup``s; selectors match (user, source) to a
leaf; a leaf admits a query immediately (below hard_concurrency), queues
it (below max_queued), or rejects it. Limits aggregate up the tree —
a query runs only if EVERY ancestor has capacity, exactly the
reference's canRunMore recursion."""

from __future__ import annotations

import re
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Deque, Dict, List, Optional, Tuple

from ..obs.metrics import QUERY_QUEUED_SECONDS, QUEUE_REJECTIONS


class QueryQueueFullError(Exception):
    """StandardErrorCode.QUERY_QUEUE_FULL (Appendix A.8).
    ``error_name`` feeds errors.classify so the rejection reaches the
    client with the Trino name + INSUFFICIENT_RESOURCES type instead
    of a generic failure."""

    error_name = "QUERY_QUEUE_FULL"


@dataclass
class ResourceGroup:
    """One node of the group tree (InternalResourceGroup.java)."""
    name: str
    hard_concurrency: int = 100
    max_queued: int = 1000
    scheduling_weight: int = 1
    # per-group memory budget for the cluster pool (server/memory.py):
    # when the group's aggregate reservation exceeds it, the
    # low-memory killer cancels the group's largest query. 0 = none.
    soft_memory_limit_bytes: int = 0
    parent: Optional["ResourceGroup"] = None
    children: Dict[str, "ResourceGroup"] = field(default_factory=dict)

    # runtime state
    running: int = 0
    # (tag, start_fn, enqueued_at) — the timestamp feeds the
    # queued-time histogram at dequeue
    _queue: Deque[Tuple[object, Callable[[], None], float]] = \
        field(default_factory=deque)

    @property
    def full_name(self) -> str:
        if self.parent is None:
            return self.name
        return f"{self.parent.full_name}.{self.name}"

    def add(self, child: "ResourceGroup") -> "ResourceGroup":
        child.parent = self
        self.children[child.name] = child
        return child

    # --- admission (called under the manager lock) -----------------------
    def _can_run_more(self) -> bool:
        g: Optional[ResourceGroup] = self
        while g is not None:
            if g.running >= g.hard_concurrency:
                return False
            g = g.parent
        return True

    def _start(self) -> None:
        g: Optional[ResourceGroup] = self
        while g is not None:
            g.running += 1
            g = g.parent

    def _finish_one(self) -> None:
        g: Optional[ResourceGroup] = self
        while g is not None:
            g.running = max(0, g.running - 1)
            g = g.parent

    def queued(self) -> int:
        return len(self._queue)


class ResourceGroupManager:
    """InternalResourceGroupManager: selector routing + dispatch.

    ``submit(session_user, source, start_fn)`` either calls start_fn
    immediately, enqueues it for later, or raises QueryQueueFullError.
    ``query_finished(group)`` releases the slot and starts the next
    queued query (weighted-fair across sibling leaves: the eligible leaf
    with the smallest running/weight ratio dequeues first — the
    WeightedFairQueue policy)."""

    def __init__(self, root: Optional[ResourceGroup] = None):
        self.root = root or ResourceGroup("global")
        self._selectors: List[Tuple[Optional[re.Pattern],
                                    Optional[re.Pattern],
                                    ResourceGroup]] = []
        self._lock = threading.Lock()

    # --- configuration ---------------------------------------------------
    def add_selector(self, group: ResourceGroup,
                     user_regex: Optional[str] = None,
                     source_regex: Optional[str] = None) -> None:
        self._selectors.append(
            (re.compile(user_regex) if user_regex else None,
             re.compile(source_regex) if source_regex else None,
             group))

    @staticmethod
    def from_config(config: dict) -> "ResourceGroupManager":
        """Build from a dict mirroring the file-based manager's JSON
        (resource-group-managers file plugin): {"rootGroups": [...],
        "selectors": [{"user": "...", "group": "a.b"}]}."""
        mgr = ResourceGroupManager()

        def build(spec: dict, parent: ResourceGroup) -> None:
            g = parent.add(ResourceGroup(
                spec["name"],
                hard_concurrency=spec.get("hardConcurrencyLimit", 100),
                max_queued=spec.get("maxQueued", 1000),
                scheduling_weight=spec.get("schedulingWeight", 1),
                soft_memory_limit_bytes=int(
                    spec.get("softMemoryLimitBytes", 0))))
            for sub in spec.get("subGroups", []):
                build(sub, g)

        for spec in config.get("rootGroups", []):
            build(spec, mgr.root)
        for sel in config.get("selectors", []):
            g = mgr.root
            for part in sel["group"].split("."):
                g = g.children[part]
            mgr.add_selector(g, sel.get("user"), sel.get("source"))
        return mgr

    # --- routing ---------------------------------------------------------
    def select(self, user: str, source: str = "") -> ResourceGroup:
        for urx, srx, group in self._selectors:
            if urx is not None and not urx.fullmatch(user or ""):
                continue
            if srx is not None and not srx.fullmatch(source or ""):
                continue
            return group
        return self.root

    # --- dispatch --------------------------------------------------------
    def submit(self, user: str, source: str,
               start_fn: Callable[[ResourceGroup], None],
               tag: object = None) -> Tuple[ResourceGroup, bool]:
        """Returns (group, started). ``start_fn(group)`` receives the
        admitting group BEFORE any query work can begin — the caller
        must record it before launching the query thread, else a
        fast-finishing query races the assignment and leaks the
        concurrency slot. When not started, the query is queued and
        start_fn fires on a later query_finished."""
        with self._lock:
            group = self.select(user, source)
            if group._can_run_more():
                group._start()
                started = True
            elif group.queued() >= group.max_queued:
                QUEUE_REJECTIONS.inc()
                raise QueryQueueFullError(
                    f"Too many queued queries for "
                    f"\"{group.full_name}\"")
            else:
                group._queue.append((tag, start_fn, time.monotonic()))
                started = False
        if started:
            start_fn(group)
        return group, started

    def remove_queued(self, tag: object) -> bool:
        """Withdraw a still-queued query (deadline-killed or canceled
        before admission). Without this a dead entry keeps consuming
        ``max_queued`` capacity until some completion dequeues it —
        and then burns a real concurrency slot starting a query that
        will never run."""
        with self._lock:
            for g in self._walk(self.root):
                for item in g._queue:
                    if item[0] == tag:
                        g._queue.remove(item)
                        return True
        return False

    def query_finished(self, group: ResourceGroup) -> None:
        to_start: List[Tuple[Callable, ResourceGroup]] = []
        with self._lock:
            group._finish_one()
            # weighted-fair pick among leaves with queued work, lowest
            # running/weight first (WeightedFairQueue.java); within a
            # leaf the queue drains FIFO — arrival order is the
            # fairness contract queued clients observe
            while True:
                candidates = [g for g in self._walk(self.root)
                              if g.queued() and g._can_run_more()]
                if not candidates:
                    break
                g = min(candidates,
                        key=lambda x: x.running / max(
                            x.scheduling_weight, 1))
                _, fn, enq = g._queue.popleft()
                QUERY_QUEUED_SECONDS.observe(time.monotonic() - enq)
                g._start()
                to_start.append((fn, g))
        for fn, g in to_start:
            fn(g)

    def _walk(self, g: ResourceGroup):
        yield g
        for c in g.children.values():
            yield from self._walk(c)

    def info(self) -> List[dict]:
        """system.runtime-style group states (ResourceGroupInfo)."""
        with self._lock:
            return [{"name": g.full_name, "running": g.running,
                     "queued": g.queued(),
                     "hardConcurrencyLimit": g.hard_concurrency,
                     "maxQueued": g.max_queued,
                     "softMemoryLimitBytes": g.soft_memory_limit_bytes}
                    for g in self._walk(self.root)]
