"""Coordinator: the client-facing HTTP control plane.

Reference parity: the dispatch + statement resources —
dispatcher/QueuedStatementResource.java:93 (POST /v1/statement),
server/protocol/ExecutingStatementResource.java:76
(/v1/statement/executing), QueryResults paging with nextUri tokens
(client/trino-client/.../StatementClientV1.java:324-336), /v1/info and
/v1/query (server/QueryResource.java), X-Trino-* headers
(ProtocolHeaders.java:24). Implemented on the stdlib ThreadingHTTPServer
— the engine below it is the in-process mesh runtime, so there is no
separate worker fleet to dispatch to over HTTP: a "stage" of remote
tasks is the SPMD program of exec/distributed.py (SURVEY.md §7.4/§7.5;
multi-host DCN dispatch is the designed extension point).
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import time
import traceback
import uuid
from dataclasses import dataclass, field
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional
from urllib.parse import urlparse

from ..obs.metrics import METRICS
from ..runner import LocalQueryRunner, QueryResult
from ..session import Session

PAGE_ROWS = 4096     # rows per QueryResults page

# query lifecycle counters (reference: QueryManager JMX stats). One
# increment per state ENTERED, so rates and totals are both readable.
_M_STATES = METRICS.counter(
    "trino_tpu_query_states_total",
    "Query state transitions by state entered", ("state",))
_M_DETAIL_PLAN_ERRORS = METRICS.counter(
    "trino_tpu_query_detail_plan_errors_total",
    "Failures re-deriving a plan for /v1/query/{id} (legacy fallback "
    "path; the plan is normally captured at execution time)")
# live worker membership (the discovery-service join/leave surface)
_M_WORKER_JOINS = METRICS.counter(
    "trino_tpu_worker_joins_total",
    "Workers added to the active set via /v1/announcement")
_M_WORKER_LEAVES = METRICS.counter(
    "trino_tpu_worker_leaves_total",
    "Workers removed from the active set via /v1/announcement")

# one wire encoding for live serving and spooled-result persistence —
# a recovered page must be byte-for-byte what the original coordinator
# would have served (fte/recovery.py owns the definition)
from ..fte.recovery import _M_RESULTS_RECOVERED  # noqa: E402
from ..fte.recovery import json_value as _json_value  # noqa: E402


@dataclass
class _Query:
    """Per-query state machine (execution/QueryStateMachine.java:
    QUEUED -> RUNNING -> FINISHED | FAILED | CANCELED). State
    transitions are lock-protected: the run thread and the cancel path
    race (VERDICT r2 weak #9)."""
    query_id: str
    slug: str
    sql: str
    session: Session
    state: str = "QUEUED"
    error: Optional[dict] = None
    result: Optional[QueryResult] = None
    created: float = field(default_factory=time.time)
    started: Optional[float] = None   # admission granted (left queue)
    ended: Optional[float] = None     # set at terminal transition
    source: str = ""
    group: Optional[object] = None   # assigned ResourceGroup
    # True when admission actually queued this query (gates the
    # post-hoc "queued" span: an immediately-admitted query's span
    # tree stays parse/plan/optimize/execute)
    admission_queued: bool = False
    # monotonic submit stamp: query_max_run_time budgets the WHOLE
    # run including queued time (the reference's QUERY_MAX_RUN_TIME,
    # as opposed to max_execution_time), so the deadline anchors here
    submit_mono: float = field(default_factory=time.monotonic)
    # the armed deadline timer (set at SUBMIT, not at dequeue: a query
    # that spends its whole budget QUEUED must die at t=limit, like
    # the reference's enforceTimeLimits covering queued queries)
    deadline_timer: Optional[threading.Timer] = None
    _done: threading.Event = field(default_factory=threading.Event)
    _cancel: threading.Event = field(default_factory=threading.Event)
    _state_lock: threading.Lock = field(default_factory=threading.Lock)

    def _transition(self, new_state: str) -> bool:
        """Move to a terminal/running state unless already terminal."""
        with self._state_lock:
            if self.state in ("FINISHED", "FAILED", "CANCELED"):
                return False
            self.state = new_state
            return True

    def run(self, runner_factory, on_result=None, on_discard=None):
        if not self._transition("RUNNING"):
            return
        # the executor polls this event between plan nodes, so cancel
        # actually interrupts execution rather than just flipping state
        self.session.cancel = self._cancel  # tt-lint: ignore[race-attr-write] run-thread setup; only this thread's executor reads session.cancel
        try:
            runner = runner_factory(self.session)
            result = runner.execute(self.sql)
            persisted = False
            if on_result is not None and self.state == "RUNNING":
                # durability-before-publication: the restart-recovery
                # persist completes BEFORE any client can observe
                # FINISHED, so "the client saw the query finish"
                # implies "its results are re-pullable". Skipped once
                # a cancel landed — a CANCELED query's results must
                # never become recoverable-as-FINISHED.
                try:
                    persisted = bool(on_result(self, result))
                except Exception:        # noqa: BLE001 — best-effort
                    pass
            if self._transition("FINISHED"):
                self.result = result  # tt-lint: ignore[race-attr-write] sole writer (transition winner); readers tolerate the pre-publication None (query_results re-polls)
            elif persisted and on_discard is not None:
                # cancel raced the persist between the state check and
                # the transition: the query ends CANCELED, so the
                # just-spooled results must not outlive it
                try:
                    on_discard(self)
                except Exception:        # noqa: BLE001
                    pass
        except Exception as e:   # error taxonomy: Appendix A.8
            if self._cancel.is_set() or not self._transition("FAILED"):
                return
            from ..errors import classify
            ename, ecode, etype = classify(e)
            self.error = {  # tt-lint: ignore[race-attr-write] sole writer (FAILED-transition winner); readers see None until _done gates them
                "message": str(e),
                "errorCode": ecode,
                "errorName": ename,
                "errorType": etype,
                "failureInfo": {"type": type(e).__name__,
                                "stack": traceback.format_exc()
                                .splitlines()[-5:]},
            }
        finally:
            if self.ended is None:
                self.ended = time.time()  # tt-lint: ignore[race-attr-write] benign last-write with do_cancel's stamp; both are wall-clock end times
            self._done.set()

    def _retire_deadline_timer(self):
        """A terminal query never needs its armed deadline timer again;
        leaving it would pin this query (and its Session) in a sleeping
        Timer thread for up to query_max_run_time — per canceled
        queued query, under exactly the overload this layer is for.
        (Timer.cancel from within its own callback is a no-op.)"""
        if self.deadline_timer is not None:
            self.deadline_timer.cancel()

    def do_cancel(self):
        self._cancel.set()
        if self._transition("CANCELED"):
            if self.ended is None:
                self.ended = time.time()  # tt-lint: ignore[race-attr-write] benign last-write with run's finally stamp; both are wall-clock end times
            self._done.set()
        self._retire_deadline_timer()

    def kill(self, message: str,
             error_name: str = "ADMINISTRATIVELY_KILLED") -> bool:
        """Engine-initiated termination (low-memory killer, deadline
        breach): unlike a user cancel this is a FAILURE carrying a
        specific error identity — the client must learn WHY the
        engine stopped its query, not just that it stopped. Sets the
        cancel event so the executor and every remote page pull /
        status watch abort their in-flight work cooperatively."""
        from ..errors import error_info
        code, etype = error_info(error_name)
        with self._state_lock:
            if self.state in ("FINISHED", "FAILED", "CANCELED"):
                return False
            self.state = "FAILED"
            self.error = {"message": message, "errorCode": code,
                          "errorName": error_name, "errorType": etype}
        self._cancel.set()
        if self.ended is None:
            self.ended = time.time()  # tt-lint: ignore[race-attr-write] benign last-write with run's finally stamp; both are wall-clock end times
        self._done.set()
        self._retire_deadline_timer()
        return True

    def wait_done(self, timeout: float) -> bool:
        return self._done.wait(timeout)


class QueryTracker:
    """dispatcher/DispatchManager + execution/QueryTracker: owns every
    query's lifecycle; one executor thread per query. Dispatch routes
    through the resource-group manager (admission control:
    dispatcher/DispatchManager.java:183 selectGroup) and emits
    lifecycle events (event/QueryMonitor.java:130,206)."""

    def __init__(self, make_runner, events=None, resource_groups=None,
                 result_store=None, memory=None, manifest_store=None,
                 history_sink=None):
        from .events import EventListenerManager
        self._queries: Dict[str, _Query] = {}
        self._lock = threading.Lock()
        self._counter = itertools.count(1)
        # per-tracker instance token baked into every query id (the
        # reference id's trailing coordinator component,
        # QueryId "yyyyMMdd_HHmmss_index_coordId"): the counter resets
        # with the process, so two coordinators started within the
        # same wall-clock second would otherwise mint COLLIDING ids —
        # and colliding ids share one spool directory, letting query
        # A's persisted results shadow query B's execution manifest
        self._instance = uuid.uuid4().hex[:5]
        self._make_runner = make_runner
        self.events = events or EventListenerManager()
        self.groups = resource_groups
        # cluster memory governance (server/memory.py
        # ClusterMemoryManager): every dispatched query registers a
        # reservation context (fed by Executor._reserve) with its
        # group's soft limit and a kill callback — the low-memory
        # killer's handle on the query
        self.memory = memory
        # coordinator-restart recovery (fte/recovery.py): finished
        # queries persist their combine output + manifest here so a
        # client can re-pull results from a NEW coordinator process
        self.results = result_store
        # mid-flight failover (fte/recovery.py ExecutionManifestStore):
        # execution manifests spooled at dispatch time, released here
        # once the query is terminal (any state — a finished, failed or
        # canceled query must not be resumable by a later coordinator)
        self.manifests = manifest_store
        # terminal-query observability (obs/history.py): called with
        # the query after EVERY terminal transition — normal runs AND
        # admission rejections — so the history store sees FINISHED,
        # FAILED, CANCELED and QUEUE_FULL alike
        self.history_sink = history_sink

    def submit(self, sql: str, session: Session,
               source: str = "") -> _Query:
        from .events import QueryCreatedEvent
        qid = (time.strftime("%Y%m%d_%H%M%S") +
               f"_{next(self._counter):05d}_{self._instance}")
        q = _Query(qid, uuid.uuid4().hex[:16], sql, session)
        q.source = source
        # stamp the session so the executor's split-completion path and
        # the trace spans carry the coordinator query id and can fan
        # out SplitCompletedEvents through this tracker's listeners
        session.query_id = qid
        session.events = self.events
        with self._lock:
            self._queries[qid] = q
        _M_STATES.inc(state="QUEUED")
        self.events.query_created(QueryCreatedEvent(
            qid, sql, session.user, session.catalog, session.schema))
        self._arm_deadline(q, session)
        self._launch(q, session, source)
        return q

    def submit_resumed(self, q: _Query, runner_factory) -> _Query:
        """Register and dispatch an already-rebuilt query — the
        mid-flight half of coordinator failover (Coordinator.
        resume_query built ``q`` from the spooled execution manifest
        with its ORIGINAL id, slug, sql, session and submit/start
        times). First registration wins: two clients whose polls both
        miss must converge on ONE resumed execution. The returned
        query is the registered one (which may be a concurrent
        winner's, or even a plain recover_query entry that landed
        first).

        Resumption goes through the full admission path: the deadline
        re-arms against the ORIGINAL submit time (a resume must not
        extend query_max_run_time) and ``_launch`` routes through the
        resource-group manager and cluster memory registration exactly
        like a fresh submit — a failed-over query competes for slots,
        it does not jump the queue."""
        from .events import QueryCreatedEvent
        session = q.session
        session.query_id = q.query_id
        session.events = self.events
        with self._lock:
            registered = self._queries.setdefault(q.query_id, q)
        if registered is not q:
            return registered
        _M_STATES.inc(state="QUEUED")
        self.events.query_created(QueryCreatedEvent(
            q.query_id, q.sql, session.user, session.catalog,
            session.schema))
        self._arm_deadline(q, session)
        self._launch(q, session, q.source, runner_factory=runner_factory)
        return q

    def _arm_deadline(self, q: _Query, session: Session) -> None:
        limit = int(session.get("query_max_run_time") or 0)
        if limit > 0:
            # QUERY_MAX_RUN_TIME enforcement, armed at SUBMIT: the
            # budget covers the whole run INCLUDING queued time, as an
            # absolute deadline — a query that burns its budget
            # sitting QUEUED dies at t=limit, not at dequeue+limit.
            # The session carries the deadline so the executor
            # (between plan nodes), the remote scheduler (attempt
            # timeouts, retry/speculation grants, backoff), and
            # worker-side executors (deadline_s in the task payload)
            # all enforce the same shrinking budget; the timer is the
            # coordinator-side backstop that fails the query with
            # EXCEEDED_TIME_LIMIT and — via the cancel event — aborts
            # in-flight remote attempts on workers instead of waiting
            # for the next client poll.
            from ..obs.metrics import DEADLINE_CANCELS
            session.deadline = q.submit_mono + limit

            def deadline_fire():
                if q.kill(
                        f"Query exceeded the maximum run time of "
                        f"{limit}s (query_max_run_time)",
                        "EXCEEDED_TIME_LIMIT"):
                    DEADLINE_CANCELS.inc()
                    self._withdraw_if_queued(q)

            q.deadline_timer = threading.Timer(
                max(session.deadline - time.monotonic(), 0.001),
                deadline_fire)
            q.deadline_timer.daemon = True
            q.deadline_timer.start()

    def _launch(self, q: _Query, session: Session, source: str,
                runner_factory=None) -> None:
        """Admission + execution of one registered query:
        resource-group routing, memory registration, the run thread,
        and every piece of terminal bookkeeping. ``runner_factory``
        (default: the coordinator's) lets a failover resume substitute
        a manifest-driven runner without forking this machinery."""
        from .events import QueryCompletedEvent
        from .resourcegroups import QueryQueueFullError
        qid = q.query_id

        def run_and_release():
            if q.started is None:
                # resumed queries arrive with the ORIGINAL admission
                # stamp from the manifest — queued/elapsed accounting
                # must span coordinators, not reset per process
                q.started = time.time()  # tt-lint: ignore[race-attr-write] single stamp before the query publishes; readers tolerate None
            if q.group is not None:
                # the admitting group's identity + scheduling weight
                # ride the session so remote/stage task payloads carry
                # them into the WORKER's shared split scheduler
                # (exec/taskexec.py fair-share drain by group)
                session.resource_group = getattr(
                    q.group, "full_name", "global")
                session.resource_group_weight = float(
                    getattr(q.group, "scheduling_weight", 1) or 1)
            if self.memory is not None:
                # cluster memory governance: the pool ledger tracks
                # this query from first reservation to completion; the
                # group's soft limit and the per-query cap ride along
                session.memory = self.memory.register(
                    qid,
                    group=getattr(q.group, "full_name", "global")
                    if q.group is not None else "global",
                    kill_fn=q.kill,
                    group_limit_bytes=getattr(
                        q.group, "soft_memory_limit_bytes", 0) or 0
                    if q.group is not None else 0,
                    query_limit_bytes=int(
                        session.get("query_max_memory") or 0))
            _M_STATES.inc(state="RUNNING")
            persist = discard = None
            if self.results is not None:
                def persist(query, result):
                    # durable results: spool the combine output + a
                    # minimal manifest so a restarted coordinator can
                    # serve this query's re-pulls
                    return self.results.persist(
                        query.query_id, query.slug, query.sql,
                        query.session.user, result)

                def discard(query):
                    # cancel won the race against the persist: reap
                    # the entry so it cannot be recovered as FINISHED
                    self.results.release(query.query_id)
            try:
                q.run(runner_factory or self._make_runner,
                      on_result=persist, on_discard=discard)
            finally:
                if q.deadline_timer is not None:
                    q.deadline_timer.cancel()
                if self.manifests is not None:
                    # terminal in ANY state: the execution manifest
                    # exists only to let another coordinator finish a
                    # RUNNING query — once this one reached a verdict
                    # the manifest must not outlive it. The spooled
                    # RESULT (fragment -1) survives; release_fragment
                    # drops only f-2.
                    self.manifests.release(qid)
                if self.memory is not None:
                    self.memory.unregister(qid)
                    session.memory = None
                if q.group is not None and self.groups is not None:
                    self.groups.query_finished(q.group)
                # queue-wait span: grafted post-hoc (the trace is born
                # inside the runner, after dequeue) so /v1/query shows
                # admission latency next to parse/plan/execute
                queued_s = ((q.started or q.created) - q.created)
                tr = getattr(q.result, "trace", None) \
                    if q.result is not None else None
                if tr is not None and q.admission_queued \
                        and queued_s > 0:
                    tr.record("queued", tr.origin_s - queued_s,
                              tr.origin_s, group=getattr(
                                  q.group, "full_name", ""))
                _M_STATES.inc(state=q.state)
                if self.results is not None:
                    try:
                        # ride-along TTL sweep (time-gated internally):
                        # clients don't DELETE fully-drained queries,
                        # so without this the persisted results of
                        # retry_policy=NONE queries — whose dispatch
                        # path never touches the spool — would pile up
                        # forever
                        self.results.spool.maybe_cleanup()
                    except Exception:    # noqa: BLE001
                        pass
                r = q.result
                stats = (getattr(r, "stats", None) or []) if r else []
                cum = None
                if stats:
                    cum = {
                        "input_rows": sum(max(s.input_rows, 0)
                                          for s in stats),
                        "output_rows": sum(max(s.output_rows, 0)
                                           for s in stats),
                        "output_bytes": sum(max(s.output_bytes, 0)
                                            for s in stats),
                        "compile_s": sum(s.compile_s for s in stats),
                        "wall_s": sum(s.wall_s for s in stats),
                    }
                self.events.query_completed(QueryCompletedEvent(
                    q.query_id, q.sql, q.session.user, q.state,
                    time.time() - q.created,
                    rows=len(r.rows) if r else 0,
                    error_name=(q.error or {}).get("errorName"),
                    error_message=(q.error or {}).get("message"),
                    peak_memory_bytes=getattr(
                        r, "peak_memory_bytes", 0) if r else 0,
                    spill_bytes=getattr(r, "spill_bytes", 0) if r else 0,
                    cumulative_operator_stats=cum,
                    operator_summaries=tuple(
                        s.to_dict() for s in stats)))
                if self.history_sink is not None:
                    try:
                        self.history_sink(q)
                    except Exception:    # noqa: BLE001 — history is
                        pass             # best-effort bookkeeping

        def start(group=None):
            # the group is recorded BEFORE the thread exists so a
            # fast-finishing query cannot race past run_and_release's
            # slot release (q.group would still be None)
            q.group = group
            with q._state_lock:
                dead = q.state in ("FINISHED", "FAILED", "CANCELED")
            if dead and group is not None and self.groups is not None:
                # a dequeued entry whose query already died (deadline
                # kill / cancel racing the withdrawal): release the
                # just-taken slot instead of spending a thread on a
                # query that will no-op
                self.groups.query_finished(group)
                return
            t = threading.Thread(target=run_and_release, daemon=True,
                                 name=f"query-{qid}")
            # tag for the leak detector: a thread outliving its
            # query's terminal state is an orphan
            # (server/diagnostics.py)
            t.trino_query_id = qid
            t.start()

        if self.groups is None:
            start()
        else:
            try:
                _, started_now = self.groups.submit(
                    session.user, source, start, tag=qid)
                if not started_now:
                    q.admission_queued = True
            except QueryQueueFullError as e:
                # protocol-correct rejection: the Trino error name with
                # ITS code and INSUFFICIENT_RESOURCES type (was a
                # hand-typed — and wrong — literal code), flowing to
                # the client as a FAILED QueryResults payload instead
                # of a bare 500
                if q.deadline_timer is not None:
                    q.deadline_timer.cancel()
                from ..errors import error_info
                code, etype = error_info("QUERY_QUEUE_FULL")
                q.error = {"message": str(e), "errorCode": code,
                           "errorName": "QUERY_QUEUE_FULL",
                           "errorType": etype}
                q._transition("FAILED")
                # terminal stamp: without it queuedTimeMillis /
                # elapsedTimeMillis grow on every poll of a query
                # that was rejected instantly
                q.ended = time.time()
                q._done.set()
                self.events.query_completed(QueryCompletedEvent(
                    q.query_id, q.sql, q.session.user, "FAILED",
                    0.0, error_name="QUERY_QUEUE_FULL",
                    error_message=str(e)))
                if self.history_sink is not None:
                    # rejections are history too: a queue-full storm
                    # must be diagnosable from system.runtime.queries
                    try:
                        self.history_sink(q)
                    except Exception:    # noqa: BLE001
                        pass

    def get(self, qid: str) -> Optional[_Query]:
        with self._lock:
            return self._queries.get(qid)

    def all(self) -> List[_Query]:
        with self._lock:
            return list(self._queries.values())

    def running(self) -> List[_Query]:
        return [q for q in self.all()
                if q.state in ("QUEUED", "RUNNING")]

    def cancel(self, qid: str) -> bool:
        q = self.get(qid)
        if q is None:
            return False
        q.do_cancel()
        self._withdraw_if_queued(q)
        return True

    def _withdraw_if_queued(self, q: _Query) -> None:
        """A query terminated before admission must leave its group's
        queue: a dead entry holds max_queued capacity and would later
        burn a concurrency slot. ``started is None`` = never dequeued;
        the dequeue-side terminal check in submit's start() covers the
        race where admission wins."""
        if self.groups is not None and q.started is None:
            self.groups.remove_queued(q.query_id)


class Coordinator:
    """HTTP server wrapper. ``start()`` binds an ephemeral (or given)
    port; ``base_uri`` mirrors server/Server.java's announcement."""

    def __init__(self, port: int = 0, distributed: bool = False,
                 catalogs=None, resource_groups=None,
                 event_listeners=None, authenticator=None,
                 worker_uris=None, failure_detector=None,
                 spool=None, spool_backend: Optional[str] = None,
                 memory_pool_bytes: Optional[int] = None,
                 history_dir: Optional[str] = None):
        from .events import EventListenerManager
        self.node_id = f"coordinator-{uuid.uuid4().hex[:8]}"
        self.started = time.time()
        self._distributed = distributed
        self._catalogs = catalogs
        self.authenticator = authenticator
        # remote worker fleet: queries dispatch leaf fragments to these
        # processes (exec/remote.py; reference: DiscoveryNodeManager's
        # active worker set feeding SqlQueryScheduler). Membership is
        # LIVE: workers join/leave at runtime through /v1/announcement
        # (add_worker/remove_worker below), guarded by one lock.
        self.workers = [str(w).rstrip("/") for w in (worker_uris or [])]
        self._members_lock = threading.Lock()
        # AOT pre-warm readiness per worker (announce payload flag,
        # exec/hotshapes.py): live_workers() lists warm workers first
        # so a fresh query's task fan-out prefers nodes that already
        # compiled the hot shapes. Workers configured at boot are
        # presumed warm-equivalent (they were part of the fleet the
        # hot list was learned from).
        self.worker_prewarmed: Dict[str, bool] = {
            w: True for w in self.workers}
        # fault-tolerant execution (trino_tpu/fte/): one failure
        # detector and one spool shared by every query. The default
        # detector is feedback-driven (schedulers report observed task
        # failures); call failure_detector.start() to add the active
        # heartbeat loop (server/main.py does for configured fleets;
        # add_worker starts it for fleets born empty).
        self.failure_detector = failure_detector
        if self.failure_detector is None and self.workers:
            from .failure import HeartbeatFailureDetector
            self.failure_detector = HeartbeatFailureDetector()
        if self.failure_detector is not None:
            for w in self.workers:
                self.failure_detector.add_service(w)
        # the spool (backend per config/arg — fte/spool.py make_spool)
        # carries fragment output for fault-tolerant queries AND the
        # finished-query results that make coordinator restarts
        # survivable; an explicit ``spool`` enables recovery even for
        # a workerless (single-node) coordinator
        self.spool = spool
        if self.spool is None and (self.workers
                                   or spool_backend is not None):
            from ..fte.spool import make_spool
            self.spool = make_spool(spool_backend)
        self.results = None
        self.manifests = None
        if self.spool is not None:
            from ..fte.recovery import (ExecutionManifestStore,
                                        ResultStore)
            self.results = ResultStore(self.spool)
            # mid-flight failover: execution manifests for RUNNING
            # queries live on the SERVER spool (like results — recovery
            # durability is a coordinator property, not a per-query
            # spool_backend choice)
            self.manifests = ExecutionManifestStore(self.spool)

        # one shared CatalogManager (memory-connector state spans
        # queries) and one shared mesh
        self._proto = LocalQueryRunner(distributed=distributed,
                                       catalogs=self._catalogs)
        self._catalogs = self._proto.catalogs
        # system catalog backed by THIS coordinator
        from ..connectors.system import SystemConnector
        self._catalogs.register("system", SystemConnector(self))

        def make_runner(session: Session):
            # result cache (exec/resultcache.py): wraps BOTH runner
            # kinds — a hit on a repeated identical deterministic
            # query returns before any planning/dispatch below
            from ..exec.resultcache import CachingQueryRunner

            def wrap(runner):
                return CachingQueryRunner(runner, session,
                                          self._catalogs)

            live = self.live_workers()
            if live:
                from ..exec.remote import DistributedHostQueryRunner
                # SET SESSION spool_backend overrides the server's
                # fragment spool for this query (result persistence
                # stays on the server spool — recovery durability is a
                # coordinator property, not a per-query choice)
                backend = str(session.get("spool_backend") or "")
                spool = self.spool
                if backend:
                    from ..fte.spool import default_spool
                    spool = default_spool(backend)
                # mid-flight failover: hand the runner the manifest
                # store plus the tracked query's identity/admission/
                # timing context; the runner persists the full
                # execution manifest (stage payloads + fan-out) at
                # dispatch time, once the DAG is serde-proven
                meta = None
                if self.manifests is not None:
                    tq = self.tracker.get(
                        getattr(session, "query_id", "") or "")
                    if tq is not None:
                        meta = {
                            "queryId": tq.query_id,
                            "slug": tq.slug,
                            "sql": tq.sql,
                            "user": session.user,
                            "source": tq.source,
                            "resourceGroup": getattr(
                                tq.group, "full_name", "global")
                            if tq.group is not None else "global",
                            "submitEpoch": tq.created,
                            "startedEpoch": tq.started,
                        }
                return wrap(DistributedHostQueryRunner(
                    live, session=session, catalogs=self._catalogs,
                    collect_node_stats=True,
                    failure_detector=self.failure_detector,
                    spool=spool,
                    manifest_store=self.manifests,
                    manifest_meta=meta,
                    # live membership: mid-query joins become retry /
                    # speculation targets (exec/remote.py syncs this
                    # before every replacement dispatch)
                    worker_supplier=self.live_workers))
            # per-node wall/row stats feed the web UI's query detail
            # (OperatorStats is always-on in the reference coordinator)
            return wrap(LocalQueryRunner(session=session,
                                         catalogs=self._catalogs,
                                         mesh=self._proto.mesh,
                                         collect_node_stats=True))

        events = EventListenerManager()
        for listener in (event_listeners or []):
            events.add_listener(listener)
        if resource_groups is None:
            # admission is ALWAYS real (ROADMAP item 2: the group tree
            # was "mostly decorative" when it only existed if the
            # operator passed one): a default manager routes every
            # query through the root group's hard_concurrency /
            # max_queued gates with the same defaults as before
            from .resourcegroups import ResourceGroupManager
            resource_groups = ResourceGroupManager()
        self.resource_groups = resource_groups
        # cluster memory pool (server/memory.py): arg beats config;
        # 0 disables governance (per-node query limits still apply)
        from ..config import CONFIG as _CONFIG
        pool_bytes = (memory_pool_bytes
                      if memory_pool_bytes is not None
                      else _CONFIG.cluster_memory_pool_bytes)
        self.memory = None
        if pool_bytes and pool_bytes > 0:
            from .memory import ClusterMemoryManager, ClusterMemoryPool
            self.memory = ClusterMemoryManager(
                ClusterMemoryPool(int(pool_bytes)))
        # query history & learned statistics (obs/history.py,
        # exec/learnedstats.py): terminal queries append durable JSONL
        # records under the spool/history dir; the learned-stats
        # registry checkpoints there too so EMAs survive restarts.
        # An explicit history_dir decouples tests (and co-located
        # coordinators) from the process-wide spool default.
        from ..exec.learnedstats import LEARNED_STATS
        from ..obs.history import (MetricsRing, QueryHistoryStore,
                                   TraceRing)
        hist_dir = history_dir or os.path.join(_CONFIG.spool_dir,
                                               "history")
        self.history = QueryHistoryStore(
            os.path.join(hist_dir, "queries.jsonl"))
        self.trace_ring = TraceRing()
        self.metrics_ring = MetricsRing()
        self._learned_stats_path = os.path.join(hist_dir,
                                                "learned_stats.json")
        self._learned_saved_at = 0.0
        LEARNED_STATS.load(self._learned_stats_path)
        # resume_query builds manifest-driven runners through the same
        # factory (live membership, failure detector, spool wiring)
        self._make_runner = make_runner
        self.tracker = QueryTracker(make_runner, events,
                                    resource_groups,
                                    result_store=self.results,
                                    memory=self.memory,
                                    manifest_store=self.manifests,
                                    history_sink=self._on_query_terminal)
        # streaming ingestion + continuous queries (trino_tpu/
        # streaming/): the process-wide message log backs POST
        # /v1/ingest/{topic} and the stream catalog's scans; the
        # continuous-query manager drives long-lived jobs whose cycles
        # are REAL tracked queries (source "continuous"). Consumer
        # offsets spool under reserved fragment -3 on the server spool
        # (or the process default for a workerless coordinator), and
        # the job ledger lives next to the query history so a
        # replacement coordinator restarts RUNNING jobs (start()).
        from ..streaming.continuous import ContinuousQueryManager
        from ..streaming.log import get_log
        from ..streaming.offsets import OffsetStore
        self.stream_log = get_log()
        off_spool = self.spool
        if off_spool is None:
            from ..fte.spool import default_spool
            off_spool = default_spool()
        self.continuous = ContinuousQueryManager(
            self._run_continuous_sql, self._catalogs,
            OffsetStore(off_spool),
            jobs_path=os.path.join(hist_dir, "continuous.jsonl"),
            log=self.stream_log)
        self._register_metric_collectors()
        self._httpd = ThreadingHTTPServer(("127.0.0.1", port),
                                          _make_handler(self))
        self.port = self._httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def _register_metric_collectors(self):
        """Polled gauges refreshed at scrape time (obs/metrics.py):
        query states and queue depth. The registry is process-global:
        the collector is unregistered on stop() (and self-unregisters
        if the coordinator is garbage-collected without stop), so test
        suites building many coordinators don't accumulate dead
        callbacks or stale gauges. With several LIVE coordinators in
        one process the gauge families are shared and last-writer-wins
        — production runs one coordinator per process."""
        import weakref
        wself = weakref.ref(self)
        g_state = METRICS.gauge(
            "trino_tpu_queries",
            "Queries currently tracked, by state", ("state",))
        g_queue = METRICS.gauge(
            "trino_tpu_queue_depth",
            "Queries admitted but not yet running (queue depth)")
        g_workers = METRICS.gauge(
            "trino_tpu_active_workers", "Known worker nodes")

        def collect():
            co = wself()
            if co is None:
                METRICS.unregister_collector(collect)
                return
            qs = co.tracker.all()
            for st in ("QUEUED", "RUNNING", "FINISHED", "FAILED",
                       "CANCELED"):
                g_state.set(sum(1 for q in qs if q.state == st),
                            state=st)
            g_queue.set(sum(1 for q in qs if q.state == "QUEUED"))
            g_workers.set(len(co.workers))

        self._metric_collector = collect
        METRICS.register_collector(collect)

    @property
    def base_uri(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    def start(self):
        self._thread = threading.Thread(  # tt-lint: ignore[race-attr-write] lifecycle: start() runs once on the owning thread before the server is shared
            target=self._httpd.serve_forever, daemon=True)
        self._thread.start()
        # coordinator-failover restart of continuous jobs: replay the
        # durable ledger; restarted consumers resume from their
        # committed offset epochs
        self.continuous.restart_jobs()
        return self

    def stop(self):
        self.continuous.stop()
        METRICS.unregister_collector(self._metric_collector)
        try:
            # final learned-stats checkpoint: the throttled per-query
            # saves may be up to one interval stale at shutdown
            from ..exec.learnedstats import LEARNED_STATS
            LEARNED_STATS.save(self._learned_stats_path)
        except Exception:        # noqa: BLE001 — shutdown best-effort
            pass
        if self.failure_detector is not None:
            self.failure_detector.stop()
        self._httpd.shutdown()

    # ---- live worker membership --------------------------------------
    def live_workers(self) -> List[str]:
        """Current worker set minus nodes the failure detector reports
        dead — the per-dispatch view the schedulers consume. Pre-warmed
        workers sort first (stable within each class), so a query's
        initial task fan-out lands on nodes whose hot-shape programs
        are already compiled; a scheduler's mid-query re-syncs are
        append-only and unaffected (exec/remote.py _sync_workers)."""
        detector = self.failure_detector
        with self._members_lock:
            workers = list(self.workers)
            warm = dict(self.worker_prewarmed)
        return sorted(
            (w for w in workers
             if detector is None or detector.is_alive(w)),
            key=lambda w: not warm.get(w, False))

    def add_worker(self, uri: str,
                   prewarmed: Optional[bool] = None) -> bool:
        """Join a worker at runtime (/v1/announcement POST; reference:
        DiscoveryNodeManager absorbing a service announcement). A
        joining worker immediately becomes a retry / speculation
        target for in-flight queries and a full member for new ones.
        Idempotent: re-announcement of a known worker is a no-op for
        membership but still refreshes its pre-warm readiness flag —
        that is how a joiner's background warm-up completion reaches
        the scheduler (the worker re-announces with prewarmed=true)."""
        uri = str(uri).rstrip("/")
        if not uri:
            return False
        with self._members_lock:
            # the whole join — membership, detector/spool bootstrap —
            # runs under the lock: concurrent first announcements must
            # not construct two detectors (a worker registered in the
            # discarded one would never be heartbeat-probed)
            if prewarmed is not None:
                self.worker_prewarmed[uri] = bool(prewarmed)
            if uri in self.workers:
                return False
            self.workers.append(uri)
            if self.failure_detector is None:
                from .failure import HeartbeatFailureDetector
                self.failure_detector = HeartbeatFailureDetector()
            self.failure_detector.add_service(uri)
            # a fleet born empty never started its heartbeat loop;
            # start() is idempotent for one already running
            self.failure_detector.start()
            if self.spool is None:
                # first worker ever: the cluster just became
                # distributed — it needs the spool (and with it
                # restart recovery and mid-flight failover)
                from ..fte.recovery import (ExecutionManifestStore,
                                            ResultStore)
                from ..fte.spool import make_spool
                self.spool = make_spool()
                self.results = ResultStore(self.spool)
                self.tracker.results = self.results
                self.manifests = ExecutionManifestStore(self.spool)
                self.tracker.manifests = self.manifests
        _M_WORKER_JOINS.inc()
        return True

    def remove_worker(self, uri: str) -> bool:
        """Graceful leave (/v1/announcement DELETE). Ungraceful deaths
        need no call — the heartbeat detector sidelines them and the
        retry engine routes around (PR 5)."""
        uri = str(uri).rstrip("/")
        with self._members_lock:
            self.worker_prewarmed.pop(uri, None)
            if uri not in self.workers:
                return False
            self.workers.remove(uri)
        if self.failure_detector is not None:
            self.failure_detector.remove_service(uri)
        _M_WORKER_LEAVES.inc()
        return True

    # ---- coordinator-restart result recovery -------------------------
    def recover_query(self, query_id: str,
                      slug: Optional[str] = None) -> Optional[_Query]:
        """Rebuild a FINISHED query this process never ran from its
        spooled manifest + result pages (fte/recovery.py) — the serving
        half of coordinator restart tolerance. ``slug`` (when the
        client supplied one) must match the manifest: the slug is the
        per-query capability token, and a restart must not weaken it."""
        if self.results is None:
            return None
        # slug checked against the manifest alone (load_manifest)
        # before the row frames are decoded: a wrong-slug probe 404s
        # without re-reading the whole persisted result
        rec = self.results.load(query_id, slug)
        if rec is None or (slug is not None and rec.slug != slug):
            return None
        q = _Query(query_id, rec.slug, rec.sql,
                   Session(user=rec.user or "user"))
        q.state = "FINISHED"
        q.result = rec.to_query_result()
        q.ended = time.time()
        q._done.set()
        with self.tracker._lock:
            # first-registration-wins: a concurrent recovery (two
            # clients re-pulling at once) must serve ONE entry
            registered = self.tracker._queries.setdefault(query_id, q)
        if registered is q:
            # counted here, not in ResultStore.load: a slug-mismatch
            # probe or a losing concurrent load is not a recovery
            _M_RESULTS_RECOVERED.inc()
        return registered

    # ---- mid-flight query resumption (coordinator failover) ----------
    def resume_query(self, query_id: str,
                     slug: Optional[str] = None) -> Optional[_Query]:
        """Finish a RUNNING query dispatched by a coordinator that
        died: the mid-flight half of failover, next to
        ``recover_query``'s FINISHED half. The execution manifest
        spooled at dispatch time carries the stage DAG's serde-proven
        wire payloads, the fan-out, the session/admission context and
        the ORIGINAL submit/start times; stage progress is read off
        the exchange spool's first-commit-wins COMMITTED markers, so
        only the partitions the dead coordinator had NOT committed are
        re-dispatched (exec/remote.py resume + stage/scheduler.py
        resume_spool).

        Gated on retry_policy=TASK (the manifest is only written under
        it, and a NONE query's fragments never touch the spool — there
        is nothing safe to resume). Returns None when no slug-matching
        manifest exists, resumption is gated off, or no workers are
        live; the caller falls through to 404 and the client's retry
        loop keeps polling."""
        if self.manifests is None:
            return None
        mf = self.manifests.load(query_id, slug)
        if mf is None:
            return None
        if not self.live_workers():
            return None
        session = Session(catalog=mf.get("catalog"),
                          schema=mf.get("schema"),
                          user=str(mf.get("user") or "user"))
        for name, value in (mf.get("properties") or {}).items():
            try:
                session.set(str(name), value)
            except (KeyError, TypeError, ValueError):
                continue    # property from a newer/older build
        from ..fte.retry import RetryPolicy
        if not RetryPolicy.from_session(session).enabled:
            return None
        q = _Query(str(mf.get("queryId") or query_id),
                   str(mf.get("slug")), str(mf.get("sql") or ""),
                   session)
        q.source = str(mf.get("source") or "")
        # original-time accounting: queued/elapsed/deadline anchor at
        # the FIRST coordinator's submit — failover must not hand the
        # query a fresh query_max_run_time budget
        try:
            q.created = float(mf.get("submitEpoch") or q.created)
        except (TypeError, ValueError):
            pass
        q.submit_mono = time.monotonic() - max(
            time.time() - q.created, 0.0)
        started = mf.get("startedEpoch")
        if started:
            try:
                q.started = float(started)
            except (TypeError, ValueError):
                pass
        make_runner = self._make_runner

        def resume_runner_factory(sess: Session):
            runner = make_runner(sess)

            class _ResumeRunner:
                """execute() ignores the SQL text: the plan was
                fragmented, proven and spooled by the dead
                coordinator; re-planning here could fragment
                differently and orphan the committed partitions."""

                def execute(self, _sql: str):
                    return runner.resume(mf)

            return _ResumeRunner()

        registered = self.tracker.submit_resumed(q, resume_runner_factory)
        if registered is q:
            # counted only for the registration winner: a losing
            # concurrent resume (or one beaten by recover_query) did
            # not resume anything
            self.manifests.mark_resumed()
        return registered

    def recovered_query_detail(self, query_id: str) -> Optional[dict]:
        """Manifest-only detail for an untracked query — the slug-less
        /v1/query/{id} surface. Full recovery (recover_query) decodes
        every persisted row frame and pins it in the tracker, which a
        request that presents no slug and needs only metadata must not
        trigger: probed ids would pin N x result_spool_max_bytes of
        rows in a process that never ran them."""
        if self.results is None:
            return None
        mf = self.results.load_manifest(query_id)
        if mf is None:
            return None
        return {
            "queryId": str(mf.get("queryId", query_id)),
            "state": "FINISHED",
            "query": str(mf.get("sql", "")),
            "user": str(mf.get("user", "")),
            "source": "",
            "error": None,
            "rows": int(mf.get("rows") or 0),
            "recovered": True,
        }

    # ---- resource payloads -------------------------------------------
    def query_results(self, q: _Query, token: int) -> dict:
        uri = f"{self.base_uri}/v1/statement/executing/{q.query_id}" \
              f"/{q.slug}"
        out = {
            "id": q.query_id,
            "infoUri": f"{self.base_uri}/ui/query.html?{q.query_id}",
            "stats": {"state": q.state,
                      "queued": q.state == "QUEUED",
                      "scheduled": q.state in ("RUNNING", "FINISHED"),
                      "elapsedTimeMillis":
                          int((time.time() - q.created) * 1000),
                      # admission latency: how long the query sat in
                      # its resource group's queue (still growing
                      # while QUEUED — the client watches back-
                      # pressure build in its nextUri polls; frozen at
                      # q.ended for queries that died without starting,
                      # e.g. queue-full rejections)
                      "queuedTimeMillis": int(
                          ((q.started or q.ended or time.time())
                           - q.created) * 1000)},
            "warnings": [],
        }
        if q.state == "FAILED":
            out["error"] = q.error
            return out
        if q.state == "CANCELED":
            out["error"] = {"message": "Query was canceled",
                            "errorCode": 2, "errorName": "USER_CANCELED",
                            "errorType": "USER_ERROR"}
            return out
        if q.state in ("QUEUED", "RUNNING") or q.result is None:
            out["nextUri"] = f"{uri}/{token}"
            return out
        res = q.result
        if res.update_type is not None:
            out["updateType"] = res.update_type
            if res.update_count is not None:
                out["updateCount"] = res.update_count
        start = token * PAGE_ROWS
        chunk = res.rows[start:start + PAGE_ROWS]
        if res.columns:
            out["columns"] = [
                {"name": n, "type": t.name,
                 "typeSignature": {"rawType": t.name.split("(")[0],
                                   "arguments": []}}
                for n, t in zip(res.columns, res.types)]
            if chunk:
                out["data"] = [[_json_value(v) for v in row]
                               for row in chunk]
        if start + PAGE_ROWS < len(res.rows):
            out["nextUri"] = f"{uri}/{token + 1}"
        return out

    def info(self) -> dict:
        return {"nodeVersion": {"version": "trino-tpu-0.1"},
                "environment": "tpu",
                "coordinator": True,
                "starting": False,
                "nodeId": self.node_id,
                "uptime": f"{time.time() - self.started:.0f}s"}

    def query_detail(self, q: _Query) -> dict:
        """Query detail for /v1/query/{id} and the web UI: state,
        timing, per-node stats, and the optimized plan tree (webapp
        QueryDetail + LivePlan analog)."""
        out = {
            "queryId": q.query_id, "state": q.state, "query": q.sql,
            "user": q.session.user, "source": q.source,
            "created": time.strftime("%Y-%m-%d %H:%M:%S",
                                     time.localtime(q.created)),
            "elapsedTimeMillis": int(
                ((q.ended or time.time()) - q.created) * 1000),
            "queuedTimeMillis": int(
                ((q.started or q.ended or time.time()) - q.created)
                * 1000),
            "error": q.error,
        }
        if q.result is not None:
            out["rows"] = len(q.result.rows)
            out["wallMillis"] = int(
                (getattr(q.result, "wall_s", 0.0) or 0.0) * 1000)
            out["peakMemoryBytes"] = getattr(
                q.result, "peak_memory_bytes", 0)
            out["spillBytes"] = getattr(q.result, "spill_bytes", 0)
            stats = getattr(q.result, "stats", None)
            if stats:
                out["nodeStats"] = [
                    {"node": s.name, "detail": s.detail,
                     "wallMillis": round(s.wall_s * 1000, 2),
                     "outputRows": s.output_rows,
                     "inputRows": s.input_rows,
                     "inputBytes": s.input_bytes,
                     "outputBytes": s.output_bytes,
                     "compileMillis": round(s.compile_s * 1000, 2),
                     "cacheHit": s.cache_hit} for s in stats]
            trace = getattr(q.result, "trace", None)
            if trace is not None and trace.roots:
                out["spans"] = trace.to_dicts()
        # the plan captured at execution time (QueryResult.plan_lines) —
        # re-planning on every GET both wasted work and could silently
        # diverge from the plan that actually ran. Checked BEFORE the
        # mid-flight fallback cache, which a poll during RUNNING may
        # have populated with a re-derived (possibly divergent) plan.
        plan = (getattr(q.result, "plan_lines", None)
                if q.result is not None else None)
        if plan is None:
            plan = getattr(q, "_plan_lines", None)
        if plan is None and q.state in ("FINISHED", "RUNNING"):
            # legacy fallback (old results without captured plans, or a
            # query mid-flight): derive once and cache on the query
            try:
                from ..planner.logical import LogicalPlanner
                from ..planner.optimizer import optimize
                from ..plan.nodes import plan_tree_lines
                from ..sql import ast as A
                from ..sql.parser import parse_statement
                stmt = parse_statement(q.sql)
                if isinstance(stmt, A.QueryStatement):
                    p = optimize(
                        LogicalPlanner(self._catalogs,
                                       q.session).plan(stmt),
                        self._catalogs, q.session)
                    plan = plan_tree_lines(p)
                else:
                    plan = []
            except Exception as e:  # noqa: BLE001 — detail is best-effort
                _M_DETAIL_PLAN_ERRORS.inc()
                out["planError"] = f"{type(e).__name__}: {e}"
                plan = []
            q._plan_lines = plan
        if plan:
            out["plan"] = plan
        return out

    def query_infos(self) -> list:
        return [{"queryId": q.query_id, "state": q.state,
                 "query": q.sql, "user": q.session.user,
                 "source": q.source,
                 "created": time.strftime(
                     "%Y-%m-%d %H:%M:%S", time.localtime(q.created)),
                 "elapsedTimeMillis": int(
                     ((q.ended or time.time()) - q.created) * 1000)}
                for q in self.tracker.all()]

    # ---- query history & learned stats (obs/history.py) ---------------
    def _on_query_terminal(self, q) -> None:
        """Terminal-query bookkeeping, called from the tracker's run
        thread (and the admission-rejection path): one history record,
        the slow-query side log, the trace ring, a metrics-ring sample
        and a throttled learned-stats checkpoint."""
        from ..exec.learnedstats import LEARNED_STATS
        from ..obs.history import record_from_query
        sess = q.session
        if bool(sess.get("query_history_enabled")):
            rec = self.history.record(record_from_query(q))
            threshold = int(sess.get("slow_query_log_ms") or 0)
            if threshold > 0 and rec["wall_s"] * 1000.0 >= threshold:
                self.history.slow_log(rec, threshold)
        trace = getattr(q.result, "trace", None) \
            if q.result is not None else None
        self.trace_ring.append(q.query_id, q.state, trace)
        self.metrics_ring.maybe_sample(self._collect_cluster_metrics)
        now = time.time()
        if now - self._learned_saved_at >= 5.0:
            # checkpoint throttle: racing terminal threads may both
            # save — harmless (atomic rename, same content modulo a
            # few observations); stop() takes the final one
            self._learned_saved_at = now  # tt-lint: ignore[race-attr-write] benign double-save
            LEARNED_STATS.save(self._learned_stats_path)

    def _collect_cluster_metrics(self) -> dict:
        """{node: parsed exposition} — this coordinator's registry
        plus a best-effort /metrics scrape of every live worker (the
        cluster-wide rollup behind system.runtime.metrics)."""
        from ..obs.metrics import parse_exposition
        nodes = {self.node_id: parse_exposition(METRICS.render())}
        import urllib.request
        for w in self.live_workers():
            try:
                with urllib.request.urlopen(f"{w}/metrics",
                                            timeout=2.0) as resp:
                    nodes[w] = parse_exposition(
                        resp.read().decode("utf-8", "replace"))
            except Exception:    # noqa: BLE001 — scrape best-effort
                continue
        return nodes

    def history_infos(self) -> list:
        """system.runtime.queries rows: live QUEUED/RUNNING queries
        first (record-shaped, built on the fly), then the durable
        terminal history, newest first."""
        from ..obs.history import record_from_query
        recs = self.history.records()
        seen = {r.get("query_id") for r in recs}
        live = [record_from_query(q) for q in self.tracker.all()
                if q.state in ("QUEUED", "RUNNING")
                and q.query_id not in seen]
        return live + recs

    def operator_stat_infos(self) -> list:
        from ..exec.learnedstats import LEARNED_STATS
        return LEARNED_STATS.snapshot()

    def metric_infos(self) -> list:
        """system.runtime.metrics rows: the current cluster-wide
        sample plus every ring snapshot, flattened."""
        self.metrics_ring.maybe_sample(self._collect_cluster_metrics)
        out = []

        def flatten(ts_ms, nodes, sample):
            for node, families in (nodes or {}).items():
                for name, series in families.items():
                    for labels, value in series.items():
                        out.append({"captured_ms": ts_ms, "node": node,
                                    "name": name,
                                    "labels": ",".join(labels),
                                    "value": value, "sample": sample})

        try:
            flatten(int(time.time() * 1000),
                    self._collect_cluster_metrics(), "current")
        except Exception:        # noqa: BLE001 — scan must not fail
            pass
        for snap in self.metrics_ring.snapshots():
            flatten(int(float(snap.get("ts") or 0.0) * 1000),
                    snap.get("nodes"), "ring")
        return out

    # ---- SystemProvider SPI (connectors/system.py) --------------------
    def node_infos(self) -> list:
        nodes = [{"nodeId": self.node_id, "uri": self.base_uri,
                  "nodeVersion": "trino-tpu-0.1", "coordinator": True,
                  "state": "active"}]
        detector = getattr(self, "failure_detector", None)
        workers = getattr(self, "workers", None) or []
        for w in workers:
            state = "active"
            if detector is not None and not detector.is_alive(w):
                state = "failed"
            nodes.append({"nodeId": w, "uri": w,
                          "nodeVersion": "trino-tpu-0.1",
                          "coordinator": False, "state": state})
        return nodes

    def resource_group_infos(self) -> list:
        if self.resource_groups is None:
            return []
        return self.resource_groups.info()

    def kill_query(self, query_id: str) -> bool:
        return self.tracker.cancel(query_id)

    def continuous_query_infos(self) -> list:
        """system.runtime.continuous_queries rows."""
        return self.continuous.infos()

    # ---- continuous-query cycle driver --------------------------------
    def _run_continuous_sql(self, sql: str):
        """One continuous-query cycle = one REAL tracked query: it
        rides admission, the stage DAG, FTE retries, history and the
        system.runtime.queries surface like any client submission."""
        session = Session(catalog="stream", schema="default",
                          user="continuous")
        q = self.tracker.submit(sql, session, source="continuous")
        if not q.wait_done(600.0):
            self.tracker.cancel(q.query_id)
            raise TimeoutError(f"continuous cycle timed out: {sql!r}")
        if q.state != "FINISHED":
            msg = (q.error or {}).get("message", f"query {q.state}")
            raise RuntimeError(msg)
        return q.result

    def leak_report(self, stuck_after_s: float = 3600.0,
                    orphan_grace_s: float = 5.0):
        """Leak/orphan snapshot (execution/QueryTracker
        enforceTimeLimits + ClusterMemoryLeakDetector analogs)."""
        from .diagnostics import leak_report
        return leak_report(self, stuck_after_s=stuck_after_s,
                           orphan_grace_s=orphan_grace_s)

    def drain(self, timeout: float = 30.0) -> bool:
        """Graceful shutdown: wait for active queries to finish
        (server/GracefulShutdownHandler.java:43,73), then stop."""
        deadline = time.time() + timeout
        for q in self.tracker.running():
            q.wait_done(max(0.0, deadline - time.time()))
        self.stop()
        return not self.tracker.running()


_UI_PAGE = """<!doctype html>
<html><head><title>trino-tpu</title><style>
body{font-family:system-ui,sans-serif;margin:2em;background:#fafafa}
h1{font-size:1.3em} table{border-collapse:collapse;width:100%}
td,th{border:1px solid #ddd;padding:6px 10px;text-align:left;
font-size:0.9em} th{background:#f0f0f0}
.FINISHED{color:#188038}.FAILED{color:#d93025}.RUNNING{color:#1a73e8}
.QUEUED{color:#e37400}.CANCELED{color:#5f6368}
</style></head><body>
<h1>trino-tpu cluster</h1><div id=info></div>
<h2>Queries</h2><table id=q><tr><th>Query ID</th><th>State</th>
<th>User</th><th>Elapsed</th><th>SQL</th></tr></table>
<script>
async function refresh(){
 const info=await (await fetch('/v1/info')).json();
 document.getElementById('info').textContent=
   'node '+info.nodeId+' — uptime '+info.uptime;
 const qs=await (await fetch('/v1/query')).json();
 const t=document.getElementById('q');
 while(t.rows.length>1)t.deleteRow(1);
 for(const q of qs.reverse()){
  const r=t.insertRow(); const c=r.insertCell();
  const a=document.createElement('a');
  a.href='/ui/query.html?'+q.queryId; a.textContent=q.queryId;
  c.appendChild(a);
  const s=r.insertCell(); s.textContent=q.state; s.className=q.state;
  r.insertCell().textContent=q.user||'';
  r.insertCell().textContent=(q.elapsedTimeMillis/1000).toFixed(1)+'s';
  r.insertCell().textContent=q.query.slice(0,120);}}
refresh(); setInterval(refresh, 2000);
</script></body></html>"""


_UI_QUERY_PAGE = """<!doctype html>
<html><head><title>query — trino-tpu</title><style>
body{font-family:system-ui,sans-serif;margin:2em;background:#fafafa}
h1{font-size:1.2em} pre{background:#fff;border:1px solid #ddd;
padding:10px;overflow-x:auto;font-size:0.85em}
table{border-collapse:collapse;margin:1em 0}
td,th{border:1px solid #ddd;padding:5px 9px;font-size:0.85em;
text-align:left} th{background:#f0f0f0}
.FINISHED{color:#188038}.FAILED{color:#d93025}.RUNNING{color:#1a73e8}
a{color:#1a73e8;text-decoration:none}
</style></head><body>
<a href="/ui">&larr; queries</a>
<h1 id=title>query</h1><div id=meta></div>
<h2>SQL</h2><pre id=sql></pre>
<h2>Plan</h2><pre id=plan>(not available)</pre>
<h2>Operator stats</h2>
<table id=stats><tr><th>Node</th><th>Wall ms</th><th>Rows</th>
<th>Detail</th></tr></table>
<pre id=error style="color:#d93025;display:none"></pre>
<script>
const qid=location.search.slice(1);
async function refresh(){
 const q=await (await fetch('/v1/query/'+qid)).json();
 document.getElementById('title').innerHTML=
   q.queryId+' — <span class="'+q.state+'">'+q.state+'</span>';
 document.getElementById('meta').textContent=
   'user '+(q.user||'')+' · created '+(q.created||'')+' · elapsed '+
   ((q.elapsedTimeMillis||0)/1000).toFixed(1)+'s'+
   (q.rows!==undefined?' · '+q.rows+' rows':'');
 document.getElementById('sql').textContent=q.query||'';
 if(q.plan)document.getElementById('plan').textContent=
   q.plan.join('\\n');
 const t=document.getElementById('stats');
 while(t.rows.length>1)t.deleteRow(1);
 for(const s of (q.nodeStats||[])){
  const r=t.insertRow(); r.insertCell().textContent=s.node;
  r.insertCell().textContent=s.wallMillis;
  r.insertCell().textContent=s.outputRows;
  r.insertCell().textContent=(s.detail||'').slice(0,100);}
 if(q.error){const e=document.getElementById('error');
  e.style.display='block';
  e.textContent=JSON.stringify(q.error,null,2);}
 if(q.state==='RUNNING'||q.state==='QUEUED')
   setTimeout(refresh,2000);}
refresh();
</script></body></html>"""


def _make_handler(co: Coordinator):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, *args):   # quiet
            pass

        def _send(self, code: int, payload, headers=None):
            body = json.dumps(payload).encode()
            self.send_response(code)
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _send_html(self, body: str):
            raw = body.encode()
            self.send_response(200)
            self.send_header("Content-Type", "text/html")
            self.send_header("Content-Length", str(len(raw)))
            self.end_headers()
            self.wfile.write(raw)

        def _auth_reject(self, code: int, payload: dict,
                         www: Optional[str] = None) -> bool:
            """Reject the request before the body is consumed: the
            connection must close (keep-alive would parse the unread
            POST body as the next request)."""
            self.close_connection = True
            self._send(code, payload,
                       headers={"WWW-Authenticate": www} if www
                       else None)
            return False

        def _authenticate(self) -> bool:
            """HTTP Basic auth against the configured password
            authenticator (server/security/PasswordAuthenticator
            analog); no authenticator = open access. On success the
            verified principal is recorded and MUST match any
            X-Trino-User header (server/security/
            AuthenticationFilter + the set-user authorization check) —
            session identity never comes from an unverified header."""
            self.principal = None
            if co.authenticator is None:
                return True
            import base64
            header = self.headers.get("Authorization", "")
            if header.startswith("Bearer ") and hasattr(
                    co.authenticator, "authenticate_token"):
                # JWT / bearer tokens (server/security/jwt/
                # JwtAuthenticator.java)
                principal = co.authenticator.authenticate_token(
                    header[7:].strip())
                if principal is not None:
                    claimed = self.headers.get("X-Trino-User")
                    if claimed and claimed != principal:
                        return self._auth_reject(403, {
                            "error": f"Access Denied: User {principal}"
                            f" cannot impersonate {claimed}"})
                    self.principal = principal
                    return True
            if header.startswith("Basic "):
                try:
                    raw = base64.b64decode(header[6:]).decode()
                    user, _, pw = raw.partition(":")
                    if co.authenticator.authenticate(user, pw):
                        claimed = self.headers.get("X-Trino-User")
                        if claimed and claimed != user:
                            return self._auth_reject(403, {
                                "error": f"Access Denied: User {user} "
                                f"cannot impersonate {claimed}"})
                        self.principal = user
                        return True
                except Exception:
                    pass
            return self._auth_reject(
                401, {"error": "Unauthorized"},
                www='Basic realm="trino-tpu"')

        def do_POST(self):
            if not self._authenticate():
                return
            path = urlparse(self.path).path
            if path == "/v1/statement":
                n = int(self.headers.get("Content-Length", 0))
                sql = self.rfile.read(n).decode()
                session = Session(
                    catalog=self.headers.get("X-Trino-Catalog", "tpch"),
                    schema=self.headers.get("X-Trino-Schema", "tiny"),
                    user=(self.principal
                          or self.headers.get("X-Trino-User", "user")))
                for kv in (self.headers.get("X-Trino-Session") or "") \
                        .split(","):
                    if "=" in kv:
                        k, v = kv.split("=", 1)
                        try:
                            session.set(k.strip(), v.strip())
                        except KeyError:
                            pass
                # client-held prepared statements (sessions are
                # per-request; the client replays its registry, the
                # reference's X-Trino-Prepared-Statement contract)
                from urllib.parse import unquote
                for kv in (self.headers.get(
                        "X-Trino-Prepared-Statement") or "").split(","):
                    if "=" in kv:
                        name, v = kv.split("=", 1)
                        session.prepared[name.strip()] = unquote(v)
                try:
                    q = co.tracker.submit(
                        sql, session,
                        source=self.headers.get("X-Trino-Source", ""))
                except Exception as e:   # noqa: BLE001 — a submission
                    # failure outside the tracked-query machinery
                    # (selector bug, bad session property) must answer
                    # with a classified error + mapped status, never
                    # the handler's bare 500 traceback
                    from ..errors import classify, http_status_for
                    name, code, etype = classify(e)
                    self._send(http_status_for(etype), {
                        "error": {"message": str(e), "errorCode": code,
                                  "errorName": name,
                                  "errorType": etype}})
                    return
                q.wait_done(0.05)   # fast queries answer immediately
                self._send(200, co.query_results(q, 0))
                return
            if path == "/v1/announcement":
                # worker join (discovery-service announcement analog);
                # idempotent, so workers re-announce on a cadence
                n = int(self.headers.get("Content-Length", 0))
                prewarmed = None
                try:
                    body = json.loads(self.rfile.read(n) or b"{}")
                    uri = str(body.get("uri", "")).strip() \
                        if isinstance(body, dict) else ""
                    if isinstance(body, dict) \
                            and "prewarmed" in body:
                        prewarmed = bool(body.get("prewarmed"))
                except (ValueError, TypeError):
                    uri = ""
                if not uri:
                    self._send(400, {"error": "missing worker uri"})
                    return
                joined = co.add_worker(uri, prewarmed=prewarmed)
                self._send(200, {"joined": joined,
                                 "workers": co.live_workers()})
                return
            # /v1/ingest/{topic}: newline-delimited messages into the
            # append-only log (producers hit the coordinator or ANY
            # worker — the segment files are the shared truth)
            parts = [p for p in path.split("/") if p]
            if len(parts) == 3 and parts[:2] == ["v1", "ingest"]:
                from ..streaming.log import ingest_http
                from urllib.parse import parse_qs
                n = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(n)
                try:
                    self._send(200, ingest_http(
                        co.stream_log, parts[2], body,
                        parse_qs(urlparse(self.path).query)))
                except ValueError as e:
                    self._send(400, {"error": str(e)})
                return
            if path == "/v1/continuous":
                n = int(self.headers.get("Content-Length", 0))
                try:
                    spec = json.loads(self.rfile.read(n) or b"{}")
                    job = co.continuous.create(spec)
                except (ValueError, KeyError) as e:
                    self._send(400, {"error": str(e)})
                    return
                self._send(200, job)
                return
            self._send(404, {"error": "not found"})

        def do_GET(self):
            if not self._authenticate():
                return
            path = urlparse(self.path).path
            parts = [p for p in path.split("/") if p]
            if path == "/metrics":
                from ..obs.metrics import write_exposition
                write_exposition(self)
                return
            if path == "/ui" or path == "/ui/":
                self._send_html(_UI_PAGE)
                return
            if path == "/ui/query.html":
                self._send_html(_UI_QUERY_PAGE)
                return
            if path == "/v1/cluster":
                qs = co.tracker.all()
                out = {
                    "runningQueries": sum(
                        1 for q in qs if q.state == "RUNNING"),
                    "queuedQueries": sum(
                        1 for q in qs if q.state == "QUEUED"),
                    "totalQueries": len(qs),
                    "activeWorkers": len(co.node_infos())}
                if co.memory is not None:
                    # memory-pool state rides the cluster overview
                    # (webapp ClusterStats reservedMemory analog)
                    out["memory"] = co.memory.info()
                self._send(200, out)
                return
            if path == "/v1/info":
                self._send(200, co.info())
                return
            if path == "/v1/query":
                self._send(200, co.query_infos())
                return
            if path == "/v1/announcement":
                detector = co.failure_detector
                self._send(200, {"workers": [
                    {"uri": w,
                     "alive": (detector is None
                               or detector.is_alive(w)),
                     "prewarmed": co.worker_prewarmed.get(w, False)}
                    for w in list(co.workers)]})
                return
            if path == "/v1/hotshapes":
                # the worker pre-warm feed (exec/hotshapes.py): the
                # top-k hottest compiled-program shapes this
                # coordinator has seen, ranked by hit count then
                # recency. ?k= bounds the list; default is the
                # hot_shape_top_k session default — the same K a
                # joining worker compiles before taking traffic.
                from urllib.parse import parse_qs
                from ..exec.hotshapes import HOT_SHAPES
                from ..session import SESSION_PROPERTIES
                q = parse_qs(urlparse(self.path).query)
                try:
                    k = int((q.get("k") or [0])[0])
                except ValueError:
                    k = 0
                if k <= 0:
                    k = int(SESSION_PROPERTIES["hot_shape_top_k"][1])
                self._send(200, {"shapes": HOT_SHAPES.top(k),
                                 "tracked": len(HOT_SHAPES)})
                return
            if path == "/v1/history":
                # the durable query-history surface (obs/history.py):
                # ?limit= bounds the page, ?state= filters (FINISHED /
                # FAILED / CANCELED)
                from urllib.parse import parse_qs
                qs = parse_qs(urlparse(self.path).query)
                try:
                    limit = int((qs.get("limit") or [0])[0]) or None
                except ValueError:
                    limit = None
                self._send(200, {
                    "records": co.history.records(
                        limit=limit,
                        state=(qs.get("state") or [None])[0]),
                    "tracked": len(co.history)})
                return
            if path == "/v1/stats":
                # learned operator statistics (exec/learnedstats.py):
                # per (plan key, operator, occurrence) selectivity and
                # throughput EMAs, most recently observed first
                from ..exec.learnedstats import LEARNED_STATS
                self._send(200, {
                    "entries": LEARNED_STATS.snapshot(),
                    "tracked": len(LEARNED_STATS)})
                return
            if path == "/v1/continuous":
                self._send(200, {"jobs": co.continuous_query_infos()})
                return
            if len(parts) == 3 and parts[:2] == ["v1", "continuous"]:
                job = co.continuous.get(parts[2])
                if job is None:
                    self._send(404, {"error": "no such job"})
                    return
                self._send(200, job)
                return
            if path == "/v1/trace":
                # bare listing (this 404'd before): recent trace ids +
                # root-span summaries, each expandable at
                # /v1/trace/{query_id}
                self._send(200, {"traces": co.trace_ring.list()})
                return
            if len(parts) == 3 and parts[:2] == ["v1", "trace"]:
                # the finished query's distributed trace as OTLP/JSON
                # (obs/otlp.py ResourceSpans shape) — the pull surface
                # of the export: worker spans share the query's trace
                # id with their true parent span ids, no collector
                # required. 404 until the query has a trace (still
                # running, untraced, or unknown id).
                q = co.tracker.get(parts[2])
                trace = (getattr(q.result, "trace", None)
                         if q is not None and q.result is not None
                         else None)
                if trace is None or not trace.roots:
                    self._send(404, {"error": "no trace for query"})
                    return
                from ..obs.otlp import trace_to_resource_spans
                self._send(200, trace_to_resource_spans(
                    trace, {"trino_tpu.query_id": q.query_id,
                            "trino_tpu.state": q.state,
                            "service.name": "trino_tpu-coordinator"}))
                return
            if len(parts) == 3 and parts[:2] == ["v1", "query"]:
                q = co.tracker.get(parts[2])
                if q is None:
                    # restart recovery, metadata-only: no slug is
                    # presented here, so serve the manifest without
                    # decoding or pinning the persisted rows
                    detail = co.recovered_query_detail(parts[2])
                    if detail is not None:
                        self._send(200, detail)
                        return
                    self._send(404, {"error": "no such query"})
                    return
                self._send(200, co.query_detail(q))
                return
            # /v1/statement/executing/{id}/{slug}/{token}
            if len(parts) == 6 and parts[:3] == ["v1", "statement",
                                                 "executing"]:
                q = co.tracker.get(parts[3])
                if q is None:
                    # a restarted coordinator serving a query the OLD
                    # process ran: rebuild it from the spooled manifest
                    # (slug-checked) and keep paging
                    q = co.recover_query(parts[3], parts[4])
                if q is None:
                    # no FINISHED result on the spool — the old
                    # coordinator died MID-FLIGHT: resume the RUNNING
                    # query from its execution manifest and let this
                    # very poll become the long-poll on the resumed run
                    q = co.resume_query(parts[3], parts[4])
                if q is None or q.slug != parts[4]:
                    self._send(404, {"error": "no such query"})
                    return
                q.wait_done(1.0)   # long-poll like the reference
                self._send(200, co.query_results(q, int(parts[5])))
                return
            self._send(404, {"error": "not found"})

        def do_DELETE(self):
            if not self._authenticate():
                return
            parsed = urlparse(self.path)
            parts = [p for p in parsed.path.split("/") if p]
            if parsed.path == "/v1/announcement":
                from urllib.parse import parse_qs
                uri = (parse_qs(parsed.query).get("uri") or [""])[0]
                left = co.remove_worker(uri) if uri else False
                self._send(200, {"left": left,
                                 "workers": co.live_workers()})
                return
            if len(parts) == 3 and parts[:2] == ["v1", "continuous"]:
                if co.continuous.cancel(parts[2]):
                    self._send(200, {"canceled": parts[2]})
                else:
                    self._send(404, {"error": "no such job"})
                return
            if len(parts) >= 4 and parts[:2] == ["v1", "statement"]:
                co.tracker.cancel(parts[3])
                if co.results is not None:
                    # the client is done with this query: reap its
                    # spooled restart-recovery results now instead of
                    # waiting out the TTL sweep. The slug is the
                    # per-query capability token — destroying durable
                    # results demands it just like reading them does
                    # (recover_query), or any client that can list
                    # query ids could revoke another client's restart
                    # recoverability.
                    slug = parts[4] if len(parts) >= 5 else None
                    q = co.tracker.get(parts[3])
                    owner = q.slug if q is not None else None
                    if owner is None:
                        mf = co.results.load_manifest(parts[3])
                        owner = str(mf.get("slug")) if mf else None
                    if slug is not None and slug == owner:
                        co.results.release(parts[3])
                        if co.manifests is not None:
                            # an abandoned query must not be resumable
                            # by whoever probes its id later
                            co.manifests.release(parts[3])
                    elif slug is not None and co.manifests is not None:
                        # untracked or owned under a different slug:
                        # the presented slug may still match the
                        # EXECUTION manifest (old coordinator died
                        # mid-flight, client gives up instead of
                        # resuming). Gated on ITS OWN slug so it can
                        # be reaped even when a same-id result
                        # artifact answers to a different owner, and
                        # never reaps anyone else's
                        if co.manifests.load(parts[3],
                                             slug=slug) is not None:
                            co.manifests.release(parts[3])
                # 204 carries no body (RFC 7230; a body would desync
                # keep-alive clients)
                self.send_response(204)
                self.send_header("Content-Length", "0")
                self.end_headers()
                return
            self._send(404, {"error": "not found"})

    return Handler
