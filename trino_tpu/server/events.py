"""Query lifecycle event system.

Reference parity: spi/eventlistener/ (EventListener.java, QueryCreated /
QueryCompleted / SplitCompleted event classes — 19 files),
event/QueryMonitor.java:88,130,206 (builds and emits the events),
eventlistener/EventListenerManager.java (fan-out to registered
listeners). Listener exceptions are swallowed — an audit hook must not
fail queries (same contract as the reference)."""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Callable, List, Optional


@dataclass(frozen=True)
class QueryCreatedEvent:
    """spi/eventlistener/QueryCreatedEvent.java"""
    query_id: str
    sql: str
    user: str
    catalog: Optional[str]
    schema: Optional[str]
    create_time: float = field(default_factory=time.time)


@dataclass(frozen=True)
class QueryCompletedEvent:
    """spi/eventlistener/QueryCompletedEvent.java — including the
    QueryStatistics block (peakUserMemoryBytes, spilledBytes,
    operatorSummaries) so listeners can act as an audit/accounting
    sink, not just a lifecycle log."""
    query_id: str
    sql: str
    user: str
    state: str                    # FINISHED | FAILED | CANCELED
    wall_s: float
    rows: int = 0
    error_name: Optional[str] = None
    error_message: Optional[str] = None
    end_time: float = field(default_factory=time.time)
    # QueryStatistics analog (spi/eventlistener/QueryStatistics.java)
    peak_memory_bytes: int = 0
    spill_bytes: int = 0
    # cumulative operator flow: {"input_rows", "output_rows",
    # "output_bytes", "compile_s", "wall_s"} summed over NodeStats
    cumulative_operator_stats: Optional[dict] = None
    # per-operator summaries, one dict per plan node (NodeStats.to_dict)
    operator_summaries: tuple = ()


@dataclass(frozen=True)
class SplitCompletedEvent:
    """spi/eventlistener/SplitCompletedEvent.java"""
    query_id: str
    split_id: str
    wall_s: float


class EventListener:
    """spi/eventlistener/EventListener.java — subclass and override."""

    def query_created(self, event: QueryCreatedEvent) -> None:
        pass

    def query_completed(self, event: QueryCompletedEvent) -> None:
        pass

    def split_completed(self, event: SplitCompletedEvent) -> None:
        pass


class EventListenerManager:
    """eventlistener/EventListenerManager.java — registration + fan-out;
    listener errors are logged-and-dropped, never propagated."""

    def __init__(self):
        self._listeners: List[EventListener] = []

    def add_listener(self, listener: EventListener) -> None:
        self._listeners.append(listener)

    def _fan_out(self, method: str, event) -> None:
        for listener in self._listeners:
            try:
                getattr(listener, method)(event)
            except Exception:
                pass

    def query_created(self, event: QueryCreatedEvent) -> None:
        self._fan_out("query_created", event)

    def query_completed(self, event: QueryCompletedEvent) -> None:
        self._fan_out("query_completed", event)

    def split_completed(self, event: SplitCompletedEvent) -> None:
        self._fan_out("split_completed", event)
