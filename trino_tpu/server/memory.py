"""Cluster memory governance: pool accounting + low-memory killer.

Reference parity: memory/ClusterMemoryManager.java (per-query
reservations aggregated into the GENERAL pool, enforcement of
query.max-memory) + memory/LowMemoryKiller.java
(TotalReservationOnBlockedNodesLowMemoryKiller collapsed to
"kill the largest reservation in the offending scope") +
resource-group soft memory limits (InternalResourceGroup
softMemoryLimit). Redesigned small: one ``ClusterMemoryPool`` tracks a
high-water reservation per query (fed by Executor._reserve capacity
estimates — the engine's single allocation decision point), a
``ClusterMemoryManager`` aggregates reservations per resource group,
and a breach of the pool (or a group's limit) kills the LARGEST query
in the offending scope with a ``CLUSTER_OUT_OF_MEMORY``-shaped error
naming the victim and the pool state. A query exceeding its own
``query_max_memory`` cap fails in-thread with
``EXCEEDED_GLOBAL_MEMORY_LIMIT`` — its reservation is the problem, so
no other query need die for it.

Thread model: reservations arrive from per-query executor threads
(dispatch threads under the coordinator tracker); one lock guards the
ledger. Kill callbacks run OUTSIDE the lock — they take the query's
own state lock (server/coordinator.py _Query._transition) and must
not nest under ours.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional, Tuple

from ..obs.metrics import (LIVE_MEMORY_BEATS, MEMORY_KILLS,
                           MEMORY_POOL_BYTES, MEMORY_POOL_QUERIES)


def parse_data_size(value: str) -> int:
    """Trino DataSize strings ("50GB", "512MB", "1.5GB") or raw byte
    counts -> bytes (io.airlift.units.DataSize, decimal-suffix-free
    subset: the reference uses binary multipliers for B/kB/MB/...)."""
    s = str(value).strip()
    units = {"B": 1, "KB": 1 << 10, "MB": 1 << 20, "GB": 1 << 30,
             "TB": 1 << 40, "PB": 1 << 50}
    up = s.upper()
    for suffix, mult in sorted(units.items(), key=lambda kv: -len(kv[0])):
        if up.endswith(suffix):
            num = s[:-len(suffix)].strip()
            return int(float(num) * mult)
    return int(float(s))


class MemoryGovernanceError(Exception):
    """Raised in the reserving thread when ITS reservation is the
    violation (per-query cap, or the killer chose the caller).
    ``error_name`` feeds errors.classify — the client sees the Trino
    error name, not a generic 500."""

    def __init__(self, message: str, error_name: str):
        super().__init__(message)
        self.error_name = error_name


class ClusterMemoryPool:
    """The GENERAL pool: per-query high-water reservations against one
    cluster-wide byte budget (memory/ClusterMemoryPool.java)."""

    def __init__(self, max_bytes: int, name: str = "general"):
        self.name = name
        self.max_bytes = int(max_bytes)
        self._lock = threading.Lock()
        # qid -> ({source: bytes}, group full name). A query's
        # reservation is the SUM of its per-source high-water marks:
        # "coordinator" is the local executor's capacity estimates
        # (the pre-PR-14 figure), and every worker TASK that streams
        # live reservation beats (server/task_worker.py
        # liveMemoryBytes) contributes its own source — the reference
        # sums per-node task reservations the same way.
        self._reservations: Dict[str, Tuple[Dict[str, int], str]] = {}
        # running total maintained by source-level deltas: live-memory
        # beats arrive per status poll (20Hz per running task), so the
        # per-beat cost must not be a full-ledger re-sum under the
        # same lock the executors' reserve() path contends for
        self._total = 0
        MEMORY_POOL_BYTES.set(self.max_bytes, kind="total")
        MEMORY_POOL_BYTES.set(0, kind="reserved")

    # -- ledger ---------------------------------------------------------
    def _publish_locked(self) -> int:
        # gauges published under the lock: a preempted stale publish
        # would otherwise overwrite a newer total and persist on an
        # idle pool
        MEMORY_POOL_BYTES.set(self._total, kind="reserved")
        MEMORY_POOL_QUERIES.set(len(self._reservations))
        return self._total

    def set_reservation(self, qid: str, nbytes: int, group: str,
                        source: str = "coordinator"
                        ) -> Tuple[int, int]:
        """Record ``qid``'s high-water reservation for one source;
        returns (the query's current total reservation, the pool
        total) so the caller never re-scans the ledger on the
        per-allocation hot path."""
        with self._lock:
            entry = self._reservations.get(qid)
            if entry is None:
                entry = ({}, group)
                self._reservations[qid] = entry
            srcs = entry[0]     # mutated in place: only ever read
            #                     under this same lock
            prev = srcs.get(source, 0)
            if int(nbytes) > prev:
                srcs[source] = int(nbytes)
                self._total += int(nbytes) - prev
            mine = sum(srcs.values())
            total = self._publish_locked()
        return mine, total

    def clear_source(self, qid: str, source: str) -> None:
        """Drop one source's reservation (a worker task/attempt
        reached a terminal state: its memory is free on the worker,
        so the pool must stop charging the query for it — otherwise
        retried attempts and sequential stage tasks ACCUMULATE dead
        high-water marks until the killer fires on a query that never
        held that much at once). The coordinator source stays
        monotonic, exactly as before."""
        with self._lock:
            entry = self._reservations.get(qid)
            if entry is None:
                return
            self._total -= entry[0].pop(source, 0)
            self._publish_locked()

    def free(self, qid: str) -> None:
        with self._lock:
            entry = self._reservations.pop(qid, None)
            if entry is not None:
                self._total -= sum(entry[0].values())
            self._publish_locked()

    def reserved_bytes(self, group: Optional[str] = None) -> int:
        with self._lock:
            return sum(sum(srcs.values())
                       for srcs, g in self._reservations.values()
                       if group is None or g == group)

    def queries(self, group: Optional[str] = None
                ) -> List[Tuple[str, int, str]]:
        """(qid, bytes, group) snapshots, largest first."""
        with self._lock:
            items = [(q, sum(srcs.values()), g) for q, (srcs, g)
                     in self._reservations.items()
                     if group is None or g == group]
        return sorted(items, key=lambda t: -t[1])

    def info(self) -> dict:
        """system.runtime / /v1/cluster-shaped pool state."""
        with self._lock:
            items = sorted(
                ((q, sum(srcs.values()), g,
                  sum(1 for s in srcs if s != "coordinator"))
                 for q, (srcs, g) in self._reservations.items()),
                key=lambda t: -t[1])
            total = sum(b for _, b, _, _ in items)
        return {"pool": self.name, "maxBytes": self.max_bytes,
                "reservedBytes": total,
                "freeBytes": max(0, self.max_bytes - total),
                "queries": [{"queryId": q, "reservedBytes": b,
                             "group": g, "workerSources": ws}
                            for q, b, g, ws in items]}

    def describe(self, group: Optional[str] = None) -> str:
        """Human-readable pool state for kill messages — the operator
        reads WHICH queries held WHAT when the killer fired."""
        items = self.queries(group)[:5]
        held = ", ".join(f"{q}={b}B" for q, b, _ in items) or "none"
        scope = f"group {group}" if group else f"pool {self.name}"
        return (f"{scope}: reserved {self.reserved_bytes(group)} of "
                f"{self.max_bytes} bytes; top reservations: {held}")


class ClusterMemoryManager:
    """Registration + enforcement: every tracked query registers with
    its group, limits, and a kill callback; ``reserve`` (called from
    the executor via the per-query ``QueryMemoryContext``) updates the
    ledger and runs the low-memory killer when the pool or the
    query's group goes over budget."""

    def __init__(self, pool: ClusterMemoryPool):
        self.pool = pool
        self._lock = threading.Lock()
        # qid -> (kill_fn(message, error_name), group, group_limit,
        #         query_limit)
        self._queries: Dict[str, Tuple[Callable[[str, str], None],
                                       str, int, int]] = {}
        self.kills = 0

    # -- lifecycle ------------------------------------------------------
    def register(self, qid: str, group: str = "global",
                 kill_fn: Optional[Callable[[str, str], None]] = None,
                 group_limit_bytes: int = 0,
                 query_limit_bytes: int = 0) -> "QueryMemoryContext":
        with self._lock:
            self._queries[qid] = (kill_fn or (lambda m, n: None),
                                  group, int(group_limit_bytes),
                                  int(query_limit_bytes))
        return QueryMemoryContext(self, qid)

    def unregister(self, qid: str) -> None:
        with self._lock:
            self._queries.pop(qid, None)
        self.pool.free(qid)

    # -- enforcement ----------------------------------------------------
    def reserve(self, qid: str, nbytes: int) -> None:
        """Record ``qid``'s high-water reservation and enforce, in
        order: the per-query cap (fails the caller), the group limit,
        then the pool limit (each kills the LARGEST query in its
        scope). Raises MemoryGovernanceError when the calling query is
        the one that must stop."""
        with self._lock:
            # registration check and ledger write are ONE atomic step
            # w.r.t. _kill_largest's pop+free (same lock): a victim
            # killed mid-reserve must not re-insert its reservation
            # as a zombie that later gets an innocent query killed
            entry = self._queries.get(qid)
            if entry is None:
                return                   # unregistered: nothing governs
            _, group, group_limit, query_limit = entry
            mine, total = self.pool.set_reservation(qid, nbytes, group)
        if query_limit > 0 and mine > query_limit:
            self.pool.free(qid)
            raise MemoryGovernanceError(
                f"Query {qid} exceeded the global memory limit of "
                f"{query_limit} bytes (reserved {mine} bytes; "
                f"{self.pool.describe(group)})",
                "EXCEEDED_GLOBAL_MEMORY_LIMIT")
        self._relieve_cache_pressure(total)
        if group_limit > 0 \
                and self.pool.reserved_bytes(group) > group_limit:
            self._kill_largest(group, group_limit, caller=qid)
        if self.pool.max_bytes > 0 and total > self.pool.max_bytes:
            self._kill_largest(None, self.pool.max_bytes, caller=qid)

    def reserve_remote(self, qid: str, source: str,
                       nbytes: int) -> None:
        """Fold a WORKER task's live reservation beat into the ledger
        and enforce. Unlike ``reserve`` this never raises: the calling
        thread is a status-poll/page-pull thread, not the governed
        query's executor — every verdict lands through the victim's
        kill callback (whose cancel event propagates to worker tasks
        as a DELETE). This is the live half of the low-memory killer:
        a query ballooning ON a worker is judged by the bytes it
        actually holds there, DURING execution, not by coordinator
        estimates or completion-time peaks."""
        kill_fn = None
        with self._lock:
            entry = self._queries.get(qid)
            if entry is None:
                return                   # finished/killed: stale beat
            _, group, group_limit, query_limit = entry
            mine, total = self.pool.set_reservation(
                qid, nbytes, group, source=source)
            if query_limit > 0 and mine > query_limit:
                # the per-query cap breach is the query's own fault:
                # retire it under the lock (registry + ledger in one
                # step, like _kill_largest) and kill it outside
                kill_fn = entry[0]
                self._queries.pop(qid, None)
                self.kills += 1
                self.pool.free(qid)
                msg = (f"Query {qid} exceeded the global memory limit "
                       f"of {query_limit} bytes (live worker "
                       f"reservations reached {mine} bytes; "
                       f"{self.pool.describe(group)})")
        LIVE_MEMORY_BEATS.inc()
        if kill_fn is not None:
            MEMORY_KILLS.inc()
            kill_fn(msg, "EXCEEDED_GLOBAL_MEMORY_LIMIT")
            return
        self._relieve_cache_pressure(total)
        if group_limit > 0 \
                and self.pool.reserved_bytes(group) > group_limit:
            self._kill_largest(group, group_limit, caller=None)
        if self.pool.max_bytes > 0 and total > self.pool.max_bytes:
            self._kill_largest(None, self.pool.max_bytes, caller=None)

    def _relieve_cache_pressure(self, reserved_total: int) -> None:
        """Cross-query cache governance: the shared scan/jit/replicate
        caches occupy the same memory the pool budgets, so when
        reservations + cache residency exceed the pool, evict cache
        entries FIRST — a cache full of one query's tables/programs
        must never get a neighbor query killed. Only if reservations
        ALONE still breach the pool does the killer run."""
        if self.pool.max_bytes <= 0:
            return
        try:
            from ..exec.executor import (cache_memory_bytes,
                                         evict_cache_pressure)
            cached = cache_memory_bytes()
            if cached > 0 \
                    and reserved_total + cached > self.pool.max_bytes:
                evict_cache_pressure(
                    reserved_total + cached - self.pool.max_bytes)
        except Exception:   # noqa: BLE001 — relief is best-effort;
            pass            # enforcement below never depends on it

    def _kill_largest(self, group: Optional[str], limit: int,
                      caller: Optional[str]) -> None:
        """LowMemoryKiller: cancel the single largest registered query
        in the offending scope. The victim's kill callback fails it
        with CLUSTER_OUT_OF_MEMORY naming the victim and the pool
        state; if the victim IS the caller, raise instead so the
        error surfaces on its own executor thread immediately.
        ``caller=None`` (remote live-beat feeds) always uses the kill
        callback — the feeding thread is never the victim's own
        executor."""
        victim = kill_fn = None
        vbytes = 0
        with self._lock:
            # re-check the breach under the lock: two threads that
            # BOTH observed an over-budget pool must not each kill a
            # query when freeing one victim already cures it
            if self.pool.reserved_bytes(group) <= limit:
                return
            for q, b, g in self.pool.queries(group):
                entry = self._queries.get(q)
                if entry is None:
                    continue             # finished between snapshots
                victim, vbytes = q, b
                kill_fn = entry[0]
                break
            if victim is None:
                return
            scope = f"resource group {group}" if group else "cluster"
            msg = (f"The cluster is out of memory ({scope} limit "
                   f"{limit} bytes exceeded) and the low-memory "
                   f"killer canceled query {victim} (largest "
                   f"reservation, {vbytes} bytes). Pool state before "
                   f"the kill — {self.pool.describe(group)}")
            # registry drop AND ledger free stay under the lock: a
            # racing reserve re-checking the breach must already see
            # the pool state this kill produces, or one breach kills
            # two queries
            self._queries.pop(victim, None)
            self.kills += 1
            self.pool.free(victim)
        MEMORY_KILLS.inc()
        if caller is not None and victim == caller:
            raise MemoryGovernanceError(msg, "CLUSTER_OUT_OF_MEMORY")
        kill_fn(msg, "CLUSTER_OUT_OF_MEMORY")

    def info(self) -> dict:
        out = self.pool.info()
        out["kills"] = self.kills
        return out


class QueryMemoryContext:
    """The per-query handle the executor feeds (Session.memory).
    ``reserve(bytes)`` is called from Executor._reserve with each
    capacity estimate; the manager keeps the high-water mark."""

    __slots__ = ("_manager", "query_id")

    def __init__(self, manager: ClusterMemoryManager, query_id: str):
        self._manager = manager
        self.query_id = query_id

    def reserve(self, nbytes: int) -> None:
        self._manager.reserve(self.query_id, nbytes)

    def reserve_remote(self, source: str, nbytes: int) -> None:
        """Fold a worker task's live reservation beat into the pool
        (never raises — verdicts land through the kill callback). The
        remote/stage schedulers feed this from task-status polls."""
        self._manager.reserve_remote(self.query_id, source, nbytes)

    def release_remote(self, source: str) -> None:
        """Drop one task attempt's live reservation (the attempt is
        terminal: its memory is free on the worker). Called by the
        schedulers when an attempt completes or fails, so retries and
        sequential stages never accumulate dead high-water marks."""
        self._manager.pool.clear_source(self.query_id, source)

    def budget_bytes(self) -> Optional[int]:
        """The tightest byte budget governing this query (its own
        query_max_memory cap, its group's soft limit, the pool size) —
        None when nothing binds. The streaming engagement check
        (exec/streamjoin.py memory_budget) consults this so a query
        that would breach the POOL un-streamed engages streaming and
        reserves its streamed peak instead of getting killed on the
        full-materialization estimate."""
        m = self._manager
        with m._lock:
            entry = m._queries.get(self.query_id)
        if entry is None:
            return None
        _, _, group_limit, query_limit = entry
        vals = [v for v in (query_limit, group_limit,
                            m.pool.max_bytes) if v and v > 0]
        return min(vals) if vals else None
