from .coordinator import Coordinator  # noqa: F401
