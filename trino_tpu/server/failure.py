"""Heartbeat failure detector.

Reference parity: failuredetector/HeartbeatFailureDetector.java:78,93,
221,318-351 — the coordinator pings every known service on a fixed
cadence and tracks an EXPONENTIALLY DECAYED failure ratio per node;
nodes above ``failure_ratio_threshold`` are reported failed and the
scheduler excludes them (NodeScheduler consulting the detector). Ours
pings the worker's /v1/info (server/task_worker.py exposes it) or any
HTTP URI; a pluggable ``probe`` hook lets tests inject failures."""

from __future__ import annotations

import json
import math
import threading
import time
import urllib.request
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional


@dataclass
class _Stats:
    """Per-service decayed failure ratio
    (HeartbeatFailureDetector.Stats)."""
    decay_seconds: float = 30.0
    weight: float = 0.0           # decayed total probes
    failed: float = 0.0           # decayed failures
    last_update: float = field(default_factory=time.time)
    last_failure: Optional[str] = None

    def _decay(self, now: float) -> None:
        dt = max(0.0, now - self.last_update)
        k = math.exp(-dt / self.decay_seconds)
        self.weight *= k
        self.failed *= k
        self.last_update = now

    def record(self, success: bool, error: Optional[str] = None):
        now = time.time()
        self._decay(now)
        self.weight += 1.0
        if not success:
            self.failed += 1.0
            self.last_failure = error

    @property
    def failure_ratio(self) -> float:
        if self.weight <= 0:
            return 0.0
        return self.failed / self.weight


class HeartbeatFailureDetector:
    """Background pinger + failed-node query surface."""

    def __init__(self, interval_s: float = 0.5,
                 failure_ratio_threshold: float = 0.1,
                 warmup_probes: int = 2,
                 probe: Optional[Callable[[str], bool]] = None,
                 timeout_s: float = 2.0):
        self.interval_s = interval_s
        self.threshold = failure_ratio_threshold
        self.warmup = warmup_probes
        self.timeout_s = timeout_s
        self._probe = probe or self._http_probe
        self._stats: Dict[str, _Stats] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _http_probe(self, uri: str) -> bool:
        try:
            with urllib.request.urlopen(uri.rstrip("/") + "/v1/info",
                                        timeout=self.timeout_s) as r:
                json.loads(r.read())
            return True
        except Exception:
            return False

    # --- membership ------------------------------------------------------
    def add_service(self, uri: str) -> None:
        with self._lock:
            self._stats.setdefault(uri, _Stats())

    def remove_service(self, uri: str) -> None:
        with self._lock:
            self._stats.pop(uri, None)

    def services(self) -> List[str]:
        with self._lock:
            return list(self._stats)

    # --- probing ---------------------------------------------------------
    def probe_once(self) -> None:
        for uri in self.services():
            ok = False
            err = None
            try:
                ok = self._probe(uri)
            except Exception as e:
                err = str(e)
            with self._lock:
                st = self._stats.get(uri)
                if st is not None:
                    st.record(ok, err)

    # --- scheduler feedback ----------------------------------------------
    def record_task_failure(self, uri: str,
                            error: Optional[str] = None) -> None:
        """An observed task failure on a node is a failed probe: the
        scheduler (exec/remote.py) reports dispatch/exchange errors here
        so the decayed ratio reflects real work, not just pings — the
        reference's RemoteTask failure feedback into the failure
        detector. Auto-registers unknown services (a worker can fail a
        task before its first heartbeat)."""
        with self._lock:
            self._stats.setdefault(uri, _Stats()).record(False, error)

    def record_task_success(self, uri: str) -> None:
        with self._lock:
            self._stats.setdefault(uri, _Stats()).record(True)

    def start(self) -> "HeartbeatFailureDetector":
        """Start the active probe loop. Idempotent AND thread-safe:
        live-membership joins call this from HTTP handler threads on
        every announcement (the detector may have been created before
        any worker existed) while main.py may call it from the main
        thread — without the lock the check-then-act could spawn two
        probe loops, doubling every node's probe weight with no way
        to stop the orphan."""
        with self._lock:
            return self._start_locked()

    def _start_locked(self) -> "HeartbeatFailureDetector":
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop.clear()

        def loop():
            while not self._stop.wait(self.interval_s):
                self.probe_once()
        self._thread = threading.Thread(target=loop, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()

    # --- queries ---------------------------------------------------------
    def is_alive(self, uri: str) -> bool:
        with self._lock:
            st = self._stats.get(uri)
            if st is None or st.weight < self.warmup:
                return True       # unknown/warming-up nodes pass
            # stale evidence ages out: ``_decay`` only runs inside
            # record(), so a node that stops receiving probes
            # (feedback-only detectors have no probe loop) would keep
            # its last ratio forever — a couple of transient task
            # failures would exclude it permanently and, excluded, it
            # never gets the task that could redeem it. After four
            # quiet decay windows the verdict expires and the node
            # earns a fresh chance.
            if time.time() - st.last_update > 4 * st.decay_seconds:
                return True
            return st.failure_ratio <= self.threshold

    def failed(self) -> List[str]:
        return [u for u in self.services() if not self.is_alive(u)]
