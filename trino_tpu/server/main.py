"""Server entry point: ``python -m trino_tpu.server.main`` (or the
``trino-tpu-server`` console script).

Reference parity: core/trino-server-main (TrinoServer.java) +
server/Server.java bootstrap + the airlift config loading model:
``etc/config.properties`` (http-server.http.port, coordinator=...),
``etc/catalog/*.properties`` (connector.name=tpch|memory|...) —
metadata/CatalogManager + connector/ConnectorManager analog."""

from __future__ import annotations

import argparse
import os
import signal
import sys
from typing import Dict, Optional


def load_properties(path: str) -> Dict[str, str]:
    """key=value lines, '#' comments (airlift config format)."""
    out: Dict[str, str] = {}
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            if "=" in line:
                k, _, v = line.partition("=")
                out[k.strip()] = v.strip()
    return out


def build_catalogs(etc_dir: Optional[str],
                   plugins: Optional[list] = None):
    """etc/catalog/*.properties -> CatalogManager via the plugin
    registry (connector.name selects the factory — the reference's
    catalog property files + PluginManager; trino_tpu/plugin.py)."""
    from .. import plugin
    from ..catalog import CatalogManager
    for mod in plugins or []:
        plugin.load_plugin(mod)
    cat_dir = os.path.join(etc_dir, "catalog") if etc_dir else None
    mgr = CatalogManager()
    made = False
    if cat_dir and os.path.isdir(cat_dir):
        for fn in sorted(os.listdir(cat_dir)):
            if not fn.endswith(".properties"):
                continue
            name = fn[:-len(".properties")]
            props = load_properties(os.path.join(cat_dir, fn))
            kind = props.get("connector.name", name)
            try:
                mgr.register(name, plugin.create_connector(
                    kind, name, props))
            except KeyError as e:
                print(f"warning: {e} for catalog {name}",
                      file=sys.stderr)
            made = True
    if not made:
        for kind in ("tpch", "tpcds", "memory", "blackhole",
                     "stream"):
            mgr.register(kind, plugin.create_connector(kind, kind))
    return mgr


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="trino-tpu-server")
    ap.add_argument("--etc-dir", default=None,
                    help="config directory (config.properties + "
                         "catalog/*.properties)")
    ap.add_argument("--port", type=int, default=None)
    ap.add_argument("--distributed", action="store_true",
                    help="execute over the device mesh")
    ap.add_argument("--workers", default=None,
                    help="comma-separated worker base URIs to dispatch "
                         "leaf fragments to (exec/remote.py); also "
                         "settable as worker.uris in config.properties")
    ap.add_argument("--role", choices=("coordinator", "worker"),
                    default=None,
                    help="worker starts a task server instead of a "
                         "coordinator (node.role in config.properties; "
                         "the reference's coordinator=true|false). "
                         "Default: coordinator")
    ap.add_argument("--coordinator-uri", default=None,
                    help="[worker role] coordinator to announce this "
                         "worker to (/v1/announcement; re-announced on "
                         "a cadence so a restarted coordinator re-"
                         "learns the fleet). Also discovery.uri in "
                         "config.properties")
    ap.add_argument("--coordinator-token", default=None,
                    help="[worker role] Bearer token sent with every "
                         "announcement — required when the coordinator "
                         "authenticates requests. Also discovery.token "
                         "in config.properties / env "
                         "TRINO_TPU_COORDINATOR_TOKEN")
    ap.add_argument("--prewarm-top-k", type=int, default=None,
                    help="[worker role] how many of the coordinator's "
                         "hot shapes to AOT-compile before advertising "
                         "this worker warm (GET /v1/hotshapes; default "
                         "env TRINO_TPU_PREWARM_TOP_K; pre-warm "
                         "disabled entirely via TRINO_TPU_PREWARM=0 or "
                         "prewarm.enabled=false)")
    ap.add_argument("--task-runners", type=int, default=None,
                    help="[worker role] size of the shared split-"
                         "scheduler runner pool time-slicing all "
                         "concurrent queries' tasks (exec/taskexec.py; "
                         "0 = auto, max(4, 2 x cores)). Also "
                         "task.runner-threads in config.properties / "
                         "env TRINO_TPU_TASK_RUNNERS")
    ap.add_argument("--spool-backend", default=None,
                    help="fault-tolerance spool backend: 'local' "
                         "(directory tree) or 'memory' (object-store "
                         "code path, in-process emulation); also "
                         "spool.backend in config.properties / env "
                         "TRINO_TPU_SPOOL_BACKEND")
    args = ap.parse_args(argv)

    props: Dict[str, str] = {}
    if args.etc_dir:
        cfg = os.path.join(args.etc_dir, "config.properties")
        if os.path.exists(cfg):
            props = load_properties(cfg)
    # plugin.load=<module>[,<module>...] loads external plugin modules
    # before catalogs resolve (server/PluginManager.java)
    plugins = [m for m in props.get("plugin.load", "").split(",") if m]
    port = args.port if args.port is not None else \
        int(props.get("http-server.http.port", "8080"))

    # explicit CLI flag beats config.properties (same precedence as
    # --port/--workers); only an omitted flag falls through to props
    role = args.role or props.get("node.role", "coordinator")
    if role == "worker":
        return _worker_main(args, props, port)

    from .coordinator import Coordinator
    resource_groups = None
    rg_path = props.get("resource-groups.config-file")
    if rg_path:
        import json as _json
        from .resourcegroups import ResourceGroupManager
        with open(rg_path) as f:
            resource_groups = ResourceGroupManager.from_config(
                _json.load(f))
    authenticator = None
    pw_path = props.get("password-authenticator.file")
    if pw_path:
        from ..security import load_password_file
        with open(pw_path) as f:
            authenticator = load_password_file(f.read())

    workers = [w.strip() for w in
               (args.workers or props.get("worker.uris", "")).split(",")
               if w.strip()]

    spool_backend = (args.spool_backend
                     or props.get("spool.backend") or None)

    # cluster memory pool sizing (server/memory.py): config.properties
    # query.max-memory (the reference's property name, accepting its
    # DataSize strings — "50GB" — as well as raw bytes) beats the env
    # default TRINO_TPU_CLUSTER_MEMORY_POOL; None keeps the config
    # default (0 = governance off)
    pool_bytes = None
    if props.get("query.max-memory"):
        from .memory import parse_data_size
        pool_bytes = parse_data_size(props["query.max-memory"])

    co = Coordinator(port=port,
                     distributed=args.distributed,
                     catalogs=build_catalogs(args.etc_dir, plugins),
                     resource_groups=resource_groups,
                     authenticator=authenticator,
                     worker_uris=workers,
                     spool_backend=spool_backend,
                     memory_pool_bytes=pool_bytes).start()
    if workers and co.failure_detector is not None:
        # a configured fleet gets the active heartbeat loop on top of
        # the scheduler's task-failure feedback
        co.failure_detector.start()
    print(f"trino-tpu coordinator listening on {co.base_uri}"
          f" (web UI: {co.base_uri}/ui)")
    _announce_fault_points()

    stop = {"flag": False}

    def on_signal(sig, frame):
        print("draining...", file=sys.stderr)
        co.drain(timeout=30.0)
        stop["flag"] = True

    signal.signal(signal.SIGINT, on_signal)
    signal.signal(signal.SIGTERM, on_signal)
    import time
    while not stop["flag"]:
        time.sleep(0.2)
    return 0


def _announce_fault_points() -> None:
    """Startup banner for TRINO_TPU_FAULTPOINTS (fte/faultpoints.py):
    an armed fault schedule changes what this process will do — an
    operator reading the log must see it, and a malformed spec must
    fail LOUDLY at boot instead of silently arming nothing."""
    spec = os.environ.get("TRINO_TPU_FAULTPOINTS", "").strip()
    if not spec:
        return
    from ..fte.faultpoints import armed_sites, parse_schedule
    parse_schedule(spec)     # raises ValueError on a malformed spec
    armed = armed_sites()
    print("FAULT INJECTION ARMED (TRINO_TPU_FAULTPOINTS): "
          + ", ".join(f"{site}={action}"
                      for site, action in sorted(armed.items())),
          file=sys.stderr)


def _worker_main(args, props: Dict[str, str], port: int) -> int:
    """Worker role: a TaskWorkerServer that joins a coordinator's
    worker set at runtime (/v1/announcement) — the elastic half of the
    cluster. Start any number of these against one coordinator; each
    announces itself now and on a cadence, so a RESTARTED coordinator
    re-learns the fleet at the next beat, and stop() sends the
    graceful leave."""
    from .task_worker import TaskWorkerServer
    spool_backend = (args.spool_backend
                     or props.get("spool.backend") or None)
    plugins = [m for m in props.get("plugin.load", "").split(",") if m]
    task_runners = args.task_runners
    if task_runners is None and props.get("task.runner-threads"):
        task_runners = int(props["task.runner-threads"])
    srv = TaskWorkerServer(
        port=port, spool_backend=spool_backend,
        task_runners=task_runners,
        # the worker resolves the same etc/catalog configs the
        # coordinator dispatches fragments against — without this a
        # fragment naming an operator-configured catalog fails on
        # every attempt
        catalogs=build_catalogs(args.etc_dir, plugins)).start()
    coordinator_uri = (args.coordinator_uri
                       or props.get("discovery.uri") or None)
    token = (args.coordinator_token or props.get("discovery.token")
             or os.environ.get("TRINO_TPU_COORDINATOR_TOKEN") or None)
    if coordinator_uri:
        from ..config import CONFIG
        prewarm = CONFIG.prewarm_enabled
        if props.get("prewarm.enabled", "").lower() in ("false", "0"):
            prewarm = False
        top_k = args.prewarm_top_k
        if top_k is None and props.get("prewarm.top-k"):
            top_k = int(props["prewarm.top-k"])
        joined = srv.announce(coordinator_uri, token=token,
                              prewarm=prewarm,
                              prewarm_top_k=top_k)
        print(f"trino-tpu worker {srv.node_id} on {srv.base_uri} "
              f"({'joined' if joined else 'announcing to'} "
              f"{coordinator_uri}"
              + (", pre-warming hot shapes" if prewarm else "") + ")")
    else:
        print(f"trino-tpu worker {srv.node_id} on {srv.base_uri} "
              "(standalone: pass --coordinator-uri to join a cluster)")
    _announce_fault_points()

    stop = {"flag": False}

    def on_signal(sig, frame):
        srv.stop()               # graceful leave + server shutdown
        stop["flag"] = True

    signal.signal(signal.SIGINT, on_signal)
    signal.signal(signal.SIGTERM, on_signal)
    import time
    while not stop["flag"]:
        time.sleep(0.2)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
