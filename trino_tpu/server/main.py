"""Server entry point: ``python -m trino_tpu.server.main`` (or the
``trino-tpu-server`` console script).

Reference parity: core/trino-server-main (TrinoServer.java) +
server/Server.java bootstrap + the airlift config loading model:
``etc/config.properties`` (http-server.http.port, coordinator=...),
``etc/catalog/*.properties`` (connector.name=tpch|memory|...) —
metadata/CatalogManager + connector/ConnectorManager analog."""

from __future__ import annotations

import argparse
import os
import signal
import sys
from typing import Dict, Optional


def load_properties(path: str) -> Dict[str, str]:
    """key=value lines, '#' comments (airlift config format)."""
    out: Dict[str, str] = {}
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            if "=" in line:
                k, _, v = line.partition("=")
                out[k.strip()] = v.strip()
    return out


def build_catalogs(etc_dir: Optional[str],
                   plugins: Optional[list] = None):
    """etc/catalog/*.properties -> CatalogManager via the plugin
    registry (connector.name selects the factory — the reference's
    catalog property files + PluginManager; trino_tpu/plugin.py)."""
    from .. import plugin
    from ..catalog import CatalogManager
    for mod in plugins or []:
        plugin.load_plugin(mod)
    cat_dir = os.path.join(etc_dir, "catalog") if etc_dir else None
    mgr = CatalogManager()
    made = False
    if cat_dir and os.path.isdir(cat_dir):
        for fn in sorted(os.listdir(cat_dir)):
            if not fn.endswith(".properties"):
                continue
            name = fn[:-len(".properties")]
            props = load_properties(os.path.join(cat_dir, fn))
            kind = props.get("connector.name", name)
            try:
                mgr.register(name, plugin.create_connector(
                    kind, name, props))
            except KeyError as e:
                print(f"warning: {e} for catalog {name}",
                      file=sys.stderr)
            made = True
    if not made:
        for kind in ("tpch", "tpcds", "memory", "blackhole"):
            mgr.register(kind, plugin.create_connector(kind, kind))
    return mgr


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="trino-tpu-server")
    ap.add_argument("--etc-dir", default=None,
                    help="config directory (config.properties + "
                         "catalog/*.properties)")
    ap.add_argument("--port", type=int, default=None)
    ap.add_argument("--distributed", action="store_true",
                    help="execute over the device mesh")
    ap.add_argument("--workers", default=None,
                    help="comma-separated worker base URIs to dispatch "
                         "leaf fragments to (exec/remote.py); also "
                         "settable as worker.uris in config.properties")
    args = ap.parse_args(argv)

    props: Dict[str, str] = {}
    if args.etc_dir:
        cfg = os.path.join(args.etc_dir, "config.properties")
        if os.path.exists(cfg):
            props = load_properties(cfg)
    # plugin.load=<module>[,<module>...] loads external plugin modules
    # before catalogs resolve (server/PluginManager.java)
    plugins = [m for m in props.get("plugin.load", "").split(",") if m]
    port = args.port if args.port is not None else \
        int(props.get("http-server.http.port", "8080"))

    from .coordinator import Coordinator
    resource_groups = None
    rg_path = props.get("resource-groups.config-file")
    if rg_path:
        import json as _json
        from .resourcegroups import ResourceGroupManager
        with open(rg_path) as f:
            resource_groups = ResourceGroupManager.from_config(
                _json.load(f))
    authenticator = None
    pw_path = props.get("password-authenticator.file")
    if pw_path:
        from ..security import load_password_file
        with open(pw_path) as f:
            authenticator = load_password_file(f.read())

    workers = [w.strip() for w in
               (args.workers or props.get("worker.uris", "")).split(",")
               if w.strip()]

    co = Coordinator(port=port,
                     distributed=args.distributed,
                     catalogs=build_catalogs(args.etc_dir, plugins),
                     resource_groups=resource_groups,
                     authenticator=authenticator,
                     worker_uris=workers).start()
    if workers and co.failure_detector is not None:
        # a configured fleet gets the active heartbeat loop on top of
        # the scheduler's task-failure feedback
        co.failure_detector.start()
    print(f"trino-tpu coordinator listening on {co.base_uri}"
          f" (web UI: {co.base_uri}/ui)")

    stop = {"flag": False}

    def on_signal(sig, frame):
        print("draining...", file=sys.stderr)
        co.drain(timeout=30.0)
        stop["flag"] = True

    signal.signal(signal.SIGINT, on_signal)
    signal.signal(signal.SIGTERM, on_signal)
    import time
    while not stop["flag"]:
        time.sleep(0.2)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
