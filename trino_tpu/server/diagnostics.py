"""Leak and orphan detection — the reference's leak-analysis tier.

Reference parity: execution/QueryTracker's enforceTimeLimits +
ClusterMemoryLeakDetector (queries gone from the tracker but still
holding reserved memory) and the testing harness's thread-leak checks
(TestingTrinoServer asserts no stray query threads after close).

``leak_report`` snapshots the suspicious state; ``ThreadLeakGuard``
wraps a scope (a test, a drain) and reports threads that outlive it.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional


@dataclass
class LeakReport:
    """One snapshot of would-be leaks; empty lists == clean."""
    stuck_queries: List[str] = field(default_factory=list)
    retained_results_bytes: int = 0
    scan_cache_bytes: int = 0
    spill_files: List[str] = field(default_factory=list)
    orphaned_threads: List[str] = field(default_factory=list)

    @property
    def clean(self) -> bool:
        return not (self.stuck_queries or self.spill_files
                    or self.orphaned_threads)


def leak_report(coordinator, stuck_after_s: float = 3600.0,
                now: Optional[float] = None,
                orphan_grace_s: float = 5.0) -> LeakReport:
    """Inspect a Coordinator for leak analogs:
    - queries RUNNING longer than ``stuck_after_s`` (the
      enforceTimeLimits sweep's candidates),
    - result sets retained by terminal queries (memory the tracker
      still pins),
    - HBM scan-cache residency,
    - spill files left on disk,
    - query-runner threads outliving their query's terminal state."""
    now = time.time() if now is None else now
    rep = LeakReport()
    for q in coordinator.tracker.all():
        if q.state == "RUNNING" and now - q.created > stuck_after_s:
            rep.stuck_queries.append(q.query_id)
        if q.result is not None:
            # rough: rows x columns x 8 (the tracker pins results for
            # the paging protocol; a terminal query kept forever is
            # the ClusterMemoryLeakDetector shape)
            rep.retained_results_bytes += (
                len(q.result.rows) * max(len(q.result.columns), 1) * 8)
    from ..exec import executor as ex
    with ex._SCAN_CACHE_LOCK:
        rep.scan_cache_bytes = sum(
            s["bytes"] for s in ex._SCAN_CACHES.values())
    from ..serde import Spiller
    rep.spill_files = Spiller.live_files()
    # a thread is orphaned only when its query has been terminal for
    # longer than the grace window — the run thread legitimately winds
    # down (event listeners, group release) for a moment after _done
    ended_at = {q.query_id: q.ended
                for q in coordinator.tracker.all()
                if q.state in ("FINISHED", "FAILED", "CANCELED")}
    for t in threading.enumerate():
        qid = getattr(t, "trino_query_id", None)
        if qid is None or qid not in ended_at or not t.is_alive():
            continue
        ended = ended_at[qid]
        if ended is None or now - ended > orphan_grace_s:
            rep.orphaned_threads.append(f"{t.name} (query {qid})")
    return rep


class ThreadLeakGuard:
    """Context manager flagging threads created inside the scope that
    are still alive at exit (the TestingTrinoServer close() check)."""

    def __init__(self, grace_s: float = 2.0,
                 ignore_prefixes: tuple = ("pydevd", "IPython")):
        self.grace_s = grace_s
        self.ignore_prefixes = ignore_prefixes
        self.leaked: List[str] = []

    def __enter__(self):
        self._before = set(threading.enumerate())
        return self

    def _new_alive(self):
        # daemon threads count: the coordinator's query threads are
        # daemons and are exactly the leak class this guard exists for
        return [t for t in threading.enumerate()
                if t not in self._before and t.is_alive()
                and not t.name.startswith(self.ignore_prefixes)]

    def __exit__(self, *exc):
        deadline = time.time() + self.grace_s
        while time.time() < deadline:
            if not self._new_alive():
                break
            time.sleep(0.05)
        else:
            self.leaked = [t.name for t in self._new_alive()]
        return False
