"""Worker task runtime + host data plane (the DCN leg).

Reference parity: the coordinator->worker task stack and the pull-based
page exchange —
  server/TaskResource.java:84-127 (POST /v1/task/{id}),
  TaskResource.java:261-266 (GET /v1/task/{id}/results/{bufferId}/{token}
  with token acknowledgement :321-325),
  execution/SqlTaskManager.java:370-403, operator/ExchangeClient.java:149.

TPU-first split (SURVEY.md §7.4): *within* a slice the exchange is an
XLA collective (parallel/spmd.py); *across hosts* pages move as
serialized column frames (serde.py: struct-of-arrays + LZ4 + xxh64) over
HTTP with the reference's pull/ack model. This module is that
cross-host leg: a worker process executes a task (SQL fragment) and
buffers its result as page frames; clients pull frames token by token.
"""

from __future__ import annotations

import json
import threading
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional

import numpy as np

from ..columnar import Batch, Column
from ..obs.metrics import METRICS
from ..serde import deserialize_batch, serialize_batch

PAGE_ROWS = 1 << 16

# exchange data-plane metrics (reference: ExchangeClient /
# ExchangeOperator JMX stats); "sent" counts frames buffered by this
# worker, "received" counts frames pulled by this process's clients
_M_PAGES = METRICS.counter(
    "trino_tpu_exchange_pages_total",
    "Exchange page frames by direction", ("direction",))
_M_PAGE_BYTES = METRICS.counter(
    "trino_tpu_exchange_bytes_total",
    "Serialized exchange bytes by direction", ("direction",))
_M_TASKS = METRICS.counter(
    "trino_tpu_worker_tasks_total",
    "Tasks executed by this worker, by terminal state", ("state",))
_M_TASKS_ABORTED = METRICS.counter(
    "trino_tpu_worker_tasks_aborted_total",
    "Tasks aborted by a coordinator DELETE while still tracked on "
    "this worker: user cancels, deadline breaches, attempt timeouts, "
    "and attempts superseded by a winning sibling")

from ..obs.metrics import WORKER_BUSY_REJECTS as _M_BUSY  # noqa: E402


class WorkerBusyError(Exception):
    """Raised by ``create_task`` when the worker sheds load under
    sustained pressure (open tasks past the shed threshold, or the
    worker memory budget breached). Surfaces as HTTP 503 — a
    RETRYABLE decline the dispatching scheduler's existing retry/
    rotation machinery absorbs by re-placing the task on another
    worker (no failure-detector demerit: a busy worker is healthy)."""


def _slice_batch(b: Batch, lo: int, hi: int) -> Batch:
    cols = {}
    for s, c in b.columns.items():
        data = np.asarray(c.data)[lo:hi]
        valid = None if c.valid is None else np.asarray(c.valid)[lo:hi]
        d2 = None if c.data2 is None else np.asarray(c.data2)[lo:hi]
        # elements ride whole: sliced offsets still index into them
        cols[s] = Column(c.type, data, valid, c.dictionary, d2,
                         c.elements)
    return Batch(cols, hi - lo)


def paginate(b: Batch, page_rows: int = PAGE_ROWS,
             codec: Optional[int] = None) -> List[bytes]:
    """Serialize a result batch as page frames (PagesSerde.serialize).
    Array results ship as a single frame: offsets reference the shared
    flat elements column, so slicing rows would re-ship the whole
    elements buffer once per page. ``codec`` None picks the default
    (LZ4 when the native library is available); the
    exchange_compression session property passes CODEC_STORE."""
    n = b.num_rows_host()
    if n == 0:
        frames = [serialize_batch(_slice_batch(b, 0, 0), codec=codec)]
    elif any(c.elements is not None for c in b.columns.values()):
        frames = [serialize_batch(_slice_batch(b, 0, n), codec=codec)]
    else:
        frames = [
            serialize_batch(_slice_batch(b, lo, min(lo + page_rows, n)),
                            codec=codec)
            for lo in range(0, n, page_rows)]
    _M_PAGES.inc(len(frames), direction="sent")
    _M_PAGE_BYTES.inc(sum(len(f) for f in frames), direction="sent")
    return frames


class _TaskMemoryContext:
    """Worker-side ``session.memory``: records the task's live
    high-water reservation (the figure ``liveMemoryBytes`` status
    beats stream back to the coordinator's cluster pool DURING
    execution) and triggers worker-local cache-pressure relief. It
    never enforces — the coordinator pool owns kill verdicts, and a
    kill reaches this task as a DELETE."""

    __slots__ = ("_task", "_worker")

    def __init__(self, task: "_Task", worker):
        self._task = task
        self._worker = worker

    def reserve(self, nbytes: int) -> None:
        t = self._task
        if int(nbytes) > t.live_memory_bytes:
            t.live_memory_bytes = int(nbytes)  # tt-lint: ignore[race-attr-write] single-writer (the task's executor thread); status threads read a monotonic int
            if self._worker is not None:
                self._worker.relieve_memory_pressure()

    def budget_bytes(self):
        """The worker-local byte budget (streaming engagement consults
        this exactly like the coordinator pool's budget); None when
        worker-local governance is off."""
        from ..config import CONFIG
        b = int(CONFIG.worker_memory_bytes or 0)
        return b if b > 0 else None


class _Task:
    """One task's lifecycle + output buffer (execution/SqlTask.java +
    the ClientBuffer token protocol)."""

    def __init__(self, task_id: str, attempt: int = 0, spool=None,
                 catalogs=None, worker=None):
        self.task_id = task_id
        # the owning TaskWorkerServer: carries the shared split
        # scheduler (exec/taskexec.py) this task's execution is
        # time-sliced through; None for schedulerless embedding
        self.worker = worker
        # live high-water reservation (bytes) of this task's executor,
        # updated DURING execution by _TaskMemoryContext and served in
        # every status response — the worker->coordinator live memory
        # feed (ISSUE 14 tentpole part 2)
        self.live_memory_bytes = 0
        # the worker's shared CatalogManager (etc/catalog configs —
        # None falls back to the runner's built-in defaults): a
        # fragment naming an operator-configured catalog must resolve
        # it here exactly as it would on the coordinator
        self.catalogs = catalogs
        # fault-tolerant execution: which attempt of its (fragment,
        # part) this task is (exec/remote.py re-dispatches failed
        # tasks with fresh attempt ids), and the spool its completed
        # output is committed to so it survives task eviction
        self.attempt = attempt
        self.spool = spool
        # committed-attempt directory, cached at commit time: the
        # X-TT-Spool-Dir header is constant once the task commits, so
        # page GETs must not re-read the COMMITTED marker per request
        self.spool_dir: Optional[str] = None
        self.state = "RUNNING"
        self.error: Optional[str] = None
        self.pages: List[bytes] = []
        self.node_stats: List[dict] = []   # NodeStats.to_dict per node
        self.spans: List[dict] = []        # worker-local span tree
        # structural program shapes this task's execution recorded
        # (exec/hotshapes.py delta): ride back in the task status so
        # the coordinator's registry covers every DISPATCHED
        # fragment's shapes, not only its own combine programs
        self.hot_shapes: List[dict] = []
        # learned-stats observation delta (exec/learnedstats.py):
        # per-operator rows-in/rows-out/wall this task observed, keyed
        # by the fragment's canonical plan key — the coordinator's
        # registry merges these from the status beat (origin-deduped)
        self.learned_stats: List[dict] = []
        self.peak_memory_bytes = 0
        self.spill_bytes = 0
        # morsel streaming (exec/streamjoin.py): chunk count + h2d
        # bytes this task's streamed operators moved, rolled up by the
        # schedulers next to peak memory
        self.stream_chunks = 0
        self.stream_h2d_bytes = 0
        # scheduler + device attribution (ISSUE 15): thread-CPU
        # seconds the shared split scheduler accounted to this task's
        # quanta (exec/taskexec.py TaskHandle.cpu_s; falls back to a
        # raw thread_time delta without a scheduler) and device
        # seconds the executor's jitted dispatches measured — both
        # ride task status so the coordinator rolls them into the
        # trace and the EXPLAIN ANALYZE stage rollup
        self.cpu_seconds = 0.0
        self.device_seconds = 0.0
        # ragged batching (exec/taskexec.py RaggedBatcher): chain
        # dispatches this task served through a co-batched program —
        # rolled up per query by the schedulers
        self.ragged_batched = 0
        # distributed tracing: the query's 128-bit trace id this
        # task's spans were born with (from the traceparent the
        # payload carried); None when the task was untraced
        self.trace_id: Optional[str] = None
        self.done = threading.Event()
        # coordinator-side abort (DELETE /v1/task): flips the running
        # task's cooperative cancel — the executor stops between plan
        # nodes and a pipelined consumer's eager exchange pull stops
        # polling instead of spinning out remote_task_timeout against
        # a query that already failed
        self.cancel_ev = threading.Event()

    def run(self, payload: dict):
        import time as _time
        from ..exec.hotshapes import HOT_SHAPES
        from ..exec.learnedstats import LEARNED_STATS
        shapes_before = HOT_SHAPES.hit_counts()
        lstats_before = LEARNED_STATS.seq()
        handle = None
        cpu0 = _time.thread_time()
        try:
            from ..runner import LocalQueryRunner
            from ..session import Session
            session = Session(catalog=payload.get("catalog"),
                              schema=payload.get("schema"),
                              cancel=self.cancel_ev)
            for name, value in payload.get("properties", {}).items():
                session.set(name, value)
            if self.worker is not None:
                # shared split scheduler (exec/taskexec.py): every
                # task registers with its query identity (the task-id
                # prefix groups all of one dispatch's tasks) and its
                # resource group's fair-share weight; execution only
                # proceeds while holding one of the worker's bounded
                # runner slots, yielded at split/chunk boundaries
                handle = self.worker.task_executor.register(
                    self.task_id.split(".", 1)[0], self.task_id,
                    group=str(payload.get("resource_group")
                              or "global"),
                    weight=float(payload.get("group_weight") or 1.0),
                    cancel=self.cancel_ev)
                session.split_yield = handle.checkpoint
                # ragged batch formation (exec/taskexec.py
                # RaggedBatcher): both the leader's window sleep and a
                # member's result wait release the runner slot —
                # members holding every slot would deadlock the
                # leader's re-acquire
                session.slot_wait = handle.run_blocked
            # live memory accounting: the executor's reservations land
            # on this task (status beats carry them to the
            # coordinator's pool) and arm worker-local cache relief
            session.memory = _TaskMemoryContext(self, self.worker)
            # deadline propagation (server/coordinator.py -> exec/
            # remote.py): the coordinator ships the REMAINING budget
            # (relative seconds — wall clocks differ across hosts) and
            # the worker re-derives an absolute deadline, so its own
            # executor stops between plan nodes once the query's
            # wall-clock budget is spent
            rem = payload.get("deadline_s")
            if rem is not None:
                import time as _time
                session.deadline = _time.monotonic() + max(
                    float(rem), 0.0)
            # per-node stats + spans ride back in the task status (the
            # reference's TaskStatus/TaskStats carrying OperatorStats
            # to the coordinator for the stage rollup)
            collect = bool(payload.get("collect_stats"))
            stage = None
            if "fragment" in payload:
                # serialized PlanFragment + split share — the remote
                # task path (reference: SqlTaskManager.java:370-403
                # executing a TaskUpdateRequest's fragment)
                from ..exec.executor import Executor
                from ..obs.trace import QueryTrace
                from ..plan.serde import from_jsonable
                from ..plan.nodes import PartitionedOutputNode
                runner = LocalQueryRunner(session=session,
                                          catalogs=self.catalogs)
                plan = from_jsonable(payload["fragment"])
                # receiving-side sanity check: the coordinator proved
                # serde round-trip stability before dispatch, so a
                # violation HERE means the bytes changed in transit or
                # the worker runs a drifted plan-IR version — fail the
                # attempt with the validator named instead of tracing
                # a corrupt plan into XLA (the failure is retriable on
                # another worker like any task error)
                from ..analysis.sanity import PlanSanityChecker
                PlanSanityChecker().validate(plan, "worker-decode")
                # distributed tracing (ISSUE 15): the task payload
                # carries a W3C traceparent naming the query's trace
                # id and the coordinator's pre-minted span id for THIS
                # task — worker spans are born inside the query's
                # trace with their true parent, so the coordinator's
                # graft is an id-preserving merge, not a clock rebase
                trace = None
                if collect:
                    ctx = QueryTrace.parse_traceparent(
                        payload.get("traceparent"))
                    trace = QueryTrace(
                        self.task_id,
                        trace_id=ctx[0] if ctx else None,
                        parent_span_id=ctx[1] if ctx else None)
                    self.trace_id = trace.trace_id  # tt-lint: ignore[race-attr-write] task-thread-private until done.set() publishes
                session.trace = trace
                ex = Executor(runner.catalogs, session,
                              collect_stats=collect)
                ex.scan_partition = (int(payload["part"]),
                                     int(payload["nparts"]))
                # stage-DAG task (trino_tpu/stage/): RemoteSource
                # leaves pull this task's partition of every upstream
                # task through the spool / partition endpoint, and the
                # PartitionedOutputNode root is peeled — partitioning
                # happens below, at the page boundary
                stage = payload.get("stage")
                body = plan
                if stage is not None:
                    from ..stage.exchange import ExchangePuller
                    puller = ExchangePuller(
                        stage.get("sources") or {},
                        part=int(payload["part"]), spool=self.spool,
                        timeout_s=float(
                            session.get("remote_task_timeout")),
                        cancel=self.cancel_ev)
                    if handle is not None:
                        # a pipelined consumer blocked on an upstream
                        # commit must not hold a runner slot: bounded
                        # runners would otherwise deadlock a producer
                        # behind its own consumer
                        ex.exchange_reader = (
                            lambda fid: handle.run_blocked(
                                puller.read_fragment, fid))
                    else:
                        ex.exchange_reader = puller.read_fragment
                    if isinstance(plan, PartitionedOutputNode):
                        body = plan.source
                if handle is not None:
                    handle.acquire()   # wait for a fair-share slot
                if trace is not None:
                    with trace.span("task_execute",
                                    task=self.task_id):
                        res = ex.execute(body)
                    self.spans = trace.to_dicts()  # tt-lint: ignore[race-attr-write] task-thread-private until done.set() publishes; status readers wait on done
                else:
                    res = ex.execute(body)
                self.node_stats = [s.to_dict() for s in ex.stats]  # tt-lint: ignore[race-attr-write] task-thread-private until done.set() publishes
                if collect and ex.stats:
                    # learned stats: observe this fragment's operator
                    # flow under the fragment body's canonical key (the
                    # peeled plan — the program the executor actually
                    # ran); exported as a delta in the finally below
                    from ..exec.learnedstats import (plan_key_for,
                                                     record_node_stats)
                    try:
                        record_node_stats(plan_key_for(body), ex.stats,
                                          session)
                    except Exception:  # noqa: BLE001 — best-effort
                        pass
                self.peak_memory_bytes = ex.peak_reserved_bytes  # tt-lint: ignore[race-attr-write] task-thread-private until done.set() publishes
                self.spill_bytes = ex.spilled_bytes  # tt-lint: ignore[race-attr-write] task-thread-private until done.set() publishes
                self.stream_chunks = ex.stream_chunks  # tt-lint: ignore[race-attr-write] task-thread-private until done.set() publishes
                self.stream_h2d_bytes = ex.stream_h2d_bytes  # tt-lint: ignore[race-attr-write] task-thread-private until done.set() publishes
                self.device_seconds = ex.device_s  # tt-lint: ignore[race-attr-write] task-thread-private until done.set() publishes
                self.ragged_batched = ex.ragged_batched  # tt-lint: ignore[race-attr-write] task-thread-private until done.set() publishes
            else:
                runner = LocalQueryRunner(session=session,
                                          catalogs=self.catalogs)
                if handle is not None:
                    handle.acquire()   # wait for a fair-share slot
                res = runner.execute_batch(payload["sql"])
            codec = None
            if not bool(session.get("exchange_compression")):
                from ..serde import CODEC_STORE
                codec = CODEC_STORE
            if stage is not None:
                # partitioned output: exactly one frame per downstream
                # task (frame i == partition i), committed to the spool
                # under the attempt-independent exchange key — the
                # spool IS the shuffle medium here, so an unwritable
                # spool must FAIL the attempt (the output would be
                # unreachable), unlike the best-effort legacy commit
                from ..stage.repartition import partition_frames
                from ..plan.nodes import PartitionedOutputNode as _PO
                keys, kind = (), "gather"
                if isinstance(plan, _PO):
                    keys, kind = plan.partition_keys, plan.kind
                self.pages = partition_frames(  # tt-lint: ignore[race-attr-write] task-thread-private until done.set() publishes
                    res, keys, kind,
                    int(stage.get("nparts_out") or 1), codec=codec,
                    session=session)
                self.spool.commit(str(stage["exchange_key"]), 0, 0,
                                  self.attempt, self.pages)
            else:
                self.pages = paginate(res, codec=codec)  # tt-lint: ignore[race-attr-write] task-thread-private until done.set() publishes
                if self.spool is not None:
                    # durable output: completed pages outlive the
                    # in-memory task entry, so an aborted/evicted
                    # task's consumer can still re-read them through
                    # /v1/spool (the exchange-spooling half of
                    # fault-tolerant execution)
                    try:
                        self.spool.commit(self.task_id, 0, 0,
                                          self.attempt, self.pages)
                        getdir = getattr(self.spool, "attempt_dir",
                                         None)
                        if getdir is not None:
                            self.spool_dir = getdir(self.task_id, 0, 0)  # tt-lint: ignore[race-attr-write] task-thread-private until done.set() publishes
                    except Exception:  # noqa: BLE001 — best-effort
                        pass
            self.state = "FINISHED"  # tt-lint: ignore[race-attr-write] races only with abort's CANCELED stamp; either terminal state is valid, done.set() publishes
        except Exception as e:   # noqa: BLE001
            self.state = "FAILED"  # tt-lint: ignore[race-attr-write] races only with abort's CANCELED stamp; either terminal state is valid, done.set() publishes
            self.error = f"{type(e).__name__}: {e}"  # tt-lint: ignore[race-attr-write] task-thread-private until done.set() publishes
        finally:
            if handle is not None:
                handle.close()      # release the runner slot + the
                #                     scheduler's per-query accounting
                # scheduler-accounted CPU: the sum of this task's
                # quantum stamps (finalized by close() above)
                self.cpu_seconds = float(handle.cpu_s)  # tt-lint: ignore[race-attr-write] task-thread-private until done.set() publishes
            else:
                # schedulerless embedding: the raw thread-CPU delta of
                # the whole run is the best available figure
                self.cpu_seconds = max(  # tt-lint: ignore[race-attr-write] task-thread-private until done.set() publishes
                    _time.thread_time() - cpu0, 0.0)
            try:
                # hit-count DELTAS since this task started: concurrent
                # tasks may each claim a shared sighting (their deltas
                # overlap), which can only over-report by the overlap —
                # never multiply cumulative counts per status the way a
                # raw export would
                self.hot_shapes = HOT_SHAPES.export_delta(shapes_before)  # tt-lint: ignore[race-attr-write] task-thread-private until done.set() publishes
            except Exception:    # noqa: BLE001
                pass
            try:
                # observation DELTAS since the task started, original
                # origins preserved — the coordinator-side merge skips
                # its own (shared-process workers) without losing a
                # remote worker's genuine observations
                self.learned_stats = LEARNED_STATS.export_delta(lstats_before)  # tt-lint: ignore[race-attr-write] task-thread-private until done.set() publishes
            except Exception:    # noqa: BLE001
                pass
            _M_TASKS.inc(state=self.state)
            self.done.set()


class TaskWorkerServer:
    """A worker node: accepts tasks, executes them, serves result pages.
    One process per worker (the reference's worker JVM)."""

    def __init__(self, port: int = 0, spool_dir: Optional[str] = None,
                 spool_backend: Optional[str] = None, catalogs=None,
                 task_runners: Optional[int] = None,
                 busy_shed_factor: Optional[int] = None,
                 busy_shed_ema_s: Optional[float] = None):
        self._tasks: Dict[str, _Task] = {}
        self._lock = threading.Lock()
        # shared split scheduler (exec/taskexec.py): ONE bounded
        # runner pool time-slices every concurrent query's task
        # splits/chunks with multilevel fair-share priority —
        # ``task_runners`` (default CONFIG.task_runner_threads; 0 =
        # max(4, 2 x cores)) bounds how many tasks EXECUTE at once
        import os as _os
        from ..config import CONFIG
        from ..exec.taskexec import TaskExecutor
        n = (int(task_runners) if task_runners is not None
             else int(CONFIG.task_runner_threads))
        if n <= 0:
            n = max(4, 2 * (_os.cpu_count() or 1))
        # busy_shed_ema_s: time constant of the queue-depth EMA the
        # shed decision smooths through (0 = spot value, the pre-EMA
        # behavior tests pin; default CONFIG.busy_shed_ema_s)
        self.task_executor = TaskExecutor(n, ema_tau_s=busy_shed_ema_s)
        self.busy_shed_factor = (
            int(busy_shed_factor) if busy_shed_factor is not None
            else int(CONFIG.busy_shed_factor))
        # operator-configured catalogs (etc/catalog via
        # main.build_catalogs) — None means the runner's defaults; a
        # standalone worker must resolve the same catalog names the
        # coordinator dispatches
        self.catalogs = catalogs
        # worker-side spool (fte/spool.py): tasks submitted with
        # "spool": true commit their output pages here, keyed by task
        # id, and /v1/spool serves them even after the task is evicted.
        # Backend per arg/config (make_spool); for the local backend
        # the base is kept SEPARATE from the coordinator's (task-id
        # keys vs query-id keys) so neither side's TTL sweep can reap
        # the other's live entries. Non-local backends skip the
        # X-TT-Spool-Dir coalescing hint (no directory to link from).
        from ..fte.spool import make_spool, worker_spool_base
        self.spool = make_spool(
            spool_backend,
            local_base_dir=spool_dir or worker_spool_base())
        worker = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def do_POST(self):
                parts = self.path.strip("/").split("/")
                # /v1/task/{id}
                if len(parts) == 3 and parts[:2] == ["v1", "task"]:
                    length = int(self.headers.get("Content-Length", 0))
                    payload = json.loads(self.rfile.read(length))
                    # W3C context propagation: the traceparent rides
                    # the HTTP header AND the payload; the header is
                    # the fallback for payloads built by clients that
                    # predate the field
                    tp = self.headers.get("traceparent")
                    if tp and "traceparent" not in payload:
                        payload["traceparent"] = tp
                    try:
                        t = worker.create_task(parts[2], payload)
                    except WorkerBusyError as e:
                        # graceful degradation: a 503 is the RETRYABLE
                        # busy signal — the scheduler re-places the
                        # task on another worker without demeriting
                        # this one in the failure detector
                        body = json.dumps(
                            {"error": str(e), "busy": True}).encode()
                        self.send_response(503)
                        self.send_header("Content-Type",
                                         "application/json")
                        self.send_header("Content-Length",
                                         str(len(body)))
                        self.end_headers()
                        self.wfile.write(body)
                        return
                    body = json.dumps(
                        {"taskId": t.task_id, "state": t.state}).encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                # /v1/ingest/{topic}: any worker accepts producer
                # appends — segment files under the shared stream dir
                # are the source of truth, so the coordinator's scans
                # see worker-side ingests with no forwarding hop
                from urllib.parse import parse_qs, urlparse
                parsed = urlparse(self.path)
                route = [p for p in parsed.path.split("/") if p]
                if len(route) == 3 and route[:2] == ["v1", "ingest"]:
                    from ..streaming.log import get_log, ingest_http
                    topic = route[2]
                    n = int(self.headers.get("Content-Length", 0))
                    data = self.rfile.read(n)
                    try:
                        out = ingest_http(get_log(), topic, data,
                                          parse_qs(parsed.query))
                        code = 200
                    except ValueError as e:
                        out, code = {"error": str(e)}, 400
                    body = json.dumps(out).encode()
                    self.send_response(code)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                self.send_error(404)

            def do_GET(self):
                parts = self.path.strip("/").split("/")
                # /v1/task/{id}/results/{token}
                if len(parts) == 5 and parts[3] == "results":
                    tid, token = parts[2], int(parts[4])
                    t = worker.get_task(tid)
                    if t is None:
                        self.send_error(404)
                        return
                    # short-poll: a still-running task answers 202 so
                    # the puller can notice cancellation between polls
                    # (reference: TaskResource's bounded long-poll)
                    if not t.done.wait(timeout=2.0) \
                            and t.state == "RUNNING":
                        self.send_response(202)
                        # live memory beat for the flat dispatch path:
                        # the puller's 202 polls carry the task's live
                        # reservation so the coordinator pool sees
                        # worker bytes DURING execution (the stage
                        # path reads the same figure off the status
                        # JSON its wait_done polls)
                        self.send_header("X-TT-Live-Memory",
                                         str(t.live_memory_bytes))
                        self.send_header("Content-Length", "0")
                        self.end_headers()
                        return
                    if t.state != "FINISHED":
                        # still RUNNING (wait timed out), FAILED, or
                        # CANCELED — never report an empty complete
                        # result for a task that didn't finish
                        body = (t.error
                                or f"task is {t.state}").encode()
                        self.send_response(500)
                        self.send_header("Content-Length",
                                         str(len(body)))
                        self.end_headers()
                        self.wfile.write(body)
                        return
                    complete = token >= len(t.pages)
                    body = b"" if complete else t.pages[token]
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     "application/octet-stream")
                    self.send_header("X-TT-Complete",
                                     "true" if complete else "false")
                    self.send_header("X-TT-Next-Token", str(token + 1))
                    if t.spool_dir:
                        # same-host coalescing hint: where this task's
                        # committed frames live on disk, so a consumer
                        # sharing the filesystem can hard-link instead
                        # of re-writing them (LocalDirSpool
                        # .commit_linked). Meaningless (and ignored)
                        # across hosts — the path won't exist there.
                        # Cached on the task at commit (constant from
                        # then on; no marker read per page GET).
                        self.send_header("X-TT-Spool-Dir", t.spool_dir)
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                # /v1/spool/{task_id}/{token}: committed output pages
                # of a (possibly evicted) task, straight off the spool
                # — same complete/next-token protocol as /results
                if len(parts) == 4 and parts[:2] == ["v1", "spool"]:
                    tid, token = parts[2], int(parts[3])
                    # frame-at-a-time off the spool: reading the whole
                    # committed set per token request would make an
                    # N-page pull O(N^2) disk I/O and overcount the
                    # spool-read byte metric by ~N x
                    nframes = worker.spool.frame_count(tid, 0, 0)
                    if nframes is None:
                        self.send_error(404)
                        return
                    complete = token >= nframes
                    body = (b"" if complete else
                            worker.spool.read_frame(tid, 0, 0, token))
                    if body is None:     # reaped between count & read
                        self.send_error(404)
                        return
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     "application/octet-stream")
                    self.send_header("X-TT-Complete",
                                     "true" if complete else "false")
                    self.send_header("X-TT-Next-Token", str(token + 1))
                    getdir = getattr(worker.spool, "attempt_dir", None)
                    if complete and getdir is not None:
                        # evicted-task path has no cached _Task entry;
                        # the consumer only needs the hint once, so pay
                        # the marker read on the final response alone
                        # (local backend only — object-store spools
                        # have no directory to link from)
                        sdir = getdir(tid, 0, 0)
                        if sdir:
                            self.send_header("X-TT-Spool-Dir", sdir)
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                # /v1/partition/{exchange_key}/{index}: ONE partition
                # frame of a committed stage-task attempt, straight
                # off the spool — the serve half of the worker-to-
                # worker exchange (consumers on a shared spool never
                # call this; it is the cross-host leg). 404 until the
                # attempt commits: the scheduler only advertises
                # FINISHED tasks, so a 404 here means eviction/reap —
                # a retriable consumer-attempt failure.
                if len(parts) == 4 and parts[:2] == ["v1", "partition"]:
                    key, index = parts[2], int(parts[3])
                    frame = worker.spool.read_frame(key, 0, 0, index)
                    if frame is None:
                        self.send_error(404)
                        return
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     "application/octet-stream")
                    self.send_header("Content-Length",
                                     str(len(frame)))
                    self.end_headers()
                    self.wfile.write(frame)
                    return
                # /v1/task/{id} -> status (incl. the worker-side
                # operator stats + span tree for the stage rollup)
                if len(parts) == 3 and parts[:2] == ["v1", "task"]:
                    # deterministic chaos site: a raise here turns into
                    # the 503 a coordinator sees from a worker whose
                    # status surface is wedged (delay models a stalled
                    # beat; crash kills the worker process outright)
                    from ..fte.faultpoints import (FaultInjected,
                                                   fault_point)
                    try:
                        fault_point("worker.pre_status_beat")
                    except FaultInjected:
                        self.send_error(503)
                        return
                    t = worker.get_task(parts[2])
                    if t is None:
                        self.send_error(404)
                        return
                    body = json.dumps(
                        {"taskId": t.task_id,
                         "state": t.state,
                         "attempt": t.attempt,
                         "error": t.error,
                         "nodeStats": t.node_stats,
                         "spans": t.spans,
                         "hotShapes": t.hot_shapes,
                         "learnedStats": t.learned_stats,
                         "peakMemoryBytes": t.peak_memory_bytes,
                         "liveMemoryBytes": t.live_memory_bytes,
                         "spillBytes": t.spill_bytes,
                         "streamChunks": t.stream_chunks,
                         "streamH2dBytes": t.stream_h2d_bytes,
                         "cpuSeconds": t.cpu_seconds,
                         "deviceSeconds": t.device_seconds,
                         "raggedBatched": t.ragged_batched,
                         "traceId": t.trace_id}).encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                if self.path.split("?")[0] == "/metrics":
                    from ..obs.metrics import write_exposition
                    write_exposition(self)
                    return
                # liveness surface: the coordinator's heartbeat
                # failure detector probes /v1/info (server/failure.py
                # _http_probe expects a JSON 200). Without it a REAL
                # worker process is declared dead after the warmup
                # probes and the coordinator silently stops
                # dispatching to it — found driving the multi-process
                # cluster, invisible to in-process tests whose
                # feedback-only detectors never probe.
                if self.path.split("?")[0] == "/v1/info":
                    body = json.dumps(
                        {"nodeId": worker.node_id,
                         "uri": worker.base_uri,
                         "coordinator": False,
                         "state": "active"}).encode()
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     "application/json")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                self.send_error(404)

            def do_DELETE(self):
                parts = self.path.strip("/").split("/")
                if len(parts) == 3 and parts[:2] == ["v1", "task"]:
                    worker.abort_task(parts[2])
                    self.send_response(204)
                    self.send_header("Content-Length", "0")
                    self.end_headers()
                    return
                self.send_error(404)

        self._httpd = ThreadingHTTPServer(("127.0.0.1", port), Handler)
        self.port = self._httpd.server_address[1]
        self.base_uri = f"http://127.0.0.1:{self.port}"
        self._thread: Optional[threading.Thread] = None
        # live membership (discovery/Announcer.java analog): when told
        # a coordinator, the worker announces itself now and on a
        # cadence — re-announcement is idempotent at the coordinator
        # and doubles as re-registration after a coordinator restart
        self._announce_stop = threading.Event()
        self._announced_to: Optional[str] = None
        self._announce_token: Optional[str] = None
        self._announce_thread: Optional[threading.Thread] = None
        # serializes every announce beat with stop()'s graceful leave:
        # a beat that already passed its stop check must finish BEFORE
        # the leave is sent, or the late announce would resurrect the
        # registration the coordinator just removed (the worker never
        # re-leaves — a phantom member until the failure detector
        # notices)
        self._announce_lock = threading.Lock()
        # AOT pre-warm state (exec/aot.py): a joining worker pulls the
        # coordinator's hot-shape list and compiles the top-K on a
        # background thread; ``prewarm_ready`` rides every announce
        # payload so the scheduler can prefer warm workers. The lock
        # guards the flag against the announce loop reading it while
        # the prewarm thread flips it.
        self._prewarm_lock = threading.Lock()
        self.prewarm_ready = False
        self._prewarm_summary: Optional[dict] = None
        import uuid as _uuid
        self.node_id = f"worker-{_uuid.uuid4().hex[:8]}"

    # -- task manager (SqlTaskManager) --------------------------------
    def live_task_bytes(self) -> int:
        """Sum of RUNNING tasks' live high-water reservations — the
        worker-local half of the memory-governance arithmetic.
        Finished tasks stay in the registry to serve status/pages,
        but their memory is free: counting them would eventually trip
        the shed/relief thresholds on a long-lived worker."""
        with self._lock:
            return sum(t.live_memory_bytes
                       for t in self._tasks.values()
                       if t.state == "RUNNING")

    def relieve_memory_pressure(self) -> None:
        """Worker-local cache governance: when live task reservations
        plus shared-cache residency exceed the worker memory budget
        (CONFIG.worker_memory_bytes), shed cache entries — caches
        yield to queries, never the other way around. No-op when the
        budget is 0 (the coordinator pool still governs globally)."""
        from ..config import CONFIG
        budget = int(CONFIG.worker_memory_bytes or 0)
        if budget <= 0:
            return
        from ..exec.executor import (cache_memory_bytes,
                                     evict_cache_pressure)
        usage = self.live_task_bytes() + cache_memory_bytes()
        if usage > budget:
            evict_cache_pressure(usage - budget)

    def _shed_reason(self) -> Optional[str]:
        """Non-None when this worker should decline NEW dispatches
        with the retryable BUSY signal (graceful degradation): the
        EMA-smoothed open-task count past busy_shed_factor x runner
        slots, or the worker memory budget breached by live
        reservations alone. The factor threshold is the FLOOR (spot
        count must also exceed it — shedding never fires below the
        static cap), and the EMA gate means a momentary dispatch
        burst rides through while sustained overload still sheds
        (PR 14 open item: the static threshold flapped on bursts)."""
        factor = int(self.busy_shed_factor or 0)
        if factor > 0:
            open_tasks = self.task_executor.open_tasks()
            cap = factor * self.task_executor.runners
            if open_tasks >= 2 * cap:
                # hard ceiling regardless of the EMA: the smoothing
                # tolerates a burst WITHIN [cap, 2*cap), never an
                # unbounded pile-up while the EMA catches up — a cold
                # worker fanned the whole cluster's dispatch must
                # still push back
                return (f"{open_tasks} open tasks >= hard ceiling "
                        f"{2 * cap} (2 x shed threshold; EMA "
                        "smoothing does not apply)")
            if open_tasks >= cap:
                ema = self.task_executor.open_tasks_ema()
                if ema >= cap:
                    return (f"open-task EMA {ema:.1f} (spot "
                            f"{open_tasks}) >= shed threshold {cap} "
                            f"({self.task_executor.runners} runners "
                            f"x factor {factor})")
        from ..config import CONFIG
        budget = int(CONFIG.worker_memory_bytes or 0)
        if budget > 0:
            live = self.live_task_bytes()
            if live > budget:
                return (f"live task reservations {live} bytes over "
                        f"the worker memory budget {budget}")
        return None

    def create_task(self, tid: str, payload: dict) -> _Task:
        try:      # reap expired spooled output (time-gated internally)
            self.spool.maybe_cleanup()
        except Exception:        # noqa: BLE001
            pass
        with self._lock:
            t = self._tasks.get(tid)
        if t is not None:
            return t          # idempotent update (TaskResource) —
            #                   never shed a re-POST of a known task
        reason = self._shed_reason()
        if reason is not None:
            _M_BUSY.inc()
            raise WorkerBusyError(
                f"worker {self.base_uri} is shedding load: {reason}")
        with self._lock:
            t = self._tasks.get(tid)
            if t is not None:
                return t          # idempotent update (TaskResource)
            t = _Task(tid, attempt=int(payload.get("attempt") or 0),
                      # a stage task ALWAYS spools: the spool is the
                      # exchange medium its consumers read
                      spool=(self.spool if payload.get("spool")
                             or payload.get("stage") else None),
                      catalogs=self.catalogs, worker=self)
            self._tasks[tid] = t
        threading.Thread(target=t.run, args=(payload,),
                         daemon=True).start()
        return t

    def get_task(self, tid: str) -> Optional[_Task]:
        with self._lock:
            return self._tasks.get(tid)

    def abort_task(self, tid: str):
        with self._lock:
            t = self._tasks.pop(tid, None)
        if t is not None:
            t.state = "CANCELED"
            t.cancel_ev.set()   # stop the running thread's executor
            #                     and its eager exchange pulls too
            t.done.set()
            # a coordinator-side stop (cancel, deadline breach, or a
            # superseded attempt) reached THIS worker and ended a live
            # task, observable in /metrics
            _M_TASKS_ABORTED.inc()

    # -- membership ---------------------------------------------------
    def _is_prewarmed(self) -> bool:
        with self._prewarm_lock:
            return self.prewarm_ready

    def prewarm_from(self, coordinator_uri: str,
                     top_k: Optional[int] = None,
                     token: Optional[str] = None) -> dict:
        """Pull the coordinator's hot-shape list and AOT-compile it
        (exec/aot.py) — the announce-loop hook that turns a cold
        joiner warm BEFORE its first fragment arrives. Sets
        ``prewarm_ready`` even when the list is empty or a shape
        fails: readiness means "the warm-up ran", not "every shape
        compiled" (a coordinator with no history must not leave its
        whole fleet permanently cold-flagged)."""
        from ..config import CONFIG
        from ..exec import aot
        k = CONFIG.prewarm_top_k if top_k is None else int(top_k)
        shapes = []
        try:
            req = urllib.request.Request(
                f"{coordinator_uri.rstrip('/')}/v1/hotshapes?k={k}")
            if token:
                req.add_header("Authorization", f"Bearer {token}")
            with urllib.request.urlopen(req, timeout=10) as r:
                shapes = json.loads(r.read()).get("shapes") or []
        except Exception:       # noqa: BLE001 — an unreachable/older
            # coordinator yields an empty warm-up, not a dead worker
            shapes = []
        summary = aot.compile_entries(shapes)
        summary["pulled"] = len(shapes)
        with self._prewarm_lock:
            self.prewarm_ready = True
            self._prewarm_summary = summary
        return summary

    def announce(self, coordinator_uri: str,
                 interval_s: float = 10.0,
                 token: Optional[str] = None,
                 prewarm: Optional[bool] = None,
                 prewarm_top_k: Optional[int] = None) -> bool:
        """Join ``coordinator_uri``'s worker set now, then keep
        re-announcing on a daemon thread (registration survives a
        coordinator restart: the fresh coordinator learns this worker
        at the next beat). ``token`` rides as a Bearer credential on
        every announce/leave — required when the coordinator runs an
        authenticator, whose gate sits in front of /v1/announcement
        like every other resource. ``prewarm`` (default: config
        TRINO_TPU_PREWARM) starts the hot-shape warm-up on a
        background thread after the first announce; the readiness
        flag rides every announce payload, and the moment warm-up
        finishes an extra beat pushes it to the coordinator so the
        scheduler prefers this worker without waiting out the
        interval. Returns whether the first announce landed. Safe to
        call repeatedly (e.g. re-pointing the worker at a new
        coordinator, or after stop()): each call retires the previous
        announcer loop via its own stop event, so exactly one loop
        ever beats."""
        from ..config import CONFIG
        self._announce_stop.set()       # retire any previous announcer
        stop = self._announce_stop = threading.Event()
        self._announced_to = coordinator_uri.rstrip("/")
        self._announce_token = token
        ok = announce_once(self._announced_to, self.base_uri,
                           self.node_id, token=token,
                           prewarmed=self._is_prewarmed())

        def loop():
            while not stop.wait(interval_s):
                try:
                    with self._announce_lock:
                        if stop.is_set():
                            return      # stop() won: no beat after it
                        announce_once(self._announced_to,
                                      self.base_uri, self.node_id,
                                      token=self._announce_token,
                                      prewarmed=self._is_prewarmed())
                except Exception:       # noqa: BLE001 — next beat
                    pass

        self._announce_thread = threading.Thread(target=loop,
                                                 daemon=True)
        self._announce_thread.start()

        if prewarm is None:
            prewarm = CONFIG.prewarm_enabled
        if prewarm and not self._is_prewarmed():
            uri, tok = self._announced_to, token

            def warmup():
                try:
                    self.prewarm_from(uri, top_k=prewarm_top_k,
                                      token=tok)
                except Exception:       # noqa: BLE001 — a failed
                    # warm-up leaves the worker cold-flagged but
                    # fully serving
                    return
                try:            # readiness beat, ahead of the cadence
                    with self._announce_lock:
                        if not stop.is_set():
                            announce_once(uri, self.base_uri,
                                          self.node_id, token=tok,
                                          prewarmed=True)
                except Exception:       # noqa: BLE001
                    pass

            threading.Thread(target=warmup, daemon=True).start()
        return ok

    # -- lifecycle ----------------------------------------------------
    def start(self):
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True)
        self._thread.start()
        return self

    def stop(self):
        # stop-then-leave under the announce lock: an in-flight beat
        # finishes first, and no beat can start after the stop event is
        # set — the leave is guaranteed to be the LAST membership write
        # this worker sends
        with self._announce_lock:
            self._announce_stop.set()
            if self._announced_to:
                try:  # graceful leave; the heartbeat detector is the
                    #   backstop for ungraceful deaths
                    req = urllib.request.Request(
                        f"{self._announced_to}/v1/announcement"
                        f"?uri={self.base_uri}", method="DELETE")
                    if self._announce_token:
                        req.add_header(
                            "Authorization",
                            f"Bearer {self._announce_token}")
                    with urllib.request.urlopen(req, timeout=5):
                        pass
                except Exception:       # noqa: BLE001
                    pass
        self._httpd.shutdown()
        self._httpd.server_close()


def announce_once(coordinator_uri: str, worker_uri: str,
                  node_id: Optional[str] = None,
                  token: Optional[str] = None,
                  prewarmed: bool = False) -> bool:
    """One worker-join announcement (POST /v1/announcement on the
    coordinator — the discovery-service registration analog).
    ``token`` is the Bearer credential for authenticated
    coordinators; ``prewarmed`` is the AOT warm-up readiness flag the
    scheduler's warm-worker preference keys on."""
    payload = json.dumps({"uri": worker_uri,
                          "nodeId": node_id or worker_uri,
                          "prewarmed": bool(prewarmed)}).encode()
    headers = {"Content-Type": "application/json"}
    if token:
        headers["Authorization"] = f"Bearer {token}"
    req = urllib.request.Request(
        f"{coordinator_uri.rstrip('/')}/v1/announcement",
        data=payload, headers=headers, method="POST")
    try:
        with urllib.request.urlopen(req, timeout=5) as r:
            return r.status == 200
    except Exception:               # noqa: BLE001
        return False


def worker_main(conn, platform: Optional[str] = None):
    """Entry point for a worker child process: binds an ephemeral port,
    reports it through the pipe, serves until killed.

    ``platform`` pins the JAX backend BEFORE anything imports jax — on
    a TPU-attached host a child must not contend for the (exclusive)
    chip the parent holds; test harnesses pass "cpu". NOTE: with the
    'spawn' start method this runs AFTER interpreter startup — a
    TPU-forcing sitecustomize (PYTHONPATH) executes first and can hang
    on a dead tunnel, so spawners must ALSO scrub the environment
    before Process.start() (see spawn_worker_env below)."""
    import os
    if platform:
        os.environ["JAX_PLATFORMS"] = platform
        os.environ.pop("PYTHONPATH", None)  # skip axon sitecustomize
        import jax
        jax.config.update("jax_platforms", platform)
    srv = TaskWorkerServer().start()
    conn.send(srv.port)
    conn.close()
    srv._thread.join()


class spawn_worker_env:
    """Context manager scrubbing the parent environment while spawning
    CPU-pinned worker children: multiprocessing 'spawn' children run
    sitecustomize (PYTHONPATH) at interpreter startup, BEFORE
    worker_main — on a TPU-attached host with a dead tunnel that import
    blocks forever unless the env is cleaned in the parent first."""

    _KEYS = ("PYTHONPATH", "JAX_PLATFORMS")

    def __enter__(self):
        import os
        self._saved = {k: os.environ.get(k) for k in self._KEYS}
        os.environ["PYTHONPATH"] = ""
        os.environ["JAX_PLATFORMS"] = "cpu"
        return self

    def __exit__(self, *exc):
        import os
        for k, v in self._saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


class RemoteTaskClient:
    """Coordinator-side proxy for one remote task (HttpRemoteTask +
    ExchangeClient/HttpPageBufferClient pull loop, collapsed)."""

    def __init__(self, base_uri: str):
        self.base_uri = base_uri.rstrip("/")

    def submit(self, task_id: str, sql: str, catalog: str = "tpch",
               schema: str = "tiny", properties: Optional[dict] = None):
        return self._post(task_id, {"sql": sql, "catalog": catalog,
                                    "schema": schema,
                                    "properties": properties or {}})

    def submit_fragment(self, task_id: str, fragment: dict,
                        catalog: str, schema: str, part: int,
                        nparts: int,
                        properties: Optional[dict] = None,
                        collect_stats: bool = False,
                        attempt: int = 0, spool: bool = False,
                        stage: Optional[dict] = None,
                        deadline_s: Optional[float] = None,
                        resource_group: Optional[str] = None,
                        group_weight: Optional[float] = None,
                        traceparent: Optional[str] = None):
        """POST a serialized plan fragment + split share (the
        HttpRemoteTask TaskUpdateRequest analog). ``attempt`` tags the
        task's retry/speculation generation; ``spool`` asks the worker
        to commit completed output pages to its spool. ``stage``
        carries the stage-DAG task context (trino_tpu/stage/): the
        stage id, the attempt-independent exchange key, the output
        partition count, and the upstream exchange sources to pull.
        ``deadline_s`` is the query's REMAINING wall-clock budget in
        seconds (relative — host clocks differ); the worker re-derives
        an absolute deadline for its executor. ``resource_group`` /
        ``group_weight`` carry the admitting group's identity and
        scheduling weight into the worker's shared split scheduler
        (exec/taskexec.py fair-share drain). ``traceparent`` is the
        W3C trace context naming the query's trace id and the
        coordinator's pre-minted span id for this task (obs/trace.py)
        — shipped both as a payload field and as the HTTP header."""
        body = {
            "fragment": fragment, "catalog": catalog, "schema": schema,
            "part": part, "nparts": nparts,
            "collect_stats": collect_stats,
            "attempt": attempt, "spool": spool,
            "properties": properties or {}}
        if stage is not None:
            body["stage"] = stage
        if deadline_s is not None:
            body["deadline_s"] = float(deadline_s)
        if resource_group is not None:
            body["resource_group"] = str(resource_group)
        if group_weight is not None:
            body["group_weight"] = float(group_weight)
        if traceparent is not None:
            body["traceparent"] = str(traceparent)
        return self._post(task_id, body, traceparent=traceparent)

    def status(self, task_id: str,
               traceparent: Optional[str] = None) -> dict:
        """GET the task status JSON, including worker-reported
        nodeStats and spans once the task finished."""
        req = urllib.request.Request(
            f"{self.base_uri}/v1/task/{task_id}")
        if traceparent:
            req.add_header("traceparent", traceparent)
        with urllib.request.urlopen(req, timeout=30) as r:
            return json.loads(r.read())

    def wait_done(self, task_id: str, cancel=None,
                  timeout_s: float = 600.0,
                  poll_s: float = 0.05, on_status=None,
                  traceparent: Optional[str] = None) -> dict:
        """Poll task status until a terminal state and return the final
        status JSON (a stage task's consumers read its output off the
        spool/partition endpoint, so completion — not pages — is what
        the scheduler waits on). ``cancel`` (anything with ``is_set``)
        aborts between polls; ``timeout_s`` bounds the wait on a
        wedged worker, turning it into a retriable attempt failure.
        ``on_status`` receives every polled status dict WHILE the task
        runs — the live-memory beat hook (the stage scheduler feeds
        ``liveMemoryBytes`` into the cluster pool per poll)."""
        import time as _time
        deadline = _time.monotonic() + timeout_s
        while True:
            if cancel is not None and cancel.is_set():
                try:
                    self.abort(task_id)
                except Exception:       # noqa: BLE001
                    pass
                raise RuntimeError(f"task {task_id} canceled")
            if _time.monotonic() > deadline:
                try:
                    self.abort(task_id)
                except Exception:       # noqa: BLE001
                    pass
                raise RuntimeError(
                    f"task {task_id} did not finish in {timeout_s}s")
            st = self.status(task_id, traceparent=traceparent)
            if on_status is not None:
                try:
                    on_status(st)
                except Exception:       # noqa: BLE001 — a beat
                    pass                # consumer bug must not fail
                #                        the attempt
            if st.get("state") != "RUNNING":
                return st
            _time.sleep(poll_s)

    def _post(self, task_id: str, body: dict,
              traceparent: Optional[str] = None):
        payload = json.dumps(body).encode()
        headers = {"Content-Type": "application/json"}
        if traceparent:
            headers["traceparent"] = traceparent
        req = urllib.request.Request(
            f"{self.base_uri}/v1/task/{task_id}", data=payload,
            headers=headers, method="POST")
        with urllib.request.urlopen(req, timeout=30) as r:
            return json.loads(r.read())

    def pages_raw(self, task_id: str, cancel=None,
                  timeout_s: float = 600.0,
                  meta_out: Optional[dict] = None,
                  on_beat=None,
                  traceparent: Optional[str] = None) -> List[bytes]:
        """Pull every result page FRAME (token-acknowledged bounded
        poll) — raw serialized bytes, so callers can spool them without
        a decode/re-encode round trip. ``cancel`` (anything with
        ``is_set()``) aborts the remote task and raises between polls —
        the ExchangeClient cancel path; ``timeout_s`` bounds the total
        wait on a wedged task. A 404 mid-pull (task evicted after
        abort, worker restart) falls back to the worker's /v1/spool
        endpoint once: committed output survives the task entry.
        ``meta_out``, when given, receives pull side-channel data —
        currently ``spool_dir``, the worker's committed-attempt
        directory (X-TT-Spool-Dir) for same-host write coalescing."""
        import urllib.error
        import time as _time
        deadline = _time.monotonic() + timeout_s
        out: List[bytes] = []
        token = 0
        from_spool = False
        while True:
            if _time.monotonic() > deadline:
                try:
                    self.abort(task_id)
                except Exception:       # noqa: BLE001
                    pass
                raise RuntimeError(
                    f"task {task_id} produced no page for {timeout_s}s")
            if cancel is not None and cancel.is_set():
                try:
                    self.abort(task_id)
                except Exception:       # noqa: BLE001
                    pass
                raise RuntimeError(f"task {task_id} canceled")
            path = (f"/v1/spool/{task_id}/{token}" if from_spool
                    else f"/v1/task/{task_id}/results/{token}")
            try:
                # per-request timeout bounded by the remaining attempt
                # deadline: a half-open socket on a dead worker must
                # not pin this pull past its budget
                per_req = max(1.0, min(600.0,
                                       deadline - _time.monotonic()))
                pull = urllib.request.Request(f"{self.base_uri}{path}")
                if traceparent:
                    # trace context on the data-plane pulls too: a
                    # proxy/collector between hosts can correlate page
                    # traffic with the owning query's trace
                    pull.add_header("traceparent", traceparent)
                with urllib.request.urlopen(pull, timeout=per_req) as r:
                    if r.status == 202:     # still running: poll again
                        if on_beat is not None:
                            # live-memory beat on the flat path: the
                            # 202 carries the running task's current
                            # reservation (X-TT-Live-Memory)
                            live = r.headers.get("X-TT-Live-Memory")
                            if live:
                                try:
                                    on_beat(int(live))
                                except Exception:  # noqa: BLE001
                                    pass
                        continue
                    complete = r.headers.get("X-TT-Complete") == "true"
                    if meta_out is not None:
                        sdir = r.headers.get("X-TT-Spool-Dir")
                        if sdir:
                            meta_out["spool_dir"] = sdir
                    body = r.read()
            except urllib.error.HTTPError as e:
                if e.code == 404 and not from_spool:
                    from_spool = True   # restart the pull off the spool
                    out, token = [], 0
                    continue
                raise
            if complete:
                break
            out.append(body)
            token += 1
        # counted once at the end: a spool-fallback restart re-pulls
        # from token 0 and must not double-count the first pass
        if out:
            _M_PAGES.inc(len(out), direction="received")
            _M_PAGE_BYTES.inc(sum(len(b) for b in out),
                              direction="received")
        return out

    def pages(self, task_id: str, cancel=None,
              timeout_s: float = 600.0) -> List[Batch]:
        """`pages_raw` decoded into Batches."""
        return [deserialize_batch(b) for b in
                self.pages_raw(task_id, cancel=cancel,
                               timeout_s=timeout_s)]

    def abort(self, task_id: str):
        req = urllib.request.Request(
            f"{self.base_uri}/v1/task/{task_id}", method="DELETE")
        with urllib.request.urlopen(req, timeout=30):
            pass
