"""Object-store-shaped spool backend.

Reference parity: trino-exchange-filesystem's S3FileSystemExchangeStorage
— the spooling exchange written through an object-store client
(put/get/list/delete over opaque keys) instead of a local directory, so
completed task output is durable across HOSTS, not just processes. The
client surface here is the S3/GCS common denominator:

    put(key, data)             unconditional write
    put_if_absent(key, data)   conditional create (S3 If-None-Match:*)
    get(key) -> bytes|None
    list(prefix) -> [keys]
    delete_prefix(prefix)
    mtime(key) -> float

``InMemoryObjectStore`` emulates that surface for tests (and for
single-process clusters that want the object-store code path without a
real bucket), including *injectable transient failures*: real object
stores throw 503 SlowDown / connection resets under load, so every
spool operation runs through a bounded-retry/backoff wrapper and the
emulation can be told to fail the next N calls.

Layout mirrors the local-dir backend (fte/spool.py) key-for-path:

    <query_id>/f<fid>.p<part>/a<attempt>/page_00000
    <query_id>/f<fid>.p<part>/COMMITTED      <- winning attempt

Commit protocol is the same first-commit-wins: frames are put under the
attempt prefix, then the COMMITTED marker is claimed with a conditional
put. Exactly one attempt wins; a loser deletes its own frames and
reports the winner. TTL cleanup reaps whole query prefixes whose newest
object is older than ``ttl_s``.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from ..obs.metrics import METRICS
from .faultpoints import fault_point
from .spool import (_M_SPOOL_DUPES, _M_SPOOL_READ, _M_SPOOL_WRITTEN,
                    SpoolManager)

_M_OBJSTORE_OPS = METRICS.counter(
    "trino_tpu_objectstore_requests_total",
    "Object-store spool requests by operation", ("op",))
_M_OBJSTORE_RETRIES = METRICS.counter(
    "trino_tpu_objectstore_retries_total",
    "Object-store spool operations retried after a transient failure")


class TransientObjectStoreError(Exception):
    """A retriable store failure (503 SlowDown, connection reset): the
    spool retries these within its budget; anything else propagates."""


class ObjectStore:
    """Minimal S3/GCS-shaped client surface the spool needs."""

    def put(self, key: str, data: bytes) -> None:
        raise NotImplementedError

    def put_if_absent(self, key: str, data: bytes) -> bool:
        """Create ``key`` only if it does not exist; returns True when
        this call created it (S3 conditional write If-None-Match:*)."""
        raise NotImplementedError

    def get(self, key: str) -> Optional[bytes]:
        raise NotImplementedError

    def list(self, prefix: str) -> List[str]:
        raise NotImplementedError

    def list_entries(self, prefix: str) -> List[Tuple[str, float]]:
        """``(key, mtime)`` pairs in one call — S3/GCS LIST responses
        already carry LastModified, so a client overriding this makes
        the TTL sweep a single listing instead of one metadata round
        trip per object. Default: list + per-key mtime (correct for
        any backend, O(objects) requests)."""
        out: List[Tuple[str, float]] = []
        for k in self.list(prefix):
            out.append((k, self.mtime(k) or 0.0))
        return out

    def delete_prefix(self, prefix: str) -> int:
        raise NotImplementedError

    def mtime(self, key: str) -> Optional[float]:
        raise NotImplementedError


class InMemoryObjectStore(ObjectStore):
    """Dict-backed emulation with injectable transient faults.

    ``inject_failures(n, ops=...)`` makes the next ``n`` matching
    operations raise ``TransientObjectStoreError`` before touching
    state — the shape of a flaky network/bucket, exercised by the
    chaos tests against the spool's retry budget."""

    def __init__(self):
        self._lock = threading.Lock()
        self._objects: Dict[str, Tuple[bytes, float]] = {}
        self._fail_remaining = 0
        self._fail_ops: Optional[frozenset] = None
        # observability for tests: how many operations actually ran
        self.op_counts: Dict[str, int] = {}

    def inject_failures(self, n: int,
                        ops: Optional[List[str]] = None) -> None:
        with self._lock:
            self._fail_remaining = int(n)
            self._fail_ops = frozenset(ops) if ops else None

    def _maybe_fail(self, op: str) -> None:
        # caller holds the lock
        self.op_counts[op] = self.op_counts.get(op, 0) + 1
        if self._fail_remaining > 0 and (self._fail_ops is None
                                         or op in self._fail_ops):
            self._fail_remaining -= 1
            raise TransientObjectStoreError(
                f"injected transient failure on {op}")

    def put(self, key: str, data: bytes) -> None:
        with self._lock:
            self._maybe_fail("put")
            self._objects[key] = (bytes(data), time.time())

    def put_if_absent(self, key: str, data: bytes) -> bool:
        with self._lock:
            self._maybe_fail("put")
            if key in self._objects:
                return False
            self._objects[key] = (bytes(data), time.time())
            return True

    def get(self, key: str) -> Optional[bytes]:
        with self._lock:
            self._maybe_fail("get")
            entry = self._objects.get(key)
            return entry[0] if entry is not None else None

    def list(self, prefix: str) -> List[str]:
        with self._lock:
            self._maybe_fail("list")
            return sorted(k for k in self._objects
                          if k.startswith(prefix))

    def list_entries(self, prefix: str) -> List[Tuple[str, float]]:
        with self._lock:
            self._maybe_fail("list")
            return sorted((k, v[1]) for k, v in self._objects.items()
                          if k.startswith(prefix))

    def delete_prefix(self, prefix: str) -> int:
        with self._lock:
            self._maybe_fail("delete")
            doomed = [k for k in self._objects if k.startswith(prefix)]
            for k in doomed:
                del self._objects[k]
            return len(doomed)

    def mtime(self, key: str) -> Optional[float]:
        with self._lock:
            entry = self._objects.get(key)
            return entry[1] if entry is not None else None


class ObjectStoreSpool(SpoolManager):
    """Spool over an ``ObjectStore`` client with bounded retries.

    Every store call is wrapped in ``_retry``: up to ``max_attempts``
    tries with exponential backoff on ``TransientObjectStoreError``.
    The budget is deliberately small — a dead bucket should fail the
    attempt (which the task-retry engine then handles), not hang the
    query."""

    def __init__(self, store: ObjectStore,
                 ttl_s: Optional[float] = None,
                 max_attempts: Optional[int] = None,
                 backoff_initial_s: Optional[float] = None):
        from ..config import CONFIG
        self.store = store
        self.ttl_s = max(float(CONFIG.spool_ttl_s if ttl_s is None
                               else ttl_s), 60.0)
        self.max_attempts = int(CONFIG.objectstore_max_attempts
                                if max_attempts is None else max_attempts)
        self.backoff_initial_s = float(
            CONFIG.objectstore_backoff_s if backoff_initial_s is None
            else backoff_initial_s)
        self._last_sweep = 0.0
        self._released: set = set()

    # -- retry wrapper -------------------------------------------------
    def _retry(self, op: str, fn: Callable):
        _M_OBJSTORE_OPS.inc(op=op)
        delay = self.backoff_initial_s
        for attempt in range(max(self.max_attempts, 1)):
            try:
                return fn()
            except TransientObjectStoreError:
                if attempt + 1 >= max(self.max_attempts, 1):
                    raise
                _M_OBJSTORE_RETRIES.inc()
                time.sleep(delay)
                delay = min(delay * 2.0, 1.0)

    # -- layout --------------------------------------------------------
    @staticmethod
    def _task_prefix(query_id: str, fragment_id: int, part: int) -> str:
        return f"{query_id}/f{fragment_id}.p{part}"

    # -- SpoolManager --------------------------------------------------
    def commit(self, query_id: str, fragment_id: int, part: int,
               attempt: int, frames: List[bytes]) -> int:
        if self._is_released(query_id):
            return attempt        # finished query: drop, don't resurrect
        tpre = self._task_prefix(query_id, fragment_id, part)
        apre = f"{tpre}/a{attempt}"
        for i, frame in enumerate(frames):
            self._retry("put", lambda k=f"{apre}/page_{i:05d}",
                        d=frame: self.store.put(k, d))
        marker = f"{tpre}/COMMITTED"
        fault_point("spool.pre_marker")
        won = self._retry("put", lambda: self.store.put_if_absent(
            marker, str(attempt).encode()))
        if won:
            _M_SPOOL_WRITTEN.inc(sum(len(f) for f in frames))
            return attempt
        winner = self.committed_attempt(query_id, fragment_id, part)
        if winner is None:
            # unreadable marker (corrupt/legacy): usurp it — same
            # degenerate-case semantics as the local backend
            self._retry("put", lambda: self.store.put(
                marker, str(attempt).encode()))
            _M_SPOOL_WRITTEN.inc(sum(len(f) for f in frames))
            return attempt
        if winner != attempt:
            _M_SPOOL_DUPES.inc()
            self._retry("delete",
                        lambda: self.store.delete_prefix(apre + "/"))
        return winner

    def committed_attempt(self, query_id: str, fragment_id: int,
                          part: int) -> Optional[int]:
        marker = f"{self._task_prefix(query_id, fragment_id, part)}" \
                 "/COMMITTED"
        raw = self._retry("get", lambda: self.store.get(marker))
        try:
            return int(raw)
        except (TypeError, ValueError):
            return None

    def read(self, query_id: str, fragment_id: int,
             part: int) -> Optional[List[bytes]]:
        attempt = self.committed_attempt(query_id, fragment_id, part)
        if attempt is None:
            return None
        apre = f"{self._task_prefix(query_id, fragment_id, part)}" \
               f"/a{attempt}/"
        keys = self._retry("list", lambda: self.store.list(apre))
        if not keys and self.committed_attempt(
                query_id, fragment_id, part) != attempt:
            # reaped between the marker get and the list: the reap
            # deletes the marker too, so its absence distinguishes
            # missing output (None — callers treat it as a failure)
            # from a legitimately empty commit ([])
            return None
        frames: List[bytes] = []
        for k in keys:
            data = self._retry("get", lambda key=k: self.store.get(key))
            if data is None:
                return None       # reaped between list and get
            frames.append(data)
        _M_SPOOL_READ.inc(sum(len(f) for f in frames))
        return frames

    def frame_count(self, query_id: str, fragment_id: int,
                    part: int) -> Optional[int]:
        attempt = self.committed_attempt(query_id, fragment_id, part)
        if attempt is None:
            return None
        apre = f"{self._task_prefix(query_id, fragment_id, part)}" \
               f"/a{attempt}/"
        return len(self._retry("list", lambda: self.store.list(apre)))

    def read_frame(self, query_id: str, fragment_id: int, part: int,
                   index: int) -> Optional[bytes]:
        attempt = self.committed_attempt(query_id, fragment_id, part)
        if attempt is None:
            return None
        key = f"{self._task_prefix(query_id, fragment_id, part)}" \
              f"/a{attempt}/page_{index:05d}"
        data = self._retry("get", lambda: self.store.get(key))
        if data is not None:
            _M_SPOOL_READ.inc(len(data))
        return data

    def release(self, query_id: str) -> None:
        self._mark_released(query_id)
        try:
            self._retry("delete", lambda: self.store.delete_prefix(
                f"{query_id}/"))
        except TransientObjectStoreError:
            pass                  # the TTL sweep backstops a failed drop

    def release_fragment(self, query_id: str, fragment_id: int) -> None:
        try:
            self._retry("delete", lambda: self.store.delete_prefix(
                f"{query_id}/f{fragment_id}.p"))
        except TransientObjectStoreError:
            pass                  # the TTL sweep backstops a failed drop

    def cleanup(self, now: Optional[float] = None) -> int:
        now = time.time() if now is None else now
        try:
            # one listing carries the mtimes (list_entries): a
            # per-object mtime round trip would make the sweep
            # O(total objects) network requests on a real bucket
            entries = self._retry(
                "list", lambda: self.store.list_entries(""))
        except TransientObjectStoreError:
            return 0
        newest: Dict[str, float] = {}
        for k, mt in entries:
            qid = k.split("/", 1)[0]
            newest[qid] = max(newest.get(qid, 0.0), mt or 0.0)
        removed = 0
        for qid, mt in newest.items():
            if mt < now - self.ttl_s:
                try:
                    self._retry("delete",
                                lambda q=qid: self.store.delete_prefix(
                                    f"{q}/"))
                    removed += 1
                except TransientObjectStoreError:
                    continue
        return removed
