"""Deterministic fault-point injection for chaos testing.

Named crash sites are sprinkled through the dispatch path
(``coordinator.pre_dispatch``, ``coordinator.post_stage_commit``,
``coordinator.mid_combine``), the worker status loop
(``worker.pre_status_beat``), the spool commit protocol
(``spool.pre_marker``) and the streaming ingest path
(``stream.pre_append`` — a producer dying before its frame lands;
``stream.pre_offset_commit`` — a consumer dying between a successful
incremental INSERT and sealing its offset epoch, the at-least-once
boundary).  Each site is a single call to :func:`fault_point`, which
is free when no schedule is armed.

A schedule maps a site name to an action:

``crash``
    hard-exit the process (``os._exit``) — models a SIGKILL'd
    coordinator/worker with no chance to run cleanup handlers.
``raise``
    raise :class:`FaultInjected` — models an unexpected exception at
    that site (e.g. a torn RPC) that unwinds through normal error
    handling.
``delay``
    sleep for N seconds, then continue — models a stall (GC pause,
    network brownout) without failing.
``call``
    invoke a test-installed callback (only available via
    :func:`install`, not the env var) — lets in-process chaos tests
    stage a real failover (kill coordinator A, boot coordinator B)
    at an exact line, then optionally raise.

Schedules come from two sources, merged with programmatic installs
winning:

* ``TRINO_TPU_FAULTPOINTS`` — comma-separated
  ``site=action[:seconds][@skip]`` entries, e.g.
  ``coordinator.post_stage_commit=crash@1`` (crash on the *second*
  hit) or ``worker.pre_status_beat=delay:0.5``.  Parsed lazily on the
  first :func:`fault_point` call so servers forked after the env is
  set pick it up without extra wiring.
* :func:`install` — tests arm a site directly, with an optional
  callable action.  :func:`reset` clears everything (and re-arms the
  env schedule on next use).

Each armed site fires ``count`` times (default 1) after ``skip``
initial hits are ignored; thereafter it is inert.  All bookkeeping is
lock-protected so sites on worker/dispatch threads count correctly.
"""
from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, Optional

from ..obs.metrics import METRICS

FAULTPOINTS_FIRED = METRICS.counter(
    "trino_tpu_fault_points_fired_total",
    "Armed fault points that fired, by site and action.",
    ("site", "action"))

ENV_VAR = "TRINO_TPU_FAULTPOINTS"

_VALID_ACTIONS = ("crash", "raise", "delay", "call")


class FaultInjected(RuntimeError):
    """Raised by a ``raise``-action fault point (and by ``call``
    actions whose callback asks for a raise)."""

    def __init__(self, site: str):
        super().__init__(f"fault injected at {site}")
        self.site = site


@dataclass
class _Armed:
    action: str
    seconds: float = 0.0
    skip: int = 0
    count: int = 1
    callback: Optional[Callable[[str], object]] = None
    hits: int = 0
    fired: int = 0
    lock: threading.Lock = field(default_factory=threading.Lock)


_LOCK = threading.Lock()
_SCHEDULE: Dict[str, _Armed] = {}
_ENV_LOADED = False


def parse_schedule(spec: str) -> Dict[str, _Armed]:
    """Parse ``site=action[:seconds][@skip]`` comma-list into a
    schedule.  Raises ``ValueError`` on malformed entries so a typo'd
    env var fails loudly at arm time rather than silently never
    firing."""
    out: Dict[str, _Armed] = {}
    for raw in spec.split(","):
        entry = raw.strip()
        if not entry:
            continue
        if "=" not in entry:
            raise ValueError(f"fault point entry missing '=': {entry!r}")
        site, rhs = entry.split("=", 1)
        site = site.strip()
        skip = 0
        if "@" in rhs:
            rhs, skip_s = rhs.rsplit("@", 1)
            skip = int(skip_s)
        seconds = 0.0
        if ":" in rhs:
            rhs, sec_s = rhs.split(":", 1)
            seconds = float(sec_s)
        action = rhs.strip()
        if action not in _VALID_ACTIONS or action == "call":
            raise ValueError(
                f"fault point action must be one of crash/raise/delay: "
                f"{entry!r}")
        if not site:
            raise ValueError(f"fault point entry missing site: {entry!r}")
        out[site] = _Armed(action=action, seconds=seconds, skip=skip)
    return out


def _load_env_locked() -> None:
    global _ENV_LOADED
    if _ENV_LOADED:
        return
    _ENV_LOADED = True
    spec = os.environ.get(ENV_VAR, "")
    if not spec:
        return
    for site, armed in parse_schedule(spec).items():
        # Programmatic installs win over the env schedule.
        _SCHEDULE.setdefault(site, armed)


def install(site: str, action: str = "raise", *, seconds: float = 0.0,
            skip: int = 0, count: int = 1,
            callback: Optional[Callable[[str], object]] = None) -> None:
    """Arm ``site`` programmatically (tests).  ``callback`` implies
    action ``call``; it receives the site name and may raise, or
    return ``"raise"`` to have :class:`FaultInjected` raised for
    it after it returns."""
    if callback is not None:
        action = "call"
    if action not in _VALID_ACTIONS:
        raise ValueError(f"unknown fault action {action!r}")
    with _LOCK:
        _SCHEDULE[site] = _Armed(action=action, seconds=seconds, skip=skip,
                                 count=count, callback=callback)


def reset() -> None:
    """Clear every armed site and forget the env schedule (it is
    re-read on the next :func:`fault_point` call)."""
    global _ENV_LOADED
    with _LOCK:
        _SCHEDULE.clear()
        _ENV_LOADED = False


def armed_sites() -> Dict[str, str]:
    """site -> action for everything currently armed (introspection /
    ``main.py`` startup logging)."""
    with _LOCK:
        _load_env_locked()
        return {site: a.action for site, a in _SCHEDULE.items()}


def fault_point(site: str) -> None:
    """Fire-through marker for a named fault site.  No-op unless the
    site is armed; see module docstring for actions."""
    with _LOCK:
        _load_env_locked()
        armed = _SCHEDULE.get(site)
    if armed is None:
        return
    with armed.lock:
        armed.hits += 1
        if armed.hits <= armed.skip or armed.fired >= armed.count:
            return
        armed.fired += 1
        action = armed.action
    FAULTPOINTS_FIRED.inc(site=site, action=action)
    if action == "delay":
        time.sleep(armed.seconds)
        return
    if action == "crash":
        # os._exit models SIGKILL: no atexit, no finally blocks, no
        # flushing — the process is simply gone.
        os._exit(137)
    if action == "call":
        cb = armed.callback
        want_raise = cb(site) if cb is not None else None
        if want_raise == "raise":
            raise FaultInjected(site)
        return
    raise FaultInjected(site)
