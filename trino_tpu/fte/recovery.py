"""Coordinator result recovery: spooled combine output + query manifest.

Reference parity: Trino's fault-tolerant execution spools the ROOT
stage's output too (the coordinator's exchange sink writes to the
exchange manager like any other stage), which is what lets a client
re-pull `QueryResults` pages after the coordinator restarts — the query
is finished, its pages are durable, only the serving process died.

Here the combine (root) stage's output — the final client-visible rows
— plus a minimal manifest (query id, slug, SQL, user, column names and
type names, update metadata) is committed to the shared spool under a
RESERVED fragment id, keyed by the COORDINATOR's query id. A restarted
coordinator that gets `GET /v1/statement/executing/{id}/{slug}/{token}`
for a query it has never heard of loads the manifest off the spool,
verifies the slug, rebuilds a FINISHED query entry, and serves the
pages as if it had run the query itself. The recovery window is the
spool TTL.

Rows are persisted in the client WIRE encoding (dates/decimals already
JSON-stringified): the recovered pages are byte-for-byte what the
original coordinator would have served, and no engine type machinery is
needed to read them back.
"""

from __future__ import annotations

import datetime
import decimal
import json
from dataclasses import dataclass
from typing import List, Optional

from ..obs.metrics import METRICS

# fragment ids from the planner are >= 0; the query's final result
# spools under this reserved id (layout: <query_id>/f-1.p0/...)
RESULT_FRAGMENT = -1

# the EXECUTION manifest — everything a restarted coordinator needs to
# resume a RUNNING query — spools under this second reserved id
# (layout: <query_id>/f-2.p0/...), written at dispatch time and
# released on normal completion
MANIFEST_FRAGMENT = -2

# rows per persisted result frame — matches the coordinator's
# QueryResults paging so one frame serves ~one client page
RESULT_PAGE_ROWS = 4096

_M_RESULTS_PERSISTED = METRICS.counter(
    "trino_tpu_query_results_spooled_total",
    "Finished queries whose results + manifest were spooled for "
    "coordinator-restart recovery")
_M_RESULTS_RECOVERED = METRICS.counter(
    "trino_tpu_query_results_recovered_total",
    "Queries rebuilt from the spooled manifest by a coordinator that "
    "did not run them (restart recovery)")
_M_RESULTS_SKIPPED = METRICS.counter(
    "trino_tpu_query_results_spool_skipped_total",
    "Finished queries whose results exceeded result_spool_max_bytes "
    "and were not persisted for restart recovery")
_M_MANIFESTS_PERSISTED = METRICS.counter(
    "trino_tpu_exec_manifests_spooled_total",
    "Execution manifests spooled at dispatch time for mid-flight "
    "coordinator-failover resumption")
_M_MANIFESTS_RESUMED = METRICS.counter(
    "trino_tpu_exec_manifests_resumed_total",
    "RUNNING queries resumed from a spooled execution manifest by a "
    "coordinator that did not dispatch them")


def json_value(v):
    """Client wire encoding of one value (QueryResults data cell)."""
    if isinstance(v, (datetime.date, datetime.datetime)):
        return v.isoformat(sep=" ") if isinstance(v, datetime.datetime) \
            else v.isoformat()
    if isinstance(v, decimal.Decimal):
        return str(v)
    return v


class _NamedType:
    """Type stand-in for recovered results: the serving path only needs
    ``.name`` (column rendering), never the engine type machinery."""

    __slots__ = ("name",)

    def __init__(self, name: str):
        self.name = name

    def __repr__(self):
        return f"_NamedType({self.name!r})"


@dataclass
class RecoveredQuery:
    """A finished query reloaded from its spooled manifest."""
    query_id: str
    slug: str
    sql: str
    user: str
    columns: List[str]
    type_names: List[str]
    rows: List[list]
    update_type: Optional[str] = None
    update_count: Optional[int] = None

    def to_query_result(self):
        from ..runner import QueryResult
        res = QueryResult(list(self.columns),
                          [_NamedType(n) for n in self.type_names],
                          self.rows, query_id=self.query_id)
        res.update_type = self.update_type
        res.update_count = self.update_count
        return res


class ResultStore:
    """Persists / recovers finished query results through a
    ``SpoolManager`` (any backend)."""

    def __init__(self, spool):
        self.spool = spool

    def persist(self, query_id: str, slug: str, sql: str, user: str,
                result, max_bytes: Optional[int] = None) -> bool:
        """Spool a finished query's manifest + wire-encoded result
        pages. Best-effort by contract: the query already succeeded,
        so a failed persist costs only restart recoverability.

        ``max_bytes`` (default CONFIG.result_spool_max_bytes) bounds
        the encoded size: the persist runs ON the query thread before
        FINISHED is client-visible (durability precedes publication),
        so an unbounded result would add O(result) latency and a
        second in-memory copy — past the cap the query simply isn't
        restart-recoverable, like every query before PR 6."""
        if max_bytes is None:
            from ..config import CONFIG
            max_bytes = int(CONFIG.result_spool_max_bytes)
        ncols = len(result.columns or []) or 1
        # floor-estimate before paying the wire re-encode: every row
        # costs at least "[v,…]," = 2 bytes per cell + brackets
        if max_bytes > 0 and len(result.rows) * (2 * ncols + 2) \
                > max_bytes:
            _M_RESULTS_SKIPPED.inc()
            return False
        rows = [[json_value(v) for v in row] for row in result.rows]
        manifest = {
            "queryId": query_id,
            "slug": slug,
            "sql": sql,
            "user": user,
            "columns": list(result.columns or []),
            "types": [t.name for t in (result.types or [])],
            "rows": len(rows),
            "updateType": result.update_type,
            "updateCount": result.update_count,
        }
        frames = [json.dumps(manifest).encode()]
        total = len(frames[0])
        for lo in range(0, len(rows), RESULT_PAGE_ROWS):
            frame = json.dumps(rows[lo:lo + RESULT_PAGE_ROWS]).encode()
            total += len(frame)
            if max_bytes > 0 and total > max_bytes:
                _M_RESULTS_SKIPPED.inc()
                return False
            frames.append(frame)
        try:
            self.spool.commit(query_id, RESULT_FRAGMENT, 0, 0, frames)
        except Exception:       # noqa: BLE001 — durable results are
            return False        # opportunistic, never a query failure
        _M_RESULTS_PERSISTED.inc()
        return True

    def load_manifest(self, query_id: str) -> Optional[dict]:
        """Read ONLY the manifest (frame 0) — the cheap peek callers
        use to verify the slug before paying for the full row decode
        (a wrong-slug probe must not re-read a 64MB result to 404)."""
        try:
            raw = self.spool.read_frame(query_id, RESULT_FRAGMENT, 0, 0)
        except Exception:       # noqa: BLE001
            return None
        if raw is None:
            return None
        try:
            mf = json.loads(raw)
        except ValueError:
            return None
        return mf if isinstance(mf, dict) else None

    def load(self, query_id: str,
             slug: Optional[str] = None) -> Optional[RecoveredQuery]:
        """Reload a query's manifest + rows, or None if nothing (or
        something unreadable) is spooled under its id. When ``slug``
        is given it is checked against the manifest BEFORE the row
        frames are read."""
        if slug is not None:
            mf = self.load_manifest(query_id)
            if mf is None or str(mf.get("slug")) != slug:
                return None
        try:
            frames = self.spool.read(query_id, RESULT_FRAGMENT, 0)
        except Exception:       # noqa: BLE001
            return None
        if not frames:
            return None
        try:
            manifest = json.loads(frames[0])
            rows: List[list] = []
            for fr in frames[1:]:
                rows.extend(json.loads(fr))
            if len(rows) != int(manifest.get("rows", len(rows))):
                return None     # torn manifest: refuse a partial answer
            rec = RecoveredQuery(
                query_id=str(manifest["queryId"]),
                slug=str(manifest["slug"]),
                sql=str(manifest.get("sql", "")),
                user=str(manifest.get("user", "")),
                columns=list(manifest.get("columns") or []),
                type_names=list(manifest.get("types") or []),
                rows=rows,
                update_type=manifest.get("updateType"),
                update_count=manifest.get("updateCount"),
            )
        except (KeyError, ValueError, TypeError):
            return None
        return rec

    def release(self, query_id: str) -> None:
        try:
            self.spool.release(query_id)
        except Exception:       # noqa: BLE001
            pass


class ExecutionManifestStore:
    """Persists / reloads the EXECUTION manifest of a RUNNING query —
    the mid-flight counterpart of ``ResultStore``.

    The manifest is written once, at dispatch time, after the stage DAG
    has been fragmented, serde-proven (``validate_stage_dag`` returns
    the round-trip-checked wire encodings) and its fan-out decided, but
    BEFORE any task is dispatched. It carries everything a coordinator
    that never saw the query needs to finish it: identity (query id,
    slug, SQL), admission context (user, catalog, schema, session
    properties, resource group + weight), timing (original submit and
    start epochs — a resume must not reset the query deadline), the
    execution id the stage scheduler keyed its exchange spool entries
    under, the per-stage fan-out, and the wire encoding of every stage
    fragment plus the root (combine) plan.

    Stage progress itself is NOT in the manifest: the stage exchange's
    first-commit-wins COMMITTED markers (keyed ``<exec>.s<sid>.p<part>``)
    are the durable progress log, and the resuming coordinator
    enumerates them directly."""

    def __init__(self, spool):
        self.spool = spool

    def persist(self, doc: dict) -> bool:
        """Spool one execution manifest (a JSON document built by the
        dispatch path). Best-effort: a failed persist costs only
        failover resumability, never the query."""
        query_id = str(doc.get("queryId"))
        try:
            frames = [json.dumps(doc).encode()]
            self.spool.commit(query_id, MANIFEST_FRAGMENT, 0, 0, frames)
        except Exception:       # noqa: BLE001
            return False
        _M_MANIFESTS_PERSISTED.inc()
        return True

    def load(self, query_id: str,
             slug: Optional[str] = None) -> Optional[dict]:
        """Reload a manifest, or None if nothing (or something
        unreadable) is spooled. ``slug`` is checked against the
        manifest when given — a wrong-slug probe must 404, not leak a
        foreign query's plan."""
        try:
            raw = self.spool.read_frame(query_id, MANIFEST_FRAGMENT,
                                        0, 0)
        except Exception:       # noqa: BLE001
            return None
        if raw is None:
            return None
        try:
            doc = json.loads(raw)
        except ValueError:
            return None
        if not isinstance(doc, dict):
            return None
        if slug is not None and str(doc.get("slug")) != slug:
            return None
        return doc

    def mark_resumed(self) -> None:
        _M_MANIFESTS_RESUMED.inc()

    def release(self, query_id: str) -> None:
        """Drop ONLY the manifest fragment: the finished result persists
        under the same query id and must survive (``spool.release``
        would tombstone the whole query)."""
        try:
            self.spool.release_fragment(query_id, MANIFEST_FRAGMENT)
        except Exception:       # noqa: BLE001
            pass
