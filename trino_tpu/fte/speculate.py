"""Straggler detection for speculative task re-dispatch.

Reference parity: speculative execution as in Trino's adaptive task
scheduling (and the classic MapReduce backup-task design): track the
runtime distribution of COMPLETED attempts per fragment; a still-running
attempt whose elapsed time exceeds a configurable multiple of the
fragment median is a straggler and earns one speculative duplicate on a
different worker. First completion wins — the spool's first-commit-wins
protocol (fte/spool.py) makes the race safe by construction.

The detector is pure bookkeeping (no threads): the scheduler's
speculation monitor polls ``is_straggler`` with each running task's
elapsed time. Quantiles come from the recorded sample list — fragments
dispatch a handful of tasks (one per worker), so O(n log n) on demand
beats maintaining a sketch.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional

from ..obs.metrics import METRICS

SPECULATIVE_TASKS = METRICS.counter(
    "trino_tpu_speculative_tasks_total",
    "Speculative duplicate task attempts launched for stragglers")
SPECULATIVE_WINS = METRICS.counter(
    "trino_tpu_speculative_wins_total",
    "Speculative attempts that committed before the original attempt")


class StragglerDetector:
    """Per-fragment runtime quantiles + the straggler predicate."""

    def __init__(self, multiplier: float = 2.0, min_samples: int = 2,
                 min_runtime_s: float = 0.2):
        self.multiplier = float(multiplier)
        self.min_samples = int(min_samples)
        self.min_runtime_s = float(min_runtime_s)
        self._lock = threading.Lock()
        self._samples: Dict[int, List[float]] = {}

    def record(self, fragment_id: int, runtime_s: float) -> None:
        with self._lock:
            self._samples.setdefault(fragment_id, []).append(
                float(runtime_s))

    def quantile(self, fragment_id: int, q: float) -> Optional[float]:
        """Nearest-rank quantile of completed runtimes, or None with no
        samples."""
        with self._lock:
            xs = sorted(self._samples.get(fragment_id, ()))
        if not xs:
            return None
        idx = min(int(q * len(xs)), len(xs) - 1)
        return xs[idx]

    def median(self, fragment_id: int) -> Optional[float]:
        return self.quantile(fragment_id, 0.5)

    def samples(self, fragment_id: int) -> int:
        with self._lock:
            return len(self._samples.get(fragment_id, ()))

    def is_straggler(self, fragment_id: int, elapsed_s: float) -> bool:
        """True once ``min_samples`` sibling attempts have completed
        and this attempt has run more than ``multiplier`` x their
        median (and past the absolute floor — re-dispatching a 5ms task
        buys nothing)."""
        if elapsed_s < self.min_runtime_s:
            return False
        if self.samples(fragment_id) < self.min_samples:
            return False
        med = self.median(fragment_id)
        return med is not None and elapsed_s > self.multiplier * med
