"""Task retry policy engine.

Reference parity: execution/RetryPolicy.java (NONE | TASK | QUERY; we
carry NONE and TASK — QUERY-level restart is a degenerate TASK retry
when every fragment fails) plus the attempt bookkeeping of
EventDrivenFaultTolerantQueryScheduler: per-task and per-query attempt
budgets (task-retry-attempts-per-task / query-retry-attempts), and
exponential backoff with jitter between attempts
(retry-initial-delay/retry-max-delay).

Determinism: the jitter is seeded from the task token + attempt number,
so a re-run of the same query schedule produces the same delays, and the
replacement worker for attempt N is a pure function of (home worker,
attempt, excluded set, detector liveness) — no RNG in the scheduler.
"""

from __future__ import annotations

import hashlib
import threading
from dataclasses import dataclass
from typing import Callable, Dict, FrozenSet, Optional, Tuple

from ..obs.metrics import METRICS

RETRY_NONE = "NONE"
RETRY_TASK = "TASK"

# the headline FTE counter: one increment per re-dispatched attempt
# (speculative duplicates count separately — speculate.py)
TASK_RETRIES = METRICS.counter(
    "trino_tpu_task_retries_total",
    "Remote task attempts re-dispatched after a failure")

# the root stage has no worker to rotate to — it re-executes on the
# coordinator over the spooled fragment output (exec/remote.py
# _execute_combine); until PR 6 it was the one unretried stage
COMBINE_RETRIES = METRICS.counter(
    "trino_tpu_combine_retries_total",
    "Coordinator combine (root) stage executions retried after a "
    "failure")


@dataclass(frozen=True)
class RetryPolicy:
    """Immutable per-query retry configuration (session-derived)."""

    policy: str = RETRY_NONE
    task_retry_attempts: int = 4      # TOTAL attempts per task (incl. 1st)
    query_retry_attempts: int = 16    # extra attempts across the query
    backoff_initial_s: float = 0.05
    backoff_max_s: float = 2.0
    backoff_multiplier: float = 2.0

    @property
    def enabled(self) -> bool:
        return self.policy.upper() == RETRY_TASK

    @classmethod
    def from_session(cls, session) -> "RetryPolicy":
        return cls(
            policy=str(session.get("retry_policy")).upper(),
            task_retry_attempts=int(session.get("task_retry_attempts")),
            query_retry_attempts=int(
                session.get("query_retry_attempts")),
            backoff_initial_s=int(
                session.get("retry_initial_delay_ms")) / 1000.0,
            backoff_max_s=int(
                session.get("retry_max_delay_ms")) / 1000.0,
        )


def backoff_delay(policy: RetryPolicy, failures: int,
                  token: str) -> float:
    """Delay before the attempt following the ``failures``-th failure:
    exponential in the failure count, capped at ``backoff_max_s``, with
    deterministic jitter in [0.5x, 1x) seeded by (token, failures) so
    concurrent retries of different tasks de-correlate without RNG."""
    exp = max(failures - 1, 0)
    base = min(policy.backoff_initial_s
               * policy.backoff_multiplier ** exp,
               policy.backoff_max_s)
    h = int.from_bytes(
        hashlib.blake2b(f"{token}:{failures}".encode(),
                        digest_size=8).digest(), "big")
    return base * (0.5 + (h % 4096) / 8192.0)


def pick_worker(n_workers: int, home: int, attempt: int,
                excluded: FrozenSet[int] = frozenset(),
                is_alive: Optional[Callable[[int], bool]] = None) -> int:
    """Deterministic worker slot for one attempt: rotate from the
    task's home worker by the attempt number (attempt 0 = home), then
    prefer candidates that are neither in the observed-failure
    ``excluded`` set nor reported dead by the failure detector.
    Degrades in order (excluded-but-alive, then anything) so the
    scheduler always has a slot — a wrong guess costs one attempt, an
    empty candidate set would wedge the query."""
    order = [(home + attempt + i) % n_workers for i in range(n_workers)]
    for wi in order:
        if wi not in excluded and (is_alive is None or is_alive(wi)):
            return wi
    if is_alive is not None:
        # excluded-but-alive beats known-dead: one failed task this
        # query is weaker evidence than heartbeats failing right now
        for wi in order:
            if is_alive(wi):
                return wi
    for wi in order:
        if wi not in excluded:
            return wi
    return order[0]


class RetryController:
    """Per-query attempt ledger enforcing both budgets (thread-safe:
    every task's dispatch thread and the speculation monitor share
    it)."""

    def __init__(self, policy: RetryPolicy):
        self.policy = policy
        self._lock = threading.Lock()
        self._task_attempts: Dict[Tuple[int, int], int] = {}
        self._query_retries = 0

    def record_failure(self, task_key: Tuple[int, int]) -> bool:
        """Count one failed attempt of ``task_key``; True grants a
        retry (within both budgets), False means the task — and with it
        the query — is out of attempts."""
        with self._lock:
            n = self._task_attempts.get(task_key, 0) + 1
            self._task_attempts[task_key] = n
            if not self.policy.enabled:
                return False
            if n >= self.policy.task_retry_attempts:
                return False
            if self._query_retries >= self.policy.query_retry_attempts:
                return False
            self._query_retries += 1
            return True

    def grant_speculation(self, task_key: Tuple[int, int]) -> bool:
        """A speculative duplicate consumes query budget (it is a real
        extra attempt) but not the task's failure budget. Deliberately
        NOT gated on ``policy.enabled``: speculation is orthogonal to
        failure retries (first-completion-wins needs no retry
        semantics), so ``speculation_enabled`` works under
        retry_policy=NONE too."""
        with self._lock:
            if self._query_retries >= self.policy.query_retry_attempts:
                return False
            self._query_retries += 1
            return True

    def failures(self, task_key: Tuple[int, int]) -> int:
        with self._lock:
            return self._task_attempts.get(task_key, 0)

    @property
    def retries_granted(self) -> int:
        with self._lock:
            return self._query_retries
