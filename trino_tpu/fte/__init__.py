"""Fault-tolerant execution (FTE).

Reference parity: Trino's fault-tolerant execution mode —
RetryPolicy.TASK (core/trino-main/.../execution/RetryPolicy.java), the
spooling exchange manager (plugin/trino-exchange-filesystem writing
completed task output to durable storage so a retried consumer re-reads
it instead of re-running the producer), EventDrivenFaultTolerantQuery-
Scheduler's task-attempt bookkeeping, and speculative execution of
slow tasks (adaptive straggler re-dispatch).

TPU-first shape: the unit of retry is a *leaf fragment task* — one
(fragment, split-share) attempt on one worker host (exec/remote.py).
Completed attempt output is committed to a spool as serialized page
frames (serde.py), first-commit-wins, so a late duplicate attempt from
a retry or a speculative re-dispatch is discarded, never double-counted
— and the coordinator combine reads the spool, not per-thread memory.
"""

from .objectstore import (InMemoryObjectStore, ObjectStore,
                          ObjectStoreSpool, TransientObjectStoreError)
from .retry import (RETRY_NONE, RETRY_TASK, RetryController, RetryPolicy,
                    backoff_delay, pick_worker)
from .speculate import StragglerDetector
from .spool import LocalDirSpool, SpoolManager, default_spool, make_spool

__all__ = [
    "RETRY_NONE", "RETRY_TASK", "RetryController", "RetryPolicy",
    "backoff_delay", "pick_worker", "StragglerDetector",
    "LocalDirSpool", "SpoolManager", "make_spool", "default_spool",
    "ObjectStore", "ObjectStoreSpool", "InMemoryObjectStore",
    "TransientObjectStoreError",
]
