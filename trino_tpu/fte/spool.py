"""Spooling exchange manager: durable fragment output.

Reference parity: trino-exchange-filesystem's FileSystemExchange — a
completed task attempt writes its output pages to durable storage under
an (exchange, partition, attempt) key; consumers read committed output
only, and a duplicate attempt (task retry or speculative re-dispatch)
is deduplicated at commit time, not at read time.

Here the spool is a local directory tree (pluggable via the
``SpoolManager`` interface; an object-store backend slots in by
implementing the same five methods), addressed by
``query/fragment.part/attempt``:

    <base>/<query_id>/f<fid>.p<part>/a<attempt>/page_00000.bin
    <base>/<query_id>/f<fid>.p<part>/COMMITTED      <- winning attempt

Commit protocol (idempotent, first-commit-wins): frames land in a
temp dir, the dir is atomically renamed to ``a<attempt>``, then the
``COMMITTED`` marker is created with O_EXCL. Exactly one attempt wins
the marker; a loser deletes its own frames and reports the winner, so
a late duplicate is discarded rather than double-counted. TTL cleanup
reaps whole query dirs whose mtime is older than ``ttl_s`` (crashed
coordinators leave spools behind; the next query sweeps them).

The same addressing doubles as the SHUFFLE layer for multi-stage MPP
(trino_tpu/stage/ — Trino's spooled exchange is the same object): a
stage task commits its hash-partitioned output under the
attempt-independent exchange key ``<qid>.s<sid>.p<part>`` with frame
index == partition index, consumers read single partitions through
``read_frame``, and first-commit-wins gives per-stage task retries and
speculation their dedup for free.
"""

from __future__ import annotations

import os
import shutil
import threading
import time
from typing import List, Optional

from ..obs.metrics import METRICS
from .faultpoints import fault_point

_M_SPOOL_WRITTEN = METRICS.counter(
    "trino_tpu_spool_bytes_written_total",
    "Serialized page-frame bytes committed to the exchange spool")
_M_SPOOL_READ = METRICS.counter(
    "trino_tpu_spool_bytes_read_total",
    "Serialized page-frame bytes read back from the exchange spool")
_M_SPOOL_DUPES = METRICS.counter(
    "trino_tpu_spool_duplicate_attempts_total",
    "Late duplicate task attempts discarded by first-commit-wins")
_M_SPOOL_COALESCED = METRICS.counter(
    "trino_tpu_spool_coalesced_commits_total",
    "Spool commits satisfied by hard-linking the worker's already-"
    "spooled frames (single-host double-write coalescing)")


class SpoolManager:
    """Pluggable spool interface (the ExchangeManager SPI analog).
    Backends: ``LocalDirSpool`` (single-host durable directory tree,
    below) and ``ObjectStoreSpool`` (fte/objectstore.py — S3/GCS-shaped
    put/get/list/delete with bounded retries, for real multi-host
    durability). ``make_spool`` selects one by name."""

    # backends set these in __init__; the time-gated sweep below is
    # shared so every backend's full scan runs at most once per TTL/4
    ttl_s: float = 3600.0
    _last_sweep: float = 0.0

    def commit(self, query_id: str, fragment_id: int, part: int,
               attempt: int, frames: List[bytes]) -> int:
        """Persist one attempt's output; returns the WINNING attempt
        for this (query, fragment, part) — not necessarily ours."""
        raise NotImplementedError

    def committed_attempt(self, query_id: str, fragment_id: int,
                          part: int) -> Optional[int]:
        raise NotImplementedError

    def read(self, query_id: str, fragment_id: int,
             part: int) -> Optional[List[bytes]]:
        """Frames of the committed attempt, or None if none committed."""
        raise NotImplementedError

    def release(self, query_id: str) -> None:
        """Drop a finished query's spool."""
        raise NotImplementedError

    def release_fragment(self, query_id: str, fragment_id: int) -> None:
        """Drop ONE fragment's spool entries without tombstoning the
        query — reserved-fragment bookkeeping (the execution manifest)
        is released on completion while the persisted result under the
        same query id must stay servable."""
        raise NotImplementedError

    def cleanup(self, now: Optional[float] = None) -> int:
        """Reap expired query spools; returns how many were removed."""
        raise NotImplementedError

    def maybe_cleanup(self, now: Optional[float] = None) -> int:
        """Time-gated ``cleanup``: the full sweep scans every query
        under the base, so callers on a dispatch hot path run it at
        most once per TTL/4 (floor 60s). The gate's check-then-set is
        under a lock — dispatch threads of concurrent queries all call
        this, and an unsynchronized gate let two threads win the same
        window and run two full-directory sweeps (the same
        shared-state-race class analysis/lint.py flags; this one sits
        across a module boundary, outside the lint's module-local
        reachability, hence fixed by hand)."""
        now = time.time() if now is None else now
        gate = max(min(self.ttl_s / 4, 900.0), 60.0)
        with _SWEEP_GATE_LOCK:
            if now - self._last_sweep < gate:
                return 0
            self._last_sweep = now
        return self.cleanup(now)

    # released-query tombstones, shared by every backend: a commit
    # arriving AFTER release (a straggler attempt of a finished query)
    # must be dropped, not resurrect the spool. The set is created
    # lazily per instance so a backend implementing only the abstract
    # surface never has to know about it (a class-level set would be
    # shared across every spool in the process).
    def _is_released(self, query_id: str) -> bool:
        return str(query_id) in getattr(self, "_released", ())

    def _mark_released(self, query_id: str) -> None:
        # under a lock: release() is called from coordinator request
        # threads and dispatch threads concurrently, and the lazy
        # check-then-set could otherwise lose a tombstone to a racing
        # first release (the cross-module race class
        # analysis/lint.py's scheduler-thread -> spool edges exist to
        # catch; this one was fixed alongside teaching it those edges)
        with _TOMBSTONE_LOCK:
            released = getattr(self, "_released", None)
            if released is None:
                released = self._released = set()
            released.add(str(query_id))
            if len(released) > 4096:
                # bounded memory; the TTL sweep backstops anything a
                # forgotten tombstone lets through
                released.clear()
                released.add(str(query_id))


_DEFAULTS: dict = {}
_DEFAULT_LOCK = threading.Lock()
# guards every spool instance's sweep gate (the gate state is
# per-instance, but a shared lock costs nothing at once-per-TTL/4
# frequency and spares each backend from carrying its own)
_SWEEP_GATE_LOCK = threading.Lock()
# guards the released-query tombstone set's lazy init + mutation
# (release() arrives from request threads and dispatch threads)
_TOMBSTONE_LOCK = threading.Lock()


def make_spool(backend: Optional[str] = None,
               local_base_dir: Optional[str] = None,
               **kwargs) -> SpoolManager:
    """Backend factory (config/session-selected; the ExchangeManager
    plugin-loading analog): ``local`` (default) is the directory-tree
    spool; ``memory`` is the object-store code path over the in-memory
    emulation — the single-process stand-in for an S3/GCS bucket (a
    real bucket client slots in by implementing the ObjectStore
    surface). ``local_base_dir`` overrides the local backend's
    directory and is ignored by directory-less backends, so callers
    with a role-scoped dir (the worker's ``-worker`` suffix) need not
    duplicate the backend-alias resolution."""
    from ..config import CONFIG
    name = (backend or CONFIG.spool_backend or "local").lower()
    if name in ("local", "filesystem", ""):
        if local_base_dir is not None:
            kwargs.setdefault("base_dir", local_base_dir)
        return LocalDirSpool(**kwargs)
    if name in ("memory", "objectstore"):
        from .objectstore import InMemoryObjectStore, ObjectStoreSpool
        return ObjectStoreSpool(InMemoryObjectStore(), **kwargs)
    raise ValueError(f"unknown spool backend '{backend}' "
                     "(expected 'local' or 'memory')")


def worker_spool_base() -> str:
    """Default base directory of a WORKER's task spool — kept separate
    from the coordinator's query-keyed spool so neither side's TTL
    sweep can reap the other's live entries. One definition: the
    worker binds it (server/task_worker.py) and the coordinator's
    spool-first root gather reads through it (exec/remote.py) — a
    drifted copy would silently degrade every gather to the HTTP
    fallback."""
    from ..config import CONFIG
    return CONFIG.spool_dir + "-worker"


def default_spool(backend: Optional[str] = None) -> SpoolManager:
    """Process-wide spool singleton per backend name, for schedulers
    not handed one explicitly. Sharing one instance keeps the
    time-gated TTL sweep (``maybe_cleanup``) at its intended
    once-per-TTL/4 cadence — a fresh spool per query would reset
    ``_last_sweep`` and pay a full scan on every dispatch — and, for
    the in-memory object store, keeps every query in the SAME store.
    Config is read once, at first use."""
    from ..config import CONFIG
    name = (backend or CONFIG.spool_backend or "local").lower()
    with _DEFAULT_LOCK:
        spool = _DEFAULTS.get(name)
        if spool is None:
            spool = _DEFAULTS[name] = make_spool(name)
        return spool


class LocalDirSpool(SpoolManager):
    """Local-directory spool backend (single-host durable storage)."""

    def __init__(self, base_dir: Optional[str] = None,
                 ttl_s: Optional[float] = None):
        from ..config import CONFIG
        self.base = base_dir or CONFIG.spool_dir
        # TTL floor: commits touch the query dir's mtime, so 60s is
        # enough to keep any live query ahead of the sweep; a smaller
        # knob value could reap in-flight output
        self.ttl_s = max(float(CONFIG.spool_ttl_s if ttl_s is None
                               else ttl_s), 60.0)
        self._last_sweep = 0.0
        # released queries must stay dead: a late speculative/retry
        # loser completing after release() would otherwise re-create
        # the query dir and leak its frames until the TTL sweep
        self._released: set = set()
        os.makedirs(self.base, exist_ok=True)
        try:
            os.chmod(self.base, 0o700)   # results transit this dir
        except OSError:
            pass

    # -- layout --------------------------------------------------------
    def _task_dir(self, query_id: str, fragment_id: int,
                  part: int) -> str:
        return os.path.join(self.base, str(query_id),
                            f"f{fragment_id}.p{part}")

    # -- SpoolManager --------------------------------------------------
    def commit(self, query_id: str, fragment_id: int, part: int,
               attempt: int, frames: List[bytes]) -> int:
        if self._is_released(query_id):
            return attempt   # query already finished: drop, do not
            #                  resurrect the released dir
        tdir = self._task_dir(query_id, fragment_id, part)
        adir = os.path.join(tdir, f"a{attempt}")
        tmp = f"{adir}.tmp{os.getpid()}.{threading.get_ident()}"
        os.makedirs(tmp, exist_ok=True)
        for i, frame in enumerate(frames):
            with open(os.path.join(tmp, f"page_{i:05d}.bin"),
                      "wb") as f:
                f.write(frame)
        return self._seal_attempt(query_id, fragment_id, part, attempt,
                                  tmp, sum(len(f) for f in frames))

    def commit_linked(self, query_id: str, fragment_id: int, part: int,
                      attempt: int, src_dir: str,
                      expect_frames: Optional[List[bytes]] = None) -> int:
        """Commit by HARD-LINKING an already-spooled attempt directory
        (the worker's task spool on the same host) instead of rewriting
        the frame bytes — the single-host double-write coalescing of
        the worker/coordinator spool pair. Hard links (not symlinks):
        the worker's TTL sweep reaping its own dir only unlinks names,
        the shared inodes survive under our layout. Falls back to a
        byte copy on cross-device links.

        ``expect_frames`` verifies the linked bytes: ``src_dir`` comes
        from a worker-supplied header (X-TT-Spool-Dir), and with a
        spool active the gather reads frames OFF the spool — so the
        linked files, not the pulled pages, become the authoritative
        combine input. Verification happens AFTER linking (so a
        rename-swap between check and link is impossible); a mismatch
        raises ``ValueError`` and nothing is published, letting the
        caller fall back to the byte commit of the pages it actually
        pulled. What this guards against is linking FOREIGN files —
        a stale or hostile path whose contents differ from the pulled
        pages. It deliberately does not defend against the worker
        later rewriting its own frames through the shared inode: the
        worker authored those bytes and could as easily have served
        the altered version as pages, so that is no new capability —
        a worker you cannot trust with its own output needs the
        object-store backend, not link coalescing. Reading back bytes
        the worker just wrote (page-cache hot) still beats re-writing
        them."""
        if self._is_released(query_id):
            return attempt
        tdir = self._task_dir(query_id, fragment_id, part)
        adir = os.path.join(tdir, f"a{attempt}")
        tmp = f"{adir}.tmp{os.getpid()}.{threading.get_ident()}"
        os.makedirs(tmp, exist_ok=True)
        try:
            names = sorted(os.listdir(src_dir))
            if expect_frames is not None \
                    and len(names) != len(expect_frames):
                raise ValueError(
                    f"coalescing source {src_dir} has {len(names)} "
                    f"frames, pulled {len(expect_frames)}")
            copied_bytes = 0
            for name in names:
                src = os.path.join(src_dir, name)
                dst = os.path.join(tmp, name)
                try:
                    os.link(src, dst)
                except OSError:
                    # cross-device (EXDEV etc): physically re-written
                    # bytes must show in the written counter — and the
                    # commit must NOT be reported as coalesced
                    shutil.copyfile(src, dst)
                    copied_bytes += os.path.getsize(dst)
            if expect_frames is not None:
                for name, frame in zip(names, expect_frames):
                    with open(os.path.join(tmp, name), "rb") as f:
                        if f.read() != frame:
                            raise ValueError(
                                f"coalescing source {src_dir}/{name} "
                                "does not match the pulled frame")
        except Exception:
            shutil.rmtree(tmp, ignore_errors=True)
            raise
        won = self._seal_attempt(query_id, fragment_id, part, attempt,
                                 tmp, written_bytes=copied_bytes)
        if won == attempt and copied_bytes == 0:
            # counted only when this attempt actually owns the marker
            # AND every frame was truly linked: a coalesced loser is a
            # discarded duplicate, and a cross-device copy fallback is
            # a real double write, not a coalesced commit
            _M_SPOOL_COALESCED.inc()
        return won

    def _seal_attempt(self, query_id: str, fragment_id: int, part: int,
                      attempt: int, tmp: str, written_bytes: int) -> int:
        """Atomically publish a fully written attempt temp dir and race
        for the COMMITTED marker (first-commit-wins)."""
        tdir = self._task_dir(query_id, fragment_id, part)
        adir = os.path.join(tdir, f"a{attempt}")
        try:
            os.rename(tmp, adir)
        except OSError:
            # the same attempt id committed twice (a client retry of an
            # already-committed attempt): keep the first copy
            shutil.rmtree(tmp, ignore_errors=True)
        # keep the TTL sweep away from live queries: every commit
        # refreshes the query dir's mtime
        try:
            os.utime(os.path.join(self.base, str(query_id)))
        except OSError:
            pass
        # the marker is hard-linked from a fully written temp file, so
        # claiming (O_EXCL semantics of link) and content are one
        # atomic step — a crash can never leave an empty marker
        fault_point("spool.pre_marker")
        marker = os.path.join(tdir, "COMMITTED")
        tmpm = f"{marker}.tmp{os.getpid()}.{threading.get_ident()}"
        with open(tmpm, "w") as f:
            f.write(str(attempt))
        try:
            for _ in range(2):
                try:
                    os.link(tmpm, marker)
                    _M_SPOOL_WRITTEN.inc(written_bytes)
                    return attempt
                except FileExistsError:
                    winner = self.committed_attempt(
                        query_id, fragment_id, part)
                    if winner is not None:
                        if winner != attempt:
                            _M_SPOOL_DUPES.inc()
                            shutil.rmtree(adir, ignore_errors=True)
                        return winner
                    # unreadable marker (legacy/corrupt): usurp it and
                    # retry the claim once — and never delete our own
                    # frames while the winner is unknown
                    try:
                        os.unlink(marker)
                    except OSError:
                        pass
            return attempt   # still contested: keep frames, claim self
        finally:
            try:
                os.unlink(tmpm)
            except OSError:
                pass

    def committed_attempt(self, query_id: str, fragment_id: int,
                          part: int) -> Optional[int]:
        marker = os.path.join(
            self._task_dir(query_id, fragment_id, part), "COMMITTED")
        try:
            with open(marker) as f:
                return int(f.read())
        except (OSError, ValueError):
            return None

    def read(self, query_id: str, fragment_id: int,
             part: int) -> Optional[List[bytes]]:
        attempt = self.committed_attempt(query_id, fragment_id, part)
        if attempt is None:
            return None
        adir = os.path.join(
            self._task_dir(query_id, fragment_id, part), f"a{attempt}")
        frames: List[bytes] = []
        try:
            for name in sorted(os.listdir(adir)):
                with open(os.path.join(adir, name), "rb") as f:
                    frames.append(f.read())
        except OSError:
            return None
        _M_SPOOL_READ.inc(sum(len(f) for f in frames))
        return frames

    def frame_count(self, query_id: str, fragment_id: int,
                    part: int) -> Optional[int]:
        """Number of committed frames, or None if nothing committed —
        lets a token-at-a-time server answer ``complete`` without
        reading frame payloads."""
        attempt = self.committed_attempt(query_id, fragment_id, part)
        if attempt is None:
            return None
        adir = os.path.join(
            self._task_dir(query_id, fragment_id, part), f"a{attempt}")
        try:
            return len(os.listdir(adir))
        except OSError:
            return None

    def read_frame(self, query_id: str, fragment_id: int, part: int,
                   index: int) -> Optional[bytes]:
        """One committed frame by index (the page-token protocol's
        unit): serving an N-frame pull frame-by-frame must cost O(N)
        disk reads total, not O(N^2) via ``read``, and must count each
        byte once in the spool-read metric."""
        attempt = self.committed_attempt(query_id, fragment_id, part)
        if attempt is None:
            return None
        path = os.path.join(
            self._task_dir(query_id, fragment_id, part),
            f"a{attempt}", f"page_{index:05d}.bin")
        try:
            with open(path, "rb") as f:
                data = f.read()
        except OSError:
            return None
        _M_SPOOL_READ.inc(len(data))
        return data

    def attempt_dir(self, query_id: str, fragment_id: int,
                    part: int) -> Optional[str]:
        """Absolute directory of the COMMITTED attempt's frames, or
        None — the handle a same-host consumer needs to coalesce its
        own commit into hard links (``commit_linked``)."""
        attempt = self.committed_attempt(query_id, fragment_id, part)
        if attempt is None:
            return None
        return os.path.join(self._task_dir(query_id, fragment_id, part),
                            f"a{attempt}")

    def release(self, query_id: str) -> None:
        self._mark_released(query_id)
        shutil.rmtree(os.path.join(self.base, str(query_id)),
                      ignore_errors=True)

    def release_fragment(self, query_id: str, fragment_id: int) -> None:
        qdir = os.path.join(self.base, str(query_id))
        try:
            entries = os.listdir(qdir)
        except OSError:
            return
        prefix = f"f{fragment_id}.p"
        for name in entries:
            if name.startswith(prefix):
                shutil.rmtree(os.path.join(qdir, name),
                              ignore_errors=True)

    def cleanup(self, now: Optional[float] = None) -> int:
        now = time.time() if now is None else now
        removed = 0
        try:
            entries = os.listdir(self.base)
        except OSError:
            return 0
        for name in entries:
            path = os.path.join(self.base, name)
            try:
                if os.path.isdir(path) \
                        and os.path.getmtime(path) < now - self.ttl_s:
                    shutil.rmtree(path, ignore_errors=True)
                    removed += 1
            except OSError:
                continue
        return removed
