"""Spooling exchange manager: durable fragment output.

Reference parity: trino-exchange-filesystem's FileSystemExchange — a
completed task attempt writes its output pages to durable storage under
an (exchange, partition, attempt) key; consumers read committed output
only, and a duplicate attempt (task retry or speculative re-dispatch)
is deduplicated at commit time, not at read time.

Here the spool is a local directory tree (pluggable via the
``SpoolManager`` interface; an object-store backend slots in by
implementing the same five methods), addressed by
``query/fragment.part/attempt``:

    <base>/<query_id>/f<fid>.p<part>/a<attempt>/page_00000.bin
    <base>/<query_id>/f<fid>.p<part>/COMMITTED      <- winning attempt

Commit protocol (idempotent, first-commit-wins): frames land in a
temp dir, the dir is atomically renamed to ``a<attempt>``, then the
``COMMITTED`` marker is created with O_EXCL. Exactly one attempt wins
the marker; a loser deletes its own frames and reports the winner, so
a late duplicate is discarded rather than double-counted. TTL cleanup
reaps whole query dirs whose mtime is older than ``ttl_s`` (crashed
coordinators leave spools behind; the next query sweeps them).
"""

from __future__ import annotations

import os
import shutil
import threading
import time
from typing import List, Optional

from ..obs.metrics import METRICS

_M_SPOOL_WRITTEN = METRICS.counter(
    "trino_tpu_spool_bytes_written_total",
    "Serialized page-frame bytes committed to the exchange spool")
_M_SPOOL_READ = METRICS.counter(
    "trino_tpu_spool_bytes_read_total",
    "Serialized page-frame bytes read back from the exchange spool")
_M_SPOOL_DUPES = METRICS.counter(
    "trino_tpu_spool_duplicate_attempts_total",
    "Late duplicate task attempts discarded by first-commit-wins")


class SpoolManager:
    """Pluggable spool interface (the ExchangeManager SPI analog)."""

    def commit(self, query_id: str, fragment_id: int, part: int,
               attempt: int, frames: List[bytes]) -> int:
        """Persist one attempt's output; returns the WINNING attempt
        for this (query, fragment, part) — not necessarily ours."""
        raise NotImplementedError

    def committed_attempt(self, query_id: str, fragment_id: int,
                          part: int) -> Optional[int]:
        raise NotImplementedError

    def read(self, query_id: str, fragment_id: int,
             part: int) -> Optional[List[bytes]]:
        """Frames of the committed attempt, or None if none committed."""
        raise NotImplementedError

    def release(self, query_id: str) -> None:
        """Drop a finished query's spool."""
        raise NotImplementedError

    def cleanup(self, now: Optional[float] = None) -> int:
        """Reap expired query spools; returns how many were removed."""
        raise NotImplementedError


_DEFAULT: Optional["LocalDirSpool"] = None
_DEFAULT_LOCK = threading.Lock()


def default_spool() -> "LocalDirSpool":
    """Process-wide ``LocalDirSpool`` for schedulers not handed one
    explicitly. Sharing one instance keeps the time-gated TTL sweep
    (``maybe_cleanup``) at its intended once-per-TTL/4 cadence — a
    fresh spool per query would reset ``_last_sweep`` and pay a full
    directory scan on every dispatch. Config is read once, at first
    use."""
    global _DEFAULT
    with _DEFAULT_LOCK:
        if _DEFAULT is None:
            _DEFAULT = LocalDirSpool()
        return _DEFAULT


class LocalDirSpool(SpoolManager):
    """Local-directory spool backend (single-host durable storage)."""

    def __init__(self, base_dir: Optional[str] = None,
                 ttl_s: Optional[float] = None):
        from ..config import CONFIG
        self.base = base_dir or CONFIG.spool_dir
        # TTL floor: commits touch the query dir's mtime, so 60s is
        # enough to keep any live query ahead of the sweep; a smaller
        # knob value could reap in-flight output
        self.ttl_s = max(float(CONFIG.spool_ttl_s if ttl_s is None
                               else ttl_s), 60.0)
        self._last_sweep = 0.0
        # released queries must stay dead: a late speculative/retry
        # loser completing after release() would otherwise re-create
        # the query dir and leak its frames until the TTL sweep
        self._released: set = set()
        os.makedirs(self.base, exist_ok=True)
        try:
            os.chmod(self.base, 0o700)   # results transit this dir
        except OSError:
            pass

    # -- layout --------------------------------------------------------
    def _task_dir(self, query_id: str, fragment_id: int,
                  part: int) -> str:
        return os.path.join(self.base, str(query_id),
                            f"f{fragment_id}.p{part}")

    # -- SpoolManager --------------------------------------------------
    def commit(self, query_id: str, fragment_id: int, part: int,
               attempt: int, frames: List[bytes]) -> int:
        if str(query_id) in self._released:
            return attempt   # query already finished: drop, do not
            #                  resurrect the released dir
        tdir = self._task_dir(query_id, fragment_id, part)
        adir = os.path.join(tdir, f"a{attempt}")
        tmp = f"{adir}.tmp{os.getpid()}.{threading.get_ident()}"
        os.makedirs(tmp, exist_ok=True)
        for i, frame in enumerate(frames):
            with open(os.path.join(tmp, f"page_{i:05d}.bin"),
                      "wb") as f:
                f.write(frame)
        try:
            os.rename(tmp, adir)
        except OSError:
            # the same attempt id committed twice (a client retry of an
            # already-committed attempt): keep the first copy
            shutil.rmtree(tmp, ignore_errors=True)
        # keep the TTL sweep away from live queries: every commit
        # refreshes the query dir's mtime
        try:
            os.utime(os.path.join(self.base, str(query_id)))
        except OSError:
            pass
        # the marker is hard-linked from a fully written temp file, so
        # claiming (O_EXCL semantics of link) and content are one
        # atomic step — a crash can never leave an empty marker
        marker = os.path.join(tdir, "COMMITTED")
        tmpm = f"{marker}.tmp{os.getpid()}.{threading.get_ident()}"
        with open(tmpm, "w") as f:
            f.write(str(attempt))
        try:
            for _ in range(2):
                try:
                    os.link(tmpm, marker)
                    _M_SPOOL_WRITTEN.inc(sum(len(f) for f in frames))
                    return attempt
                except FileExistsError:
                    winner = self.committed_attempt(
                        query_id, fragment_id, part)
                    if winner is not None:
                        if winner != attempt:
                            _M_SPOOL_DUPES.inc()
                            shutil.rmtree(adir, ignore_errors=True)
                        return winner
                    # unreadable marker (legacy/corrupt): usurp it and
                    # retry the claim once — and never delete our own
                    # frames while the winner is unknown
                    try:
                        os.unlink(marker)
                    except OSError:
                        pass
            return attempt   # still contested: keep frames, claim self
        finally:
            try:
                os.unlink(tmpm)
            except OSError:
                pass

    def committed_attempt(self, query_id: str, fragment_id: int,
                          part: int) -> Optional[int]:
        marker = os.path.join(
            self._task_dir(query_id, fragment_id, part), "COMMITTED")
        try:
            with open(marker) as f:
                return int(f.read())
        except (OSError, ValueError):
            return None

    def read(self, query_id: str, fragment_id: int,
             part: int) -> Optional[List[bytes]]:
        attempt = self.committed_attempt(query_id, fragment_id, part)
        if attempt is None:
            return None
        adir = os.path.join(
            self._task_dir(query_id, fragment_id, part), f"a{attempt}")
        frames: List[bytes] = []
        try:
            for name in sorted(os.listdir(adir)):
                with open(os.path.join(adir, name), "rb") as f:
                    frames.append(f.read())
        except OSError:
            return None
        _M_SPOOL_READ.inc(sum(len(f) for f in frames))
        return frames

    def frame_count(self, query_id: str, fragment_id: int,
                    part: int) -> Optional[int]:
        """Number of committed frames, or None if nothing committed —
        lets a token-at-a-time server answer ``complete`` without
        reading frame payloads."""
        attempt = self.committed_attempt(query_id, fragment_id, part)
        if attempt is None:
            return None
        adir = os.path.join(
            self._task_dir(query_id, fragment_id, part), f"a{attempt}")
        try:
            return len(os.listdir(adir))
        except OSError:
            return None

    def read_frame(self, query_id: str, fragment_id: int, part: int,
                   index: int) -> Optional[bytes]:
        """One committed frame by index (the page-token protocol's
        unit): serving an N-frame pull frame-by-frame must cost O(N)
        disk reads total, not O(N^2) via ``read``, and must count each
        byte once in the spool-read metric."""
        attempt = self.committed_attempt(query_id, fragment_id, part)
        if attempt is None:
            return None
        path = os.path.join(
            self._task_dir(query_id, fragment_id, part),
            f"a{attempt}", f"page_{index:05d}.bin")
        try:
            with open(path, "rb") as f:
                data = f.read()
        except OSError:
            return None
        _M_SPOOL_READ.inc(len(data))
        return data

    def release(self, query_id: str) -> None:
        self._released.add(str(query_id))
        if len(self._released) > 4096:
            # bounded memory; the TTL sweep backstops anything a
            # forgotten tombstone lets through
            self._released.clear()
            self._released.add(str(query_id))
        shutil.rmtree(os.path.join(self.base, str(query_id)),
                      ignore_errors=True)

    def maybe_cleanup(self, now: Optional[float] = None) -> int:
        """Time-gated ``cleanup``: the full sweep stats every query dir
        under the base, so callers on a dispatch hot path run it at
        most once per TTL/4 (floor 60s)."""
        now = time.time() if now is None else now
        gate = max(min(self.ttl_s / 4, 900.0), 60.0)
        if now - self._last_sweep < gate:
            return 0
        self._last_sweep = now
        return self.cleanup(now)

    def cleanup(self, now: Optional[float] = None) -> int:
        now = time.time() if now is None else now
        removed = 0
        try:
            entries = os.listdir(self.base)
        except OSError:
            return 0
        for name in entries:
            path = os.path.join(self.base, name)
            try:
                if os.path.isdir(path) \
                        and os.path.getmtime(path) < now - self.ttl_s:
                    shutil.rmtree(path, ignore_errors=True)
                    removed += 1
            except OSError:
                continue
        return removed
